"""SPADL → Atomic-SPADL converter.

Splits composite actions into atomic events by inserting outcome rows
after passes (receival / interception / out / offside), shots (goal /
owngoal / out) and carded fouls (yellow_card / red_card), then re-runs
dribble synthesis and converts start/end pairs to ``(x, y, dx, dy)``.

Parity: reference ``socceraction/atomic/spadl/base.py:15-235``, including
its quirks: the post-insert ``_add_dribbles`` re-run adds extra dribbles
(the reference comments "for some reason this adds more dribbles" — the
inserted events change the consecutive-action pairs); inserted
interceptions resolve to the SPADL interception id (see
:mod:`.config`); own goals and cards trigger on *result* regardless of
action type. This pass is host-side frame surgery (row counts grow ~2x)
and sits above the packed-tensor boundary.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from ...spadl import config as _spadl
from ...spadl.base import _add_dribbles
from . import config as _atomic
from .schema import AtomicSPADLSchema

__all__ = ['convert_to_atomic']

_PASSLIKE_IDS = tuple(
    _spadl.actiontypes.index(t)
    for t in (
        'pass',
        'cross',
        'throw_in',
        'freekick_short',
        'freekick_crossed',
        'corner_crossed',
        'corner_short',
        'clearance',
        'goalkick',
    )
)
_INTERCEPTIONLIKE_IDS = tuple(
    _spadl.actiontypes.index(t)
    for t in (
        'interception',
        'tackle',
        'keeper_punch',
        'keeper_save',
        'keeper_claim',
        'keeper_pick_up',
    )
)
_SHOT_IDS = (_spadl.SHOT, _spadl.SHOT_FREEKICK, _spadl.SHOT_PENALTY)
_GOALKICK = _spadl.actiontypes.index('goalkick')
_THROW_IN = _spadl.actiontypes.index('throw_in')
_CORNER_IDS = (
    _spadl.actiontypes.index('corner_crossed'),
    _spadl.actiontypes.index('corner_short'),
)
_FREEKICK_IDS = (
    _spadl.actiontypes.index('freekick_crossed'),
    _spadl.actiontypes.index('freekick_short'),
    _spadl.SHOT_FREEKICK,
)


def convert_to_atomic(actions: pd.DataFrame) -> pd.DataFrame:
    """Convert a SPADL action frame to Atomic-SPADL.

    Parameters
    ----------
    actions : pd.DataFrame
        A SPADL dataframe (one or more games, ordered within each game).

    Returns
    -------
    pd.DataFrame
        The Atomic-SPADL dataframe.
    """
    atomic = actions.copy()
    atomic = _extra_from_passes(atomic)
    atomic = _add_dribbles(atomic)  # reference re-runs this; adds more dribbles
    atomic = _extra_from_shots(atomic)
    atomic = _extra_from_fouls(atomic)
    atomic = _convert_columns(atomic)
    atomic = _simplify(atomic)
    return AtomicSPADLSchema.validate(atomic)


def _next(actions: pd.DataFrame) -> pd.DataFrame:
    """The successor row for each action (last row: all-NaN phantom)."""
    return actions.shift(-1)


def _merge_and_renumber(actions: pd.DataFrame, extra: pd.DataFrame) -> pd.DataFrame:
    out = pd.concat([actions, extra], ignore_index=True, sort=False)
    out = out.sort_values(['game_id', 'period_id', 'action_id']).reset_index(drop=True)
    out['action_id'] = range(len(out))
    return out


def _extra_template(prev: pd.DataFrame) -> pd.DataFrame:
    """Common fields of an inserted outcome row: at the parent's end point."""
    extra = pd.DataFrame(index=prev.index)
    extra['game_id'] = prev['game_id']
    if 'original_event_id' in prev.columns:
        extra['original_event_id'] = prev['original_event_id']
    extra['period_id'] = prev['period_id']
    extra['action_id'] = prev['action_id'] + 0.1
    extra['time_seconds'] = prev['time_seconds']
    extra['start_x'] = prev['end_x']
    extra['start_y'] = prev['end_y']
    extra['end_x'] = prev['end_x']
    extra['end_y'] = prev['end_y']
    extra['bodypart_id'] = prev['bodypart_id']
    extra['result_id'] = -1
    extra['team_id'] = prev['team_id']
    extra['player_id'] = prev['player_id']
    return extra


def _extra_from_passes(actions: pd.DataFrame) -> pd.DataFrame:
    nex = _next(actions)
    same_team = (actions['team_id'] == nex['team_id']).to_numpy()
    samegame = (actions['game_id'] == nex['game_id']).to_numpy()
    sameperiod = (actions['period_id'] == nex['period_id']).to_numpy()

    extra_idx = (
        actions['type_id'].isin(_PASSLIKE_IDS).to_numpy()
        & samegame
        & sameperiod
        & ~nex['type_id'].isin(_INTERCEPTIONLIKE_IDS).to_numpy()
    )
    prev = actions[extra_idx]
    nex = nex[extra_idx]
    sel_same_team = same_team[extra_idx]

    extra = _extra_template(prev)
    # passes' outcome events happen mid-flight and are foot events
    extra['time_seconds'] = (prev['time_seconds'] + nex['time_seconds']) / 2
    extra['bodypart_id'] = _spadl.FOOT

    offside = (prev['result_id'] == _spadl.OFFSIDE).to_numpy()
    out = (
        (nex['type_id'] == _GOALKICK).to_numpy() & ~sel_same_team
    ) | (nex['type_id'] == _THROW_IN).to_numpy()

    type_id = np.where(sel_same_team, _atomic.RECEIVAL, _atomic.INTERCEPTION)
    type_id = np.where(out, _atomic.OUT, type_id)
    type_id = np.where(offside, _atomic.OFFSIDE, type_id)
    extra['type_id'] = type_id

    is_interception = type_id == _atomic.INTERCEPTION
    extra['team_id'] = prev['team_id'].mask(is_interception, nex['team_id'])
    extra['player_id'] = (
        nex['player_id'].mask(out | offside, prev['player_id'])
        .astype(prev['player_id'].dtype)
    )
    return _merge_and_renumber(actions, extra)


def _extra_from_shots(actions: pd.DataFrame) -> pd.DataFrame:
    nex = _next(actions)
    samegame = (actions['game_id'] == nex['game_id']).to_numpy()
    sameperiod = (actions['period_id'] == nex['period_id']).to_numpy()

    shot = actions['type_id'].isin(_SHOT_IDS).to_numpy()
    goal = shot & (actions['result_id'] == _spadl.SUCCESS).to_numpy()
    owngoal = (actions['result_id'] == _spadl.OWNGOAL).to_numpy()
    next_restart = nex['type_id'].isin(_CORNER_IDS + (_GOALKICK,)).to_numpy()
    out = shot & next_restart & samegame & sameperiod

    extra_idx = goal | owngoal | out
    prev = actions[extra_idx]

    extra = _extra_template(prev)
    type_id = np.full(len(prev), -1)
    type_id = np.where(out[extra_idx], _atomic.OUT, type_id)
    type_id = np.where(goal[extra_idx], _atomic.GOAL, type_id)
    type_id = np.where(owngoal[extra_idx], _atomic.OWNGOAL, type_id)
    extra['type_id'] = type_id
    return _merge_and_renumber(actions, extra)


def _extra_from_fouls(actions: pd.DataFrame) -> pd.DataFrame:
    yellow = (actions['result_id'] == _spadl.YELLOW_CARD).to_numpy()
    red = (actions['result_id'] == _spadl.RED_CARD).to_numpy()

    extra_idx = yellow | red
    prev = actions[extra_idx]

    extra = _extra_template(prev)
    extra['type_id'] = np.where(
        red[extra_idx], _atomic.RED_CARD, _atomic.YELLOW_CARD
    )
    return _merge_and_renumber(actions, extra)


def _convert_columns(actions: pd.DataFrame) -> pd.DataFrame:
    actions['x'] = actions['start_x']
    actions['y'] = actions['start_y']
    actions['dx'] = actions['end_x'] - actions['start_x']
    actions['dy'] = actions['end_y'] - actions['start_y']
    cols = [
        'game_id',
        'original_event_id',
        'action_id',
        'period_id',
        'time_seconds',
        'team_id',
        'player_id',
        'x',
        'y',
        'dx',
        'dy',
        'type_id',
        'bodypart_id',
    ]
    if 'original_event_id' not in actions.columns:
        cols.remove('original_event_id')
    return actions[cols]


def _simplify(actions: pd.DataFrame) -> pd.DataFrame:
    type_id = actions['type_id']
    type_id = type_id.mask(type_id.isin(_CORNER_IDS), _atomic.CORNER)
    type_id = type_id.mask(type_id.isin(_FREEKICK_IDS), _atomic.FREEKICK)
    actions['type_id'] = type_id
    return actions
