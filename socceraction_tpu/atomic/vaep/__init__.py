"""Atomic-VAEP: the VAEP framework over atomic actions."""

from . import features, formula, labels  # noqa: F401
from .base import AtomicVAEP

__all__ = ['AtomicVAEP', 'features', 'labels', 'formula']
