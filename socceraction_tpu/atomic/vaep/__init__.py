"""Atomic-VAEP: the VAEP framework over atomic actions."""

from .base import AtomicVAEP

__all__ = ['AtomicVAEP']
