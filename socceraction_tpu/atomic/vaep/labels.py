"""Label transformers of the Atomic-VAEP framework (pandas oracle side).

Parity: reference ``socceraction/atomic/vaep/labels.py``. Goals and own
goals are atomic action *types* (not shot results); the lookahead clamps
at the last row like the SPADL labels.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from ...config import LABEL_LOOKAHEAD
from ...vaep.labels import _lookahead
from ..spadl import config as atomicspadl


def _goal_masks(actions: pd.DataFrame) -> tuple[np.ndarray, np.ndarray]:
    goal = (actions['type_id'] == atomicspadl.GOAL).to_numpy()
    owngoal = (actions['type_id'] == atomicspadl.OWNGOAL).to_numpy()
    return goal, owngoal


def scores(actions: pd.DataFrame, nr_actions: int = LABEL_LOOKAHEAD) -> pd.DataFrame:
    """True when the acting team scores within the next ``nr_actions``."""
    goal, owngoal = _goal_masks(actions)
    team = actions['team_id'].to_numpy()
    res = _lookahead(goal, owngoal, team, nr_actions, concede=False)
    return pd.DataFrame({'scores': res}, index=actions.index)


def concedes(actions: pd.DataFrame, nr_actions: int = LABEL_LOOKAHEAD) -> pd.DataFrame:
    """True when the acting team concedes within the next ``nr_actions``."""
    goal, owngoal = _goal_masks(actions)
    team = actions['team_id'].to_numpy()
    res = _lookahead(goal, owngoal, team, nr_actions, concede=True)
    return pd.DataFrame({'concedes': res}, index=actions.index)


def goal_from_shot(actions: pd.DataFrame) -> pd.DataFrame:
    """True when a goal directly followed a shot (xG label)."""
    shot = (actions['type_id'] == atomicspadl.actiontypes.index('shot')).to_numpy()
    next_goal = np.append(
        (actions['type_id'].to_numpy()[1:] == atomicspadl.GOAL), False
    )
    return pd.DataFrame({'goal': shot & next_goal}, index=actions.index)
