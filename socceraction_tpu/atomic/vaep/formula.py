"""Value formula of the Atomic-VAEP framework (pandas oracle side).

Parity: reference ``socceraction/atomic/vaep/formula.py``. Differences
from the regular VAEP formula: no 10-second same-phase cutoff and no
penalty/corner priors (the reference comments both out), and the
previous-goal reset keys on the ``goal``/``owngoal`` action *types*.
"""

from __future__ import annotations

import pandas as pd


def _prev(x: pd.Series) -> pd.Series:
    prev_x = x.shift(1)
    prev_x.iloc[:1] = x.values[0]
    return prev_x


def offensive_value(
    actions: pd.DataFrame, scores: pd.Series, concedes: pd.Series
) -> pd.Series:
    """Change in scoring probability produced by each action."""
    sameteam = _prev(actions['team_id']) == actions['team_id']
    prev_scores = _prev(scores) * sameteam + _prev(concedes) * (~sameteam)
    prevgoal = _prev(actions['type_name']).isin(['goal', 'owngoal'])
    prev_scores = prev_scores.mask(prevgoal, 0)
    return scores - prev_scores


def defensive_value(
    actions: pd.DataFrame, scores: pd.Series, concedes: pd.Series
) -> pd.Series:
    """Change in conceding probability produced by each action."""
    sameteam = _prev(actions['team_id']) == actions['team_id']
    prev_concedes = _prev(concedes) * sameteam + _prev(scores) * (~sameteam)
    prevgoal = _prev(actions['type_name']).isin(['goal', 'owngoal'])
    prev_concedes = prev_concedes.mask(prevgoal, 0)
    return -(concedes - prev_concedes)


def value(actions: pd.DataFrame, Pscores: pd.Series, Pconcedes: pd.Series) -> pd.DataFrame:
    """Offensive, defensive and total VAEP value of each atomic action."""
    v = pd.DataFrame(index=actions.index)
    v['offensive_value'] = offensive_value(actions, Pscores, Pconcedes)
    v['defensive_value'] = defensive_value(actions, Pscores, Pconcedes)
    v['vaep_value'] = v['offensive_value'] + v['defensive_value']
    return v
