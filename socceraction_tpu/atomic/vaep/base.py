"""The Atomic-VAEP model class.

Parity: reference ``socceraction/atomic/vaep/base.py:34-79`` — a subclass
of :class:`~socceraction_tpu.vaep.base.VAEP` that swaps the class-level
module handles (the "shared transform core + per-language specialization"
coupling noted in SURVEY §2) plus, in this build, the packed-tensor kernel
handles and the atomic batch packer.
"""

from __future__ import annotations

from typing import Any, List

from ...core.batch import AtomicActionBatch, pack_atomic_actions
from ...ops import atomic as _atomicops
from ...vaep.base import VAEP
from .. import spadl as spadlcfg
from . import features as fs
from . import formula as vaepformula
from . import labels as lab

__all__ = ['AtomicVAEP', 'xfns_default']

xfns_default: List[fs.FeatureTransfomer] = [
    fs.actiontype,
    fs.actiontype_onehot,
    fs.bodypart,
    fs.bodypart_onehot,
    fs.time,
    fs.team,
    fs.time_delta,
    fs.location,
    fs.polar,
    fs.movement_polar,
    fs.direction,
    fs.goalscore,
]


class AtomicVAEP(VAEP):
    """VAEP over atomic actions.

    Distinguishes the contribution of the player who initiates an action
    (e.g. gives the pass) from the player who completes it (e.g. receives
    the pass). Same API and backends as :class:`VAEP`.
    """

    _spadlcfg = spadlcfg
    _fs = fs
    _lab = lab
    _vaep = vaepformula
    _kernels = _atomicops.ATOMIC_KERNELS
    _compute_features_kernel = staticmethod(_atomicops.compute_features)
    _labels_kernel = staticmethod(_atomicops.scores_concedes)
    _formula_kernel = staticmethod(_atomicops.vaep_values)
    _fused_registry = 'atomic'

    def _default_xfns(self) -> List[fs.FeatureTransfomer]:
        return list(xfns_default)

    def _pack(self, game_actions: Any, home_team_id: int) -> AtomicActionBatch:
        batch, _ = pack_atomic_actions(game_actions, home_team_id=home_team_id)
        return batch
