"""Framework-wide configuration knobs.

The reference has no config system -- its knobs are module-level constants
scattered across files (SURVEY.md section 5). This module centralizes
exactly those knobs so both backends read one source of truth:

===========================  =========  ==========================================
knob                         default    reference source
===========================  =========  ==========================================
XT_GRID_LENGTH (N)           16         socceraction/xthreat.py:22
XT_GRID_WIDTH (M)            12         socceraction/xthreat.py:21
XT_EPS                       1e-5       socceraction/xthreat.py:267
LABEL_LOOKAHEAD              10         socceraction/vaep/labels.py:9
SAMEPHASE_SECONDS            10         socceraction/vaep/formula.py:14
PENALTY_PRIOR                0.792453   socceraction/vaep/formula.py:62
CORNER_PRIOR                 0.046500   socceraction/vaep/formula.py:66
NB_PREV_ACTIONS              3          socceraction/vaep/base.py:90
MIN_DRIBBLE_LENGTH           3.0        socceraction/spadl/base.py:49
MAX_DRIBBLE_LENGTH           60.0       socceraction/spadl/base.py:50
MAX_DRIBBLE_DURATION         10.0       socceraction/spadl/base.py:51
===========================  =========  ==========================================

Plus the TPU-build additions: the default execution backend and packing
alignment.
"""

from __future__ import annotations

import os
from typing import Optional

# xT grid
XT_GRID_LENGTH: int = 16  # N: cells along pitch length (x)
XT_GRID_WIDTH: int = 12  # M: cells along pitch width (y)
XT_EPS: float = 1e-5

# VAEP
LABEL_LOOKAHEAD: int = 10
SAMEPHASE_SECONDS: float = 10
PENALTY_PRIOR: float = 0.792453
CORNER_PRIOR: float = 0.046500
NB_PREV_ACTIONS: int = 3

# dribble synthesis (SPADL converters)
MIN_DRIBBLE_LENGTH: float = 3.0
MAX_DRIBBLE_LENGTH: float = 60.0
MAX_DRIBBLE_DURATION: float = 10.0

# TPU runtime
DEFAULT_BACKEND: str = 'jax'
ACTION_AXIS_ALIGNMENT: int = 128  # TPU lane width the action axis pads to

#: Environment variable naming the persistent XLA compilation cache
#: directory — the middle tier of the cold-start ladder (shipped AOT
#: executables > this cache > cold compile). Unset (the default) leaves
#: jax's compilation cache off; pointing it at a shared directory makes
#: every replica after the first hit warm compiles instead of paying
#: XLA again. Applied lazily by
#: :func:`socceraction_tpu.serve.aot.enable_compile_cache` (wired into
#: ``RatingService.warmup``) so this module stays import-light.
COMPILE_CACHE_ENV: str = 'SOCCERACTION_TPU_COMPILE_CACHE'


def compile_cache_dir() -> Optional[str]:
    """The configured persistent compile-cache directory, or ``None``.

    Reads ``SOCCERACTION_TPU_COMPILE_CACHE`` at call time (not import
    time — tests and the cold-start bench flip it per subprocess); an
    empty value means disabled, same as unset.
    """
    path = os.environ.get(COMPILE_CACHE_ENV, '').strip()
    return path or None
