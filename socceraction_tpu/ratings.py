"""Player-level aggregation of action values.

The reference ships this only as notebook code
(``public-notebooks/4-compute-vaep-values-and-top-players.ipynb``: per-player
sums of VAEP values, minutes-played normalization to a per-90 rating, and a
minimum-minutes cut); here it is library API.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pandas as pd

__all__ = ['player_ratings']

_VALUE_COLS = ['vaep_value', 'offensive_value', 'defensive_value']


def player_ratings(
    rated_actions: pd.DataFrame,
    players: Optional[pd.DataFrame] = None,
    player_games: Optional[pd.DataFrame] = None,
    min_minutes: float = 180.0,
) -> pd.DataFrame:
    """Aggregate rated actions into per-player (per-90) VAEP ratings.

    Parameters
    ----------
    rated_actions : pd.DataFrame
        Actions with ``player_id`` and the value columns produced by
        ``VAEP.rate`` (``vaep_value``, ``offensive_value``,
        ``defensive_value``).
    players : pd.DataFrame, optional
        Player table with ``player_id`` and ``player_name`` (and optionally
        ``nickname``, preferred when non-empty, like the reference
        notebook).
    player_games : pd.DataFrame, optional
        Per-game appearances with ``player_id`` and ``minutes_played``
        (e.g. from
        :func:`~socceraction_tpu.data.statsbomb.extract_player_games`).
        When given, adds ``*_rating`` columns normalized to 90 minutes and
        drops players with ``min_minutes`` total minutes or fewer.
    min_minutes : float
        Cut-off on total minutes for the normalized table; the boundary is
        EXCLUSIVE (strictly more than ``min_minutes`` survives), matching
        the reference notebook's ``minutes_played > 180`` filter
        (reference public-notebooks/4-compute-vaep-values-and-top-players.ipynb,
        comment "at least two full games").

    Returns
    -------
    pd.DataFrame
        One row per player, sorted by total (or per-90, when normalized)
        VAEP value, descending.
    """
    cols = [c for c in _VALUE_COLS if c in rated_actions.columns]
    if not cols:
        raise ValueError(
            f'rated_actions must contain at least one of {_VALUE_COLS}'
        )
    summed = (
        rated_actions[['player_id', *cols]]
        .groupby('player_id')
        .agg(count=('player_id', 'size'), **{c: (c, 'sum') for c in cols})
        .reset_index()
    )

    if players is not None:
        name_cols = [c for c in ('nickname', 'player_name') if c in players.columns]
        lookup = players[['player_id', *name_cols]].drop_duplicates('player_id')
        summed = summed.merge(lookup, on='player_id', how='left')
        if 'nickname' in name_cols and 'player_name' in name_cols:
            nick = summed['nickname']
            use_nick = nick.notna() & (nick.astype(str) != '')
            summed['player_name'] = np.where(
                use_nick, nick, summed['player_name']
            )
            summed = summed.drop(columns=['nickname'])

    sort_col = cols[0] if 'vaep_value' not in cols else 'vaep_value'
    if player_games is not None:
        minutes = (
            player_games[['player_id', 'minutes_played']]
            .groupby('player_id')
            .sum()
            .reset_index()
        )
        summed = summed.merge(minutes, on='player_id', how='inner')
        summed = summed[summed['minutes_played'] > min_minutes]
        for c in cols:
            summed[c.replace('_value', '_rating')] = (
                summed[c] * 90.0 / summed['minutes_played']
            )
        sort_col = sort_col.replace('_value', '_rating')
    return summed.sort_values(sort_col, ascending=False).reset_index(drop=True)
