"""Expected goals (xG): P(goal) models over SPADL shots.

Library-API form of the reference's xG recipe
(``public-notebooks/EXTRA-build-expected-goals-model.ipynb``), which is
notebook-only upstream: gamestate features restricted to shot actions,
shot-success labels, one binary classifier, Brier/AUC/log-loss report.
The notebook's feature recipe is reproduced exactly — its ``xfns`` list
at ``nb_prev_actions=2``, minus the columns that leak the shot's own
identity or outcome (``type_*_a0`` one-hots: every row is a shot;
``dx_a0``/``dy_a0``/``movement_a0``: the shot's end point encodes where
the ball went).

The estimator rides the same infrastructure as VAEP: feature
transformers from :mod:`socceraction_tpu.vaep.features`, learners from
:mod:`socceraction_tpu.ml.learners` (logistic regression and XGBoost as
in the notebook, plus the JAX MLP and the other boosters).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from .spadl import config as spadlconfig
from .spadl import utils as spadlutils
from .vaep import features as fs
from .vaep.labels import goal_from_shot

__all__ = ['XGModel', 'xfns_default']

#: The reference notebook's transformer set (EXTRA notebook, cell 6).
xfns_default: List[fs.FeatureTransfomer] = [
    fs.actiontype_onehot,
    fs.bodypart_onehot,
    fs.startlocation,
    fs.movement,
    fs.space_delta,
    fs.startpolar,
    fs.team,
]

#: Feature columns removed from the matrix (EXTRA notebook, cell 6):
#: the shot's own action-type one-hot block and its movement columns.
_LEAKY = re.compile(r'^type_[a-z_]+_a0$')
_LEAKY_EXACT = frozenset({'dx_a0', 'dy_a0', 'movement_a0'})


def _fit_logistic(
    X: Any,
    y: Any,
    eval_set: Any = None,
    tree_params: Optional[Dict[str, Any]] = None,
    fit_params: Optional[Dict[str, Any]] = None,
) -> Any:
    """The notebook's first model: logistic regression.

    Standardization is added for solver conditioning (the notebook fits
    raw columns and rides out the convergence warning); predictions are
    the same model family, the scaler only affects the optimizer path.
    """
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler

    clf = make_pipeline(
        StandardScaler(), LogisticRegression(max_iter=1000, **(tree_params or {}))
    )
    return clf.fit(X, y, **(fit_params or {}))


class XGModel:
    """An xG estimator over SPADL shots.

    Parameters
    ----------
    xfns : list of feature transformers, optional
        Defaults to the reference notebook's set (:data:`xfns_default`).
    nb_prev_actions : int
        Game-state depth; the notebook uses 2.
    drop_leaky : bool
        Remove the shot's own type one-hots and movement columns like the
        notebook does. Disable to keep the full feature matrix.
    """

    def __init__(
        self,
        xfns: Optional[Sequence[fs.FeatureTransfomer]] = None,
        nb_prev_actions: int = 2,
        drop_leaky: bool = True,
    ) -> None:
        self.xfns = list(xfns) if xfns is not None else list(xfns_default)
        self.nb_prev_actions = nb_prev_actions
        self.drop_leaky = drop_leaky
        self.clf = None
        # constant for a given (xfns, k, drop_leaky); deriving it executes
        # every transformer on a dummy frame, so do it once
        names = fs.feature_column_names(self.xfns, self.nb_prev_actions)
        if self.drop_leaky:
            names = [
                n for n in names
                if not _LEAKY.match(n) and n not in _LEAKY_EXACT
            ]
        self._feature_names = names

    # ------------------------------------------------------------------
    # features / labels
    # ------------------------------------------------------------------

    def _shot_states(
        self, game: Any, game_actions: pd.DataFrame
    ) -> tuple[pd.DataFrame, Any, np.ndarray]:
        # gamestates' shifted views assume a RangeIndex; normalize so
        # filtered/sliced caller frames don't misalign the axis=1 concat
        actions = spadlutils.add_names(game_actions.reset_index(drop=True))
        states = fs.play_left_to_right(
            fs.gamestates(actions, self.nb_prev_actions), game.home_team_id
        )
        shots = actions['type_id'].isin(spadlconfig.SHOT_LIKE).to_numpy()
        return actions, states, shots

    def _shot_features(self, states: Any, shots: np.ndarray) -> pd.DataFrame:
        feats = pd.concat([fn(states) for fn in self.xfns], axis=1)
        return feats.loc[shots, self._feature_names]

    def feature_column_names(self) -> List[str]:
        """Feature columns after the notebook's leak filter."""
        return list(self._feature_names)

    def compute_features(self, game: Any, game_actions: pd.DataFrame) -> pd.DataFrame:
        """Game-state features of the game's shots (one row per shot)."""
        _, states, shots = self._shot_states(game, game_actions)
        return self._shot_features(states, shots)

    def compute_labels(self, game: Any, game_actions: pd.DataFrame) -> pd.DataFrame:
        """``goal`` label per shot: the shot scored.

        Delegates to :func:`~socceraction_tpu.vaep.labels.goal_from_shot`
        so the goal definition cannot drift from the VAEP labels. Labels
        need no game states, so none are built (unlike the feature path).
        """
        actions = spadlutils.add_names(game_actions.reset_index(drop=True))
        shots = actions['type_id'].isin(spadlconfig.SHOT_LIKE).to_numpy()
        goal = goal_from_shot(actions)['goal_from_shot'].to_numpy()
        return pd.DataFrame({'goal': goal[shots]})

    # ------------------------------------------------------------------
    # fit / estimate / score
    # ------------------------------------------------------------------

    def fit(
        self,
        X: pd.DataFrame,
        y: pd.DataFrame,
        learner: str = 'logistic',
        **kwargs,
    ) -> 'XGModel':
        """Fit P(goal | shot features).

        ``learner`` is ``'logistic'`` or ``'xgboost'`` (the notebook's two
        models) or any registered VAEP learner (``sklearn``, ``catboost``,
        ``lightgbm``, ``mlp``).
        """
        from .ml.learners import LEARNERS

        learners: Dict[str, Callable] = {'logistic': _fit_logistic, **LEARNERS}
        if learner not in learners:
            raise ValueError(
                f'unknown learner {learner!r}; choose from {sorted(learners)}'
            )
        yv = (y['goal'] if isinstance(y, pd.DataFrame) else y).astype(int)
        kwargs.setdefault('eval_set', None)  # caller-supplied eval_set wins
        self.clf = learners[learner](X, yv, **kwargs)
        return self

    def estimate(self, game: Any, game_actions: pd.DataFrame) -> pd.DataFrame:
        """xG of every action: P(goal) for shots, NaN elsewhere.

        Returns a frame aligned with ``game_actions`` (like
        ``ExpectedThreat.rate``'s NaN pattern for non-move actions).
        """
        if self.clf is None:
            raise ValueError('fit the model before calling estimate')
        _, states, shots = self._shot_states(game, game_actions)
        xg = np.full(len(shots), np.nan)
        if shots.any():
            xg[shots] = self.clf.predict_proba(
                self._shot_features(states, shots)
            )[:, 1]
        return pd.DataFrame({'xg': xg}, index=game_actions.index)

    def score(self, X: pd.DataFrame, y: pd.DataFrame) -> Dict[str, float]:
        """Brier, ROC-AUC and log loss (the notebook's report)."""
        from sklearn.metrics import brier_score_loss, log_loss, roc_auc_score

        if self.clf is None:
            raise ValueError('fit the model before calling score')
        yv = (y['goal'] if isinstance(y, pd.DataFrame) else y).astype(int)
        p = self.clf.predict_proba(X)[:, 1]
        out = {'brier': float(brier_score_loss(yv, p))}
        if yv.nunique() > 1:
            out['auroc'] = float(roc_auc_score(yv, p))
            out['log_loss'] = float(log_loss(yv, p))
        return out
