"""socceraction-tpu: a TPU-native soccer action-valuation framework.

A brand-new framework with the capabilities of `socceraction` (reference:
``/root/reference``, fork of ML-KULeuven/socceraction v1.2.3) redesigned
around a columnar action-tensor runtime executed with JAX/XLA on TPU:

- :mod:`socceraction_tpu.spadl` -- the SPADL action language: vocabulary,
  schemas and provider converters.
- :mod:`socceraction_tpu.core` -- the columnar ``ActionBatch`` tensor bundle
  that packs seasons of SPADL actions into padded ``(game, action)`` device
  arrays.
- :mod:`socceraction_tpu.ops` -- the JAX/XLA kernels for the valuation hot
  paths (xT value iteration, VAEP feature/label/formula transforms).
- :mod:`socceraction_tpu.xthreat` -- the Expected Threat (xT) model.
- :mod:`socceraction_tpu.xg` -- expected-goals models over SPADL shots.
"""

__version__ = '0.1.0'
