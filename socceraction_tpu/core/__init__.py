"""Columnar action-tensor runtime core."""

from .batch import ActionBatch, pack_actions, pad_length, unpack_values

__all__ = ['ActionBatch', 'pack_actions', 'pad_length', 'unpack_values']
