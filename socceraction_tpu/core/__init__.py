"""Columnar action-tensor runtime core."""

from .batch import (
    ActionBatch,
    AtomicActionBatch,
    pack_actions,
    pack_atomic_actions,
    pad_length,
    unpack_values,
)

__all__ = [
    'ActionBatch',
    'AtomicActionBatch',
    'pack_actions',
    'pack_atomic_actions',
    'pad_length',
    'unpack_values',
]
