"""The columnar action-tensor bundle at the heart of the TPU runtime.

The reference operates on one pandas DataFrame per game, row by row. The
TPU-native design instead packs a whole *collection* of games into a padded
struct-of-arrays bundle of shape ``(G games, A actions)`` living in HBM:

- integer categorical columns (type/result/bodypart/period) as ``int32``,
- coordinates and timestamps as ``float32`` (or ``float64`` off-TPU),
- team identity reduced to an ``is_home`` bool -- soccer has exactly two
  teams per game, so every team-equality predicate used downstream
  (possession flags in features, label team checks, formula team continuity)
  is equivalent to equality of ``is_home`` flags,
- a validity ``mask`` plus per-game length vector for the padding.

Games are left-aligned and padded to a common ``A`` (rounded up to a
multiple of 128 to keep the TPU lane dimension aligned). Every valuation
kernel in :mod:`socceraction_tpu.ops` is written per-game on ``(A,)`` arrays
and ``jax.vmap``-ed over the game axis; the game axis is the data-parallel
sharding axis (see :mod:`socceraction_tpu.parallel`).

This replaces the reference's per-game DataFrame plumbing (e.g.
``socceraction/vaep/base.py:97-137`` computing features game by game).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
from flax import struct

__all__ = [
    'ActionBatch',
    'AtomicActionBatch',
    'pack_actions',
    'pack_atomic_actions',
    'pack_row_values',
    'unpack_values',
    'pad_length',
    'bucket_games',
    'bucket_ladder',
    'bucket_window',
    'window_ladder',
    'pad_batch_games',
]

from ..config import ACTION_AXIS_ALIGNMENT

# TPU vector lanes are 128 wide; keeping the action axis a multiple of the
# lane width lets XLA tile elementwise kernels without a ragged remainder.
_LANE = ACTION_AXIS_ALIGNMENT


def pad_length(n: int, multiple: int = _LANE) -> int:
    """Round ``n`` up to a multiple of ``multiple`` (minimum one tile)."""
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


@struct.dataclass
class ActionBatch:
    """A padded ``(G, A)`` struct-of-arrays bundle of SPADL actions.

    All per-action fields have shape ``(G, A)``; per-game fields ``(G,)``.
    """

    # per-action categorical / ordinal
    type_id: jax.Array  # int32
    result_id: jax.Array  # int32
    bodypart_id: jax.Array  # int32
    period_id: jax.Array  # int32
    is_home: jax.Array  # bool: team_id == home_team_id
    # per-action continuous
    time_seconds: jax.Array  # float
    start_x: jax.Array  # float
    start_y: jax.Array  # float
    end_x: jax.Array  # float
    end_y: jax.Array  # float
    # padding bookkeeping
    mask: jax.Array  # bool (G, A): True on valid rows
    n_actions: jax.Array  # int32 (G,): valid rows per game
    # host-side identity (static, not involved in kernels)
    game_id: jax.Array  # (G,) int64-as-int32-safe identifier index
    row_index: jax.Array  # (G, A) int32: positional row in the source frame (-1 pad)

    @property
    def n_games(self) -> int:
        """Number of games (leading axis) in the batch."""
        return self.type_id.shape[0]

    @property
    def max_actions(self) -> int:
        """Padded per-game action capacity (second axis)."""
        return self.type_id.shape[1]

    @property
    def total_actions(self) -> int:
        """Total number of valid (unpadded) actions, as a host int."""
        return int(np.asarray(jax.device_get(self.n_actions)).sum())

    def astype(self, float_dtype: Any) -> 'ActionBatch':
        """Return a copy with the continuous fields cast to ``float_dtype``."""
        return self.replace(
            time_seconds=self.time_seconds.astype(float_dtype),
            start_x=self.start_x.astype(float_dtype),
            start_y=self.start_y.astype(float_dtype),
            end_x=self.end_x.astype(float_dtype),
            end_y=self.end_y.astype(float_dtype),
        )


@struct.dataclass
class AtomicActionBatch:
    """A padded ``(G, A)`` struct-of-arrays bundle of Atomic-SPADL actions.

    Atomic rows carry a location and displacement ``(x, y, dx, dy)`` and no
    result (outcomes are themselves action types).
    """

    type_id: jax.Array  # int32
    bodypart_id: jax.Array  # int32
    period_id: jax.Array  # int32
    is_home: jax.Array  # bool
    time_seconds: jax.Array  # float
    x: jax.Array  # float
    y: jax.Array  # float
    dx: jax.Array  # float
    dy: jax.Array  # float
    mask: jax.Array  # bool (G, A)
    n_actions: jax.Array  # int32 (G,)
    game_id: jax.Array  # (G,) int32 index
    row_index: jax.Array  # (G, A) int32 (-1 pad)

    n_games = ActionBatch.n_games
    max_actions = ActionBatch.max_actions
    total_actions = ActionBatch.total_actions


_FLOAT_COLS = ('time_seconds', 'start_x', 'start_y', 'end_x', 'end_y')
_INT_COLS = ('type_id', 'result_id', 'bodypart_id', 'period_id')
_ATOMIC_FLOAT_COLS = ('time_seconds', 'x', 'y', 'dx', 'dy')
_ATOMIC_INT_COLS = ('type_id', 'bodypart_id', 'period_id')


def _pack_frame(
    actions: pd.DataFrame,
    home_team_ids: Any,
    home_team_id: Optional[Any],
    max_actions: Optional[int],
    float_dtype: Any,
    device: Any,
    float_cols: Tuple[str, ...],
    int_cols: Tuple[str, ...],
    make_batch: Any,
    as_numpy: bool = False,
) -> Tuple[Any, Any]:
    """Shared packing core: group by game, left-align, pad, build the batch.

    ``make_batch`` is the batch dataclass constructor, called with one
    keyword per packed column (``float_cols`` + ``int_cols``) plus
    ``is_home``, ``mask``, ``n_actions``, ``game_id`` and ``row_index``.

    ``as_numpy=True`` keeps every field a host numpy array (a staging
    batch): no implicit host→device copy happens inside the pack, so a
    streaming feed can overlap the explicit transfer of chunk N+1 with
    device compute on chunk N (``pipeline/feed.py``), and the packed-cache
    builder can write columns straight into its memmaps without a device
    round trip. Mutually exclusive with ``device``.
    """
    if 'game_id' not in actions.columns:
        raise ValueError('actions frame must contain a game_id column')
    if len(actions) == 0:
        raise ValueError('cannot pack an empty actions frame')

    # Fully vectorized packing: one scatter per column instead of a
    # per-game Python loop (the loop measured 0.56M actions/s on host —
    # BELOW the 1M/s device rating target, making packing the bottleneck
    # of any cold store -> rate pipeline).
    # Stable game order: order of first appearance (factorize contract).
    gi, game_index = pd.factorize(actions['game_id'], sort=False)
    game_ids = list(game_index)
    n_games = len(game_ids)
    # position of each row within its game, in frame order
    pos = actions.groupby(gi, sort=False).cumcount().to_numpy()
    n_actions = np.bincount(gi, minlength=n_games).astype(np.int32)

    if home_team_ids is None:
        if home_team_id is not None:
            home_team_ids = {g: home_team_id for g in game_ids}
        elif 'home_team_id' in actions.columns:
            home_team_ids = (
                actions.groupby('game_id', sort=False)['home_team_id'].first().to_dict()
            )
        else:
            raise ValueError('home_team_ids (or home_team_id) is required')

    longest = int(n_actions.max())
    A = max_actions if max_actions is not None else pad_length(longest)
    if longest > A:
        raise ValueError(f'game of length {longest} exceeds max_actions={A}')

    flat = gi * A + pos  # destination of every source row in a (G, A) grid

    def scatter(values, dtype, fill=0):
        out = np.full(n_games * A, fill, dtype=dtype)
        out[flat] = values
        return out.reshape(n_games, A)

    cols = {
        c: scatter(actions[c].to_numpy(dtype=float_dtype), float_dtype)
        for c in float_cols
    }
    cols.update(
        {
            c: scatter(
                actions[c].to_numpy(dtype=np.int64).astype(np.int32), np.int32
            )
            for c in int_cols
        }
    )
    home_of_game = np.asarray([home_team_ids[g] for g in game_ids])
    is_home = scatter(
        actions['team_id'].to_numpy() == home_of_game[gi], bool, False
    )
    mask = scatter(np.ones(len(actions), dtype=bool), bool, False)
    row_index = scatter(
        np.arange(len(actions), dtype=np.int32), np.int32, -1
    )

    if as_numpy:
        if device is not None:
            raise ValueError('as_numpy and device are mutually exclusive')
        return make_batch(
            **cols,
            is_home=is_home,
            mask=mask,
            n_actions=n_actions,
            game_id=np.arange(n_games, dtype=np.int32),
            row_index=row_index,
        ), game_ids

    jcols = {c: jnp.asarray(v) for c, v in cols.items()}
    batch = make_batch(
        **jcols,
        is_home=jnp.asarray(is_home),
        mask=jnp.asarray(mask),
        n_actions=jnp.asarray(n_actions),
        game_id=jnp.arange(n_games, dtype=jnp.int32),
        row_index=jnp.asarray(row_index),
    )
    if device is not None:
        batch = jax.device_put(batch, device)
    return batch, game_ids


def pack_actions(
    actions: pd.DataFrame,
    home_team_ids: Optional[Dict[Any, Any]] = None,
    *,
    home_team_id: Optional[Any] = None,
    max_actions: Optional[int] = None,
    float_dtype: Any = np.float32,
    device: Optional[Any] = None,
    as_numpy: bool = False,
) -> Tuple[ActionBatch, List[Any]]:
    """Pack a SPADL DataFrame (one or many games) into an :class:`ActionBatch`.

    Parameters
    ----------
    actions : pd.DataFrame
        SPADL actions, ordered within each game. May contain any number of
        games (distinguished by ``game_id``).
    home_team_ids : dict, optional
        Mapping ``game_id -> home_team_id``. Required for multi-game frames
        unless ``home_team_id`` is given.
    home_team_id : optional
        Home team for a single-game frame (reference-style call sites pass
        one game plus its home team).
    max_actions : int, optional
        Pad/clamp the action axis to this length. Defaults to the longest
        game rounded up to a lane multiple.
    float_dtype
        dtype of continuous fields (float32 on TPU, float64 for parity runs).
    device : optional
        If given, ``jax.device_put`` the batch onto this device/sharding.
    as_numpy : bool
        Return a host staging batch (every field a numpy array, no device
        copy) for callers that transfer explicitly or write to memmaps;
        mutually exclusive with ``device``.

    Returns
    -------
    (ActionBatch, list)
        The packed batch and the list of game_ids in game-axis order.
    """
    return _pack_frame(
        actions, home_team_ids, home_team_id, max_actions, float_dtype, device,
        _FLOAT_COLS, _INT_COLS, ActionBatch, as_numpy,
    )


def pack_atomic_actions(
    actions: pd.DataFrame,
    home_team_ids: Optional[Dict[Any, Any]] = None,
    *,
    home_team_id: Optional[Any] = None,
    max_actions: Optional[int] = None,
    float_dtype: Any = np.float32,
    device: Optional[Any] = None,
    as_numpy: bool = False,
) -> Tuple[AtomicActionBatch, List[Any]]:
    """Pack an Atomic-SPADL DataFrame into an :class:`AtomicActionBatch`.

    Same contract as :func:`pack_actions` but for atomic frames
    (``x, y, dx, dy``; no result column).
    """
    return _pack_frame(
        actions, home_team_ids, home_team_id, max_actions, float_dtype, device,
        _ATOMIC_FLOAT_COLS, _ATOMIC_INT_COLS, AtomicActionBatch, as_numpy,
    )


def bucket_games(n: int) -> int:
    """Round a game count up to its shape bucket (the next power of two).

    Every distinct leading-axis length is a distinct XLA compilation; a
    caller that rates arbitrary-length batches retraces once per unique
    row count. Padding the game axis to a power-of-two ladder caps the
    compiled-shape set at ``log2(max_games)`` entries — the bucket
    discipline shared by :meth:`~socceraction_tpu.vaep.base.VAEP.rate_batch`
    and the online batcher (:mod:`socceraction_tpu.serve.batcher`).
    """
    if n < 1:
        raise ValueError(f'need at least one game, got {n}')
    return 1 << (n - 1).bit_length()


def bucket_ladder(max_games: int) -> Tuple[int, ...]:
    """The full bucket ladder up to ``max_games``: ``(1, 2, 4, ..., B)``.

    ``max_games`` itself is rounded up to a bucket, so the top rung always
    admits a full batch.
    """
    top = bucket_games(max_games)
    return tuple(1 << i for i in range(top.bit_length()))


def bucket_window(n: int, max_actions: int) -> int:
    """Round a valid-action count up to its window-length rung.

    The time-axis analog of :func:`bucket_games`: serving a sequence head
    over windows whose action axis tracks the longest live game would
    retrace once per unique length. Rungs are power-of-two multiples of
    the 128-wide lane tile (128, 256, 512, ...) capped at ``max_actions``,
    so the compiled-shape set stays ``O(log2(max_actions / 128))`` and
    every rung keeps the action axis MXU/VPU tile aligned.
    """
    if n < 0:
        raise ValueError(f'need a non-negative action count, got {n}')
    if max_actions < 1:
        raise ValueError(f'need a positive capacity, got {max_actions}')
    rung = pad_length(max(n, 1))
    rung = 1 << (rung - 1).bit_length()
    return min(rung, max_actions)


def window_ladder(max_actions: int) -> Tuple[int, ...]:
    """Every window-length rung up to ``max_actions``, ascending.

    ``max_actions`` itself is always the top rung (it is the capacity the
    service padded to at pack time, not necessarily a power of two), so a
    full-capacity window never retraces outside the warmed set.
    """
    rungs = []
    n = 1
    while True:
        rung = bucket_window(n, max_actions)
        rungs.append(rung)
        if rung >= max_actions:
            break
        n = rung + 1
    return tuple(rungs)


def pad_batch_games(batch: Any, n_games: int) -> Any:
    """Pad a batch's game axis to ``n_games`` with masked padding games.

    Works on :class:`ActionBatch` and :class:`AtomicActionBatch` with
    either host (numpy) or device fields. Padding games carry all-False
    masks, ``n_actions == 0`` and ``row_index == -1``, so every masked
    consumer (``unpack_values``, the label/formula kernels' valid rows)
    ignores them; their computed values are garbage by contract and must
    be sliced away by the caller.
    """
    G = batch.n_games
    if n_games == G:
        return batch
    if n_games < G:
        raise ValueError(f'cannot pad {G} games down to {n_games}')

    def pad(a, fill=0):
        width = [(0, n_games - G)] + [(0, 0)] * (a.ndim - 1)
        if isinstance(a, np.ndarray):
            return np.pad(a, width, constant_values=fill)
        return jnp.pad(a, width, constant_values=fill)

    padded = jax.tree.map(pad, batch)
    return padded.replace(row_index=pad(batch.row_index, fill=-1))


def pack_row_values(values: Any, batch: ActionBatch, *, fill: Any = 0) -> np.ndarray:
    """Scatter per-row values into a batch's ``(G, A)`` layout.

    The inverse of :func:`unpack_values`: ``values`` is aligned with the
    positional row order of the DataFrame that was packed (one entry per
    valid action), and comes back as a ``(G, A)`` host array with
    ``fill`` in every padding slot — ready to ride along the batch into
    a kernel (e.g. the per-action ``group_id`` of a batched xT fit).

    Parameters
    ----------
    values : array-like
        Shape ``(total_actions,)``, one value per source-frame row.
    batch : ActionBatch
        The batch whose layout to scatter into.
    fill
        Value for padding slots (default 0; grouped xT uses ``-1``,
        the "in no group" id every kernel drops).
    """
    vals = np.asarray(values)
    ri = np.asarray(jax.device_get(batch.row_index))
    valid = ri >= 0
    if vals.shape[:1] != (int(valid.sum()),):
        raise ValueError(
            f'got {vals.shape[0]} values for a batch of {int(valid.sum())} '
            'valid actions'
        )
    out = np.full(ri.shape, fill, dtype=vals.dtype)
    out[valid] = vals[ri[valid]]
    return out


def unpack_values(values: Any, batch: ActionBatch) -> np.ndarray:
    """Return per-action device output in the source frame's row order.

    Padding rows are dropped and valid rows are scattered back to the
    positional order of the DataFrame that was packed, so
    ``df['rating'] = unpack_values(model.rate(batch), batch)`` aligns
    correctly even when games were interleaved in the source frame.

    Parameters
    ----------
    values : array
        Shape ``(G, A)`` or ``(G, A, F)`` device/host array.
    batch : ActionBatch
        The batch the values were computed for.

    Returns
    -------
    np.ndarray
        Shape ``(total_actions,)`` or ``(total_actions, F)``.
    """
    arr = np.asarray(jax.device_get(values))
    mask = np.asarray(jax.device_get(batch.mask))
    rows = np.asarray(jax.device_get(batch.row_index))[mask]
    picked = arr[mask]
    out = np.empty_like(picked)
    out[rows] = picked
    return out
