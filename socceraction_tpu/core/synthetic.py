"""Synthetic SPADL action streams for benchmarks and compile checks.

Generates statistically plausible (not physically consistent) action
tensors directly as an :class:`ActionBatch` — no pandas round-trip — so
benchmarks measure kernel throughput, not host packing.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:  # pandas is imported lazily inside the frame generator
    import pandas as pd

from ..config import CORNER_PRIOR, PENALTY_PRIOR
from ..spadl import config as spadlconfig
from .batch import ActionBatch

__all__ = [
    'append_synthetic_games',
    'synthetic_batch',
    'write_synthetic_season',
]


def _draw_spadl_columns(
    rng: 'np.random.Generator', G: int, A: int, float_dtype: type, int_dtype: type
) -> dict:
    """Draw the marginal SPADL column distributions for a ``(G, A)`` grid.

    Single source of the distributions shared by :func:`synthetic_batch`
    (float32/int32 device tensors) and :func:`write_synthetic_season`
    (float64/int64 store frames): action types loosely matching real SPADL
    streams (passes dominate, then dribbles, a tail over the rest),
    monotone period/clock, and end points as noisy displacements of start
    points. Cast points sit exactly where :func:`synthetic_batch` always
    had them so its draws stay bit-identical for a given seed.
    """
    n_types = len(spadlconfig.actiontypes)
    probs = np.full(n_types, 0.02)
    probs[spadlconfig.PASS] = 0.45
    probs[spadlconfig.DRIBBLE] = 0.25
    probs[spadlconfig.SHOT] = 0.03
    probs /= probs.sum()

    L, W = spadlconfig.field_length, spadlconfig.field_width
    type_id = rng.choice(n_types, size=(G, A), p=probs).astype(int_dtype)
    result_id = rng.choice(
        len(spadlconfig.results), size=(G, A), p=[0.25, 0.68, 0.02, 0.02, 0.02, 0.01]
    ).astype(int_dtype)
    bodypart_id = rng.choice(
        len(spadlconfig.bodyparts), size=(G, A), p=[0.85, 0.08, 0.05, 0.02]
    ).astype(int_dtype)
    period_id = np.sort(rng.integers(1, 5, size=(G, A)), axis=1).astype(int_dtype)
    time_seconds = np.sort(
        rng.uniform(0, 3000, size=(G, A)).astype(float_dtype), axis=1
    )
    start_x = rng.uniform(0, L, size=(G, A)).astype(float_dtype)
    start_y = rng.uniform(0, W, size=(G, A)).astype(float_dtype)
    end_x = np.clip(start_x + rng.normal(0, 12, size=(G, A)), 0, L).astype(float_dtype)
    end_y = np.clip(start_y + rng.normal(0, 8, size=(G, A)), 0, W).astype(float_dtype)
    is_home = rng.integers(0, 2, size=(G, A)).astype(bool)
    return {
        'type_id': type_id,
        'result_id': result_id,
        'bodypart_id': bodypart_id,
        'period_id': period_id,
        'time_seconds': time_seconds,
        'start_x': start_x,
        'start_y': start_y,
        'end_x': end_x,
        'end_y': end_y,
        'is_home': is_home,
    }


def synthetic_batch(
    n_games: int = 64,
    n_actions: int = 1664,
    *,
    fill: float = 1.0,
    seed: int = 0,
) -> ActionBatch:
    """Build a random but schema-valid ``(G, A)`` batch.

    Parameters
    ----------
    n_games, n_actions
        Batch shape. The default action count (1664 = 13×128) is the
        typical SPADL game length (~1.5-2.5k actions per game, SURVEY §5)
        rounded to a lane multiple.
    fill : float
        Fraction of each game's action axis that is valid (rest padding).
    seed : int
        numpy seed for reproducibility.
    """
    rng = np.random.default_rng(seed)
    G, A = n_games, n_actions
    n_valid = max(2, int(A * fill))

    cols = _draw_spadl_columns(rng, G, A, np.float32, np.int32)
    type_id, result_id, bodypart_id, period_id = (
        cols['type_id'], cols['result_id'], cols['bodypart_id'], cols['period_id']
    )
    time_seconds = cols['time_seconds']
    start_x, start_y = cols['start_x'], cols['start_y']
    end_x, end_y = cols['end_x'], cols['end_y']
    is_home = cols['is_home']

    mask = np.zeros((G, A), dtype=bool)
    mask[:, :n_valid] = True
    row_index = np.where(
        mask, np.arange(G * A).reshape(G, A) % (G * n_valid), -1
    ).astype(np.int32)
    # row_index must be a permutation of [0, total) over valid rows
    row_index[mask] = np.arange(G * n_valid, dtype=np.int32)

    return ActionBatch(
        type_id=jnp.asarray(type_id),
        result_id=jnp.asarray(result_id),
        bodypart_id=jnp.asarray(bodypart_id),
        period_id=jnp.asarray(period_id),
        is_home=jnp.asarray(is_home),
        time_seconds=jnp.asarray(time_seconds),
        start_x=jnp.asarray(start_x),
        start_y=jnp.asarray(start_y),
        end_x=jnp.asarray(end_x),
        end_y=jnp.asarray(end_y),
        mask=jnp.asarray(mask),
        n_actions=jnp.full(G, n_valid, dtype=jnp.int32),
        game_id=jnp.arange(G, dtype=jnp.int32),
        row_index=jnp.asarray(row_index),
    )


# --- possession-chain generator -------------------------------------------
# Role layout for the 11-player rosters: 1=GK, 2-5 DEF, 6-8 MID, 9-11 FWD.
# Roles pick who acts where (defenders in the own third, forwards up front)
# and carry persistent finishing skill, so player identity correlates with
# shot quality the way it does in real data.
_ROLE_OF = {1: 'gk', **{j: 'def' for j in (2, 3, 4, 5)},
            **{j: 'mid' for j in (6, 7, 8)}, **{j: 'fwd' for j in (9, 10, 11)}}
_FINISH_MULT = {'gk': 0.5, 'def': 0.8, 'mid': 1.0, 'fwd': 1.2}
_ZONE_ROLE_P = {
    0: {'gk': 0.05, 'def': 0.55, 'mid': 0.30, 'fwd': 0.10},
    1: {'gk': 0.01, 'def': 0.29, 'mid': 0.45, 'fwd': 0.25},
    2: {'gk': 0.01, 'def': 0.14, 'mid': 0.40, 'fwd': 0.45},
}
_ROLES = ['gk', 'def', 'mid', 'fwd']


def _team_strength(team_id: int) -> float:
    """Persistent per-team quality in [0.94, 1.06], a pure function of the
    team id — the same team is the same strength in every generated game."""
    return 1.0 + float(np.random.default_rng(int(team_id)).uniform(-0.06, 0.06))


def _player_finish(player_id: int, j: int) -> float:
    """Persistent finishing skill: role multiplier × a per-player jitter
    derived from the player id, stable across games and seeds."""
    jit = float(np.random.default_rng(int(player_id)).uniform(-0.08, 0.08))
    return _FINISH_MULT[_ROLE_OF[j]] * (1.0 + jit)


def synthetic_actions_frame(
    game_id: int = 1,
    *,
    home_team_id: int = 100,
    away_team_id: int = 200,
    n_actions: int = 1600,
    seed: int = 0,
    include_latents: bool = False,
) -> 'pd.DataFrame':
    """A schema-valid synthetic SPADL DataFrame for one game.

    Statistically plausible AND **learnable**: the generator simulates
    possession chains with the same *sequential* feature→label structure
    real soccer has, so models trained on these games must beat chance on
    held-out games (the air-gapped stand-in for the reference's real-data
    quality tier — see QUALITY.md), and history-aware features must beat
    location-only features on BOTH label heads (the ablation tiers):

    - **ball continuity**: each action starts where the previous one
      ended; a turnover hands the ball to the other team *at that spot*;
    - **possession quality** (``hot``): each possession is a hot attack
      (~22%) or cold circulation. Hot possessions build momentum (which
      multiplies move success, shot hazard and conversion); cold ones
      plateau low. The quality is hidden but telegraphed through the
      recent history — successes, forward progress, tempo — exactly what
      the ``team``/``time_delta``/``space_delta`` context transformers
      and k>1 state copies expose;
    - **fast breaks**: half the hot possessions (and most possessions won
      off a deep loss) play at counterattack tempo with shots from range
      that location-only features cannot tell from hopeless long shots;
    - **defensive exposure**: sustained forward commitment builds a
      per-team exposure latent; losing the ball over-committed
      (exposure > 0.40) springs a fast counter the other way, so a
      team's own recent long forward ``space_delta`` chain predicts
      *conceding* — the planted signal behind the concedes-head
      ablation;
    - **set pieces with the formula's priors**: failed dribbles in the
      box draw penalties converted at ``PENALTY_PRIOR`` (0.792453) and
      saved shots/corner situations yield ``corner_crossed`` sequences
      whose total conversion is pinned to ``CORNER_PRIOR`` (0.0465) —
      the constants the VAEP formula replaces prev-action xG with
      (``/root/reference/socceraction/vaep/formula.py:61-66``);
    - **bodyparts**: corner and cross deliveries are finished by headers
      (0.55× the foot conversion), long passes are sometimes headed on,
      so ``bodypart_id`` carries real signal;
    - **persistent skill**: team strength and per-player finishing are
      pure functions of the ids (:func:`_team_strength`,
      :func:`_player_finish`), stable across games — and correlated with
      observables because forwards both finish better and act in the
      attacking third;
    - **score effects**: a trailing team presses (higher shot hazard),
      giving the ``goalscore`` feature forward-looking signal.

    Measured ceilings and the committed-season numbers live in
    QUALITY.md; the executable floors in
    ``tests/test_quality_synthetic.py``.

    Used by the synthetic stand-in store
    (``tests/datasets/make_synthetic_store.py``) that lets the @e2e tier
    execute without network egress, by the xG tier (``tests/test_xg.py``)
    and by the walkthrough chapters.
    """
    import pandas as pd

    rng = np.random.default_rng(seed)
    n = int(n_actions)
    L, W = spadlconfig.field_length, spadlconfig.field_width
    half = n // 2

    other = {home_team_id: away_team_id, away_team_id: home_team_id}
    strength = {t: _team_strength(t) for t in (home_team_id, away_team_id)}
    finish = {
        t: {j: _player_finish(t * 1000 + j, j) for j in range(1, 12)}
        for t in (home_team_id, away_team_id)
    }

    CORNER = spadlconfig.actiontypes.index('corner_crossed')
    CROSS = spadlconfig.actiontypes.index('cross')
    SHOT = spadlconfig.SHOT
    SHOT_PENALTY = spadlconfig.SHOT_PENALTY
    PASS = spadlconfig.PASS
    DRIBBLE = spadlconfig.DRIBBLE
    FOOT = spadlconfig.bodyparts.index('foot')
    HEAD = spadlconfig.bodyparts.index('head')

    n_types = len(spadlconfig.actiontypes)
    # no shot-like vocabulary in the tail draw: penalties/corners are
    # explicit mechanics below, and a tail-drawn shot would resolve as a
    # move (~89% success) — unpredictable fake goals that poison both
    # label heads
    tail_types = np.array([
        t for t in range(n_types)
        if not spadlconfig.shot_like_mask[t]
        and t not in (PASS, DRIBBLE, CORNER, CROSS)
    ])

    team_id = np.empty(n, dtype=np.int64)
    player_id = np.empty(n, dtype=np.int64)
    type_id = np.empty(n, dtype=np.int64)
    result_id = np.empty(n, dtype=np.int64)
    bodypart_id = np.empty(n, dtype=np.int64)
    period_id = np.where(np.arange(n) < half, 1, 2).astype(np.int64)
    time_seconds = np.empty(n, dtype=np.float64)
    start_x = np.empty(n)
    start_y = np.empty(n)
    end_x = np.empty(n)
    end_y = np.empty(n)
    momentum_lat = np.empty(n)
    fast_lat = np.empty(n, dtype=bool)
    hot_lat = np.empty(n, dtype=bool)
    exposure_lat = np.empty(n)

    # mutable match state
    team = home_team_id if rng.integers(2) else away_team_id
    x, y = L / 2.0, W / 2.0
    t = 0.0
    momentum = 0.0
    fast_break = False
    hot = False
    exposure: Dict[int, float] = {home_team_id: 0.0, away_team_id: 0.0}
    pin_count: Dict[int, int] = {home_team_id: 0, away_team_id: 0}
    score = {home_team_id: 0, away_team_id: 0}
    pending = None  # 'penalty' | 'corner' | 'corner_shot'
    after_cross = False

    def new_possession(new_team, *, kickoff=False, p_hot=0.22):
        nonlocal team, momentum, fast_break, hot, x, y, after_cross
        team = new_team
        momentum = 0.0
        hot = bool(rng.random() < p_hot)
        fast_break = hot and bool(rng.random() < 0.5)
        after_cross = False
        if kickoff:
            x, y = L / 2.0, W / 2.0

    def turnover(loser):
        """Possession flips; breaks feed on the loser's exposure / deep loss."""
        nonlocal momentum, fast_break, hot
        e = exposure[loser]
        loser_own_goal_x = 0.0 if loser == home_team_id else L
        deep = float(np.hypot(x - loser_own_goal_x, y - W / 2.0)) < 45.0
        new_possession(other[loser])
        if deep:
            # a ball lost near one's own goal is a prime chance: the winner
            # is already in range — and how LONG the loser has been pinned
            # decides how hard the punishment hits. The pin length is the
            # k>1 concedes signal: location-only features see "deep now",
            # history sees "deep for a while and failing"
            pins = min(pin_count[loser], 6)
            momentum = 0.08 + 0.12 * pins
            hot = pins >= 2 or bool(rng.random() < 0.3)
            fast_break = fast_break or bool(rng.random() < 0.15 + 0.12 * pins)
        elif e > 0.40:
            # the loser over-committed up the pitch: the winner springs a
            # fast counter the length of the field. There is no location
            # cue here — the ball was lost in midfield or higher — so only
            # the loser's multi-action history (the long forward chain
            # that built the exposure) predicts the concede
            momentum = 0.65
            hot = True
            fast_break = True
        exposure[loser] = 0.5 * e

    def pick_player():
        attacks_right = team == home_team_id
        xa = x if attacks_right else L - x
        zone = 0 if xa < L / 3 else (1 if xa < 2 * L / 3 else 2)
        p = _ZONE_ROLE_P[zone]
        role = _ROLES[int(rng.choice(4, p=[p[r] for r in _ROLES]))]
        j = int(rng.choice([j for j in range(1, 12) if _ROLE_OF[j] == role]))
        return j, team * 1000 + j

    def resolve_shot(i, p_goal):
        nonlocal t
        goal = rng.random() < p_goal
        result_id[i] = spadlconfig.SUCCESS if goal else spadlconfig.FAIL
        if goal:
            score[team] += 1
            t += rng.uniform(30.0, 60.0)  # celebration + restart
            new_possession(other[team], kickoff=True)
        return goal

    for i in range(n):
        if i == half:  # second half: clock restarts, away kicks off
            t = 0.0
            pending = None
            exposure = {home_team_id: 0.0, away_team_id: 0.0}
            new_possession(away_team_id, kickoff=True)

        attacks_right = team == home_team_id
        goal_x = L if attacks_right else 0.0
        trailing = score[team] < score[other[team]]

        # ---- forced set-piece actions ----
        if pending == 'penalty':
            t += rng.uniform(20.0, 40.0)  # set-up time
            time_seconds[i] = t
            team_id[i] = team
            player_id[i] = team * 1000 + 11  # designated taker
            px = goal_x - 11.0 if attacks_right else goal_x + 11.0
            start_x[i], start_y[i] = px, W / 2.0
            end_x[i], end_y[i] = goal_x, W / 2.0 + rng.normal(0, 1.0)
            type_id[i] = SHOT_PENALTY
            bodypart_id[i] = FOOT
            momentum_lat[i], fast_lat[i], hot_lat[i] = momentum, False, hot
            exposure_lat[i] = exposure[team]
            goal = resolve_shot(i, PENALTY_PRIOR)
            if not goal:
                new_possession(other[team])
                x = (rng.uniform(3.0, 12.0) if team == home_team_id
                     else rng.uniform(L - 12.0, L - 3.0))
                y = rng.uniform(W * 0.3, W * 0.7)
            pending = None
            continue

        if pending == 'corner':
            t += rng.uniform(15.0, 30.0)
            time_seconds[i] = t
            team_id[i] = team
            j, pid = pick_player()
            player_id[i] = pid
            cy = 0.0 if rng.random() < 0.5 else W
            start_x[i], start_y[i] = goal_x, cy
            ex = (goal_x - rng.uniform(3.0, 10.0) if attacks_right
                  else goal_x + rng.uniform(3.0, 10.0))
            ey = float(np.clip(W / 2.0 + rng.normal(0, 6.0), 0.0, W))
            ex = float(np.clip(ex, 0.0, L))
            end_x[i], end_y[i] = ex, ey
            type_id[i] = CORNER
            bodypart_id[i] = FOOT
            momentum_lat[i], fast_lat[i], hot_lat[i] = momentum, False, hot
            exposure_lat[i] = exposure[team]
            ok = rng.random() < 0.55
            result_id[i] = spadlconfig.SUCCESS if ok else spadlconfig.FAIL
            x, y = ex, ey
            if ok:
                pending = 'corner_shot'
            else:
                pending = None
                new_possession(other[team])
            continue

        if pending == 'corner_shot':
            t += rng.uniform(1.0, 3.0)
            time_seconds[i] = t
            team_id[i] = team
            j, pid = pick_player()
            player_id[i] = pid
            start_x[i], start_y[i] = x, y
            end_x[i], end_y[i] = goal_x, W / 2.0 + rng.normal(0, 2.0)
            bp = HEAD if rng.random() < 0.75 else FOOT
            type_id[i] = SHOT
            bodypart_id[i] = bp
            momentum_lat[i], fast_lat[i], hot_lat[i] = momentum, False, hot
            exposure_lat[i] = exposure[team]
            # pinned so that P(goal | corner) = 0.55 * E[p_goal] = CORNER_PRIOR
            # (the head/foot mix cancels exactly: 0.75*0.85 + 0.25*1.45 = 1;
            # skill is excluded here, as on penalties, to keep the pin exact)
            base = CORNER_PRIOR / 0.55
            p_goal = base * (0.85 if bp == HEAD else 1.45)
            goal = resolve_shot(i, float(np.clip(p_goal, 0.01, 0.5)))
            if not goal:
                turnover(team)
                x = float(np.clip(x + rng.normal(0, 8), 0, L))
                y = float(np.clip(y + rng.normal(0, 8), 0, W))
            pending = None
            continue

        # ---- open play ----
        dist_goal = float(np.hypot(x - goal_x, y - W / 2.0))
        t += rng.uniform(1.0, 4.0) if fast_break else rng.uniform(2.0, 9.0)
        time_seconds[i] = t
        team_id[i] = team
        j, pid = pick_player()
        player_id[i] = pid
        start_x[i], start_y[i] = x, y
        momentum_lat[i], fast_lat[i], hot_lat[i] = momentum, fast_break, hot
        exposure_lat[i] = exposure[team]
        own_gx = 0.0 if attacks_right else L
        if float(np.hypot(x - own_gx, y - W / 2.0)) < 35.0:
            pin_count[team] += 1
        else:
            pin_count[team] = 0

        # shot hazard: proximity × momentum × (pressing when trailing);
        # on a fast break the shot comes EARLY, from range, because the
        # defense is unset — location-only features cannot tell these
        # high-value chances from hopeless long shots, history can
        p_shot = (
            0.12 * np.exp(-dist_goal / 11.0)
            * (1.0 + 2.5 * momentum)
            * (1.25 if trailing else 1.0)
        )
        if fast_break:
            p_shot = max(p_shot, 0.20 * np.exp(-dist_goal / 32.0))
        if after_cross and dist_goal < 18.0:
            p_shot = max(p_shot, 0.45)
        u = rng.random()
        if u < p_shot:
            a_type = SHOT
        elif u < p_shot + 0.08:
            a_type = int(rng.choice(tail_types))
        elif u < p_shot + 0.08 + (1 - p_shot - 0.08) * 0.72:
            a_type = PASS
        else:
            a_type = DRIBBLE

        wide = y < W * 0.22 or y > W * 0.78

        # movement: build-up drifts toward the attacked goal
        if a_type == SHOT:
            ex, ey = goal_x, W / 2.0 + rng.normal(0, 2.0)
            bp = HEAD if (after_cross and rng.random() < 0.6) else (
                HEAD if rng.random() < 0.04 else FOOT)
        else:
            step = (abs(rng.normal(18.0 if fast_break else 14.0, 8.0))
                    if a_type == PASS else abs(rng.normal(6.0, 3.0)))
            to_goal_x = goal_x - x
            to_goal_y = (W / 2.0 - y) * 0.4
            norm = max(float(np.hypot(to_goal_x, to_goal_y)), 1e-6)
            drift = 0.55 if not fast_break else 0.8  # breaks go forward
            ex = x + step * (drift * to_goal_x / norm + rng.normal(0, 0.6))
            ey = y + step * (drift * to_goal_y / norm + rng.normal(0, 0.6))
            bp = (HEAD if (a_type == PASS and step > 22 and rng.random() < 0.2)
                  else FOOT)
        ex = float(np.clip(ex, 0.0, L))
        ey = float(np.clip(ey, 0.0, W))
        end_dist = float(np.hypot(ex - goal_x, ey - W / 2.0))
        if a_type == PASS and wide and end_dist < 17.0 and dist_goal < 40.0:
            a_type = CROSS  # a wide delivery into the box
        end_x[i], end_y[i] = ex, ey
        type_id[i] = a_type
        bodypart_id[i] = bp

        if a_type == SHOT:
            # conversion: the *history* — not just where the shot is taken
            # from — decides whether chances convert; headers convert at
            # 0.55× and persistent skill scales everything
            skill = strength[team] * finish[team][j]
            bp_mult = 0.55 if bp == HEAD else 1.0
            if fast_break:
                p_goal = 0.16 * np.exp(-dist_goal / 28.0) * (1.0 + 2.0 * momentum)
            else:
                p_goal = 0.055 * np.exp(-dist_goal / 10.0) * (1.0 + 3.5 * momentum)
            p_goal = float(np.clip(p_goal * skill * bp_mult, 0.01, 0.55))
            goal = resolve_shot(i, p_goal)
            after_cross = False
            if not goal:
                if rng.random() < 0.2:
                    pending = 'corner'  # saved/deflected behind
                else:
                    # miss: opponent restarts deep in their own territory
                    new_possession(other[team])
                    x = (rng.uniform(L - 14.0, L - 3.0) if attacks_right
                         else rng.uniform(3.0, 14.0))
                    y = rng.uniform(W * 0.25, W * 0.75)
            continue

        # moves: success decays with attempted length, rises with momentum;
        # crosses are risky and pinned teams play under pressure
        move_len = float(np.hypot(ex - x, ey - y))
        own_goal_x = 0.0 if attacks_right else L
        pinned = float(np.hypot(x - own_goal_x, y - W / 2.0)) < 30.0
        p_success = float(np.clip(
            (0.89 - 0.011 * move_len + 0.12 * momentum) * strength[team]
            * (0.8 if a_type == CROSS else 1.0) * (0.9 if pinned else 1.0),
            0.30, 0.97,
        ))
        ok = rng.random() < p_success
        result_id[i] = spadlconfig.SUCCESS if ok else spadlconfig.FAIL
        if ok:
            forward = (ex - x) if attacks_right else (x - ex)
            # SLOW decay: the state persists across the 10-action label
            # window; hot possessions build it, cold ones plateau low
            gain = (0.10 + (0.08 if forward > 6.0 else 0.0)) if hot else 0.03
            momentum = float(np.clip(0.85 * momentum + gain, 0.0, 1.0))
            # committing players forward builds exposure over several
            # actions; it decays while the other side holds the ball
            exposure[team] = float(np.clip(
                0.93 * exposure[team] + (0.10 if forward > 6.0 else 0.01),
                0.0, 1.0))
            exposure[other[team]] = 0.95 * exposure[other[team]]
            after_cross = a_type == CROSS
            x, y = ex, ey
            if rng.random() < 0.05:  # natural possession end (ball out etc.)
                new_possession(other[team])
        else:
            after_cross = False
            x, y = ex, ey  # turnover at the failed action's end point
            in_box = (abs(ex - goal_x) < 16.5) and (abs(ey - W / 2.0) < 20.0)
            if a_type == DRIBBLE and in_box and rng.random() < 0.08:
                pending = 'penalty'  # fouled in the box; ball retained
                continue
            turnover(team)

    # clocks are strictly increasing within each period by construction
    frame = pd.DataFrame(
        {
            'game_id': np.full(n, game_id, dtype=np.int64),
            'original_event_id': [f'synth-{game_id}-{i}' for i in range(n)],
            'action_id': np.arange(n, dtype=np.int64),
            'period_id': period_id,
            'time_seconds': time_seconds,
            'team_id': team_id,
            'player_id': player_id,
            'start_x': start_x,
            'start_y': start_y,
            'end_x': end_x,
            'end_y': end_y,
            'type_id': type_id,
            'result_id': result_id,
            'bodypart_id': bodypart_id,
        }
    )
    if include_latents:
        # the generator's hidden state at each action, for diagnostics and
        # the ablation tier's oracle ceiling (NOT part of the SPADL schema;
        # drop before passing to converters/stores)
        frame['latent_momentum'] = momentum_lat
        frame['latent_fast_break'] = fast_lat
        frame['latent_hot'] = hot_lat
        frame['latent_exposure'] = exposure_lat
    return frame


def write_synthetic_season(
    path: str,
    n_games: int = 3072,
    n_actions: int = 1600,
    *,
    seed: int = 0,
) -> str:
    """Write an ``n_games`` synthetic season to a :class:`SeasonStore`.

    The throughput companion of the per-game chain generator: draws the
    whole season's SPADL columns **vectorized across games** (the same
    marginal distributions as :func:`synthetic_batch`) and writes per-game
    frames under the reference store layout (one ``actions/game_<id>`` key
    per game plus ``games``/``teams``/``players`` and the vocab tables —
    ``/root/reference``'s ``tests/datasets/download.py:63-125``). The
    per-action possession-chain simulation of
    :func:`synthetic_actions_frame` costs ~135 ms/game on one host core,
    which at cold-path benchmark scale (3k games) would be ~7 minutes of
    setup for a benchmark whose point is the *read → pack → rate* path;
    this writer costs ~2 ms/game to draw. Quality tiers keep using the
    chain generator; this one exists for IO/throughput benchmarks
    (``bench.py`` cold path).

    Games all have exactly ``n_actions`` valid actions. Returns ``path``.
    """
    import pandas as pd

    from ..pipeline.store import SeasonStore

    rng = np.random.default_rng(seed)
    G, A = n_games, n_actions
    cols = _draw_spadl_columns(rng, G, A, np.float64, np.int64)

    game_ids = 9000 + np.arange(G)
    home = 100 + 2 * (np.arange(G) % 16)
    away = home + 1
    # home/away alternate per action; player drawn from the acting team
    team_id = np.where(cols['is_home'], home[:, None], away[:, None]).astype(np.int64)
    player_id = team_id * 1000 + rng.integers(1, 12, size=(G, A))
    action_id = np.arange(A, dtype=np.int64)

    games, teams, players = [], {}, []
    with SeasonStore(path, mode='w') as store:
        store.put('actiontypes', spadlconfig.actiontypes_df())
        store.put('results', spadlconfig.results_df())
        store.put('bodyparts', spadlconfig.bodyparts_df())
        for i in range(G):
            gid = int(game_ids[i])
            frame = pd.DataFrame(
                {
                    'game_id': np.full(A, gid, dtype=np.int64),
                    'action_id': action_id,
                    'period_id': cols['period_id'][i],
                    'time_seconds': cols['time_seconds'][i],
                    'team_id': team_id[i],
                    'player_id': player_id[i],
                    'start_x': cols['start_x'][i],
                    'start_y': cols['start_y'][i],
                    'end_x': cols['end_x'][i],
                    'end_y': cols['end_y'][i],
                    'type_id': cols['type_id'][i],
                    'result_id': cols['result_id'][i],
                    'bodypart_id': cols['bodypart_id'][i],
                }
            )
            store.put_actions(gid, frame)
            games.append(
                {
                    'game_id': gid,
                    'home_team_id': int(home[i]),
                    'away_team_id': int(away[i]),
                }
            )
            for t in (int(home[i]), int(away[i])):
                teams[t] = {'team_id': t, 'team_name': f'Team {t}'}
        for t in teams:
            players.extend(
                {
                    'team_id': t,
                    'player_id': t * 1000 + j,
                    'player_name': f'Player {t}-{j}',
                    'minutes_played': 90,
                }
                for j in range(1, 12)
            )
        store.put('games', pd.DataFrame(games))
        store.put('teams', pd.DataFrame(list(teams.values())))
        store.put('players', pd.DataFrame(players))
        store.put('meta', pd.DataFrame({'synthetic': [True]}))
    return path


def append_synthetic_games(
    path: str,
    n_games: int = 4,
    *,
    n_actions: int = 300,
    seed: int = 0,
    start_id: Optional[int] = None,
) -> List[int]:
    """Land ``n_games`` new synthetic matches in an *existing* store.

    The test/bench stand-in for a live data pipeline delivering played
    matches: per-game frames come from the learnable chain generator
    (:func:`synthetic_actions_frame`) and the ``games`` table is extended
    in place — exactly the append-only mutation the continuous-learning
    loop (:mod:`socceraction_tpu.learn`) watches for. Returns the new
    game ids (``start_id`` defaults past the largest stored id).
    """
    import pandas as pd

    from ..pipeline.store import SeasonStore

    with SeasonStore(path, mode='a') as store:
        games = store.games()
        existing = set(store.game_ids())
        if start_id is None:
            numeric = [int(g) for g in existing if str(g).lstrip('-').isdigit()]
            start_id = max(numeric) + 1 if numeric else 1
        new_rows = []
        gid = int(start_id)
        for j in range(int(n_games)):
            while gid in existing:
                gid += 1
            home = 100 + 2 * (j % 16)
            away = home + 1
            frame = synthetic_actions_frame(
                gid, home_team_id=home, away_team_id=away,
                n_actions=n_actions, seed=seed + j,
            )
            store.put_actions(gid, frame)
            new_rows.append(
                {'game_id': gid, 'home_team_id': home, 'away_team_id': away}
            )
            existing.add(gid)
            gid += 1
        store.put(
            'games',
            pd.concat([games, pd.DataFrame(new_rows)], ignore_index=True),
        )
    return [r['game_id'] for r in new_rows]
