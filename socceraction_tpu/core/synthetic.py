"""Synthetic SPADL action streams for benchmarks and compile checks.

Generates statistically plausible (not physically consistent) action
tensors directly as an :class:`ActionBatch` — no pandas round-trip — so
benchmarks measure kernel throughput, not host packing.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:  # pandas is imported lazily inside the frame generator
    import pandas as pd

from ..spadl import config as spadlconfig
from .batch import ActionBatch

__all__ = ['synthetic_batch', 'write_synthetic_season']


def _draw_spadl_columns(
    rng: 'np.random.Generator', G: int, A: int, float_dtype: type, int_dtype: type
) -> dict:
    """Draw the marginal SPADL column distributions for a ``(G, A)`` grid.

    Single source of the distributions shared by :func:`synthetic_batch`
    (float32/int32 device tensors) and :func:`write_synthetic_season`
    (float64/int64 store frames): action types loosely matching real SPADL
    streams (passes dominate, then dribbles, a tail over the rest),
    monotone period/clock, and end points as noisy displacements of start
    points. Cast points sit exactly where :func:`synthetic_batch` always
    had them so its draws stay bit-identical for a given seed.
    """
    n_types = len(spadlconfig.actiontypes)
    probs = np.full(n_types, 0.02)
    probs[spadlconfig.PASS] = 0.45
    probs[spadlconfig.DRIBBLE] = 0.25
    probs[spadlconfig.SHOT] = 0.03
    probs /= probs.sum()

    L, W = spadlconfig.field_length, spadlconfig.field_width
    type_id = rng.choice(n_types, size=(G, A), p=probs).astype(int_dtype)
    result_id = rng.choice(
        len(spadlconfig.results), size=(G, A), p=[0.25, 0.68, 0.02, 0.02, 0.02, 0.01]
    ).astype(int_dtype)
    bodypart_id = rng.choice(
        len(spadlconfig.bodyparts), size=(G, A), p=[0.85, 0.08, 0.05, 0.02]
    ).astype(int_dtype)
    period_id = np.sort(rng.integers(1, 5, size=(G, A)), axis=1).astype(int_dtype)
    time_seconds = np.sort(
        rng.uniform(0, 3000, size=(G, A)).astype(float_dtype), axis=1
    )
    start_x = rng.uniform(0, L, size=(G, A)).astype(float_dtype)
    start_y = rng.uniform(0, W, size=(G, A)).astype(float_dtype)
    end_x = np.clip(start_x + rng.normal(0, 12, size=(G, A)), 0, L).astype(float_dtype)
    end_y = np.clip(start_y + rng.normal(0, 8, size=(G, A)), 0, W).astype(float_dtype)
    is_home = rng.integers(0, 2, size=(G, A)).astype(bool)
    return {
        'type_id': type_id,
        'result_id': result_id,
        'bodypart_id': bodypart_id,
        'period_id': period_id,
        'time_seconds': time_seconds,
        'start_x': start_x,
        'start_y': start_y,
        'end_x': end_x,
        'end_y': end_y,
        'is_home': is_home,
    }


def synthetic_batch(
    n_games: int = 64,
    n_actions: int = 1664,
    *,
    fill: float = 1.0,
    seed: int = 0,
) -> ActionBatch:
    """Build a random but schema-valid ``(G, A)`` batch.

    Parameters
    ----------
    n_games, n_actions
        Batch shape. The default action count (1664 = 13×128) is the
        typical SPADL game length (~1.5-2.5k actions per game, SURVEY §5)
        rounded to a lane multiple.
    fill : float
        Fraction of each game's action axis that is valid (rest padding).
    seed : int
        numpy seed for reproducibility.
    """
    rng = np.random.default_rng(seed)
    G, A = n_games, n_actions
    n_valid = max(2, int(A * fill))

    cols = _draw_spadl_columns(rng, G, A, np.float32, np.int32)
    type_id, result_id, bodypart_id, period_id = (
        cols['type_id'], cols['result_id'], cols['bodypart_id'], cols['period_id']
    )
    time_seconds = cols['time_seconds']
    start_x, start_y = cols['start_x'], cols['start_y']
    end_x, end_y = cols['end_x'], cols['end_y']
    is_home = cols['is_home']

    mask = np.zeros((G, A), dtype=bool)
    mask[:, :n_valid] = True
    row_index = np.where(
        mask, np.arange(G * A).reshape(G, A) % (G * n_valid), -1
    ).astype(np.int32)
    # row_index must be a permutation of [0, total) over valid rows
    row_index[mask] = np.arange(G * n_valid, dtype=np.int32)

    return ActionBatch(
        type_id=jnp.asarray(type_id),
        result_id=jnp.asarray(result_id),
        bodypart_id=jnp.asarray(bodypart_id),
        period_id=jnp.asarray(period_id),
        is_home=jnp.asarray(is_home),
        time_seconds=jnp.asarray(time_seconds),
        start_x=jnp.asarray(start_x),
        start_y=jnp.asarray(start_y),
        end_x=jnp.asarray(end_x),
        end_y=jnp.asarray(end_y),
        mask=jnp.asarray(mask),
        n_actions=jnp.full(G, n_valid, dtype=jnp.int32),
        game_id=jnp.arange(G, dtype=jnp.int32),
        row_index=jnp.asarray(row_index),
    )


def synthetic_actions_frame(
    game_id: int = 1,
    *,
    home_team_id: int = 100,
    away_team_id: int = 200,
    n_actions: int = 1600,
    seed: int = 0,
    include_latents: bool = False,
) -> 'pd.DataFrame':
    """A schema-valid synthetic SPADL DataFrame for one game.

    Statistically plausible AND **learnable**: the generator simulates
    possession chains with the same *sequential* feature→label structure
    real soccer has, so models trained on these games must beat chance on
    held-out games (the air-gapped stand-in for the reference's real-data
    quality tier — see QUALITY.md), and history-aware features must beat
    location-only features (the ablation tier):

    - **ball continuity**: each action starts where the previous one
      ended; a turnover hands the ball to the other team *at that spot*,
      so ``space_delta``/``startlocation`` chains carry real state;
    - **momentum**: a latent state that rises with consecutive successful
      actions and forward progress and resets on turnover. It multiplies
      move success, shot hazard AND shot conversion, so the *recent
      history* (previous results, forward progress, tempo — exactly what
      the ``team``/``time_delta``/``space_delta`` context transformers
      and the k>1 state copies expose) genuinely predicts P(goal in the
      next 10 actions) beyond what the current location says;
    - **build-up toward goal**: within a possession, moves drift toward
      the attacked goal, so chains progress like real build-up play;
    - **tempo**: possessions are fast breaks (short ``time_delta``,
      higher conversion) or slow build-up, making inter-action time
      predictive;
    - **score effects**: a trailing team presses (higher shot hazard),
      giving the ``goalscore`` feature forward-looking signal;
    - shot hazard still decays with distance to the attacked goal and
      conversion with shot distance, so location features keep their
      baseline signal (and the xG tier its distance structure).

    Used by the synthetic stand-in store
    (``tests/datasets/make_synthetic_store.py``) that lets the @e2e tier
    execute without network egress, and by
    ``tests/test_quality_synthetic.py`` (held-out AUC floor + history
    ablation).
    """
    import pandas as pd

    rng = np.random.default_rng(seed)
    n = int(n_actions)
    L, W = spadlconfig.field_length, spadlconfig.field_width
    half = n // 2

    other = {home_team_id: away_team_id, away_team_id: home_team_id}
    n_types = len(spadlconfig.actiontypes)
    # occasional non-move vocabulary tail (throw-ins, fouls, clearances...)
    tail_types = np.array(
        [
            t for t in range(n_types)
            if t not in (spadlconfig.PASS, spadlconfig.DRIBBLE, spadlconfig.SHOT)
        ]
    )

    team_id = np.empty(n, dtype=np.int64)
    type_id = np.empty(n, dtype=np.int64)
    result_id = np.empty(n, dtype=np.int64)
    period_id = np.where(np.arange(n) < half, 1, 2).astype(np.int64)
    time_seconds = np.empty(n, dtype=np.float64)
    start_x = np.empty(n)
    start_y = np.empty(n)
    end_x = np.empty(n)
    end_y = np.empty(n)
    momentum_lat = np.empty(n)  # latent record (include_latents=True)
    fast_lat = np.empty(n, dtype=bool)

    # mutable match state
    team = home_team_id if rng.integers(2) else away_team_id
    x, y = L / 2.0, W / 2.0
    t = 0.0
    momentum = 0.0  # latent, in [0, 1]
    fast_break = False
    score = {home_team_id: 0, away_team_id: 0}

    def new_possession(new_team, *, kickoff=False):
        nonlocal team, momentum, fast_break, x, y
        team = new_team
        momentum = 0.0
        fast_break = bool(rng.random() < 0.3)
        if kickoff:
            x, y = L / 2.0, W / 2.0

    for i in range(n):
        if i == half:  # second half: clock restarts, away kicks off
            t = 0.0
            new_possession(away_team_id, kickoff=True)

        attacks_right = team == home_team_id
        goal_x = L if attacks_right else 0.0
        dist_goal = float(np.hypot(x - goal_x, y - W / 2.0))
        trailing = score[team] < score[other[team]]

        t += rng.uniform(1.0, 4.0) if fast_break else rng.uniform(2.0, 9.0)
        time_seconds[i] = t
        team_id[i] = team
        start_x[i], start_y[i] = x, y
        momentum_lat[i], fast_lat[i] = momentum, fast_break

        # shot hazard: proximity x momentum x (pressing when trailing);
        # on a fast break the shot comes EARLY, from range, because the
        # defense is unset — location-only features cannot tell these
        # high-value chances from hopeless long shots, history can
        p_shot = (
            0.10
            * np.exp(-dist_goal / 11.0)
            * (1.0 + 2.5 * momentum)
            * (1.25 if trailing else 1.0)
        )
        if fast_break:
            p_shot = max(p_shot, 0.18 * np.exp(-dist_goal / 30.0))
        u = rng.random()
        if u < p_shot:
            a_type = spadlconfig.SHOT
        elif u < p_shot + 0.08:
            a_type = int(rng.choice(tail_types))
        elif u < p_shot + 0.08 + (1 - p_shot - 0.08) * 0.72:
            a_type = spadlconfig.PASS
        else:
            a_type = spadlconfig.DRIBBLE

        # movement: build-up drifts toward the attacked goal
        if a_type == spadlconfig.SHOT:
            ex, ey = goal_x, W / 2.0 + rng.normal(0, 2.0)
        else:
            step = (
                abs(rng.normal(14.0, 8.0))
                if a_type == spadlconfig.PASS
                else abs(rng.normal(6.0, 3.0))
            )
            to_goal_x = goal_x - x
            to_goal_y = (W / 2.0 - y) * 0.4
            norm = max(float(np.hypot(to_goal_x, to_goal_y)), 1e-6)
            drift = 0.55 if not fast_break else 0.8  # breaks go forward
            ex = x + step * (drift * to_goal_x / norm + rng.normal(0, 0.6))
            ey = y + step * (drift * to_goal_y / norm + rng.normal(0, 0.6))
        ex = float(np.clip(ex, 0.0, L))
        ey = float(np.clip(ey, 0.0, W))
        end_x[i], end_y[i] = ex, ey
        type_id[i] = a_type

        shot_like = bool(spadlconfig.shot_like_mask[a_type])
        if shot_like:
            # conversion: the *history* — not just where the shot is taken
            # from — decides whether chances convert. Set-play shots decay
            # steeply with distance but multiply with momentum (~4.5x);
            # counterattack finishes face an unset defense, so distance
            # hardly protects and the break itself sets the value. Both
            # factors are invisible to location-only features — this is
            # what the ablation tier asserts.
            if fast_break:
                p_goal = float(
                    np.clip(
                        0.16
                        * np.exp(-dist_goal / 28.0)
                        * (1.0 + 2.0 * momentum),
                        0.01,
                        0.55,
                    )
                )
            else:
                p_goal = float(
                    np.clip(
                        0.055
                        * np.exp(-dist_goal / 10.0)
                        * (1.0 + 3.5 * momentum),
                        0.01,
                        0.55,
                    )
                )
            goal = rng.random() < p_goal
            result_id[i] = spadlconfig.SUCCESS if goal else spadlconfig.FAIL
            if goal:
                score[team] += 1
                t += rng.uniform(30.0, 60.0)  # celebration + restart
                new_possession(other[team], kickoff=True)
            else:
                # miss: opponent restarts deep in their own territory
                new_possession(other[team])
                opp_right = team == home_team_id
                x = rng.uniform(3.0, 14.0) if opp_right else rng.uniform(L - 14.0, L - 3.0)
                y = rng.uniform(W * 0.25, W * 0.75)
            continue

        # moves: success decays with attempted length, rises with momentum
        move_len = float(np.hypot(ex - x, ey - y))
        p_success = float(
            np.clip(0.89 - 0.011 * move_len + 0.12 * momentum, 0.35, 0.97)
        )
        ok = rng.random() < p_success
        result_id[i] = spadlconfig.SUCCESS if ok else spadlconfig.FAIL
        if ok:
            forward = (ex - x) if attacks_right else (x - ex)
            # SLOW decay: the state persists across the 10-action label
            # window, so the noisy 3-action measurement the features give
            # (recent results, forward progress, tempo) still predicts
            # goals several actions ahead — short memory here would make
            # momentum unpredictive at the label horizon
            momentum = float(
                np.clip(
                    0.85 * momentum + 0.10 + (0.08 if forward > 6.0 else 0.0),
                    0.0,
                    1.0,
                )
            )
            x, y = ex, ey
            if rng.random() < 0.05:  # natural possession end (ball out etc.)
                new_possession(other[team])
        else:
            x, y = ex, ey  # turnover at the failed action's end point
            new_possession(other[team])
            # a ball lost near one's own goal is a counterattack chance:
            # the winning team starts with momentum and often breaks fast,
            # so a deep failed action predicts conceding soon — the
            # concedes head's planted sequential signal
            won_goal_x = L if team == home_team_id else 0.0
            if np.hypot(x - won_goal_x, y - W / 2.0) < 45.0:
                momentum = 0.4
                fast_break = bool(rng.random() < 0.6)

    # clocks are strictly increasing within each period by construction
    players = {
        home_team_id: np.arange(1, 12) + home_team_id * 1000,
        away_team_id: np.arange(1, 12) + away_team_id * 1000,
    }
    player_id = np.array([rng.choice(players[tm]) for tm in team_id])

    frame = pd.DataFrame(
        {
            'game_id': np.full(n, game_id, dtype=np.int64),
            'original_event_id': [f'synth-{game_id}-{i}' for i in range(n)],
            'action_id': np.arange(n, dtype=np.int64),
            'period_id': period_id.astype(np.int64),
            'time_seconds': time_seconds,
            'team_id': team_id,
            'player_id': player_id.astype(np.int64),
            'start_x': start_x.astype(np.float64),
            'start_y': start_y.astype(np.float64),
            'end_x': end_x.astype(np.float64),
            'end_y': end_y.astype(np.float64),
            'type_id': type_id.astype(np.int64),
            'result_id': result_id.astype(np.int64),
            'bodypart_id': rng.choice(
                len(spadlconfig.bodyparts), size=n, p=[0.85, 0.08, 0.05, 0.02]
            ).astype(np.int64),
        }
    )
    if include_latents:
        # the generator's hidden state at each action, for diagnostics and
        # the ablation tier's oracle ceiling (NOT part of the SPADL schema;
        # drop before passing to converters/stores)
        frame['latent_momentum'] = momentum_lat
        frame['latent_fast_break'] = fast_lat
    return frame


def write_synthetic_season(
    path: str,
    n_games: int = 3072,
    n_actions: int = 1600,
    *,
    seed: int = 0,
) -> str:
    """Write an ``n_games`` synthetic season to a :class:`SeasonStore`.

    The throughput companion of the per-game chain generator: draws the
    whole season's SPADL columns **vectorized across games** (the same
    marginal distributions as :func:`synthetic_batch`) and writes per-game
    frames under the reference store layout (one ``actions/game_<id>`` key
    per game plus ``games``/``teams``/``players`` and the vocab tables —
    ``/root/reference``'s ``tests/datasets/download.py:63-125``). The
    per-action possession-chain simulation of
    :func:`synthetic_actions_frame` costs ~135 ms/game on one host core,
    which at cold-path benchmark scale (3k games) would be ~7 minutes of
    setup for a benchmark whose point is the *read → pack → rate* path;
    this writer costs ~2 ms/game to draw. Quality tiers keep using the
    chain generator; this one exists for IO/throughput benchmarks
    (``bench.py`` cold path).

    Games all have exactly ``n_actions`` valid actions. Returns ``path``.
    """
    import pandas as pd

    from ..pipeline.store import SeasonStore

    rng = np.random.default_rng(seed)
    G, A = n_games, n_actions
    cols = _draw_spadl_columns(rng, G, A, np.float64, np.int64)

    game_ids = 9000 + np.arange(G)
    home = 100 + 2 * (np.arange(G) % 16)
    away = home + 1
    # home/away alternate per action; player drawn from the acting team
    team_id = np.where(cols['is_home'], home[:, None], away[:, None]).astype(np.int64)
    player_id = team_id * 1000 + rng.integers(1, 12, size=(G, A))
    action_id = np.arange(A, dtype=np.int64)

    games, teams, players = [], {}, []
    with SeasonStore(path, mode='w') as store:
        store.put('actiontypes', spadlconfig.actiontypes_df())
        store.put('results', spadlconfig.results_df())
        store.put('bodyparts', spadlconfig.bodyparts_df())
        for i in range(G):
            gid = int(game_ids[i])
            frame = pd.DataFrame(
                {
                    'game_id': np.full(A, gid, dtype=np.int64),
                    'action_id': action_id,
                    'period_id': cols['period_id'][i],
                    'time_seconds': cols['time_seconds'][i],
                    'team_id': team_id[i],
                    'player_id': player_id[i],
                    'start_x': cols['start_x'][i],
                    'start_y': cols['start_y'][i],
                    'end_x': cols['end_x'][i],
                    'end_y': cols['end_y'][i],
                    'type_id': cols['type_id'][i],
                    'result_id': cols['result_id'][i],
                    'bodypart_id': cols['bodypart_id'][i],
                }
            )
            store.put_actions(gid, frame)
            games.append(
                {
                    'game_id': gid,
                    'home_team_id': int(home[i]),
                    'away_team_id': int(away[i]),
                }
            )
            for t in (int(home[i]), int(away[i])):
                teams[t] = {'team_id': t, 'team_name': f'Team {t}'}
        for t in teams:
            players.extend(
                {
                    'team_id': t,
                    'player_id': t * 1000 + j,
                    'player_name': f'Player {t}-{j}',
                    'minutes_played': 90,
                }
                for j in range(1, 12)
            )
        store.put('games', pd.DataFrame(games))
        store.put('teams', pd.DataFrame(list(teams.values())))
        store.put('players', pd.DataFrame(players))
        store.put('meta', pd.DataFrame({'synthetic': [True]}))
    return path
