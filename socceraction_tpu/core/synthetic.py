"""Synthetic SPADL action streams for benchmarks and compile checks.

Generates statistically plausible (not physically consistent) action
tensors directly as an :class:`ActionBatch` — no pandas round-trip — so
benchmarks measure kernel throughput, not host packing.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..spadl import config as spadlconfig
from .batch import ActionBatch

__all__ = ['synthetic_batch']


def synthetic_batch(
    n_games: int = 64,
    n_actions: int = 1664,
    *,
    fill: float = 1.0,
    seed: int = 0,
) -> ActionBatch:
    """Build a random but schema-valid ``(G, A)`` batch.

    Parameters
    ----------
    n_games, n_actions
        Batch shape. The default action count (1664 = 13×128) is the
        typical SPADL game length (~1.5-2.5k actions per game, SURVEY §5)
        rounded to a lane multiple.
    fill : float
        Fraction of each game's action axis that is valid (rest padding).
    seed : int
        numpy seed for reproducibility.
    """
    rng = np.random.default_rng(seed)
    G, A = n_games, n_actions
    n_valid = max(2, int(A * fill))

    # Action-type distribution loosely matching real SPADL streams:
    # passes dominate, then dribbles, with a tail over the remaining vocab.
    n_types = len(spadlconfig.actiontypes)
    probs = np.full(n_types, 0.02)
    probs[spadlconfig.PASS] = 0.45
    probs[spadlconfig.DRIBBLE] = 0.25
    probs[spadlconfig.SHOT] = 0.03
    probs /= probs.sum()

    type_id = rng.choice(n_types, size=(G, A), p=probs).astype(np.int32)
    result_id = rng.choice(
        len(spadlconfig.results), size=(G, A), p=[0.25, 0.68, 0.02, 0.02, 0.02, 0.01]
    ).astype(np.int32)
    bodypart_id = rng.choice(
        len(spadlconfig.bodyparts), size=(G, A), p=[0.85, 0.08, 0.05, 0.02]
    ).astype(np.int32)
    period_id = np.sort(rng.integers(1, 5, size=(G, A)), axis=1).astype(np.int32)
    time_seconds = np.sort(
        rng.uniform(0, 3000, size=(G, A)).astype(np.float32), axis=1
    )
    L, W = spadlconfig.field_length, spadlconfig.field_width
    start_x = rng.uniform(0, L, size=(G, A)).astype(np.float32)
    start_y = rng.uniform(0, W, size=(G, A)).astype(np.float32)
    end_x = np.clip(start_x + rng.normal(0, 12, size=(G, A)), 0, L).astype(np.float32)
    end_y = np.clip(start_y + rng.normal(0, 8, size=(G, A)), 0, W).astype(np.float32)
    is_home = rng.integers(0, 2, size=(G, A)).astype(bool)

    mask = np.zeros((G, A), dtype=bool)
    mask[:, :n_valid] = True
    row_index = np.where(
        mask, np.arange(G * A).reshape(G, A) % (G * n_valid), -1
    ).astype(np.int32)
    # row_index must be a permutation of [0, total) over valid rows
    row_index[mask] = np.arange(G * n_valid, dtype=np.int32)

    return ActionBatch(
        type_id=jnp.asarray(type_id),
        result_id=jnp.asarray(result_id),
        bodypart_id=jnp.asarray(bodypart_id),
        period_id=jnp.asarray(period_id),
        is_home=jnp.asarray(is_home),
        time_seconds=jnp.asarray(time_seconds),
        start_x=jnp.asarray(start_x),
        start_y=jnp.asarray(start_y),
        end_x=jnp.asarray(end_x),
        end_y=jnp.asarray(end_y),
        mask=jnp.asarray(mask),
        n_actions=jnp.full(G, n_valid, dtype=jnp.int32),
        game_id=jnp.arange(G, dtype=jnp.int32),
        row_index=jnp.asarray(row_index),
    )


def synthetic_actions_frame(
    game_id: int = 1,
    *,
    home_team_id: int = 100,
    away_team_id: int = 200,
    n_actions: int = 1600,
    seed: int = 0,
):
    """A schema-valid synthetic SPADL DataFrame for one game.

    Statistically plausible AND **learnable**: the generator plants the
    same feature→label structure real soccer has, so models trained on
    these games must beat chance on held-out games (the air-gapped stand-in
    for the reference's real-data quality tier — see QUALITY.md):

    - possession alternates in runs; the home team attacks left→right,
      the away team right→left;
    - **shot hazard rises with proximity to the attacking goal**
      (``p_shot ∝ exp(-dist/11 m)``), so shots cluster in the box;
    - **shot conversion falls with distance** (``P(goal|shot) ∝
      exp(-dist/9 m)``), so P(score in next 10 actions) is genuinely
      predictable from location/type features;
    - pass/dribble success falls with attempted distance, giving the
      result features real signal too.

    Used by the synthetic stand-in store
    (``tests/datasets/make_synthetic_store.py``) that lets the @e2e tier
    execute without network egress, and by
    ``tests/test_quality_synthetic.py`` (held-out AUC floor).
    """
    import pandas as pd

    rng = np.random.default_rng(seed)
    n = int(n_actions)

    # possession runs: geometric lengths, alternating teams
    team_id = np.empty(n, dtype=np.int64)
    pos = 0
    team = home_team_id if rng.integers(2) else away_team_id
    while pos < n:
        run = 1 + rng.geometric(0.22)
        team_id[pos : pos + run] = team
        team = away_team_id if team == home_team_id else home_team_id
        pos += run

    half = n // 2
    period_id = np.where(np.arange(n) < half, 1, 2)
    time_seconds = np.concatenate(
        [
            np.sort(rng.uniform(0, 45 * 60, size=half)),
            np.sort(rng.uniform(0, 45 * 60, size=n - half)),
        ]
    )

    L, W = spadlconfig.field_length, spadlconfig.field_width
    # positions drift like a bounded random walk so dribbles/passes move
    start_x = np.clip(np.cumsum(rng.normal(0, 9, size=n)) % (2 * L), 0, None)
    start_x = np.where(start_x > L, 2 * L - start_x, start_x)
    start_y = np.clip(np.cumsum(rng.normal(0, 6, size=n)) % (2 * W), 0, None)
    start_y = np.where(start_y > W, 2 * W - start_y, start_y)
    end_x = np.clip(start_x + rng.normal(4, 10, size=n), 0, L)
    end_y = np.clip(start_y + rng.normal(0, 7, size=n), 0, W)

    # distance from the action's start to the goal its team attacks
    attacks_right = team_id == home_team_id
    goal_x = np.where(attacks_right, L, 0.0)
    dist_goal = np.hypot(start_x - goal_x, start_y - W / 2)

    # action types: shot hazard decays with distance to the attacked goal
    # (~20-30 shots/game, overwhelmingly inside ~25 m); the rest of the
    # vocabulary keeps the pass/dribble-dominated mix
    n_types = len(spadlconfig.actiontypes)
    probs = np.full(n_types, 0.012)
    probs[spadlconfig.PASS] = 0.50
    probs[spadlconfig.DRIBBLE] = 0.22
    probs[spadlconfig.SHOT] = 0.0
    probs /= probs.sum()
    type_id = rng.choice(n_types, size=n, p=probs)
    p_shot = 0.32 * np.exp(-dist_goal / 11.0)
    type_id = np.where(rng.random(n) < p_shot, spadlconfig.SHOT, type_id)

    # results: shots convert by proximity; moves succeed by attempted
    # length (long balls fail more often). ALL shot-like types (open play,
    # penalty, freekick) get the distance rule — a "successful"
    # shot_penalty IS a goal to the label kernels, so giving set-piece
    # shots the generic ~90% move-success rate would scatter dozens of
    # position-independent goals per game and bury the planted signal.
    move_len = np.hypot(end_x - start_x, end_y - start_y)
    p_success = np.clip(0.92 - 0.012 * move_len, 0.3, 0.95)
    result_id = np.where(
        rng.random(n) < p_success, spadlconfig.SUCCESS, spadlconfig.FAIL
    )
    shot_like = spadlconfig.shot_like_mask[type_id]
    p_goal = np.clip(0.45 * np.exp(-dist_goal[shot_like] / 9.0), 0.02, 0.6)
    result_id[shot_like] = np.where(
        rng.random(shot_like.sum()) < p_goal, spadlconfig.SUCCESS, spadlconfig.FAIL
    )

    players = {
        home_team_id: np.arange(1, 12) + home_team_id * 1000,
        away_team_id: np.arange(1, 12) + away_team_id * 1000,
    }
    player_id = np.array([rng.choice(players[t]) for t in team_id])

    return pd.DataFrame(
        {
            'game_id': np.full(n, game_id, dtype=np.int64),
            'original_event_id': [f'synth-{game_id}-{i}' for i in range(n)],
            'action_id': np.arange(n, dtype=np.int64),
            'period_id': period_id.astype(np.int64),
            'time_seconds': time_seconds,
            'team_id': team_id,
            'player_id': player_id.astype(np.int64),
            'start_x': start_x.astype(np.float64),
            'start_y': start_y.astype(np.float64),
            'end_x': end_x.astype(np.float64),
            'end_y': end_y.astype(np.float64),
            'type_id': type_id.astype(np.int64),
            'result_id': result_id.astype(np.int64),
            'bodypart_id': rng.choice(
                len(spadlconfig.bodyparts), size=n, p=[0.85, 0.08, 0.05, 0.02]
            ).astype(np.int64),
        }
    )
