"""Synthetic SPADL action streams for benchmarks and compile checks.

Generates statistically plausible (not physically consistent) action
tensors directly as an :class:`ActionBatch` — no pandas round-trip — so
benchmarks measure kernel throughput, not host packing.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..spadl import config as spadlconfig
from .batch import ActionBatch

__all__ = ['synthetic_batch']


def synthetic_batch(
    n_games: int = 64,
    n_actions: int = 1664,
    *,
    fill: float = 1.0,
    seed: int = 0,
) -> ActionBatch:
    """Build a random but schema-valid ``(G, A)`` batch.

    Parameters
    ----------
    n_games, n_actions
        Batch shape. The default action count (1664 = 13×128) is the
        typical SPADL game length (~1.5-2.5k actions per game, SURVEY §5)
        rounded to a lane multiple.
    fill : float
        Fraction of each game's action axis that is valid (rest padding).
    seed : int
        numpy seed for reproducibility.
    """
    rng = np.random.default_rng(seed)
    G, A = n_games, n_actions
    n_valid = max(2, int(A * fill))

    # Action-type distribution loosely matching real SPADL streams:
    # passes dominate, then dribbles, with a tail over the remaining vocab.
    n_types = len(spadlconfig.actiontypes)
    probs = np.full(n_types, 0.02)
    probs[spadlconfig.PASS] = 0.45
    probs[spadlconfig.DRIBBLE] = 0.25
    probs[spadlconfig.SHOT] = 0.03
    probs /= probs.sum()

    type_id = rng.choice(n_types, size=(G, A), p=probs).astype(np.int32)
    result_id = rng.choice(
        len(spadlconfig.results), size=(G, A), p=[0.25, 0.68, 0.02, 0.02, 0.02, 0.01]
    ).astype(np.int32)
    bodypart_id = rng.choice(
        len(spadlconfig.bodyparts), size=(G, A), p=[0.85, 0.08, 0.05, 0.02]
    ).astype(np.int32)
    period_id = np.sort(rng.integers(1, 5, size=(G, A)), axis=1).astype(np.int32)
    time_seconds = np.sort(
        rng.uniform(0, 3000, size=(G, A)).astype(np.float32), axis=1
    )
    L, W = spadlconfig.field_length, spadlconfig.field_width
    start_x = rng.uniform(0, L, size=(G, A)).astype(np.float32)
    start_y = rng.uniform(0, W, size=(G, A)).astype(np.float32)
    end_x = np.clip(start_x + rng.normal(0, 12, size=(G, A)), 0, L).astype(np.float32)
    end_y = np.clip(start_y + rng.normal(0, 8, size=(G, A)), 0, W).astype(np.float32)
    is_home = rng.integers(0, 2, size=(G, A)).astype(bool)

    mask = np.zeros((G, A), dtype=bool)
    mask[:, :n_valid] = True
    row_index = np.where(
        mask, np.arange(G * A).reshape(G, A) % (G * n_valid), -1
    ).astype(np.int32)
    # row_index must be a permutation of [0, total) over valid rows
    row_index[mask] = np.arange(G * n_valid, dtype=np.int32)

    return ActionBatch(
        type_id=jnp.asarray(type_id),
        result_id=jnp.asarray(result_id),
        bodypart_id=jnp.asarray(bodypart_id),
        period_id=jnp.asarray(period_id),
        is_home=jnp.asarray(is_home),
        time_seconds=jnp.asarray(time_seconds),
        start_x=jnp.asarray(start_x),
        start_y=jnp.asarray(start_y),
        end_x=jnp.asarray(end_x),
        end_y=jnp.asarray(end_y),
        mask=jnp.asarray(mask),
        n_actions=jnp.full(G, n_valid, dtype=jnp.int32),
        game_id=jnp.arange(G, dtype=jnp.int32),
        row_index=jnp.asarray(row_index),
    )
