"""Base class and utilities shared by all event-stream data loaders.

Parity: reference ``socceraction/data/base.py`` — the 5-method
``EventDataLoader`` ABC (``:82-168``), the JSON getters (``:24-55``), the
injury-time ``_expand_minute`` helper (``:57-79``) and the exception types
(``:16-21``).
"""

from __future__ import annotations

import json
import re
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Union
from urllib.request import urlopen

import pandas as pd

JSONType = Union[str, int, float, bool, None, Dict[str, Any], List[Any]]

__all__ = [
    'EventDataLoader',
    'ParseError',
    'MissingDataError',
    'JSONType',
]


class ParseError(Exception):
    """Raised when a data file is not correctly formatted."""


class MissingDataError(Exception):
    """Raised when a field is missing in the input data."""


def _snake(name: str) -> str:
    """camelCase / PascalCase -> snake_case (shared by the feed parsers)."""
    step = re.sub('(.)([A-Z][a-z]+)', r'\1_\2', name)
    return re.sub('([a-z0-9])([A-Z])', r'\1_\2', step).lower()


def _remoteloadjson(path: str) -> JSONType:
    """Load JSON data from a URL."""
    return json.loads(urlopen(path).read())


def _localloadjson(path: str) -> JSONType:
    """Load JSON data from a local file path."""
    with open(path, encoding='utf-8') as fh:
        return json.load(fh)


def _expand_minute(minute: int, periods_duration: List[int]) -> int:
    """Expand a game-clock minute with the injury time of earlier periods.

    Parameters
    ----------
    minute : int
        Timestamp in regular-clock minutes.
    periods_duration : list of int
        Actual duration of each period in minutes (including injury time).
    """
    expanded_minute = minute
    periods_regular = [45, 45, 15, 15, 0]
    for period in range(len(periods_duration) - 1):
        if minute > sum(periods_regular[: period + 1]):
            expanded_minute += periods_duration[period] - periods_regular[period]
        else:
            break
    return expanded_minute


class EventDataLoader(ABC):
    """Load event data from a remote location or a local folder.

    Every provider implements five methods, each returning a
    schema-validated DataFrame (see :mod:`socceraction_tpu.data.schema`).
    """

    @abstractmethod
    def competitions(self) -> pd.DataFrame:
        """Return all available competitions and seasons."""

    @abstractmethod
    def games(self, competition_id: int, season_id: int) -> pd.DataFrame:
        """Return all available games in a season."""

    @abstractmethod
    def teams(self, game_id: int) -> pd.DataFrame:
        """Return both teams that participated in a game."""

    @abstractmethod
    def players(self, game_id: int) -> pd.DataFrame:
        """Return all players that participated in a game."""

    @abstractmethod
    def events(self, game_id: int) -> pd.DataFrame:
        """Return the event stream of a game."""
