"""Schemas for Opta loader output.

Parity: reference ``socceraction/data/opta/schema.py:17-85`` — the base
schemas extended with Opta-specific columns.
"""

from __future__ import annotations

from ...schema import Field, Schema

OptaCompetitionSchema = Schema(
    fields={
        'season_id': Field(),
        'season_name': Field(dtype='str'),
        'competition_id': Field(),
        'competition_name': Field(dtype='str'),
    },
    strict=False,
)

OptaGameSchema = Schema(
    fields={
        'game_id': Field(),
        'season_id': Field(),
        'competition_id': Field(),
        'game_day': Field(nullable=True, required=False),
        'game_date': Field(dtype='datetime64[ns]'),
        'home_team_id': Field(),
        'away_team_id': Field(),
        'home_score': Field(nullable=True, required=False),
        'away_score': Field(nullable=True, required=False),
        'duration': Field(nullable=True, required=False),
        'referee': Field(nullable=True, required=False),
        'venue': Field(nullable=True, required=False),
        'attendance': Field(nullable=True, required=False),
        'home_manager': Field(nullable=True, required=False),
        'away_manager': Field(nullable=True, required=False),
    },
    strict=False,
)

OptaTeamSchema = Schema(
    fields={
        'team_id': Field(),
        'team_name': Field(dtype='str'),
    },
    strict=False,
)

OptaPlayerSchema = Schema(
    fields={
        'game_id': Field(),
        'team_id': Field(),
        'player_id': Field(),
        'player_name': Field(dtype='str'),
        'is_starter': Field(dtype='bool'),
        'minutes_played': Field(dtype='int64'),
        'jersey_number': Field(dtype='int64'),
        'starting_position': Field(dtype='str', required=False),
    },
    strict=False,
)

OptaEventSchema = Schema(
    fields={
        'game_id': Field(),
        'event_id': Field(),
        'period_id': Field(dtype='int64'),
        'team_id': Field(nullable=True),
        'player_id': Field(nullable=True),
        'type_id': Field(dtype='int64'),
        'type_name': Field(dtype='str'),
        'timestamp': Field(dtype='datetime64[ns]'),
        'minute': Field(dtype='int64'),
        'second': Field(dtype='int64', ge=0, le=59),
        'outcome': Field(nullable=True),
        'start_x': Field(nullable=True),
        'start_y': Field(nullable=True),
        'end_x': Field(nullable=True),
        'end_y': Field(nullable=True),
        'qualifiers': Field(dtype='object'),
        'assist': Field(required=False),
        'keypass': Field(required=False),
        'goal': Field(required=False),
        'shot': Field(required=False),
        'touch': Field(required=False),
        'related_player_id': Field(nullable=True, required=False),
    },
    strict=False,
)
