"""Opta event data provider.

Parity: reference ``socceraction/data/opta/__init__.py``.
"""

from .loader import OptaLoader, eventtypes_df
from .parsers import (
    F1JSONParser,
    F7XMLParser,
    F9JSONParser,
    F24JSONParser,
    F24XMLParser,
    MA1JSONParser,
    MA3JSONParser,
    OptaParser,
    WhoScoredParser,
)
from .schema import (
    OptaCompetitionSchema,
    OptaEventSchema,
    OptaGameSchema,
    OptaPlayerSchema,
    OptaTeamSchema,
)

__all__ = [
    'OptaLoader',
    'eventtypes_df',
    'OptaParser',
    'F1JSONParser',
    'F7XMLParser',
    'F9JSONParser',
    'F24JSONParser',
    'F24XMLParser',
    'MA1JSONParser',
    'MA3JSONParser',
    'WhoScoredParser',
    'OptaCompetitionSchema',
    'OptaGameSchema',
    'OptaPlayerSchema',
    'OptaTeamSchema',
    'OptaEventSchema',
]
