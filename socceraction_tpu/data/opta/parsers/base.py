"""Base classes and helpers for Opta(-derived) feed parsers.

Parity: reference ``socceraction/data/opta/parsers/base.py:15-179``. A
parser wraps a single feed file and exposes ``extract_*`` methods that
return id-keyed dictionaries; the loader deep-merges the dictionaries of
all configured feeds (Opta data is spread over complementary files).
"""

from __future__ import annotations

import json
from abc import ABC
from typing import Any, Dict, List, Optional, Tuple

from .spec import derived

__all__ = [
    'OptaParser',
    'OptaJSONParser',
    'OptaXMLParser',
    'assertget',
]


class OptaParser(ABC):
    """Extract data from one Opta data-stream file.

    Parameters
    ----------
    path : str
        Path of the data file.
    """

    def __init__(self, path: str, **kwargs: Any) -> None:
        raise NotImplementedError

    def extract_competitions(self) -> Dict[Tuple[Any, Any], Dict[str, Any]]:
        """Return ``{(competition_id, season_id): info}`` for all competitions."""
        return {}

    def extract_games(self) -> Dict[Any, Dict[str, Any]]:
        """Return ``{game_id: info}`` for all games."""
        return {}

    def extract_teams(self) -> Dict[Any, Dict[str, Any]]:
        """Return ``{team_id: info}`` for all teams."""
        return {}

    def extract_players(self) -> Dict[Tuple[Any, Any], Dict[str, Any]]:
        """Return ``{(game_id, player_id): info}`` for all players."""
        return {}

    def extract_lineups(self) -> Dict[Any, Dict[str, Any]]:
        """Return ``{team_id: lineup info}`` for each team."""
        return {}

    def extract_events(self) -> Dict[Tuple[Any, Any], Dict[str, Any]]:
        """Return ``{(game_id, event_id): info}`` for all events."""
        return {}


class OptaJSONParser(OptaParser):
    """Parser backed by a JSON feed file."""

    def __init__(self, path: str, **kwargs: Any) -> None:
        with open(path, encoding='utf-8') as fh:
            self.root = json.load(fh)


class OptaXMLParser(OptaParser):
    """Parser backed by an XML feed file."""

    def __init__(self, path: str, **kwargs: Any) -> None:
        # lxml is an optional dependency (the 'io' extra): only the XML
        # feeds (F7/F24) need it, so JSON-only installs must still import
        # this package.
        from lxml import objectify

        with open(path, 'rb') as fh:
            self.root = objectify.fromstring(fh.read())


def assertget(dictionary: Dict[str, Any], key: str) -> Any:
    """Return ``dictionary[key]``, raising AssertionError when absent."""
    value = dictionary.get(key)
    assert value is not None, 'KeyError: ' + key + ' not found in ' + str(dictionary)
    return value


def _team_on_side(contestants: List[Dict[str, Any]], side: str) -> Optional[str]:
    """Return the id of the contestant on ``side`` ('home'/'away')."""
    from ...base import MissingDataError

    for team in contestants:
        if assertget(team, 'position') == side:
            return assertget(team, 'id')
    raise MissingDataError


# Qualifier ids carrying end coordinates: 140/141 pass end point, 146/147
# blocked-shot location, 102 goal-mouth y (the x is then the goal line).
def _get_end_x(qualifiers: Dict[int, Any]) -> Optional[float]:
    try:
        if 140 in qualifiers:
            return float(qualifiers[140])
        if 146 in qualifiers:
            return float(qualifiers[146])
        if 102 in qualifiers:
            return 100.0
        return None
    except ValueError:
        return None


def _get_end_y(qualifiers: Dict[int, Any]) -> Optional[float]:
    try:
        if 141 in qualifiers:
            return float(qualifiers[141])
        if 147 in qualifiers:
            return float(qualifiers[147])
        if 102 in qualifiers:
            return float(qualifiers[102])
        return None
    except ValueError:
        return None


def _derive_end_x(record: Dict[str, Any], raw: Any) -> float:
    return _get_end_x(record['qualifiers']) or record['start_x']


def _derive_end_y(record: Dict[str, Any], raw: Any) -> float:
    return _get_end_y(record['qualifiers']) or record['start_y']


#: Spec fragment shared by every event feed: end coordinates derived
#: from the qualifier dict (seeded by the parser), start-point fallback.
END_COORD_FIELDS = (
    derived('end_x', _derive_end_x),
    derived('end_y', _derive_end_y),
)


