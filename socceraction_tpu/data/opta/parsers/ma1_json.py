"""Parser for Stats Perform MA1 (fixtures / lineups) JSON feeds.

Parity: reference ``socceraction/data/opta/parsers/ma1_json.py:9-263``.
MA1 feeds use string ids and carry fixtures plus (optionally) live lineup
and card data.
"""

from __future__ import annotations

from datetime import datetime
from typing import Any, Dict, List, Optional, Tuple

from ...base import MissingDataError
from .base import OptaJSONParser, _team_on_side, assertget
from .spec import extract_record
from .statsperform import COMPETITION_FIELDS, SUBSTITUTION_FIELDS, TEAM_FIELDS


def _person_name(obj: Dict[str, Any]) -> Optional[str]:
    if 'name' in obj:
        return assertget(obj, 'name')
    if 'firstName' in obj:
        return f"{assertget(obj, 'firstName')} {assertget(obj, 'lastName')}"
    return None


class MA1JSONParser(OptaJSONParser):
    """Extract fixture, team and player data from an MA1 JSON feed."""

    def _get_matches(self) -> List[Dict[str, Any]]:
        if 'matchInfo' in self.root:
            return [self.root]
        if 'match' in self.root:
            return self.root['match']
        raise MissingDataError

    @staticmethod
    def _match_info(match: Dict[str, Any]) -> Dict[str, Any]:
        if 'matchInfo' in match:
            return match['matchInfo']
        raise MissingDataError

    @staticmethod
    def _live_data(match: Dict[str, Any]) -> Dict[str, Any]:
        return match.get('liveData', {})

    def extract_competitions(self) -> Dict[Tuple[str, str], Dict[str, Any]]:
        """Return ``{(competition_id, season_id): info}``."""
        competitions = {}
        for match in self._get_matches():
            record = extract_record(self._match_info(match), COMPETITION_FIELDS)
            competitions[(record['competition_id'], record['season_id'])] = record
        return competitions

    def extract_games(self) -> Dict[str, Dict[str, Any]]:
        """Return ``{game_id: info}``."""
        games = {}
        for match in self._get_matches():
            info = self._match_info(match)
            game_id = assertget(info, 'id')
            venue = assertget(info, 'venue')
            contestants = assertget(info, 'contestant')
            game_datetime = f"{assertget(info, 'date')} {assertget(info, 'time')}"
            games[game_id] = dict(
                game_id=game_id,
                competition_id=assertget(assertget(info, 'competition'), 'id'),
                season_id=assertget(assertget(info, 'tournamentCalendar'), 'id'),
                game_day=int(info['week']) if 'week' in info else None,
                game_date=datetime.strptime(game_datetime, '%Y-%m-%dZ %H:%M:%SZ'),
                home_team_id=_team_on_side(contestants, 'home'),
                away_team_id=_team_on_side(contestants, 'away'),
                venue=venue.get('shortName'),
            )
            live = self._live_data(match)
            details = live.get('matchDetails')
            if details is not None:
                if 'matchLengthMin' in details:
                    games[game_id]['duration'] = details['matchLengthMin']
                if 'scores' in details:
                    totals = assertget(assertget(details, 'scores'), 'total')
                    games[game_id]['home_score'] = totals['home']
                    games[game_id]['away_score'] = totals['away']
                extra = live.get('matchDetailsExtra')
                if extra is not None:
                    if 'attendance' in extra:
                        games[game_id]['attendance'] = int(extra['attendance'])
                    for official in extra.get('matchOfficial', []):
                        if official['type'] == 'Main':
                            games[game_id]['referee'] = _person_name(official)
        return games

    def extract_teams(self) -> Dict[str, Dict[str, Any]]:
        """Return ``{team_id: info}``."""
        teams = {}
        for match in self._get_matches():
            info = self._match_info(match)
            for contestant in assertget(info, 'contestant'):
                record = extract_record(contestant, TEAM_FIELDS)
                teams[record['team_id']] = record
        return teams

    def extract_players(self) -> Dict[Tuple[str, str], Dict[str, Any]]:
        """Return ``{(game_id, player_id): info}``."""
        players: Dict[Tuple[str, str], Dict[str, Any]] = {}
        subs = self.extract_substitutions()
        for match in self._get_matches():
            info = self._match_info(match)
            game_id = assertget(info, 'id')
            live = self._live_data(match)
            if 'lineUp' not in live:
                continue
            sent_off = {
                c['playerId']: c['timeMin']
                for c in live.get('card', [])
                if c.get('type') in ('Y2C', 'RC') and 'playerId' in c
            }
            for lineup in assertget(live, 'lineUp'):
                team_id = assertget(lineup, 'contestantId')
                for individual in assertget(lineup, 'player'):
                    player_id = assertget(individual, 'playerId')
                    is_starter = assertget(individual, 'position') != 'Substitute'
                    players[(game_id, player_id)] = dict(
                        game_id=game_id,
                        team_id=team_id,
                        player_id=player_id,
                        player_name=_person_name(individual),
                        is_starter=is_starter,
                        jersey_number=assertget(individual, 'shirtNumber'),
                        starting_position=assertget(individual, 'position'),
                    )
                    if 'matchDetails' not in live or 'substitute' not in live:
                        continue
                    details = assertget(live, 'matchDetails')
                    if 'matchLengthMin' not in details:
                        continue
                    duration = assertget(details, 'matchLengthMin')
                    sub_in = [
                        s
                        for s in subs.values()
                        if s['game_id'] == game_id and s['player_in_id'] == player_id
                    ]
                    sub_out = [
                        s
                        for s in subs.values()
                        if s['game_id'] == game_id and s['player_out_id'] == player_id
                    ]
                    minute_start: Optional[int]
                    if is_starter:
                        minute_start = 0
                    elif len(sub_in) == 1:
                        minute_start = sub_in[0]['minute']
                    else:
                        minute_start = None
                    minute_end = duration
                    if len(sub_out) == 1:
                        minute_end = sub_out[0]['minute']
                    elif player_id in sent_off:
                        minute_end = sent_off[player_id]
                    if is_starter or minute_start is not None:
                        players[(game_id, player_id)]['minutes_played'] = (
                            minute_end - minute_start
                        )
                    else:
                        players[(game_id, player_id)]['minutes_played'] = 0
        return players

    def extract_substitutions(self) -> Dict[Tuple[Any, Any], Dict[str, Any]]:
        """Return ``{(game_id, player_on_id): info}`` for all substitutions."""
        subs = {}
        for match in self._get_matches():
            info = self._match_info(match)
            game_id = assertget(info, 'id')
            live = self._live_data(match)
            for e in live.get('substitute', []):
                record = extract_record(
                    e, SUBSTITUTION_FIELDS, seed={'game_id': game_id}
                )
                subs[(game_id, record['player_in_id'])] = record
        return subs
