"""Shared field specs for the Opta F24 (match events) feed.

F24 ships in two dialects — a JSON tree and an XML document — that
describe the *same* Game/Event model (reference:
``socceraction/data/opta/parsers/f24_json.py`` and ``f24_xml.py``,
which duplicate the walk per dialect). Here the model is declared once;
the dialect modules contribute only what differs: how records are
located, the timestamp shape, and which attributes may be absent.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from .base import END_COORD_FIELDS
from .spec import Field, flag, ts

__all__ = [
    'GAME_FIELDS',
    'EVENT_FIELDS',
    'JSON_EVENT_FIELDS',
    'XML_EVENT_FIELDS',
    'event_seed',
]

#: Game header, dialect-independent part. ``game_date`` differs per
#: dialect (JSON nests it under a locale key, XML stores seconds-only).
GAME_FIELDS: Tuple[Field, ...] = (
    Field('game_id', 'id', int),
    Field('season_id', 'season_id', int),
    Field('competition_id', 'competition_id', int),
    Field('game_day', 'matchday', int),
    Field('home_team_id', 'home_team_id', int),
    Field('away_team_id', 'away_team_id', int),
)


#: Event row, dialect-independent part. The seed carries ``game_id``
#: and the prebuilt qualifier dict; end coordinates derive from
#: qualifiers 140/141 (pass end), 146/147 (blocked shot) or 102
#: (goal mouth), falling back to the start point.
EVENT_FIELDS: Tuple[Field, ...] = (
    Field('event_id', 'id', int),
    Field('period_id', 'period_id', int),
    Field('team_id', 'team_id', int),
    Field('type_id', 'type_id', int),
    Field('minute', 'min', int),
    Field('second', 'sec', int),
    Field('start_x', 'x', float),
    Field('start_y', 'y', float),
) + END_COORD_FIELDS + (
    Field('assist', 'assist', flag, default=False),
    Field('keypass', 'keypass', flag, default=False),
)

#: JSON dialect: sub-second UTC stamps under a ``locale`` key; every
#: event carries a player and ``outcome`` defaults to success.
JSON_EVENT_FIELDS: Tuple[Field, ...] = EVENT_FIELDS + (
    Field('timestamp', ('TimeStamp', 'locale'), ts('%Y-%m-%dT%H:%M:%S.%fZ')),
    Field('player_id', 'player_id', int),
    Field('outcome', 'outcome', flag, default=True),
)

#: XML dialect: naive sub-second stamps; system events may omit the
#: player and the outcome, which then stay ``None``.
XML_EVENT_FIELDS: Tuple[Field, ...] = EVENT_FIELDS + (
    Field('timestamp', 'timestamp', ts('%Y-%m-%dT%H:%M:%S.%f')),
    Field('player_id', 'player_id', int, default=None),
    Field('outcome', 'outcome', flag, default=None),
)


def event_seed(
    game_id: int, qualifiers: Dict[int, Optional[str]]
) -> Dict[str, Any]:
    """Context an event record needs beyond its own attributes."""
    return {'game_id': game_id, 'qualifiers': qualifiers}
