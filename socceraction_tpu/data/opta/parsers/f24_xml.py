"""Parser for Opta F24 (match events) XML feeds.

Parity: reference ``socceraction/data/opta/parsers/f24_xml.py:10-105``,
re-architected onto the declarative spec engine: the record model lives
in :mod:`.f24`; this module adapts XML elements (attribute dicts,
``Q`` children) into it.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from .base import OptaXMLParser, assertget
from .f24 import GAME_FIELDS, XML_EVENT_FIELDS, event_seed
from .spec import Field, extract_record, ts

#: XML-dialect game header: naive seconds-resolution stamp plus the
#: final score, which only this dialect carries.
_GAME_FIELDS = GAME_FIELDS + (
    Field('game_date', 'game_date', ts('%Y-%m-%dT%H:%M:%S')),
    Field('home_score', 'home_score', int),
    Field('away_score', 'away_score', int),
)


class F24XMLParser(OptaXMLParser):
    """Extract game and event data from an Opta F24 XML feed."""

    def extract_games(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{game_id: info}``."""
        game = self.root.find('Game')
        record = extract_record(dict(game.attrib), _GAME_FIELDS)
        return {record['game_id']: record}

    def extract_events(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(game_id, event_id): info}``."""
        game = self.root.find('Game')
        game_id = int(assertget(game.attrib, 'id'))
        events = {}
        for element in game.iterchildren('Event'):
            qualifiers = {
                int(q.attrib['qualifier_id']): q.attrib.get('value')
                for q in element.iterchildren('Q')
            }
            record = extract_record(
                dict(element.attrib),
                XML_EVENT_FIELDS,
                seed=event_seed(game_id, qualifiers),
            )
            events[(game_id, record['event_id'])] = record
        return events
