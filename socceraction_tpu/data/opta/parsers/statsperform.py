"""Shared field specs for Stats Perform (MA-series) feeds.

MA1 (fixtures/lineups) and MA3 (events) are one data model split over
two files: both carry the same ``matchInfo`` header with string ids
(reference: ``socceraction/data/opta/parsers/ma1_json.py`` and
``ma3_json.py``, which each re-extract it imperatively). The common
records — competition/season, contestant teams, the event row — are
declared once here; the parser modules keep only feed-specific logic
(roster assembly, substitution windows).
"""

from __future__ import annotations

from typing import Tuple

from .base import END_COORD_FIELDS
from .spec import Field, flag, ts

__all__ = ['COMPETITION_FIELDS', 'TEAM_FIELDS', 'EVENT_FIELDS', 'SUBSTITUTION_FIELDS']

#: Competition/season header out of a ``matchInfo`` node.
COMPETITION_FIELDS: Tuple[Field, ...] = (
    Field('season_id', ('tournamentCalendar', 'id')),
    Field('season_name', ('tournamentCalendar', 'name')),
    Field('competition_id', ('competition', 'id')),
    Field('competition_name', ('competition', 'name')),
)

#: One contestant out of ``matchInfo.contestant[]``.
TEAM_FIELDS: Tuple[Field, ...] = (
    Field('team_id', 'id'),
    Field('team_name', 'name'),
)

#: One event out of ``liveData.event[]`` (MA3). camelCase keys, string
#: team/player ids, mixed sub-second / whole-second timestamps.
EVENT_FIELDS: Tuple[Field, ...] = (
    Field('event_id', 'id', int),
    Field('period_id', 'periodId', int),
    Field('team_id', 'contestantId'),
    Field('player_id', 'playerId', default=None),
    Field('type_id', 'typeId', int),
    Field('timestamp', 'timeStamp', ts('%Y-%m-%dT%H:%M:%S.%fZ', '%Y-%m-%dT%H:%M:%SZ')),
    Field('minute', 'timeMin', int),
    Field('second', 'timeSec', int),
    Field('outcome', 'outcome', flag, default=True),
    Field('start_x', 'x', float),
    Field('start_y', 'y', float),
) + END_COORD_FIELDS + (
    Field('assist', 'assist', flag, default=False),
    Field('keypass', 'keyPass', flag, default=False),
)

#: One substitution out of ``liveData.substitute[]`` (MA1).
SUBSTITUTION_FIELDS: Tuple[Field, ...] = (
    Field('team_id', 'contestantId'),
    Field('period_id', 'periodId', int),
    Field('minute', 'timeMin', int),
    Field('player_in_id', 'playerOnId'),
    Field('player_out_id', 'playerOffId'),
)
