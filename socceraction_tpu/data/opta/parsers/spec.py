"""Declarative field-spec engine for Opta-family feed parsers.

The Opta/StatsPerform feeds are complementary files that all reduce to
the same job: walk a tree-shaped record (JSON mapping or XML attribute
dict), pull out named leaves, cast them, and assemble an output dict
keyed by the unified column names (reference behavior:
``socceraction/data/opta/parsers/*.py`` — each parser there hand-writes
the walk). Here the walk is data: a feed declares a tuple of
:class:`Field` rows (output name → source path + cast + default) and one
shared engine does the rest. New feeds are spec tables, not code.

Missing-key semantics follow the reference's ``assertget``: a source
that resolves to ``None`` (absent key anywhere along the path, or an
explicit JSON null) raises ``AssertionError`` unless the field declares
a ``default``. Defaults are **output-domain** values — they are emitted
as-is, never fed through the cast — which covers both reference idioms
(``attr.get('outcome', 1)`` → declare ``default=True``;
``int(attr['player_id']) if 'player_id' in attr else None`` → declare
``default=None``).
"""

from __future__ import annotations

from dataclasses import dataclass
from datetime import datetime
from typing import Any, Callable, Dict, Mapping, Optional, Sequence, Tuple, Union

__all__ = [
    'Field',
    'derived',
    'extract_record',
    'flag',
    'ref_id',
    'ts',
]


class _Required:
    """Sentinel: the field has no fallback; missing source is an error."""

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return '<REQUIRED>'


REQUIRED = _Required()


@dataclass(frozen=True)
class Field:
    """One output column of a feed record.

    Parameters
    ----------
    out : str
        Output field name (unified schema column).
    src : str or tuple of str, optional
        Key, or path of keys, into the source mapping. ``None`` only for
        derived fields.
    cast : callable, optional
        Applied to the resolved source value (``int``, ``float``,
        :func:`ts`, :func:`flag`, ...). Identity when omitted.
    default : any
        Output-domain fallback when the source is missing. When left at
        ``REQUIRED`` a missing source raises ``AssertionError`` (the
        reference's ``assertget`` contract).
    derive : callable, optional
        ``derive(record, raw) -> value`` computed from the fields
        extracted so far plus the raw source; used for cross-field
        output such as qualifier-driven end coordinates.
    """

    out: str
    src: Optional[Union[str, Tuple[str, ...]]] = None
    cast: Optional[Callable[[Any], Any]] = None
    default: Any = REQUIRED
    derive: Optional[Callable[[Dict[str, Any], Mapping[str, Any]], Any]] = None


def derived(out: str, fn: Callable[[Dict[str, Any], Mapping[str, Any]], Any]) -> Field:
    """A field computed from already-extracted fields (and the raw source)."""
    return Field(out, derive=fn)


def _resolve(raw: Mapping[str, Any], path: Union[str, Tuple[str, ...]]) -> Any:
    """Walk ``path`` into ``raw``; ``None`` when any hop is absent/null."""
    node: Any = raw
    for key in (path,) if isinstance(path, str) else path:
        if not isinstance(node, Mapping):
            return None
        node = node.get(key)
        if node is None:
            return None
    return node


def extract_record(
    raw: Mapping[str, Any],
    fields: Sequence[Field],
    seed: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Run a spec table over one source record.

    ``seed`` pre-populates the output (context such as ``game_id`` or a
    prebuilt ``qualifiers`` dict) so spec rows and ``derive`` hooks can
    reference it.
    """
    record: Dict[str, Any] = dict(seed) if seed else {}
    for field in fields:
        if field.derive is not None:
            record[field.out] = field.derive(record, raw)
            continue
        assert field.src is not None, f'field {field.out!r} has no src and no derive'
        value = _resolve(raw, field.src)
        if value is None:
            if isinstance(field.default, _Required):
                raise AssertionError(
                    'KeyError: ' + str(field.src) + ' not found in ' + str(raw)
                )
            record[field.out] = field.default
        else:
            record[field.out] = field.cast(value) if field.cast else value
    return record


def ts(*formats: str) -> Callable[[str], datetime]:
    """Timestamp cast trying each strptime format; tz info is dropped.

    Several feeds mix sub-second and whole-second stamps in one file
    (StatsPerform MA3), hence the fallback chain. Offset-carrying
    formats (Opta F9's ``%z``) are normalized to naive datetimes, the
    reference's convention.
    """

    def parse(value: str) -> datetime:
        last: Optional[ValueError] = None
        for fmt in formats:
            try:
                return datetime.strptime(value, fmt).replace(tzinfo=None)
            except ValueError as e:
                last = e
        raise last  # type: ignore[misc]

    return parse


def flag(value: Any) -> bool:
    """Opta boolean attribute: ``'1'``/``1`` truthy, ``'0'``/``0`` falsy."""
    return bool(int(value))


def ref_id(value: str) -> int:
    """Typed Opta reference (``g1234``, ``t56``, ``p789``) → numeric id."""
    return int(value[1:])
