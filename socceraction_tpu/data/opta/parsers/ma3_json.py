"""Parser for Stats Perform MA3 (match events) JSON feeds.

Parity: reference ``socceraction/data/opta/parsers/ma3_json.py:11-364``.
MA3 feeds carry one game's event stream; lineups are encoded as
"team set up" events (type 34) whose qualifiers hold parallel id lists.
"""

from __future__ import annotations

from datetime import datetime
from typing import Any, Dict, List, Tuple

import pandas as pd

from ...base import MissingDataError
from .base import OptaJSONParser, _team_on_side, assertget
from .spec import extract_record
from .statsperform import COMPETITION_FIELDS, EVENT_FIELDS, TEAM_FIELDS

_POSITIONS = {
    1: 'Goalkeeper',
    2: 'Defender',
    3: 'Midfielder',
    4: 'Forward',
    5: 'Substitute',
}


class MA3JSONParser(OptaJSONParser):
    """Extract game, team, player and event data from an MA3 JSON feed."""

    def _match_info(self) -> Dict[str, Any]:
        if 'matchInfo' in self.root:
            return self.root['matchInfo']
        raise MissingDataError

    def _live_data(self) -> Dict[str, Any]:
        if 'liveData' in self.root:
            return self.root['liveData']
        raise MissingDataError

    def extract_competitions(self) -> Dict[Tuple[str, str], Dict[str, Any]]:
        """Return ``{(competition_id, season_id): info}``."""
        record = extract_record(self._match_info(), COMPETITION_FIELDS)
        return {(record['competition_id'], record['season_id']): record}

    def extract_games(self) -> Dict[str, Dict[str, Any]]:
        """Return ``{game_id: info}``."""
        info = self._match_info()
        live = self._live_data()
        game_id = assertget(info, 'id')
        contestants = assertget(info, 'contestant')
        details = assertget(live, 'matchDetails')
        score_total = assertget(assertget(details, 'scores'), 'total')
        home_score = away_score = None
        if isinstance(score_total, dict):
            home_score = assertget(score_total, 'home')
            away_score = assertget(score_total, 'away')
        game_datetime = (
            f"{assertget(info, 'date')[0:10]}T{assertget(info, 'time')[0:8]}"
        )
        return {
            game_id: dict(
                game_id=game_id,
                season_id=assertget(assertget(info, 'tournamentCalendar'), 'id'),
                competition_id=assertget(assertget(info, 'competition'), 'id'),
                game_day=int(assertget(info, 'week')),
                game_date=datetime.strptime(game_datetime, '%Y-%m-%dT%H:%M:%S'),
                home_team_id=_team_on_side(contestants, 'home'),
                away_team_id=_team_on_side(contestants, 'away'),
                home_score=home_score,
                away_score=away_score,
                duration=assertget(details, 'matchLengthMin'),
                venue=assertget(assertget(info, 'venue'), 'shortName'),
            )
        }

    def extract_teams(self) -> Dict[str, Dict[str, Any]]:
        """Return ``{team_id: info}``."""
        info = self._match_info()
        records = [
            extract_record(c, TEAM_FIELDS) for c in assertget(info, 'contestant')
        ]
        return {r['team_id']: r for r in records}

    def extract_players(self) -> Dict[Tuple[str, str], Dict[str, Any]]:
        """Return ``{(game_id, player_id): info}`` (players with minutes > 0).

        Lineups come from the type-34 "team set up" events: qualifier 30
        lists player ids, 44 starting positions, 131 formation slots and 59
        jersey numbers, all as comma-separated parallel lists.
        """
        info = self._match_info()
        game_id = assertget(info, 'id')
        live = self._live_data()
        events = assertget(live, 'event')

        duration = self._extract_duration()
        names: Dict[str, str] = {}
        columns: Dict[str, List[Any]] = {
            'starting_position_id': [],
            'player_id': [],
            'team_id': [],
            'position_in_formation': [],
            'jersey_number': [],
        }
        sent_off: Dict[str, int] = {}
        for event in events:
            type_id = assertget(event, 'typeId')
            if type_id == 34:
                team_id = assertget(event, 'contestantId')
                for q in assertget(event, 'qualifier'):
                    qualifier_id = assertget(q, 'qualifierId')
                    values = assertget(q, 'value').split(', ')
                    if qualifier_id == 30:
                        columns['player_id'] += values
                        columns['team_id'] += [team_id] * len(values)
                    elif qualifier_id == 44:
                        columns['starting_position_id'] += [int(v) for v in values]
                    elif qualifier_id == 131:
                        columns['position_in_formation'] += [int(v) for v in values]
                    elif qualifier_id == 59:
                        columns['jersey_number'] += [int(v) for v in values]
            elif type_id == 17 and 'playerId' in event:
                for q in assertget(event, 'qualifier'):
                    if assertget(q, 'qualifierId') in (32, 33):
                        sent_off[event['playerId']] = event['timeMin']
            player_id = event.get('playerId')
            if player_id is not None and player_id not in names:
                names[player_id] = assertget(event, 'playerName')

        roster = pd.DataFrame.from_dict(columns)

        subs = pd.DataFrame(
            list(self.extract_substitutions().values()),
            columns=['player_id', 'team_id', 'minute_start', 'minute_end'],
        )
        subs = subs.groupby(['player_id', 'team_id']).max().reset_index()
        subs['minute_start'] = subs['minute_start'].fillna(0)
        subs['minute_end'] = subs['minute_end'].fillna(duration)
        if subs.empty:
            roster['minute_start'] = 0
            roster['minute_end'] = duration
        else:
            roster = roster.merge(subs, on=['team_id', 'player_id'], how='left')
        roster['minute_end'] = roster.apply(
            lambda row: sent_off.get(row['player_id'], row['minute_end']), axis=1
        )
        roster['is_starter'] = roster['position_in_formation'] > 0
        starter_rows = roster['is_starter']
        roster.loc[starter_rows & roster['minute_start'].isnull(), 'minute_start'] = 0
        roster.loc[starter_rows & roster['minute_end'].isnull(), 'minute_end'] = duration
        roster['minutes_played'] = (
            (roster['minute_end'] - roster['minute_start']).fillna(0).astype(int)
        )

        players = {}
        for _, row in roster.iterrows():
            if row.minutes_played > 0:
                players[(game_id, row.player_id)] = dict(
                    game_id=game_id,
                    team_id=row.team_id,
                    player_id=row.player_id,
                    player_name=names[row.player_id],
                    is_starter=row.is_starter,
                    minutes_played=row.minutes_played,
                    jersey_number=row.jersey_number,
                    starting_position=_POSITIONS.get(
                        row.starting_position_id, 'Unknown'
                    ),
                )
        return players

    def extract_events(self) -> Dict[Tuple[str, int], Dict[str, Any]]:
        """Return ``{(game_id, event_id): info}``."""
        info = self._match_info()
        live = self._live_data()
        game_id = assertget(info, 'id')
        events = {}
        for element in assertget(live, 'event'):
            qualifiers = {
                int(q['qualifierId']): q.get('value')
                for q in element.get('qualifier', [])
            }
            record = extract_record(
                element,
                EVENT_FIELDS,
                seed={'game_id': game_id, 'qualifiers': qualifiers},
            )
            events[(game_id, record['event_id'])] = record
        return events

    def extract_substitutions(self) -> Dict[Any, Dict[str, Any]]:
        """Return per-player substitution windows from type 18/19 events."""
        live = self._live_data()
        subs: Dict[Any, Dict[str, Any]] = {}
        for e in assertget(live, 'event'):
            type_id = assertget(e, 'typeId')
            if type_id in (18, 19):
                sub_id = assertget(e, 'playerId')
                record = {
                    'player_id': sub_id,
                    'team_id': assertget(e, 'contestantId'),
                }
                if type_id == 18:
                    record['minute_end'] = assertget(e, 'timeMin')
                else:
                    record['minute_start'] = assertget(e, 'timeMin')
                subs[sub_id] = record
        return subs

    def _extract_duration(self) -> int:
        live = self._live_data()
        duration = 90
        for event in assertget(live, 'event'):
            if assertget(event, 'typeId') == 30:
                for q in assertget(event, 'qualifier'):
                    if assertget(q, 'qualifierId') == 209:
                        duration = max(duration, assertget(event, 'timeMin'))
        return duration
