"""Parser for Opta F24 (match events) JSON feeds.

Parity: reference ``socceraction/data/opta/parsers/f24_json.py:9-122``,
re-architected onto the declarative spec engine: the record model lives
in :mod:`.f24`, this module only locates the Game node inside the JSON
envelope and feeds its attribute dicts through the shared specs.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from ...base import MissingDataError
from .base import OptaJSONParser, assertget
from .f24 import GAME_FIELDS, JSON_EVENT_FIELDS, event_seed
from .spec import Field, extract_record, ts

#: JSON-dialect game header: the UTC stamp nests under a locale key.
_GAME_FIELDS = GAME_FIELDS + (
    Field('game_date', ('game_date', 'locale'), ts('%Y-%m-%dT%H:%M:%S.%fZ')),
)


class F24JSONParser(OptaJSONParser):
    """Extract game and event data from an Opta F24 JSON feed."""

    def _get_game(self) -> Dict[str, Any]:
        for node in self.root:
            if 'Games' in node['data'].keys():
                data = assertget(node, 'data')
                games = assertget(data, 'Games')
                return assertget(games, 'Game')
        raise MissingDataError

    def extract_games(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{game_id: info}``."""
        attr = assertget(self._get_game(), '@attributes')
        record = extract_record(attr, _GAME_FIELDS)
        return {record['game_id']: record}

    def extract_events(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(game_id, event_id): info}``."""
        game = self._get_game()
        game_id = int(assertget(assertget(game, '@attributes'), 'id'))
        events = {}
        for element in assertget(game, 'Event'):
            attr = assertget(element, '@attributes')
            qualifiers = {
                int(q['@attributes']['qualifier_id']): q['@attributes']['value']
                for q in element.get('Q', [])
            }
            record = extract_record(
                attr, JSON_EVENT_FIELDS, seed=event_seed(game_id, qualifiers)
            )
            events[(game_id, record['event_id'])] = record
        return events
