"""Parser for Opta F24 (match events) JSON feeds.

Parity: reference ``socceraction/data/opta/parsers/f24_json.py:9-122``.
The F24 feed holds one game's full event stream with qualifiers.
"""

from __future__ import annotations

from datetime import datetime
from typing import Any, Dict, Tuple

from ...base import MissingDataError
from .base import OptaJSONParser, _get_end_x, _get_end_y, assertget


class F24JSONParser(OptaJSONParser):
    """Extract game and event data from an Opta F24 JSON feed."""

    def _get_game(self) -> Dict[str, Any]:
        for node in self.root:
            if 'Games' in node['data'].keys():
                data = assertget(node, 'data')
                games = assertget(data, 'Games')
                return assertget(games, 'Game')
        raise MissingDataError

    def extract_games(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{game_id: info}``."""
        game = self._get_game()
        attr = assertget(game, '@attributes')
        game_id = int(assertget(attr, 'id'))
        return {
            game_id: dict(
                game_id=game_id,
                season_id=int(assertget(attr, 'season_id')),
                competition_id=int(assertget(attr, 'competition_id')),
                game_day=int(assertget(attr, 'matchday')),
                game_date=datetime.strptime(
                    assertget(assertget(attr, 'game_date'), 'locale'),
                    '%Y-%m-%dT%H:%M:%S.%fZ',
                ).replace(tzinfo=None),
                home_team_id=int(assertget(attr, 'home_team_id')),
                away_team_id=int(assertget(attr, 'away_team_id')),
            )
        }

    def extract_events(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(game_id, event_id): info}``."""
        game = self._get_game()
        game_attr = assertget(game, '@attributes')
        game_id = int(assertget(game_attr, 'id'))
        events = {}
        for element in assertget(game, 'Event'):
            attr = element['@attributes']
            ts_raw = attr['TimeStamp'].get('locale') if attr.get('TimeStamp') else None
            timestamp = datetime.strptime(ts_raw, '%Y-%m-%dT%H:%M:%S.%fZ')
            qualifiers = {
                int(q['@attributes']['qualifier_id']): q['@attributes']['value']
                for q in element.get('Q', [])
            }
            start_x = float(assertget(attr, 'x'))
            start_y = float(assertget(attr, 'y'))
            event_id = int(assertget(attr, 'id'))
            events[(game_id, event_id)] = dict(
                game_id=game_id,
                event_id=event_id,
                period_id=int(assertget(attr, 'period_id')),
                team_id=int(assertget(attr, 'team_id')),
                player_id=int(assertget(attr, 'player_id')),
                type_id=int(assertget(attr, 'type_id')),
                timestamp=timestamp,
                minute=int(assertget(attr, 'min')),
                second=int(assertget(attr, 'sec')),
                outcome=bool(int(attr.get('outcome', 1))),
                start_x=start_x,
                start_y=start_y,
                end_x=_get_end_x(qualifiers) or start_x,
                end_y=_get_end_y(qualifiers) or start_y,
                qualifiers=qualifiers,
                assist=bool(int(attr.get('assist', 0))),
                keypass=bool(int(attr.get('keypass', 0))),
            )
        return events
