"""Parser for Opta F1 (fixtures) JSON feeds.

Parity: reference ``socceraction/data/opta/parsers/f1_json.py:9-102``.
The F1 feed lists a competition-season's fixtures.
"""

from __future__ import annotations

from datetime import datetime
from typing import Any, Dict, Tuple

from ...base import MissingDataError
from .base import OptaJSONParser, assertget


class F1JSONParser(OptaJSONParser):
    """Extract competition and fixture data from an Opta F1 JSON feed."""

    def _get_doc(self) -> Dict[str, Any]:
        for node in self.root:
            if 'OptaFeed' in node['data'].keys():
                data = assertget(node, 'data')
                feed = assertget(data, 'OptaFeed')
                return assertget(feed, 'OptaDocument')
        raise MissingDataError

    def extract_competitions(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(competition_id, season_id): info}``."""
        doc = self._get_doc()
        attr = assertget(doc, '@attributes')
        competition_id = int(assertget(attr, 'competition_id'))
        season_id = int(assertget(attr, 'season_id'))
        return {
            (competition_id, season_id): dict(
                season_id=season_id,
                season_name=str(assertget(attr, 'season_id')),
                competition_id=competition_id,
                competition_name=assertget(attr, 'competition_name'),
            )
        }

    def extract_games(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{game_id: info}`` for every fixture in the feed."""
        doc = self._get_doc()
        attr = assertget(doc, '@attributes')
        competition_id = int(assertget(attr, 'competition_id'))
        season_id = int(assertget(attr, 'season_id'))
        games = {}
        for match in assertget(doc, 'MatchData'):
            match_attr = assertget(match, '@attributes')
            info = assertget(match, 'MatchInfo')
            info_attr = assertget(info, '@attributes')
            game_id = int(assertget(match_attr, 'uID')[1:])
            record: Dict[str, Any] = dict(
                game_id=game_id,
                competition_id=competition_id,
                season_id=season_id,
                game_day=int(assertget(info_attr, 'MatchDay')),
                game_date=datetime.strptime(
                    assertget(info, 'Date'), '%Y-%m-%d %H:%M:%S'
                ),
            )
            for team in assertget(match, 'TeamData'):
                team_attr = assertget(team, '@attributes')
                prefix = 'home' if assertget(team_attr, 'Side') == 'Home' else 'away'
                record[f'{prefix}_team_id'] = int(assertget(team_attr, 'TeamRef')[1:])
                record[f'{prefix}_score'] = int(assertget(team_attr, 'Score'))
            games[game_id] = record
        return games
