"""Parser for Opta F1 (fixtures) JSON feeds.

Parity: reference ``socceraction/data/opta/parsers/f1_json.py:9-102``,
on the declarative spec engine: the competition header and fixture core
are spec tables; only the per-side TeamData fold stays imperative.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from ...base import MissingDataError
from .base import OptaJSONParser, assertget
from .spec import Field, extract_record, ref_id, ts

#: Competition/season header out of the OptaDocument attributes. The
#: season's display name is just its id rendered as text.
_COMPETITION_FIELDS = (
    Field('season_id', 'season_id', int),
    Field('season_name', 'season_id', str),
    Field('competition_id', 'competition_id', int),
    Field('competition_name', 'competition_name'),
)

#: Fixture core out of a MatchData node; home/away columns are folded
#: in afterwards from the TeamData children.
_GAME_FIELDS = (
    Field('game_id', ('@attributes', 'uID'), ref_id),
    Field('game_day', ('MatchInfo', '@attributes', 'MatchDay'), int),
    Field('game_date', ('MatchInfo', 'Date'), ts('%Y-%m-%d %H:%M:%S')),
)


class F1JSONParser(OptaJSONParser):
    """Extract competition and fixture data from an Opta F1 JSON feed."""

    def _get_doc(self) -> Dict[str, Any]:
        for node in self.root:
            if 'OptaFeed' in node['data'].keys():
                data = assertget(node, 'data')
                feed = assertget(data, 'OptaFeed')
                return assertget(feed, 'OptaDocument')
        raise MissingDataError

    def extract_competitions(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(competition_id, season_id): info}``."""
        attr = assertget(self._get_doc(), '@attributes')
        record = extract_record(attr, _COMPETITION_FIELDS)
        return {(record['competition_id'], record['season_id']): record}

    def extract_games(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{game_id: info}`` for every fixture in the feed."""
        doc = self._get_doc()
        attr = assertget(doc, '@attributes')
        context = {
            'competition_id': int(assertget(attr, 'competition_id')),
            'season_id': int(assertget(attr, 'season_id')),
        }
        games = {}
        for match in assertget(doc, 'MatchData'):
            record = extract_record(match, _GAME_FIELDS, seed=context)
            for team in assertget(match, 'TeamData'):
                team_attr = assertget(team, '@attributes')
                prefix = 'home' if assertget(team_attr, 'Side') == 'Home' else 'away'
                record[f'{prefix}_team_id'] = ref_id(assertget(team_attr, 'TeamRef'))
                record[f'{prefix}_score'] = int(assertget(team_attr, 'Score'))
            games[record['game_id']] = record
        return games
