"""Parser for Opta F7 (match results / lineups) XML feeds.

Parity: reference ``socceraction/data/opta/parsers/f7_xml.py:10-245``.
"""

from __future__ import annotations

from datetime import datetime
from typing import TYPE_CHECKING, Any, Dict, Tuple

if TYPE_CHECKING:
    from lxml import objectify

from .base import OptaXMLParser, assertget


class F7XMLParser(OptaXMLParser):
    """Extract competition, game, team and player data from an F7 XML feed."""

    def _get_doc(self) -> objectify.ObjectifiedElement:
        return self.root.find('SoccerDocument')

    def _stats_of(self, element: objectify.ObjectifiedElement) -> Dict[str, Any]:
        return {stat.attrib['Type']: stat.text for stat in element.find('Stat')}

    def _name_of(self, element: objectify.ObjectifiedElement) -> str:
        if 'Known' in element:
            return element.Known
        return element.First + ' ' + element.Last

    def extract_competitions(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(competition_id, season_id): info}``."""
        doc = self._get_doc()
        competition = doc.Competition
        competition_id = int(competition.attrib['uID'][1:])
        stats = self._stats_of(competition)
        season_id = int(assertget(stats, 'season_id'))
        return {
            (competition_id, season_id): dict(
                competition_id=competition_id,
                season_id=season_id,
                season_name=assertget(stats, 'season_name'),
                competition_name=competition.Name.text,
            )
        }

    def extract_games(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{game_id: info}``."""
        doc = self._get_doc()
        competition = doc.Competition
        competition_stats = self._stats_of(competition)
        match_info = doc.MatchData.MatchInfo
        match_stats = self._stats_of(doc.MatchData)
        game_id = int(doc.attrib['uID'][1:])
        sides = {t.attrib['Side']: t for t in doc.MatchData.iterchildren('TeamData')}
        home_ref = int(sides['Home'].attrib['TeamRef'][1:])
        managers = {}
        for team in doc.iterchildren('Team'):
            side = 'Home' if home_ref == int(team.attrib['uID'][1:]) else 'Away'
            for official in team.iterchildren('TeamOfficial'):
                if official.attrib['Type'] == 'Manager':
                    managers[side] = self._name_of(official.PersonName)
        return {
            game_id: dict(
                game_id=game_id,
                season_id=int(assertget(competition_stats, 'season_id')),
                competition_id=int(competition.attrib['uID'][1:]),
                game_day=int(competition_stats['matchday'])
                if 'matchday' in competition_stats
                else None,
                game_date=datetime.strptime(
                    match_info.Date.text, '%Y%m%dT%H%M%S%z'
                ).replace(tzinfo=None),
                home_team_id=home_ref,
                away_team_id=int(sides['Away'].attrib['TeamRef'][1:]),
                home_score=int(sides['Home'].attrib['Score']),
                away_score=int(sides['Away'].attrib['Score']),
                duration=int(match_stats['match_time']),
                referee=self._name_of(doc.MatchData.MatchOfficial.OfficialName),
                venue=doc.Venue.Name.text,
                attendance=int(match_info.Attendance),
                home_manager=managers.get('Home'),
                away_manager=managers.get('Away'),
            )
        }

    def extract_teams(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{team_id: info}``."""
        doc = self._get_doc()
        teams = {}
        for team in doc.iterchildren('Team'):
            team_id = int(assertget(team.attrib, 'uID')[1:])
            teams[team_id] = dict(team_id=team_id, team_name=team.Name.text)
        return teams

    def extract_lineups(self) -> Dict[int, Dict[str, Any]]:
        """Return per-team lineup info incl. per-player minutes played."""
        doc = self._get_doc()
        match_stats = self._stats_of(doc.MatchData)
        lineups: Dict[int, Dict[str, Any]] = {}
        for team in doc.MatchData.iterchildren('TeamData'):
            team_id = int(team.attrib['TeamRef'][1:])
            lineups[team_id] = dict(
                formation=team.attrib['Formation'],
                score=int(team.attrib['Score']),
                side=team.attrib['Side'],
                players=dict(),
            )
            substitutions = [s.attrib for s in team.iterchildren('Substitution')]
            sent_off = {
                int(b.attrib['PlayerRef'][1:]): int(b.attrib['Min'])
                for b in team.iterchildren('Booking')
                if 'CardType' in b.attrib
                and b.attrib['CardType'] in ('Red', 'SecondYellow')
                and 'PlayerRef' in b.attrib  # absent for coach cards
            }
            for player in team.PlayerLineUp.iterchildren('MatchPlayer'):
                player_id = int(player.attrib['PlayerRef'][1:])
                sub_on = int(
                    next(
                        (
                            s['Time']
                            for s in substitutions
                            if 'Retired' not in s and s['SubOn'] == f'p{player_id}'
                        ),
                        match_stats['match_time']
                        if player.attrib['Status'] == 'Sub'
                        else 0,
                    )
                )
                sub_off = int(
                    next(
                        (s['Time'] for s in substitutions if s['SubOff'] == f'p{player_id}'),
                        match_stats['match_time']
                        if player_id not in sent_off
                        else sent_off[player_id],
                    )
                )
                lineups[team_id]['players'][player_id] = dict(
                    starting_position_id=int(player.attrib['Formation_Place']),
                    starting_position_name=player.attrib['Position'],
                    jersey_number=int(player.attrib['ShirtNumber']),
                    is_starter=int(player.attrib['Formation_Place']) != 0,
                    minutes_played=sub_off - sub_on,
                )
        return lineups

    def extract_players(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(game_id, player_id): info}``."""
        doc = self._get_doc()
        game_id = int(doc.attrib['uID'][1:])
        lineups = self.extract_lineups()
        players = {}
        for team in doc.iterchildren('Team'):
            team_id = int(team.attrib['uID'][1:])
            for player in team.iterchildren('Player'):
                player_id = int(player.attrib['uID'][1:])
                entry = lineups[team_id]['players'][player_id]
                players[(game_id, player_id)] = dict(
                    game_id=game_id,
                    team_id=team_id,
                    player_id=player_id,
                    player_name=self._name_of(player.PersonName),
                    is_starter=entry['is_starter'],
                    minutes_played=entry['minutes_played'],
                    jersey_number=entry['jersey_number'],
                    starting_position=entry['starting_position_name'],
                )
        return players
