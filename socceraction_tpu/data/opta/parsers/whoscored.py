"""Parser for JSON match-centre data scraped from WhoScored.

Parity: reference ``socceraction/data/opta/parsers/whoscored.py:17-418``.
WhoScored republishes Opta data; ids for competition/season/game are not
always embedded and can be supplied from the file path instead.
"""

from __future__ import annotations

import json
from datetime import datetime, timedelta
from typing import Any, Dict, Optional, Tuple

from ...base import MissingDataError, _snake
from .base import OptaParser, _get_end_x, _get_end_y, assertget
from .spec import Field, derived, extract_record, ts


class WhoScoredParser(OptaParser):
    """Extract data from a WhoScored match-centre JSON file.

    Parameters
    ----------
    path : str
        Path of the data file.
    competition_id, season_id, game_id : int, optional
        Ids of the data file's scope; read from same-named JSON fields when
        not given.
    """

    def __init__(
        self,
        path: str,
        competition_id: Optional[int] = None,
        season_id: Optional[int] = None,
        game_id: Optional[int] = None,
    ) -> None:
        with open(path, encoding='utf-8') as fh:
            self.root = json.load(fh)
        for name, value in (
            ('competition_id', competition_id),
            ('season_id', season_id),
            ('game_id', game_id),
        ):
            if value is None:
                try:
                    value = int(assertget(self.root, name))
                except AssertionError as e:
                    raise MissingDataError(
                        f'Could not determine the {name}. Add it to the file '
                        f"path or include a field '{name}' in the JSON."
                    ) from e
            setattr(self, name, value)

    def _period_id(self, event: Dict[str, Any]) -> int:
        return int(assertget(assertget(event, 'period'), 'value'))

    def _period_milliseconds(self, event: Dict[str, Any]) -> int:
        period_id = self._period_id(event)
        if period_id in (14, 16):  # post-game / pre-match
            return 0
        limits = assertget(self.root, 'periodMinuteLimits')
        minute = int(assertget(event, 'minute'))
        period_minute = minute
        if period_id > 1:
            period_minute = minute - limits[str(period_id - 1)]
        return (period_minute * 60 + int(event.get('second', 0))) * 1000

    #: Game header straight off the match-centre root; scope ids come in
    #: via the seed (path-supplied when not embedded in the JSON).
    _GAME_FIELDS = (
        Field('game_date', 'startTime', ts('%Y-%m-%dT%H:%M:%S')),
        Field('home_team_id', ('home', 'teamId'), int),
        Field('away_team_id', ('away', 'teamId'), int),
        Field('home_score', ('home', 'scores', 'running'), int),
        Field('away_score', ('away', 'scores', 'running'), int),
        Field('duration', 'expandedMaxMinute', int, default=None),
        Field('referee', ('referee', 'name'), default=None),
        Field('venue', 'venueName', default=None),
        Field('attendance', 'attendance', int, default=None),
        Field('home_manager', ('home', 'managerName'), default=None),
        Field('away_manager', ('away', 'managerName'), default=None),
    )

    _TEAM_FIELDS = (
        Field('team_id', 'teamId', int),
        Field('team_name', 'name'),
    )

    def extract_games(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{game_id: info}``."""
        record = extract_record(
            self.root,
            self._GAME_FIELDS,
            seed={
                'game_id': self.game_id,
                'season_id': self.season_id,
                'competition_id': self.competition_id,
                'game_day': None,  # not in the data stream
            },
        )
        return {self.game_id: record}

    def extract_teams(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{team_id: info}``."""
        records = [
            extract_record(self.root[side], self._TEAM_FIELDS)
            for side in ('home', 'away')
        ]
        return {r['team_id']: r for r in records}

    def extract_players(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(game_id, player_id): info}``."""
        gamestats = self.extract_playergamestats()
        players = {}
        for team in (self.root['home'], self.root['away']):
            team_id = int(assertget(team, 'teamId'))
            for p in team['players']:
                player_id = int(assertget(p, 'playerId'))
                stats = gamestats[(self.game_id, player_id)]
                players[(self.game_id, player_id)] = dict(
                    game_id=self.game_id,
                    team_id=team_id,
                    player_id=player_id,
                    player_name=assertget(p, 'name'),
                    is_starter=bool(p.get('isFirstEleven', False)),
                    minutes_played=stats['minutes_played'],
                    jersey_number=stats['jersey_number'],
                    starting_position=stats['position_code'],
                )
        return players

    def _event_fields(self, time_start: datetime) -> Tuple[Field, ...]:
        """Event spec; closures carry feed-wide context (kickoff, periods)."""
        return (
            # Scraped files disagree on the id key's name.
            derived(
                'event_id',
                lambda rec, raw: int(
                    assertget(raw, 'id' if 'id' in raw else 'eventId')
                ),
            ),
            derived('period_id', lambda rec, raw: self._period_id(raw)),
            Field('team_id', 'teamId', int),
            Field('player_id', 'playerId', int, default=None),
            Field('type_id', ('type', 'value'), int),
            Field('minute', 'expandedMinute', int),
            Field('second', 'second', int, default=0),
            # No true timestamp in the stream; reconstructed from the
            # kickoff time for compatibility with other Opta feeds.
            derived(
                'timestamp',
                lambda rec, raw: time_start
                + timedelta(seconds=rec['minute'] * 60 + rec['second']),
            ),
            derived(
                'outcome',
                lambda rec, raw: bool(raw['outcomeType'].get('value'))
                if 'outcomeType' in raw
                else None,
            ),
            Field('start_x', 'x', float),
            Field('start_y', 'y', float),
            # The stream's own end point wins over the qualifier-derived one.
            derived(
                'end_x',
                lambda rec, raw: raw.get('endX')
                or _get_end_x(rec['qualifiers'])
                or rec['start_x'],
            ),
            derived(
                'end_y',
                lambda rec, raw: raw.get('endY')
                or _get_end_y(rec['qualifiers'])
                or rec['start_y'],
            ),
            Field('related_player_id', 'relatedPlayerId', int, default=None),
            Field('touch', 'isTouch', bool, default=False),
            # NOTE: shot/goal are intentionally crossed to reproduce the
            # reference's mapping (``parsers/whoscored.py:240-241``);
            # downstream SPADL conversion keys off type_id, not these.
            Field('shot', 'isGoal', bool, default=False),
            Field('goal', 'isShot', bool, default=False),
        )

    def extract_events(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(game_id, event_id): info}``."""
        time_start = datetime.strptime(
            assertget(self.root, 'startTime'), '%Y-%m-%dT%H:%M:%S'
        )
        fields = self._event_fields(time_start)
        events = {}
        for attr in self.root['events']:
            qualifiers = {
                int(q['type']['value']): q.get('value', True)
                for q in attr.get('qualifiers', [])
            }
            record = extract_record(
                attr,
                fields,
                seed={'game_id': self.game_id, 'qualifiers': qualifiers},
            )
            events[(self.game_id, record['event_id'])] = record
        return events

    def extract_substitutions(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(game_id, player_in_id): info}`` for substitutions."""
        subs = {}
        for e in self.root['events']:
            if e['type'].get('value') != 19:
                continue
            sub_id = int(assertget(e, 'playerId'))
            subs[(self.game_id, sub_id)] = dict(
                game_id=self.game_id,
                team_id=int(assertget(e, 'teamId')),
                period_id=self._period_id(e),
                period_milliseconds=self._period_milliseconds(e),
                player_in_id=int(assertget(e, 'playerId')),
                player_out_id=int(assertget(e, 'relatedPlayerId')),
            )
        return subs

    def extract_positions(self) -> Dict[Tuple[int, int, int], Dict[str, Any]]:
        """Return each player's position per formation epoch."""
        positions = {}
        period_end_minutes = assertget(self.root, 'periodEndMinutes')
        period_minute_limits = assertget(self.root, 'periodMinuteLimits')
        for team in (self.root['home'], self.root['away']):
            team_id = int(assertget(team, 'teamId'))
            for formation in assertget(team, 'formations'):
                slots = assertget(formation, 'formationPositions')
                player_ids = assertget(formation, 'playerIds')
                scheme = assertget(formation, 'formationName')
                start_minute = int(assertget(formation, 'startMinuteExpanded'))
                end_minute = int(assertget(formation, 'endMinuteExpanded'))
                for period_id in sorted(period_end_minutes.keys()):
                    if period_end_minutes[period_id] > start_minute:
                        break
                period_id = int(period_id)
                period_minute = start_minute
                if period_id > 1:
                    period_minute = start_minute - period_minute_limits[str(period_id - 1)]
                for i, slot in enumerate(slots):
                    player_id = int(player_ids[i])
                    x = float(assertget(slot, 'vertical'))
                    y = float(assertget(slot, 'horizontal'))
                    positions[(self.game_id, player_id, start_minute)] = dict(
                        game_id=self.game_id,
                        team_id=team_id,
                        player_id=player_id,
                        period_id=period_id,
                        period_milliseconds=period_minute * 60 * 1000,
                        start_milliseconds=start_minute * 60 * 1000,
                        end_milliseconds=end_minute * 60 * 1000,
                        formation_scheme=scheme,
                        player_position='GK' if x == 0 and y == 5 else 'Unknown',
                        player_position_x=x,
                        player_position_y=y,
                    )
        return positions

    def extract_teamgamestats(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return per-team aggregated game statistics."""
        out = {}
        for team in (self.root['home'], self.root['away']):
            team_id = int(assertget(team, 'teamId'))
            stats = {
                _snake(name): sum(value.values())
                for name, value in team['stats'].items()
                if isinstance(value, dict)
            }
            scores = assertget(team, 'scores')
            out[(self.game_id, team_id)] = dict(
                game_id=self.game_id,
                team_id=team_id,
                side=assertget(team, 'field'),
                score=assertget(scores, 'fulltime'),
                shootout_score=scores.get('penalty'),
                **{k: v for k, v in stats.items() if not k.endswith('Success')},
            )
        return out

    def extract_playergamestats(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return per-player aggregated game statistics incl. minutes."""
        out = {}
        for team in (self.root['home'], self.root['away']):
            team_id = int(assertget(team, 'teamId'))
            sent_off = {
                e['playerId']: e['expandedMinute']
                for e in team.get('incidentEvents', [])
                if 'cardType' in e
                and e['cardType']['displayName'] in ('Red', 'SecondYellow')
                and 'playerId' in e  # absent for coach cards
            }
            for player in team['players']:
                stats = {
                    _snake(name): sum(stat.values())
                    for name, stat in player['stats'].items()
                }
                player_id = int(assertget(player, 'playerId'))
                p = dict(
                    game_id=self.game_id,
                    team_id=team_id,
                    player_id=player_id,
                    is_starter=bool(player.get('isFirstEleven', False)),
                    position_code=player.get('position', None),
                    jersey_number=int(player.get('shirtNo', 0)),
                    mvp=bool(player.get('isManOfTheMatch', False)),
                    **{k: v for k, v in stats.items() if not k.endswith('success')},
                )
                if 'subbedInExpandedMinute' in player:
                    p['minute_start'] = player['subbedInExpandedMinute']
                if 'subbedOutExpandedMinute' in player:
                    p['minute_end'] = player['subbedOutExpandedMinute']
                if player_id in sent_off:
                    p['minute_end'] = sent_off[player_id]

                full_time = self.root.get('expandedMaxMinute')
                p['minutes_played'] = 0
                if p['is_starter'] and 'minute_end' not in p:
                    p['minute_start'] = 0
                    p['minute_end'] = full_time
                    p['minutes_played'] = full_time
                elif p['is_starter']:
                    p['minute_start'] = 0
                    p['minutes_played'] = p['minute_end']
                elif 'minute_start' in p and 'minute_end' not in p:
                    p['minute_end'] = full_time
                    p['minutes_played'] = full_time - p['minute_start']
                elif 'minute_start' in p:
                    p['minutes_played'] = p['minute_end'] - p['minute_start']
                out[(self.game_id, player_id)] = p
        return out
