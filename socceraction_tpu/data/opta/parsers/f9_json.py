"""Parser for Opta F9 (match results / lineups) JSON feeds.

Parity: reference ``socceraction/data/opta/parsers/f9_json.py:9-301``.
The F9 feed holds one game's result, teams, lineups and player stats.
"""

from __future__ import annotations

from datetime import datetime
from typing import Any, Dict, List, Optional, Tuple

from ...base import MissingDataError
from .base import OptaJSONParser, assertget


def _stats_of(obj: Dict[str, Any]) -> Dict[str, Any]:
    """Collect an element's ``Stat`` children into ``{type: value}``."""
    if 'Stat' not in obj:
        return {}
    stat_list = obj['Stat'] if isinstance(obj['Stat'], list) else [obj['Stat']]
    return {s['@attributes']['Type']: s['@value'] for s in stat_list}


def _name_of(obj: Dict[str, Any]) -> Optional[str]:
    """A person's display name: the Known name, else 'First Last'."""
    if 'Known' in obj and obj['Known'].strip():
        return obj['Known']
    if 'First' in obj and 'Last' in obj and obj['Last'].strip() or obj['First'].strip():
        return (obj['First'] + ' ' + obj['Last']).strip()
    return None


class F9JSONParser(OptaJSONParser):
    """Extract game, team, player and lineup data from an F9 JSON feed."""

    def _get_doc(self) -> Dict[str, Any]:
        for node in self.root:
            if 'OptaFeed' in node['data'].keys():
                data = assertget(node, 'data')
                feed = assertget(data, 'OptaFeed')
                return assertget(feed, 'OptaDocument')[0]
        raise MissingDataError

    def extract_games(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{game_id: info}``."""
        doc = self._get_doc()
        attr = assertget(doc, '@attributes')
        matchdata = assertget(doc, 'MatchData')
        competition = assertget(doc, 'Competition')
        competition_stats = _stats_of(competition)
        matchinfo = assertget(matchdata, 'MatchInfo')
        matchofficial = assertget(matchdata, 'MatchOfficial')
        matchstat = _stats_of(matchdata)
        venue = assertget(doc, 'Venue')

        game_id = int(assertget(attr, 'uID')[1:])
        record: Dict[str, Any] = dict(
            game_id=game_id,
            competition_id=int(
                assertget(assertget(competition, '@attributes'), 'uID')[1:]
            ),
            season_id=assertget(competition_stats, 'season_id'),
            game_day=competition_stats.get('matchday'),
            game_date=datetime.strptime(
                assertget(matchinfo, 'Date'), '%Y%m%dT%H%M%S%z'
            ).replace(tzinfo=None),
            duration=int(assertget(matchstat, 'match_time')),
            referee=_name_of(matchofficial['OfficialName'])
            if 'OfficialName' in matchofficial
            else None,
            venue=venue.get('Name'),
            attendance=int(matchinfo['Attendance']) if 'Attendance' in matchinfo else None,
        )
        for team in assertget(matchdata, 'TeamData'):
            team_attr = assertget(team, '@attributes')
            prefix = 'home' if assertget(team_attr, 'Side') == 'Home' else 'away'
            record[f'{prefix}_team_id'] = int(assertget(team_attr, 'TeamRef')[1:])
            record[f'{prefix}_score'] = int(assertget(team_attr, 'Score'))
            record[f'{prefix}_manager'] = (
                _name_of(team['TeamOfficial']['PersonName'])
                if 'TeamOfficial' in team
                else None
            )
        return {game_id: record}

    def extract_teams(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{team_id: info}``."""
        doc = self._get_doc()
        teams = {}
        for team in assertget(doc, 'Team'):
            if 'id' in team.keys():
                team_id = int(team['id'])
                teams[team_id] = dict(
                    team_id=team_id,
                    team_name=team.get('nameObj').get('name'),
                )
        return teams

    def extract_players(self) -> Dict[Tuple[int, int], Dict[str, Any]]:
        """Return ``{(game_id, player_id): info}``."""
        doc = self._get_doc()
        attr = assertget(doc, '@attributes')
        game_id = int(assertget(attr, 'uID')[1:])
        lineups = self.extract_lineups()
        players = {}
        for team in assertget(doc, 'Team'):
            team_id = int(team['@attributes']['uID'].replace('t', ''))
            for player in team['Player']:
                player_id = int(player['@attributes']['uID'].replace('p', ''))
                assert 'nameObj' in player['PersonName']
                if player['PersonName']['nameObj'].get('is_unknown'):
                    continue
                record = dict(
                    game_id=game_id,
                    team_id=team_id,
                    player_id=player_id,
                    player_name=_name_of(player['PersonName']),
                )
                in_lineup = lineups[team_id]['players'].get(player_id)
                if in_lineup:
                    record.update(
                        jersey_number=in_lineup['jersey_number'],
                        starting_position=in_lineup['starting_position_name'],
                        is_starter=in_lineup['is_starter'],
                        minutes_played=in_lineup['minutes_played'],
                    )
                players[(game_id, player_id)] = record
        return players

    def extract_lineups(self) -> Dict[int, Dict[str, Any]]:
        """Return ``{team_id: {'players': {player_id: info}}}``."""
        doc = self._get_doc()
        try:
            teamdata = doc['MatchData']['TeamData']
        except KeyError as e:
            raise MissingDataError from e
        match_time = _stats_of(doc['MatchData'])['match_time']

        lineups: Dict[int, Dict[str, Any]] = {}
        for team in teamdata:
            team_id = int(team['@attributes']['TeamRef'].replace('t', ''))
            lineups[team_id] = dict(players=dict())
            substitutions = [s['@attributes'] for s in team['Substitution']]
            sent_off = {
                int(b['@attributes']['PlayerRef'].replace('p', '')): b['@attributes']['Time']
                for b in team.get('Booking', [])
                if 'CardType' in b['@attributes']
                and b['@attributes']['CardType'] in ('Red', 'SecondYellow')
                and 'PlayerRef' in b['@attributes']  # absent for coach cards
            }
            for player in team['PlayerLineUp']['MatchPlayer']:
                p_attr = player['@attributes']
                player_id = int(p_attr['PlayerRef'].replace('p', ''))
                player_stats = {
                    s['@attributes']['Type']: s['@value'] for s in player['Stat']
                }
                sub_on = next(
                    (
                        s['Time']
                        for s in substitutions
                        if 'Retired' not in s and s['SubOn'] == f'p{player_id}'
                    ),
                    match_time if p_attr['Status'] == 'Sub' else 0,
                )
                sub_off = next(
                    (s['Time'] for s in substitutions if s['SubOff'] == f'p{player_id}'),
                    match_time if player_id not in sent_off else sent_off[player_id],
                )
                lineups[team_id]['players'][player_id] = dict(
                    jersey_number=p_attr['ShirtNumber'],
                    starting_position_name=p_attr['Position'],
                    starting_position_id=p_attr['position_id'],
                    is_starter=p_attr['Status'] == 'Start',
                    minutes_played=sub_off - sub_on,
                    **player_stats,
                )
        return lineups

    def extract_teamgamestats(self) -> List[Dict[str, Any]]:
        """Return per-team aggregated match statistics."""
        doc = self._get_doc()
        attr = assertget(doc, '@attributes')
        game_id = int(assertget(attr, 'uID')[1:])
        try:
            teamdata = doc['MatchData']['TeamData']
        except KeyError as e:
            raise MissingDataError from e
        out = []
        for team in teamdata:
            team_attr = team['@attributes']
            out.append(
                dict(
                    game_id=game_id,
                    team_id=int(team_attr['TeamRef'].replace('t', '')),
                    side=team_attr['Side'],
                    score=team_attr['Score'],
                    shootout_score=team_attr['ShootOutScore'],
                    **_stats_of(team),
                )
            )
        return out
