"""Feed parsers for Opta(-derived) data streams.

Parity: reference ``socceraction/data/opta/parsers/__init__.py``.
"""

from .base import OptaParser
from .f1_json import F1JSONParser
from .f7_xml import F7XMLParser
from .f9_json import F9JSONParser
from .f24_json import F24JSONParser
from .f24_xml import F24XMLParser
from .ma1_json import MA1JSONParser
from .ma3_json import MA3JSONParser
from .whoscored import WhoScoredParser

__all__ = [
    'OptaParser',
    'F1JSONParser',
    'F7XMLParser',
    'F9JSONParser',
    'F24JSONParser',
    'F24XMLParser',
    'MA1JSONParser',
    'MA3JSONParser',
    'WhoScoredParser',
]
