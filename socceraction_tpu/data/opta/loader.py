"""Opta event data loader.

Parity: reference ``socceraction/data/opta/loader.py:204-465``. Feeds are
discovered by glob patterns with ``{competition_id}/{season_id}/{game_id}``
placeholders; each matching file is handed to the feed's parser and the
per-id dictionaries of all feeds are deep-merged (Opta spreads one game's
data over complementary files).
"""

from __future__ import annotations

import copy
import datetime
import glob
import os
import re
import warnings
from typing import Any, Dict, Mapping, Optional, Type, Union

import pandas as pd

from ..base import EventDataLoader
from .parsers import (
    F1JSONParser,
    F7XMLParser,
    F9JSONParser,
    F24JSONParser,
    F24XMLParser,
    MA1JSONParser,
    MA3JSONParser,
    OptaParser,
    WhoScoredParser,
)
from .schema import (
    OptaCompetitionSchema,
    OptaEventSchema,
    OptaGameSchema,
    OptaPlayerSchema,
    OptaTeamSchema,
)

__all__ = ['OptaLoader']

_PARSER_SETS: Dict[str, Mapping[str, Type[OptaParser]]] = {
    'json': {
        'f1': F1JSONParser,
        'f9': F9JSONParser,
        'f24': F24JSONParser,
        'ma1': MA1JSONParser,
        'ma3': MA3JSONParser,
    },
    'xml': {'f7': F7XMLParser, 'f24': F24XMLParser},
    'statsperform': {'ma1': MA1JSONParser, 'ma3': MA3JSONParser},
    'whoscored': {'whoscored': WhoScoredParser},
}

_DEFAULT_FEEDS: Dict[str, Dict[str, str]] = {
    'json': {
        'f1': 'f7-{competition_id}-{season_id}-{game_id}.json',
        'f9': 'f7-{competition_id}-{season_id}-{game_id}.json',
        'f24': 'f24-{competition_id}-{season_id}-{game_id}.json',
    },
    'xml': {
        'f7': 'f7-{competition_id}-{season_id}-{game_id}.json',
        'f24': 'f24-{competition_id}-{season_id}-{game_id}.json',
    },
    'statsperform': {
        'ma1': 'ma1-{competition_id}-{season_id}.json',
        'ma3': 'ma3-{competition_id}-{season_id}-{game_id}.json',
    },
    'whoscored': {
        'whoscored': '{competition_id}-{season_id}-{game_id}.json',
    },
}

#: Opta event type id → name (reference ``data/opta/loader.py:56-144``).
_EVENT_TYPES = [
    (1, 'pass'), (2, 'offside pass'), (3, 'take on'), (4, 'foul'),
    (5, 'out'), (6, 'corner awarded'), (7, 'tackle'), (8, 'interception'),
    (9, 'turnover'), (10, 'save'), (11, 'claim'), (12, 'clearance'),
    (13, 'miss'), (14, 'post'), (15, 'attempt saved'), (16, 'goal'),
    (17, 'card'), (18, 'player off'), (19, 'player on'),
    (20, 'player retired'), (21, 'player returns'),
    (22, 'player becomes goalkeeper'), (23, 'goalkeeper becomes player'),
    (24, 'condition change'), (25, 'official change'), (26, 'unknown26'),
    (27, 'start delay'), (28, 'end delay'), (29, 'unknown29'), (30, 'end'),
    (31, 'unknown31'), (32, 'start'), (33, 'unknown33'), (34, 'team set up'),
    (35, 'player changed position'), (36, 'player changed jersey number'),
    (37, 'collection end'), (38, 'temp_goal'), (39, 'temp_attempt'),
    (40, 'formation change'), (41, 'punch'), (42, 'good skill'),
    (43, 'deleted event'), (44, 'aerial'), (45, 'challenge'),
    (46, 'unknown46'), (47, 'rescinded card'), (48, 'unknown46'),
    (49, 'ball recovery'), (50, 'dispossessed'), (51, 'error'),
    (52, 'keeper pick-up'), (53, 'cross not claimed'), (54, 'smother'),
    (55, 'offside provoked'), (56, 'shield ball opp'), (57, 'foul throw in'),
    (58, 'penalty faced'), (59, 'keeper sweeper'), (60, 'chance missed'),
    (61, 'ball touch'), (62, 'unknown62'), (63, 'temp_save'), (64, 'resume'),
    (65, 'contentious referee decision'), (66, 'possession data'),
    (67, '50/50'), (68, 'referee drop ball'), (69, 'failed to block'),
    (70, 'injury time announcement'), (71, 'coach setup'),
    (72, 'caught offside'), (73, 'other ball contact'), (74, 'blocked pass'),
    (75, 'delayed start'), (76, 'early end'), (77, 'player off pitch'),
    (78, 'temp card'), (79, 'coverage interruption'), (80, 'drop of ball'),
    (81, 'obstacle'), (83, 'attempted tackle'), (84, 'deleted after review'),
    (10000, 'offside given'),  # WhoScored-specific
]

eventtypes_df = pd.DataFrame(_EVENT_TYPES, columns=['type_id', 'type_name'])


def _deepupdate(target: Dict[Any, Any], src: Dict[Any, Any]) -> None:
    """Deep-merge ``src`` into ``target`` (lists extend, dicts recurse)."""
    for k, v in src.items():
        if isinstance(v, list):
            if k not in target:
                target[k] = copy.deepcopy(v)
            else:
                target[k].extend(v)
        elif isinstance(v, dict):
            if k not in target:
                target[k] = copy.deepcopy(v)
            else:
                _deepupdate(target[k], v)
        elif isinstance(v, set):
            if k not in target:
                target[k] = v.copy()
            else:
                target[k].update(v.copy())
        else:
            target[k] = copy.copy(v)


def _extract_ids_from_path(path: str, pattern: str) -> Dict[str, Union[str, int]]:
    """Recover the id placeholders of a feed pattern from a concrete path."""
    regex = re.compile(
        '.+?'
        + re.escape(pattern)
        .replace(r'\{competition_id\}', r'(?P<competition_id>[a-zA-Z0-9-_ ]+)')
        .replace(r'\{season_id\}', r'(?P<season_id>[a-zA-Z0-9-_ ]+)')
        .replace(r'\{game_id\}', r'(?P<game_id>[a-zA-Z0-9-_ ]+)')
    )
    m = re.match(regex, path)
    if m is None:
        raise ValueError(f'The filepath {path} does not match the format {pattern}.')
    return {k: int(v) if v.isdigit() else v for k, v in m.groupdict().items()}


class OptaLoader(EventDataLoader):
    """Load Opta data from a local folder.

    Parameters
    ----------
    root : str
        Root path of the data.
    parser : str or dict
        'xml' (F7+F24), 'json' (F1+F9+F24), 'statsperform' (MA1+MA3),
        'whoscored', or a mapping of feed name to a custom
        :class:`~socceraction_tpu.data.opta.parsers.OptaParser` subclass.
    feeds : dict, optional
        Glob pattern per feed, with ``{competition_id}``, ``{season_id}``
        and ``{game_id}`` placeholders.

    Raises
    ------
    ValueError
        If an invalid parser is provided.
    """

    def __init__(
        self,
        root: str,
        parser: Union[str, Mapping[str, Type[OptaParser]]] = 'xml',
        feeds: Optional[Dict[str, str]] = None,
    ) -> None:
        self.root = root
        if isinstance(parser, str):
            if parser not in _PARSER_SETS:
                raise ValueError('Invalid parser provided.')
            if feeds is None:
                feeds = dict(_DEFAULT_FEEDS[parser])
            self.parsers = self._select_parsers(_PARSER_SETS[parser], feeds)
        elif isinstance(parser, dict):
            if feeds is None:
                raise ValueError('You must specify a feed for each parser.')
            self.parsers = self._select_parsers(parser, feeds)
        else:
            raise ValueError('Invalid parser provided.')
        self.feeds = feeds

    @staticmethod
    def _select_parsers(
        available: Mapping[str, Type[OptaParser]], feeds: Dict[str, str]
    ) -> Mapping[str, Type[OptaParser]]:
        parsers = {}
        for feed in feeds:
            if feed in available:
                parsers[feed] = available[feed]
            else:
                warnings.warn(
                    f'No parser available for {feed} feeds. This feed is ignored.'
                )
        return parsers

    def _collect(
        self,
        extractor: str,
        competition_id: Any = '*',
        season_id: Any = '*',
        game_id: Any = '*',
    ) -> Dict[Any, Dict[str, Any]]:
        """Run one ``extract_*`` method over every matching feed file."""
        data: Dict[Any, Dict[str, Any]] = {}
        for feed, feed_pattern in self.feeds.items():
            glob_pattern = feed_pattern.format(
                competition_id=competition_id, season_id=season_id, game_id=game_id
            )
            for path in glob.glob(os.path.join(self.root, glob_pattern)):
                ids = _extract_ids_from_path(path, feed_pattern)
                parser = self.parsers[feed](path, **ids)
                _deepupdate(data, getattr(parser, extractor)())
        return data

    def competitions(self) -> pd.DataFrame:
        """Return all available competitions and seasons."""
        data = self._collect('extract_competitions')
        return OptaCompetitionSchema.validate(pd.DataFrame(list(data.values())))

    def games(self, competition_id: int, season_id: int) -> pd.DataFrame:
        """Return all available games of one competition-season."""
        data = self._collect(
            'extract_games', competition_id=competition_id, season_id=season_id
        )
        return OptaGameSchema.validate(pd.DataFrame(list(data.values())))

    def teams(self, game_id: int) -> pd.DataFrame:
        """Return both teams of one game."""
        data = self._collect('extract_teams', game_id=game_id)
        return OptaTeamSchema.validate(pd.DataFrame(list(data.values())))

    def players(self, game_id: int) -> pd.DataFrame:
        """Return all players of one game."""
        data = self._collect('extract_players', game_id=game_id)
        df = pd.DataFrame(list(data.values()))
        df['game_id'] = game_id
        return OptaPlayerSchema.validate(df)

    def events(self, game_id: int) -> pd.DataFrame:
        """Return the event stream of one game, cleaned and ordered."""
        data = self._collect('extract_events', game_id=game_id)
        events = (
            pd.DataFrame(list(data.values()))
            .merge(eventtypes_df, on='type_id', how='left')
            .sort_values(['game_id', 'period_id', 'minute', 'second', 'timestamp'])
            .reset_index(drop=True)
        )
        # pre-match events can carry negative seconds
        events.loc[events['second'] < 0, 'second'] = 0
        events = events.sort_values(
            ['game_id', 'period_id', 'minute', 'second', 'timestamp']
        )
        # drop deleted events (type 43) and rows with corrupt datetimes
        # (negated form keeps NaT timestamps, matching the reference filter)
        events = events[events['type_id'] != 43]
        events = events[
            ~(
                (events['timestamp'] < datetime.datetime(1900, 1, 1))
                | (events['timestamp'] > datetime.datetime(2100, 1, 1))
            )
        ]
        return OptaEventSchema.validate(events)
