"""Provider data access: loaders for event-stream data sources.

Layer L1 of the framework (SURVEY §1): everything here is host-side,
dict-shaped and ragged — the columnar device runtime starts at the SPADL
boundary (:mod:`socceraction_tpu.spadl`, :mod:`socceraction_tpu.core`).
"""

from .base import EventDataLoader, MissingDataError, ParseError

__all__ = ['EventDataLoader', 'MissingDataError', 'ParseError']
