"""StatsBomb event data loader.

Parity: reference ``socceraction/data/statsbomb/loader.py:39-503``.
Supports the open-data local directory layout (``competitions.json``,
``matches/<comp>/<season>.json``, ``lineups/<game>.json``,
``events/<game>.json``, ``three-sixty/<game>.json``) and remote access via
the optional ``statsbombpy`` package.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, List, Optional

import pandas as pd

try:
    from statsbombpy import api_client, sb

    def _quiet_has_auth(creds: Dict[str, str]) -> bool:
        """Suppress statsbombpy's repeated no-auth print messages."""
        if creds.get('user') in [None, ''] or creds.get('passwd') in [None, '']:
            warnings.warn('credentials were not supplied. open data access only')
            return False
        return True

    api_client.has_auth = _quiet_has_auth
except ImportError:  # pragma: no cover
    sb = None

from ..base import EventDataLoader, ParseError, _expand_minute, _localloadjson
from .schema import (
    StatsBombCompetitionSchema,
    StatsBombEventSchema,
    StatsBombGameSchema,
    StatsBombPlayerSchema,
    StatsBombTeamSchema,
)

__all__ = ['StatsBombLoader', 'extract_player_games']


class StatsBombLoader(EventDataLoader):
    """Load StatsBomb data from the open-data directory layout or the API.

    Parameters
    ----------
    getter : str
        'remote' (requires ``statsbombpy``) or 'local'.
    root : str, optional
        Root path of the data (required for 'local').
    creds : dict, optional
        ``{'user': ..., 'passwd': ...}`` API credentials ('remote' only).
    """

    def __init__(
        self,
        getter: str = 'remote',
        root: Optional[str] = None,
        creds: Optional[Dict[str, str]] = None,
    ) -> None:
        if getter == 'remote':
            if sb is None:
                raise ImportError(
                    "The 'statsbombpy' package is required for remote access."
                )
            self._creds = creds or sb.DEFAULT_CREDS
            self._local = False
        elif getter == 'local':
            if root is None:
                raise ValueError(
                    "The 'root' parameter is required when loading local data."
                )
            self._local = True
            self._root = root
        else:
            raise ValueError('Invalid getter specified')

    def competitions(self) -> pd.DataFrame:
        """Return all available competitions and seasons."""
        cols = [
            'season_id',
            'competition_id',
            'competition_name',
            'country_name',
            'competition_gender',
            'season_name',
        ]
        if self._local:
            obj = _localloadjson(os.path.join(self._root, 'competitions.json'))
        else:
            obj = list(sb.competitions(fmt='dict', creds=self._creds).values())
        if not isinstance(obj, list):
            raise ParseError('The retrieved data should contain a list of competitions')
        if len(obj) == 0:
            return pd.DataFrame(columns=cols)
        return StatsBombCompetitionSchema.validate(pd.DataFrame(obj)[cols])

    def games(self, competition_id: int, season_id: int) -> pd.DataFrame:
        """Return all available games of a season."""
        cols = [
            'game_id',
            'season_id',
            'competition_id',
            'competition_stage',
            'game_day',
            'game_date',
            'home_team_id',
            'away_team_id',
            'home_score',
            'away_score',
            'venue',
            'referee',
        ]
        if self._local:
            obj = _localloadjson(
                os.path.join(self._root, f'matches/{competition_id}/{season_id}.json')
            )
        else:
            obj = list(
                sb.matches(competition_id, season_id, fmt='dict', creds=self._creds).values()
            )
        if not isinstance(obj, list):
            raise ParseError('The retrieved data should contain a list of games')
        if len(obj) == 0:
            return pd.DataFrame(columns=cols)
        games = pd.DataFrame(_flatten(m) for m in obj)
        games['kick_off'] = games['kick_off'].fillna('12:00:00.000')
        games['match_date'] = pd.to_datetime(
            games[['match_date', 'kick_off']].agg(' '.join, axis=1)
        )
        games = games.rename(
            columns={
                'match_id': 'game_id',
                'match_date': 'game_date',
                'match_week': 'game_day',
                'stadium_name': 'venue',
                'referee_name': 'referee',
                'competition_stage_name': 'competition_stage',
            }
        )
        for optional in ('venue', 'referee'):
            if optional not in games:
                games[optional] = None
        return StatsBombGameSchema.validate(games[cols])

    def _lineups(self, game_id: int) -> List[Dict[str, Any]]:
        if self._local:
            obj = _localloadjson(os.path.join(self._root, f'lineups/{game_id}.json'))
        else:
            obj = list(sb.lineups(game_id, fmt='dict', creds=self._creds).values())
        if not isinstance(obj, list):
            raise ParseError('The retrieved data should contain a list of teams')
        if len(obj) != 2:
            raise ParseError('The retrieved data should contain two teams')
        return obj

    def teams(self, game_id: int) -> pd.DataFrame:
        """Return both teams of a game."""
        obj = self._lineups(game_id)
        return StatsBombTeamSchema.validate(
            pd.DataFrame(obj)[['team_id', 'team_name']]
        )

    def players(self, game_id: int) -> pd.DataFrame:
        """Return all players that appeared in a game, with minutes played."""
        cols = [
            'game_id',
            'team_id',
            'player_id',
            'player_name',
            'nickname',
            'jersey_number',
            'is_starter',
            'starting_position_id',
            'starting_position_name',
            'minutes_played',
        ]
        obj = self._lineups(game_id)
        players = pd.DataFrame(
            _flatten_id(p) for lineup in obj for p in lineup['lineup']
        )
        player_games = extract_player_games(self.events(game_id))
        players = pd.merge(
            players,
            player_games[
                ['player_id', 'team_id', 'position_id', 'position_name', 'minutes_played']
            ],
            on='player_id',
        )
        players['game_id'] = game_id
        players['position_name'] = players['position_name'].replace(0, 'Substitute')
        players['position_id'] = players['position_id'].fillna(0).astype(int)
        players['is_starter'] = players['position_id'] != 0
        players = players.rename(
            columns={
                'player_nickname': 'nickname',
                'country_name': 'country',
                'position_id': 'starting_position_id',
                'position_name': 'starting_position_name',
            }
        )
        return StatsBombPlayerSchema.validate(players[cols])

    def events(self, game_id: int, load_360: bool = False) -> pd.DataFrame:
        """Return the event stream of a game.

        Parameters
        ----------
        game_id : int
            The ID of the game.
        load_360 : bool
            Whether to merge StatsBomb 360 freeze frames into the events.
        """
        cols = [
            'game_id',
            'event_id',
            'period_id',
            'team_id',
            'player_id',
            'type_id',
            'type_name',
            'index',
            'timestamp',
            'minute',
            'second',
            'possession',
            'possession_team_id',
            'possession_team_name',
            'play_pattern_id',
            'play_pattern_name',
            'team_name',
            'duration',
            'extra',
            'related_events',
            'player_name',
            'position_id',
            'position_name',
            'location',
            'under_pressure',
            'counterpress',
        ]
        if self._local:
            obj = _localloadjson(os.path.join(self._root, f'events/{game_id}.json'))
        else:
            obj = list(sb.events(game_id, fmt='dict', creds=self._creds).values())
        if not isinstance(obj, list):
            raise ParseError('The retrieved data should contain a list of events')
        if len(obj) == 0:
            return pd.DataFrame(columns=cols)

        events = pd.DataFrame(_flatten_id(e) for e in obj)
        events['match_id'] = game_id
        events['timestamp'] = pd.to_datetime(events['timestamp'], format='%H:%M:%S.%f')
        # not every game/event carries the optional fields
        for optional in (
            'related_events',
            'player_id',
            'player_name',
            'position_id',
            'position_name',
            'location',
            'duration',
        ):
            if optional not in events:
                events[optional] = None
        events['related_events'] = events['related_events'].apply(
            lambda d: d if isinstance(d, list) else []
        )
        for flag in ('under_pressure', 'counterpress'):
            if flag not in events:
                events[flag] = False
            events[flag] = events[flag].fillna(False).astype(bool)
        events = events.rename(
            columns={'id': 'event_id', 'period': 'period_id', 'match_id': 'game_id'}
        )
        if not load_360:
            return StatsBombEventSchema.validate(events[cols])

        cols_360 = ['visible_area_360', 'freeze_frame_360']
        if self._local:
            obj = _localloadjson(os.path.join(self._root, f'three-sixty/{game_id}.json'))
        else:
            obj = sb.frames(game_id, fmt='dict', creds=self._creds)
        if not isinstance(obj, list):
            raise ParseError('The retrieved data should contain a list of frames')
        if len(obj) == 0:
            events['visible_area_360'] = None
            events['freeze_frame_360'] = None
            return StatsBombEventSchema.validate(events[cols + cols_360])
        frames = pd.DataFrame(obj).rename(
            columns={
                'event_uuid': 'event_id',
                'visible_area': 'visible_area_360',
                'freeze_frame': 'freeze_frame_360',
            }
        )[['event_id', 'visible_area_360', 'freeze_frame_360']]
        merged = pd.merge(events, frames, on='event_id', how='left')
        return StatsBombEventSchema.validate(merged[cols + cols_360])


def extract_player_games(events: pd.DataFrame) -> pd.DataFrame:
    """Compute per-player minutes played from a game's events.

    Handles substitutions and red cards (incl. second yellows), expanding
    minutes with the injury time of earlier periods; shoot-outs contribute
    no minutes. Parity: reference ``statsbomb/loader.py:379-473``.
    """
    periods_regular = pd.DataFrame(
        [
            {'period_id': 1, 'minute': 45},
            {'period_id': 2, 'minute': 45},
            {'period_id': 3, 'minute': 15},
            {'period_id': 4, 'minute': 15},
        ]
    ).set_index('period_id')
    periods_minutes = (
        events.loc[events['type_name'] == 'Half End', ['period_id', 'minute']]
        .drop_duplicates()
        .set_index('period_id')
        .sort_index()
        .subtract(periods_regular.cumsum().shift(1).fillna(0))
        .minute.dropna()
        .astype(int)
        .tolist()
    )
    game_minutes = sum(periods_minutes)

    game_id = events['game_id'].mode().values[0]
    players: Dict[Any, Dict[str, Any]] = {}

    red_cards = events[
        events.apply(
            lambda x: any(
                e in x.extra
                and 'card' in x.extra[e]
                and x.extra[e]['card']['name'] in ['Second Yellow', 'Red Card']
                for e in ['foul_committed', 'bad_behaviour']
            ),
            axis=1,
        )
    ]

    def _minutes_until_red(player_id: Any, default: int) -> int:
        card = red_cards[red_cards['player_id'] == player_id]
        if len(card) > 0:
            return _expand_minute(int(card.iloc[0]['minute']), periods_minutes)
        return default

    for startxi in events[events['type_name'] == 'Starting XI'].itertuples():
        team_id, team_name = startxi.team_id, startxi.team_name
        for player in startxi.extra['tactics']['lineup']:
            player = _flatten_id(player)
            player.update(
                game_id=game_id,
                team_id=team_id,
                team_name=team_name,
                minutes_played=_minutes_until_red(player['player_id'], game_minutes),
            )
            players[player['player_id']] = player

    for sub in events[events['type_name'] == 'Substitution'].itertuples():
        exp_sub_minute = _expand_minute(int(sub.minute), periods_minutes)
        replacement_id = sub.extra['substitution']['replacement']['id']
        players[replacement_id] = {
            'player_id': replacement_id,
            'player_name': sub.extra['substitution']['replacement']['name'],
            'minutes_played': _minutes_until_red(replacement_id, game_minutes)
            - exp_sub_minute,
            'team_id': sub.team_id,
            'game_id': game_id,
            'team_name': sub.team_name,
        }
        players[sub.player_id]['minutes_played'] = exp_sub_minute

    pg = pd.DataFrame(players.values()).fillna(0)
    for col in pg.columns:
        if '_id' in col:
            pg[col] = pg[col].astype(int)
    return pg


def _flatten_id(d: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten ``{'id', 'name'}`` sub-dicts to ``*_id``/``*_name`` columns.

    Remaining dict-valued entries are collected into an ``extra`` dict
    column (reference ``statsbomb/loader.py:475-488``).
    """
    newd: Dict[str, Any] = {}
    extra: Dict[str, Any] = {}
    for k, v in d.items():
        if isinstance(v, dict):
            if 'id' in v and 'name' in v:
                newd[k + '_id'] = v['id']
                newd[k + '_name'] = v['name']
            else:
                extra[k] = v
        else:
            newd[k] = v
    newd['extra'] = extra
    return newd


def _flatten(d: Dict[str, Any]) -> Dict[str, Any]:
    """Recursively flatten nested dicts (match metadata records)."""
    newd: Dict[str, Any] = {}
    for k, v in d.items():
        if isinstance(v, dict):
            if 'id' in v and 'name' in v:
                newd[k + '_id'] = v['id']
                newd[k + '_name'] = v['name']
                newd[k + '_extra'] = {
                    l: w for (l, w) in v.items() if l not in ('id', 'name')
                }
            else:
                newd = {**newd, **_flatten(v)}
        else:
            newd[k] = v
    return newd
