"""Schemas for StatsBomb loader output.

Parity: reference ``socceraction/data/statsbomb/schema.py:16-99`` — the
base schemas extended with StatsBomb-specific columns.
"""

from __future__ import annotations

from ...schema import Field, Schema

StatsBombCompetitionSchema = Schema(
    fields={
        'season_id': Field(),
        'competition_id': Field(),
        'competition_name': Field(dtype='str'),
        'country_name': Field(dtype='str'),
        'competition_gender': Field(dtype='str'),
        'season_name': Field(dtype='str'),
    },
    strict=False,
)

StatsBombGameSchema = Schema(
    fields={
        'game_id': Field(),
        'season_id': Field(),
        'competition_id': Field(),
        'competition_stage': Field(dtype='str'),
        'game_day': Field(nullable=True),
        'game_date': Field(dtype='datetime64[ns]'),
        'home_team_id': Field(),
        'away_team_id': Field(),
        'home_score': Field(dtype='int64'),
        'away_score': Field(dtype='int64'),
        'venue': Field(nullable=True),
        'referee': Field(nullable=True),
    },
    strict=False,
)

StatsBombTeamSchema = Schema(
    fields={
        'team_id': Field(),
        'team_name': Field(dtype='str'),
    },
    strict=False,
)

StatsBombPlayerSchema = Schema(
    fields={
        'game_id': Field(),
        'team_id': Field(),
        'player_id': Field(),
        'player_name': Field(dtype='str'),
        'nickname': Field(nullable=True),
        'jersey_number': Field(dtype='int64'),
        'is_starter': Field(dtype='bool'),
        'starting_position_id': Field(dtype='int64'),
        'starting_position_name': Field(dtype='str'),
        'minutes_played': Field(dtype='int64'),
    },
    strict=False,
)

StatsBombEventSchema = Schema(
    fields={
        'game_id': Field(),
        'event_id': Field(),
        'period_id': Field(dtype='int64'),
        'team_id': Field(),
        'player_id': Field(nullable=True),
        'type_id': Field(dtype='int64'),
        'type_name': Field(dtype='str'),
        'index': Field(dtype='int64'),
        'timestamp': Field(dtype='datetime64[ns]'),
        'minute': Field(dtype='int64'),
        'second': Field(dtype='int64'),
        'possession': Field(dtype='int64'),
        'possession_team_id': Field(),
        'possession_team_name': Field(dtype='str'),
        'play_pattern_id': Field(dtype='int64'),
        'play_pattern_name': Field(dtype='str'),
        'team_name': Field(dtype='str'),
        'duration': Field(dtype='float64'),
        'extra': Field(),
        'related_events': Field(),
        'player_name': Field(nullable=True),
        'position_id': Field(nullable=True),
        'position_name': Field(nullable=True),
        'location': Field(nullable=True),
        'under_pressure': Field(dtype='bool'),
        'counterpress': Field(dtype='bool'),
        'visible_area_360': Field(nullable=True, required=False),
        'freeze_frame_360': Field(nullable=True, required=False),
    },
    strict=False,
)
