"""StatsBomb data loader."""

from .loader import StatsBombLoader, extract_player_games
from .schema import (
    StatsBombCompetitionSchema,
    StatsBombEventSchema,
    StatsBombGameSchema,
    StatsBombPlayerSchema,
    StatsBombTeamSchema,
)

__all__ = [
    'StatsBombLoader',
    'extract_player_games',
    'StatsBombCompetitionSchema',
    'StatsBombGameSchema',
    'StatsBombTeamSchema',
    'StatsBombPlayerSchema',
    'StatsBombEventSchema',
]
