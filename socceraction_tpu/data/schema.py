"""Base schemas that every provider's loader output must satisfy.

Parity: reference ``socceraction/data/schema.py:13-109`` (pandera models),
expressed with the dependency-free schema core. Provider-specific loaders
extend these with extra columns (``strict=False`` permits them).
"""

from __future__ import annotations

from ..schema import Field, Schema

CompetitionSchema = Schema(
    fields={
        'season_id': Field(),
        'season_name': Field(dtype='str'),
        'competition_id': Field(),
        'competition_name': Field(dtype='str'),
    },
    strict=False,
)

GameSchema = Schema(
    fields={
        'game_id': Field(),
        'season_id': Field(),
        'competition_id': Field(),
        'game_day': Field(nullable=True),
        'game_date': Field(dtype='datetime64[ns]'),
        'home_team_id': Field(),
        'away_team_id': Field(),
    },
    strict=False,
)

TeamSchema = Schema(
    fields={
        'team_id': Field(),
        'team_name': Field(dtype='str'),
    },
    strict=False,
)

PlayerSchema = Schema(
    fields={
        'game_id': Field(),
        'team_id': Field(),
        'player_id': Field(),
        'player_name': Field(dtype='str'),
        'is_starter': Field(dtype='bool'),
        'minutes_played': Field(dtype='int64'),
        'jersey_number': Field(dtype='int64'),
    },
    strict=False,
)

EventSchema = Schema(
    fields={
        'game_id': Field(),
        'event_id': Field(),
        'period_id': Field(dtype='int64'),
        'team_id': Field(nullable=True),
        'player_id': Field(nullable=True),
        'type_id': Field(dtype='int64'),
        'type_name': Field(dtype='str'),
    },
    strict=False,
)
