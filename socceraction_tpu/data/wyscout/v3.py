"""Wyscout API-v3 raw event flattening.

The v3 converter (:mod:`socceraction_tpu.spadl.wyscout_v3`) consumes a
flat-column frame; the v3 API delivers nested camelCase JSON
(``type.primary``, ``pass.endLocation.x``, ``groundDuel.duelType``, ...).
This module bridges them:

- nested objects flatten with ``_``-joined snake_case paths
  (``pass.endLocation.x`` → ``pass_end_location_x``,
  ``shot.isGoal`` → ``shot_is_goal``),
- the ``type.secondary`` label list becomes one flag column per label
  (``type_cross``, ``type_save``, ``type_head_pass``, ...), matching the
  column names the converter reads,
- ``matchPeriod`` strings stay for the converter's period mapping.

The reference fork has no v3 *loader* at all (its ``wyscout_v3.py``
converter sketch assumes the flat frame already exists); this completes
the ingest path.
"""

from __future__ import annotations

from typing import Any, Dict, List

import pandas as pd

from ..base import _localloadjson, _snake

__all__ = ['flatten_v3_events', 'load_v3_events']


def _flatten(obj: Dict[str, Any], prefix: str, out: Dict[str, Any]) -> None:
    for key, value in obj.items():
        col = prefix + _snake(key)
        if isinstance(value, dict):
            _flatten(value, col + '_', out)
        elif col == 'type_secondary' and isinstance(value, list):
            for label in value:
                out[f'type_{label}'] = 1
        else:
            out[col] = value


def flatten_v3_events(events: List[Dict[str, Any]]) -> pd.DataFrame:
    """Flatten raw v3 event dicts into the converter's column layout.

    Parameters
    ----------
    events : list of dict
        Raw Wyscout v3 event objects (the ``events`` array of a match
        feed).

    Returns
    -------
    pd.DataFrame
        One row per event, flat snake_case columns, secondary-type flag
        columns filled with 0 where absent.
    """
    rows: List[Dict[str, Any]] = []
    for event in events:
        row: Dict[str, Any] = {}
        _flatten(event, '', row)
        rows.append(row)
    df = pd.DataFrame(rows)
    # secondary-type flags are sparse per event: absent means 0
    for col in df.columns:
        if col.startswith('type_') and col != 'type_primary':
            df[col] = df[col].fillna(0)
    return df


def load_v3_events(path: str) -> pd.DataFrame:
    """Load one v3 match feed (JSON with an ``events`` array) and flatten it."""
    obj = _localloadjson(path)
    events = obj['events'] if isinstance(obj, dict) and 'events' in obj else obj
    return flatten_v3_events(events)
