"""Schemas for Wyscout loader output.

Parity: reference ``socceraction/data/wyscout/schema.py:14-47`` — the base
schemas extended with Wyscout-specific columns.
"""

from __future__ import annotations

from ...schema import Field, Schema

WyscoutCompetitionSchema = Schema(
    fields={
        'season_id': Field(),
        'competition_id': Field(),
        'competition_name': Field(dtype='str'),
        'country_name': Field(dtype='str'),
        'competition_gender': Field(dtype='str'),
        'season_name': Field(dtype='str'),
    },
    strict=False,
)

WyscoutGameSchema = Schema(
    fields={
        'game_id': Field(),
        'season_id': Field(),
        'competition_id': Field(),
        'game_day': Field(nullable=True),
        'game_date': Field(dtype='datetime64[ns]'),
        'home_team_id': Field(),
        'away_team_id': Field(),
    },
    strict=False,
)

WyscoutTeamSchema = Schema(
    fields={
        'team_id': Field(),
        'team_name': Field(dtype='str'),
        'team_name_short': Field(dtype='str'),
    },
    strict=False,
)

WyscoutPlayerSchema = Schema(
    fields={
        'game_id': Field(),
        'team_id': Field(),
        'player_id': Field(),
        'player_name': Field(dtype='str'),
        'firstname': Field(dtype='str'),
        'lastname': Field(dtype='str'),
        'nickname': Field(nullable=True),
        'birth_date': Field(nullable=True),
        'is_starter': Field(dtype='bool'),
        'minutes_played': Field(dtype='int64'),
        'jersey_number': Field(dtype='int64'),
    },
    strict=False,
)

WyscoutEventSchema = Schema(
    fields={
        'game_id': Field(),
        'event_id': Field(),
        'period_id': Field(dtype='int64'),
        'team_id': Field(nullable=True),
        'player_id': Field(nullable=True),
        'type_id': Field(dtype='int64'),
        'type_name': Field(dtype='str'),
        'subtype_id': Field(dtype='int64'),
        'subtype_name': Field(dtype='str'),
        'milliseconds': Field(dtype='float64'),
        'positions': Field(dtype='object'),
        'tags': Field(dtype='object'),
    },
    strict=False,
)
