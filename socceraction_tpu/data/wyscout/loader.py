"""Wyscout event data loaders.

Parity: reference ``socceraction/data/wyscout/loader.py:32-804``. Two
loaders share one set of frame converters:

- :class:`PublicWyscoutLoader` — the public figshare release of the
  2017/18 top-5-league + WC2018 + Euro2016 dataset (per-competition
  ``matches_*.json`` / ``events_*.json`` files plus global
  ``competitions.json`` / ``teams.json`` / ``players.json``).
- :class:`WyscoutLoader` — the Wyscout API v2 layout, remote or as local
  feed files discovered by glob patterns.

Everything here is host-side IO; the columnar pipeline starts once events
reach :func:`socceraction_tpu.spadl.wyscout.convert_to_actions`.
"""

from __future__ import annotations

import glob
import os
import re
import warnings
from pathlib import Path
from typing import Any, Dict, List, Optional
from urllib.parse import urlparse
from urllib.request import urlopen, urlretrieve
from zipfile import ZipFile, is_zipfile

import pandas as pd

from ..base import (
    EventDataLoader,
    MissingDataError,
    ParseError,
    _expand_minute,
    _localloadjson,
    _remoteloadjson,
)
from .schema import (
    WyscoutCompetitionSchema,
    WyscoutEventSchema,
    WyscoutGameSchema,
    WyscoutPlayerSchema,
    WyscoutTeamSchema,
)

__all__ = ['PublicWyscoutLoader', 'WyscoutLoader']

#: Wyscout match-period code -> SPADL period id.
wyscout_periods: Dict[str, int] = {'1H': 1, '2H': 2, 'E1': 3, 'E2': 4, 'P': 5}

# The seven competitions in the public dataset release, keyed by
# (competition_id, season_id); reference ``data/wyscout/loader.py:69-122``.
_PUBLIC_DATASET_INDEX = [
    (524, 181248, '2017/2018', 'Italy'),
    (364, 181150, '2017/2018', 'England'),
    (795, 181144, '2017/2018', 'Spain'),
    (412, 181189, '2017/2018', 'France'),
    (426, 181137, '2017/2018', 'Germany'),
    (102, 9291, '2016', 'European_Championship'),
    (28, 10078, '2018', 'World_Cup'),
]

# figshare download ids for the public dataset; reference ``:124-131``.
_PUBLIC_DATASET_URLS = {
    'competitions': 'https://ndownloader.figshare.com/files/15073685',
    'teams': 'https://ndownloader.figshare.com/files/15073697',
    'players': 'https://ndownloader.figshare.com/files/15073721',
    'matches': 'https://ndownloader.figshare.com/files/14464622',
    'events': 'https://ndownloader.figshare.com/files/14464685',
}


def _country_of(area: Dict[str, Any]) -> str:
    name = area.get('name', '')
    return name if name != '' else 'International'


def _competitions_frame(competitions: List[Dict[str, Any]]) -> pd.DataFrame:
    df = pd.DataFrame(competitions)
    return pd.DataFrame(
        {
            'competition_id': df['wyId'],
            'competition_name': df['name'],
            'country_name': df['area'].apply(_country_of),
            'competition_gender': df.get('gender', pd.Series(['male'] * len(df))),
        }
    )


def _seasons_frame(seasons: List[Dict[str, Any]]) -> pd.DataFrame:
    df = pd.DataFrame(seasons)
    return pd.DataFrame(
        {
            'season_id': df['wyId'],
            'season_name': df['name'],
            'competition_id': df['competitionId'],
        }
    )


def _side_team_id(teams_data: Dict[Any, Any], side: str) -> int:
    for team_id, data in teams_data.items():
        if data['side'] == side:
            return int(team_id)
    raise ValueError(f'no team with side {side!r}')


def _games_frame(matches: List[Dict[str, Any]]) -> pd.DataFrame:
    df = pd.DataFrame(matches)
    return pd.DataFrame(
        {
            'game_id': df['wyId'],
            'competition_id': df['competitionId'],
            'season_id': df['seasonId'],
            'game_date': pd.to_datetime(df['dateutc']),
            'game_day': df['gameweek'],
            'home_team_id': df['teamsData'].apply(_side_team_id, side='home'),
            'away_team_id': df['teamsData'].apply(_side_team_id, side='away'),
        }
    )


def _teams_frame(teams: List[Dict[str, Any]]) -> pd.DataFrame:
    df = pd.DataFrame(teams)
    return pd.DataFrame(
        {
            'team_id': df['wyId'],
            'team_name_short': df['name'],
            'team_name': df['officialName'],
        }
    )


def _players_frame(players: pd.DataFrame) -> pd.DataFrame:
    out = pd.DataFrame(
        {
            'player_id': players['wyId'],
            'nickname': players['shortName'],
            'firstname': players['firstName'],
            'lastname': players['lastName'],
            'birth_date': pd.to_datetime(players['birthDate']),
        }
    )
    out['player_name'] = out['firstname'].str.cat(out['lastname'], sep=' ')
    return out


_CAMEL_BOUNDARY = re.compile(r'(?<!^)(?=[A-Z])')


def _events_frame(raw_events: List[Dict[str, Any]]) -> pd.DataFrame:
    """Normalize raw API-v2 event dicts into the WyscoutEventSchema frame.

    In the raw feed ``eventId``/``subEventId`` are the *type* codes and
    ``id`` is the row identifier; reference ``data/wyscout/loader.py:690-734``.
    """
    df = pd.DataFrame(raw_events)
    df.columns = [_CAMEL_BOUNDARY.sub('_', c).lower() for c in df.columns]
    type_ids = pd.to_numeric(df.get('event_id'), errors='coerce').fillna(0).astype(int)
    subtype_ids = pd.to_numeric(df.get('sub_event_id'), errors='coerce').fillna(0).astype(int)
    return pd.DataFrame(
        {
            'event_id': df['id'],
            'game_id': df['match_id'],
            'period_id': df['match_period'].map(wyscout_periods),
            'milliseconds': df['event_sec'] * 1000,
            'team_id': df['team_id'],
            'player_id': df['player_id'],
            'type_id': type_ids,
            'type_name': df['event_name'],
            'subtype_id': subtype_ids,
            'subtype_name': df['sub_event_name'].fillna(''),
            'positions': df['positions'],
            'tags': df['tags'],
        }
    )


def _minutes_played(
    teams_data: Any, events: List[Dict[str, Any]]
) -> pd.DataFrame:
    """Compute per-player minutes played from lineups + the event clock.

    Period durations are estimated as the rounded maximum event timestamp in
    each period; substitutions and red cards truncate a player's span, with
    regular-clock minutes expanded by earlier periods' injury time
    (reference ``data/wyscout/loader.py:737-801``).
    """
    latest: Dict[int, float] = {}
    for e in events:
        pid = wyscout_periods[e['matchPeriod']]
        latest[pid] = max(latest.get(pid, 0.0), e['eventSec'])
    # Penalty shootouts (period id 5) do not count towards minutes played.
    durations = [
        round(latest[pid] / 60)
        for pid in sorted(latest)
        if pid < 5 and latest[pid] != 0
    ]
    match_minutes = sum(durations)

    if isinstance(teams_data, dict):
        teams_data = list(teams_data.values())

    rows: Dict[int, Dict[str, Any]] = {}
    for team in teams_data:
        formation = team.get('formation', {})
        team_id = team['teamId']
        # A red card caps the player's span at its (expanded) minute.
        sent_off = {
            p['playerId']: _expand_minute(int(p['redCards']), durations)
            for group in ('bench', 'lineup')
            for p in formation.get(group, [])
            if p['redCards'] != '0'
        }
        for p in formation.get('lineup', []):
            rows[p['playerId']] = {
                'team_id': team_id,
                'player_id': p['playerId'],
                'jersey_number': p.get('shirtNumber', 0),
                'minutes_played': sent_off.get(p['playerId'], match_minutes),
                'is_starter': True,
            }
        substitutions = formation.get('substitutions', [])
        if substitutions != 'null':
            bench = formation.get('bench', [])
            for sub in substitutions:
                sub_minute = _expand_minute(sub['minute'], durations)
                played = match_minutes - sub_minute
                if sub['playerIn'] in sent_off:
                    played = sent_off[sub['playerIn']] - sub_minute
                rows[sub['playerIn']] = {
                    'team_id': team_id,
                    'player_id': sub['playerIn'],
                    'jersey_number': next(
                        (
                            p.get('shirtNumber', 0)
                            for p in bench
                            if p['playerId'] == sub['playerIn']
                        ),
                        0,
                    ),
                    'minutes_played': played,
                    'is_starter': False,
                }
                if sub['playerOut'] in rows:
                    rows[sub['playerOut']]['minutes_played'] = sub_minute
    return pd.DataFrame(rows.values())


class PublicWyscoutLoader(EventDataLoader):
    """Load the public figshare release of the Wyscout dataset.

    Contains all matches of the 2017/18 season of the top-5 European
    leagues, the FIFA World Cup 2018 and the UEFA Euro 2016 (Pappalardo
    et al., Sci Data 6, 236 (2019)).

    Parameters
    ----------
    root : str, optional
        Directory holding (or receiving) a local copy of the dataset.
        Defaults to ``./wyscout_data``.
    download : bool
        Force a (re)download of the dataset archives.
    """

    def __init__(self, root: Optional[str] = None, download: bool = False) -> None:
        if root is None:
            self.root = os.path.join(os.getcwd(), 'wyscout_data')
            os.makedirs(self.root, exist_ok=True)
        else:
            self.root = root
        self.get = _localloadjson
        if download or len(os.listdir(self.root)) == 0:
            self._download_repo()

        index = pd.DataFrame(
            [
                {
                    'competition_id': cid,
                    'season_id': sid,
                    'season_name': season,
                    'db_matches': f'matches_{name}.json',
                    'db_events': f'events_{name}.json',
                }
                for cid, sid, season, name in _PUBLIC_DATASET_INDEX
            ]
        )
        self._index = index.set_index(['competition_id', 'season_id'])
        self._match_index = self._build_match_index().set_index('match_id')

    def _download_repo(self) -> None:
        for url in _PUBLIC_DATASET_URLS.values():
            resolved = urlopen(url).geturl()
            target = os.path.join(self.root, Path(urlparse(resolved).path).name)
            local_file, _ = urlretrieve(resolved, target)
            if is_zipfile(local_file):
                with ZipFile(local_file) as zf:
                    zf.extractall(self.root)

    def _build_match_index(self) -> pd.DataFrame:
        frames = [
            pd.DataFrame(self.get(path))
            for path in glob.iglob(os.path.join(self.root, 'matches_*.json'))
        ]
        matches = pd.concat(frames) if frames else pd.DataFrame(
            columns=['wyId', 'competitionId', 'seasonId']
        )
        matches = matches.rename(
            columns={
                'wyId': 'match_id',
                'competitionId': 'competition_id',
                'seasonId': 'season_id',
            }
        )
        return pd.merge(
            matches[['match_id', 'competition_id', 'season_id']],
            self._index,
            on=['competition_id', 'season_id'],
            how='left',
        )

    def _db_path(self, game_id: int, kind: str) -> str:
        comp_id, season_id = self._match_index.loc[
            game_id, ['competition_id', 'season_id']
        ]
        return os.path.join(self.root, self._index.at[(comp_id, season_id), kind])

    def competitions(self) -> pd.DataFrame:
        """Return all seven available competition-seasons."""
        raw = self.get(os.path.join(self.root, 'competitions.json'))
        df = _competitions_frame(raw)
        df['competition_gender'] = 'male'
        df = pd.merge(
            df,
            self._index.reset_index()[['competition_id', 'season_id', 'season_name']],
            on='competition_id',
            how='left',
        )
        cols = [
            'competition_id',
            'season_id',
            'country_name',
            'competition_name',
            'competition_gender',
            'season_name',
        ]
        return WyscoutCompetitionSchema.validate(df[cols])

    def games(self, competition_id: int, season_id: int) -> pd.DataFrame:
        """Return all games of one competition-season."""
        path = os.path.join(
            self.root, self._index.at[(competition_id, season_id), 'db_matches']
        )
        return WyscoutGameSchema.validate(_games_frame(self.get(path)))

    def _lineups(self, game_id: int) -> List[Dict[str, Any]]:
        matches = pd.DataFrame(
            self.get(self._db_path(game_id, 'db_matches'))
        ).set_index('wyId')
        return list(matches.at[game_id, 'teamsData'].values())

    def teams(self, game_id: int) -> pd.DataFrame:
        """Return both teams of one game."""
        teams = pd.DataFrame(
            self.get(os.path.join(self.root, 'teams.json'))
        ).set_index('wyId')
        ids = pd.DataFrame(self._lineups(game_id))['teamId']
        selected = teams.loc[ids].reset_index()
        return WyscoutTeamSchema.validate(_teams_frame(selected.to_dict('records')))

    def players(self, game_id: int) -> pd.DataFrame:
        """Return all players that appeared in one game, with minutes played."""
        all_players = pd.DataFrame(
            self.get(os.path.join(self.root, 'players.json'))
        ).set_index('wyId')
        lineups = self._lineups(game_id)
        per_team = []
        for team in lineups:
            squad = team['formation']['lineup']
            if team['formation']['substitutions'] != 'null':
                for sub in team['formation']['substitutions']:
                    try:
                        squad.append(
                            next(
                                p
                                for p in team['formation']['bench']
                                if p['playerId'] == sub['playerIn']
                            )
                        )
                    except StopIteration:
                        warnings.warn(
                            f'Substitute with ID={sub["playerIn"]} (minute '
                            f'{sub["minute"]}, game {game_id}) not found on the bench.'
                        )
            df = pd.DataFrame(squad)
            df['side'] = team['side']
            df['team_id'] = team['teamId']
            per_team.append(df)
        squad_df = (
            pd.concat(per_team)
            .rename(columns={'playerId': 'wyId'})
            .set_index('wyId')
            .join(all_players, how='left')
            .reset_index()
        )
        for c in ('shortName', 'lastName', 'firstName'):
            squad_df[c] = squad_df[c].apply(lambda s: s.encode().decode('unicode-escape'))
        out = _players_frame(squad_df)

        # team_id / jersey / starter flags / minutes all come from the
        # lineup-derived minutes table (reference ``loader.py:294-305``).
        events = self.get(self._db_path(game_id, 'db_events'))
        game_events = [e for e in events if e['matchId'] == game_id]
        out = pd.merge(
            out, _minutes_played(lineups, game_events), on='player_id', how='left'
        )
        out['minutes_played'] = out['minutes_played'].fillna(0).astype(int)
        out['is_starter'] = out['is_starter'].fillna(False).astype(bool)
        out['jersey_number'] = out['jersey_number'].fillna(0).astype(int)
        out['game_id'] = game_id
        return WyscoutPlayerSchema.validate(out)

    def events(self, game_id: int) -> pd.DataFrame:
        """Return the raw event stream of one game."""
        events = self.get(self._db_path(game_id, 'db_events'))
        game_events = [e for e in events if e['matchId'] == game_id]
        return WyscoutEventSchema.validate(_events_frame(game_events))


class WyscoutLoader(EventDataLoader):
    """Load Wyscout API-v2 data from the API or from local feed files.

    Parameters
    ----------
    root : str
        Root path (or API base URL) of the data.
    getter : str
        'remote' or 'local'.
    feeds : dict, optional
        Glob/format pattern per feed. Defaults depend on the getter; see
        reference ``data/wyscout/loader.py:339-356``.
    """

    _wyscout_api: str = 'https://apirest.wyscout.com/v2/'

    def __init__(
        self,
        root: str = _wyscout_api,
        getter: str = 'remote',
        feeds: Optional[Dict[str, str]] = None,
    ) -> None:
        self.root = root
        if getter == 'remote':
            self.get = _remoteloadjson
        elif getter == 'local':
            self.get = _localloadjson
        else:
            raise ValueError('Invalid getter specified')
        if feeds is not None:
            self.feeds = feeds
        elif getter == 'remote':
            self.feeds = {
                'competitions': 'competitions',
                'seasons': 'competitions/{season_id}/seasons',
                'games': 'seasons/{season_id}/matches',
                'events': 'matches/{game_id}/events',
            }
        else:
            self.feeds = {
                'competitions': 'competitions.json',
                'seasons': 'seasons_{competition_id}.json',
                'games': 'matches_{season_id}.json',
                'events': 'matches/events_{game_id}.json',
            }

    def _resolve_feed(
        self,
        feed: str,
        competition_id: Optional[int] = None,
        season_id: Optional[int] = None,
        game_id: Optional[int] = None,
    ) -> List[str]:
        pattern = self.feeds[feed].format(
            competition_id='*' if competition_id is None else competition_id,
            season_id='*' if season_id is None else season_id,
            game_id='*' if game_id is None else game_id,
        )
        if '*' in pattern:
            matches = glob.glob(os.path.join(self.root, pattern))
            if not matches:
                raise MissingDataError
            return matches
        return [pattern]

    def competitions(self) -> pd.DataFrame:
        """Return all available competitions and seasons."""
        if 'competitions' in self.feeds:
            path = os.path.join(self.root, self._resolve_feed('competitions')[0])
            obj = self.get(path)
            if not isinstance(obj, dict) or 'competitions' not in obj:
                raise ParseError(f'{path} should contain a list of competitions')
            season_feeds = [
                self._resolve_feed('seasons', competition_id=c['wyId'])[0]
                for c in obj['competitions']
            ]
        else:
            season_feeds = self._resolve_feed('seasons')
        competitions: List[Dict[str, Any]] = []
        seasons: List[Dict[str, Any]] = []
        for feed in season_feeds:
            path = os.path.join(self.root, feed)
            try:
                obj = self.get(path)
            except FileNotFoundError:
                warnings.warn(f'File not found: {feed}')
                continue
            if not isinstance(obj, dict) or 'competition' not in obj or 'seasons' not in obj:
                raise ParseError(
                    f'{path} should contain a competition and a list of seasons'
                )
            competitions.append(obj['competition'])
            seasons.extend(s['season'] for s in obj['seasons'])
        merged = pd.merge(
            _competitions_frame(competitions),
            _seasons_frame(seasons),
            on='competition_id',
        )
        return WyscoutCompetitionSchema.validate(merged)

    def games(self, competition_id: int, season_id: int) -> pd.DataFrame:
        """Return all available games of one competition-season."""
        if 'games' in self.feeds:
            path = os.path.join(
                self.root,
                self._resolve_feed(
                    'games', competition_id=competition_id, season_id=season_id
                )[0],
            )
            obj = self.get(path)
            if not isinstance(obj, dict) or 'matches' not in obj:
                raise ParseError(f'{path} should contain a list of matches')
            detail_feeds = [
                self._resolve_feed(
                    'events',
                    competition_id=competition_id,
                    season_id=season_id,
                    game_id=g['matchId'],
                )[0]
                for g in obj['matches']
            ]
        else:
            detail_feeds = self._resolve_feed(
                'events', competition_id=competition_id, season_id=season_id
            )
        matches = []
        for feed in detail_feeds:
            path = os.path.join(self.root, feed)
            try:
                obj = self.get(path)
            except FileNotFoundError:
                warnings.warn(f'File not found: {feed}')
                continue
            if not isinstance(obj, dict) or 'match' not in obj:
                raise ParseError(f'{path} should contain a match')
            matches.append(obj['match'])
        return WyscoutGameSchema.validate(_games_frame(matches))

    def _game_feed(self, game_id: int, key: str) -> Dict[str, Any]:
        path = os.path.join(self.root, self._resolve_feed('events', game_id=game_id)[0])
        obj = self.get(path)
        if not isinstance(obj, dict) or key not in obj:
            raise ParseError(f'{path} should contain {key}')
        return obj

    def teams(self, game_id: int) -> pd.DataFrame:
        """Return both teams of one game."""
        obj = self._game_feed(game_id, 'teams')
        teams = [t['team'] for t in obj['teams'].values() if t.get('team')]
        return WyscoutTeamSchema.validate(_teams_frame(teams))

    def players(self, game_id: int) -> pd.DataFrame:
        """Return all players of one game, with minutes played."""
        obj = self._game_feed(game_id, 'players')
        players = [
            entry['player']
            for team in obj['players'].values()
            for entry in team
            if entry.get('player')
        ]
        df = _players_frame(pd.DataFrame(players).drop_duplicates('wyId'))
        df = pd.merge(
            df,
            _minutes_played(obj['match']['teamsData'], obj['events']),
            on='player_id',
            how='right',
        )
        df['minutes_played'] = df['minutes_played'].fillna(0).astype(int)
        df['game_id'] = game_id
        return WyscoutPlayerSchema.validate(df)

    def events(self, game_id: int) -> pd.DataFrame:
        """Return the raw event stream of one game."""
        obj = self._game_feed(game_id, 'events')
        return WyscoutEventSchema.validate(_events_frame(obj['events']))
