"""Wyscout event data provider.

Parity: reference ``socceraction/data/wyscout/__init__.py``.
"""

from .loader import PublicWyscoutLoader, WyscoutLoader, wyscout_periods
from .v3 import flatten_v3_events, load_v3_events
from .schema import (
    WyscoutCompetitionSchema,
    WyscoutEventSchema,
    WyscoutGameSchema,
    WyscoutPlayerSchema,
    WyscoutTeamSchema,
)

__all__ = [
    'PublicWyscoutLoader',
    'WyscoutLoader',
    'wyscout_periods',
    'flatten_v3_events',
    'load_v3_events',
    'WyscoutCompetitionSchema',
    'WyscoutGameSchema',
    'WyscoutPlayerSchema',
    'WyscoutTeamSchema',
    'WyscoutEventSchema',
]
