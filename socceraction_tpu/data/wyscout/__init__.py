"""Wyscout event data provider.

Parity: reference ``socceraction/data/wyscout/__init__.py``.
"""

from .loader import PublicWyscoutLoader, WyscoutLoader, wyscout_periods
from .schema import (
    WyscoutCompetitionSchema,
    WyscoutEventSchema,
    WyscoutGameSchema,
    WyscoutPlayerSchema,
    WyscoutTeamSchema,
)

__all__ = [
    'PublicWyscoutLoader',
    'WyscoutLoader',
    'wyscout_periods',
    'WyscoutCompetitionSchema',
    'WyscoutGameSchema',
    'WyscoutPlayerSchema',
    'WyscoutTeamSchema',
    'WyscoutEventSchema',
]
