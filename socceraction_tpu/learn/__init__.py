"""Continuous learning: stream → incremental train → shadow-eval → hot-swap.

The control loop that keeps a production rating service from going stale
or regressing silently (ROADMAP item 4) — the first subsystem that
exercises every prior layer at once:

- :mod:`socceraction_tpu.learn.ingest` — :class:`SeasonWatcher` (which
  matches are new) and :func:`extend_packed` (O(new matches) incremental
  packed-cache extension over the existing build machinery).
- :mod:`socceraction_tpu.learn.calibration` — device calibration
  metrics: reliability curves, ECE, the Brier decomposition and
  bootstrap CIs via one ``vmap``'d resample-ensemble dispatch (per
  arXiv 2409.04889).
- :mod:`socceraction_tpu.learn.shadow` — bitwise-reproducible replay of
  captured traffic (:class:`~socceraction_tpu.serve.capture.TrafficCapture`)
  through candidate vs active model.
- :mod:`socceraction_tpu.learn.drift` — the drift watch: device-side
  PSI/KS of the capture ring's feature and prediction distributions vs
  the active model's training reference (one vmap'd dispatch), the
  learner's optional early retrain trigger and an extra fail-closed
  gate input (``GateConfig.max_drift_psi``).
- :mod:`socceraction_tpu.learn.gate` — :class:`GateConfig` calibration
  bands and the typed :class:`PromotionReport` every decision becomes.
- :mod:`socceraction_tpu.learn.loop` — :class:`ContinuousLearner`, the
  orchestrator: warm-started :meth:`VAEP.fit_packed` continuation,
  staged registry candidates, gated atomic hot-swap, explicit rollback.

Quickstart::

    from socceraction_tpu.learn import ContinuousLearner, LearnConfig

    learner = ContinuousLearner(store, registry, service=service,
                                config=LearnConfig(max_actions=512))
    report = learner.run_once()       # ingest -> train -> shadow -> gate
    if not report.promoted:
        print(report.reasons)         # and obsctl promotions <runlog>
    # bad promotion in production? one warm, atomic step back:
    learner.rollback()

See ``docs/continuous_learning.md`` for the architecture, gate
configuration and the operational runbook.
"""

from .calibration import CalibrationSummary, calibration_summary, reliability_curve
from .drift import (
    DriftConfig,
    DriftReference,
    DriftResult,
    DriftWatch,
    build_drift_reference,
    drift_statistics,
)
from .gate import GateConfig, PromotionReport, evaluate_gate, record_report
from .ingest import SeasonWatcher, extend_packed, newest_game_ids
from .loop import ContinuousLearner, LearnConfig
from .shadow import ShadowResult, shadow_replay

__all__ = [
    'CalibrationSummary',
    'ContinuousLearner',
    'DriftConfig',
    'DriftReference',
    'DriftResult',
    'DriftWatch',
    'GateConfig',
    'LearnConfig',
    'PromotionReport',
    'SeasonWatcher',
    'ShadowResult',
    'build_drift_reference',
    'calibration_summary',
    'drift_statistics',
    'evaluate_gate',
    'extend_packed',
    'newest_game_ids',
    'record_report',
    'reliability_curve',
    'shadow_replay',
]
