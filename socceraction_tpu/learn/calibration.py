"""Device calibration metrics: reliability curves, ECE, Brier, bootstrap CIs.

The promotion criterion of the continuous-learning loop follows
PAPERS.md's *Moving from Machine Learning to Statistics: Expected Points*
(arXiv 2409.04889): a probability model earns deployment by being
*calibrated* — its predicted probabilities match observed frequencies —
not by a marginally lower loss, and every point estimate carries a
bootstrap uncertainty interval so a gate never acts on noise.

Everything here runs on device as a handful of XLA dispatches over the
replayed traffic:

- :func:`reliability_curve` — equal-width probability bins with weighted
  per-bin confidence (mean predicted probability) and accuracy (observed
  positive rate); the raw curve behind every other metric.
- :func:`calibration_summary` — one jitted kernel computing the expected
  calibration error (ECE), the Brier score and its Murphy decomposition
  (reliability − resolution + uncertainty, binned form), plus bootstrap
  confidence intervals for ECE and Brier via **one** ``vmap``'d
  resample-ensemble dispatch: ``n_boot`` row-resamples evaluated as a
  single batched computation, the way 2409.04889 computes uncertainty
  bands over expected-points curves.

Weights make padding free: packed batches carry ``(G, A)`` masks, and a
zero-weight row contributes to no bin, no score and no resample. All
reductions are deterministic for a fixed input on CPU — the shadow
evaluation's bitwise-replay contract extends through these metrics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    'CalibrationSummary',
    'calibration_summary',
    'reliability_curve',
]

_EPS = 1e-12


def _flatten(
    probs: Any, labels: Any, weights: Any
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    p = jnp.asarray(probs, jnp.float32).reshape(-1)
    y = jnp.asarray(labels, jnp.float32).reshape(-1)
    if weights is None:
        w = jnp.ones_like(p)
    else:
        w = jnp.asarray(weights, jnp.float32).reshape(-1)
    if p.shape != y.shape or p.shape != w.shape:
        raise ValueError(
            f'probs/labels/weights disagree on shape: {p.shape} vs '
            f'{y.shape} vs {w.shape}'
        )
    return p, y, w


def _binned_sums(
    p: jax.Array, y: jax.Array, w: jax.Array, n_bins: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Weighted per-bin (mass, Σw·p, Σw·y) over equal-width bins."""
    bins = jnp.clip((p * n_bins).astype(jnp.int32), 0, n_bins - 1)
    seg = partial(jax.ops.segment_sum, segment_ids=bins, num_segments=n_bins)
    return seg(w), seg(w * p), seg(w * y)


def _point_metrics(
    p: jax.Array, y: jax.Array, w: jax.Array, n_bins: int
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """(n, ece, brier, reliability, resolution, uncertainty) — one trace."""
    wsum, psum, ysum = _binned_sums(p, y, w, n_bins)
    n = jnp.maximum(jnp.sum(w), _EPS)
    conf = psum / jnp.maximum(wsum, _EPS)
    acc = ysum / jnp.maximum(wsum, _EPS)
    ece = jnp.sum(wsum / n * jnp.abs(conf - acc))
    brier = jnp.sum(w * jnp.square(p - y)) / n
    base = jnp.sum(w * y) / n
    reliability = jnp.sum(wsum * jnp.square(conf - acc)) / n
    resolution = jnp.sum(wsum * jnp.square(acc - base)) / n
    uncertainty = base * (1.0 - base)
    return n, ece, brier, reliability, resolution, uncertainty


@partial(jax.jit, static_argnames=('n_bins',))
def _curve_kernel(
    p: jax.Array, y: jax.Array, w: jax.Array, n_bins: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    wsum, psum, ysum = _binned_sums(p, y, w, n_bins)
    conf = psum / jnp.maximum(wsum, _EPS)
    acc = ysum / jnp.maximum(wsum, _EPS)
    return conf, acc, wsum


@partial(jax.jit, static_argnames=('n_bins', 'n_boot'))
def _summary_kernel(
    p: jax.Array,
    y: jax.Array,
    w: jax.Array,
    seed: int,
    n_bins: int,
    n_boot: int,
    ci: float,
) -> Tuple[jax.Array, ...]:
    n, ece, brier, rel, res, unc = _point_metrics(p, y, w, n_bins)

    def one_resample(key):
        idx = jax.random.randint(key, (p.shape[0],), 0, p.shape[0])
        _, e, b, _, _, _ = _point_metrics(p[idx], y[idx], w[idx], n_bins)
        return e, b

    # ONE dispatch for the whole resample ensemble: n_boot row-resamples
    # of (probs, labels, weights) evaluated as a batched computation
    keys = jax.random.split(jax.random.PRNGKey(seed), n_boot)
    eces, briers = jax.vmap(one_resample)(keys)
    lo = (1.0 - ci) / 2.0
    q = jnp.asarray([lo, 1.0 - lo], jnp.float32)
    ece_ci = jnp.quantile(eces, q)
    brier_ci = jnp.quantile(briers, q)
    return n, ece, brier, rel, res, unc, ece_ci, brier_ci


def reliability_curve(
    probs: Any,
    labels: Any,
    weights: Any = None,
    *,
    n_bins: int = 10,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Weighted reliability curve over ``n_bins`` equal-width bins.

    Returns ``(confidence, accuracy, bin_weight)`` host arrays of length
    ``n_bins``: per-bin mean predicted probability, observed positive
    rate and total sample weight. Empty bins report zero confidence and
    accuracy with zero weight (callers mask on ``bin_weight > 0``).
    """
    p, y, w = _flatten(probs, labels, weights)
    conf, acc, wsum = _curve_kernel(p, y, w, int(n_bins))
    return np.asarray(conf), np.asarray(acc), np.asarray(wsum)


@dataclass(frozen=True)
class CalibrationSummary:
    """Point calibration metrics plus bootstrap uncertainty for one head.

    ``ece`` is the expected calibration error (bin-weighted |confidence −
    accuracy|); ``brier`` the weighted Brier score with its binned Murphy
    decomposition (``brier ≈ reliability − resolution + uncertainty``, up
    to within-bin variance); ``ece_ci``/``brier_ci`` are bootstrap
    ``ci_level`` intervals from the resample ensemble.
    """

    n: float
    ece: float
    brier: float
    brier_reliability: float
    brier_resolution: float
    brier_uncertainty: float
    ece_ci: Tuple[float, float]
    brier_ci: Tuple[float, float]
    n_bins: int = 10
    n_boot: int = 200
    ci_level: float = 0.95
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        """A flat, JSON-ready rendering (promotion reports embed this)."""
        return {
            'n': self.n,
            'ece': self.ece,
            'brier': self.brier,
            'brier_reliability': self.brier_reliability,
            'brier_resolution': self.brier_resolution,
            'brier_uncertainty': self.brier_uncertainty,
            'ece_ci': list(self.ece_ci),
            'brier_ci': list(self.brier_ci),
            'n_bins': self.n_bins,
            'n_boot': self.n_boot,
            'ci_level': self.ci_level,
            **self.extra,
        }


def calibration_summary(
    probs: Any,
    labels: Any,
    weights: Any = None,
    *,
    n_bins: int = 10,
    n_boot: int = 200,
    seed: int = 0,
    ci_level: float = 0.95,
) -> CalibrationSummary:
    """Full calibration summary of one probability head on device.

    Parameters
    ----------
    probs, labels, weights
        Any matching leading shape (``(G, A)`` packed tensors or flat
        rows); ``weights`` (e.g. the packed batch mask) zero out padding.
    n_bins : int
        Equal-width reliability bins (2409.04889 uses 10).
    n_boot : int
        Bootstrap resamples, evaluated in one ``vmap`` dispatch.
    seed : int
        PRNG seed of the resample ensemble — fixed seed, fixed input ⇒
        fixed intervals (the shadow replay's reproducibility contract).
    ci_level : float
        Central interval mass (default 0.95).
    """
    if n_bins < 2:
        raise ValueError(f'need at least 2 bins, got {n_bins}')
    if n_boot < 1:
        raise ValueError(f'need at least 1 bootstrap resample, got {n_boot}')
    p, y, w = _flatten(probs, labels, weights)
    out = _summary_kernel(
        p, y, w, int(seed), int(n_bins), int(n_boot), float(ci_level)
    )
    n, ece, brier, rel, res, unc, ece_ci, brier_ci = jax.device_get(out)
    return CalibrationSummary(
        n=float(n),
        ece=float(ece),
        brier=float(brier),
        brier_reliability=float(rel),
        brier_resolution=float(res),
        brier_uncertainty=float(unc),
        ece_ci=(float(ece_ci[0]), float(ece_ci[1])),
        brier_ci=(float(brier_ci[0]), float(brier_ci[1])),
        n_bins=int(n_bins),
        n_boot=int(n_boot),
        ci_level=float(ci_level),
    )
