"""The promotion gate: calibration bands, typed reports, recording.

A candidate model is promoted only when its calibration on the shadow
replay does not regress beyond configured bands — per 2409.04889, the
deployment criterion is statistical (reliability, uncertainty), not a
marginally better loss. The gate compares candidate vs active per
probability head (scores/concedes) and produces a typed
:class:`PromotionReport` that is recorded *everywhere an operator might
look*: the active :class:`~socceraction_tpu.obs.trace.RunLog` (a
``promotion_report`` event — what ``obsctl promotions`` tails), the
always-on flight recorder ring (post-mortem bundles), and the ``learn``
metric area (``learn/promotions{verdict}``, per-head
``learn/ece``/``learn/brier`` gauges).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..obs import RECORDER, counter, gauge
from ..obs.trace import current_runlog
from .calibration import CalibrationSummary

__all__ = ['GateConfig', 'PromotionReport', 'evaluate_gate', 'record_report']


@dataclass(frozen=True)
class GateConfig:
    """Calibration bands and replay parameters of the promotion gate.

    A candidate is **blocked** when, on any head, its expected
    calibration error exceeds the active model's by more than
    ``max_ece_regression`` or its Brier score by more than
    ``max_brier_regression``. Bands are absolute deltas on [0, 1]
    metrics; negative deltas (improvements) always pass. Bootstrap CIs
    ride along in the report as evidence — the verdict itself stays a
    deterministic function of the point estimates and bands, so the same
    replay always gates the same way.

    ``min_replay_actions`` refuses to promote on a traffic window too
    small to measure calibration at all (the gate fails *closed*: no
    evidence, no promotion).

    ``max_drift_psi``, when set, adds the drift watch as a second
    fail-closed input: a candidate is blocked when the serving traffic
    has drifted past the band from the active model's training reference
    (the calibration comparison is then answering the wrong question —
    both models are being scored on a distribution neither trained on),
    **and** when the drift statistics are unavailable (window too small,
    no watch configured): no evidence, no promotion, same direction as
    ``min_replay_actions``.

    ``max_parity_err``, when set, adds the serving layer's shadow-parity
    probe (:class:`socceraction_tpu.obs.parity.ParityProbe`) as a third
    fail-closed input: a candidate is blocked when the probe's worst
    observed fused-vs-reference error exceeds the band — a numerically
    broken serving path makes every calibration number measured through
    it untrustworthy — when the serving service's in-dispatch guards
    detected non-finite values (``serve_nonfinite_events`` in the
    stats: the captured traffic window itself is suspect), and, in the
    same fail-closed direction, when no parity statistics exist at all
    (no probe attached, nothing sampled yet): no evidence, no
    promotion.
    """

    max_ece_regression: float = 0.01
    max_brier_regression: float = 0.005
    min_replay_actions: int = 64
    max_drift_psi: Optional[float] = None
    max_parity_err: Optional[float] = None
    n_bins: int = 10
    n_boot: int = 200
    seed: int = 0
    ci_level: float = 0.95


@dataclass
class PromotionReport:
    """One loop iteration's full decision record (JSON-ready via
    :meth:`to_dict`). ``verdict`` is one of ``'promoted'``,
    ``'rejected'``, ``'no_new_data'``, ``'publish_failed'`` (the gate
    passed but the registry publish / service swap raised), or
    ``'error'`` (the shadow/gate stages themselves raised). The two
    failure verdicts are recorded *before* the error surfaces to the
    caller — every iteration that consumed data leaves a decision
    trail."""

    name: str
    verdict: str
    reasons: List[str] = field(default_factory=list)
    active_version: Optional[str] = None
    candidate_tag: Optional[str] = None
    #: set only when the candidate was actually published
    candidate_version: Optional[str] = None
    new_games: List[Any] = field(default_factory=list)
    #: per-head metric comparison:
    #: ``{head: {'candidate': {...}, 'active': {...}, 'delta_ece': .,
    #: 'delta_brier': .}}`` (summaries are CalibrationSummary.to_dict())
    heads: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    replay: Dict[str, Any] = field(default_factory=dict)
    #: the drift watch's statistics for this iteration's traffic window
    #: (``DriftResult.to_dict()``; empty when no watch is configured)
    drift: Dict[str, Any] = field(default_factory=dict)
    #: the serving parity probe's lifetime stats at gate time
    #: (``ParityProbe.stats()``; empty when no probe is attached)
    parity: Dict[str, Any] = field(default_factory=dict)
    #: the candidate's per-head architecture (``{head: 'mlp'|'seq'|...}``)
    #: so operators can tell which model KIND a verdict judged — an mlp
    #: and a seq candidate pass the same gates but are different programs
    archs: Dict[str, str] = field(default_factory=dict)
    stage_seconds: Dict[str, float] = field(default_factory=dict)
    time_unix: float = field(default_factory=time.time)

    @property
    def promoted(self) -> bool:
        """True iff this iteration published (and activated) the candidate."""
        return self.verdict == 'promoted'

    def to_dict(self) -> Dict[str, Any]:
        """JSON-ready rendering — the run-log/recorder event payload."""
        return {
            'name': self.name,
            'verdict': self.verdict,
            'reasons': list(self.reasons),
            'active_version': self.active_version,
            'candidate_tag': self.candidate_tag,
            'candidate_version': self.candidate_version,
            'new_games': [
                g.item() if hasattr(g, 'item') else g for g in self.new_games
            ],
            'heads': self.heads,
            'replay': dict(self.replay),
            'drift': dict(self.drift),
            'parity': dict(self.parity),
            'archs': dict(self.archs),
            'stage_seconds': {
                k: round(v, 6) for k, v in self.stage_seconds.items()
            },
            'time_unix': self.time_unix,
        }


def compare_heads(
    active: Dict[str, CalibrationSummary],
    candidate: Dict[str, CalibrationSummary],
) -> Dict[str, Dict[str, Any]]:
    """The report's per-head block: both summaries plus the deltas."""
    heads: Dict[str, Dict[str, Any]] = {}
    for col, cand in candidate.items():
        entry: Dict[str, Any] = {'candidate': cand.to_dict()}
        act = active.get(col) if active else None
        if act is not None:
            entry['active'] = act.to_dict()
            entry['delta_ece'] = cand.ece - act.ece
            entry['delta_brier'] = cand.brier - act.brier
        heads[col] = entry
    return heads


def evaluate_gate(
    active: Optional[Dict[str, CalibrationSummary]],
    candidate: Dict[str, CalibrationSummary],
    config: GateConfig,
    *,
    drift: Any = None,
    parity: Optional[Dict[str, Any]] = None,
) -> Tuple[bool, List[str]]:
    """Apply the calibration bands; returns ``(passed, reasons)``.

    ``active=None`` is the bootstrap case (no serving baseline yet): the
    candidate passes by default, with the reason recorded. Otherwise
    every head must stay within both bands; all violations are listed,
    not just the first.

    ``drift`` is the iteration's
    :class:`~socceraction_tpu.learn.drift.DriftResult` (or None). With
    ``config.max_drift_psi`` set the drift check is fail-closed: absent
    or unevaluated statistics block exactly like a breach — the gate
    must not certify calibration measured on a distribution it cannot
    vouch for. Drift reasons apply even in the bootstrap case.

    ``parity`` is the serving parity probe's
    :meth:`~socceraction_tpu.obs.parity.ParityProbe.stats` dict (or
    None). With ``config.max_parity_err`` set the check is fail-closed
    in the same way: no probe statistics, or a worst observed error past
    the band, both block — calibration measured through a numerically
    diverged serving path proves nothing. Parity reasons apply even in
    the bootstrap case.
    """
    reasons: List[str] = []
    if config.max_drift_psi is not None:
        if drift is None or not getattr(drift, 'evaluated', False):
            reasons.append(
                'drift: statistics unavailable for this replay window '
                '(fail closed; configure a drift watch or widen the '
                'capture window)'
            )
        elif drift.max_psi > config.max_drift_psi:
            reasons.append(
                f'drift: {drift.max_psi_feature} PSI {drift.max_psi:.4f} '
                f'> band {config.max_drift_psi:.4f} — the replay window '
                'no longer resembles the training reference'
            )
    if config.max_parity_err is not None:
        if parity and parity.get('serve_nonfinite_events'):
            reasons.append(
                'numerics: the serving service detected '
                f'{parity["serve_nonfinite_events"]} non-finite dispatch '
                'value(s) — traffic served (and captured) through a '
                'non-finite path is not promotion evidence (fail closed)'
            )
        if not parity or not parity.get('evaluated'):
            reasons.append(
                'parity: no shadow-parity probes observed (fail closed; '
                'attach a ParityProbe to the serving service so the '
                'fused path is measured against the reference)'
            )
        elif parity['max_abs_err'] > config.max_parity_err:
            reasons.append(
                'parity: fused-vs-reference max abs error '
                f'{parity["max_abs_err"]:.3e} > band '
                f'{config.max_parity_err:.3e} over {parity["probes"]} '
                'probe(s) — the serving path numerically diverged from '
                'the reference implementation'
            )
    if active is None:
        if reasons:
            return False, reasons
        return True, ['bootstrap: no active model to compare against']
    for col, cand in candidate.items():
        act = active.get(col)
        if act is None:
            reasons.append(f'{col}: active model has no such head')
            continue
        if cand.n < config.min_replay_actions:
            reasons.append(
                f'{col}: replay window too small '
                f'({cand.n:.0f} < {config.min_replay_actions} actions)'
            )
            continue
        ci_pct = f'{cand.ci_level:.0%}'
        d_ece = cand.ece - act.ece
        if d_ece > config.max_ece_regression:
            reasons.append(
                f'{col}: ECE regressed {act.ece:.4f} -> {cand.ece:.4f} '
                f'(+{d_ece:.4f} > band {config.max_ece_regression:.4f}; '
                f'candidate {ci_pct} CI '
                f'[{cand.ece_ci[0]:.4f}, {cand.ece_ci[1]:.4f}])'
            )
        d_brier = cand.brier - act.brier
        if d_brier > config.max_brier_regression:
            reasons.append(
                f'{col}: Brier regressed {act.brier:.4f} -> {cand.brier:.4f} '
                f'(+{d_brier:.4f} > band {config.max_brier_regression:.4f}; '
                f'candidate {ci_pct} CI '
                f'[{cand.brier_ci[0]:.4f}, {cand.brier_ci[1]:.4f}])'
            )
    return not reasons, reasons


def record_report(report: PromotionReport) -> None:
    """Land one report in the run log, the flight recorder and metrics.

    Call once per loop iteration, after the verdict is final (including
    the published version on promotion). Never raises — the decision has
    already been acted on; losing telemetry must not unwind it.
    """
    payload = report.to_dict()
    counter('learn/promotions', unit='count').inc(1, verdict=report.verdict)
    for col, entry in report.heads.items():
        for which in ('candidate', 'active'):
            metrics = entry.get(which)
            if metrics:
                gauge('learn/ece', unit='value').set(
                    metrics['ece'], head=col, model=which
                )
                gauge('learn/brier', unit='value').set(
                    metrics['brier'], head=col, model=which
                )
    try:
        RECORDER.record('promotion_report', **payload)
        log = current_runlog()
        if log is not None:
            log.event('promotion_report', **payload)
    except Exception:
        pass
