"""The continuous-learning orchestrator: stream → train → shadow → swap.

:class:`ContinuousLearner` closes the loop between every subsystem built
so far. One :meth:`~ContinuousLearner.run_once` iteration:

1. **ingest** — poll the :class:`~socceraction_tpu.learn.ingest.SeasonWatcher`
   for newly landed matches; nothing new short-circuits to a
   ``no_new_data`` report (and a bitwise no-op on the serving model).
   Otherwise the packed cache is extended incrementally
   (:func:`~socceraction_tpu.learn.ingest.extend_packed` — O(new
   matches) store IO).
2. **train** — stream the season through the packed feed
   (:func:`~socceraction_tpu.pipeline.feed.iter_batches`, cache-hit) into
   :meth:`VAEP.fit_packed`, **warm-started** from the active registry
   model's parameters (and in-process adam state) so the candidate is an
   incremental continuation, not a from-scratch retrain.
3. **shadow** — replay recent traffic (the service's
   :class:`~socceraction_tpu.serve.capture.TrafficCapture`, falling back
   to the newest stored matches when no capture exists) through the
   candidate AND the active model over one byte-identical packed batch;
   compute per-head calibration with bootstrap CIs on device
   (:mod:`socceraction_tpu.learn.calibration`).
4. **gate** — apply the calibration bands
   (:class:`~socceraction_tpu.learn.gate.GateConfig`); every decision
   becomes a typed :class:`~socceraction_tpu.learn.gate.PromotionReport`
   recorded to the run log, the flight recorder and ``learn/*`` metrics.
5. **publish** — on pass, the staged candidate is atomically promoted to
   the next registry version and hot-swapped into the service
   (pre-warmed ladder, zero steady-state retraces); on rejection the
   candidate stays staged for post-mortems, the retention policy
   (:meth:`ModelRegistry.gc_candidates`) bounds the backlog, and a
   flight-recorder debug bundle is dumped automatically.

:meth:`~ContinuousLearner.rollback` is the explicit escape hatch back to
the previously active version (service ladder pre-warmed, counted under
``serve/model_swaps{reason="rollback"}``).

Every stage runs inside a ``learn/*`` span and lands its wall time in
the ``learn/stage_seconds{stage=...}`` histogram — the source of the
bench's ``continuous_learning`` per-stage breakdown. The whole loop is
CPU-runnable end to end (``make learn-smoke``).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import pandas as pd

from ..obs import counter, histogram, span
from ..obs.recorder import RECORDER, default_debug_dir, dump_debug_bundle
from ..resil.faults import fault_point
from ..resil.journal import IterationJournal
from .drift import (
    DriftConfig,
    DriftResult,
    DriftWatch,
    build_drift_reference,
)
from .gate import (
    GateConfig,
    PromotionReport,
    compare_heads,
    evaluate_gate,
    record_report,
)
from .ingest import SeasonWatcher, extend_packed, newest_game_ids
from .shadow import ShadowResult, pack_replay_batch, shadow_replay

__all__ = ['ContinuousLearner', 'LearnConfig']


@dataclass
class LearnConfig:
    """Knobs of one :class:`ContinuousLearner`.

    ``train_params`` are the MLP head hyperparameters (``tree_params`` of
    :meth:`VAEP.fit_packed`); under a warm start they override the
    inherited schedule knobs while the architecture stays the warm
    model's. ``model_factory`` builds the bootstrap model (default: a
    fresh default :class:`~socceraction_tpu.vaep.base.VAEP`).
    """

    model_name: str = 'vaep'
    max_actions: int = 1664
    games_per_batch: int = 64
    learner: str = 'mlp'
    train_params: Optional[Dict[str, Any]] = None
    fit_params: Optional[Dict[str, Any]] = None
    val_size: float = 0.25
    random_state: Optional[int] = 0
    warm_start: bool = True
    gate: GateConfig = field(default_factory=GateConfig)
    #: drift watch over the capture ring vs the active model's training
    #: reference; None (default) disables the watch entirely
    drift: Optional[DriftConfig] = None
    #: wait for at least this many new games before retraining — the
    #: drift watch is the early trigger: a triggered check overrides the
    #: floor and retrains on whatever has landed
    min_new_games: int = 1
    #: traffic source of last resort: replay the newest N stored matches
    #: when no capture ring is attached (or it is empty)
    fallback_replay_games: int = 8
    #: staged candidates kept by the retention policy after a rejection
    retention_keep: int = 2
    cache_dir: Optional[str] = None
    float_dtype: Any = 'float32'
    family: str = 'standard'
    model_factory: Optional[Callable[[], Any]] = None
    debug_dir: Optional[str] = None
    #: durable iteration journal (resil.journal.IterationJournal): every
    #: stage of every iteration is fsync'd here BEFORE its effects
    #: proceed, and a new learner replays it at startup — consumed games
    #: are never retrained, a half-finished publish is completed, and
    #: the decision trail survives any crash. None (default) keeps the
    #: in-memory-only behavior.
    journal_path: Optional[str] = None
    #: serving shapes to compile + ship as AOT executables with every
    #: staged candidate (``{'ladder': (1, ..., B), 'max_actions': N}`` —
    #: match the replicas' ``RatingService`` bucket ladder/capacity).
    #: The artifacts ride the candidate through the promotion's atomic
    #: rename, so a replica hot-swapping to the promoted version warms
    #: by deserializing instead of recompiling
    #: (:mod:`socceraction_tpu.serve.aot`). ``None`` (default) ships
    #: none — the training process then never pays the export compile.
    aot: Optional[Dict[str, Any]] = None


def _head_archs(model: Any) -> Dict[str, str]:
    """Per-head architecture kinds of a VAEP model (``{}`` for ``None``).

    The short names match the checkpoint head-kind vocabulary
    (``'mlp'``/``'seq'``); anything else — tree learners, test doubles —
    reports its class name, so the promotion record never loses the
    information, it just gets less pretty.
    """
    from ..ml.mlp import MLPClassifier
    from ..seq.classifier import SeqClassifier

    kinds: Dict[str, str] = {}
    for col, head in getattr(model, '_models', {}).items():
        if isinstance(head, SeqClassifier):
            kinds[col] = 'seq'
        elif isinstance(head, MLPClassifier):
            kinds[col] = 'mlp'
        else:
            kinds[col] = type(head).__name__
    return kinds


class ContinuousLearner:
    """Drives the stream → train → shadow-eval → gated hot-swap loop.

    Parameters
    ----------
    store : SeasonStore
        Where new matches land.
    registry : ModelRegistry
        Versioned model store; the loop publishes candidates here and
        reads the active model as its warm-start / comparison baseline.
    service : RatingService, optional
        A live serving front end. When given, promotions go through its
        pre-warmed atomic :meth:`swap_model` and the shadow replay reads
        its traffic capture ring by default.
    capture : TrafficCapture, optional
        Explicit traffic source for the shadow replay; defaults to
        ``service.capture``.
    config : LearnConfig, optional
    prime_watcher : bool
        ``True`` (default when the registry already has an active model
        AND no journal is configured) marks the store's current games as
        consumed, so the first iteration only trains when *new* matches
        land. With a ``journal_path`` in the config, the journal's
        replayed ``consumed`` entries are the priming source instead —
        games that landed while the process was down stay *pending* and
        train on the first post-restart iteration.
    """

    def __init__(
        self,
        store: Any,
        registry: Any,
        *,
        service: Any = None,
        capture: Any = None,
        config: Optional[LearnConfig] = None,
        prime_watcher: Optional[bool] = None,
    ) -> None:
        self.store = store
        self.registry = registry
        self.service = service
        self.capture = capture if capture is not None else (
            getattr(service, 'capture', None) if service is not None else None
        )
        self.config = config if config is not None else LearnConfig()
        if prime_watcher is None:
            # with a journal, the journal IS the consumption record: a
            # blanket "everything present is consumed" prime would mark
            # games that landed while the process was down as trained
            # (the exact restart gap the journal closes) — so prime from
            # the replayed 'consumed' entries instead
            prime_watcher = (
                self._active() is not None
                and not self.config.journal_path
            )
        self.watcher = SeasonWatcher(store, prime=prime_watcher)
        self.last_report: Optional[PromotionReport] = None
        self._drift_watch: Optional[DriftWatch] = None
        self._drift_version: Optional[str] = None
        self.journal: Optional[IterationJournal] = (
            IterationJournal(self.config.journal_path)
            if self.config.journal_path
            else None
        )
        self.last_recovery: Optional[Dict[str, Any]] = None
        if self.journal is not None:
            self._recover()

    # -- pieces ------------------------------------------------------------

    def _active(self) -> Optional[Tuple[str, str, Any]]:
        try:
            return self.registry.active()
        except RuntimeError:
            return None

    def _debug_dir(self) -> str:
        return self.config.debug_dir or default_debug_dir()

    def _journal_append(self, stage: str, **fields: Any) -> None:
        """Durably journal one iteration stage (no-op without a journal)."""
        if self.journal is not None:
            self.journal.append(
                stage, model_name=self.config.model_name, **fields
            )

    def _recover(self) -> None:
        """Replay the journal: re-consume games, finish half-done publishes.

        Runs once at construction, before the first :meth:`run_once`.
        Three invariants come out of it (see
        :mod:`socceraction_tpu.resil.journal` for the stage grammar):

        - **no double-consumed games** — every game any past iteration
          committed is marked consumed on the fresh watcher, so a crash
          mid-iteration never retrains data it already trained on;
        - **no half-published registry** — a ``verdict: promoted``
          without ``published`` promotes the still-staged candidate
          under its intended version (the rename is atomic — an intent
          whose version dir already exists just proceeds); ``published``
          without ``activated`` activates/swap-warms the version;
        - **nothing silent** — every completion/abandonment is itself
          journaled (``recovered`` fields mark it), counted under
          ``resil/recoveries{outcome}`` and put in the flight recorder.

        A recovery step that *fails* (the registry is gone, the swap
        target no longer validates) records ``outcome='failed'`` and
        leaves the journal as-was — the next restart retries; the
        learner still constructs so the operator can inspect it.
        """
        assert self.journal is not None
        state = self.journal.replay()
        summary: Dict[str, Any] = {
            'consumed_games': len(state.consumed_games),
            'skipped_lines': state.skipped_lines,
            'pending_stage': state.pending_stage,
            'outcome': None,
        }
        if state.consumed_games:
            self.watcher.commit(state.consumed_games)
        pending = state.open_iteration
        if pending is not None:
            name = pending.get('model_name') or self.config.model_name
            tag = pending.get('tag')
            try:
                outcome = self._finish_pending(pending, name, tag)
            except Exception as e:
                outcome = 'failed'
                summary['error'] = f'{type(e).__name__}: {e}'
            summary['outcome'] = outcome
            counter('resil/recoveries', unit='count').inc(1, outcome=outcome)
        RECORDER.record('journal_recovery', **summary)
        try:
            # dual-write to the run log so `obsctl resil <runlog>` can
            # show what a restart found (the recorder ring dies with
            # the process)
            from ..obs.trace import current_runlog

            log = current_runlog()
            if log is not None:
                log.event('journal_recovery', **summary)
        except Exception:
            pass  # telemetry must not fail the recovery
        self.last_recovery = summary

    def _finish_pending(
        self, pending: Dict[str, Any], name: str, tag: Optional[str]
    ) -> str:
        """Complete (or close out) one half-done journaled iteration."""
        stage = pending.get('stage')
        verdict = pending.get('verdict')
        if stage in ('consumed',) or (stage == 'verdict' and verdict is None):
            # crashed in shadow/gate: games stay consumed, the staged
            # candidate stays for post-mortems, the iteration closes as
            # a recorded abandonment (retraining would double-consume)
            self._journal_append(
                'verdict', verdict='abandoned', tag=tag, recovered=True
            )
            return 'abandoned'
        if verdict != 'promoted':
            # a terminal verdict that somehow stayed open — close it
            self._journal_append(
                'verdict', verdict='abandoned', tag=tag, recovered=True
            )
            return 'abandoned'
        version = pending.get('version')
        if stage in ('verdict', 'intent_publish'):
            if version is None:
                version = self.registry.next_version(name)
                self._journal_append(
                    'intent_publish', version=version, tag=tag, recovered=True
                )
            # the crash may have hit between the atomic rename and its
            # journal entry: a version dir that already exists means the
            # publish completed — proceed straight to activation
            if version not in self.registry.versions(name):
                self.registry.promote_candidate(name, version, tag)
            self._journal_append(
                'published', version=version, tag=tag, recovered=True
            )
        if self.service is not None:
            self.service.swap_model(name, version)
        else:
            self.registry.activate(name, version)
        self._journal_append(
            'activated', version=version, tag=tag, recovered=True
        )
        return 'completed_publish'

    def _new_model(self, active_model: Any) -> Any:
        """An unfitted candidate shell matching the active feature layout."""
        if active_model is not None:
            return type(active_model)(
                xfns=list(active_model.xfns),
                nb_prev_actions=active_model.nb_prev_actions,
                backend=active_model.backend,
            )
        if self.config.model_factory is not None:
            return self.config.model_factory()
        from ..vaep.base import VAEP

        return VAEP()

    def _train_candidate(self, active_model: Any) -> Any:
        """Incremental fit: packed feed (cache hit) + warm start."""
        from ..pipeline.feed import iter_batches

        cfg = self.config
        candidate = self._new_model(active_model)
        batches = iter_batches(
            self.store,
            cfg.games_per_batch,
            max_actions=cfg.max_actions,
            float_dtype=cfg.float_dtype,
            packed_cache=cfg.cache_dir if cfg.cache_dir else True,
            family=cfg.family,
        )
        warm = active_model if (cfg.warm_start and active_model is not None) else None
        candidate.fit_packed(
            batches,
            learner=cfg.learner,
            val_size=cfg.val_size,
            tree_params=cfg.train_params,
            fit_params=cfg.fit_params,
            random_state=cfg.random_state,
            warm_start=warm,
        )
        return candidate

    def _build_manifest(
        self, candidate: Any, new_ids: Any
    ) -> Dict[str, Any]:
        """The candidate's training manifest (staged with the checkpoint).

        Two provenance facts a restarted process cannot reconstruct
        from the checkpoint alone:

        - ``trained_game_ids`` — everything this candidate's fit
          streamed (the whole store at train time: the packed feed is a
          full-season pass, warm-started or not);
        - ``drift_reference`` — the frozen PSI/KS reference
          (:meth:`DriftReference.to_dict`, bit-exact round trip) built
          from the newest stored matches *with the candidate's own
          prediction heads*, so once promoted, a drift watch rebuilt
          from the manifest is the watch the in-process learner uses —
          the PR 8 restart limitation ("promoted-past games are
          indistinguishable from training data") closes here.

        The reference is built only under a ``drift`` config (it costs
        a replay dispatch); the manifest with the id list is written
        always.
        """
        cfg = self.config
        trained = sorted(self.store.game_ids(), key=str)
        manifest: Dict[str, Any] = {
            'format_version': 1,
            'created_unix': round(time.time(), 3),
            'model_name': cfg.model_name,
            'trained_game_ids': trained,
            'new_game_ids': sorted(list(new_ids), key=str),
            'drift_reference': None,
        }
        if cfg.drift is not None:
            ids = newest_game_ids(trained, cfg.drift.reference_games)
            if ids:
                reference = build_drift_reference(
                    candidate, self._pack_games(ids), cfg.drift
                )
                manifest['drift_reference'] = reference.to_dict()
                manifest['drift_reference_games'] = list(ids)
        return manifest

    def _pack_games(self, ids: Any) -> Any:
        """Pack the given stored games into one replay batch (the shared
        reference-batch construction of the manifest build and the
        legacy drift-reference fallback)."""
        home = self.store.home_team_ids()
        frames = [
            (self.store.get_actions(gid), home.get(gid)) for gid in ids
        ]
        return pack_replay_batch(frames, max_actions=self.config.max_actions)

    def _parity_stats(self) -> Optional[Dict[str, Any]]:
        """The serving layer's numeric-health stats for the gate.

        The fail-closed ``GateConfig(max_parity_err=)`` input: the
        parity probe's stats plus the service's drained nonfinite-event
        count (``serve_nonfinite_events`` — a NaN that reached served
        values makes the captured window untrustworthy regardless of
        path parity). None when no service (or no probe and no
        detections) is attached — with the band set, that absence
        itself blocks promotion.
        """
        probe = getattr(self.service, 'parity', None)
        stats = probe.stats() if probe is not None else None
        nonfinite = int(getattr(self.service, 'nonfinite_events', 0) or 0)
        if stats is None and nonfinite:
            stats = {'evaluated': False, 'probes': 0}
        if stats is not None:
            stats['serve_nonfinite_events'] = nonfinite
        return stats

    @staticmethod
    def _train_health_reasons(candidate: Any) -> List[str]:
        """Divergence verdicts from the candidate's training-health telemetry.

        Each MLP head records a :attr:`train_health_` dict inside its
        epoch dispatches (:mod:`socceraction_tpu.ml.mlp`); any head that
        saw a non-finite loss/gradient step — or ended on non-finite
        norms — makes the candidate unpromotable regardless of what the
        shadow calibration would say about it.
        """
        reasons: List[str] = []
        for col, head in getattr(candidate, '_models', {}).items():
            health = getattr(head, 'train_health_', None)
            if health is None or health.get('finite', True):
                continue
            reasons.append(
                f'{col}: training diverged — '
                f'{health.get("nonfinite_steps", 0)} non-finite '
                f'loss/grad step(s) over {health.get("epochs", 0)} '
                f'epoch(s); grad_norm {health.get("grad_norm_last")}, '
                f'weight_norm {health.get("weight_norm_last")}'
            )
        return reasons

    def _replay_frames(
        self, exclude: Any = ()
    ) -> Tuple[List[Tuple[pd.DataFrame, Any]], str]:
        """The traffic window plus its actual source.

        Capture ring first (genuinely served traffic — kept even when it
        overlaps the new games), stored games as the fallback. The
        source travels with the frames so the report can never claim
        ``'capture'`` for a window that was actually the fallback (the
        ring may fill concurrently with this call).

        ``exclude`` (the games this iteration just trained on) is
        dropped from the *fallback* window: scoring the candidate on its
        own fresh training data while the active model is out-of-sample
        would bias the gate toward promotion. When nothing else exists
        (the bootstrap store is only new games), the in-sample window is
        used anyway but labeled ``'store_fallback_in_sample'`` so the
        report carries the caveat.
        """
        if self.capture is not None:
            frames = self.capture.frames()
            if frames:
                return frames, 'capture'
        n = int(self.config.fallback_replay_games)
        if n <= 0:
            return [], 'store_fallback'
        exclude = set(exclude)
        # numeric-aware recency: the raw listing is key-string ordered,
        # whose tail is NOT the newest games once ids grow a digit
        all_ids = self.store.game_ids()
        game_ids = newest_game_ids(
            [g for g in all_ids if g not in exclude], n
        )
        source = 'store_fallback'
        if not game_ids and exclude:
            game_ids = newest_game_ids(all_ids, n)
            source = 'store_fallback_in_sample'
        home = self.store.home_team_ids()
        return [
            (self.store.get_actions(gid), home.get(gid))
            for gid in game_ids
        ], source

    def _drift_check(
        self,
        active_model: Any,
        active_version: Optional[str],
        pending_ids: Any = (),
    ) -> Optional[DriftResult]:
        """Score the capture ring against the active model's reference.

        Returns None when the watch cannot run (no ``drift`` config, no
        active model, no captured traffic) — with the gate's
        ``max_drift_psi`` band set, that absence itself fails closed.
        The reference comes from the active version's registry
        **training manifest** first (:meth:`DriftWatch.from_manifest`):
        the frozen statistics the promoting learner wrote at stage time
        travel with the checkpoint, so an in-process rebuild and a
        process restart reconstruct the *identical* watch — the PR 8
        restart limitation (pre-restart promoted games indistinguishable
        from training data) is closed. Versions that predate manifests
        (bootstrap publishes, old registries) fall back to rebuilding
        from the newest stored matches, EXCLUDING ``pending_ids``
        (games landed but not yet consumed by a retrain): the active
        model never trained on those, and folding a drifted fresh batch
        into its own reference would make the watch compare drift
        against drift and read PSI ~0.
        """
        cfg = self.config
        if cfg.drift is None or active_model is None:
            return None
        if self.capture is None:
            return None
        frames = self.capture.frames()
        if not frames:
            return None
        if (
            self._drift_watch is None
            or self._drift_version != active_version
        ):
            watch: Optional[DriftWatch] = None
            try:
                manifest = self.registry.load_manifest(
                    cfg.model_name, active_version
                )
            except OSError:
                manifest = None  # transient read failure: legacy rebuild
            except ValueError as e:
                # a CORRUPT manifest must surface (load_manifest's
                # contract), but a drift check must not wedge the loop:
                # flag it loudly, then fall back to the legacy rebuild
                manifest = None
                counter('learn/manifest_corrupt', unit='count').inc(1)
                payload = {
                    'model': cfg.model_name,
                    'version': active_version,
                    'error': f'{type(e).__name__}: {e}',
                }
                RECORDER.record('manifest_corrupt', **payload)
                try:
                    from ..obs.trace import current_runlog

                    log = current_runlog()
                    if log is not None:
                        log.event('manifest_corrupt', **payload)
                except Exception:
                    pass
            if manifest and manifest.get('drift_reference'):
                watch = DriftWatch.from_manifest(
                    manifest, cfg.drift, model_version=active_version
                )
            if watch is None:
                pending = set(pending_ids)
                ids = newest_game_ids(
                    [g for g in self.store.game_ids() if g not in pending],
                    cfg.drift.reference_games,
                )
                if not ids:
                    return None
                watch = DriftWatch.from_batch(
                    active_model, self._pack_games(ids), cfg.drift,
                    model_version=active_version,
                )
            self._drift_watch = watch
            self._drift_version = active_version
        batch = pack_replay_batch(frames, max_actions=cfg.max_actions)
        return self._drift_watch.check(active_model, batch)

    # -- the loop ----------------------------------------------------------

    def run_once(self) -> PromotionReport:
        """One full loop iteration; returns (and records) the report."""
        cfg = self.config
        gate_cfg = cfg.gate
        stage_s: Dict[str, float] = {}

        def timed_stage(stage: str):
            return _StageTimer(stage, stage_s)

        with span('learn/loop', model=cfg.model_name):
            active = self._active()
            active_version = active[1] if active else None
            active_model = active[2] if active else None

            with timed_stage('ingest'), span('learn/ingest'):
                new_ids = self.watcher.poll()
                if new_ids:
                    extend_packed(
                        self.store,
                        max_actions=cfg.max_actions,
                        float_dtype=cfg.float_dtype,
                        cache_dir=cfg.cache_dir,
                        family=cfg.family,
                    )
            # the drift watch runs every iteration — continuous
            # monitoring, not promotion-time-only — and doubles as the
            # early retrain trigger below
            drift_res: Optional[DriftResult] = None
            if cfg.drift is not None:
                with timed_stage('drift'):
                    drift_res = self._drift_check(
                        active_model, active_version, pending_ids=new_ids
                    )
            drift_triggered = bool(drift_res is not None and drift_res.triggered)
            if not new_ids or (
                len(new_ids) < cfg.min_new_games and not drift_triggered
            ):
                # nothing to train on — or not enough yet and the serving
                # distribution is stable, so waiting is free (the
                # uncommitted games stay pending for the next poll)
                reasons = (
                    ['no new matches since the last iteration']
                    if not new_ids
                    else [
                        f'waiting: {len(new_ids)} new game(s) < '
                        f'min_new_games={cfg.min_new_games} and drift is '
                        'below trigger'
                    ]
                )
                report = PromotionReport(
                    name=cfg.model_name,
                    verdict='no_new_data',
                    reasons=reasons,
                    active_version=active_version,
                    drift=drift_res.to_dict() if drift_res else {},
                    archs=_head_archs(active_model),
                    stage_seconds=dict(stage_s),
                )
                self._finish(report)
                return report
            if drift_triggered and len(new_ids) < cfg.min_new_games:
                # the early trigger: the distribution moved, so retrain
                # on whatever has landed instead of waiting out the floor
                counter('learn/early_trains', unit='count').inc(1)
                RECORDER.record(
                    'drift_early_train',
                    new_games=len(new_ids),
                    min_new_games=cfg.min_new_games,
                    max_psi=drift_res.max_psi,
                    feature=drift_res.max_psi_feature,
                )
            counter('learn/new_games', unit='count').inc(len(new_ids))

            with timed_stage('train'), span('learn/train', games=len(new_ids)):
                candidate = self._train_candidate(active_model)
                tag, _path = self.registry.stage_candidate(
                    cfg.model_name,
                    candidate,
                    manifest=self._build_manifest(candidate, new_ids),
                    aot=cfg.aot,
                )
            # the games are consumed once a candidate was trained over
            # them — a rejected candidate must not retrain the same data
            # forever, and a crash before this line retries it. The
            # journal entry is written AFTER the in-memory commit but is
            # the durable half: a restarted learner re-consumes from the
            # journal, never from memory
            self.watcher.commit(new_ids)
            self._journal_append('consumed', games=list(new_ids), tag=tag)

            # everything past the commit must end in a recorded report —
            # an exception here would otherwise consume the games with no
            # decision trail anywhere (same contract as the publish guard)
            try:
                # training-health gate first: a diverging incremental
                # retrain is a poisoned candidate — reject it with a
                # typed report before the shadow replay can score NaN
                # probabilities (the games stay committed: retraining
                # the same data would diverge again). Inside this try on
                # purpose: a raise out of the rejection bookkeeping
                # still records the 'error' report below.
                health_reasons = self._train_health_reasons(candidate)
                if health_reasons:
                    counter('learn/training_diverged', unit='count').inc(1)
                    self._journal_append(
                        'verdict', verdict='rejected', tag=tag
                    )
                    report = PromotionReport(
                        name=cfg.model_name,
                        verdict='rejected',
                        reasons=health_reasons,
                        active_version=active_version,
                        candidate_tag=tag,
                        new_games=list(new_ids),
                        drift=drift_res.to_dict() if drift_res else {},
                        archs=_head_archs(candidate),
                        stage_seconds=dict(stage_s),
                    )
                    self.registry.gc_candidates(
                        cfg.model_name, keep=cfg.retention_keep
                    )
                    try:
                        dump_debug_bundle(
                            self._debug_dir(),
                            reason='training_diverged',
                            trigger={
                                'type': 'training_diverged',
                                **report.to_dict(),
                            },
                        )
                    except Exception:
                        pass  # a failing dump must never unwind the verdict
                    self._finish(report)
                    return report

                act_res: Optional[ShadowResult] = None
                cand_res: Optional[ShadowResult] = None
                with timed_stage('shadow'), span('learn/shadow'):
                    frames, replay_source = self._replay_frames(
                        exclude=new_ids
                    )
                    if frames:
                        batch = pack_replay_batch(
                            frames, max_actions=cfg.max_actions
                        )
                        # ONE packed batch replayed through both models:
                        # candidate and active see byte-identical inputs
                        # and labels
                        cand_res = shadow_replay(
                            candidate, batch=batch,
                            n_bins=gate_cfg.n_bins, n_boot=gate_cfg.n_boot,
                            seed=gate_cfg.seed, ci_level=gate_cfg.ci_level,
                        )
                        if active_model is not None:
                            act_res = shadow_replay(
                                active_model, batch=batch,
                                n_bins=gate_cfg.n_bins,
                                n_boot=gate_cfg.n_boot,
                                seed=gate_cfg.seed,
                                ci_level=gate_cfg.ci_level,
                            )
                if cand_res is None:
                    # fail CLOSED, but on the record: the candidate stays
                    # staged unevaluated and the decision is a typed
                    # report (built OUTSIDE the stage timer, so the
                    # shadow wall it just measured is included)
                    self._journal_append(
                        'verdict', verdict='rejected', tag=tag
                    )
                    report = PromotionReport(
                        name=cfg.model_name,
                        verdict='rejected',
                        reasons=[
                            'no replay traffic available (capture empty '
                            'and the store fallback is disabled)'
                        ],
                        active_version=active_version,
                        candidate_tag=tag,
                        new_games=list(new_ids),
                        drift=drift_res.to_dict() if drift_res else {},
                        archs=_head_archs(candidate),
                        stage_seconds=dict(stage_s),
                    )
                    self.registry.gc_candidates(
                        cfg.model_name, keep=cfg.retention_keep
                    )
                    self._finish(report)
                    return report

                with timed_stage('gate'), span('learn/gate'):
                    parity_stats = self._parity_stats()
                    passed, reasons = evaluate_gate(
                        act_res.summaries if act_res else None,
                        cand_res.summaries,
                        gate_cfg,
                        drift=drift_res,
                        parity=parity_stats,
                    )
            except Exception as e:
                self._journal_append('verdict', verdict='error', tag=tag)
                report = PromotionReport(
                    name=cfg.model_name,
                    verdict='error',
                    reasons=[
                        f'shadow/gate failed: {type(e).__name__}: {e}'
                    ],
                    active_version=active_version,
                    candidate_tag=tag,
                    new_games=list(new_ids),
                    archs=_head_archs(candidate),
                    stage_seconds=dict(stage_s),
                )
                self.registry.gc_candidates(
                    cfg.model_name, keep=cfg.retention_keep
                )
                self._finish(report)
                raise

            report = PromotionReport(
                name=cfg.model_name,
                verdict='promoted' if passed else 'rejected',
                reasons=reasons,
                active_version=active_version,
                candidate_tag=tag,
                new_games=list(new_ids),
                heads=compare_heads(
                    act_res.summaries if act_res else {}, cand_res.summaries
                ),
                replay={
                    'frames': cand_res.n_frames,
                    'actions': cand_res.n_actions,
                    'source': replay_source,
                },
                drift=drift_res.to_dict() if drift_res else {},
                parity=parity_stats or {},
                archs=_head_archs(candidate),
            )

            self._journal_append(
                'verdict',
                verdict='promoted' if passed else 'rejected',
                tag=tag,
            )
            if passed:
                try:
                    with timed_stage('publish'), span('learn/publish'):
                        version = self.registry.next_version(cfg.model_name)
                        # write-ahead intent: a crash between the atomic
                        # rename below and its 'published' entry is
                        # recoverable because the intended version is
                        # already durable (the restart checks whether
                        # the rename landed and resumes either way)
                        self._journal_append(
                            'intent_publish', version=version, tag=tag
                        )
                        fault_point('learn.publish', version=version)
                        self.registry.promote_candidate(
                            cfg.model_name, version, tag
                        )
                        self._journal_append(
                            'published', version=version, tag=tag
                        )
                        if self.service is not None:
                            self.service.swap_model(cfg.model_name, version)
                        else:
                            self.registry.activate(cfg.model_name, version)
                        self._journal_append(
                            'activated', version=version, tag=tag
                        )
                        report.candidate_version = version
                        self._transplant_opt_state(candidate)
                except Exception as e:
                    # an operational publish failure (version race, disk,
                    # swap validation) still gets a typed decision record
                    # before it surfaces — the report contract holds for
                    # every iteration that got past the commit
                    report.verdict = 'publish_failed'
                    report.reasons = [
                        f'publish failed: {type(e).__name__}: {e}'
                    ]
                    report.candidate_version = None
                    report.stage_seconds = dict(stage_s)
                    self._finish(report)
                    raise
            else:
                # the rejected candidate stays staged for post-mortems;
                # retention bounds the backlog, and the flight recorder
                # is dumped with the full decision attached
                self.registry.gc_candidates(
                    cfg.model_name, keep=cfg.retention_keep
                )
                try:
                    dump_debug_bundle(
                        self._debug_dir(),
                        reason='promotion_rejected',
                        trigger={
                            'type': 'promotion_rejected',
                            **report.to_dict(),
                        },
                    )
                except Exception:
                    pass  # a failing dump must never unwind the verdict

            report.stage_seconds = dict(stage_s)
            self._finish(report)
            return report

    def _transplant_opt_state(self, candidate: Any) -> None:
        """Carry the candidate's adam state onto the freshly *loaded* active.

        Promotion activates the checkpoint read back from disk —
        parameter-identical to the candidate (the msgpack round trip is
        exact) but with ``opt_state_ = None``, because checkpoints
        deliberately exclude optimizer state. Transplanting the
        in-process state keeps the next iteration's warm start a true
        optimizer continuation; across process restarts it degrades
        gracefully to a params-only warm start. Architecture-checked per
        head: both packed head kinds (MLP and the seq head) carry adam
        state, but state only transplants between heads of the SAME
        class — a cross-architecture promotion starts the next iteration
        cold, which is also what its warm-start path does.
        """
        from ..ml.mlp import MLPClassifier
        from ..seq.classifier import SeqClassifier

        try:
            active = self.registry.active()[2]
        except RuntimeError:
            return
        for col, head in getattr(active, '_models', {}).items():
            cand_head = candidate._models.get(col)
            if (
                isinstance(head, (MLPClassifier, SeqClassifier))
                and type(cand_head) is type(head)
                and cand_head.opt_state_ is not None
            ):
                head.opt_state_ = cand_head.opt_state_

    def _finish(self, report: PromotionReport) -> None:
        for stage, seconds in report.stage_seconds.items():
            histogram('learn/stage_seconds', unit='s').observe(
                seconds, stage=stage
            )
        record_report(report)
        self.last_report = report

    # -- rollback ----------------------------------------------------------

    def rollback(self) -> Tuple[str, str]:
        """Restore the previously active version (explicit escape hatch).

        Through the service when one is attached (ladder pre-warmed
        before the swap goes live), directly on the registry otherwise.
        Either way the swap is atomic and counted under
        ``serve/model_swaps{reason="rollback"}``.
        """
        if self.service is not None:
            name, version = self.service.rollback_model()
        else:
            name, version = self.registry.rollback()
        counter('learn/rollbacks', unit='count').inc(1)
        RECORDER.record('rollback', name=name, version=version)
        return name, version


class _StageTimer:
    """Record one stage's wall clock into a shared dict on exit."""

    def __init__(self, stage: str, sink: Dict[str, float]) -> None:
        self.stage = stage
        self.sink = sink

    def __enter__(self) -> '_StageTimer':
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.sink[self.stage] = (
            self.sink.get(self.stage, 0.0) + time.perf_counter() - self.t0
        )
