"""Drift watch: device PSI/KS of live traffic vs the training reference.

Between the promotion gate's calibration checks (PR 6) the serving model
runs blind: if the traffic distribution moves — a new league's pitch
geometry, a rule change shifting shot mix, a provider re-mapping action
types — nothing notices until enough new matches land to trigger a
retrain *and* the gate happens to catch the damage. Per 2409.04889's
argument that statistical honesty must be monitored *continuously*, not
only at promotion time, this module watches the serving distribution
itself:

- :func:`build_drift_reference` — fix per-feature bin edges and
  reference proportions from the active model's training data (a packed
  batch of stored matches): raw packed action fields (locations, clock,
  action/result/bodypart ids) plus each probability head's prediction
  distribution.
- :class:`DriftWatch` / :func:`drift_statistics` — score a current
  traffic window (the serve layer's capture ring, packed exactly like a
  replay) against the reference with the **population stability index**
  (PSI, the classic ``(p-q)·ln(p/q)`` score-drift statistic) and a
  binned **Kolmogorov–Smirnov** statistic per feature, computed on
  device in **one** ``vmap``'d dispatch over the stacked feature/head
  rows — the same packed-mask semantics as
  :mod:`socceraction_tpu.learn.calibration`: zero-weight (padding) rows
  contribute to no bin, and the row axis is padded to a power of two so
  varying window sizes reuse one compiled program.

Results surface three ways: ``drift/*`` gauges (per-feature PSI/KS,
the max, check/trigger counters), a ``drift_check`` event in the run
log + flight recorder (``obsctl drift`` tails them), and the typed
:class:`DriftResult` the continuous learner threads into its promotion
report, its optional early retrain trigger, and the gate's fail-closed
``max_drift_psi`` band.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from functools import lru_cache, partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import counter, gauge, span
from ..obs.recorder import RECORDER
from ..obs.trace import current_runlog

__all__ = [
    'DriftConfig',
    'DriftReference',
    'DriftResult',
    'DriftWatch',
    'build_drift_reference',
    'drift_statistics',
]

#: Packed action fields monitored by default: the continuous geometry /
#: clock signals plus the categorical ids (binned by value — adjacent ids
#: may share a bin past ``n_bins`` categories, which is fine for drift:
#: reference and current windows are binned identically).
DEFAULT_FIELDS: Tuple[str, ...] = (
    'start_x', 'start_y', 'end_x', 'end_y', 'time_seconds',
    'type_id', 'result_id', 'bodypart_id',
)

_EPS = 1e-6


@dataclass(frozen=True)
class DriftConfig:
    """Knobs of one drift watch.

    ``psi_trigger`` uses the classic banding: PSI < 0.1 stable,
    0.1–0.25 drifting, > 0.25 shifted — the default trigger fires on a
    genuine shift, not sampling noise. ``min_actions`` refuses to score
    a window too small to estimate proportions (the result then reports
    ``evaluated=False``, which the gate's ``max_drift_psi`` band treats
    as *no evidence* and fails closed on).
    """

    n_bins: int = 16
    psi_trigger: float = 0.25
    ks_trigger: Optional[float] = None
    min_actions: int = 256
    fields: Tuple[str, ...] = DEFAULT_FIELDS
    include_predictions: bool = True
    #: stored matches used to build the training reference (newest-first)
    reference_games: int = 16


@dataclass(frozen=True)
class DriftReference:
    """Frozen training-side distribution: bin edges + proportions.

    ``lo``/``hi`` fix the equal-width bin edges per monitored row —
    stored so every later window is binned *identically* to the
    reference (prediction rows are pinned to [0, 1]); ``props`` is the
    ``(F, n_bins)`` reference proportion stack.
    """

    names: Tuple[str, ...]
    lo: np.ndarray
    hi: np.ndarray
    props: np.ndarray
    n_bins: int
    n_actions: int
    model_version: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        """JSON-able form for the registry training manifest.

        The float arrays are emitted as Python floats — every float32
        is exactly representable as a float64 and JSON round-trips
        float64 exactly in Python, so :meth:`from_dict` reconstructs
        the reference **bit-for-bit**: a drift watch rebuilt from a
        manifest after a process restart scores windows identically to
        the in-process watch that wrote it.
        """
        return {
            'names': list(self.names),
            'lo': [float(v) for v in np.asarray(self.lo, np.float32)],
            'hi': [float(v) for v in np.asarray(self.hi, np.float32)],
            'props': [
                [float(v) for v in row]
                for row in np.asarray(self.props, np.float32)
            ],
            'n_bins': int(self.n_bins),
            'n_actions': int(self.n_actions),
            'model_version': self.model_version,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'DriftReference':
        """Rebuild a reference serialized with :meth:`to_dict` (exact)."""
        return cls(
            names=tuple(d['names']),
            lo=np.asarray(d['lo'], np.float32),
            hi=np.asarray(d['hi'], np.float32),
            props=np.asarray(d['props'], np.float32),
            n_bins=int(d['n_bins']),
            n_actions=int(d['n_actions']),
            model_version=d.get('model_version'),
        )


@dataclass
class DriftResult:
    """One window's drift statistics vs the reference (JSON-ready)."""

    psi: Dict[str, float] = field(default_factory=dict)
    ks: Dict[str, float] = field(default_factory=dict)
    max_psi: float = 0.0
    max_psi_feature: Optional[str] = None
    max_ks: float = 0.0
    max_ks_feature: Optional[str] = None
    n_actions: int = 0
    reference_actions: int = 0
    #: False when the window was too small to score (no statistics)
    evaluated: bool = True
    triggered: bool = False
    reasons: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        """Flat rendering for reports, run-log events and ``obsctl``."""
        return {
            'psi': {k: round(v, 6) for k, v in self.psi.items()},
            'ks': {k: round(v, 6) for k, v in self.ks.items()},
            'max_psi': round(self.max_psi, 6),
            'max_psi_feature': self.max_psi_feature,
            'max_ks': round(self.max_ks, 6),
            'max_ks_feature': self.max_ks_feature,
            'n_actions': self.n_actions,
            'reference_actions': self.reference_actions,
            'evaluated': self.evaluated,
            'triggered': self.triggered,
            'reasons': list(self.reasons),
        }


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _stack_rows(
    batch: Any,
    fields: Sequence[str],
    probs: Optional[Dict[str, np.ndarray]],
) -> Tuple[Tuple[str, ...], np.ndarray, np.ndarray]:
    """``(names, x (F, N), w (N,))`` from a packed batch (+ predictions).

    ``N`` is padded up to a power of two with zero-weight rows, so a
    drift check compiles one program per power-of-two window size
    instead of one per distinct capture-window length — the same
    padding-is-free mask semantics as the calibration kernels.
    """
    rows: List[np.ndarray] = []
    names: List[str] = []
    for f in fields:
        rows.append(np.asarray(getattr(batch, f), np.float32).reshape(-1))
        names.append(f)
    for head in sorted(probs or {}):
        rows.append(np.asarray(probs[head], np.float32).reshape(-1))
        names.append(f'pred_{head}')
    w = np.asarray(batch.mask, np.float32).reshape(-1)
    x = np.stack(rows, axis=0)
    n = x.shape[1]
    padded = _pow2(max(n, 1))
    if padded != n:
        x = np.pad(x, [(0, 0), (0, padded - n)])
        w = np.pad(w, [(0, padded - n)])
    return tuple(names), x, w


def _weighted_props(xi: Any, w: Any, lo_i: Any, hi_i: Any, n_bins: int) -> Any:
    """Masked equal-width bin proportions of one stacked row (traced)."""
    import jax
    import jax.numpy as jnp

    width = jnp.maximum(hi_i - lo_i, _EPS)
    t = (xi - lo_i) / width
    bins = jnp.clip((t * n_bins).astype(jnp.int32), 0, n_bins - 1)
    cnt = jax.ops.segment_sum(w, bins, num_segments=n_bins)
    return cnt / jnp.maximum(jnp.sum(cnt), _EPS)


def _props_kernel(x: Any, w: Any, lo: Any, hi: Any, n_bins: int) -> Any:
    import jax

    return jax.vmap(
        lambda xi, lo_i, hi_i: _weighted_props(xi, w, lo_i, hi_i, n_bins)
    )(x, lo, hi)


@lru_cache(maxsize=None)
def _jitted(n_bins: int) -> Tuple[Any, Any]:
    """Jitted (props, drift) kernels for one static bin count."""
    import jax
    import jax.numpy as jnp

    props = jax.jit(partial(_props_kernel, n_bins=n_bins))

    def drift(x, w, lo, hi, ref):
        p = _props_kernel(x, w, lo, hi, n_bins)
        # clamp-and-renormalize both sides identically: PSI's log blows
        # up on empty bins, and the clamp must not bias p against q
        p = jnp.clip(p, _EPS, None)
        p = p / jnp.sum(p, axis=1, keepdims=True)
        q = jnp.clip(ref, _EPS, None)
        q = q / jnp.sum(q, axis=1, keepdims=True)
        psi = jnp.sum((p - q) * jnp.log(p / q), axis=1)
        ks = jnp.max(
            jnp.abs(jnp.cumsum(p, axis=1) - jnp.cumsum(q, axis=1)), axis=1
        )
        return psi, ks

    return props, jax.jit(drift)


def build_drift_reference(
    model: Any,
    batch: Any,
    config: Optional[DriftConfig] = None,
    *,
    model_version: Optional[str] = None,
) -> DriftReference:
    """Freeze the training-side distribution of ``model`` over ``batch``.

    ``batch`` is a packed :class:`~socceraction_tpu.core.batch.ActionBatch`
    of the matches the active model trained on (the learner packs the
    newest ``reference_games`` stored matches). Bin edges come from the
    reference's own masked min/max per field — predictions are pinned to
    [0, 1] so the head distributions bin identically forever.
    """
    from .shadow import replay_probs

    cfg = config if config is not None else DriftConfig()
    probs = replay_probs(model, batch) if cfg.include_predictions else None
    names, x, w = _stack_rows(batch, cfg.fields, probs)
    mask = w > 0
    n_actions = int(mask.sum())
    if n_actions == 0:
        raise ValueError('cannot build a drift reference from an empty batch')
    lo = np.empty(len(names), np.float32)
    hi = np.empty(len(names), np.float32)
    for i, name in enumerate(names):
        if name.startswith('pred_'):
            lo[i], hi[i] = 0.0, 1.0
        else:
            vals = x[i][mask]
            lo[i], hi[i] = float(vals.min()), float(vals.max())
            if hi[i] <= lo[i]:
                hi[i] = lo[i] + 1.0  # a constant field still bins sanely
    props_fn, _ = _jitted(int(cfg.n_bins))
    props = np.asarray(props_fn(x, w, lo, hi))
    return DriftReference(
        names=names, lo=lo, hi=hi, props=props,
        n_bins=int(cfg.n_bins), n_actions=n_actions,
        model_version=model_version,
    )


def drift_statistics(
    reference: DriftReference,
    batch: Any,
    probs: Optional[Dict[str, np.ndarray]] = None,
    *,
    fields: Optional[Sequence[str]] = None,
) -> Tuple[Dict[str, float], Dict[str, float], int]:
    """``(psi, ks, n_actions)`` of one window vs the reference.

    One vmap'd device dispatch over the stacked rows; the row set must
    match the reference's (same fields, same prediction heads).
    """
    use_fields = tuple(fields) if fields is not None else tuple(
        n for n in reference.names if not n.startswith('pred_')
    )
    names, x, w = _stack_rows(batch, use_fields, probs)
    if names != reference.names:
        raise ValueError(
            f'window rows {names} do not match the reference '
            f'{reference.names}; rebuild the reference for this model'
        )
    _, drift_fn = _jitted(int(reference.n_bins))
    psi, ks = drift_fn(x, w, reference.lo, reference.hi, reference.props)
    psi = np.asarray(psi)
    ks = np.asarray(ks)
    n_actions = int((w > 0).sum())
    return (
        {n: float(v) for n, v in zip(names, psi)},
        {n: float(v) for n, v in zip(names, ks)},
        n_actions,
    )


class DriftWatch:
    """A frozen reference plus the check that scores windows against it.

    Build once per active model (:meth:`from_batch`); each ``check`` is
    one device dispatch that lands the statistics in the ``drift/*``
    gauges, the run log and the flight recorder, and returns the typed
    :class:`DriftResult` the learner acts on.
    """

    def __init__(
        self, reference: DriftReference, config: Optional[DriftConfig] = None
    ) -> None:
        self.reference = reference
        self.config = config if config is not None else DriftConfig()

    @classmethod
    def from_batch(
        cls,
        model: Any,
        batch: Any,
        config: Optional[DriftConfig] = None,
        *,
        model_version: Optional[str] = None,
    ) -> 'DriftWatch':
        """Build the reference from ``model``'s training batch and wrap it."""
        cfg = config if config is not None else DriftConfig()
        return cls(
            build_drift_reference(
                model, batch, cfg, model_version=model_version
            ),
            cfg,
        )

    @classmethod
    def from_manifest(
        cls,
        manifest: Dict[str, Any],
        config: Optional[DriftConfig] = None,
        *,
        model_version: Optional[str] = None,
    ) -> 'DriftWatch':
        """Rebuild the watch from a registry **training manifest**.

        The restart path: the manifest's ``drift_reference`` block
        (written by the learner at candidate-stage time, promoted
        atomically with the checkpoint) reconstructs the exact
        reference the in-process watch used — a restarted process
        scores drift against the distribution the active model actually
        trained on, not a recency guess over the store.

        ``model_version`` stamps the reference with the version it now
        serves (the manifest was written at *stage* time, before a
        version existed, so its stored ``model_version`` is None) —
        drift events then carry the version for operator correlation.
        """
        ref = (manifest or {}).get('drift_reference')
        if not ref:
            raise ValueError(
                'manifest carries no drift_reference block '
                '(pre-resilience version? fall back to from_batch)'
            )
        reference = DriftReference.from_dict(ref)
        if model_version is not None:
            reference = replace(reference, model_version=model_version)
        return cls(reference, config)

    def check(self, model: Any, batch: Any) -> DriftResult:
        """Score one traffic window; record gauges + events; never raises
        past telemetry (statistic errors do propagate — a broken check
        must not read as "no drift")."""
        from .shadow import replay_probs

        cfg = self.config
        with span('learn/drift_check'):
            probs = (
                replay_probs(model, batch)
                if cfg.include_predictions
                else None
            )
            # the window size gate reads the MASKED row count (padding is
            # not evidence)
            n_actions = int(np.asarray(batch.mask).sum())
            if n_actions < cfg.min_actions:
                result = DriftResult(
                    n_actions=n_actions,
                    reference_actions=self.reference.n_actions,
                    evaluated=False,
                    triggered=False,
                    reasons=[
                        f'window too small to score drift ({n_actions} < '
                        f'{cfg.min_actions} actions)'
                    ],
                )
                self._record(result)
                return result
            psi, ks, n_actions = drift_statistics(
                self.reference, batch, probs
            )
        max_psi_feature = max(psi, key=psi.get)
        max_ks_feature = max(ks, key=ks.get)
        reasons: List[str] = []
        if psi[max_psi_feature] > cfg.psi_trigger:
            reasons.append(
                f'{max_psi_feature}: PSI {psi[max_psi_feature]:.4f} > '
                f'trigger {cfg.psi_trigger:.4f}'
            )
        if (
            cfg.ks_trigger is not None
            and ks[max_ks_feature] > cfg.ks_trigger
        ):
            reasons.append(
                f'{max_ks_feature}: KS {ks[max_ks_feature]:.4f} > '
                f'trigger {cfg.ks_trigger:.4f}'
            )
        result = DriftResult(
            psi=psi,
            ks=ks,
            max_psi=psi[max_psi_feature],
            max_psi_feature=max_psi_feature,
            max_ks=ks[max_ks_feature],
            max_ks_feature=max_ks_feature,
            n_actions=n_actions,
            reference_actions=self.reference.n_actions,
            evaluated=True,
            triggered=bool(reasons),
            reasons=reasons,
        )
        self._record(result)
        return result

    def _record(self, result: DriftResult) -> None:
        """Gauges + run-log/recorder events; telemetry never raises."""
        counter('drift/checks', unit='count').inc(1)
        if result.evaluated:
            psi_g = gauge('drift/psi', unit='value')
            ks_g = gauge('drift/ks', unit='value')
            for name, v in result.psi.items():
                psi_g.set(v, feature=name)
            for name, v in result.ks.items():
                ks_g.set(v, feature=name)
            gauge('drift/max_psi', unit='value').set(result.max_psi)
        if result.triggered:
            counter('drift/triggers', unit='count').inc(1)
        try:
            payload = result.to_dict()
            payload['model_version'] = self.reference.model_version
            RECORDER.record('drift_check', **payload)
            log = current_runlog()
            if log is not None:
                log.event('drift_check', **payload)
        except Exception:
            pass
