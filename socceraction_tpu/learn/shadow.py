"""Shadow evaluation: replay captured traffic through candidate vs active.

The promotion gate never judges a candidate on a held-out split — it
replays *recent real traffic* (the serving layer's
:class:`~socceraction_tpu.serve.capture.TrafficCapture`: one-shot rating
requests and per-match session streams) through both the candidate and
the currently active model, and compares their calibration on the
outcomes those action sequences actually produced (labels from the
device label kernel). This is the replay-based evaluation PAPERS.md's
*What Happened Next?* (2106.01786) motivates: event sequences as they
occurred, not rows in isolation.

Reproducibility is a hard contract here: for a fixed model and a fixed
traffic window, :func:`shadow_replay` is **bitwise-stable on CPU** —
same packed batch, same feature/probability dispatches, same reductions,
no RNG outside the seeded bootstrap ensemble. The promotion report's
numbers can therefore be regenerated exactly from a capture dump, and
``tests/test_learn.py`` pins candidate replay stability across runs.

Both models are evaluated by the *same* function of the same packed
batch (features → probability heads), so the comparison is symmetric:
any truncation a captured window imposes on the label lookahead affects
candidate and active identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from ..core.batch import ActionBatch, pack_actions
from ..obs import counter, span
from .calibration import CalibrationSummary, calibration_summary

__all__ = ['ShadowResult', 'pack_replay_batch', 'replay_probs', 'shadow_replay']


def pack_replay_batch(
    frames: Sequence[Tuple[pd.DataFrame, Any]],
    *,
    max_actions: int,
) -> ActionBatch:
    """Pack captured ``(frame, home_team_id)`` traffic into one host batch.

    Each traffic unit becomes its own game row (game ids are
    renumbered positionally — captures from different sources may reuse
    ids), packed to the service's fixed ``max_actions`` exactly like a
    live request; a frame longer than the window keeps its most recent
    ``max_actions`` rows (still a contiguous action sequence). The
    per-unit staging batches are concatenated on host, the same idiom
    the service's flusher uses to coalesce a flush.
    """
    if not frames:
        raise ValueError('no captured traffic to replay')
    stagings: List[ActionBatch] = []
    for i, (frame, home_team_id) in enumerate(frames):
        if len(frame) == 0:
            continue
        if len(frame) > max_actions:
            frame = frame.iloc[-max_actions:]
        work = frame.assign(game_id=i)
        staging, _ids = pack_actions(
            work, home_team_id=home_team_id, max_actions=max_actions,
            as_numpy=True,
        )
        stagings.append(staging)
    if not stagings:
        raise ValueError('captured traffic is empty')
    if len(stagings) == 1:
        return stagings[0]
    import jax

    return jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *stagings)


def replay_probs(model: Any, batch: ActionBatch) -> Dict[str, np.ndarray]:
    """Per-head probability tensors ``(G, A)`` of one model on one batch.

    Deliberately the *same* path for every model under comparison:
    each head's own reference representation over one shared batch
    (device MLPs read the materialized feature tensor, sequence heads
    read the packed game states, tree heads go through their host
    predictors). Values on padding rows are garbage by contract —
    callers mask with ``batch.mask``. The feature tensor is only
    materialized when some head actually consumes it — an all-sequence
    model replays straight from the packed representation.
    """
    from ..seq.classifier import SeqClassifier

    need_feats = any(
        not isinstance(m, SeqClassifier) for m in model._models.values()
    )
    feats = model.compute_features_batch(batch) if need_feats else None
    probs = model._estimate_probabilities_batch(feats, batch=batch)
    return {col: np.asarray(p) for col, p in probs.items()}


@dataclass(frozen=True)
class ShadowResult:
    """One model's replay over one traffic window."""

    #: per-head calibration (key: label column, e.g. 'scores'/'concedes')
    summaries: Dict[str, CalibrationSummary]
    #: per-head raw probability tensors (masked rows included) — kept so
    #: reproducibility can be asserted bitwise, not just on summaries
    probs: Dict[str, np.ndarray] = field(repr=False, default_factory=dict)
    n_frames: int = 0
    n_actions: int = 0

    def to_dict(self) -> Dict[str, Any]:
        """JSON-ready per-head summary block (reports embed this)."""
        return {
            'n_frames': self.n_frames,
            'n_actions': self.n_actions,
            'heads': {c: s.to_dict() for c, s in self.summaries.items()},
        }


def shadow_replay(
    model: Any,
    frames: Optional[Sequence[Tuple[pd.DataFrame, Any]]] = None,
    *,
    batch: Optional[ActionBatch] = None,
    max_actions: int = 1664,
    n_bins: int = 10,
    n_boot: int = 200,
    seed: int = 0,
    ci_level: float = 0.95,
) -> ShadowResult:
    """Replay a traffic window through ``model``; calibration per head.

    Give either ``frames`` (captured ``(frame, home_team_id)`` pairs,
    packed here) or a pre-packed ``batch`` — the loop packs once and
    replays the same batch through candidate and active so both models
    see byte-identical inputs. Labels come from the model family's
    device label kernel over the same batch; padding rows carry zero
    weight.
    """
    if (frames is None) == (batch is None):
        raise ValueError('give exactly one of frames= or batch=')
    if batch is None:
        batch = pack_replay_batch(frames, max_actions=max_actions)
    n_frames = int(batch.n_games)
    n_actions = int(batch.total_actions)
    with span('learn/shadow_replay', frames=n_frames, actions=n_actions):
        probs = replay_probs(model, batch)
        tensors = model._labels_kernel(batch)
        labels = dict(zip(model._label_columns, tensors))
        weights = np.asarray(batch.mask, dtype=np.float32)
        summaries = {
            col: calibration_summary(
                probs[col], labels[col], weights,
                n_bins=n_bins, n_boot=n_boot, seed=seed, ci_level=ci_level,
            )
            for col in probs
        }
    counter('learn/replayed_actions', unit='actions').inc(n_actions)
    return ShadowResult(
        summaries=summaries, probs=probs,
        n_frames=n_frames, n_actions=n_actions,
    )
