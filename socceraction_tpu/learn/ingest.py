"""Ingest side of the continuous-learning loop: watch + extend.

Two small primitives close the gap between "matches land in the season
store" and "the training feed can stream them":

- :class:`SeasonWatcher` — tracks which stored games the loop has
  already consumed into training and reports the newly landed ones.
  ``poll()`` is read-only (a crashed iteration re-polls the same games);
  :meth:`SeasonWatcher.commit` marks games consumed once their training
  pass actually completed.
- :func:`extend_packed` — brings the season's packed memmap cache up to
  date *incrementally*: new games invalidate the cache's store
  fingerprint, but an append-only store leaves every previously packed
  row exactly right, so the rebuild seeds the new cache from the old
  one (:meth:`~socceraction_tpu.pipeline.packed.PackedSeasonWriter.seed_from`)
  and reads/packs only the games that actually landed — O(new matches)
  store IO, same atomic publish as the overlapped first build.

Contract: the store is **append-only per game** (matches land; played
matches never mutate). A pipeline that rewrites an existing game's
actions must delete the cache directory before the next loop iteration.
"""

from __future__ import annotations

import json
import os
from typing import Any, List, Optional, Sequence, Set, Tuple

from ..obs import counter
from ..pipeline.packed import (
    FAMILIES,
    PackedSeason,
    PackedSeasonWriter,
    open_packed,
    packed_cache_dir,
)
from ..pipeline.store import SeasonStore

__all__ = ['SeasonWatcher', 'extend_packed', 'newest_game_ids']


def newest_game_ids(game_ids: Sequence[Any], n: int) -> List[Any]:
    """The ``n`` most recently assigned game ids of a listing.

    ``SeasonStore.game_ids()`` is ordered by *key string*, which sorts
    lexicographically (``game_9999`` after ``game_10000``) — taking its
    tail would return stale games once ids grow a digit. Providers
    assign increasing numeric ids, so "newest" is the largest ids under
    numeric-aware order; non-numeric ids sort after numeric ones by
    their string form (a deterministic, if arbitrary, recency proxy).
    """
    def key(gid: Any):
        s = str(gid)
        if s.lstrip('-').isdigit():
            return (0, int(s), '')
        return (1, 0, s)

    return sorted(game_ids, key=key)[-max(0, int(n)):] if n > 0 else []


class SeasonWatcher:
    """Tracks which stored games the learning loop has consumed.

    Parameters
    ----------
    store : SeasonStore
        The season store new matches land in.
    prime : bool
        ``True`` marks every game already present at construction as
        consumed — the posture of a loop attached to an already-trained
        serving model. ``False`` (default) treats the whole store as new,
        so the first iteration is the bootstrap fit.
    """

    def __init__(self, store: SeasonStore, *, prime: bool = False) -> None:
        self.store = store
        self._seen: Set[Any] = set(store.game_ids()) if prime else set()

    @property
    def seen(self) -> Set[Any]:
        """Game ids already consumed (a copy)."""
        return set(self._seen)

    def poll(self) -> List[Any]:
        """Newly landed game ids, in store order. Read-only: polling does
        NOT consume — call :meth:`commit` once training over them
        succeeded, so a crashed iteration retries the same games."""
        return [g for g in self.store.game_ids() if g not in self._seen]

    def commit(self, game_ids: Sequence[Any]) -> None:
        """Mark ``game_ids`` as consumed into training."""
        self._seen.update(game_ids)


def extend_packed(
    store: SeasonStore,
    *,
    max_actions: int,
    float_dtype: Any = 'float32',
    cache_dir: Optional[str] = None,
    family: str = 'standard',
    build_chunk: int = 256,
) -> Tuple[PackedSeason, int, int]:
    """Bring the packed cache up to date; returns ``(season, reused, packed)``.

    A valid cache returns immediately (``reused == n_games``,
    ``packed == 0``). Otherwise a new build starts and, when the stale
    cache on disk matches this build's family/shape/dtype, every game it
    already packed is copied memmap→memmap
    (:meth:`~socceraction_tpu.pipeline.packed.PackedSeasonWriter.seed_from`)
    before a :meth:`write_missing` pass reads **only the remaining
    games** from the store. The publish is the writer's usual atomic
    rename, so readers always see either the old complete cache or the
    new complete cache.

    ``reused``/``packed`` count games served from the old cache vs.
    freshly read from the store — the loop reports them under
    ``learn/cache_games{source=reused|packed}``.
    """
    fam = FAMILIES[family]
    cache_dir = cache_dir or packed_cache_dir(
        store.path, max_actions, float_dtype, family
    )
    season = open_packed(
        store,
        max_actions=max_actions,
        float_dtype=float_dtype,
        cache_dir=cache_dir,
        family=family,
    )
    if season is not None:
        return season, len(season.game_ids), 0

    # a stale-but-shaped cache is the incremental seed; anything else
    # (absent, torn, other family/shape/dtype) means a cold build
    old: Optional[PackedSeason] = None
    if os.path.isdir(cache_dir):
        try:
            cand = PackedSeason(cache_dir)
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            cand = None
        if cand is not None and cand.family.name == fam.name:
            old = cand

    writer = PackedSeasonWriter(
        store,
        max_actions=max_actions,
        float_dtype=float_dtype,
        cache_dir=cache_dir,
        family=family,
    )
    try:
        reused = writer.seed_from(old) if old is not None else 0
        writer.write_missing(store, build_chunk=build_chunk)
        season = writer.finalize()
    except BaseException:
        writer.abort()
        raise
    packed = len(writer.game_ids) - reused
    counter('learn/cache_games', unit='count').inc(reused, source='reused')
    counter('learn/cache_games', unit='count').inc(packed, source='packed')
    return season, reused, packed
