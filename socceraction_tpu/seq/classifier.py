"""The GRU sequence head as a trainable binary classifier.

``SeqClassifier`` is the second head architecture behind the VAEP
probability interface: same labels, same packed
:class:`~socceraction_tpu.ops.fused.TrainStates` input, same
one-dispatch-per-epoch training discipline — a different function of
the window. It deliberately does **not** subclass
:class:`~socceraction_tpu.ml.mlp.MLPClassifier` (the fused serving
fold's ``isinstance`` dispatch must keep meaning "an MLP head"); the
pieces that are genuinely architecture-agnostic — the scan-epoch fit
loop, the training-health verdict, the cached standardization stats —
are shared as unbound functions instead, so there is exactly one
implementation of each and the seq head inherits every fix for free.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ml.mlp import MLPClassifier, _weighted_bce
from ..obs import counter, histogram
from .model import init_seq_params, seq_param_shapes, seq_train_logits

__all__ = ['SeqClassifier', 'SEQ_FORMAT_VERSION']

#: Version stamped into :meth:`SeqClassifier.save` artifacts — the seq
#: head's own lineage, independent of ``MLP_FORMAT_VERSION`` (the two
#: artifact layouts evolve separately). :meth:`SeqClassifier.load`
#: rejects artifacts stamped NEWER than this with an actionable error,
#: the same contract the model registry relies on for MLP heads.
SEQ_FORMAT_VERSION = 1


class SeqClassifier:
    """Binary classifier: GRU over the k-action window -> sigmoid.

    Parameters
    ----------
    embed_dim : int
        Width of the combined-id token embedding (the
        ``(combo_size, E)`` table trained through
        :func:`~socceraction_tpu.ops.fused.table_lookup`).
    hidden : int
        GRU hidden-state width.
    readout : int
        Width of the dense-conditioned readout layer.
    learning_rate, batch_size, max_epochs, patience, pos_weight, seed
        Training protocol knobs, identical in meaning to
        :class:`~socceraction_tpu.ml.mlp.MLPClassifier`.
    """

    def __init__(
        self,
        embed_dim: int = 32,
        hidden: int = 64,
        readout: int = 64,
        learning_rate: float = 1e-3,
        batch_size: int = 8192,
        max_epochs: int = 50,
        patience: int = 5,
        pos_weight: float = 1.0,
        seed: int = 0,
    ) -> None:
        self.embed_dim = int(embed_dim)
        self.hidden = int(hidden)
        self.readout = int(readout)
        self.learning_rate = learning_rate
        self.batch_size = batch_size
        self.max_epochs = max_epochs
        self.patience = patience
        self.pos_weight = pos_weight
        self.seed = seed
        self.params: Any = None
        self._mean: Optional[np.ndarray] = None
        self._std: Optional[np.ndarray] = None
        self._mean_dev: Any = None
        self._std_dev: Any = None
        #: epoch-function retrace count of the last fit (1 == the epoch
        #: compiled once and was reused across every epoch) — the same
        #: pin the MLP carries; ``tests/test_seq.py`` asserts it
        self.n_epoch_traces_: int = 0
        #: adam state matching :attr:`params` (see the MLP's docs); the
        #: continuous-learning loop transplants it across warm starts
        self.opt_state_: Any = None
        #: training-health verdict of the last fit (same schema as the
        #: MLP's) — the learn loop's divergence rejection reads it
        #: through the identical attribute, so a diverging seq candidate
        #: is fail-closed rejected by the same gate
        self.train_health_: Optional[Dict[str, Any]] = None

    # -- shared machinery (unbound reuse, NOT subclassing) ------------------
    # These are attribute-generic: they only touch hyperparameters and
    # fitted-state attributes both classes define. Sharing the function
    # objects keeps one implementation of the epoch loop and the health
    # verdict without making a SeqClassifier satisfy
    # ``isinstance(x, MLPClassifier)`` (which gates the fused MLP fold).
    mean_ = MLPClassifier.mean_
    std_ = MLPClassifier.std_
    _device_stats = MLPClassifier._device_stats
    _fit_loop = MLPClassifier._fit_loop
    _record_train_health = MLPClassifier._record_train_health
    _resolve_states = staticmethod(MLPClassifier._resolve_states)

    # -- training -----------------------------------------------------------

    def _layout_dims(self, layout: Any) -> Tuple[int, int]:
        """``(combo_size, n_dense)`` of a layout — the init-shape inputs."""
        from ..ops.fused import REGISTRIES

        registry = REGISTRIES[layout.registry_name]
        n_dense = sum(
            w for _n, kind, _o, w in layout.spans if kind == 'dense'
        )
        return int(registry.combo_size), int(n_dense)

    def _init_params(self, layout: Any) -> Dict[str, Any]:
        combo_size, n_dense = self._layout_dims(layout)
        return init_seq_params(
            self.seed,
            combo_size=combo_size,
            n_dense=n_dense,
            embed_dim=self.embed_dim,
            hidden=self.hidden,
            readout=self.readout,
        )

    def _check_init_params(
        self, init_params: Any, layout: Any
    ) -> Dict[str, Any]:
        """Validate + deep-copy a warm-start pytree (donation safety).

        Same contract as the MLP's ``_check_init_params``: structure and
        leaf shapes must match a fresh init for this architecture and
        layout (an abstract template — nothing is allocated), and the
        copy is mandatory because the epoch dispatch donates its
        parameter buffers.
        """
        combo_size, n_dense = self._layout_dims(layout)
        template = seq_param_shapes(
            combo_size=combo_size,
            n_dense=n_dense,
            embed_dim=self.embed_dim,
            hidden=self.hidden,
            readout=self.readout,
        )
        t_struct = jax.tree.structure(template)
        i_struct = jax.tree.structure(init_params)
        if t_struct != i_struct:
            raise ValueError(
                f'init_params tree structure {i_struct} does not match '
                f'this classifier (embed_dim={self.embed_dim}, '
                f'hidden={self.hidden}, readout={self.readout}): {t_struct}'
            )
        t_shapes = [jnp.shape(leaf) for leaf in jax.tree.leaves(template)]
        i_shapes = [jnp.shape(leaf) for leaf in jax.tree.leaves(init_params)]
        if t_shapes != i_shapes:
            raise ValueError(
                f'init_params leaf shapes {i_shapes} do not match the '
                f'feature layout / architecture ({t_shapes}); warm starts '
                'require an unchanged layout'
            )
        return jax.tree.map(lambda a: jnp.array(a, jnp.float32), init_params)

    def fit_packed(
        self,
        batch: Any,
        y: Any,
        *,
        names: Tuple[str, ...],
        k: int,
        registry: str = 'standard',
        eval_set: Optional[Tuple[Any, Any]] = None,
        mean: Optional[Any] = None,
        std: Optional[Any] = None,
        path: str = 'seq',
        init_params: Any = None,
        init_opt_state: Any = None,
    ) -> 'SeqClassifier':
        """Train the GRU head on packed game states — same entry as the MLP.

        Identical signature and protocol to
        :meth:`~socceraction_tpu.ml.mlp.MLPClassifier.fit_packed` (the
        learner registry depends on that): packed batch or precomputed
        ``(TrainStates, TrainLayout)``, full-column statistics (computed
        from the packed form when not provided — kept full-length so
        stats stay interchangeable with MLP heads across warm starts),
        early stopping on ``eval_set``, warm starts via
        ``init_params``/``init_opt_state``. Each epoch is ONE jitted
        scan dispatch (``n_epoch_traces_`` pins it).
        """
        from ..ops.fused import packed_feature_stats

        t0 = time.perf_counter()
        states, layout, _raw = self._resolve_states(
            batch, names=tuple(names), k=k, registry=registry
        )
        yd = jnp.asarray(y, dtype=jnp.float32).reshape(-1)
        if yd.shape[0] != states.weight.shape[0]:
            raise ValueError(
                f'labels have {yd.shape[0]} rows, packed states have '
                f'{states.weight.shape[0]}'
            )
        if mean is None or std is None:
            mean, raw_std = packed_feature_stats(states, layout)
            std = jnp.where(raw_std > 0, raw_std, 1.0)
        self.mean_ = np.asarray(mean)
        self.std_ = np.asarray(std)
        self._mean_dev = jnp.asarray(mean)
        self._std_dev = jnp.asarray(std)
        mean_dev, std_dev = self._device_stats()

        if init_params is None:
            params = self._init_params(layout)
        else:
            params = self._check_init_params(init_params, layout)
        pos_w = self.pos_weight

        def loss_fn(params: Any, mb: Dict[str, Any], w: jax.Array) -> jax.Array:
            logits = seq_train_logits(
                params, mb['x'], mb['ids'],
                layout=layout, mean=mean_dev, std=std_dev,
            )
            return _weighted_bce(logits, mb['y'], w * mb['w'], pos_w)

        data = {
            'x': states.x_dense,
            'ids': states.combo_ids,
            'w': states.weight,
            'y': yd,
        }
        eval_data = None
        if eval_set is not None:
            ev_states, ev_layout, _ev_batch = self._resolve_states(
                eval_set[0], names=tuple(names), k=k, registry=registry
            )
            if ev_layout.n_features != layout.n_features:
                raise ValueError('eval_set feature layout differs from train')
            ev_y = jnp.asarray(eval_set[1], dtype=jnp.float32).reshape(-1)
            eval_data = {
                'x': ev_states.x_dense,
                'ids': ev_states.combo_ids,
                'w': ev_states.weight,
                'y': ev_y,
            }

        n = int(states.weight.shape[0])
        n_valid = int(np.asarray(jnp.sum(states.weight)))
        out = self._fit_loop(
            params, data, n, loss_fn, eval_data, path=path,
            n_samples=n_valid, init_opt_state=init_opt_state,
        )
        labels = {'platform': jax.default_backend()}
        counter('seq/fits', unit='count').inc(1, **labels)
        histogram('seq/fit_seconds', unit='s').observe(
            time.perf_counter() - t0, **labels
        )
        return out

    # -- inference ----------------------------------------------------------

    def predict_proba_states(self, states: Any, layout: Any) -> jax.Array:
        """P(y=1) per packed row -> ``(N,)`` device array."""
        if self.params is None:
            raise ValueError('classifier is not fitted')
        mean_dev, std_dev = self._device_stats()
        logits = seq_train_logits(
            self.params, states.x_dense, states.combo_ids,
            layout=layout, mean=mean_dev, std=std_dev,
        )
        return jax.nn.sigmoid(logits)

    def predict_proba_device_batch(
        self,
        batch: Any,
        *,
        names: Tuple[str, ...],
        k: int,
        registry: str = 'standard',
    ) -> jax.Array:
        """P(y=1) per action of a packed batch -> ``(G, A)``.

        The reference/fallback inference path: packs the batch
        (:func:`~socceraction_tpu.ops.fused.build_train_states`) and
        runs the head on the rows — no serving fold, no pair fusion.
        ``names``/``k``/``registry`` must match the trained layout.
        """
        from ..ops.fused import build_train_states

        states, layout = build_train_states(
            batch, names=tuple(names), k=k, registry_name=registry
        )
        G, A = batch.type_id.shape
        return self.predict_proba_states(states, layout).reshape(G, A)

    # -- persistence --------------------------------------------------------

    def save(self, path: str) -> None:
        """Save the fitted head to one ``.npz`` file (msgpack params).

        Same artifact discipline as the MLP: parameter pytree as
        msgpack bytes, full-column standardization statistics, the
        hyperparameters, and a format-version stamp the loader checks
        first. No optimizer state (in-process only, by contract).
        """
        import json

        from flax import serialization

        if self.params is None:
            raise ValueError('cannot save an unfitted classifier')
        hyper: Dict[str, Any] = {
            'embed_dim': self.embed_dim,
            'hidden': self.hidden,
            'readout': self.readout,
            'learning_rate': self.learning_rate,
            'batch_size': self.batch_size,
            'max_epochs': self.max_epochs,
            'patience': self.patience,
            'pos_weight': self.pos_weight,
            'seed': self.seed,
        }
        host_params = jax.tree.map(
            lambda a: np.asarray(a, dtype=np.float32), self.params
        )
        with open(path, 'wb') as f:
            np.savez(
                f,
                format_version=np.array(SEQ_FORMAT_VERSION),
                seq_params_msgpack=np.frombuffer(
                    serialization.msgpack_serialize(host_params),
                    dtype=np.uint8,
                ),
                mean=self.mean_,
                std=self.std_,
                hyper_json=np.array(json.dumps(hyper)),
            )

    @classmethod
    def load(cls, path: str) -> 'SeqClassifier':
        """Load a head saved with :meth:`save` (corruption -> ValueError).

        The ``seq_params_msgpack`` key doubles as the artifact's kind
        marker: an MLP artifact handed to this loader fails with the
        corrupt-artifact error instead of deserializing garbage.
        """
        import json
        import zipfile

        from flax import serialization

        try:
            with np.load(path, allow_pickle=False) as data:
                version = (
                    int(data['format_version'])
                    if 'format_version' in data
                    else 1
                )
                if version > SEQ_FORMAT_VERSION:
                    raise ValueError(
                        f'checkpoint at {path!r} has '
                        f'format_version={version}, newer than this '
                        f'library understands (<= {SEQ_FORMAT_VERSION}); '
                        'upgrade socceraction_tpu to load it'
                    )
                hyper = json.loads(str(data['hyper_json']))
                mean = data['mean']
                std = data['std']
                raw = data['seq_params_msgpack'].tobytes()
        except (
            zipfile.BadZipFile,
            EOFError,
            KeyError,
            json.JSONDecodeError,
        ) as e:
            raise ValueError(
                f'checkpoint artifact corrupt: {path!r} failed to parse '
                f'as a seq checkpoint ({type(e).__name__}: {e}); the '
                'file is truncated, damaged or not a save() artifact'
            ) from e
        clf = cls(**hyper)
        clf.mean_ = mean.astype(np.float32)
        clf.std_ = std.astype(np.float32)
        clf.params = serialization.msgpack_restore(raw)
        return clf
