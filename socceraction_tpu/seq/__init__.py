"""Sequence-model valuation: a GRU head over the k-action window.

The second head architecture behind the VAEP probability interface
(arXiv 2106.01786's deep-sequence direction on this repo's packed
pipeline): token embedding through the fused combined-id machinery, a
small unrolled GRU, a dense-conditioned readout. Train through
``VAEP.fit_packed(learner='seq')``; serve through the standard
``RatingService`` ladder, padded in time as well as batch
(``core.batch.bucket_window``). ``docs/sequence.md`` is the narrative
entry point.
"""

from .classifier import SEQ_FORMAT_VERSION as SEQ_FORMAT_VERSION
from .classifier import SeqClassifier as SeqClassifier
from .model import dense_stats as dense_stats
from .model import init_seq_params as init_seq_params
from .model import seq_logits as seq_logits
from .model import seq_pair_probs as seq_pair_probs
from .model import seq_param_shapes as seq_param_shapes
from .model import seq_train_logits as seq_train_logits

__all__ = [
    'SEQ_FORMAT_VERSION',
    'SeqClassifier',
    'dense_stats',
    'init_seq_params',
    'seq_logits',
    'seq_pair_probs',
    'seq_param_shapes',
    'seq_train_logits',
]
