"""The GRU sequence head: compute kernels over packed game states.

*What Happened Next?* (arXiv 2106.01786) shows that a deep sequence
model over the raw action stream credits **defensive and off-ball
value** the hand-crafted VAEP features structurally cannot express: the
model sees the k-action window as an ordered sequence and learns what a
state is worth from how such sequences tend to continue, rather than
from per-state aggregate columns alone.

Architecture choice — a small **GRU**, not a causal transformer
(``docs/sequence.md`` carries the full rationale):

- the window is short (``k`` = 3..8 actions): a fixed-depth unrolled
  recurrence is a handful of ``(E, H)``/``(H, H)`` matmuls — pure MXU
  work with no attention masks, no positional encodings and no
  ``O(k^2)`` score tensor that would be all padding at these lengths;
- parameter count is independent of the window length, so one
  checkpoint serves every window rung of the serving ladder;
- the unrolled loop is shape-stable: every serving bucket compiles to
  the same fixed sequence of dense ops, which is what keeps the
  zero-steady-state-retrace contract cheap to uphold.

The embedding layer IS the fused machinery: each game state already has
a combined categorical id (:mod:`socceraction_tpu.ops.fused`), so the
token embedding is one :func:`~socceraction_tpu.ops.fused.table_lookup`
over a ``(combo_size, E)`` table — the same custom-VJP gather the fused
MLP trains through, whose backward lowers to the MXU one-hot
segment-sum (:mod:`socceraction_tpu.ops.segment`) unchanged: the
``(N, k, E)`` cotangent and ``(N, k)`` id matrix flatten to rows
exactly like the MLP's per-state gathers.

Dense feature columns (the continuous ~10% of the layout) enter at the
**readout**: they are per-state window aggregates already, so they
condition the final value estimate rather than being forced through the
recurrence as pseudo-tokens.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..obs.xla import instrument_jit
from ..ops.fused import REGISTRIES, TrainLayout, table_lookup

__all__ = [
    'init_seq_params',
    'seq_param_shapes',
    'dense_stats',
    'seq_logits',
    'seq_train_logits',
    'seq_pair_probs',
]


def seq_param_shapes(
    *,
    combo_size: int,
    n_dense: int,
    embed_dim: int,
    hidden: int,
    readout: int,
) -> Dict[str, Any]:
    """Abstract f32 shapes of a seq parameter pytree (for validation).

    The same structure :func:`init_seq_params` returns, as
    ``ShapeDtypeStruct`` leaves — warm-start validation compares against
    this without allocating or running the PRNG.
    """
    f32 = jnp.float32

    def s(*shape: int) -> jax.ShapeDtypeStruct:
        return jax.ShapeDtypeStruct(shape, f32)

    return {
        'embed': s(combo_size, embed_dim),
        'gru': {
            'wz': s(embed_dim, hidden), 'uz': s(hidden, hidden), 'bz': s(hidden),
            'wr': s(embed_dim, hidden), 'ur': s(hidden, hidden), 'br': s(hidden),
            'wh': s(embed_dim, hidden), 'uh': s(hidden, hidden), 'bh': s(hidden),
        },
        'readout': {
            'w1': s(hidden + n_dense, readout),
            'b1': s(readout),
            'w2': s(readout),
            'b2': s(),
        },
    }


def init_seq_params(
    seed: int,
    *,
    combo_size: int,
    n_dense: int,
    embed_dim: int,
    hidden: int,
    readout: int,
) -> Dict[str, Any]:
    """Initialize a GRU head's parameter pytree (plain nested dict).

    Variance-scaling normal init (LeCun: ``std = 1/sqrt(fan_in)``) on
    every kernel, zeros on biases — the same family flax's ``Dense``
    default draws from, kept explicit because this pytree is not a flax
    module (no ``apply``-time machinery is needed; the forward is a
    fixed unrolled recurrence).
    """
    shapes = seq_param_shapes(
        combo_size=combo_size, n_dense=n_dense,
        embed_dim=embed_dim, hidden=hidden, readout=readout,
    )
    rng = jax.random.fold_in(jax.random.PRNGKey(seed), 2**31 - 2)
    leaves, treedef = jax.tree.flatten(shapes)
    keys = jax.random.split(rng, len(leaves))

    def draw(key: jax.Array, tpl: jax.ShapeDtypeStruct) -> jax.Array:
        if len(tpl.shape) < 2:
            return jnp.zeros(tpl.shape, tpl.dtype)  # biases (and w2/b2)
        fan_in = tpl.shape[0]
        scale = 1.0 / jnp.sqrt(jnp.asarray(float(max(fan_in, 1))))
        return (jax.random.normal(key, tpl.shape, tpl.dtype) * scale)

    params = jax.tree.unflatten(
        treedef, [draw(k, t) for k, t in zip(keys, leaves)]
    )
    # w2 is rank-1 but is a kernel, not a bias: give it a scaled draw too
    k2 = jax.random.fold_in(rng, 7)
    params['readout']['w2'] = jax.random.normal(
        k2, shapes['readout']['w2'].shape, jnp.float32
    ) / jnp.sqrt(jnp.asarray(float(max(shapes['readout']['w2'].shape[0], 1))))
    return params


def dense_stats(
    mean: jax.Array, std: jax.Array, layout: TrainLayout
) -> Tuple[jax.Array, jax.Array]:
    """Slice full-column ``(mean, std)`` down to the dense sub-columns.

    The fit path computes statistics over the FULL feature columns
    (:func:`~socceraction_tpu.ops.fused.packed_feature_stats`) so
    warm-start stat reuse stays layout-shaped and arch-agnostic; the seq
    head standardizes only the dense sub-tensor it consumes. ``layout``
    is static, so the slices are trace-time constants.
    """
    means = []
    stds = []
    for _name, kind, off, width in layout.spans:
        if kind == 'dense':
            means.append(mean[off : off + width])
            stds.append(std[off : off + width])
    if not means:
        z = jnp.zeros((0,), jnp.float32)
        return z, jnp.ones((0,), jnp.float32)
    return jnp.concatenate(means), jnp.concatenate(stds)


def _gru_pass(params: Dict[str, Any], emb: jax.Array) -> jax.Array:
    """Run the unrolled GRU oldest-to-newest over ``(N, k, E)`` tokens.

    Token ``i`` of a state window is the action ``i`` steps back
    (``i == 0`` is the action being valued), so the recurrence consumes
    ``i = k-1 .. 0``: the hidden state accumulates context forward in
    match time and ends on the current action. ``k`` is a static shape,
    so the loop unrolls into ``k`` fixed MXU matmul groups.
    """
    g = params['gru']
    n, k, _e = emb.shape
    h = jnp.zeros((n, g['uz'].shape[0]), emb.dtype)
    for i in range(k - 1, -1, -1):
        x = emb[:, i, :]
        z = jax.nn.sigmoid(x @ g['wz'] + h @ g['uz'] + g['bz'])
        r = jax.nn.sigmoid(x @ g['wr'] + h @ g['ur'] + g['br'])
        hh = jnp.tanh(x @ g['wh'] + (r * h) @ g['uh'] + g['bh'])
        h = (1.0 - z) * h + z * hh
    return h


def seq_logits(
    params: Dict[str, Any],
    x_dense: jax.Array,
    combo_ids: jax.Array,
    *,
    dense_mean: jax.Array,
    dense_std: jax.Array,
) -> jax.Array:
    """Differentiable GRU-head logits over packed rows -> ``(N,)``.

    One :func:`~socceraction_tpu.ops.fused.table_lookup` embeds the
    whole ``(N, k)`` id matrix at once — forward a single gather,
    backward a single MXU segment-sum over ``N * k`` rows — then the
    unrolled GRU runs oldest-to-newest and the readout conditions the
    final hidden state on the standardized dense sub-columns.
    """
    embed = params['embed']
    emb = table_lookup(embed, combo_ids, int(embed.shape[0]))
    h = _gru_pass(params, emb)
    dn = (x_dense - dense_mean) / dense_std
    cat = jnp.concatenate([h, dn.astype(h.dtype)], axis=-1)
    ro = params['readout']
    r1 = jax.nn.relu(cat @ ro['w1'] + ro['b1'])
    return r1 @ ro['w2'] + ro['b2']


def seq_train_logits(
    params: Dict[str, Any],
    x_dense: jax.Array,
    combo_ids: jax.Array,
    *,
    layout: TrainLayout,
    mean: jax.Array,
    std: jax.Array,
) -> jax.Array:
    """Training-path logits from full-column statistics -> ``(N,)``.

    The signature mirror of
    :func:`~socceraction_tpu.ops.fused.fused_train_logits`: callers hold
    layout-shaped ``mean``/``std`` (so stats stay interchangeable with
    the MLP's) and this wrapper slices the dense sub-columns before the
    shared forward. Validates the parameter/layout agreement up front —
    a silent mismatch would train a corrupted head.
    """
    registry = REGISTRIES[layout.registry_name]
    combo_size = int(params['embed'].shape[0])
    if combo_size != registry.combo_size:
        raise ValueError(
            f'embedding table has {combo_size} rows but registry '
            f'{layout.registry_name!r} has combo_size={registry.combo_size}'
        )
    n_dense = sum(w for _n, kind, _o, w in layout.spans if kind == 'dense')
    hidden = int(params['gru']['uz'].shape[0])
    w1_rows = int(params['readout']['w1'].shape[0])
    if w1_rows != hidden + n_dense:
        raise ValueError(
            f'readout expects {w1_rows} inputs but hidden={hidden} plus '
            f'the layout dense width {n_dense} gives {hidden + n_dense}'
        )
    dm, ds = dense_stats(mean, std, layout)
    return seq_logits(
        params, x_dense, combo_ids, dense_mean=dm, dense_std=ds
    )


@functools.partial(
    instrument_jit, name='seq_pair_probs',
    static_argnames=('names', 'k', 'registry_name'),
)
def _seq_pair_fn(
    params_a: Dict[str, Any],
    params_b: Dict[str, Any],
    stats_a: Tuple[jax.Array, jax.Array],
    stats_b: Tuple[jax.Array, jax.Array],
    batch: Any,
    overrides: Optional[Dict[str, jax.Array]],
    *,
    names: Tuple[str, ...],
    k: int,
    registry_name: str,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Both heads' probabilities over a batch in ONE jitted dispatch.

    Mirrors ``ops.fused._train_states_arrays``' packing (the dense
    kernels and the combined-id gathers run once, shared by both heads)
    with the serving layer's ``dense_overrides`` substitution: an
    override replaces a named dense kernel's block wholesale — the
    whole-match ``goalscore`` injection for suffix windows rides through
    here exactly like the fused MLP path. Returns a nonfinite count as a
    device scalar alongside the probabilities (drained by the caller
    into the numerics guard surface, no sync here).
    """
    registry = REGISTRIES[registry_name]
    s = registry.make_states(batch, k)
    G, A = batch.type_id.shape
    n = G * A
    dense_blocks = []
    for name in names:
        if name in registry.onehot_specs:
            continue
        if overrides is not None and name in overrides:
            dense_blocks.append(jnp.asarray(overrides[name]))
        else:
            dense_blocks.append(registry.kernels[name](s))
    x_dense = (
        jnp.concatenate(dense_blocks, axis=-1).reshape(n, -1).astype(jnp.float32)
        if dense_blocks
        else jnp.zeros((n, 0), jnp.float32)
    )
    ids = jnp.stack(
        [registry.combo_ids(s, i).reshape(n) for i in range(k)], axis=1
    ).astype(jnp.int32)
    pa = jax.nn.sigmoid(
        seq_logits(
            params_a, x_dense, ids,
            dense_mean=stats_a[0], dense_std=stats_a[1],
        )
    ).reshape(G, A)
    pb = jax.nn.sigmoid(
        seq_logits(
            params_b, x_dense, ids,
            dense_mean=stats_b[0], dense_std=stats_b[1],
        )
    ).reshape(G, A)
    bad = jnp.sum(~jnp.isfinite(pa)) + jnp.sum(~jnp.isfinite(pb))
    return pa, pb, bad


def seq_pair_probs(
    clf_a: Any,
    clf_b: Any,
    batch: Any,
    *,
    names: Tuple[str, ...],
    k: int,
    registry_name: str = 'standard',
    dense_overrides: Optional[Dict[str, jax.Array]] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Probabilities of two GRU heads in one jitted call -> ``((G,A), (G,A))``.

    The seq analog of :func:`~socceraction_tpu.ops.fused.fused_pair_probs`
    — ``VAEP.rate_batch`` rates a scores and a concedes head over the
    same batch, and the packing work (dense kernels, id gathers) is
    shared between them inside one dispatch. The heads'
    standardization constants come from their cached device stats, and
    the dense sub-slices are trace-time constants of the static layout.
    """
    from ..obs import numerics
    from ..ops.fused import train_layout

    layout = train_layout(
        batch, names=tuple(names), k=k, registry_name=registry_name
    )
    mean_a, std_a = clf_a._device_stats()
    mean_b, std_b = clf_b._device_stats()
    pa, pb, bad = _seq_pair_fn(
        clf_a.params,
        clf_b.params,
        dense_stats(mean_a, std_a, layout),
        dense_stats(mean_b, std_b, layout),
        batch,
        dense_overrides or None,
        names=tuple(names),
        k=k,
        registry_name=registry_name,
    )
    numerics.note_guard('seq_pair_probs', 'probs', bad)
    return pa, pb
