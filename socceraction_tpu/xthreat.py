"""The Expected Threat (xT) model.

xT values ball-progressing actions as the difference in long-term scoring
probability between an action's start and end cell of an ``M x N`` pitch
grid, where the value surface solves a Markov possession model by value
iteration (Karun Singh, 2019).

API parity: reference ``socceraction/xthreat.py`` (``ExpectedThreat`` class
with ``fit``/``rate``/``save_model``; module-level ``scoring_prob``,
``action_prob``, ``move_transition_matrix``, ``get_move_actions``,
``get_successful_move_actions``, ``load_model``). Two execution backends:

- ``backend='pandas'``: a vectorized numpy oracle with the reference's exact
  semantics (bincount scatters stand in for ``value_counts``; the value
  iteration is the same mat-vec the reference's quadruple Python loop
  computes, reference ``xthreat.py:306-312``).
- ``backend='jax'`` (default): packs actions into an
  :class:`~socceraction_tpu.core.batch.ActionBatch` and runs the kernels in
  :mod:`socceraction_tpu.ops.xt` -- scatter-add count matrices and a
  ``lax.while_loop`` value iteration, one MXU mat-vec per sweep.

The count matrices are additive across game shards, so the JAX path scales
to multi-chip by psum-reducing :class:`~socceraction_tpu.ops.xt.XTCounts`.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import numpy as np
import pandas as pd

from .obs import gauge, histogram, span
from .obs.perf import record_dispatch
from .obs.residency import claim_bytes
from .spadl import config as spadlconfig

try:  # pragma: no cover - import guard mirrors optional-dependency handling
    import jax
    import jax.numpy as jnp

    from .core.batch import ActionBatch, pack_actions, pack_row_values
    from .ops import xt as _xtops

    _HAS_JAX = True
except ImportError:  # pragma: no cover
    _HAS_JAX = False


class NotFittedError(ValueError):
    """Raised when ``rate``/``save_model`` is called before ``fit``."""


M: int = 12
N: int = 16

Actions = Union[pd.DataFrame, 'ActionBatch']

#: ``group_by`` spec: a frame column name or a per-action key array.
GroupBy = Union[str, Sequence[Any], np.ndarray]


# ---------------------------------------------------------------------------
# Functional numpy oracle (reference xthreat.py:25-218 semantics)
# ---------------------------------------------------------------------------


def _get_cell_indexes(
    x: np.ndarray, y: np.ndarray, l: int = N, w: int = M
) -> Tuple[np.ndarray, np.ndarray]:
    """Bin coordinates: truncate toward zero, clip into the grid."""
    xi = np.asarray(x, dtype=np.float64) / spadlconfig.field_length * l
    yj = np.asarray(y, dtype=np.float64) / spadlconfig.field_width * w
    xi = np.clip(xi.astype(np.int64), 0, l - 1)
    yj = np.clip(yj.astype(np.int64), 0, w - 1)
    return xi, yj


def _get_flat_indexes(x: np.ndarray, y: np.ndarray, l: int = N, w: int = M) -> np.ndarray:
    xi, yj = _get_cell_indexes(x, y, l, w)
    return (w - 1 - yj) * l + xi


def _count(x: np.ndarray, y: np.ndarray, l: int = N, w: int = M) -> np.ndarray:
    """Count actions per grid cell (top-left origin ``(w, l)`` matrix)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    ok = ~np.isnan(x) & ~np.isnan(y)
    flat = _get_flat_indexes(x[ok], y[ok], l, w)
    return np.bincount(flat, minlength=w * l).astype(np.float64).reshape(w, l)


def _preview_keys(keys: Any, limit: int = 8) -> str:
    """A bounded, readable preview of a grouped fit's key set for errors."""
    items = list(keys)
    shown = ', '.join(repr(k) for k in items[:limit])
    if len(items) > limit:
        shown += f', ... ({len(items) - limit} more)'
    return f'[{shown}]'


def _safe_divide(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return np.divide(a, b, out=np.zeros_like(a, dtype=np.float64), where=b != 0)


def scoring_prob(actions: pd.DataFrame, l: int = N, w: int = M) -> np.ndarray:
    """P(goal | shot from cell) for each grid cell."""
    shots = actions[actions['type_id'] == spadlconfig.SHOT]
    goals = shots[shots['result_id'] == spadlconfig.SUCCESS]
    shotmatrix = _count(shots['start_x'].to_numpy(), shots['start_y'].to_numpy(), l, w)
    goalmatrix = _count(goals['start_x'].to_numpy(), goals['start_y'].to_numpy(), l, w)
    return _safe_divide(goalmatrix, shotmatrix)


def get_move_actions(actions: pd.DataFrame) -> pd.DataFrame:
    """All ball-progressing actions: passes, dribbles and crosses."""
    t = actions['type_id']
    return actions[
        (t == spadlconfig.PASS) | (t == spadlconfig.DRIBBLE) | (t == spadlconfig.CROSS)
    ]


def get_successful_move_actions(actions: pd.DataFrame) -> pd.DataFrame:
    """All successful ball-progressing actions."""
    moves = get_move_actions(actions)
    return moves[moves['result_id'] == spadlconfig.SUCCESS]


def action_prob(
    actions: pd.DataFrame, l: int = N, w: int = M
) -> Tuple[np.ndarray, np.ndarray]:
    """P(choose shot) and P(choose move) for each grid cell."""
    moves = get_move_actions(actions)
    shots = actions[actions['type_id'] == spadlconfig.SHOT]
    movematrix = _count(moves['start_x'].to_numpy(), moves['start_y'].to_numpy(), l, w)
    shotmatrix = _count(shots['start_x'].to_numpy(), shots['start_y'].to_numpy(), l, w)
    total = movematrix + shotmatrix
    return _safe_divide(shotmatrix, total), _safe_divide(movematrix, total)


def _successful_move_pairs(
    actions: pd.DataFrame, l: int, w: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """``(start_counts, pair_start, pair_end)`` of the move stream.

    The single source of the parity-critical NaN-mask / flat-index /
    normalization semantics for the pandas backend (shared by the dense
    transition-matrix build and the matrix-free sweeps). Moves with NaN
    coordinates are excluded (consistent with ``_count``'s NaN filter; the
    reference's float->int cast on NaN here is undefined behavior that we
    do not reproduce). ``start_counts`` counts *all* valid-start moves,
    successful or not, like reference ``xthreat.py:206-216``; the pairs
    cover only successful moves with valid end points.
    """
    moves = get_move_actions(actions)
    sx = moves['start_x'].to_numpy(dtype=np.float64)
    sy = moves['start_y'].to_numpy(dtype=np.float64)
    ex = moves['end_x'].to_numpy(dtype=np.float64)
    ey = moves['end_y'].to_numpy(dtype=np.float64)
    start_ok = ~np.isnan(sx) & ~np.isnan(sy)
    end_ok = start_ok & ~np.isnan(ex) & ~np.isnan(ey)
    success = (moves['result_id'] == spadlconfig.SUCCESS).to_numpy() & end_ok

    start = _get_flat_indexes(sx[start_ok], sy[start_ok], l, w)
    start_counts = np.bincount(start, minlength=w * l).astype(np.float64)
    pair_start = _get_flat_indexes(sx[success], sy[success], l, w)
    pair_end = _get_flat_indexes(ex[success], ey[success], l, w)
    return start_counts, pair_start, pair_end


def move_transition_matrix(actions: pd.DataFrame, l: int = N, w: int = M) -> np.ndarray:
    """P(successful move from cell i ends in cell j).

    Normalized by the count of *all* moves started in cell i (successful or
    not), like reference ``xthreat.py:206-216``.
    """
    n_cells = w * l
    start_counts, pair_start, pair_end = _successful_move_pairs(actions, l, w)
    pair = pair_start * n_cells + pair_end
    counts = np.bincount(pair, minlength=n_cells * n_cells).reshape(n_cells, n_cells)
    return _safe_divide(counts.astype(np.float64), start_counts[:, None])


#: Solver-variant names accepted by ``ExpectedThreat(variant=)`` —
#: mirrors :data:`socceraction_tpu.ops.xt.SOLVERS` (kept as a literal so
#: the pandas-only install can still validate without importing jax).
VARIANTS = ('picard', 'anderson', 'anchored', 'momentum')


def _resolve_variant(
    variant: Optional[str], accelerate: bool, backend: str, keep_heatmaps: bool
) -> str:
    """Validate + normalize the solver variant (shared by ``__init__`` and
    ``fit`` — the public attributes are mutable)."""
    if variant == 'plain':
        variant = 'picard'
    if variant is None:
        variant = 'anderson' if accelerate else 'picard'
    elif variant not in VARIANTS:
        raise ValueError(f'unknown variant {variant!r} (want one of {VARIANTS})')
    elif accelerate and variant != 'anderson':
        raise ValueError(
            "accelerate=True is a deprecated alias of variant='anderson' "
            f'and conflicts with variant={variant!r}'
        )
    if variant == 'picard':
        return variant
    if backend != 'jax':
        raise ValueError(
            f'variant={variant!r} (accelerated value iteration) is a '
            "JAX-backend feature; the pandas backend keeps the reference's "
            'plain iteration'
        )
    if keep_heatmaps:
        raise ValueError(
            'keep_heatmaps records the plain Picard iterate sequence; '
            f'{variant} iterates are a different (non-monotone) sequence'
        )
    return variant


def _pow2_bucket(n: int) -> int:
    """Round a grid count up to a power of two (the ``n_grids`` metric
    label stays cardinality-bounded at ``log2(max fleet size)`` values)."""
    return 1 << max(n - 1, 0).bit_length()


# ---------------------------------------------------------------------------
# Model class
# ---------------------------------------------------------------------------


class ExpectedThreat:
    """The Expected Threat model with selectable execution backend.

    Parameters
    ----------
    l : int
        Grid cells along the pitch length (x). Default 16.
    w : int
        Grid cells along the pitch width (y). Default 12.
    eps : float
        Convergence threshold of the value iteration. Default 1e-5.
    backend : {'jax', 'pandas'}
        Execution backend for ``fit`` and ``rate``. Default 'jax' when JAX
        is importable.
    max_iter : int
        Safety cap on value-iteration sweeps. Default 1000.
    keep_heatmaps : bool
        When True, store the value surface after every iteration in
        ``self.heatmaps`` like the reference. Implies host-stepped iteration
        on the JAX backend; leave False for large grids.
    solver : {'dense', 'matrix-free'}, optional
        ``'dense'`` materializes the ``(w*l, w*l)`` transition matrix and
        sweeps with a mat-vec; ``'matrix-free'`` sweeps with a gather +
        scatter-add over the successful-move action stream — ``O(actions)``
        per sweep and ``O(w*l)`` memory, the only tractable form for fine
        grids (192×125 ⇒ dense T is 2.3 GB fp32). Default: dense up to
        4096 cells, matrix-free beyond. ``transition_matrix`` stays ``None``
        on the matrix-free path.
    accelerate : bool
        Deprecated alias of ``variant='anderson'``.
    variant : {'picard', 'anderson', 'anchored', 'momentum'}, optional
        Value-iteration variant (``'plain'`` is accepted as an alias of
        ``'picard'``, the default). All variants share the fixed point
        and return the same convergence certificate
        (:class:`~socceraction_tpu.ops.xt.XTSolution` semantics:
        ``solve_residual`` / ``converged`` / ``n_iter``); the
        accelerated three are JAX-backend features. See ``docs/xt.md``
        for the selection guide. Orthogonal to ``solver`` — ``solver``
        picks the sweep *structure* (dense mat-vec vs matrix-free
        gather/scatter), ``variant`` picks the iteration *schedule*
        around it.
    """

    #: Cell count above which the auto solver goes matrix-free.
    DENSE_CELL_LIMIT = 4096

    def __init__(
        self,
        l: int = N,
        w: int = M,
        eps: float = 1e-5,
        backend: Optional[str] = None,
        max_iter: int = 1000,
        keep_heatmaps: bool = False,
        solver: Optional[str] = None,
        accelerate: bool = False,
        variant: Optional[str] = None,
    ) -> None:
        if backend is None:
            backend = 'jax' if _HAS_JAX else 'pandas'
        if backend not in ('jax', 'pandas'):
            raise ValueError(f'unknown backend {backend!r}')
        if backend == 'jax' and not _HAS_JAX:
            raise ImportError('JAX backend requested but jax is not importable')
        if solver is not None and solver not in ('dense', 'matrix-free'):
            raise ValueError(f'unknown solver {solver!r}')
        _resolve_variant(variant, accelerate, backend, keep_heatmaps)
        self.l = l
        self.w = w
        self.eps = eps
        self.backend = backend
        self.max_iter = max_iter
        self.keep_heatmaps = keep_heatmaps
        self._solver = solver
        self.accelerate = accelerate
        self.variant = variant
        # (keep_heatmaps + jax + matrix-free is rejected in _fit_jax: the
        # solver auto-resolution tracks w/l, which may change after
        # construction, so fit time is the only reliable point to check)
        self.n_iter: int = 0
        #: residual the solver last tested before exiting (``max(new - old)``
        #: Picard / ``max|f(x) - x|`` on the accelerated variants): ``<= eps``
        #: after a normally converged ``fit``, larger when ``max_iter`` cut
        #: the loop, ``None`` before fitting. Recorded per fit in the
        #: ``xt/solve_residual`` gauge of the telemetry registry. For a
        #: grouped fit this is the WORST grid's residual
        #: (``solve_residual_per_grid_`` has the full vector).
        self.solve_residual: Optional[float] = None
        #: ``True`` when the last fit's residual met ``eps`` (every grid,
        #: for grouped fits), ``False`` when ``max_iter`` cut the loop,
        #: ``None`` before fitting — the model-level convergence
        #: certificate flag.
        self.converged: Optional[bool] = None
        self.heatmaps: List[np.ndarray] = []
        self.xT: np.ndarray = np.zeros((w, l))
        self.scoring_prob_matrix: Optional[np.ndarray] = None
        self.shot_prob_matrix: Optional[np.ndarray] = None
        self.move_prob_matrix: Optional[np.ndarray] = None
        self.transition_matrix: Optional[np.ndarray] = None
        #: Grouped-fit state (``fit(..., group_by=)``): the ``(G, w, l)``
        #: surface stack, the sorted group keys aligned with its leading
        #: axis, the grouping column name (when a column was used), the
        #: per-grid certificate vectors, and the stacked probability
        #: matrices (``(G, w, l)``; transition ``(G, w·l, w·l)`` on the
        #: dense path, ``None`` matrix-free). The documented single-grid
        #: ``*_matrix`` slots stay ``None`` on grouped fits so 2-D
        #: consumers fail loudly rather than read a stack. All ``None``
        #: / scalar defaults for ungrouped models.
        self.grids_: Optional[np.ndarray] = None
        self.group_keys_: Optional[np.ndarray] = None
        self.group_by_: Optional[str] = None
        self.n_iter_per_grid_: Optional[np.ndarray] = None
        self.solve_residual_per_grid_: Optional[np.ndarray] = None
        self.converged_per_grid_: Optional[np.ndarray] = None
        self.scoring_prob_matrices_: Optional[np.ndarray] = None
        self.shot_prob_matrices_: Optional[np.ndarray] = None
        self.move_prob_matrices_: Optional[np.ndarray] = None
        self.transition_matrices_: Optional[np.ndarray] = None

    @property
    def solver(self) -> str:
        """Active solver: as requested, else auto by the *current* grid size.

        Auto selection tracks ``self.w``/``self.l`` so models whose grid is
        set after construction (e.g. :func:`load_model`) still pick the
        tractable solver on a later ``fit``. Grouped fits use
        :meth:`_effective_solver` instead, which folds the fleet size in.
        """
        return self._effective_solver(1)

    def _effective_solver(self, n_grids: int) -> str:
        """Auto solver with the group axis folded in.

        Dense builds an ``(G, w·l, w·l)`` transition stack, so the gate is
        memory-equivalent to the single-grid rule (``T`` entries ≤
        ``DENSE_CELL_LIMIT²``): ``G · (w·l)² ≤ DENSE_CELL_LIMIT²``. A
        ``group_by='player_id'`` fit with thousands of groups therefore
        lands on the matrix-free path automatically (which never builds
        the stack) instead of allocating gigabytes — or tripping
        ``segment_sum_2d``'s int32 flat-index guard.
        """
        if self._solver is not None:
            return self._solver
        n_cells = self.w * self.l
        dense_ok = n_grids * n_cells * n_cells <= self.DENSE_CELL_LIMIT ** 2
        return 'dense' if dense_ok else 'matrix-free'

    # -- fitting -----------------------------------------------------------

    def _value_iteration(self, sweep: Callable[[np.ndarray], np.ndarray]) -> None:
        """Iterate ``xT <- sweep(xT)`` to convergence (shared host loop)."""
        xT = np.zeros((self.w, self.l))
        if self.keep_heatmaps:
            self.heatmaps.append(xT.copy())
        it = 0
        resid = None
        while it < self.max_iter:
            new = sweep(xT)
            diff = new - xT
            xT = new
            it += 1
            resid = float(np.max(diff))
            if self.keep_heatmaps:
                self.heatmaps.append(xT.copy())
            if not np.any(diff > self.eps):
                break
        self.xT = xT
        self.n_iter = it
        self.solve_residual = resid
        self.converged = resid is not None and resid <= self.eps

    def _solve_numpy(self) -> None:
        gs = self.scoring_prob_matrix * self.shot_prob_matrix
        T = self.transition_matrix

        def sweep(xT: np.ndarray) -> np.ndarray:
            payoff = (T @ xT.reshape(-1)).reshape(self.w, self.l)
            return gs + self.move_prob_matrix * payoff

        self._value_iteration(sweep)

    def _solve_numpy_matrix_free(self, actions: pd.DataFrame) -> None:
        """Sweep by gather + weighted bincount over successful moves (no dense T)."""
        n_cells = self.w * self.l
        start_counts, pair_start, pair_end = _successful_move_pairs(
            actions, self.l, self.w
        )
        # every successful move is itself counted in start_counts, so the
        # denominator is always >= 1
        wgt = 1.0 / start_counts[pair_start]

        gs = self.scoring_prob_matrix * self.shot_prob_matrix

        def sweep(xT: np.ndarray) -> np.ndarray:
            payoff = np.bincount(
                pair_start,
                weights=xT.reshape(-1)[pair_end] * wgt,
                minlength=n_cells,
            )
            return gs + self.move_prob_matrix * payoff.reshape(self.w, self.l)

        self._value_iteration(sweep)

    def _fit_pandas(self, actions: pd.DataFrame) -> None:
        self.scoring_prob_matrix = scoring_prob(actions, self.l, self.w)
        self.shot_prob_matrix, self.move_prob_matrix = action_prob(actions, self.l, self.w)
        if self.solver == 'matrix-free':
            self.transition_matrix = None
            self._solve_numpy_matrix_free(actions)
        else:
            self.transition_matrix = move_transition_matrix(actions, self.l, self.w)
            self._solve_numpy()

    def _take_solution(self, sol: '_xtops.XTSolution') -> None:
        """Adopt a single-grid :class:`~socceraction_tpu.ops.xt.XTSolution`."""
        from .obs.numerics import record_nonfinite

        self.xT = np.asarray(sol.grid, dtype=np.float64)
        self.n_iter = int(sol.iterations)
        r = float(sol.residual)
        self.solve_residual = r if math.isfinite(r) else None
        self.converged = bool(sol.converged)
        # numeric guard on the certificate the fit already materialized
        # for its own metrics (host arrays — zero extra device work): a
        # non-finite surface or residual is counted into num/* and
        # recorded as a nonfinite_detected event
        record_nonfinite('solve_xt', 'grid', int(np.sum(~np.isfinite(self.xT))))
        record_nonfinite('solve_xt', 'residual', int(not math.isfinite(r)))

    def _fit_jax(self, batch: 'ActionBatch', variant: str) -> None:
        if self.solver == 'matrix-free':
            if self.keep_heatmaps:
                raise ValueError(
                    "keep_heatmaps on the JAX backend requires solver='dense' "
                    "(use backend='pandas' for matrix-free heatmaps)"
                )
            sol, probs = _xtops.solve_xt_matrix_free(
                batch.type_id,
                batch.result_id,
                batch.start_x,
                batch.start_y,
                batch.end_x,
                batch.end_y,
                batch.mask,
                l=self.l,
                w=self.w,
                eps=self.eps,
                max_iter=self.max_iter,
                solver=variant,
            )
            self.scoring_prob_matrix = np.asarray(probs.p_score, dtype=np.float64)
            self.shot_prob_matrix = np.asarray(probs.p_shot, dtype=np.float64)
            self.move_prob_matrix = np.asarray(probs.p_move, dtype=np.float64)
            self.transition_matrix = None
            self._take_solution(sol)
            return
        counts = _xtops.xt_counts(
            batch.type_id,
            batch.result_id,
            batch.start_x,
            batch.start_y,
            batch.end_x,
            batch.end_y,
            batch.mask,
            l=self.l,
            w=self.w,
        )
        probs = _xtops.xt_probabilities(counts, l=self.l, w=self.w)
        self.scoring_prob_matrix = np.asarray(probs.p_score, dtype=np.float64)
        self.shot_prob_matrix = np.asarray(probs.p_shot, dtype=np.float64)
        self.move_prob_matrix = np.asarray(probs.p_move, dtype=np.float64)
        self.transition_matrix = np.asarray(probs.transition, dtype=np.float64)
        if self.keep_heatmaps:
            # Host-stepped sweeps so every intermediate surface can be kept.
            self._solve_numpy()
        else:
            sol = _xtops.solve_xt(
                probs, eps=self.eps, max_iter=self.max_iter, solver=variant,
            )
            self._take_solution(sol)

    def _group_codes(self, actions: pd.DataFrame, group_by: Any) -> tuple:
        """``(codes, keys)`` for a grouped fit/rate: per-row int codes into
        the sorted unique key array (``-1`` for null keys)."""
        if isinstance(group_by, str):
            if group_by not in actions.columns:
                raise ValueError(f'group_by column {group_by!r} not in actions')
            values = actions[group_by]
        else:
            values = np.asarray(group_by)
            if len(values) != len(actions):
                raise ValueError(
                    f'group_by array has {len(values)} entries for '
                    f'{len(actions)} actions'
                )
        codes, keys = pd.factorize(values, sort=True)
        return codes.astype(np.int32), np.asarray(keys)

    def _fit_jax_grouped(
        self,
        actions: pd.DataFrame,
        codes: np.ndarray,
        keys: np.ndarray,
        group_by: Any,
        variant: str,
    ) -> None:
        """One dispatch for the whole keyed surface fleet (see ``fit``)."""
        if self.keep_heatmaps:
            raise ValueError(
                'keep_heatmaps records one plain Picard iterate sequence; '
                'a grouped fit solves a whole fleet of grids at once'
            )
        batch = self._as_batch(actions)
        group_id = jnp.asarray(pack_row_values(codes, batch, fill=-1))
        G = len(keys)
        fields = (
            batch.type_id, batch.result_id,
            batch.start_x, batch.start_y, batch.end_x, batch.end_y,
            batch.mask,
        )
        if self._effective_solver(G) == 'matrix-free':
            sol, probs = _xtops.solve_xt_matrix_free(
                *fields, l=self.l, w=self.w, eps=self.eps,
                max_iter=self.max_iter, solver=variant,
                group_id=group_id, n_groups=G,
            )
        else:
            counts = _xtops.xt_counts(
                *fields, l=self.l, w=self.w, group_id=group_id, n_groups=G
            )
            probs = _xtops.xt_probabilities(counts, l=self.l, w=self.w)
            sol = _xtops.solve_xt(
                probs, eps=self.eps, max_iter=self.max_iter, solver=variant
            )
        # HBM residency: the fleet's device stacks — (G, w·l) grids and
        # probability surfaces, plus the (G, n, n) dense transition
        # stack when one was built — are the xT layer's footprint while
        # the fit converts them to host arrays. Claimed under the
        # `xt_fleet` owner for that window and released on every exit
        # path, so `mem/owned_bytes{owner="xt_fleet"}` spikes exactly
        # while the stacks are resident.
        claim = claim_bytes('xt_fleet', (probs, sol.grid))
        try:
            self._adopt_fleet(sol, probs, keys, group_by)
        finally:
            claim.release()

    def _adopt_fleet(
        self, sol: Any, probs: Any, keys: np.ndarray, group_by: Any
    ) -> None:
        """Convert one fleet solve's device stacks into host model state."""
        self.transition_matrices_ = (
            np.asarray(probs.transition, np.float64)
            if getattr(probs, 'transition', None) is not None
            else None
        )
        # the documented single-grid probability slots keep their 2-D
        # contract: grouped stacks live in the *_matrices_ attributes and
        # the single-grid slots stay None (same decision as the zeroed
        # ``xT`` slot — existing (w, l)-shaped consumers fail loudly
        # instead of silently reading a (G, ...) stack)
        self.scoring_prob_matrix = None
        self.shot_prob_matrix = None
        self.move_prob_matrix = None
        self.transition_matrix = None
        self.scoring_prob_matrices_ = np.asarray(probs.p_score, dtype=np.float64)
        self.shot_prob_matrices_ = np.asarray(probs.p_shot, dtype=np.float64)
        self.move_prob_matrices_ = np.asarray(probs.p_move, dtype=np.float64)
        self.grids_ = np.asarray(sol.grid, dtype=np.float64)
        self.group_keys_ = keys
        self.group_by_ = group_by if isinstance(group_by, str) else None
        self.n_iter_per_grid_ = np.asarray(sol.iterations)
        self.solve_residual_per_grid_ = np.asarray(sol.residual, np.float64)
        self.converged_per_grid_ = np.asarray(sol.converged)
        self.n_iter = int(self.n_iter_per_grid_.max())
        worst = float(self.solve_residual_per_grid_.max())
        self.solve_residual = worst if math.isfinite(worst) else None
        self.converged = bool(self.converged_per_grid_.all())
        # fleet-wide numeric guard over the certificate arrays the fit
        # just materialized (host-side — zero extra device work)
        from .obs.numerics import record_nonfinite

        record_nonfinite(
            'solve_xt', 'grid', int(np.sum(~np.isfinite(self.grids_)))
        )
        record_nonfinite(
            'solve_xt', 'residual',
            int(np.sum(~np.isfinite(self.solve_residual_per_grid_))),
        )
        # the single-surface slot stays zeroed: grouped models rate
        # through the stack (``rate``/``surface``)
        self.xT = np.zeros((self.w, self.l))

    def _as_batch(self, actions: Actions) -> 'ActionBatch':
        if isinstance(actions, pd.DataFrame):
            df = actions
            if 'game_id' not in df.columns:
                df = df.assign(game_id=0)
            # xT only reads type/result/coordinates; fill whatever other
            # packed columns a minimal frame omits (the pandas backend and
            # the reference accept such frames too).
            defaults = {
                'team_id': 0,
                'period_id': 1,
                'time_seconds': 0.0,
                'bodypart_id': 0,
                'result_id': 0,
            }
            missing = {c: v for c, v in defaults.items() if c not in df.columns}
            if missing:
                df = df.assign(**missing)
            # xT is team-agnostic: home side is irrelevant, any constant works.
            batch, _ = pack_actions(df, home_team_ids={g: None for g in df['game_id'].unique()})
            return batch
        return actions

    def fit(
        self, actions: Actions, *, group_by: Optional[GroupBy] = None
    ) -> 'ExpectedThreat':
        """Fit the model on SPADL actions (DataFrame or packed batch).

        Parameters
        ----------
        actions : DataFrame or ActionBatch
            SPADL actions.
        group_by : str or array-like, optional
            JAX backend only: fit one surface **per group** — a column
            name (``'team_id'``, ``'competition_id'``, a phase bucket
            you derived…) or a per-action array of group keys aligned
            with the frame's rows. The whole fleet of grids is counted
            by one scatter-add and solved in ONE XLA dispatch
            (:mod:`socceraction_tpu.ops.xt` batched path), populating
            ``grids_`` / ``group_keys_`` and the per-grid certificate
            vectors; ``rate`` then gathers each action from its own
            group's surface. Requires a DataFrame (the keys live in
            frame columns).

        Each fit reports to the telemetry registry
        (:mod:`socceraction_tpu.obs`) under a ``(grid, solver, variant,
        backend, n_grids)`` label set — ``variant`` is the
        value-iteration schedule (picard/anderson/anchored/momentum) and
        ``n_grids`` the fleet size bucketed to powers of two
        (cardinality-bounded): iterations-to-convergence
        (``xt/solve_iterations``; the worst grid for grouped fits),
        solve wall time (``xt/solve_seconds`` — host-synced, since the
        iteration count fetch forces the device solve to completion) and
        the exit residual (``xt/solve_residual``); the whole fit runs
        inside an ``xt/fit`` span.
        """
        # re-validated here, not only in __init__: backend/variant/
        # keep_heatmaps are plain public attributes that may have been
        # mutated since construction (same rationale as the matrix-free/
        # keep_heatmaps check living in _fit_jax)
        variant = _resolve_variant(
            self.variant, self.accelerate, self.backend, self.keep_heatmaps
        )
        if group_by is not None:
            if self.backend != 'jax':
                raise ValueError(
                    'group_by (batched surface fleets) is a JAX-backend '
                    'feature'
                )
            if not isinstance(actions, pd.DataFrame):
                raise ValueError(
                    'group_by requires a DataFrame (group keys live in '
                    'frame columns)'
                )
            codes, keys = self._group_codes(actions, group_by)
            n_grids = len(keys)
            if n_grids == 0:
                raise ValueError('group_by produced no groups (all keys null?)')
        else:
            codes = keys = None
            n_grids = 1
        labels = {
            'grid': f'{self.l}x{self.w}',
            'solver': self._effective_solver(n_grids),
            'variant': variant,
            'backend': self.backend,
            'n_grids': str(_pow2_bucket(n_grids)),
        }
        t0 = time.perf_counter()
        with span('xt/fit', **labels):
            if group_by is not None:
                self._fit_jax_grouped(actions, codes, keys, group_by, variant)
            else:
                # a refit without group_by drops any previous fleet state
                self.grids_ = None
                self.group_keys_ = None
                self.group_by_ = None
                self.n_iter_per_grid_ = None
                self.solve_residual_per_grid_ = None
                self.converged_per_grid_ = None
                self.scoring_prob_matrices_ = None
                self.shot_prob_matrices_ = None
                self.move_prob_matrices_ = None
                self.transition_matrices_ = None
                if self.backend == 'jax':
                    self._fit_jax(self._as_batch(actions), variant)
                else:
                    self._fit_pandas(actions)
        solve_s = time.perf_counter() - t0
        if self.backend == 'jax' and not self.keep_heatmaps:
            # live-roofline feed: the fit wall is host-synced (the
            # certificate fetch forces the solve), and the fn name
            # matches the instrumented solver so the AOT cost lookup
            # finds its books; bucket = the pow-2 fleet size, the same
            # bounded dimension the xt/* labels use
            fn = (
                'solve_xt'
                if self._effective_solver(n_grids) == 'dense'
                else 'solve_xt_matrix_free'
            )
            record_dispatch(fn, solve_s, bucket=_pow2_bucket(n_grids))
        # grid is user-controlled (any l×w), so these instruments collapse
        # past-budget label sets into the reserved {overflow="true"} series
        # instead of raising — telemetry degrades, fit() never crashes
        histogram(
            'xt/solve_iterations', unit='iterations', on_overflow='overflow'
        ).observe(self.n_iter, **labels)
        histogram(
            'xt/solve_seconds', unit='s', on_overflow='overflow'
        ).observe(solve_s, **labels)
        if self.solve_residual is not None:
            gauge(
                'xt/solve_residual', unit='value', on_overflow='overflow'
            ).set(self.solve_residual, **labels)
        return self

    # -- inference ---------------------------------------------------------

    def _grid(self, use_interpolation: bool) -> Tuple[np.ndarray, int, int]:
        if not use_interpolation:
            return self.xT, self.l, self.w
        l = int(spadlconfig.field_length * 10)
        w = int(spadlconfig.field_width * 10)
        if self.backend == 'jax':
            fine = np.asarray(_xtops.interpolate_grid(jnp.asarray(self.xT), l, w))
        else:
            fine = self._interpolate_numpy(l, w)
        return fine, l, w

    def _interpolate_numpy(self, l_out: int, w_out: int) -> np.ndarray:
        """Bilinear upsampling between cell centers, borders clamped.

        Border samples clamp to the edge cell centers — the behavior of
        the reference's FITPACK-backed ``interp2d(kind='linear')``
        (``fpbisp`` clamps queries into the knot range; see
        ``ops/xt.py:interpolate_grid`` and ``tests/test_interp_oracle.py``).
        """
        cell_l = spadlconfig.field_length / self.l
        cell_w = spadlconfig.field_width / self.w
        xs = np.linspace(0.0, spadlconfig.field_length, l_out)
        ys = np.linspace(0.0, spadlconfig.field_width, w_out)
        fx = (xs - 0.5 * cell_l) / cell_l
        fy = (ys - 0.5 * cell_w) / cell_w
        ix = np.clip(np.floor(fx).astype(np.int64), 0, self.l - 2)
        iy = np.clip(np.floor(fy).astype(np.int64), 0, self.w - 2)
        tx = np.clip(fx - ix, 0.0, 1.0)
        ty = np.clip(fy - iy, 0.0, 1.0)
        r0 = self.w - 1 - iy
        r1 = self.w - 2 - iy
        g00 = self.xT[r0][:, ix]
        g01 = self.xT[r0][:, ix + 1]
        g10 = self.xT[r1][:, ix]
        g11 = self.xT[r1][:, ix + 1]
        top = g00 * (1 - tx[None, :]) + g01 * tx[None, :]
        bot = g10 * (1 - tx[None, :]) + g11 * tx[None, :]
        fine = top * (1 - ty[:, None]) + bot * ty[:, None]
        return fine[::-1]

    def _rate_grouped(
        self, actions: pd.DataFrame, use_interpolation: bool, group_by: Any
    ) -> np.ndarray:
        """Batched rating against the fitted surface fleet.

        Every action gathers from its own group's grid in one dispatch
        (:func:`~socceraction_tpu.ops.xt.rate_actions` with a surface
        stack); actions whose key the fit never saw rate NaN, like any
        other unrated action.
        """
        if group_by is None:
            group_by = self.group_by_
        if group_by is None:
            raise ValueError(
                'this model was grouped by a per-action array, so rate() '
                'cannot look the keys up in a frame column; pass group_by= '
                '(a column name or a per-action key array) to rate. Fitted '
                f'group keys: {_preview_keys(self.group_keys_)}'
            )
        if not isinstance(actions, pd.DataFrame):
            raise ValueError('rating a grouped model requires a DataFrame')
        if isinstance(group_by, str):
            if group_by not in actions.columns:
                raise ValueError(f'group_by column {group_by!r} not in actions')
            values = actions[group_by].to_numpy()
        else:
            values = np.asarray(group_by)
            if len(values) != len(actions):
                raise ValueError(
                    f'group_by array has {len(values)} entries for '
                    f'{len(actions)} actions'
                )
        # unseen keys -> -1 -> NaN in the kernel
        codes = pd.Index(self.group_keys_).get_indexer(values).astype(np.int32)

        grids = self.grids_
        l, w = self.l, self.w
        if use_interpolation:
            # interpolate ONLY the groups this frame references: the fine
            # fleet is (G, 680, 1050) — ~2.9 MB per grid — so upsampling
            # all G surfaces to rate a frame touching a handful of teams
            # would burn gigabytes at four-digit fleet sizes
            used = np.unique(codes[codes >= 0])
            if used.size == 0:
                return np.full(len(actions), np.nan)
            remap = np.full(len(self.group_keys_), -1, dtype=np.int32)
            remap[used] = np.arange(used.size, dtype=np.int32)
            codes = np.where(codes >= 0, remap[np.clip(codes, 0, None)], -1)
            codes = codes.astype(np.int32)
            l = int(spadlconfig.field_length * 10)
            w = int(spadlconfig.field_width * 10)
            grids = np.asarray(
                _xtops.interpolate_grid(jnp.asarray(grids[used]), l, w)
            )
        batch = self._as_batch(actions)
        group_id = jnp.asarray(pack_row_values(codes, batch, fill=-1))
        vals = _xtops.rate_actions(
            jnp.asarray(grids, dtype=jnp.float32),
            batch.type_id,
            batch.result_id,
            batch.start_x,
            batch.start_y,
            batch.end_x,
            batch.end_y,
            batch.mask,
            l=l,
            w=w,
            group_id=group_id,
        )
        from .core.batch import unpack_values

        return unpack_values(vals, batch)

    def surface(self, key: Any) -> np.ndarray:
        """The fitted ``(w, l)`` surface of one group (grouped fits)."""
        if self.grids_ is None:
            raise NotFittedError('fit the model with group_by= first')
        idx = pd.Index(self.group_keys_).get_indexer([key])[0]
        if idx < 0:
            raise KeyError(
                f'{key!r} is not a fitted group key; this fit has '
                f'{len(self.group_keys_)} keys: '
                f'{_preview_keys(self.group_keys_)} (rate() maps unseen '
                'keys to NaN instead of raising)'
            )
        return self.grids_[idx]

    def surfaces(self) -> dict:
        """``{group key -> (w, l) surface}`` of a grouped fit."""
        if self.grids_ is None:
            raise NotFittedError('fit the model with group_by= first')
        return {k: self.grids_[i] for i, k in enumerate(self.group_keys_)}

    def rate(
        self,
        actions: Actions,
        use_interpolation: bool = False,
        *,
        group_by: Optional[GroupBy] = None,
    ) -> np.ndarray:
        """Compute per-action xT ratings.

        Only successful pass/dribble/cross actions are rated; all other rows
        receive NaN (reference ``xthreat.py:453-464``). A grouped model
        (``fit(..., group_by=)``) rates every action against its own
        group's surface in one batched gather; ``group_by`` here
        overrides the fit-time column (required when the fit grouped by
        a per-action array). Actions with keys the fit never saw rate
        NaN.
        """
        if self.grids_ is not None:
            return self._rate_grouped(actions, use_interpolation, group_by)
        if group_by is not None:
            raise ValueError(
                'group_by rating requires a group_by fit: this model was '
                'fit as a single surface; refit with '
                'fit(actions, group_by=<column or per-action array>) to '
                'rate per group'
            )
        if not np.any(self.xT):
            raise NotFittedError('fit the model before calling rate')

        grid, l, w = self._grid(use_interpolation)

        if self.backend == 'jax' and not isinstance(actions, pd.DataFrame):
            batch = actions
            vals = _xtops.rate_actions(
                jnp.asarray(grid, dtype=jnp.float32),
                batch.type_id,
                batch.result_id,
                batch.start_x,
                batch.start_y,
                batch.end_x,
                batch.end_y,
                batch.mask,
                l=l,
                w=w,
            )
            return np.asarray(vals)

        df = actions.reset_index(drop=True)
        ratings = np.full(len(df), np.nan)
        moves = get_successful_move_actions(df)
        sxi, syj = _get_cell_indexes(
            moves['start_x'].to_numpy(), moves['start_y'].to_numpy(), l, w
        )
        exi, eyj = _get_cell_indexes(moves['end_x'].to_numpy(), moves['end_y'].to_numpy(), l, w)
        xt_start = grid[w - 1 - syj, sxi]
        xt_end = grid[w - 1 - eyj, exi]
        ratings[moves.index.to_numpy()] = xt_end - xt_start
        return ratings

    predict = rate  # deprecated alias kept for API parity (xthreat.py:380)

    def interpolator(self, kind: str = 'linear') -> Callable[..., np.ndarray]:
        """A callable interpolating the xT surface over the pitch.

        API parity: reference ``xthreat.py:327-350`` (an ``interp2d``-style
        wrapper: called with 1-D ``xs``/``ys`` meter coordinates, returns
        the ``(len(ys), len(xs))`` interpolated surface). Built on
        ``scipy.interpolate.RegularGridInterpolator`` (``interp2d`` was
        removed from SciPy) with the same cell-centered sample points.
        Queries outside the cell-center hull are clamped into it first,
        reproducing FITPACK's border behavior (``fpbisp`` clamps, never
        extrapolates) that the ``interp2d``-backed reference actually
        had — where ``RegularGridInterpolator(fill_value=None)`` would
        linearly extrapolate instead.

        Known deviation (documented in PARITY.md): the returned ``f(x, y)``
        is correctly oriented in pitch coordinates — the surface is flipped
        (``self.xT[::-1]``) because grid row 0 is the *top* of the pitch.
        The reference's interpolator skips that flip, returning a
        y-mirrored function whose flip only cancels against the
        ``grid[w-1-yc, xc]`` indexing inside the reference's own
        ``rate()``; callers porting the reference's direct-interpolator
        usage get y-mirrored values there, but not here.
        ``rate(use_interpolation=True)`` matches the reference either way.

        Parameters
        ----------
        kind : {'linear', 'cubic', 'quintic'}
            Spline order, as in the reference.
        """
        try:
            from scipy.interpolate import RegularGridInterpolator
        except ImportError as exc:
            raise ImportError('Interpolation requires scipy to be installed.') from exc

        methods = {'linear': 'linear', 'cubic': 'cubic', 'quintic': 'quintic'}
        if kind not in methods:
            raise ValueError(f'kind must be one of {sorted(methods)}, got {kind!r}')
        if self.grids_ is not None:
            # the single-surface slot is deliberately zeroed on grouped
            # fits — interpolating it would silently return a flat zero
            # function instead of any group's surface
            raise ValueError(
                'a grouped fit holds a surface collection, not one grid; '
                'interpolate a single surface via surface(key), or rate '
                'with rate(..., use_interpolation=True)'
            )

        cell_l = spadlconfig.field_length / self.l
        cell_w = spadlconfig.field_width / self.w
        xs = np.arange(0.0, spadlconfig.field_length, cell_l) + 0.5 * cell_l
        ys = np.arange(0.0, spadlconfig.field_width, cell_w) + 0.5 * cell_w
        # grid row 0 is the TOP of the pitch: flip to ascending-y order
        interp = RegularGridInterpolator(
            (ys, xs),
            self.xT[::-1],
            method=methods[kind],
            # inert under the query clamp in f() below (every point is
            # in-bounds); kept so a future unclamped call path degrades
            # to extrapolation rather than NaNs
            bounds_error=False,
            fill_value=None,
        )

        def f(x: np.ndarray, y: np.ndarray) -> np.ndarray:
            # clamp into the knot hull: FITPACK border behavior (see above)
            x = np.clip(np.asarray(x, dtype=np.float64), xs[0], xs[-1])
            y = np.clip(np.asarray(y, dtype=np.float64), ys[0], ys[-1])
            gx, gy = np.meshgrid(x, y)
            return interp(np.stack([gy.ravel(), gx.ravel()], axis=-1)).reshape(
                len(y), len(x)
            )

        return f

    # -- persistence -------------------------------------------------------

    def save_model(self, filepath: str, overwrite: bool = True) -> None:
        """Save the xT value surface as a JSON 2-D matrix."""
        if self.grids_ is not None:
            raise ValueError(
                'a grouped fit holds a surface collection, not one grid; '
                'save per-group surfaces via surfaces() / surface(key)'
            )
        if not np.any(self.xT):
            raise NotFittedError('fit the model before saving')
        if not overwrite and os.path.isfile(filepath):
            raise ValueError(
                f'save_model got overwrite=False, but file {filepath!r} already exists'
            )
        with open(filepath, 'w') as f:
            json.dump(np.asarray(self.xT).tolist(), f)


def load_model(path: str, backend: Optional[str] = None) -> ExpectedThreat:
    """Create a model from a pre-computed xT value surface (JSON 2-D matrix)."""
    with open(path) as f:
        grid = np.asarray(json.load(f), dtype=np.float64)
    model = ExpectedThreat(backend=backend)
    model.xT = grid
    model.w, model.l = grid.shape
    return model
