"""Auxiliary subsystems: tracing/profiling hooks and structured logging.

The reference has no tracing or profiling facilities (its only signal is an
iteration-count print in the xT solver, reference xthreat.py:320); a TPU
framework needs them, so this package provides:

- :mod:`socceraction_tpu.utils.profiling` -- ``jax.profiler``-backed trace
  contexts, named-scope annotation for XLA ops, and a lightweight wall-clock
  timer registry for host-side stages.
"""

from socceraction_tpu.utils.profiling import (
    Timer,
    annotate,
    profile_trace,
    timed,
    timer_report,
)

__all__ = ['Timer', 'annotate', 'profile_trace', 'timed', 'timer_report']
