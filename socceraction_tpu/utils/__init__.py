"""Auxiliary subsystems: tracing/profiling hooks and structured logging.

The reference has no tracing or profiling facilities (its only signal is an
iteration-count print in the xT solver, reference xthreat.py:320); a TPU
framework needs them, so this package provides:

- :mod:`socceraction_tpu.utils.profiling` -- ``jax.profiler``-backed trace
  contexts, named-scope annotation for XLA ops, and a lightweight wall-clock
  timer registry for host-side stages.
- :mod:`socceraction_tpu.utils.env` -- the clean virtual-CPU subprocess
  environment recipe shared by the test tier, the driver dryrun, and the
  benchmark fallback.

The profiling symbols are re-exported lazily (PEP 562): ``env`` is imported
by jax-free bootstrap processes (tests/conftest.py, bench.py) that must not
pay — or depend on — a ``jax`` import.
"""

from typing import Any

from socceraction_tpu.utils.env import cpu_device_env

__all__ = [
    'Timer',
    'annotate',
    'cpu_device_env',
    'profile_trace',
    'record_value',
    'timed',
    'timer_report',
]

_PROFILING_SYMBOLS = (
    'Timer', 'annotate', 'profile_trace', 'record_value', 'timed', 'timer_report'
)


def __getattr__(name: str) -> Any:
    if name in _PROFILING_SYMBOLS:
        from socceraction_tpu.utils import profiling

        return getattr(profiling, name)
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
