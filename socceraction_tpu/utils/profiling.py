"""Tracing and profiling hooks (façade over :mod:`socceraction_tpu.obs`).

Three layers of observability, all optional and zero-cost when unused:

1. :func:`profile_trace` -- context manager around ``jax.profiler`` that
   captures a device trace (TensorBoard-viewable) for a code region.
2. :func:`annotate` -- names a region inside a traced/jitted computation via
   ``jax.named_scope`` so it is identifiable in XLA/HLO dumps and profiles.
3. :class:`Timer` / :func:`timed` / :func:`record_value` /
   :func:`timer_report` -- the legacy wall-clock timer API, now a thin
   façade over the typed metric registry
   (:mod:`socceraction_tpu.obs.metrics`): ``timed(name)`` records into a
   seconds histogram, ``record_value`` into a true gauge, and
   ``timer_report()`` renders the legacy flat report from the registry's
   typed snapshot. Existing call sites keep working unchanged; new code
   should use :mod:`socceraction_tpu.obs` directly (labels, units,
   spans, exporters).

The report shim translates the labeled pipeline stage histogram
(``pipeline/stage_seconds{stage=...}``) back to the pre-obs flat names
(``pipeline/read_actions``, ``pipeline/pack``, ...) and includes the
queue-depth gauge, so pre-obs consumers of ``timer_report()`` see the
same keys they always did. Entries now carry unit-correct
``count/total/mean/max`` keys plus a ``unit`` field; the old
``total_s``/``mean_s``/``max_s`` keys remain as deprecated aliases (only
actually seconds when ``unit == 's'``).

jax is imported lazily, only by the paths that need it (device
synchronization, named scopes, profiler traces): the registry façade
must stay importable by jax-free processes — the SeasonStore read path
times its stages from data-prep/bootstrap contexts that must not pay,
or depend on, a jax import.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, ContextManager, Dict, Iterator, Optional, Union

from socceraction_tpu.obs import metrics as _metrics
from socceraction_tpu.obs.export import timer_report_compat

__all__ = [
    'Timer',
    'annotate',
    'profile_trace',
    'record_value',
    'timed',
    'timer_report',
]

#: the labeled stage histogram the pipeline records into, and the legacy
#: flat names ``timer_report()`` keeps publishing them under
STAGE_SECONDS = 'pipeline/stage_seconds'
LEGACY_STAGE_NAMES: Dict[str, str] = {
    'read': 'pipeline/read_actions',
    'read_io': 'pipeline/read_io',
    'decode': 'pipeline/decode',
    'pack': 'pipeline/pack',
    'transfer': 'pipeline/transfer',
    'read_cache': 'pipeline/read_cache',
    'cache_write': 'pipeline/cache_write',
    'pack_cache_build': 'pipeline/pack_cache_build',
    'load_events': 'pipeline/load_events',
    'convert': 'pipeline/convert',
    'feed_wait': 'pipeline/feed_wait',
}
_FEED_QUEUE_DEPTH = 'pipeline/feed_queue_depth'

# names created through this façade (timed / record_value): the report
# publishes exactly these plus the pipeline mappings above, so metrics
# recorded through the obs API proper don't leak into legacy consumers'
# output (e.g. the walkthrough's printed timer table)
_legacy_lock = threading.Lock()
_legacy_names: set = set()


class Timer:
    """Legacy accumulating timer view over one histogram series."""

    def __init__(self, name: str, _series: Optional[_metrics.Series] = None) -> None:
        self.name = name
        self._series = (
            _series
            if _series is not None
            else _metrics.histogram(name, unit='s').labels()
        )
        self._sync_targets: list = []

    def add(self, elapsed_s: float) -> None:
        """Record one timed interval of ``elapsed_s`` seconds."""
        self._series.observe(elapsed_s)

    def sync(self, value: Any) -> Any:
        """Register device output(s) produced in the timed region.

        At context exit only these values are synchronized
        (``jax.block_until_ready``), so the stage is charged for its own
        device work and nothing else. Returns ``value`` unchanged for
        inline use: ``out = t.sync(kernel(x))``.
        """
        self._sync_targets.append(value)
        return value

    @property
    def count(self) -> int:
        """Recorded interval count."""
        return self._series.count

    @property
    def total_s(self) -> float:
        """Sum of recorded seconds."""
        return self._series.total

    @property
    def max_s(self) -> float:
        """Largest recorded interval (0.0 while empty)."""
        m = self._series.max
        return 0.0 if m != m else m  # NaN while empty

    def as_dict(self) -> Dict[str, float]:
        """Snapshot: count plus total/mean/max seconds."""
        count = self.count
        total = self.total_s
        return {
            'count': count,
            'total_s': total,
            'mean_s': total / count if count else 0.0,
            'max_s': self.max_s,
        }


@contextlib.contextmanager
def timed(
    name: str,
    *,
    block_until_ready: bool = False,
    sync: Union[None, Any, Callable[[], Any]] = None,
) -> Iterator[Timer]:
    """Time a host-side stage and record it under ``name`` (seconds).

    Device-synced timing charges only this stage's own work: pass the
    arrays (or a zero-arg callable returning them) as ``sync=``, or
    register outputs produced inside the region via
    :meth:`Timer.sync` — the exit then waits on exactly those values.

    ``block_until_ready=True`` *without* any registered sync target
    falls back to the legacy behavior of synchronizing **all** live JAX
    arrays, which charges unrelated in-flight work to this stage — kept
    for backward compatibility, deprecated; prefer ``sync=`` /
    ``Timer.sync``.
    """
    with _legacy_lock:
        _legacy_names.add(name)
    timer = Timer(name)
    t0 = time.perf_counter()
    try:
        yield timer
    finally:
        targets = list(timer._sync_targets)
        if sync is not None:
            targets.append(sync() if callable(sync) else sync)
        if targets:
            import jax

            jax.block_until_ready(targets)
        elif block_until_ready:
            import jax

            # Legacy coarse sync: jax.effects_barrier() only waits on
            # *effectful* computations, so block on all live arrays —
            # note this charges ANY in-flight device work to this stage.
            jax.block_until_ready(jax.live_arrays())
        timer.add(time.perf_counter() - t0)


def record_value(name: str, value: float) -> None:
    """Record a dimensionless sample into a gauge in the shared registry.

    The legacy spelling of ``obs.gauge(name).set(value)``: the series
    reports under unit-correct ``count/total/mean/max`` keys with
    ``unit='value'`` (the pre-obs ``*_s`` keys remain as deprecated
    aliases). When the name is already registered as a gauge with a real
    unit (e.g. the feed's ``pipeline/feed_queue_depth`` gauge,
    ``unit='chunks'``), the sample lands on that gauge — the legacy
    spelling and the obs spelling of one metric must interoperate, not
    conflict. A name registered as a different *kind* (a ``timed``
    histogram) still raises. Prefer the obs API directly for new code —
    it can also carry labels and a real unit.
    """
    with _legacy_lock:
        _legacy_names.add(name)
    inst = _metrics.REGISTRY.get(name)
    if isinstance(inst, _metrics.Gauge):
        inst.set(float(value))
        return
    _metrics.gauge(name, unit='value').set(float(value))


def timer_report(reset: bool = False) -> Dict[str, Dict[str, float]]:
    """Legacy flat report ``{name: {count, total, mean, max, unit, ...}}``.

    Rendered from the typed registry snapshot: façade-recorded series
    under their own names, the labeled pipeline stage histogram under
    the pre-obs flat names, and the feed queue-depth gauge. ``reset``
    zeroes every registry series in place (instruments stay registered).
    """
    snapshot = _metrics.REGISTRY.snapshot()
    with _legacy_lock:
        spec: Dict[str, Any] = {
            n: n for n in _legacy_names if n in snapshot.instruments
        }
    for stage, legacy in LEGACY_STAGE_NAMES.items():
        spec[legacy] = (STAGE_SECONDS, {'stage': stage})
    if _FEED_QUEUE_DEPTH in snapshot.instruments:
        spec[_FEED_QUEUE_DEPTH] = _FEED_QUEUE_DEPTH
    report = timer_report_compat(snapshot, spec)
    if reset:
        _metrics.REGISTRY.reset()
    return report


def annotate(name: str) -> ContextManager[Any]:
    """Named scope visible in XLA profiles; usable inside jitted code.

    Example::

        with annotate('xt/solve'):
            solution = solve_xt(probs, eps=eps)
    """
    import jax

    return jax.named_scope(name)


@contextlib.contextmanager
def profile_trace(
    log_dir: str,
    *,
    create_perfetto_link: bool = False,
    enabled: bool = True,
) -> Iterator[None]:
    """Capture a ``jax.profiler`` device trace for the enclosed region.

    Writes a TensorBoard-loadable trace to ``log_dir``. ``enabled=False``
    turns the context into a no-op so call sites can keep the hook in place
    unconditionally.

    The capture itself is registered with the telemetry layer: the
    region runs inside an ``xla/profile_trace`` span carrying ``log_dir``
    in its attributes, so a :class:`~socceraction_tpu.obs.trace.RunLog`
    (and the flight recorder) records when a device trace was taken and
    where the artifact went — profiler captures are no longer invisible
    to the run's own timeline.
    """
    if not enabled:
        yield
        return
    import jax

    from socceraction_tpu.obs.trace import span as _span

    with _span('xla/profile_trace', log_dir=log_dir):
        jax.profiler.start_trace(
            log_dir, create_perfetto_link=create_perfetto_link
        )
        try:
            yield
        finally:
            jax.profiler.stop_trace()
