"""Tracing and profiling hooks.

Three layers of observability, all optional and zero-cost when unused:

1. :func:`profile_trace` -- context manager around ``jax.profiler`` that
   captures a device trace (TensorBoard-viewable) for a code region.
2. :func:`annotate` -- names a region inside a traced/jitted computation via
   ``jax.named_scope`` so it is identifiable in XLA/HLO dumps and profiles.
3. :class:`Timer` / :func:`timed` -- host-side wall-clock timers for the
   stages that stay off-device (JSON parsing, event surgery, Arrow packing),
   aggregated in a process-wide registry readable via :func:`timer_report`.

The reference library has no equivalent (SURVEY §5: "Tracing / profiling:
none"); this subsystem is new, designed for the TPU runtime where host-side
ingest and device-side kernels need to be attributed separately.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, ContextManager, Dict, Iterator

# jax is imported lazily, only by the paths that need it (device
# synchronization, named scopes, profiler traces): the wall-clock timer
# registry itself must stay importable by jax-free processes — the
# SeasonStore read path times its stages from data-prep/bootstrap
# contexts that must not pay, or depend on, a jax import

_registry_lock = threading.Lock()
_timers: Dict[str, 'Timer'] = {}


class Timer:
    """Accumulating wall-clock timer (count, total, max) for one stage."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self._lock = threading.Lock()

    def add(self, elapsed_s: float) -> None:
        """Record one timed interval of ``elapsed_s`` seconds."""
        with self._lock:
            self.count += 1
            self.total_s += elapsed_s
            self.max_s = max(self.max_s, elapsed_s)

    def as_dict(self) -> Dict[str, float]:
        """Snapshot: count plus total/mean/max seconds."""
        return {
            'count': self.count,
            'total_s': self.total_s,
            'mean_s': self.total_s / self.count if self.count else 0.0,
            'max_s': self.max_s,
        }


def _get_timer(name: str) -> Timer:
    with _registry_lock:
        timer = _timers.get(name)
        if timer is None:
            timer = _timers[name] = Timer(name)
        return timer


@contextlib.contextmanager
def timed(name: str, *, block_until_ready: bool = False) -> Iterator[Timer]:
    """Time a host-side stage and record it under ``name``.

    With ``block_until_ready=True`` the context exit synchronizes all live
    JAX arrays first, so asynchronously dispatched device work is charged to
    the stage that launched it.
    """
    timer = _get_timer(name)
    t0 = time.perf_counter()
    try:
        yield timer
    finally:
        if block_until_ready:
            import jax

            # jax.effects_barrier() only waits on *effectful* computations;
            # pure async dispatches leave no runtime token, so block on the
            # live arrays themselves to charge device time to this stage.
            jax.block_until_ready(jax.live_arrays())
        timer.add(time.perf_counter() - t0)


def record_value(name: str, value: float) -> None:
    """Record a dimensionless sample (gauge) into the shared registry.

    The registry's accumulators are unit-agnostic: ``count``/``total_s``/
    ``mean_s``/``max_s`` read as count/total/mean/max of whatever was
    recorded. Used for non-time series that want the same report plumbing
    as the stage timers — e.g. ``pipeline/feed_queue_depth``, where each
    sample is the prefetch queue depth observed at one consumer take, so
    ``mean_s`` is the average buffered-chunk count (producer ahead) and a
    mean near zero means the consumer is starved (host-bound feed).
    """
    _get_timer(name).add(float(value))


def timer_report(reset: bool = False) -> Dict[str, Dict[str, float]]:
    """Snapshot of all timers as ``{name: {count, total_s, mean_s, max_s}}``."""
    with _registry_lock:
        report = {name: t.as_dict() for name, t in sorted(_timers.items())}
        if reset:
            _timers.clear()
    return report


def annotate(name: str) -> ContextManager[Any]:
    """Named scope visible in XLA profiles; usable inside jitted code.

    Example::

        with annotate('xt/solve'):
            grid = solve_xt(probs, eps=eps)
    """
    import jax

    return jax.named_scope(name)


@contextlib.contextmanager
def profile_trace(
    log_dir: str,
    *,
    create_perfetto_link: bool = False,
    enabled: bool = True,
) -> Iterator[None]:
    """Capture a ``jax.profiler`` device trace for the enclosed region.

    Writes a TensorBoard-loadable trace to ``log_dir``. ``enabled=False``
    turns the context into a no-op so call sites can keep the hook in place
    unconditionally.
    """
    if not enabled:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
