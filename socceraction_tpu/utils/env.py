"""Clean virtual-CPU JAX environments for subprocess bootstrapping.

In this image a ``sitecustomize`` hook registers the remote-TPU ("axon")
PJRT plugin at interpreter startup and latches ``JAX_PLATFORMS`` before
any user code runs, so a process that needs a CPU device mesh must be
*started* with the right environment — mutating ``os.environ`` inside the
process is too late. This is the single source of truth for that recipe;
it is shared by ``tests/conftest.py`` (the multi-device test tier),
``__graft_entry__.dryrun_multichip`` (the driver's mesh dryrun), and
``bench.py`` (the degraded CPU-fallback path).
"""

from __future__ import annotations

import os
import re
from typing import Mapping, MutableMapping, Optional

__all__ = ['cpu_device_env']

_DEVICE_COUNT_FLAG = re.compile(r'--xla_force_host_platform_device_count=\d+')


def cpu_device_env(
    n_devices: Optional[int] = None,
    *,
    base: Optional[Mapping[str, str]] = None,
    override: bool = True,
) -> MutableMapping[str, str]:
    """Environment for a clean ``n_devices``-virtual-CPU JAX subprocess.

    Parameters
    ----------
    n_devices : int, optional
        Requested ``--xla_force_host_platform_device_count``. ``None``
        strips any existing count flag (single-device CPU).
    base : mapping, optional
        Environment to derive from; defaults to ``os.environ``.
    override : bool
        When False, an ``--xla_force_host_platform_device_count`` already
        present in ``XLA_FLAGS`` is preserved instead of replaced (used by
        the test tier so callers can pin their own mesh size).
    """
    env = dict(os.environ if base is None else base)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PALLAS_AXON_POOL_IPS'] = ''  # skip remote-TPU plugin registration
    flags = env.get('XLA_FLAGS', '')
    had_count = _DEVICE_COUNT_FLAG.search(flags) is not None
    if n_devices is None or (had_count and not override):
        if n_devices is None:
            flags = _DEVICE_COUNT_FLAG.sub('', flags)
    else:
        flags = _DEVICE_COUNT_FLAG.sub('', flags)
        flags = f'{flags} --xla_force_host_platform_device_count={int(n_devices)}'
    env['XLA_FLAGS'] = ' '.join(flags.split())
    return env
