"""Clean virtual-CPU JAX environments for subprocess bootstrapping.

In this image a ``sitecustomize`` hook registers the remote-TPU ("axon")
PJRT plugin at interpreter startup and latches ``JAX_PLATFORMS`` before
any user code runs, so a process that needs a CPU device mesh must be
*started* with the right environment — mutating ``os.environ`` inside the
process is too late. This is the single source of truth for that recipe;
it is shared by ``tests/conftest.py`` (the multi-device test tier),
``__graft_entry__.dryrun_multichip`` (the driver's mesh dryrun), and
``bench.py`` (the degraded CPU-fallback path).
"""

from __future__ import annotations

import os
import re
from typing import Mapping, MutableMapping, Optional

__all__ = ['cpu_device_env', 'run_distributed_cpu_workers']

_DEVICE_COUNT_FLAG = re.compile(r'--xla_force_host_platform_device_count=\d+')


def cpu_device_env(
    n_devices: Optional[int] = None,
    *,
    base: Optional[Mapping[str, str]] = None,
    override: bool = True,
) -> MutableMapping[str, str]:
    """Environment for a clean ``n_devices``-virtual-CPU JAX subprocess.

    Parameters
    ----------
    n_devices : int, optional
        Requested ``--xla_force_host_platform_device_count``. ``None``
        strips any existing count flag (single-device CPU).
    base : mapping, optional
        Environment to derive from; defaults to ``os.environ``.
    override : bool
        When False, an ``--xla_force_host_platform_device_count`` already
        present in ``XLA_FLAGS`` is preserved instead of replaced (used by
        the test tier so callers can pin their own mesh size).
    """
    env = dict(os.environ if base is None else base)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PALLAS_AXON_POOL_IPS'] = ''  # skip remote-TPU plugin registration
    flags = env.get('XLA_FLAGS', '')
    had_count = _DEVICE_COUNT_FLAG.search(flags) is not None
    if n_devices is None or (had_count and not override):
        if n_devices is None:
            flags = _DEVICE_COUNT_FLAG.sub('', flags)
    else:
        flags = _DEVICE_COUNT_FLAG.sub('', flags)
        flags = f'{flags} --xla_force_host_platform_device_count={int(n_devices)}'
    env['XLA_FLAGS'] = ' '.join(flags.split())
    return env


def run_distributed_cpu_workers(
    worker_path: str,
    num_processes: int = 2,
    *,
    local_devices: int = 4,
    timeout_s: float = 280.0,
) -> list:
    """Spawn ``num_processes`` ``jax.distributed`` CPU worker processes.

    Shared by the multi-process test tier (``tests/test_distributed.py``)
    and the scale-out walkthrough so the launch/collect/cleanup logic
    cannot drift between them. Each worker is started as
    ``python worker_path <process_id> <num_processes> <port>`` in a clean
    ``local_devices``-virtual-CPU environment with this package's repo
    root on ``PYTHONPATH``; a free coordinator port is picked here.

    Returns the workers' combined stdout/stderr texts. Raises
    ``RuntimeError`` naming the first failing worker if any exits
    nonzero; on a hang, every still-running worker is killed before the
    ``TimeoutExpired`` propagates.
    """
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]

    env = cpu_device_env(local_devices)
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env['PYTHONPATH'] = root + (
        os.pathsep + env['PYTHONPATH'] if env.get('PYTHONPATH') else ''
    )
    procs = [
        subprocess.Popen(
            [sys.executable, worker_path, str(i), str(num_processes), str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(num_processes)
    ]
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout_s)
            outputs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outputs)):
        if p.returncode != 0:
            raise RuntimeError(
                f'distributed worker {i} failed (rc={p.returncode}):\n'
                + out[-3000:]
            )
    return outputs
