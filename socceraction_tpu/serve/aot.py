"""AOT-serialized serving executables: compile once, ship, deserialize.

ROADMAP item 5's closing move. A "millions of users" service scales out
by starting replicas, and PR 11's cold-start ledger put a number on what
each one costs: ~10s on CPU, of which the per-rung ladder compile is the
second-largest phase. Every replica was re-deriving the *same* XLA
programs from the *same* checkpoint on the *same* platform. This module
makes the compiled programs themselves registry artifacts:

- :func:`export_serving_aot` — for each bucket rung of a model's
  serving ladder, build the exact dispatch plan the flush will run
  (:func:`socceraction_tpu.ops.fused.pair_dispatch_plan` — the shared
  single source, so exporter and server can never skew), lower it from
  ``ShapeDtypeStruct`` specs, compile, and serialize the compiled
  executable (``jax.experimental.serialize_executable``) into
  ``<dir>/aot/`` next to a ``manifest.json`` carrying the environment
  fingerprint, per-artifact sha256 checksums (the PR 10 discipline) and
  the export-time XLA cost analysis. Both compiled programs of a
  serving dispatch ship: the two-head pair dispatch *and* the
  ``vaep_values`` formula kernel.
- :func:`load_serving_aot` — the deserialize tier of
  ``RatingService.warmup()``: when the stored fingerprint matches the
  running process, every artifact is checksum-verified, deserialized
  and preloaded into its jit's signature cache
  (:meth:`socceraction_tpu.obs.xla.InstrumentedJit.preload`), so the
  ladder warmup dispatches through shipped executables instead of
  compiling. A fingerprint mismatch degrades loudly-but-gracefully:
  ``outcome='stale'`` (counted, evented, in ``health()['aot']``) and
  the service recompiles — wrong executables are never served. Artifact
  reads run through the ``registry.aot`` fault point and the typed
  retry policy; a corrupt/truncated artifact is a *named* failure that
  falls back to recompile, never a failed swap.
- :func:`enable_compile_cache` — the middle tier: jax's persistent
  compilation cache (``SOCCERACTION_TPU_COMPILE_CACHE`` via
  :mod:`socceraction_tpu.config`), for replicas without shipped
  artifacts that still share a filesystem.

The serialized executables are **weight-independent**: model parameters
and prepared tables are runtime *arguments* of the compiled programs,
so one exported ladder serves every same-architecture version — a
hot-swap to a retrained model reuses the preloaded programs with the
new weights, and re-loading a newer version's artifacts just replaces
identical keys.

Everything here is importable without jax (module contract shared with
the rest of :mod:`socceraction_tpu.obs`): jax loads only when artifacts
are actually exported, loaded, or the cache enabled. ``read_manifest``
is deliberately jax-free so control-plane tooling (``obsctl``) can
inspect shipped fingerprints without paying the jax import.

Outcomes land in ``serve/aot_loads{outcome=hit|stale|miss}`` (one
``hit`` per deserialized artifact — the capacity smoke asserts hits ≥
ladder rungs — one ``stale``/``miss`` per load attempt) plus an
``aot_load`` event in the flight recorder and the active run log.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..config import compile_cache_dir
from ..obs import counter
from ..resil.faults import fault_point
from ..resil.retry import RetryPolicy, retry_call

__all__ = [
    'AOT_DIRNAME',
    'AOT_FORMAT',
    'enable_compile_cache',
    'env_fingerprint',
    'export_serving_aot',
    'fingerprint_diff',
    'last_aot_load',
    'load_serving_aot',
    'read_manifest',
]

#: subdirectory of a registry version dir holding the shipped executables
AOT_DIRNAME = 'aot'

#: manifest format; a reader refuses anything newer (same stance as the
#: checkpoint format stamps)
AOT_FORMAT = 1

#: Artifact reads retried under this policy: transient filesystem errors
#: (registry on network storage mid-failover) back off and retry;
#: checksum mismatches and parse failures (ValueError) are permanent —
#: the caller falls back to recompiling, waiting cannot fix bit rot.
AOT_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.05, max_delay_s=1.0)

#: the last load attempt's summary (process-wide), for live ``obsctl
#: capacity`` — the runlog-free counterpart of the ``aot_load`` event
_LAST_LOAD: Optional[Dict[str, Any]] = None
_LAST_LOAD_LOCK = threading.Lock()


def last_aot_load() -> Optional[Dict[str, Any]]:
    """The most recent :func:`load_serving_aot` summary, or ``None``."""
    with _LAST_LOAD_LOCK:
        return dict(_LAST_LOAD) if _LAST_LOAD is not None else None


def _note_load(summary: Dict[str, Any]) -> None:
    global _LAST_LOAD
    with _LAST_LOAD_LOCK:
        _LAST_LOAD = dict(summary)


def _emit_event(kind: str, **payload: Any) -> None:
    """Recorder + run-log fan-out; telemetry must never fail a load."""
    try:
        from ..obs.recorder import RECORDER
        from ..obs.trace import current_runlog

        RECORDER.record(kind, **payload)
        log = current_runlog()
        if log is not None:
            log.event(kind, **payload)
    except Exception:
        pass


# --------------------------------------------------------------------------
# environment fingerprint
# --------------------------------------------------------------------------


def _profile_sha256() -> str:
    """sha256 of the committed platform-profile file (or 'absent').

    The profile gates the Pallas kernel and the rating path, both of
    which select *which* program serves — two processes with different
    profiles may compile different executables for the same model.
    """
    from ..ops import profile as _profile

    path = getattr(_profile, '_PROFILE_FILE', None)
    try:
        with open(path, 'rb') as f:  # type: ignore[arg-type]
            return hashlib.sha256(f.read()).hexdigest()
    except (OSError, TypeError):
        return 'absent'


def env_fingerprint() -> Dict[str, str]:
    """The compiled-program compatibility key of THIS process.

    Everything that changes what (or whether) a serialized executable
    can serve here: jax/jaxlib versions and the backend + device kind
    (the PJRT executable format is tied to all four), the platform
    profile hash and resolved rating path / first-layer kernel (they
    select which program compiles), the in-dispatch guard flag (it
    changes the program's outputs) and the checkpoint format (what a
    version dir's weights mean). Imports jax — callers that only need
    to *read* a shipped fingerprint use :func:`read_manifest`.
    """
    import jax
    import jaxlib

    from ..ml.mlp import MLP_FORMAT_VERSION
    from ..obs import numerics
    from ..ops.gather_matmul import fused_kernel_method
    from ..ops.profile import preferred_rating_path

    try:
        device_kind = jax.devices()[0].device_kind
    except Exception:
        device_kind = 'unknown'
    try:
        kernel = fused_kernel_method()
    except Exception:
        kernel = 'invalid'
    return {
        'aot_format': str(AOT_FORMAT),
        'jax': str(jax.__version__),
        'jaxlib': str(jaxlib.__version__),
        'backend': str(jax.default_backend()),
        'device_kind': str(device_kind),
        'platform_profile_sha256': _profile_sha256(),
        'rating_path': str(preferred_rating_path()),
        'kernel': str(kernel),
        'guards': '1' if numerics.guards_enabled() else '0',
        'checkpoint_format': str(MLP_FORMAT_VERSION),
    }


def fingerprint_diff(
    stored: Dict[str, Any], current: Dict[str, Any]
) -> List[str]:
    """Keys on which two fingerprints disagree (empty = compatible).

    Compared over the union of keys: a field one side lacks IS a
    mismatch (an older manifest without ``guards`` must not silently
    pass a guard-enabled process).
    """
    keys = set(stored) | set(current)
    return sorted(
        k for k in keys if str(stored.get(k)) != str(current.get(k))
    )


# --------------------------------------------------------------------------
# the serving plans: one (pair, formula) program pair per ladder rung
# --------------------------------------------------------------------------


def _spec_tree(tree: Any) -> Any:
    """Array leaves -> ShapeDtypeStructs (specs pass through unchanged)."""
    import jax

    from ..obs.xla import _spec_leaf

    return jax.tree_util.tree_map(_spec_leaf, tree)


def _serving_plans(
    model: Any, *, ladder: Tuple[int, ...], max_actions: int
) -> Iterator[Tuple[str, Any, Tuple[Any, ...], Dict[str, Any]]]:
    """Yield ``(entry_id, jit, spec_args, kwargs)`` per serving program.

    One pair dispatch plus one formula kernel per bucket rung, with the
    argument trees the live flush will use — ``dense_overrides`` carries
    the goalscore block exactly when the model has the kernel (the
    serving layer injects it on EVERY request for such models, so there
    is one program per rung, not two). Everything is resolved through
    :func:`~socceraction_tpu.ops.fused.pair_dispatch_plan`, the same
    single source the dispatch uses.
    """
    import jax
    import jax.numpy as jnp

    from ..ops import formula as _formula
    from ..ops.fused import _abstract_batch, pair_dispatch_plan
    from ..ops.profile import (
        FUSED_PATH_HIDDEN_DTYPES,
        hidden_dtype_for,
        preferred_rating_path,
    )

    if getattr(model, '_fused_registry', None) != 'standard':
        # the serving plans below are the STANDARD family's: the batch
        # spec is the standard ActionBatch and the formula program is
        # ops.formula.vaep_values — lowering an atomic model over them
        # would either crash or export programs whose keys never match
        # a live dispatch (a silent always-recompile "hit"). Same
        # boundary as RatingService._validate_model, stated at export
        # time instead of serve time.
        raise ValueError(
            'AOT export covers standard-SPADL serving models '
            f'(got fused registry {getattr(model, "_fused_registry", None)!r})'
        )
    path = preferred_rating_path()
    if not getattr(model, '_can_fuse', lambda: False)() or (
        path not in FUSED_PATH_HIDDEN_DTYPES
    ):
        raise ValueError(
            'AOT export covers the fused serving path; this model/'
            f'platform configuration rates through {path!r} without a '
            'fused dispatch to serialize'
        )
    cols = list(model._label_columns)
    clf_a, clf_b = model._models[cols[0]], model._models[cols[1]]
    gs = 'goalscore' in model._kernel_names()
    A = int(max_actions)
    for b in ladder:
        b = int(b)
        batch_spec = _abstract_batch(G=b, A=A)
        overrides = (
            {'goalscore': jax.ShapeDtypeStruct((b, A, 3), jnp.float32)}
            if gs
            else None
        )
        plan = pair_dispatch_plan(
            clf_a,
            clf_b,
            batch_spec,
            names=model._kernel_names(),
            k=model.nb_prev_actions,
            registry_name=model._fused_registry,
            dense_overrides=overrides,
            hidden_dtype=hidden_dtype_for(path),
            prepared=model._prepared_pair(),
        )
        yield (
            f'pair-b{b}',
            plan.fn,
            _spec_tree(plan.args),
            plan.kwargs,
        )
        probs = jax.ShapeDtypeStruct((b, A), jnp.float32)
        yield (
            f'formula-b{b}',
            _formula.vaep_values,
            (batch_spec, probs, probs),
            {},
        )


def _plan_signature(fn: Any, args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> str:
    """The human-readable abstract signature string of one plan.

    Stored per artifact and re-derived at load time from the *loaded*
    model: an artifact exported for a different architecture (or static
    configuration) can never preload under a signature it was not
    compiled for — the string IS the exact-abstract-signature guard.
    """
    from ..obs.xla import signature_of

    sig = signature_of(args, kwargs, fn._static_names)
    return ' '.join(f'{p}={d}' for p, d in sig)


# --------------------------------------------------------------------------
# export
# --------------------------------------------------------------------------


def export_serving_aot(
    model: Any,
    dest: str,
    *,
    ladder: Tuple[int, ...],
    max_actions: int,
) -> Dict[str, Any]:
    """Compile ``model``'s serving ladder and serialize it into ``dest``.

    ``dest`` is the ``aot/`` directory (created; must not already hold a
    manifest — artifacts are immutable like everything else in the
    registry). ``ladder`` / ``max_actions`` are the serving shapes to
    cover (``RatingService``'s bucket ladder and action-axis capacity —
    export with the shapes replicas will serve). Each program is
    AOT-lowered from specs (never touching live buffers or the dispatch
    cache), compiled, cost-analyzed and serialized; the manifest records
    the environment fingerprint, per-artifact sha256 and the cost books
    that :func:`load_serving_aot` seeds the compile observatory with.
    Returns the manifest dict.
    """
    from jax.experimental import serialize_executable as _se

    manifest_path = os.path.join(dest, 'manifest.json')
    if os.path.exists(manifest_path):
        raise ValueError(
            f'AOT artifacts already exist at {dest!r}; they are '
            'immutable — export into a fresh version/candidate instead'
        )
    os.makedirs(dest, exist_ok=True)
    entries: List[Dict[str, Any]] = []
    for entry_id, fn, spec_args, kwargs in _serving_plans(
        model, ladder=tuple(ladder), max_actions=max_actions
    ):
        compiled = fn.lower(*spec_args, **kwargs).compile()
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, list):
                cost = cost[0]
            cost_flops = float(cost.get('flops', 0.0))
            cost_bytes = float(cost.get('bytes accessed', 0.0))
        except Exception:
            cost_flops = cost_bytes = None  # type: ignore[assignment]
        blob = pickle.dumps(_se.serialize(compiled), protocol=4)
        filename = f'{entry_id}.jaxexec'
        with open(os.path.join(dest, filename), 'wb') as f:
            f.write(blob)
        entries.append(
            {
                'id': entry_id,
                'file': filename,
                'fn': fn.name,
                'sha256': hashlib.sha256(blob).hexdigest(),
                'nbytes': len(blob),
                'cost_flops': cost_flops,
                'cost_bytes': cost_bytes,
                'signature': _plan_signature(fn, spec_args, kwargs),
            }
        )
    manifest = {
        'format': AOT_FORMAT,
        'fingerprint': env_fingerprint(),
        'created_unix': time.time(),
        'ladder': [int(b) for b in ladder],
        'max_actions': int(max_actions),
        'entries': entries,
    }
    with open(manifest_path, 'w', encoding='utf-8') as f:
        json.dump(manifest, f, sort_keys=True)
    return manifest


# --------------------------------------------------------------------------
# load
# --------------------------------------------------------------------------


def read_manifest(aot_dir: str) -> Optional[Dict[str, Any]]:
    """The AOT manifest of ``aot_dir``, or ``None`` when absent.

    jax-free (control-plane tooling inspects shipped fingerprints with
    it). A *corrupt* manifest raises ``ValueError`` naming the file —
    half-written provenance must surface, not read as absent; a reader
    newer than this library is refused like a too-new checkpoint.
    """
    path = os.path.join(aot_dir, 'manifest.json')
    if not os.path.isfile(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ValueError(
            f'AOT manifest corrupt: {path!r} failed to parse '
            f'({type(e).__name__}: {e})'
        ) from e
    if not isinstance(manifest, dict) or 'entries' not in manifest:
        raise ValueError(
            f'AOT manifest corrupt: {path!r} is not a manifest object'
        )
    if int(manifest.get('format', 0)) > AOT_FORMAT:
        raise ValueError(
            f'AOT manifest at {path!r} has format={manifest.get("format")}, '
            f'newer than this library understands (<= {AOT_FORMAT}); '
            'upgrade socceraction_tpu to load it'
        )
    return manifest


def _read_artifact(aot_dir: str, entry: Dict[str, Any]) -> bytes:
    """One checksum-verified artifact read (the ``registry.aot`` site).

    The fault point sits INSIDE the retried callable, so an injected
    transient error exercises the retry policy and an injected
    ``ValueError`` (bit rot) surfaces immediately — both paths then hit
    the caller's recompile fallback.
    """
    path = os.path.join(aot_dir, entry['file'])

    def _read() -> bytes:
        fault_point('registry.aot', artifact=entry['file'])
        with open(path, 'rb') as f:
            blob = f.read()
        digest = hashlib.sha256(blob).hexdigest()
        if digest != entry.get('sha256'):
            raise ValueError(
                f'AOT artifact corrupt: {path!r} sha256 {digest[:12]}… '
                f'does not match the manifest ({str(entry.get("sha256"))[:12]}…); '
                'the executable is truncated or damaged — recompiling'
            )
        return blob

    return retry_call(_read, site='registry.aot', policy=AOT_RETRY)


def load_serving_aot(
    model: Any,
    aot_dir: str,
    *,
    ladder: Tuple[int, ...],
    max_actions: int,
    context: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Deserialize shipped executables and preload the serving jits.

    The tier-1 half of ``RatingService.warmup()``. Never raises: the
    summary dict's ``outcome`` is

    - ``'hit'`` — fingerprint matched and every covered rung's programs
      were checksum-verified, deserialized and preloaded (one
      ``serve/aot_loads{outcome="hit"}`` count per artifact);
    - ``'stale'`` — artifacts exist but were built under a different
      environment (or architecture): nothing preloads, ``mismatch``
      names the fingerprint keys (or signatures) that moved, and the
      caller recompiles — loudly counted, never silently served;
    - ``'miss'`` — no artifacts, or a corrupt/unreadable artifact
      (``reason`` says which): the caller recompiles.

    Partial failures fail the whole load as ``'miss'`` *after* the
    already-preloaded rungs were installed — those rungs still skip
    their compile; the missing rungs compile in the warmup loop (the
    degraded-not-broken contract of every registry artifact).
    """
    summary: Dict[str, Any] = {
        'outcome': 'miss',
        'entries_loaded': 0,
        'aot_dir': aot_dir,
        **(context or {}),
    }
    try:
        # OSError included: a registry on network storage mid-failover
        # can fail the manifest open itself — the never-raises contract
        # (warmups and swaps degrade to recompile, never fail) covers
        # the manifest read exactly like the artifact reads below
        manifest = read_manifest(aot_dir)
    except (ValueError, OSError) as e:
        summary['reason'] = f'{type(e).__name__}: {e}'
        return _finish_load(summary)
    if manifest is None:
        summary['reason'] = 'no AOT artifacts shipped'
        return _finish_load(summary, count=False)
    stored = dict(manifest.get('fingerprint') or {})
    summary['fingerprint'] = stored
    current = env_fingerprint()
    mismatch = fingerprint_diff(stored, current)
    if mismatch:
        summary['outcome'] = 'stale'
        summary['mismatch'] = {
            k: {'stored': stored.get(k), 'current': current.get(k)}
            for k in mismatch
        }
        return _finish_load(summary)
    from jax.experimental import serialize_executable as _se

    from ..obs.xla import call_key

    by_id = {e.get('id'): e for e in manifest.get('entries', [])}
    loaded = 0
    try:
        for entry_id, fn, spec_args, kwargs in _serving_plans(
            model, ladder=tuple(ladder), max_actions=max_actions
        ):
            entry = by_id.get(entry_id)
            if entry is None:
                summary['reason'] = (
                    f'artifact {entry_id!r} missing from the manifest '
                    f'(shipped ladder {manifest.get("ladder")}, '
                    f'max_actions {manifest.get("max_actions")})'
                )
                return _finish_load(summary)
            signature = _plan_signature(fn, spec_args, kwargs)
            if entry.get('signature') != signature:
                # exported for a different architecture / static config:
                # the same staleness class as a fingerprint mismatch
                summary['outcome'] = 'stale'
                summary['mismatch'] = {
                    entry_id: {
                        'stored': entry.get('signature'),
                        'current': signature,
                    }
                }
                return _finish_load(summary)
            blob = _read_artifact(aot_dir, entry)
            compiled = _se.deserialize_and_load(*pickle.loads(blob))
            cost = (
                (entry['cost_flops'], entry['cost_bytes'])
                if entry.get('cost_flops') is not None
                else None
            )
            key = call_key(spec_args, kwargs, fn._static_names)
            fn.preload(key, compiled, cost=cost)
            loaded += 1
            summary['entries_loaded'] = loaded
            counter('serve/aot_loads', unit='count').inc(1, outcome='hit')
    except Exception as e:
        summary['reason'] = f'{type(e).__name__}: {e}'
        return _finish_load(summary)
    summary['outcome'] = 'hit'
    return _finish_load(summary, count=False)


def _finish_load(summary: Dict[str, Any], count: bool = True) -> Dict[str, Any]:
    """Count the terminal outcome, emit the event, stash the summary.

    ``hit`` outcomes were already counted per artifact (the smoke's
    "hits ≥ ladder rungs" contract needs per-artifact granularity);
    ``stale``/``miss`` count once per load attempt. A fully absent
    ``aot/`` dir does not count a miss — a model-backed service with no
    registry must not page anyone — but still stashes the summary.
    """
    if count and summary['outcome'] in ('stale', 'miss'):
        counter('serve/aot_loads', unit='count').inc(
            1, outcome=summary['outcome']
        )
    _emit_event('aot_load', **summary)
    _note_load(summary)
    return summary


# --------------------------------------------------------------------------
# the persistent compile cache (tier 2)
# --------------------------------------------------------------------------

_CACHE_LOCK = threading.Lock()
_CACHE_ENABLED: Optional[str] = None


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at ``path`` (idempotent).

    ``path`` defaults to ``SOCCERACTION_TPU_COMPILE_CACHE``
    (:func:`socceraction_tpu.config.compile_cache_dir`); with neither
    set this is a no-op returning ``None`` — the cache stays off, the
    stock jax behavior. Enabled, every XLA compile is written to (and
    looked up in) ``path`` with no size/time floor, so a replica whose
    fingerprint missed the shipped artifacts still warms from the cache
    a sibling already paid for. Returns the active cache dir.
    """
    global _CACHE_ENABLED
    path = path or compile_cache_dir()
    if not path:
        return _CACHE_ENABLED
    with _CACHE_LOCK:
        if _CACHE_ENABLED == path:
            return path
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update('jax_compilation_cache_dir', path)
        # replicas share SMALL programs too (the formula kernel, the
        # low rungs): no entry-size or compile-time floor
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', -1)
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)
        _CACHE_ENABLED = path
    _emit_event('compile_cache_enabled', path=path)
    return path
