"""Micro-batching queue: coalesce concurrent rating requests into buckets.

One request is one match's (or one session window's) actions — a single
game-row of a device batch. Dispatching each request alone would pay a
full XLA dispatch per request and compile one program per distinct batch
shape; the batcher instead multiplexes every concurrent caller onto the
fused one-dispatch rating path:

- **coalescing** — requests accumulate in a bounded queue and flush as
  ONE device batch when ``max_batch_size`` requests are waiting or the
  oldest request has aged ``max_wait_ms`` (latency bound), whichever
  comes first;
- **shape buckets** — a flush of ``n`` requests is padded up to the
  power-of-two bucket ladder
  (:func:`socceraction_tpu.core.batch.bucket_ladder`), so steady-state
  traffic executes a small, pinned set of compiled shapes instead of
  retracing per unique batch size;
- **admission control** — past ``max_queue`` waiting requests, ``submit``
  raises :class:`Overloaded` immediately instead of growing the queue
  (and its memory) without bound; callers shed load explicitly.

The batcher is policy-only: it never touches jax. A ``runner`` callable
(the service's flush, :meth:`socceraction_tpu.serve.service.RatingService._flush`)
turns a list of payloads plus a bucket size into one result per payload;
the batcher owns the queue, the deadline clock, the futures and the
``serve/*`` telemetry. Everything is thread-safe; all device work happens
on the flusher threads.

With ``n_lanes > 1`` (the mesh-serving fan-out) N flusher threads drain
the ONE shared queue concurrently: each lane takes a flush, dispatches it
through the runner with its lane index (one in-flight dispatch per
replica device), and goes back for more — a sick or slow replica never
blocks the others' take loop. Crash supervision is per lane: a lane's
restart budget is its own, and a permanently dead lane strands nothing —
its un-flushed requests go back to the shared queue for live lanes, and
only the death of the LAST live lane fails the queue and rejects new
submits. Flush-scoped telemetry carries a ``replica=`` label when lanes
are named (``lane_names``, validated against the
:class:`~socceraction_tpu.obs.wire.ReplicaRegistry` by the service).
"""

from __future__ import annotations

import inspect
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..core.batch import bucket_ladder
from ..obs import counter, gauge, histogram, span
from ..obs.context import (
    DeadlineExceeded,
    RequestContext,
    record_request_done,
    record_request_enqueue,
    record_segment,
)
from ..obs.recorder import RECORDER
from ..resil.faults import fault_point

__all__ = ['DeadlineExceeded', 'MicroBatcher', 'Overloaded']


class Overloaded(RuntimeError):
    """Raised by ``submit`` when the admission queue is full.

    The explicit load-shedding signal: the caller sees it synchronously
    (no future is created) and can retry, down-sample or propagate a 429 —
    the alternative, unbounded queueing, turns overload into unbounded
    memory growth and unbounded latency for every request behind it.
    """


class _Request:
    __slots__ = ('payload', 'kind', 'future', 't0', 'ctx')

    def __init__(
        self, payload: Any, kind: str, ctx: Optional[RequestContext] = None
    ) -> None:
        self.payload = payload
        self.kind = kind
        self.future: Future = Future()
        self.ctx = ctx
        self.t0 = ctx.enqueue_t if ctx is not None else time.perf_counter()


class MicroBatcher:
    """Thread-safe micro-batching queue in front of a batch runner.

    Parameters
    ----------
    runner : callable
        ``runner(payloads, bucket) -> results`` — rates one coalesced
        batch; ``bucket >= len(payloads)`` is the ladder size the device
        batch must be padded to, and ``results`` must align with
        ``payloads``. Runs on a flusher thread only. A runner declaring
        a ``lane`` parameter receives the dispatching lane's index as
        ``lane=<int>`` (the service routes it to that replica's device);
        a two-argument runner keeps working unchanged.
    max_batch_size : int
        Flush immediately once this many requests are waiting. Also the
        top of the bucket ladder (rounded up to a power of two).
    max_wait_ms : float
        Deadline flush: a request never waits longer than this for
        co-batching before its flush is dispatched.
    max_queue : int
        Admission bound: ``submit`` past this many waiting requests
        raises :class:`Overloaded`.
    on_crash : callable, optional
        ``on_crash(exc)`` invoked (once, on the dying thread) if the
        flusher thread dies *permanently* — i.e. an exception escapes
        the take loop rather than a flush (flush failures land on the
        affected futures and the thread lives on) and the restart
        supervisor's budget is spent. The service hooks its
        flight-recorder dump here.
    max_flusher_restarts : int
        Supervised-restart budget: a crashed flusher thread is replaced
        (its un-flushed requests re-queued at the front, so nothing is
        stranded or reordered) up to this many times within
        ``flusher_restart_window_s``. Past the budget the crash is
        permanent: queued requests fail, new submits are rejected and
        ``on_crash`` fires — a crash loop must not masquerade as a
        healthy service. ``0`` restores the pre-supervision behavior
        (every crash is permanent).
    flusher_restart_window_s : float
        The sliding window the restart budget is counted over.
    on_restart : callable, optional
        ``on_restart(exc, n_in_window)`` invoked (on the dying thread,
        before its replacement starts) per supervised restart; must not
        raise (it is guarded). Restarts are always recorded in the
        flight recorder and counted under ``serve/flusher_restarts``
        regardless — the hook is for callers that want more (no debug
        bundle by default: the permanent-death ``flusher_crash`` bundle
        must stay the newest artifact after a crash loop).
    on_request_done : callable, optional
        ``on_request_done(ctx, kind, wall_s, status)`` invoked on the
        flusher thread for every request that reaches a terminal state
        (``status`` in ``'ok'`` | ``'error'`` | ``'expired'``). The
        service hooks its SLO engine here; the hook must not raise (a
        raising hook is swallowed, never the flush).
    n_lanes : int
        Concurrent flusher threads draining the shared queue (default 1,
        the classic single-flusher batcher). The mesh service runs one
        lane per replica so every replica keeps one dispatch in flight.
        Restart budgets, crash state and flush telemetry are per lane.
    lane_names : sequence of str, optional
        Telemetry identity per lane (the service passes replica ids).
        When given, flush-scoped ``serve/*`` series carry a
        ``replica=<name>`` label; when omitted they stay unlabeled, so a
        single-lane batcher's series are byte-identical to before.
    """

    def __init__(
        self,
        runner: Callable[[List[Any], int], Sequence[Any]],
        *,
        max_batch_size: int = 64,
        max_wait_ms: float = 2.0,
        max_queue: int = 256,
        on_crash: Optional[Callable[[BaseException], None]] = None,
        on_request_done: Optional[
            Callable[[Optional[RequestContext], str, float, str], None]
        ] = None,
        max_flusher_restarts: int = 3,
        flusher_restart_window_s: float = 60.0,
        on_restart: Optional[Callable[[BaseException, int], None]] = None,
        n_lanes: int = 1,
        lane_names: Optional[Sequence[str]] = None,
    ) -> None:
        if max_batch_size < 1:
            raise ValueError('max_batch_size must be >= 1')
        if max_queue < max_batch_size:
            raise ValueError('max_queue must be >= max_batch_size')
        if n_lanes < 1:
            raise ValueError('n_lanes must be >= 1')
        if lane_names is not None and len(lane_names) != n_lanes:
            raise ValueError(
                f'{len(lane_names)} lane_names for {n_lanes} lanes'
            )
        self._runner = runner
        self.max_batch_size = max_batch_size
        self.max_wait_s = max_wait_ms / 1000.0
        self.max_queue = max_queue
        self.ladder: Tuple[int, ...] = bucket_ladder(max_batch_size)
        self.n_lanes = int(n_lanes)
        self.lane_names: Optional[Tuple[str, ...]] = (
            tuple(lane_names) if lane_names is not None else None
        )
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[_Request] = []
        self._closed = False
        self._threads: Dict[int, threading.Thread] = {}
        self._on_crash = on_crash
        self._on_request_done = on_request_done
        self._crashed_lanes: Dict[int, BaseException] = {}
        self._last_flush_t: Optional[float] = None
        self.max_flusher_restarts = int(max_flusher_restarts)
        self.flusher_restart_window_s = float(flusher_restart_window_s)
        self._on_restart = on_restart
        self._restart_times: Dict[int, 'deque[float]'] = {
            i: deque() for i in range(self.n_lanes)
        }
        self._restarts_total = 0

    @property
    def _runner(self) -> Callable:
        return self._runner_fn

    @_runner.setter
    def _runner(self, runner: Callable) -> None:
        # a runner declaring `lane` gets the dispatching lane's index;
        # legacy (payloads, bucket) runners keep working unchanged. A
        # setter (not a one-shot __init__ probe) so tests that swap
        # `_runner` for a two-arg stub get the legacy calling convention.
        self._runner_fn = runner
        try:
            self._runner_takes_lane = (
                'lane' in inspect.signature(runner).parameters
            )
        except (TypeError, ValueError):  # builtins / C callables
            self._runner_takes_lane = False

    def _lane_kw(self, lane: int) -> Dict[str, str]:
        """The ``replica=`` label of one lane's flush-scoped series."""
        if self.lane_names is None:
            return {}
        return {'replica': self.lane_names[lane]}

    def _lane_label(self, lane: int) -> str:
        return (
            self.lane_names[lane] if self.lane_names is not None
            else str(lane)
        )

    # -- submission --------------------------------------------------------

    def submit(
        self,
        payload: Any,
        *,
        kind: str = 'rate',
        ctx: Optional[RequestContext] = None,
    ) -> Future:
        """Enqueue one request; returns its :class:`concurrent.futures.Future`.

        Raises :class:`Overloaded` when the admission queue is full and
        ``RuntimeError`` after :meth:`close`. ``kind`` is a low-cardinality
        telemetry label (``rate`` | ``session`` | ``warmup``). ``ctx``, when
        given, is the request's trace identity: its id links the request
        into the flush span and run-log events, and its deadline is
        enforced at flush time — an expired request is failed with
        :class:`~socceraction_tpu.obs.context.DeadlineExceeded` instead
        of being dispatched late.
        """
        req = _Request(payload, kind, ctx)
        with self._cond:
            if self._closed:
                raise RuntimeError('batcher is closed')
            if len(self._crashed_lanes) >= self.n_lanes:
                exc = next(iter(self._crashed_lanes.values()))
                raise RuntimeError(
                    f'flusher thread died: {exc!r} '
                    '(see the debug bundle; start a new service)'
                )
            if len(self._queue) >= self.max_queue:
                counter('serve/rejected_total', unit='requests').inc(1)
                raise Overloaded(
                    f'{len(self._queue)} requests already queued '
                    f'(max_queue={self.max_queue}); shed load or raise the bound'
                )
            self._queue.append(req)
            depth = len(self._queue)
            if not self._threads:
                for lane in range(self.n_lanes):
                    self._spawn_lane(lane)
            self._cond.notify()
        gauge('serve/queue_depth', unit='requests').set(depth)
        counter('serve/requests', unit='requests').inc(1, kind=kind)
        if ctx is not None:
            req.future.request_id = ctx.request_id  # type: ignore[attr-defined]
            req.future.context = ctx  # type: ignore[attr-defined]
            record_request_enqueue(ctx, depth)
        return req.future

    def _spawn_lane(self, lane: int) -> None:
        """Start (or replace) lane ``lane``'s flusher thread. Lock held."""
        name = 'serve-flusher' if self.n_lanes == 1 else (
            f'serve-flusher-{self._lane_label(lane)}'
        )
        t = threading.Thread(
            target=self._flush_loop, args=(lane,), name=name, daemon=True
        )
        self._threads[lane] = t
        t.start()

    def bucket_for(self, n: int) -> int:
        """The smallest ladder rung admitting ``n`` requests."""
        for b in self.ladder:
            if b >= n:
                return b
        return self.ladder[-1]

    # -- the flusher thread ------------------------------------------------

    def _take(self) -> Tuple[List[_Request], str]:
        """Block until a flush is due; pop and return (requests, reason).

        Called on the flusher thread. Returns ``([], 'closed')`` when the
        batcher is closed and drained.
        """
        with self._cond:
            while True:
                if self._queue:
                    if len(self._queue) >= self.max_batch_size:
                        reason = 'full'
                        break
                    if self._closed:
                        reason = 'close'
                        break
                    deadline = self._queue[0].t0 + self.max_wait_s
                    now = time.perf_counter()
                    if now >= deadline:
                        reason = 'deadline'
                        break
                    self._cond.wait(timeout=deadline - now)
                elif self._closed:
                    return [], 'closed'
                else:
                    self._cond.wait()
            take = self._queue[: self.max_batch_size]
            del self._queue[: len(take)]
            depth = len(self._queue)
        gauge('serve/queue_depth', unit='requests').set(depth)
        return take, reason

    def _flush_loop(self, lane: int = 0) -> None:
        taken: List[_Request] = []
        try:
            while True:
                taken, reason = self._take()
                if not taken:
                    return
                # the named chaos point for flusher-death schedules: an
                # injected error here escapes the take loop (not the
                # per-flush guard) and exercises the restart supervisor
                fault_point('batcher.flush', requests=len(taken))
                self._flush(taken, reason, lane)
                taken = []
                self._last_flush_t = time.monotonic()
        except BaseException as e:  # noqa: BLE001 - the thread is dying
            self._crash(e, taken, lane)

    def _crash(
        self, e: BaseException, taken: List[_Request], lane: int
    ) -> None:
        """A dying flusher thread's last act: restart, retire or fail all.

        Within the lane's budget (``max_flusher_restarts`` per
        ``flusher_restart_window_s``, counted per lane) the thread is
        replaced and the requests it had taken but not flushed go back
        to the FRONT of the queue — order preserved, no future stranded,
        callers never see the crash. Past the budget the lane's death is
        permanent — but with live lanes remaining it retires ALONE: its
        taken requests re-queue for the survivors and submits keep
        flowing (the mesh topology's single-sick-replica degradation).
        Only the LAST live lane's permanent death fails the queue,
        rejects new submits and fires ``on_crash``.
        """
        now = time.monotonic()
        restarted = False
        n_window = 0
        with self._cond:
            times = self._restart_times[lane]
            cutoff = now - self.flusher_restart_window_s
            while times and times[0] < cutoff:
                times.popleft()
            if (
                not self._closed
                and len(times) < self.max_flusher_restarts
            ):
                times.append(now)
                self._restarts_total += 1
                n_window = len(times)
                self._queue[:0] = taken
                restarted = True
        if restarted:
            # account + hook BEFORE the replacement starts: the new
            # thread may crash instantly (a persistent fault), and its
            # permanent-death dump must come chronologically after this
            # restart's, not race it
            counter('serve/flusher_restarts', unit='count').inc(
                1, **self._lane_kw(lane)
            )
            restart_payload = {
                'error': f'{type(e).__name__}: {e}',
                'restarts_in_window': n_window,
                'requeued': len(taken),
                'lane': self._lane_label(lane),
            }
            RECORDER.record('flusher_restart', **restart_payload)
            try:
                # dual-write to the run log so `obsctl resil <runlog>`
                # can show supervised restarts post-mortem (the recorder
                # ring dies with the process)
                from ..obs.trace import current_runlog

                log = current_runlog()
                if log is not None:
                    log.event('flusher_restart', **restart_payload)
            except Exception:
                pass  # telemetry must not fail the restart
            if self._on_restart is not None:
                try:
                    self._on_restart(e, n_window)
                except Exception:  # the hook must not kill the handler
                    pass
            with self._cond:
                # spawn even if close() raced in: the replacement drains
                # a closed queue correctly and exits via _take
                self._spawn_lane(lane)
                self._cond.notify_all()
            return
        counter('serve/flusher_crashes', unit='count').inc(
            1, **self._lane_kw(lane)
        )
        with self._cond:
            self._crashed_lanes[lane] = e
            last_lane = len(self._crashed_lanes) >= self.n_lanes
            if last_lane:
                dropped, self._queue = self._queue, []
            else:
                # survivors drain these: order preserved, nothing strands
                self._queue[:0] = taken
                self._cond.notify_all()
        RECORDER.record(
            'flusher_crash', error=f'{type(e).__name__}: {e}',
            queue_depth=self.queue_depth, lane=self._lane_label(lane),
            last_lane=last_lane,
        )
        if not last_lane:
            return
        # The LAST flusher died: anything queued (and any future submit)
        # would otherwise strand forever — fail it all and hand the
        # exception to the crash hook (the service's debug-bundle dump).
        dropped = taken + dropped
        for r in dropped:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(
                    RuntimeError(f'flusher thread died: {e!r}')
                )
        if self._on_crash is not None:
            try:
                self._on_crash(e)
            except Exception:  # the hook must not mask the crash
                pass

    def _notify_done(self, req: _Request, wall_s: float, status: str) -> None:
        """Invoke the terminal-state hook; a raising hook never escapes."""
        if self._on_request_done is not None:
            try:
                self._on_request_done(req.ctx, req.kind, wall_s, status)
            except Exception:
                pass

    def _expire(self, req: _Request, now: float) -> None:
        """Fail one deadline-expired request without dispatching it.

        The whole wait was queue time, so it is attributed to the
        ``queue_wait`` segment; the request never reaches the runner
        (a caller that stopped waiting must not burn device time) and —
        because the future resolves with an error — is never recorded
        by the service's traffic capture.
        """
        ctx = req.ctx
        assert ctx is not None  # only ctx-carrying requests have deadlines
        wait = now - req.t0
        ctx.segments['queue_wait'] = wait
        record_segment('queue_wait', wait, ctx.request_id)
        counter('serve/deadline_expired', unit='requests').inc(1, kind=req.kind)
        err = DeadlineExceeded(
            f'request {ctx.request_id} spent {wait * 1e3:.1f}ms queued, past '
            f'its deadline (never dispatched); slow down or raise the deadline'
        )
        record_request_done(ctx, 'expired', wait, error=str(err))
        self._notify_done(req, wait, 'expired')
        req.future.set_exception(err)

    def _flush(self, take: List[_Request], reason: str, lane: int = 0) -> None:
        # Transition every future to RUNNING; a caller that cancel()ed
        # while queued is dropped here. After this point cancel() can no
        # longer succeed, so set_result below cannot raise
        # InvalidStateError and kill the flusher thread.
        take = [r for r in take if r.future.set_running_or_notify_cancel()]
        try:
            self._flush_running(take, reason, lane)
        except BaseException as e:  # noqa: BLE001 - never strand a future
            # a RUNNING future whose flush died any other way than the
            # runner path below would hang its caller forever (and the
            # escaping exception would kill the flusher thread for
            # everyone else) — fail what this flush owns, with the same
            # per-request error accounting as a runner failure (the SLO
            # engine and the trace must see these failures too), and
            # live on
            self._fail_requests(take, e)

    def _fail_requests(
        self,
        requests: List[_Request],
        exc: BaseException,
        *,
        bucket: Optional[int] = None,
        coalesced: Optional[int] = None,
    ) -> None:
        """Resolve every unresolved request as failed, fully accounted.

        Each request's accounting (request_done event, SLO hook) is
        individually guarded: if telemetry itself is what raised (a full
        disk under the run log), the remaining futures must still fail
        rather than strand.
        """
        done = time.perf_counter()
        for r in requests:
            if r.future.done():
                continue
            wall = done - r.t0
            if r.ctx is not None:
                try:
                    record_request_done(
                        r.ctx, 'error', wall, bucket=bucket,
                        coalesced=coalesced,
                        error=f'{type(exc).__name__}: {exc}',
                    )
                except Exception:
                    pass
            self._notify_done(r, wall, 'error')
            r.future.set_exception(exc)

    def _flush_running(
        self, take: List[_Request], reason: str, lane: int = 0
    ) -> None:
        now = time.perf_counter()
        live: List[_Request] = []
        for r in take:
            if r.ctx is not None and r.ctx.expired(now):
                self._expire(r, now)
            else:
                live.append(r)
        if not live:
            return
        lane_kw = self._lane_kw(lane)
        bucket = self.bucket_for(len(live))
        fill = len(live) / bucket
        counter('serve/flushes', unit='count').inc(1, reason=reason, **lane_kw)
        gauge('serve/batch_fill_ratio', unit='ratio').set(fill)
        request_ids = [r.ctx.request_id for r in live if r.ctx is not None]
        RECORDER.record(
            'serve_queue', taken=len(live), bucket=bucket, reason=reason,
            queue_depth=self.queue_depth, fill_ratio=fill,
            request_ids=request_ids, lane=self._lane_label(lane),
        )
        # every coalesced request's queue wait ends here: the flush owns
        # the rest of the wall (pad/dispatch/slice, recorded by the runner)
        flush_t0 = time.perf_counter()
        for r in live:
            wait = flush_t0 - r.t0
            if r.ctx is not None:
                r.ctx.segments['queue_wait'] = wait
            record_segment(
                'queue_wait', wait, r.ctx.request_id if r.ctx else None,
                **lane_kw,
            )
        try:
            # the flush span lists the coalesced request ids: the link
            # from one shared dispatch back to every request it served
            with span(
                'serve/flush', requests=len(live), bucket=bucket,
                request_ids=request_ids, **lane_kw,
            ) as flush_span:
                with histogram('serve/flush_seconds', unit='s').time(
                    bucket=str(bucket), **lane_kw
                ):
                    payloads = [r.payload for r in live]
                    if self._runner_takes_lane:
                        results = self._runner(payloads, bucket, lane=lane)
                    else:
                        results = self._runner(payloads, bucket)
            if len(results) != len(live):
                raise RuntimeError(
                    f'runner returned {len(results)} results for '
                    f'{len(live)} requests'
                )
        except BaseException as e:  # noqa: BLE001 - failures go to the futures
            self._fail_requests(live, e, bucket=bucket, coalesced=len(live))
            return
        done = time.perf_counter()
        lat = histogram('serve/request_seconds', unit='s')
        for r, out in zip(live, results):
            wall = done - r.t0
            lat.observe(
                wall,
                exemplar=(
                    {'request_id': r.ctx.request_id} if r.ctx else None
                ),
                kind=r.kind,
            )
            if r.ctx is not None:
                record_request_done(
                    r.ctx, 'ok', wall, bucket=bucket, coalesced=len(live),
                    flush_span_id=flush_span.span_id,
                )
            self._notify_done(r, wall, 'ok')
            r.future.set_result(out)

    # -- introspection -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        """Requests currently waiting for a flush."""
        with self._lock:
            return len(self._queue)

    @property
    def crashed(self) -> Optional[BaseException]:
        """The exception that killed the LAST flusher thread, or None.

        A multi-lane batcher with live lanes remaining reports None here
        (it still serves); :attr:`dead_lanes` names partial casualties.
        """
        with self._lock:
            if len(self._crashed_lanes) < self.n_lanes:
                return None
            return next(iter(self._crashed_lanes.values()))

    @property
    def dead_lanes(self) -> Dict[int, BaseException]:
        """Lanes whose flusher died permanently (index -> exception)."""
        with self._lock:
            return dict(self._crashed_lanes)

    @property
    def flusher_restarts(self) -> int:
        """Supervised flusher restarts performed so far (lifetime)."""
        with self._lock:
            return self._restarts_total

    @property
    def flusher_alive(self) -> bool:
        """False once ALL flusher lanes have died (crash or exit); True
        while any runs or before they have lazily started."""
        with self._lock:
            if len(self._crashed_lanes) >= self.n_lanes:
                return False
            threads = list(self._threads.values())
        return not threads or any(t.is_alive() for t in threads)

    @property
    def last_flush_age_s(self) -> Optional[float]:
        """Seconds since the last completed flush (None before any)."""
        t = self._last_flush_t
        return None if t is None else time.monotonic() - t

    # -- lifecycle ---------------------------------------------------------

    def close(self, *, drain: bool = True) -> None:
        """Stop the flusher. ``drain=True`` (default) rates what is queued
        first; ``drain=False`` fails queued requests with RuntimeError."""
        with self._cond:
            if not self._closed:
                self._closed = True
                if not drain:
                    dropped, self._queue = self._queue, []
                    for r in dropped:
                        if r.future.set_running_or_notify_cancel():
                            r.future.set_exception(
                                RuntimeError('batcher closed before flush')
                            )
            self._cond.notify_all()
            threads = list(self._threads.values())
        for t in threads:
            t.join(timeout=30.0)

    def __enter__(self) -> 'MicroBatcher':
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
