"""Traffic capture: a bounded ring of recently served requests.

Shadow evaluation (:mod:`socceraction_tpu.learn.shadow`) judges a
candidate model on *the traffic the service actually saw*, not on a
held-out split — the replay-based evaluation PAPERS.md's "What Happened
Next?" (2106.01786) argues for. :class:`TrafficCapture` is the source of
that traffic: a thread-safe, bounded, host-only ring the
:class:`~socceraction_tpu.serve.service.RatingService` feeds as it
serves:

- **one-shot requests** — every successful :meth:`RatingService.rate`
  submission records a copy of the request frame (``deque`` with
  ``maxlen``: the ring holds the most recent requests and silently
  drops the oldest);
- **streaming sessions** — every committed
  :meth:`~socceraction_tpu.serve.session.MatchSession.add_actions` tick
  appends its new rows to a per-match stream, so a live match replays
  as the full action sequence it actually produced (suffix windows
  alone would truncate the label lookahead). Streams are bounded too:
  past ``max_sessions`` matches, the least-recently-updated stream is
  evicted.

Capture is copy-on-record (callers may mutate their frames after
submission) and never touches the device — recording costs a DataFrame
copy and a lock, cheap enough to leave on in production. ``Overloaded``
submissions are *not* captured: shed load never happened, and replaying
it would skew calibration toward burst traffic.

Everything is reported under the ``serve`` telemetry area
(``serve/captured_requests``, ``serve/captured_actions``,
``serve/capture_evictions{kind}``).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Tuple

import pandas as pd

from ..obs import counter

__all__ = ['TrafficCapture']


class TrafficCapture:
    """Bounded host-side ring of recently served rating traffic.

    Parameters
    ----------
    max_frames : int
        One-shot request frames kept (newest win).
    max_sessions : int
        Per-match session streams kept (least-recently-updated evicted).
    max_session_actions : int
        Row bound per session stream; a match longer than this keeps its
        most recent rows (the stream stays a contiguous suffix, so the
        replayed sequence is still a valid action sequence).
    """

    def __init__(
        self,
        max_frames: int = 256,
        max_sessions: int = 64,
        max_session_actions: int = 4096,
    ) -> None:
        self._lock = threading.Lock()
        self._frames: 'deque[Tuple[pd.DataFrame, Any]]' = deque(
            maxlen=int(max_frames)
        )
        self.max_sessions = int(max_sessions)
        self.max_session_actions = int(max_session_actions)
        self._sessions: 'OrderedDict[Any, Dict[str, Any]]' = OrderedDict()

    # -- recording (called by the serving layer) ---------------------------

    def record_frame(
        self, actions: pd.DataFrame, home_team_id: Any, *, copy: bool = True
    ) -> None:
        """Record one successfully submitted one-shot request.

        ``copy=False`` hands ownership of ``actions`` to the ring (the
        caller must never mutate it afterwards) — the serving layer
        copies on the *caller* thread at submit time so the flusher
        thread's success callback never pays a DataFrame copy inside the
        flush loop.
        """
        if self._frames.maxlen == 0:
            return  # one-shot capture disabled: no phantom metrics either
        frame = actions.copy() if copy else actions
        with self._lock:
            if len(self._frames) == self._frames.maxlen:
                counter('serve/capture_evictions', unit='count').inc(
                    1, kind='frame'
                )
            self._frames.append((frame, home_team_id))
        counter('serve/captured_requests', unit='count').inc(1, kind='rate')
        counter('serve/captured_actions', unit='actions').inc(len(frame))

    def record_session(
        self, match_id: Any, new_actions: pd.DataFrame, home_team_id: Any
    ) -> None:
        """Append one committed session tick's new rows to its stream."""
        if self.max_sessions <= 0 or self.max_session_actions <= 0:
            return  # session capture disabled: no phantom metrics either
        part = new_actions.copy()
        with self._lock:
            stream = self._sessions.get(match_id)
            if stream is None:
                stream = {'home_team_id': home_team_id, 'parts': [], 'rows': 0}
                self._sessions[match_id] = stream
                while len(self._sessions) > self.max_sessions:
                    self._sessions.popitem(last=False)
                    counter('serve/capture_evictions', unit='count').inc(
                        1, kind='session'
                    )
            self._sessions.move_to_end(match_id)
            stream['parts'].append(part)
            stream['rows'] += len(part)
            # keep the most recent rows: drop whole leading parts first,
            # then trim the (new) head part if one part alone overflows
            while (
                stream['rows'] > self.max_session_actions
                and len(stream['parts']) > 1
            ):
                dropped = stream['parts'].pop(0)
                stream['rows'] -= len(dropped)
            if stream['rows'] > self.max_session_actions:
                only = stream['parts'][0]
                stream['parts'][0] = only.iloc[
                    len(only) - self.max_session_actions :
                ]
                stream['rows'] = self.max_session_actions
        counter('serve/captured_requests', unit='count').inc(1, kind='session')
        counter('serve/captured_actions', unit='actions').inc(len(part))

    # -- replay (consumed by the learn loop) -------------------------------

    def frames(self) -> List[Tuple[pd.DataFrame, Any]]:
        """Every captured traffic unit as ``(frame, home_team_id)`` pairs.

        One-shot requests come back as recorded; each session stream as
        one concatenated frame in arrival order. Every returned frame is
        a fresh copy — callers may pack/mutate it freely without
        corrupting the ring (later replays must see the traffic as
        recorded; the bitwise-replay contract depends on it).

        Only reference snapshots happen under the ring lock; the copies
        and concats run outside it, so a replay over a full ring never
        stalls the serving threads' ``record_*`` calls. The stored
        frames themselves are immutable by construction (``record_*``
        copies on the way in and nothing mutates them after), so
        copying them lock-free is safe.
        """
        with self._lock:
            raw = list(self._frames)
            streams = [
                (list(s['parts']), s['home_team_id'])
                for s in self._sessions.values()
                if s['parts']
            ]
        out = [(frame.copy(), home) for frame, home in raw]
        for parts, home in streams:
            whole = parts[0].copy() if len(parts) == 1 else pd.concat(parts)
            out.append((whole, home))
        return out

    def clear(self) -> None:
        """Drop everything captured so far (post-promotion reset)."""
        with self._lock:
            self._frames.clear()
            self._sessions.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._frames) + len(self._sessions)

    @property
    def total_actions(self) -> int:
        """Rows currently captured across frames and session streams."""
        with self._lock:
            return sum(len(f) for f, _ in self._frames) + sum(
                s['rows'] for s in self._sessions.values()
            )
