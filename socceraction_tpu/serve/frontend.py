"""The serving front door: a stdlib RPC server over one RatingService.

N client *processes* talk to one mesh-serving process. The
:class:`RatingService` is in-process only — its ``rate()`` returns a
``Future``, which cannot cross a process boundary — so this module puts
the same front door on a unix socket:

- :class:`ServingFrontend` — a ``ThreadingHTTPServer`` over AF_UNIX with
  the exact posture of the telemetry endpoint
  (:mod:`socceraction_tpu.obs.endpoint`): socket directory ``0700``,
  socket file ``0600``, filesystem permissions ARE the access control;
  one daemon thread per in-flight request, host-side work only on those
  threads (packing happens in :meth:`RatingService.rate` on the handler
  thread; the device dispatch stays on the service's flush lanes).
- :class:`FrontendClient` — the client half: mints a
  :class:`~socceraction_tpu.obs.context.RequestContext` per call and
  ships ``ctx.to_wire()`` with the request, so the ``request_id`` (and
  the remaining deadline budget) survive the hop and ``obsctl trace
  <id> client.jsonl server.jsonl`` stitches client → front end →
  replica flush into one timeline.

Admission control and SLO shedding run BEFORE the device ever sees a
request, exactly as in-process: the service's queue bound raises
``Overloaded`` and burn-rate shedding raises ``SLOShed``, both mapped to
``429`` with a machine-readable body (``retriable`` + the shed reason),
so a client process can back off the same way an in-process caller
does. A request whose shipped deadline expires while queued maps to
``504``; malformed requests to ``400``; anything else to ``500`` with
the exception text. Sessions get the same treatment: ``/session/open``
returns a server-side session id, ``/session/add`` rates the next slice
through the session's O(new actions) window path, ``/session/close``
drops it.

Values come back as plain JSON (columns + rows + index). The wire
format is deliberately boring — a dict of SPADL columns — because the
clients this exists for (the bench's fan-out driver, a live ingestion
sidecar) already hold exactly that.
"""

from __future__ import annotations

import http.client
import http.server
import json
import os
import socket
import socketserver
import stat
import tempfile
import threading
import uuid
from typing import Any, Dict, Optional, Tuple
from urllib.parse import urlsplit

import numpy as np
import pandas as pd

from ..obs import counter
from ..obs.context import DeadlineExceeded, RequestContext, new_request_context
from .batcher import Overloaded
from .service import RATING_COLUMNS, SLOShed

__all__ = ['FrontendClient', 'FrontendError', 'ServingFrontend', 'default_frontend_path']


class FrontendError(RuntimeError):
    """A front-end request failed; carries the HTTP status and payload."""

    def __init__(self, status: int, payload: Dict[str, Any]) -> None:
        self.status = int(status)
        self.payload = dict(payload)
        super().__init__(f'frontend returned {status}: {payload.get("error")}')

    @property
    def retriable(self) -> bool:
        """Whether backing off and retrying can help (shed/overload)."""
        return bool(self.payload.get('retriable'))


def default_frontend_path() -> str:
    """The default unix-socket path for this process's serving front end.

    Same layout policy as the telemetry endpoint's socket: a per-user
    ``0700`` directory under the tempdir. One file per process —
    serving traffic and telemetry scrapes stay on separate sockets.
    """
    base = os.path.join(
        tempfile.gettempdir(), f'socceraction-tpu-serving-{os.getuid()}'
    )
    return os.path.join(base, f'frontend-{os.getpid()}.sock')


# -- wire forms -------------------------------------------------------------


def _frame_to_wire(frame: pd.DataFrame) -> Dict[str, Any]:
    """One SPADL slice as JSON-able columns (+ index for re-alignment)."""
    return {
        'columns': {
            c: np.asarray(frame[c]).tolist() for c in frame.columns
        },
        'index': np.asarray(frame.index).tolist(),
    }


def _frame_from_wire(doc: Dict[str, Any]) -> pd.DataFrame:
    cols = doc.get('columns')
    if not isinstance(cols, dict) or not cols:
        raise ValueError('actions must carry non-empty {column: [values]}')
    frame = pd.DataFrame(cols)
    index = doc.get('index')
    if index is not None:
        frame.index = pd.Index(index)
    return frame


def _values_to_wire(values: pd.DataFrame) -> Dict[str, Any]:
    return {
        'columns': list(values.columns),
        'index': np.asarray(values.index).tolist(),
        'values': np.asarray(values, dtype=np.float64).tolist(),
    }


def _values_from_wire(doc: Dict[str, Any]) -> pd.DataFrame:
    return pd.DataFrame(
        doc['values'], columns=doc['columns'], index=pd.Index(doc['index'])
    )


# -- the server -------------------------------------------------------------


class _UnixServer(socketserver.ThreadingMixIn, http.server.HTTPServer):
    """AF_UNIX ThreadingHTTPServer with the telemetry endpoint's posture."""

    daemon_threads = True
    address_family = socket.AF_UNIX
    request_queue_size = 128

    def server_bind(self) -> None:
        # permissions before accept, same rationale as obs.endpoint: the
        # file is chmod'd 0600 between bind and listen inside a 0700
        # directory, so the pre-chmod window is already access-controlled
        socketserver.TCPServer.server_bind(self)
        os.chmod(self.server_address, stat.S_IRUSR | stat.S_IWUSR)
        self.server_name = 'unix'
        self.server_port = 0

    def get_request(self) -> Tuple[Any, Any]:
        request, _ = self.socket.accept()
        return request, ('unix-peer', 0)


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = 'socceraction-tpu-serving'
    protocol_version = 'HTTP/1.1'

    def do_GET(self) -> None:  # noqa: N802 (http.server contract)
        frontend: 'ServingFrontend' = self.server.frontend  # type: ignore[attr-defined]
        path = urlsplit(self.path).path
        if path == '/health':
            try:
                body = frontend.service.health()
            except Exception as e:
                self._send(500, {'error': f'{type(e).__name__}: {e}'})
                return
            self._send(200, body)
        else:
            self._send(404, {
                'error': f'unknown route GET {path!r}',
                'routes': [
                    'GET /health', 'POST /rate', 'POST /scenarios',
                    'POST /session/*',
                ],
            })

    def do_POST(self) -> None:  # noqa: N802 (http.server contract)
        frontend: 'ServingFrontend' = self.server.frontend  # type: ignore[attr-defined]
        path = urlsplit(self.path).path
        try:
            n = int(self.headers.get('Content-Length') or 0)
            doc = json.loads(self.rfile.read(n) or b'{}')
        except (ValueError, OSError) as e:
            self._send(400, {'error': f'bad request body: {e}'})
            return
        try:
            if path == '/rate':
                self._send(200, frontend.handle_rate(doc))
            elif path == '/scenarios':
                self._send(200, frontend.handle_scenarios(doc))
            elif path == '/session/open':
                self._send(200, frontend.handle_session_open(doc))
            elif path == '/session/add':
                self._send(200, frontend.handle_session_add(doc))
            elif path == '/session/close':
                self._send(200, frontend.handle_session_close(doc))
            else:
                self._send(404, {'error': f'unknown route POST {path!r}'})
        except SLOShed as e:
            counter('serve/frontend_shed', unit='requests').inc(
                1, reason='slo'
            )
            self._send(429, {
                'error': 'slo_shed', 'retriable': True, 'reason': e.reason,
            })
        except Overloaded as e:
            counter('serve/frontend_shed', unit='requests').inc(
                1, reason='overload'
            )
            self._send(429, {
                'error': 'overloaded', 'retriable': True, 'detail': str(e),
            })
        except DeadlineExceeded as e:
            self._send(504, {'error': 'deadline_exceeded', 'detail': str(e)})
        except (KeyError, ValueError, TypeError) as e:
            self._send(400, {'error': f'{type(e).__name__}: {e}'})
        except Exception as e:  # a broken request must not kill the server
            self._send(500, {'error': f'{type(e).__name__}: {e}'})

    def _send(self, status: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload, sort_keys=True, default=str).encode('utf-8')
        self.send_response(status)
        self.send_header('Content-Type', 'application/json')
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def address_string(self) -> str:  # AF_UNIX peers have no host:port
        addr = self.client_address
        return addr[0] if isinstance(addr, tuple) and addr else 'unix-peer'

    def log_message(self, format: str, *args: Any) -> None:
        pass  # request accounting lives in serve/* metrics, not stderr


class ServingFrontend:
    """The running front door over one :class:`RatingService`.

    Parameters
    ----------
    service : RatingService
        The (possibly mesh-replicated) service all client processes
        share. Admission control, SLO shedding, coalescing, replica
        fan-out and breakers all stay the service's — the front end
        only moves requests across the process boundary.
    unix_path : str, optional
        Socket path (default :func:`default_frontend_path`).
    result_timeout_s : float
        Hard ceiling on one request's wait for its flush (deadline-less
        requests only; a shipped deadline bounds itself). A lane outage
        must surface as an error, not a wedged client connection.
    """

    def __init__(
        self,
        service: Any,
        *,
        unix_path: Optional[str] = None,
        result_timeout_s: float = 60.0,
    ) -> None:
        self.service = service
        self.result_timeout_s = float(result_timeout_s)
        path = unix_path or default_frontend_path()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, mode=0o700, exist_ok=True)
        if os.path.exists(path):
            os.unlink(path)  # AF_UNIX does not SO_REUSEADDR over stale files
        self._server = _UnixServer(path, _Handler)
        self._server.frontend = self  # type: ignore[attr-defined]
        self.address = path
        self._sessions: Dict[str, Any] = {}
        self._session_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name='serving-frontend',
            daemon=True,
        )
        self._thread.start()

    # -- route handlers (one handler thread each) --------------------------

    def _context_of(self, doc: Dict[str, Any]) -> Optional[RequestContext]:
        """The request's trace identity: shipped headers, or a fresh one.

        A client that ships ``ctx.to_wire()`` keeps its ``request_id``
        (and remaining deadline) across the hop; a bare request gets a
        front-end-minted context so the flush is traceable either way.
        """
        headers = doc.get('context')
        if headers is not None:
            return RequestContext.from_wire(headers)
        deadline_ms = doc.get('deadline_ms')
        return new_request_context(
            str(doc.get('kind') or 'rate'),
            deadline_ms=float(deadline_ms) if deadline_ms is not None else None,
        )

    def _await(self, future: Any, ctx: Optional[RequestContext]) -> Any:
        remaining = ctx.remaining_s() if ctx is not None else None
        timeout = (
            self.result_timeout_s if remaining is None
            else max(0.0, remaining) + 5.0  # grace for the expiry error path
        )
        return future.result(timeout=timeout)

    def handle_rate(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """``POST /rate``: rate one match frame through the service.

        Reconstructs the client's :class:`RequestContext` from the wire
        (hop + 1, deadline re-anchored) so ``obsctl trace`` stitches
        the client hop to this process's flush events.
        """
        frame = _frame_from_wire(doc.get('actions') or {})
        ctx = self._context_of(doc)
        future = self.service.rate(
            frame,
            home_team_id=doc.get('home_team_id'),
            context=ctx,
        )
        values = self._await(future, ctx)
        out = _values_to_wire(values)
        out['request_id'] = ctx.request_id if ctx is not None else None
        return out

    def handle_scenarios(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """``POST /scenarios``: value a counterfactual grid for one match.

        The wire form of
        :meth:`~socceraction_tpu.serve.service.RatingService.rate_scenarios`:
        ``doc['grid']`` is a
        :meth:`~socceraction_tpu.scenario.grid.ScenarioGrid.to_wire`
        document, the reply carries the flat ``(P, n_rows, 3)`` value
        block plus its shape, the value column names and the frame's
        row index — everything a decision-heatmap client needs to
        reassemble ranked tables without a second round trip.
        """
        from ..scenario.grid import ScenarioGrid

        frame = _frame_from_wire(doc.get('actions') or {})
        grid = ScenarioGrid.from_wire(doc.get('grid') or {})
        ctx = self._context_of(doc)
        future = self.service.rate_scenarios(
            frame,
            grid,
            home_team_id=doc.get('home_team_id'),
            context=ctx,
        )
        values = np.asarray(self._await(future, ctx), dtype=np.float64)
        return {
            'shape': list(values.shape),
            'values': values.ravel().tolist(),
            'columns': list(RATING_COLUMNS),
            'index': np.asarray(frame.index).tolist(),
            'request_id': ctx.request_id if ctx is not None else None,
        }

    def handle_session_open(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """``POST /session/open``: open a match session, return its id."""
        session = self.service.open_session(
            doc['match_id'], home_team_id=doc['home_team_id']
        )
        session_id = uuid.uuid4().hex
        with self._session_lock:
            self._sessions[session_id] = session
        return {'session_id': session_id}

    def _session(self, doc: Dict[str, Any]) -> Tuple[str, Any]:
        session_id = str(doc.get('session_id') or '')
        with self._session_lock:
            session = self._sessions.get(session_id)
        if session is None:
            raise ValueError(f'unknown session_id {session_id!r}')
        return session_id, session

    def handle_session_add(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """``POST /session/add``: append actions, return their values."""
        _sid, session = self._session(doc)
        frame = _frame_from_wire(doc.get('actions') or {})
        values = session.add_actions(frame, timeout=self.result_timeout_s)
        return _values_to_wire(values)

    def handle_session_close(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """``POST /session/close``: drop the session (idempotent)."""
        session_id = str(doc.get('session_id') or '')
        with self._session_lock:
            self._sessions.pop(session_id, None)
        return {'closed': session_id}

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Stop accepting, drop sessions, remove the socket file.

        The service itself stays up — the front end is a detachable
        door, and ownership of the service's lifecycle stays with
        whoever constructed it.
        """
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
        with self._session_lock:
            self._sessions.clear()
        try:
            os.unlink(self.address)
        except OSError:
            pass

    def __enter__(self) -> 'ServingFrontend':
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# -- the client half --------------------------------------------------------


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: float) -> None:
        super().__init__('localhost', timeout=timeout)
        self._path = path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._path)
        self.sock = sock


class FrontendClient:
    """A client process's handle on a :class:`ServingFrontend` socket.

    Every :meth:`rate` call mints a
    :class:`~socceraction_tpu.obs.context.RequestContext` in THIS
    process (recorded in this process's run log) and ships its
    ``to_wire()`` headers, so the server-side flush carries the same
    ``request_id`` — the stitch key ``obsctl trace`` joins the two run
    logs on. Raises :class:`FrontendError` on any non-200 reply;
    ``err.retriable`` distinguishes backoff-and-retry (shed, overload)
    from hard failures.
    """

    def __init__(self, path: str, *, timeout_s: float = 120.0) -> None:
        self.path = path
        self.timeout_s = float(timeout_s)

    def _call(
        self, method: str, route: str, doc: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        conn = _UnixHTTPConnection(self.path, self.timeout_s)
        try:
            body = json.dumps(doc or {}, default=str).encode('utf-8')
            conn.request(
                method, route, body=body if method == 'POST' else None,
                headers={'Content-Type': 'application/json'},
            )
            response = conn.getresponse()
            payload = json.loads(response.read() or b'{}')
            if response.status != 200:
                raise FrontendError(response.status, payload)
            return payload
        finally:
            conn.close()

    def rate(
        self,
        actions: pd.DataFrame,
        *,
        home_team_id: Any = None,
        deadline_ms: Optional[float] = None,
    ) -> pd.DataFrame:
        """Rate one match's actions through the front end (blocking).

        Returns the :data:`RATING_COLUMNS` DataFrame aligned to
        ``actions``' index — the same contract as
        ``RatingService.rate_sync``, across the process boundary.
        """
        import time as _time

        from ..obs.context import record_request_done, record_request_enqueue

        ctx = new_request_context('rate', deadline_ms=deadline_ms)
        # hop 0 of the trace: the client's enqueue/done events land in
        # THIS process's run log; the server's from_wire hop records the
        # rest, and `obsctl trace <id> client.jsonl server.jsonl`
        # stitches the two on the preserved request_id
        record_request_enqueue(ctx, queue_depth=0)
        t0 = _time.perf_counter()
        try:
            out = self._call('POST', '/rate', {
                'actions': _frame_to_wire(actions),
                'home_team_id': home_team_id,
                'context': ctx.to_wire(),
            })
        except Exception as e:
            record_request_done(
                ctx, 'error', _time.perf_counter() - t0,
                error=f'{type(e).__name__}: {e}',
            )
            raise
        record_request_done(ctx, 'ok', _time.perf_counter() - t0)
        self.last_request_id = out.get('request_id', ctx.request_id)
        return _values_from_wire(out)

    def rate_scenarios(
        self,
        actions: pd.DataFrame,
        grid: Any,
        *,
        home_team_id: Any = None,
        deadline_ms: Optional[float] = None,
    ) -> np.ndarray:
        """Value a counterfactual grid through the front end (blocking).

        Ships the frame plus ``grid.to_wire()`` to ``POST /scenarios``
        and returns the ``(P, len(actions), 3)`` value array — the same
        contract as ``RatingService.rate_scenarios_sync``, across the
        process boundary, with the request id preserved for trace
        stitching exactly like :meth:`rate`.
        """
        import time as _time

        from ..obs.context import record_request_done, record_request_enqueue

        ctx = new_request_context('scenario', deadline_ms=deadline_ms)
        record_request_enqueue(ctx, queue_depth=0)
        t0 = _time.perf_counter()
        try:
            out = self._call('POST', '/scenarios', {
                'actions': _frame_to_wire(actions),
                'grid': grid.to_wire(),
                'home_team_id': home_team_id,
                'context': ctx.to_wire(),
            })
        except Exception as e:
            record_request_done(
                ctx, 'error', _time.perf_counter() - t0,
                error=f'{type(e).__name__}: {e}',
            )
            raise
        record_request_done(ctx, 'ok', _time.perf_counter() - t0)
        self.last_request_id = out.get('request_id', ctx.request_id)
        return np.asarray(out['values'], dtype=np.float64).reshape(
            out['shape']
        )

    def health(self) -> Dict[str, Any]:
        """The service's health dict, across the boundary."""
        return self._call('GET', '/health')

    def open_session(self, match_id: Any, *, home_team_id: Any) -> str:
        """Open a live-match session; returns its server-side id."""
        return self._call('POST', '/session/open', {
            'match_id': match_id, 'home_team_id': home_team_id,
        })['session_id']

    def session_add(self, session_id: str, actions: pd.DataFrame) -> pd.DataFrame:
        """Append new actions to a session; returns THEIR values only."""
        out = self._call('POST', '/session/add', {
            'session_id': session_id, 'actions': _frame_to_wire(actions),
        })
        return _values_from_wire(out)

    def session_close(self, session_id: str) -> None:
        """Release the server-side session state (idempotent)."""
        self._call('POST', '/session/close', {'session_id': session_id})
