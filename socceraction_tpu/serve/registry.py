"""Versioned model registry with warm device residency and atomic hot-swap.

A serving process outlives any single model: ratings traffic keeps
flowing while a newly trained model is rolled out (or a bad one rolled
back). The registry layers three things over the existing
:meth:`~socceraction_tpu.vaep.base.VAEP.save_model` /
:meth:`~socceraction_tpu.ml.mlp.MLPClassifier.save` artifacts:

- **named + versioned storage** — ``root/<name>/<version>/`` directories,
  each one a ``save_model`` checkpoint. Loaders go through
  :func:`socceraction_tpu.vaep.base.load_model`, so the
  ``format_version`` stamp rejects artifacts from a newer library with a
  clear error instead of a deep ``KeyError``.
- **warm device residency** — on load, every MLP head's parameter pytree
  and standardization statistics are uploaded to the device once
  (:meth:`MLPClassifier._device_stats` caches) so steady-state rating
  dispatches re-upload nothing; the per-state combined-table fold and
  XLA compilation are warmed per shape bucket by
  :meth:`~socceraction_tpu.serve.service.RatingService.warmup`.
- **atomic hot-swap** — :meth:`activate` replaces the active
  ``(name, version, model)`` triple under a lock in one reference
  assignment; the service's flusher reads the triple once per flush, so
  every request in a batch is rated by exactly one model version, never
  a half-swapped mixture.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..obs import counter, span

__all__ = ['ModelRegistry']

_NAME_RE = re.compile(r'^[A-Za-z0-9][A-Za-z0-9._-]*$')


def _version_sort_key(version: str) -> Tuple[Any, ...]:
    """Order versions numerically when they look numeric ('2' < '10')."""
    parts = re.split(r'[._-]', version)
    return tuple(
        (0, int(p)) if p.isdigit() else (1, p) for p in parts
    )


class ModelRegistry:
    """Named, versioned store of rating models over ``save_model`` artifacts.

    Parameters
    ----------
    root : str
        Directory holding ``<name>/<version>/`` checkpoints. Created on
        first publish; a pre-existing tree is picked up as-is.
    """

    def __init__(self, root: str) -> None:
        self.root = root
        self._lock = threading.Lock()
        self._loaded: Dict[Tuple[str, str], Any] = {}
        self._active: Optional[Tuple[str, str, Any]] = None

    # -- storage -----------------------------------------------------------

    def _dir(self, name: str, version: str) -> str:
        for part in (name, version):
            if not _NAME_RE.match(part):
                raise ValueError(
                    f'invalid registry name/version {part!r} '
                    '(want [A-Za-z0-9][A-Za-z0-9._-]*)'
                )
        return os.path.join(self.root, name, version)

    def publish(self, name: str, version: str, model: Any) -> str:
        """Save a fitted model as ``name``/``version``; returns its path.

        Refuses to overwrite an existing version — versions are immutable
        (republish under a new version instead).
        """
        path = self._dir(name, version)
        if os.path.exists(path):
            raise ValueError(
                f'model {name}/{version} already exists at {path!r}; '
                'versions are immutable — publish a new version'
            )
        os.makedirs(path)
        model.save_model(path)
        return path

    def names(self) -> List[str]:
        """Published model names."""
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d))
        )

    def versions(self, name: str) -> List[str]:
        """Published versions of ``name``, oldest to newest."""
        base = os.path.join(self.root, name)
        if not os.path.isdir(base):
            return []
        found = [
            v for v in os.listdir(base)
            if os.path.isfile(os.path.join(base, v, 'meta.json'))
        ]
        return sorted(found, key=_version_sort_key)

    # -- loading + residency ----------------------------------------------

    def load(self, name: str, version: Optional[str] = None) -> Any:
        """Load (and device-warm) ``name``/``version`` (default: newest).

        Loaded models are cached per ``(name, version)`` — versions are
        immutable, so a cache entry can never go stale.
        """
        version = self.resolve_version(name, version)
        key = (name, version)
        with self._lock:
            model = self._loaded.get(key)
        if model is not None:
            return model
        from ..vaep.base import load_model

        path = self._dir(name, version)
        if not os.path.isfile(os.path.join(path, 'meta.json')):
            raise FileNotFoundError(f'no model at {path!r}')
        with span('serve/model_load', model=name, version=version):
            model = load_model(path)
            self.warm(model)
        with self._lock:
            self._loaded.setdefault(key, model)
            return self._loaded[key]

    @staticmethod
    def warm(model: Any) -> Any:
        """Upload a model's constants to the device once.

        MLP heads get device-resident parameter pytrees and cached
        device standardization statistics, so per-dispatch host→device
        transfers disappear. (Per-bucket XLA compilation is the
        service's :meth:`~socceraction_tpu.serve.service.RatingService.warmup`,
        which needs the batch shapes.)
        """
        import jax
        import jax.numpy as jnp

        from ..ml.mlp import MLPClassifier

        for clf in getattr(model, '_models', {}).values():
            if isinstance(clf, MLPClassifier) and clf.params is not None:
                clf.params = jax.tree.map(jnp.asarray, clf.params)
                if clf.mean_ is not None and clf.std_ is not None:
                    clf._device_stats()
        return model

    # -- the active model --------------------------------------------------

    def resolve_version(self, name: str, version: Optional[str]) -> str:
        """``version``, or the newest published version of ``name``.

        Callers that validate/warm a model before activating it resolve
        ONCE and pass the pinned version everywhere after — re-resolving
        'newest' later would race a concurrent publish.
        """
        if version is not None:
            return version
        available = self.versions(name)
        if not available:
            raise FileNotFoundError(
                f'no versions of model {name!r} under {self.root!r}'
            )
        return available[-1]

    def activate(self, name: str, version: Optional[str] = None) -> Tuple[str, str]:
        """Atomically make ``name``/``version`` the active serving model.

        The version is resolved FIRST and that exact version is loaded,
        device-warmed and activated — a publish racing this call can
        never make the recorded version string mismatch the live model.
        The swap itself is one locked reference assignment, so a
        concurrent flush reads either the old triple or the new one —
        never a mixture. Returns the ``(name, version)`` that went live.
        """
        version = self.resolve_version(name, version)
        model = self.load(name, version)
        with self._lock:
            self._active = (name, version, model)
        counter('serve/model_swaps', unit='count').inc(1)
        return name, version

    def active(self) -> Tuple[str, str, Any]:
        """The active ``(name, version, model)`` triple (one atomic read)."""
        with self._lock:
            active = self._active
        if active is None:
            raise RuntimeError(
                'no active model: call activate(name, version) first'
            )
        return active
