"""Versioned model registry with warm device residency and atomic hot-swap.

A serving process outlives any single model: ratings traffic keeps
flowing while a newly trained model is rolled out (or a bad one rolled
back). The registry layers three things over the existing
:meth:`~socceraction_tpu.vaep.base.VAEP.save_model` /
:meth:`~socceraction_tpu.ml.mlp.MLPClassifier.save` artifacts:

- **named + versioned storage** — ``root/<name>/<version>/`` directories,
  each one a ``save_model`` checkpoint. Loaders go through
  :func:`socceraction_tpu.vaep.base.load_model`, so the
  ``format_version`` stamp rejects artifacts from a newer library with a
  clear error instead of a deep ``KeyError``.
- **warm device residency** — on load, every MLP head's parameter pytree
  and standardization statistics are uploaded to the device once
  (:meth:`MLPClassifier._device_stats` caches) so steady-state rating
  dispatches re-upload nothing; the per-state combined-table fold and
  XLA compilation are warmed per shape bucket by
  :meth:`~socceraction_tpu.serve.service.RatingService.warmup`.
- **atomic hot-swap** — :meth:`activate` replaces the active
  ``(name, version, model)`` triple under a lock in one reference
  assignment; the service's flusher reads the triple once per flush, so
  every request in a batch is rated by exactly one model version, never
  a half-swapped mixture.

The continuous-learning loop (:mod:`socceraction_tpu.learn`) adds two
lifecycle stages on top:

- **candidates** — :meth:`stage_candidate` saves a freshly trained model
  under ``root/<name>/.candidates/<tag>`` (invisible to
  :meth:`versions`; the leading dot is outside the version grammar, so a
  candidate can never be activated by accident). A candidate that passes
  the promotion gate is :meth:`promote_candidate`-d — one atomic rename
  into a real version directory, no re-serialization — and one that
  fails stays on disk for post-mortems until the retention policy
  (:meth:`gc_candidates`) reclaims it.
- **rollback** — :meth:`rollback` re-activates the version that was
  serving *before* the last activation. The previous model is still
  resident in the load cache (versions are immutable, entries are never
  evicted), so a rollback is one warm, atomic reference swap — counted
  under ``serve/model_swaps{reason="rollback"}``.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..obs import counter, span
from ..obs.residency import Claim, claim_bytes
from ..resil.faults import fault_point
from ..resil.retry import RetryPolicy, retry_call

__all__ = ['ModelRegistry']

#: Checkpoint loads retried under this policy: transient filesystem
#: errors (a registry on network storage mid-failover) back off and
#: retry; corrupt artifacts (checksum mismatch → ValueError) and missing
#: versions (FileNotFoundError) raise immediately — waiting cannot fix
#: either.
LOAD_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.05, max_delay_s=1.0)

_NAME_RE = re.compile(r'^[A-Za-z0-9][A-Za-z0-9._-]*$')

#: Subdirectory of ``root/<name>/`` holding staged (gate-pending or
#: gate-rejected) candidate checkpoints. The leading dot keeps it out of
#: the version grammar (``_NAME_RE``) and out of ``versions()`` listings.
_CANDIDATES = '.candidates'


def _version_sort_key(version: str) -> Tuple[Any, ...]:
    """Order versions numerically when they look numeric ('2' < '10')."""
    parts = re.split(r'[._-]', version)
    return tuple(
        (0, int(p)) if p.isdigit() else (1, p) for p in parts
    )


class ModelRegistry:
    """Named, versioned store of rating models over ``save_model`` artifacts.

    Parameters
    ----------
    root : str
        Directory holding ``<name>/<version>/`` checkpoints. Created on
        first publish; a pre-existing tree is picked up as-is.
    """

    def __init__(self, root: str) -> None:
        self.root = root
        self._lock = threading.Lock()
        self._loaded: Dict[Tuple[str, str], Any] = {}
        #: HBM residency claims per cached version (owner ``registry``
        #: in the ledger) — claimed at load, released when the load
        #: cache prunes the version, so ``mem/owned_bytes{owner=
        #: "registry"}`` answers "how many model versions are warm"
        self._claims: Dict[Tuple[str, str], Claim] = {}
        self._active: Optional[Tuple[str, str, Any]] = None
        self._previous: Optional[Tuple[str, str, Any]] = None
        self._candidate_seq = 0

    # -- storage -----------------------------------------------------------

    def _dir(self, name: str, version: str) -> str:
        for part in (name, version):
            if not _NAME_RE.match(part):
                raise ValueError(
                    f'invalid registry name/version {part!r} '
                    '(want [A-Za-z0-9][A-Za-z0-9._-]*)'
                )
        return os.path.join(self.root, name, version)

    def publish(
        self,
        name: str,
        version: str,
        model: Any,
        *,
        aot: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Save a fitted model as ``name``/``version``; returns its path.

        Refuses to overwrite an existing version — versions are immutable
        (republish under a new version instead).

        ``aot`` (``{'ladder': (...), 'max_actions': N}``) additionally
        compiles the model's serving ladder and ships the serialized
        executables in an ``aot/`` subdirectory of the version
        (:func:`socceraction_tpu.serve.aot.export_serving_aot`) — a
        replica whose environment fingerprint matches then warms by
        deserializing instead of compiling. Export with the shapes
        replicas serve (``RatingService``'s bucket ladder /
        ``max_actions``).
        """
        path = self._dir(name, version)
        if os.path.exists(path):
            raise ValueError(
                f'model {name}/{version} already exists at {path!r}; '
                'versions are immutable — publish a new version'
            )
        os.makedirs(path)
        model.save_model(path)
        if aot is not None:
            self._export_aot_into(model, path, aot)
        return path

    @staticmethod
    def _export_aot_into(model: Any, path: str, aot: Dict[str, Any]) -> None:
        """Ship the serving executables inside a version/candidate dir.

        A failed export (non-fusable model, a forced non-fused rating
        path, an XLA error) removes the just-created directory before
        re-raising: the immutability guard would otherwise refuse every
        retry of the same version, stranding a slot the caller can
        neither complete nor redo. (A *crash* mid-export needs no
        cleanup — the manifest is written last, so a manifest-less
        ``aot/`` reads as no-artifacts and the version serves via
        recompile.)
        """
        from .aot import AOT_DIRNAME, export_serving_aot

        try:
            export_serving_aot(
                model,
                os.path.join(path, AOT_DIRNAME),
                ladder=tuple(aot['ladder']),
                max_actions=int(aot['max_actions']),
            )
        except Exception:
            shutil.rmtree(path, ignore_errors=True)
            raise

    def aot_dir(self, name: str, version: str) -> str:
        """The ``aot/`` artifact directory of ``name``/``version``.

        Purely a path computation — existence (and fingerprint match)
        is the loader's business: ``RatingService.warmup`` treats an
        absent directory as the no-artifacts tier.
        """
        from .aot import AOT_DIRNAME

        return os.path.join(self._dir(name, version), AOT_DIRNAME)

    def export_aot(
        self,
        name: str,
        version: Optional[str] = None,
        *,
        ladder: Any,
        max_actions: int,
    ) -> Dict[str, Any]:
        """Retro-fit AOT artifacts onto an already-published version.

        The backfill path for versions published before AOT shipping
        (or with different serving shapes): loads the version, compiles
        its ladder and writes ``aot/`` into the version dir. The
        artifact set itself is immutable once written (same stance as
        the checkpoint: re-export into a new version instead). Returns
        the manifest.
        """
        from .aot import export_serving_aot

        version = self.resolve_version(name, version)
        model = self.load(name, version)
        return export_serving_aot(
            model,
            self.aot_dir(name, version),
            ladder=tuple(ladder),
            max_actions=int(max_actions),
        )

    def names(self) -> List[str]:
        """Published model names."""
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d))
        )

    def versions(self, name: str) -> List[str]:
        """Published versions of ``name``, oldest to newest."""
        base = os.path.join(self.root, name)
        if not os.path.isdir(base):
            return []
        found = [
            v for v in os.listdir(base)
            if os.path.isfile(os.path.join(base, v, 'meta.json'))
        ]
        return sorted(found, key=_version_sort_key)

    # -- loading + residency ----------------------------------------------

    def load(self, name: str, version: Optional[str] = None) -> Any:
        """Load (and device-warm) ``name``/``version`` (default: newest).

        Loaded models are cached per ``(name, version)`` — versions are
        immutable, so a cache entry can never go *stale*. The cache is
        pruned to the active + previous versions at every activation
        (:meth:`activate` / :meth:`rollback`), so a loop that promotes a
        new version per iteration holds at most two models resident
        instead of growing without bound.
        """
        version = self.resolve_version(name, version)
        key = (name, version)
        with self._lock:
            model = self._loaded.get(key)
        if model is not None:
            return model
        from ..vaep.base import load_model

        path = self._dir(name, version)
        if not os.path.isfile(os.path.join(path, 'meta.json')):
            raise FileNotFoundError(f'no model at {path!r}')
        with span('serve/model_load', model=name, version=version):

            def _load() -> Any:
                fault_point('registry.load', model=name, version=version)
                return load_model(path)

            model = retry_call(_load, site='registry.load', policy=LOAD_RETRY)
            self.warm(model)
        with self._lock:
            if key not in self._loaded:
                self._loaded[key] = model
                # attribute the version's device residency (params +
                # cached device stats) to the registry: keyed per
                # version, released when the cache prunes it
                self._claims[key] = claim_bytes(
                    'registry', self._resident_arrays(model),
                    key=f'{name}/{version}',
                )
            return self._loaded[key]

    @staticmethod
    def _resident_arrays(model: Any) -> list:
        """The device-resident arrays :meth:`warm` uploaded for ``model``.

        Per MLP head: the parameter pytree plus the cached device
        standardization statistics — the bytes one warm model version
        actually holds in HBM (the residency ledger's ``registry``
        owner claims exactly these).
        """
        from ..ml.mlp import MLPClassifier

        arrays: list = []
        for clf in getattr(model, '_models', {}).values():
            if isinstance(clf, MLPClassifier) and clf.params is not None:
                arrays.append(clf.params)
                if clf.mean_ is not None and clf.std_ is not None:
                    arrays.append(clf._device_stats())
        # the prepared serving fold (quantized / Pallas-served combined
        # tables, built by warm()) is part of the version's residency:
        # the per-version claim delta between an int8 and an f32 fold IS
        # the "how many more versions fit warm" number the bench reports
        serving = getattr(model, 'serving_arrays', None)
        if callable(serving):
            arrays.extend(serving())
        return arrays

    @staticmethod
    def warm(model: Any) -> Any:
        """Upload a model's constants to the device once.

        MLP heads get device-resident parameter pytrees and cached
        device standardization statistics, so per-dispatch host→device
        transfers disappear. (Per-bucket XLA compilation is the
        service's :meth:`~socceraction_tpu.serve.service.RatingService.warmup`,
        which needs the batch shapes.)
        """
        import jax
        import jax.numpy as jnp

        from ..ml.mlp import MLPClassifier

        for clf in getattr(model, '_models', {}).values():
            if isinstance(clf, MLPClassifier) and clf.params is not None:
                clf.params = jax.tree.map(jnp.asarray, clf.params)
                if clf.mean_ is not None and clf.std_ is not None:
                    clf._device_stats()
        # build the prepared serving fold (quantized tables / Pallas
        # kernel configurations) at warm time so the first flush gathers
        # from resident tables instead of paying the fold build — and so
        # the residency claim below sees the fold's bytes
        warm_serving = getattr(model, 'warm_serving', None)
        if callable(warm_serving):
            warm_serving()
        return model

    # -- the active model --------------------------------------------------

    def resolve_version(self, name: str, version: Optional[str]) -> str:
        """``version``, or the newest published version of ``name``.

        Callers that validate/warm a model before activating it resolve
        ONCE and pass the pinned version everywhere after — re-resolving
        'newest' later would race a concurrent publish.
        """
        if version is not None:
            return version
        available = self.versions(name)
        if not available:
            raise FileNotFoundError(
                f'no versions of model {name!r} under {self.root!r}'
            )
        return available[-1]

    def activate(self, name: str, version: Optional[str] = None) -> Tuple[str, str]:
        """Atomically make ``name``/``version`` the active serving model.

        The version is resolved FIRST and that exact version is loaded,
        device-warmed and activated — a publish racing this call can
        never make the recorded version string mismatch the live model.
        The swap itself is one locked reference assignment, so a
        concurrent flush reads either the old triple or the new one —
        never a mixture. Returns the ``(name, version)`` that went live.
        """
        version = self.resolve_version(name, version)
        model = self.load(name, version)
        with self._lock:
            if self._active is not None and self._active[:2] != (name, version):
                self._previous = self._active
            self._active = (name, version, model)
            self._prune_loaded_locked()
        counter('serve/model_swaps', unit='count').inc(1)
        return name, version

    def _prune_loaded_locked(self) -> None:
        """Drop cached models other than the active/previous versions.

        Called (under the lock) at every activation: rollback needs
        exactly those two warm, and anything older would otherwise
        accumulate one full parameter set per promotion for the life of
        the process. A caller still holding a reference to an evicted
        model keeps using it unaffected — only the cache lets go.
        """
        keep = {
            triple[:2]
            for triple in (self._active, self._previous)
            if triple is not None
        }
        self._loaded = {k: v for k, v in self._loaded.items() if k in keep}
        # the evicted versions' residency claims go with them: the
        # ledger's `registry` owner tracks exactly the cache's warm set
        # (a caller still holding an evicted model keeps its arrays
        # live — those bytes then show up as the census's unattributed
        # remainder, which is the honest place for them)
        for key in [k for k in self._claims if k not in keep]:
            self._claims.pop(key).release()

    def active(self) -> Tuple[str, str, Any]:
        """The active ``(name, version, model)`` triple (one atomic read)."""
        with self._lock:
            active = self._active
        if active is None:
            raise RuntimeError(
                'no active model: call activate(name, version) first'
            )
        return active

    def previous(self) -> Optional[Tuple[str, str]]:
        """The ``(name, version)`` that was serving before the last swap.

        ``None`` until a second distinct version has been activated.
        This is what :meth:`rollback` will restore — callers that need
        to pre-warm compile caches (the serving ladder) before the swap
        read it first.
        """
        with self._lock:
            prev = self._previous
        return prev[:2] if prev is not None else None

    def rollback(
        self, expected: Optional[Tuple[str, str]] = None
    ) -> Tuple[str, str]:
        """Atomically re-activate the previously active version.

        The previous *model object* is still warm (it was serving until
        the last swap, and the load cache retains active + previous), so
        the whole exchange happens under one lock hold — read previous,
        swap the triples — the same atomicity as :meth:`activate`, with
        no window for a concurrent activation to slip between a read
        and the swap. Callers that validated/pre-warmed a specific
        target first (``RatingService.rollback_model``) pass it as
        ``expected``; a concurrent activation that changed "previous"
        in the meantime then raises instead of silently activating a
        version nobody validated. After a rollback the
        *rolled-back-from* version becomes the new "previous", so a
        mistaken rollback can itself be rolled back. Counted under
        ``serve/model_swaps{reason="rollback"}``.
        """
        with self._lock:
            prev = self._previous
            if prev is None:
                raise RuntimeError(
                    'no previous version to roll back to (rollback needs '
                    'a completed swap first)'
                )
            if expected is not None and prev[:2] != tuple(expected):
                raise RuntimeError(
                    f'previous version changed concurrently (expected '
                    f'{tuple(expected)}, found {prev[:2]}); re-read '
                    'previous() and retry'
                )
            name, version, _model = prev
            self._previous = self._active
            self._active = prev
            self._prune_loaded_locked()
        counter('serve/model_swaps', unit='count').inc(1, reason='rollback')
        return name, version

    # -- candidate lifecycle (the continuous-learning loop) ----------------

    def _candidate_dir(self, name: str, tag: str) -> str:
        if not _NAME_RE.match(name) or not _NAME_RE.match(tag):
            raise ValueError(
                f'invalid candidate name/tag {name!r}/{tag!r} '
                '(want [A-Za-z0-9][A-Za-z0-9._-]*)'
            )
        return os.path.join(self.root, name, _CANDIDATES, tag)

    def stage_candidate(
        self,
        name: str,
        model: Any,
        tag: Optional[str] = None,
        *,
        manifest: Optional[Dict[str, Any]] = None,
        aot: Optional[Dict[str, Any]] = None,
    ) -> Tuple[str, str]:
        """Save ``model`` as a staged candidate of ``name``; returns
        ``(tag, path)``.

        Candidates live under ``root/<name>/.candidates/<tag>`` — real
        ``save_model`` checkpoints, but invisible to :meth:`versions` /
        :meth:`resolve_version`, so nothing can activate one before the
        promotion gate passes. The default tag is a timestamp plus a
        process-local sequence number (collision-free within a process;
        across processes the timestamp + refusal-to-overwrite guard
        surfaces the race instead of corrupting a checkpoint).

        ``manifest``, when given, is written next to the checkpoint as
        ``manifest.json`` — the **training manifest** (trained-game ids
        + frozen drift-reference statistics) that travels with the
        candidate through :meth:`promote_candidate`'s atomic rename, so
        every published version carries the provenance a restarted
        process needs (:meth:`load_manifest`; the drift watch rebuilds
        its reference from it instead of guessing from store recency).

        ``aot`` (``{'ladder': ..., 'max_actions': ...}``) ships the
        serving executables in the candidate's ``aot/`` subdirectory —
        it rides :meth:`promote_candidate`'s atomic rename with the
        checkpoint, so the version a gate promotes already carries the
        compiled programs and a hot-swapping replica never recompiles.
        """
        if tag is None:
            with self._lock:
                self._candidate_seq += 1
                seq = self._candidate_seq
            tag = f'{time.strftime("%Y%m%dT%H%M%S")}-{os.getpid()}-{seq}'
        path = self._candidate_dir(name, tag)
        if os.path.exists(path):
            raise ValueError(f'candidate {name}/{tag} already staged at {path!r}')
        os.makedirs(path)
        model.save_model(path)
        if manifest is not None:
            with open(os.path.join(path, 'manifest.json'), 'w') as f:
                json.dump(manifest, f, sort_keys=True, default=str)
        if aot is not None:
            self._export_aot_into(model, path, aot)
        return tag, path

    def load_manifest(
        self, name: str, version: Optional[str] = None
    ) -> Optional[Dict[str, Any]]:
        """The training manifest of ``name``/``version`` (default newest).

        ``None`` when the version predates manifests (bootstrap
        versions, pre-resilience checkpoints) — callers fall back to
        their legacy reconstruction; a *corrupt* manifest raises (a
        half-written provenance record must surface, not silently read
        as absent).
        """
        version = self.resolve_version(name, version)
        path = os.path.join(self._dir(name, version), 'manifest.json')
        if not os.path.isfile(path):
            return None
        with open(path, encoding='utf-8') as f:
            return json.load(f)

    def candidates(self, name: str) -> List[str]:
        """Staged candidate tags of ``name``, oldest first (by mtime)."""
        base = os.path.join(self.root, name, _CANDIDATES)
        if not os.path.isdir(base):
            return []
        found = [
            t for t in os.listdir(base)
            if os.path.isfile(os.path.join(base, t, 'meta.json'))
        ]
        return sorted(found, key=lambda t: os.path.getmtime(os.path.join(base, t)))

    def promote_candidate(self, name: str, version: str, tag: str) -> str:
        """Publish a staged candidate as ``name``/``version`` (atomic).

        One ``os.replace`` of the candidate directory into the version
        slot — the checkpoint bytes the gate evaluated ARE the bytes
        that serve; nothing is re-serialized between evaluation and
        publication. The usual immutability rule applies: an existing
        version refuses to be overwritten.
        """
        src = self._candidate_dir(name, tag)
        if not os.path.isfile(os.path.join(src, 'meta.json')):
            raise FileNotFoundError(f'no staged candidate {name}/{tag}')
        dst = self._dir(name, version)
        if os.path.exists(dst):
            raise ValueError(
                f'model {name}/{version} already exists at {dst!r}; '
                'versions are immutable — promote under a new version'
            )
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(src, dst)
        return dst

    def next_version(self, name: str) -> str:
        """The next free numeric version string of ``name`` ('1', '2', …).

        Non-numeric published versions are ignored for the increment but
        can never collide (the result is purely numeric).
        """
        numeric = [
            int(v) for v in self.versions(name)
            if v.isdigit()
        ]
        return str(max(numeric) + 1 if numeric else 1)

    def gc_candidates(self, name: Optional[str] = None, *, keep: int = 2) -> List[str]:
        """Retention policy: delete all but the newest ``keep`` candidates.

        Gate-rejected candidates are kept on disk for post-mortems, but
        a loop that keeps training (and keeps getting rejected) must not
        grow the registry without bound. Returns the removed candidate
        directories. ``name=None`` sweeps every published name.
        """
        removed: List[str] = []
        names = [name] if name is not None else self.names()
        for n in names:
            tags = self.candidates(n)
            for tag in tags[: max(0, len(tags) - max(0, int(keep)))]:
                path = self._candidate_dir(n, tag)
                shutil.rmtree(path, ignore_errors=True)
                removed.append(path)
                counter('serve/candidates_expired', unit='count').inc(1)
        return removed
