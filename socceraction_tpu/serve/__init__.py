"""Online serving: micro-batched, shape-bucketed live rating.

The subsystem that turns the batch-oriented valuation core into the
thing the ROADMAP's north star describes — a server multiplexing many
concurrent callers onto the fused one-dispatch rating path:

- :mod:`socceraction_tpu.serve.batcher` — the thread-safe micro-batching
  queue: deadline-bounded coalescing, power-of-two shape buckets,
  bounded-queue admission control (:class:`Overloaded`).
- :mod:`socceraction_tpu.serve.session` — :class:`MatchSession`, live
  per-match streaming: O(new actions) incremental rating with the
  whole-match ``goalscore`` carry injected as a dense override.
- :mod:`socceraction_tpu.serve.registry` — :class:`ModelRegistry`,
  named+versioned checkpoints with warm device residency and atomic
  hot-swap.
- :mod:`socceraction_tpu.serve.service` — :class:`RatingService`, the
  front end (``rate() -> Future``, ``open_session``, ``swap_model``,
  ``rollback_model``), fully instrumented under the ``serve`` telemetry
  area.
- :mod:`socceraction_tpu.serve.capture` — :class:`TrafficCapture`, the
  bounded ring of recently served traffic the continuous-learning
  loop's shadow evaluation (:mod:`socceraction_tpu.learn`) replays.

Quickstart::

    from socceraction_tpu.serve import RatingService

    service = RatingService(model, max_wait_ms=2.0)
    service.warmup()                      # compile the bucket ladder
    fut = service.rate(actions_df, home_team_id=782)
    values = fut.result()                 # offensive/defensive/vaep cols

    live = service.open_session('match-1', home_team_id=782)
    live.add_actions(first_minutes_df)    # rates only the new suffix

See ``docs/serving.md`` for the architecture and overload/swap
semantics.
"""

from ..obs.context import DeadlineExceeded
from .batcher import MicroBatcher, Overloaded
from .capture import TrafficCapture
from .registry import ModelRegistry
from .service import RatingService, SLOShed
from .session import MatchSession

__all__ = [
    'DeadlineExceeded',
    'MicroBatcher',
    'Overloaded',
    'ModelRegistry',
    'RatingService',
    'SLOShed',
    'MatchSession',
    'TrafficCapture',
]
