"""Online serving: micro-batched, shape-bucketed live rating.

The subsystem that turns the batch-oriented valuation core into the
thing the ROADMAP's north star describes — a server multiplexing many
concurrent callers onto the fused one-dispatch rating path:

- :mod:`socceraction_tpu.serve.batcher` — the thread-safe micro-batching
  queue: deadline-bounded coalescing, power-of-two shape buckets,
  bounded-queue admission control (:class:`Overloaded`).
- :mod:`socceraction_tpu.serve.session` — :class:`MatchSession`, live
  per-match streaming: O(new actions) incremental rating with the
  whole-match ``goalscore`` carry injected as a dense override.
- :mod:`socceraction_tpu.serve.registry` — :class:`ModelRegistry`,
  named+versioned checkpoints with warm device residency and atomic
  hot-swap.
- :mod:`socceraction_tpu.serve.aot` — AOT-serialized serving
  executables: compile the ladder once, ship the compiled programs in
  the registry version dir, and let every matching replica warm by
  deserializing instead of recompiling (plus the persistent
  compile-cache middle tier, ``SOCCERACTION_TPU_COMPILE_CACHE``).
- :mod:`socceraction_tpu.serve.service` — :class:`RatingService`, the
  front end (``rate() -> Future``, ``rate_scenarios() -> Future`` — the
  counterfactual verb over :mod:`socceraction_tpu.scenario` grids —
  ``open_session``, ``swap_model``, ``rollback_model``), fully
  instrumented under the ``serve`` telemetry area.
- :mod:`socceraction_tpu.serve.capture` — :class:`TrafficCapture`, the
  bounded ring of recently served traffic the continuous-learning
  loop's shadow evaluation (:mod:`socceraction_tpu.learn`) replays.
- :mod:`socceraction_tpu.serve.frontend` — :class:`ServingFrontend` /
  :class:`FrontendClient`, the cross-process door: a unix-socket RPC
  server over one (possibly mesh-replicated) :class:`RatingService`,
  forwarding ``RequestContext.to_wire()`` so traces stitch client →
  front end → replica flush.

Quickstart::

    from socceraction_tpu.serve import RatingService

    service = RatingService(model, max_wait_ms=2.0)
    service.warmup()                      # AOT artifacts > compile cache
    fut = service.rate(actions_df, home_team_id=782)
    values = fut.result()                 # offensive/defensive/vaep cols

    live = service.open_session('match-1', home_team_id=782)
    live.add_actions(first_minutes_df)    # rates only the new suffix

Submodules load lazily (PEP 562): ``from socceraction_tpu.serve import
ModelRegistry`` pulls neither jax nor pandas, so control-plane
processes — registry listings, AOT-manifest/fingerprint inspection,
``obsctl`` — stay import-light; the heavy service machinery loads the
first time :class:`RatingService`/:class:`MatchSession` (or anything
else from the data plane) is touched. Pinned by the import-audit tests.

See ``docs/serving.md`` for the architecture, the cold-start runbook
and overload/swap semantics.
"""

from typing import Any

__all__ = [
    'DeadlineExceeded',
    'MicroBatcher',
    'Overloaded',
    'ModelRegistry',
    'RatingService',
    'SLOShed',
    'MatchSession',
    'TrafficCapture',
    'ServingFrontend',
    'FrontendClient',
    'FrontendError',
]

#: exported name -> (submodule, attribute) for the lazy loader; kept
#: explicit so ``__all__`` and the resolution table cannot drift apart
_LAZY = {
    'DeadlineExceeded': ('socceraction_tpu.obs.context', 'DeadlineExceeded'),
    'MicroBatcher': ('socceraction_tpu.serve.batcher', 'MicroBatcher'),
    'Overloaded': ('socceraction_tpu.serve.batcher', 'Overloaded'),
    'ModelRegistry': ('socceraction_tpu.serve.registry', 'ModelRegistry'),
    'RatingService': ('socceraction_tpu.serve.service', 'RatingService'),
    'SLOShed': ('socceraction_tpu.serve.service', 'SLOShed'),
    'MatchSession': ('socceraction_tpu.serve.session', 'MatchSession'),
    'TrafficCapture': ('socceraction_tpu.serve.capture', 'TrafficCapture'),
    'ServingFrontend': ('socceraction_tpu.serve.frontend', 'ServingFrontend'),
    'FrontendClient': ('socceraction_tpu.serve.frontend', 'FrontendClient'),
    'FrontendError': ('socceraction_tpu.serve.frontend', 'FrontendError'),
}


_SUBMODULES = {
    'aot', 'batcher', 'capture', 'frontend', 'registry', 'service', 'session',
}


def __getattr__(name: str) -> Any:
    import importlib

    if name in _SUBMODULES:
        # attribute-style submodule access (serve.batcher) without a
        # prior explicit import of the submodule
        return importlib.import_module(f'{__name__}.{name}')
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f'module {__name__!r} has no attribute {name!r}'
        ) from None
    value = getattr(importlib.import_module(module_name), attr)
    globals()[name] = value  # cache: next access skips the import hook
    return value


def __dir__() -> list:
    return sorted(set(globals()) | set(__all__))
