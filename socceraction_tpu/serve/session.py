"""Per-match streaming sessions: rate a live game in O(new actions) ticks.

A :class:`MatchSession` accepts SPADL actions incrementally as the match
is played and rates only the new suffix per update. The trick is that the
VAEP computation is *almost* local: with ``nb_prev_actions = k``, an
action's features read at most the ``k - 1`` actions before it, and the
VAEP formula reads the previous action's probabilities (whose features
reach ``k`` actions back in total). So a window of ``k`` context actions
plus the new suffix reproduces the full-game computation for every new
row — except for one feature:

**goalscore** is a whole-match prefix sum (goals scored so far, anchored
to the team of the match's FIRST action), which a suffix window cannot
know. The session therefore carries the running score on the host — a
handful of integers — and injects the exact ``(team_score, opp_score,
diff)`` block for the window's rows via ``rate_batch``'s
``dense_overrides`` (the same mechanism sequence parallelism uses for its
cross-shard goalscore correction). The injected values are small integer
counts, exactly representable in f32, so incremental ratings match a
full-game replay bit-for-bit up to XLA reordering (pinned ≤ 1e-5, in
practice ~0).

Each update packs its window with the owning service's fixed
``max_actions`` and submits it through the service's micro-batcher, so
concurrent live matches coalesce into the same bucketed device batches
as one-shot rating requests.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np
import pandas as pd

from ..core.batch import pack_actions
from ..spadl import config as spadlconfig

__all__ = ['MatchSession']

#: Feature kernels whose value at action ``i`` depends only on actions
#: ``i-k+1 .. i`` (the game-state window) — safe to evaluate on a suffix
#: window as-is. Everything standard except ``goalscore``.
WINDOW_LOCAL_KERNELS = frozenset(
    {
        'actiontype', 'actiontype_onehot', 'result', 'result_onehot',
        'actiontype_result_onehot', 'bodypart', 'bodypart_onehot', 'time',
        'startlocation', 'endlocation', 'startpolar', 'endpolar', 'movement',
        'team', 'time_delta', 'space_delta',
    }
)

_GS_COLS = ('_gs_team', '_gs_opp')


def _goal_flags(
    type_id: np.ndarray, result_id: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Host mirror of ``ops.labels._goal_masks`` (goal, owngoal) per row."""
    shot_like = (
        (type_id == spadlconfig.SHOT)
        | (type_id == spadlconfig.SHOT_PENALTY)
        | (type_id == spadlconfig.SHOT_FREEKICK)
    )
    return (
        shot_like & (result_id == spadlconfig.SUCCESS),
        shot_like & (result_id == spadlconfig.OWNGOAL),
    )


def score_prefix(
    type_id: np.ndarray,
    result_id: np.ndarray,
    team_is_a: np.ndarray,
    carry_a: int = 0,
    carry_b: int = 0,
) -> Any:
    """Per-row ``(team_score, opp_score)`` BEFORE each action, plus the
    advanced ``(carry_a, carry_b)`` totals.

    The ONE host mirror of ``ops.features._goalscore``'s exclusive prefix
    sums — shared by the session's running carry and the service's
    whole-frame block, so the two cannot drift. Pure: callers commit the
    returned carries when (and only when) the rating succeeds.
    """
    goal, owngoal = _goal_flags(type_id, result_id)
    goals_a = ((goal & team_is_a) | (owngoal & ~team_is_a)).astype(np.int64)
    goals_b = ((goal & ~team_is_a) | (owngoal & team_is_a)).astype(np.int64)
    before_a = carry_a + np.cumsum(goals_a) - goals_a
    before_b = carry_b + np.cumsum(goals_b) - goals_b
    team = np.where(team_is_a, before_a, before_b).astype(np.float32)
    opp = np.where(team_is_a, before_b, before_a).astype(np.float32)
    return team, opp, carry_a + int(goals_a.sum()), carry_b + int(goals_b.sum())


def goalscore_block(
    team: np.ndarray, opp: np.ndarray, max_actions: int
) -> np.ndarray:
    """Assemble the ``(1, A, 3)`` dense-override block (zeros on padding)."""
    gs = np.zeros((1, max_actions, 3), dtype=np.float32)
    n = len(team)
    gs[0, :n, 0] = team
    gs[0, :n, 1] = opp
    gs[0, :n, 2] = team - opp
    return gs


class MatchSession:
    """One live match's incremental rating state.

    Create via :meth:`socceraction_tpu.serve.service.RatingService.open_session`.

    Parameters
    ----------
    service
        The owning :class:`~socceraction_tpu.serve.service.RatingService`;
        window requests go through its micro-batcher.
    match_id
        Identifier used as the packed frame's ``game_id``.
    home_team_id
        The match's home side (SPADL team orientation).
    """

    def __init__(self, service: Any, match_id: Any, home_team_id: Any) -> None:
        self._service = service
        self.match_id = match_id
        self.home_team_id = home_team_id
        self.k = int(service.nb_prev_actions)
        #: last <= k actions (with their stored goalscore rows) — the
        #: game-state ring buffer the next window's context comes from
        self._tail: Optional[pd.DataFrame] = None
        # running whole-match score state (goalscore's global carry)
        self._team_a_is_home: Optional[bool] = None
        self._score_a = 0
        self._score_b = 0
        self.n_actions = 0
        self._chunks: List[pd.DataFrame] = []

    # -- the per-tick update ----------------------------------------------

    def add_actions(self, actions: pd.DataFrame, *, timeout: Optional[float] = None) -> pd.DataFrame:
        """Rate the next slice of the match; returns the new rows' values.

        ``actions`` are the match's newest SPADL rows, in order,
        continuing from everything previously added. The update cost is
        O(len(actions)): a window of ``k`` buffered context actions plus
        the new rows is packed, rated through the service's shared
        micro-batcher, and only the new rows' ratings are kept.

        Returns a DataFrame with ``offensive_value`` / ``defensive_value``
        / ``vaep_value`` columns aligned to ``actions``' index.
        """
        if len(actions) == 0:
            return pd.DataFrame(
                columns=['offensive_value', 'defensive_value', 'vaep_value']
            )
        # An oversized tick splits into window-sized parts, but ALL state
        # (goalscore carry, ring buffer, totals) commits exactly once,
        # after every part's future has resolved — a failure anywhere in
        # the tick leaves the session untouched, so the documented
        # retry-the-same-tick contract holds for ticks of any size. The
        # sub-windows depend only on the actions (never on each other's
        # ratings), so they are all submitted before the first wait and
        # coalesce into the same flushes.
        max_rows = self._service.max_actions - self.k
        gs_enabled = getattr(self._service, '_gs_enabled', True)
        tail = self._tail
        team_a = self._team_a_is_home
        score_a, score_b = self._score_a, self._score_b
        pending: List[Any] = []
        for i in range(0, len(actions), max_rows):
            part = actions.iloc[i : i + max_rows]
            if gs_enabled:
                is_home = part['team_id'].to_numpy() == self.home_team_id
                if team_a is None:
                    team_a = bool(is_home[0])
                team, opp, score_a, score_b = score_prefix(
                    part['type_id'].to_numpy(dtype=np.int64),
                    part['result_id'].to_numpy(dtype=np.int64),
                    is_home == team_a,
                    score_a,
                    score_b,
                )
                new = part.copy()
                new[_GS_COLS[0]] = team
                new[_GS_COLS[1]] = opp
            else:  # the model has no goalscore kernel: no carry to keep
                new = part
            context = 0 if tail is None else len(tail)
            window = new if context == 0 else pd.concat([tail, new])
            future = self._service._submit_window(
                window, context, len(new),
                match_id=self.match_id, home_team_id=self.home_team_id,
            )
            pending.append((future, part.index))
            tail = window.iloc[-self.k :]
        parts = [
            pd.DataFrame(
                future.result(timeout=timeout),
                columns=['offensive_value', 'defensive_value', 'vaep_value'],
                index=index,
            )
            for future, index in pending
        ]

        # commit ONLY on success: an Overloaded/timeout/flush failure
        # leaves the session exactly where it was, so the caller can
        # retry the same tick without corrupting the goalscore carry
        self._team_a_is_home = team_a
        self._score_a, self._score_b = score_a, score_b
        self._tail = tail
        self.n_actions += len(actions)
        # commit-time capture: a retried tick records its rows exactly
        # once, and the captured stream is the match as actually rated
        capture = getattr(self._service, 'capture', None)
        if capture is not None:
            capture.record_session(self.match_id, actions, self.home_team_id)
        out = parts[0] if len(parts) == 1 else pd.concat(parts)
        self._chunks.append(out)
        return out

    def ratings(self) -> pd.DataFrame:
        """All ratings produced so far, in arrival order."""
        if not self._chunks:
            return pd.DataFrame(
                columns=['offensive_value', 'defensive_value', 'vaep_value']
            )
        return pd.concat(self._chunks)


def pack_window(
    window: pd.DataFrame, match_id: Any, home_team_id: Any, max_actions: int
) -> Any:
    """Pack one session window into a host staging batch + goalscore block.

    Returns ``(staging ActionBatch (1, A) numpy fields, gs (1, A, 3) f32)``
    where the goalscore block carries the stored whole-match
    ``(team_score, opp_score, diff)`` rows for the window's actions and
    zeros on padding — or ``gs = None`` when the window carries no score
    columns (the serving model has no ``goalscore`` kernel).
    """
    frame = window.drop(columns=list(_GS_COLS), errors='ignore')
    if 'game_id' not in frame.columns:
        frame = frame.assign(game_id=match_id)
    staging, _ids = pack_actions(
        frame, home_team_id=home_team_id, max_actions=max_actions,
        as_numpy=True,
    )
    if _GS_COLS[0] not in window.columns:
        return staging, None
    gs = goalscore_block(
        window[_GS_COLS[0]].to_numpy(dtype=np.float32),
        window[_GS_COLS[1]].to_numpy(dtype=np.float32),
        max_actions,
    )
    return staging, gs
