"""The online rating service: micro-batched, shape-bucketed, hot-swappable.

:class:`RatingService` is the in-process front end that turns the
batch-oriented valuation core (``VAEP.rate_batch`` and the fused
one-dispatch path behind it) into a multiplexed, latency-bounded server:

- ``rate(actions) -> Future`` — rate one match's SPADL actions; packing
  happens on the calling thread, the dispatch is coalesced with every
  other concurrent request by the micro-batcher
  (:mod:`socceraction_tpu.serve.batcher`) into power-of-two shape
  buckets, so steady traffic runs a pinned set of compiled programs;
- ``open_session(match_id, ...)`` — a per-match streaming
  :class:`~socceraction_tpu.serve.session.MatchSession` that rates a
  live game in O(new actions) per tick through the same batcher;
- ``swap_model(name, version)`` — atomic hot-swap via the
  :class:`~socceraction_tpu.serve.registry.ModelRegistry`: each flush
  reads the active model once, so no request is ever rated by a
  half-swapped model;
- overload raises :class:`~socceraction_tpu.serve.batcher.Overloaded` at
  ``rate()`` time (bounded queue — load is shed, not buffered forever).

Every stage reports to :mod:`socceraction_tpu.obs` under the ``serve``
area (queue depth, batch fill ratio, request latency histogram with
p50/p99 estimates, rejections, per-bucket trace counters) and runs
inside spans, so a :class:`~socceraction_tpu.obs.trace.RunLog` captures
the full serving timeline.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ..core.batch import (
    ActionBatch,
    bucket_window,
    pack_actions,
    pad_batch_games,
    unpack_values,
    window_ladder,
)
from ..obs import REGISTRY, counter, gauge, histogram, span
from ..obs.context import RequestContext, new_request_context, record_segment
from ..obs.numerics import drain_guards
from ..obs.parity import ParityProbe
from ..obs.perf import perf_snapshot, record_dispatch
from ..obs.residency import owned_bytes
from ..obs.recorder import dump_debug_bundle
from ..obs.slo import SLOConfig, SLOEngine
from ..resil.breaker import CircuitBreaker
from ..resil.faults import fault_point
from ..scenario.engine import (
    bucket_perturbations,
    expand_scenarios,
    perturbation_ladder,
    rate_scenarios_reference,
)
from ..scenario.grid import ScenarioGrid, pad_perturbations
from .batcher import MicroBatcher, Overloaded
from .session import (
    WINDOW_LOCAL_KERNELS,
    MatchSession,
    goalscore_block,
    pack_window,
    score_prefix,
)

__all__ = ['RatingService', 'SLOShed']


class SLOShed(Overloaded):
    """Raised by ``rate()`` when SLO burn-rate admission control sheds.

    A subclass of :class:`~socceraction_tpu.serve.batcher.Overloaded`,
    so callers with queue-overload handling (retry, down-sample, 429)
    keep working unchanged — but the cause is different: the service is
    *burning its error budget* (latency or error-rate objective past the
    burn threshold over both windows), and taking more load would make
    it worse. ``reason`` is the machine-readable payload: objective
    name, per-window burn rates, threshold, windows and remaining
    budget.
    """

    def __init__(self, reason: Dict[str, Any]) -> None:
        self.reason = dict(reason)
        super().__init__(
            'shedding by SLO burn rate: objective '
            f'{reason.get("objective")!r} burning at '
            f'{reason.get("burn_rate_fast")}x (fast) / '
            f'{reason.get("burn_rate_slow")}x (slow) of budget, '
            f'threshold {reason.get("threshold")}x '
            f'(budget remaining: {reason.get("budget_remaining")})'
        )

RATING_COLUMNS = ['offensive_value', 'defensive_value', 'vaep_value']


class _Payload:
    """One packed request: a staging batch plus its result recipe."""

    __slots__ = ('staging', 'gs', 'keep', 'index', 'ctx')

    def __init__(
        self,
        staging: Any,
        gs: Optional[np.ndarray],
        keep: Optional[Tuple[int, int]] = None,
        index: Any = None,
        ctx: Any = None,
    ) -> None:
        self.staging = staging  # host ActionBatch, (1, A) numpy fields
        self.gs = gs  # (1, A, 3) f32 goalscore block
        self.keep = keep  # None (whole frame) | (context, m) window slice
        self.index = index  # pandas index for frame requests
        self.ctx = ctx  # RequestContext (trace identity + segments)


class _ScenarioPayload:
    """One packed counterfactual request: a staging batch plus its grid.

    Rides the same batcher queue as :class:`_Payload` (admission,
    deadline expiry, SLO scoring and lane fan-out all apply unchanged)
    but is dispatched as its own flush: the grid's perturbation axis is
    folded into the game axis at its own power-of-two bucket, so it can
    never share a game-axis bucket with coalesced rate traffic.
    """

    __slots__ = ('staging', 'gs', 'grid', 'index', 'ctx')

    def __init__(
        self,
        staging: Any,
        gs: Optional[np.ndarray],
        grid: Any,
        index: Any = None,
        ctx: Any = None,
    ) -> None:
        self.staging = staging  # host ActionBatch, (1, A) numpy fields
        self.gs = gs  # (1, A, 3) f32 goalscore block
        self.grid = grid  # ScenarioGrid, P perturbations
        self.index = index  # pandas index of the request frame
        self.ctx = ctx  # RequestContext (trace identity + segments)


class RatingService:
    """In-process online rating server over a fitted VAEP model.

    Parameters
    ----------
    model : VAEP, optional
        A fitted standard-SPADL :class:`~socceraction_tpu.vaep.base.VAEP`.
        Give either ``model`` or ``registry``.
    registry : ModelRegistry, optional
        A :class:`~socceraction_tpu.serve.registry.ModelRegistry` whose
        active model serves traffic; enables :meth:`swap_model`.
    max_actions : int
        Fixed action-axis capacity of every device batch (one compiled
        ladder serves all traffic). A request/window longer than this is
        rejected at call time.
    max_batch_size : int
        Requests per flush cap == top of the bucket ladder.
    max_wait_ms : float
        Deadline bound: a lone request is dispatched at most this long
        after arrival.
    max_queue : int
        Admission bound; past it ``rate()`` raises
        :class:`~socceraction_tpu.serve.batcher.Overloaded`.
    slo_p99_ms : float
        The p99 end-to-end latency budget :meth:`health` compares the
        measured ``serve/request_seconds`` p99 against. Observability
        only — nothing is throttled by it (``slo=`` is the throttling
        form).
    slo : SLOConfig, optional
        Declarative service-level objectives
        (:class:`~socceraction_tpu.obs.slo.SLOConfig`). When given, an
        :class:`~socceraction_tpu.obs.slo.SLOEngine` scores every
        terminal request, ``health()`` reports per-objective budget
        remaining, a burn-rate breach dumps a rate-limited debug bundle,
        and ``rate()`` / session ticks **shed by burn rate**: past the
        config's threshold over both windows, submissions raise
        :class:`SLOShed` with the machine-readable reason. ``None``
        (default) keeps the PR-4 behavior: shedding by queue depth only.
    request_deadline_ms : float, optional
        Default per-request deadline. A request still queued when its
        deadline passes is failed with
        :class:`~socceraction_tpu.obs.context.DeadlineExceeded` — never
        dispatched, never captured. ``rate(deadline_ms=...)`` overrides
        per call; ``None`` (default) means no deadline.
    capture : TrafficCapture, optional
        A :class:`~socceraction_tpu.serve.capture.TrafficCapture` ring
        that records served traffic (successful ``rate`` submissions and
        committed session ticks) for the continuous-learning loop's
        shadow replay. ``None`` (default) captures nothing.
    parity : ParityProbe, optional
        A :class:`~socceraction_tpu.obs.parity.ParityProbe`: a sampled
        fraction of flushes is re-rated through the materialized
        reference path **off the flusher thread** and the abs/ulp error
        recorded per path pair (``num/parity_abs_err{pair=...}`` with
        the request id as exemplar). A probe past its band fires the
        rate-limited debug bundle (``reason="parity"``), degrades
        :meth:`health`, and — through
        :meth:`~socceraction_tpu.obs.parity.ParityProbe.stats` — feeds
        the learn gate's fail-closed ``max_parity_err`` input. The
        probe is closed with the service. ``None`` (default) probes
        nothing. Independent of the probe, every flush drains the
        in-dispatch finite guards (:mod:`socceraction_tpu.obs.numerics`):
        a non-finite value in a served dispatch is counted under
        ``num/nonfinite_total``, dumps a rate-limited debug bundle
        (``reason="nonfinite"``) and degrades :meth:`health`.
    breaker : CircuitBreaker, optional
        The circuit breaker on the fused dispatch
        (:class:`~socceraction_tpu.resil.breaker.CircuitBreaker`).
        ``breaker_failures`` consecutive *flush-level* dispatch failures
        trip it open; flushes then route through the materialized
        reference fallback (``rate_batch_reference`` — correct values,
        slower path) instead of failing callers, :meth:`health` reports
        ``'degraded'`` with the breaker block, and after
        ``breaker_recovery_s`` one half-open probe flush tries the
        fused path again — success closes the breaker. The default is a
        breaker with those knobs; pass an explicit instance to share or
        tune one, or ``breaker_failures=0`` to disable degradation
        entirely (dispatch failures then fail their flush's futures, the
        pre-resilience behavior).
    n_replicas : int
        Replica fan-out across the device mesh (default 1, the classic
        single-device service — byte-identical behavior). With ``N > 1``
        the service becomes the mesh topology's one front door: N flush
        lanes drain the shared queue concurrently, each lane dispatching
        to its own device through a
        :class:`~socceraction_tpu.parallel.serve.ReplicaDispatcher`
        (params replicated once per device at model load), with a
        per-replica circuit breaker (a sick replica degrades ALONE onto
        the materialized fallback; the others stay fused), per-replica
        shape accounting (``serve/shape_traces{replica=}``), and
        mesh-wide atomic hot-swap: a swap target is ladder-warmed on
        EVERY replica before any of them activates it — one failed warm
        aborts the swap fleet-wide. Replica ids ``r0..rN-1`` are
        registered with the fleet's
        :class:`~socceraction_tpu.obs.wire.ReplicaRegistry`. Requires
        ``N`` visible devices and a fused-dispatch-capable model.
    max_perturbations : int
        Top of the scenario verb's perturbation bucket ladder
        (:meth:`rate_scenarios`). A grid with more perturbations than
        this is rejected at call time; the ladder itself is
        ``(1, 2, 4, ..., max_perturbations)`` (rounded up to a power of
        two), and :meth:`warmup` with ``scenario_buckets=`` pre-compiles
        chosen rungs so steady-state scenario traffic never retraces.
    aot_dir : str, optional
        An explicit AOT artifact directory (the ``aot/`` layout
        :func:`socceraction_tpu.serve.aot.export_serving_aot` writes)
        for model-backed services. Registry-backed services resolve the
        active version's ``aot/`` directory automatically; this
        parameter is the escape hatch when the model object arrives
        without its registry (the cold-start bench child). ``None``
        (default) with no registry disables the AOT tier.
    debug_dir : str, optional
        Where automatic flight-recorder bundles land
        (:func:`~socceraction_tpu.obs.recorder.dump_debug_bundle` on
        flusher-thread death, ``Overloaded`` bursts past
        ``overload_dump_threshold`` within ``overload_dump_window_s``,
        and hot-swap failure). Default:
        ``$SOCCERACTION_TPU_DEBUG_DIR`` or
        ``<tmpdir>/socceraction-tpu-debug``. Dumps are rate-limited to
        one per reason per ``dump_interval_s``.
    """

    def __init__(
        self,
        model: Any = None,
        registry: Any = None,
        *,
        max_actions: int = 1664,
        max_batch_size: int = 64,
        max_wait_ms: float = 2.0,
        max_queue: int = 256,
        slo_p99_ms: float = 250.0,
        slo: Optional[SLOConfig] = None,
        request_deadline_ms: Optional[float] = None,
        capture: Any = None,
        parity: Optional[ParityProbe] = None,
        breaker: Optional[CircuitBreaker] = None,
        breaker_failures: int = 3,
        breaker_recovery_s: float = 5.0,
        n_replicas: int = 1,
        max_perturbations: int = 4096,
        aot_dir: Optional[str] = None,
        debug_dir: Optional[str] = None,
        overload_dump_threshold: int = 64,
        overload_dump_window_s: float = 10.0,
        dump_interval_s: float = 60.0,
    ) -> None:
        if (model is None) == (registry is None):
            raise ValueError('give exactly one of model= or registry=')
        self._registry = registry
        self._model = None
        if model is not None:
            self._validate_model(model)
            self._model = model
            first = model
        else:
            first = registry.active()[2]
            self._validate_model(first)
        # whether requests must carry the host goalscore block: invariant
        # across swaps (swap_model rejects feature-layout changes), so
        # models without the kernel never pay the per-request prefix work
        self._gs_enabled = 'goalscore' in first._kernel_names()
        self.max_actions = int(max_actions)
        self.slo_p99_ms = float(slo_p99_ms)
        self.capture = capture
        self.parity = parity
        if parity is not None and parity.on_exceed is None:
            parity.on_exceed = self._on_parity_exceed
        #: nonfinite guard events drained by THIS service's flushes.
        #: Scope caveat: the pending-guard ring is process-global and
        #: only the fused pair path feeds it, so with several services
        #: (or standalone guarded ``rate_batch`` calls) in one process,
        #: whichever flush drains first absorbs the event — a NaN
        #: detected anywhere in the process's rating path degrades the
        #: draining service. That errs fail-closed on purpose: the
        #: shared compiled path IS this service's path. Host-recorded
        #: guards (training, solve_xt) never enter the ring and never
        #: land here.
        self._nonfinite_events = 0
        from ..obs.recorder import default_debug_dir

        self.debug_dir = debug_dir or default_debug_dir()
        self.overload_dump_threshold = int(overload_dump_threshold)
        self.overload_dump_window_s = float(overload_dump_window_s)
        self.dump_interval_s = float(dump_interval_s)
        self.last_dump_path: Optional[str] = None
        self._dump_lock = threading.Lock()
        self._last_dump_t: Dict[str, float] = {}
        self._overloads: 'deque[float]' = deque()
        self._started_t = time.monotonic()
        self.request_deadline_ms = request_deadline_ms
        self._model_activated_t = time.monotonic()
        self._slo: Optional[SLOEngine] = (
            SLOEngine(
                slo,
                model_age_s=lambda: time.monotonic() - self._model_activated_t,
                on_breach=self._on_slo_breach,
            )
            if slo is not None
            else None
        )
        self.n_replicas = int(n_replicas)
        if self.n_replicas < 1:
            raise ValueError('n_replicas must be >= 1')
        self.max_perturbations = int(max_perturbations)
        if self.max_perturbations < 1:
            raise ValueError('max_perturbations must be >= 1')
        if self.n_replicas > 1:
            if breaker is not None:
                raise ValueError(
                    'a shared breaker instance defeats per-replica '
                    'degradation; with n_replicas > 1 the service builds '
                    'one breaker per replica from breaker_failures/'
                    'breaker_recovery_s'
                )
            from ..obs.wire import REPLICAS

            self.replica_ids: Tuple[str, ...] = tuple(
                REPLICAS.register(f'r{i}') for i in range(self.n_replicas)
            )
            self._breakers: List[Optional[CircuitBreaker]] = [
                CircuitBreaker(
                    failure_threshold=int(breaker_failures),
                    recovery_time_s=float(breaker_recovery_s),
                    name=f'serve.dispatch.{rid}',
                )
                if int(breaker_failures) > 0
                else None
                for rid in self.replica_ids
            ]
            # fail at construction, not first flush: the fan-out needs
            # one device per replica and the fused dispatch path, and a
            # service that cannot serve its topology must say so here
            self._dispatchers: List[Tuple[Any, Any]] = [
                (first, self._build_dispatcher(first))
            ]
        else:
            self.replica_ids = ()
            if breaker is not None:
                self._breakers = [breaker]
            elif int(breaker_failures) > 0:
                self._breakers = [
                    CircuitBreaker(
                        failure_threshold=int(breaker_failures),
                        recovery_time_s=float(breaker_recovery_s),
                        name='serve.dispatch',
                    )
                ]
            else:
                self._breakers = [None]
            self._dispatchers = []
        self._dispatcher_lock = threading.Lock()
        self._batcher = MicroBatcher(
            self._flush,
            max_batch_size=max_batch_size,
            max_wait_ms=max_wait_ms,
            max_queue=max_queue,
            on_crash=self._on_flusher_crash,
            on_request_done=self._on_request_done,
            n_lanes=self.n_replicas,
            lane_names=self.replica_ids or None,
        )
        self._shape_lock = threading.Lock()
        self._seen_shapes: set = set()
        self._seen_scenario_buckets: set = set()
        #: explicit artifact source for model-backed services
        self._aot_dir_override = aot_dir
        #: last AOT load summary + the (name, version) it was tried for
        self._aot_state: Optional[Dict[str, Any]] = None
        self._aot_tried_for: Optional[Tuple[str, str]] = None
        #: tier-2 (persistent compile cache) status from the last warmup
        self._cache_state: Optional[Dict[str, Any]] = None

    # -- model plumbing ----------------------------------------------------

    @staticmethod
    def _validate_model(model: Any) -> None:
        if not getattr(model, '_models', None):
            raise ValueError('the serving model must be fitted')
        if getattr(model, '_fused_registry', None) != 'standard':
            raise ValueError(
                'RatingService serves standard-SPADL VAEP models '
                '(atomic serving is not wired up yet)'
            )
        model._kernel_names()  # raises for kernel-less custom transformers

    def _active(self) -> Tuple[str, str, Any]:
        """One consistent ``(name, version, model)`` read (swap atomicity)."""
        if self._model is not None:
            return ('default', '0', self._model)
        return self._registry.active()

    @property
    def model(self) -> Any:
        """The model currently serving traffic."""
        return self._active()[2]

    @property
    def nb_prev_actions(self) -> int:
        """Game-state depth ``k`` of the serving model."""
        return int(self.model.nb_prev_actions)

    def _model_quantize(self) -> str:
        """Table-storage mode of the serving model ('none' when unknown).

        Mid-swap head disagreement (or a tree-head model with no fused
        fold) reports 'none' — health() must never raise over a label.
        """
        try:
            return str(getattr(self.model, 'quantize', 'none'))
        except ValueError:
            return 'none'

    def _model_kernel(self) -> str:
        """Resolved first-layer lowering serving this process.

        The value of :func:`~socceraction_tpu.ops.gather_matmul.fused_kernel_method`
        for the serving model's combined-table size — what a flush will
        actually dispatch through, after the env override and the
        platform-profile gate ('xla' for non-fused models: there is no
        first-layer kernel to select).
        """
        model = self.model
        if not getattr(model, '_can_fuse', lambda: False)():
            return 'xla'
        from ..ops.fused import REGISTRIES
        from ..ops.gather_matmul import fused_kernel_method

        registry = REGISTRIES[model._fused_registry]
        try:
            return fused_kernel_method(registry.combo_size)
        except ValueError:
            # a malformed SOCCERACTION_TPU_FUSED_KERNEL value must not
            # take down the health endpoint the operator needs to
            # diagnose it — the flush path raises (and degrades) on its
            # own terms
            return 'invalid'

    # -- replica fan-out plumbing ------------------------------------------

    @property
    def _breaker(self) -> Optional[CircuitBreaker]:
        """Lane 0's breaker — the single-replica service's only one."""
        return self._breakers[0]

    def _replica_kw(self, lane: int) -> Dict[str, str]:
        """The ``replica=`` label of one lane's serve-area series."""
        if not self.replica_ids:
            return {}
        return {'replica': self.replica_ids[lane]}

    def _build_dispatcher(self, model: Any) -> Any:
        """A :class:`~socceraction_tpu.parallel.serve.ReplicaDispatcher`
        for one model: params committed to every replica device once."""
        from ..parallel.serve import ReplicaDispatcher

        return ReplicaDispatcher(model, self.n_replicas)

    def _dispatcher_for(self, model: Any) -> Any:
        """The mesh executor serving ``model`` (built once per model).

        Keyed by model identity, bounded to the registry's working set
        (active + swap target + rollback source): a flush that read the
        active model mid-swap keeps ITS model's dispatcher even while a
        new one warms, so swap atomicity extends to the replica tier.
        """
        with self._dispatcher_lock:
            for m, d in self._dispatchers:
                if m is model:
                    return d
        dispatcher = self._build_dispatcher(model)
        with self._dispatcher_lock:
            for m, d in self._dispatchers:
                if m is model:  # lost a build race: keep the first
                    return d
            self._dispatchers.append((model, dispatcher))
            del self._dispatchers[:-3]
        return dispatcher

    def _prepare_swap_target(self, name: str, version: str) -> Any:
        """Load, validate, layout-guard and ladder-warm a swap target.

        The shared half of :meth:`swap_model` and :meth:`rollback_model`:
        the target must be serve-compatible (fitted, standard SPADL) and
        keep the active model's feature layout — sessions in flight pin
        their window shape to ``nb_prev_actions`` and the bucket ladder
        pins compiled shapes, so a layout change requires a new service,
        not a swap. The ladder is pre-warmed *before* the target goes
        live: a different head architecture is a different XLA program,
        and without this the first post-swap request would pay its
        compile inside its latency budget (observed ~1s on CPU);
        same-arch targets hit the jit cache and cost a few no-op
        dispatches. When the target version ships AOT artifacts
        (``aot/``, see :mod:`socceraction_tpu.serve.aot`) they are
        deserialized first, so even a *different*-architecture swap
        pre-warms by loading executables instead of compiling — and a
        corrupt or stale artifact set degrades to the compile loop
        below, never failing the swap.
        """
        old = self.model
        new = self._registry.load(name, version)
        self._validate_model(new)
        if new.nb_prev_actions != old.nb_prev_actions or (
            new._kernel_names() != old._kernel_names()
        ):
            raise ValueError(
                'swap target changes the feature layout '
                '(nb_prev_actions/xfns); start a new RatingService for it'
            )
        self._load_aot_for(name, version, new)
        A = self.max_actions
        # mesh-wide atomicity: EVERY replica is prepared (dispatcher
        # params committed to its device) and ladder-warmed before the
        # caller activates the target anywhere — one replica failing to
        # warm raises out of this loop and aborts the swap for all of
        # them, so no mixed-version mesh can ever serve
        rungs: Tuple[Optional[int], ...] = (
            window_ladder(A)
            if getattr(new, 'time_rungs', False)
            else (None,)
        )
        for lane in range(self.n_replicas):
            for b in self._batcher.ladder:
                for tl in rungs:
                    self._device_rate(
                        _empty_host_batch(1, A), _empty_gs(1, A), new, b,
                        lane=lane, time_len=tl,
                    )
        return new

    def swap_model(self, name: str, version: Optional[str] = None) -> Tuple[str, str]:
        """Atomically swap serving to ``name``/``version`` (default newest).

        The new version is validated, layout-guarded and ladder-warmed
        before activation (:meth:`_prepare_swap_target`). That ordering
        is the corrupt-checkpoint fallback: a damaged artifact (the
        registry load verifies content checksums and raises a
        ``ValueError`` naming the artifact) fails *this call* on the
        caller's thread — the previously active model keeps serving and
        the flusher never sees the broken candidate.
        """
        if self._registry is None:
            raise RuntimeError('swap_model needs a registry-backed service')
        try:
            # pin 'newest' NOW: the version validated and pre-warmed below
            # must be the exact version activated (a publish racing this
            # call could otherwise slip an unvalidated, cold model past the
            # gates)
            version = self._registry.resolve_version(name, version)
            self._prepare_swap_target(name, version)
            out = self._registry.activate(name, version)
            self._model_activated_t = time.monotonic()  # freshness SLO clock
            return out
        except Exception as e:
            # a failed rollout is exactly when an operator wants the
            # flight recorder: what was serving, what was queued, which
            # gate the new version failed
            self._maybe_dump(
                'swap_failure',
                {
                    'type': 'swap_failure',
                    'target': f'{name}/{version or "newest"}',
                    'error': f'{type(e).__name__}: {e}',
                },
            )
            raise

    def rollback_model(self) -> Tuple[str, str]:
        """Atomically roll serving back to the previously active version.

        The operator escape hatch after a bad promotion: the registry's
        :meth:`~socceraction_tpu.serve.registry.ModelRegistry.rollback`
        restores the version that was serving before the last swap —
        still resident in the load cache, so the swap itself is one
        reference assignment — after this service re-warms the bucket
        ladder for it (a rolled-back-to model with the same architecture
        hits the jit cache; the warmup is then a few no-op dispatches).
        Counted under ``serve/model_swaps{reason="rollback"}``; a
        failure dumps the flight recorder like a failed forward swap.
        """
        if self._registry is None:
            raise RuntimeError('rollback_model needs a registry-backed service')
        prev = self._registry.previous()
        if prev is None:
            raise RuntimeError('no previous version to roll back to')
        name, version = prev
        try:
            self._prepare_swap_target(name, version)
            # pin the exact version just validated/warmed: a promotion
            # racing this call changes "previous", and rolling back to a
            # version nobody validated must fail, not slip through
            out = self._registry.rollback(expected=(name, version))
            self._model_activated_t = time.monotonic()  # freshness SLO clock
            return out
        except Exception as e:
            self._maybe_dump(
                'swap_failure',
                {
                    'type': 'rollback_failure',
                    'target': f'{name}/{version}',
                    'error': f'{type(e).__name__}: {e}',
                },
            )
            raise

    # -- request entry points ----------------------------------------------

    def rate(
        self,
        actions: pd.DataFrame,
        *,
        home_team_id: Any = None,
        deadline_ms: Optional[float] = None,
        context: Optional[RequestContext] = None,
    ) -> Future:
        """Rate one match's SPADL actions; returns a Future of a DataFrame.

        ``actions`` is a single game's frame (like ``VAEP.rate``'s input,
        sans the metadata row); ``home_team_id`` defaults to the frame's
        ``home_team_id`` column when present. Packing runs on the calling
        thread; the device dispatch is coalesced with concurrent
        requests. The future resolves to a DataFrame with
        ``offensive_value`` / ``defensive_value`` / ``vaep_value``
        aligned to ``actions``' index, exactly equal to
        ``VAEP.rate``'s values for the same frame.

        Every call mints a :class:`~socceraction_tpu.obs.context.RequestContext`
        exposed on the future as ``future.context`` (and its id as
        ``future.request_id``) — the handle ``obsctl trace
        <request_id>`` reconstructs the request's path from.
        ``deadline_ms`` (default: the service's ``request_deadline_ms``)
        bounds the total wait: a request still queued past it fails with
        :class:`~socceraction_tpu.obs.context.DeadlineExceeded` instead
        of dispatching late.

        ``context`` accepts a pre-built :class:`RequestContext` — the
        process-hop form: a front-end process ships
        ``ctx.to_wire()`` with the request, the replica reconstructs it
        with :meth:`RequestContext.from_wire` and passes it here, so
        the ``request_id`` (and the remaining deadline budget) survive
        the hop end-to-end and ``obsctl trace <id>`` can stitch the
        request across both processes' run logs. ``deadline_ms`` is
        ignored when a context is given: the shipped context already
        carries the caller's remaining budget.

        Raises :class:`~socceraction_tpu.serve.batcher.Overloaded`
        synchronously when the admission queue is full, and its subclass
        :class:`SLOShed` when burn-rate admission control is shedding.
        """
        if len(actions) == 0:
            raise ValueError('cannot rate an empty actions frame')
        # shed BEFORE the packing work: a rejected request must cost the
        # burning service as close to nothing as possible
        self._check_admission('rate')
        if 'game_id' in actions.columns and actions['game_id'].nunique() > 1:
            raise ValueError(
                'one request rates one match; split multi-game frames '
                '(or use VAEP.rate_batch for offline batches)'
            )
        if home_team_id is None:
            if 'home_team_id' not in actions.columns:
                raise ValueError('home_team_id is required')
            home_team_id = actions['home_team_id'].iloc[0]
        if len(actions) > self.max_actions:
            raise ValueError(
                f'{len(actions)} actions exceed the service window '
                f'(max_actions={self.max_actions})'
            )
        frame = actions
        if 'game_id' not in frame.columns:
            frame = frame.assign(game_id=0)
        staging, _ids = pack_actions(
            frame, home_team_id=home_team_id, max_actions=self.max_actions,
            as_numpy=True,
        )
        gs = (
            self._frame_goalscore(frame, home_team_id)
            if self._gs_enabled
            else None
        )
        if context is not None:
            ctx = context
        else:
            ctx = new_request_context(
                'rate',
                deadline_ms=(
                    deadline_ms if deadline_ms is not None
                    else self.request_deadline_ms
                ),
            )
        payload = _Payload(staging, gs, keep=None, index=actions.index, ctx=ctx)
        future = self._submit(payload, 'rate', ctx)
        # capture ONLY on success, via the future: shed (Overloaded)
        # traffic never ran, deadline-expired requests were never
        # dispatched, and a failed flush never produced ratings —
        # replaying any of them would put traffic the service never
        # served into the shadow-calibration window. The copy happens
        # HERE, on the caller's thread: done-callbacks run on the
        # flusher thread, which must never pay a DataFrame copy per
        # request inside the flush loop.
        if self.capture is not None:
            capture = self.capture
            captured = actions.copy()

            def _record(fut: Future, _a=captured, _h=home_team_id) -> None:
                try:
                    if not fut.cancelled() and fut.exception() is None:
                        capture.record_frame(_a, _h, copy=False)
                except Exception:  # capture must never hurt the caller
                    pass

            future.add_done_callback(_record)
        return future

    def rate_sync(
        self, actions: pd.DataFrame, *, home_team_id: Any = None,
        timeout: Optional[float] = None,
        deadline_ms: Optional[float] = None,
    ) -> pd.DataFrame:
        """Blocking convenience wrapper around :meth:`rate`."""
        return self.rate(
            actions, home_team_id=home_team_id, deadline_ms=deadline_ms
        ).result(timeout)

    def rate_scenarios(
        self,
        actions: pd.DataFrame,
        grid: ScenarioGrid,
        *,
        home_team_id: Any = None,
        deadline_ms: Optional[float] = None,
        context: Optional[RequestContext] = None,
    ) -> Future:
        """Value every perturbation of one match in ONE fused dispatch.

        The counterfactual verb: ``actions`` is a single game's SPADL
        frame (same contract as :meth:`rate`), ``grid`` a
        :class:`~socceraction_tpu.scenario.grid.ScenarioGrid` of ``P``
        alternatives per action. The future resolves to a
        ``(P, len(actions), 3)`` float array — perturbation ``p``'s rows
        align with ``actions``' row order and carry the usual
        ``offensive/defensive/vaep`` triplet; row ``p`` is exactly what
        :meth:`rate` would return for the frame with perturbation ``p``
        applied (bitwise on CPU, pinned by test).

        ``P`` is snapped to its own power-of-two bucket
        (:func:`~socceraction_tpu.scenario.engine.bucket_perturbations`,
        edge-padded grid, result sliced back), so 1/64/4096-perturbation
        traffic each hits one compiled plateau — and because the folded
        dispatch is *the same program* as a ``P_bucket``-game rate flush,
        field-update grids reuse the serving rungs' compiled programs,
        warmup and AOT artifacts verbatim (custom dense-override grids
        compile their own signature once per bucket). Admission control,
        deadlines, SLO scoring (kind ``'scenario'``), the per-lane
        circuit breaker (fallback: the looped materialized reference —
        correct, slow) and the flight recorder all apply exactly as for
        :meth:`rate`; metrics land under the ``scenario`` area with
        ``n_perturbations_bucket`` labels.
        """
        if len(actions) == 0:
            raise ValueError('cannot rate scenarios for an empty actions frame')
        self._check_admission('scenario')
        if not isinstance(grid, ScenarioGrid):
            raise TypeError(
                'rate_scenarios needs a ScenarioGrid (build one with '
                'end_location_grid / action_type_sweep / custom_grid)'
            )
        P = grid.n_perturbations
        if P > self.max_perturbations:
            raise ValueError(
                f'{P} perturbations exceed the scenario ladder '
                f'(max_perturbations={self.max_perturbations})'
            )
        if 'game_id' in actions.columns and actions['game_id'].nunique() > 1:
            raise ValueError(
                'one request rates one match; split multi-game frames '
                '(or use rate_scenarios_batch for offline grids)'
            )
        if home_team_id is None:
            if 'home_team_id' not in actions.columns:
                raise ValueError('home_team_id is required')
            home_team_id = actions['home_team_id'].iloc[0]
        if len(actions) > self.max_actions:
            raise ValueError(
                f'{len(actions)} actions exceed the service window '
                f'(max_actions={self.max_actions})'
            )
        frame = actions
        if 'game_id' not in frame.columns:
            frame = frame.assign(game_id=0)
        staging, _ids = pack_actions(
            frame, home_team_id=home_team_id, max_actions=self.max_actions,
            as_numpy=True,
        )
        # fail malformed grids HERE, on the caller's thread, with the
        # model's named validation errors — not on the flusher
        for name, upd in grid.field_updates.items():
            if upd.ndim == 3 and upd.shape[1:] != (1, self.max_actions):
                raise ValueError(
                    f'field update {name!r} has shape {upd.shape}; per-action '
                    f'updates must be (P, 1, max_actions) = '
                    f'({P}, 1, {self.max_actions}) for this service'
                )
        model = self.model
        for name, block in grid.dense_overrides.items():
            model._validate_dense_overrides(staging, {name: block[0]})
        gs = (
            self._frame_goalscore(frame, home_team_id)
            if self._gs_enabled
            else None
        )
        if context is not None:
            ctx = context
        else:
            ctx = new_request_context(
                'scenario',
                deadline_ms=(
                    deadline_ms if deadline_ms is not None
                    else self.request_deadline_ms
                ),
            )
        counter('scenario/requests', unit='count').inc(1, verb='serve')
        payload = _ScenarioPayload(staging, gs, grid, actions.index, ctx)
        return self._submit(payload, 'scenario', ctx)

    def rate_scenarios_sync(
        self,
        actions: pd.DataFrame,
        grid: ScenarioGrid,
        *,
        home_team_id: Any = None,
        timeout: Optional[float] = None,
        deadline_ms: Optional[float] = None,
    ) -> np.ndarray:
        """Blocking convenience wrapper around :meth:`rate_scenarios`."""
        return self.rate_scenarios(
            actions, grid, home_team_id=home_team_id, deadline_ms=deadline_ms
        ).result(timeout)

    def open_session(self, match_id: Any, *, home_team_id: Any) -> MatchSession:
        """Start a live-match streaming session (see :class:`MatchSession`)."""
        names = set(self.model._kernel_names())
        nonlocal_names = names - WINDOW_LOCAL_KERNELS - {'goalscore'}
        if nonlocal_names:
            raise ValueError(
                f'feature kernels {sorted(nonlocal_names)} are not '
                'window-local; streaming sessions cannot rate suffixes '
                'under this model'
            )
        counter('serve/sessions_opened', unit='count').inc(1)
        return MatchSession(self, match_id, home_team_id)

    def _submit_window(
        self, window: pd.DataFrame, context: int, m: int,
        *, match_id: Any, home_team_id: Any,
    ) -> Future:
        """Session entry: pack a context+suffix window and enqueue it."""
        self._check_admission('session')
        staging, gs = pack_window(
            window, match_id, home_team_id, self.max_actions
        )
        ctx = new_request_context(
            'session', deadline_ms=self.request_deadline_ms
        )
        payload = _Payload(staging, gs, keep=(context, m), ctx=ctx)
        return self._submit(payload, 'session', ctx)

    def _check_admission(self, kind: str) -> None:
        """SLO burn-rate admission control; raises :class:`SLOShed`.

        A no-op without an ``slo=`` config. The verdict comes from the
        engine's cached multi-window evaluation, so the per-request cost
        is a dict lookup; sheds are counted per objective under
        ``slo/shed_total`` and, like queue overloads, feed the
        overload-burst debug-bundle trigger.
        """
        if self._slo is None:
            return
        shed, reason = self._slo.should_shed(kind)
        if shed:
            counter('slo/shed_total', unit='requests').inc(
                1, objective=reason['objective']
            )
            self._note_overload()
            raise SLOShed(reason)

    def _on_request_done(
        self, ctx: Optional[RequestContext], kind: str, wall_s: float,
        status: str,
    ) -> None:
        """Batcher terminal-state hook: score the request against the SLOs."""
        if self._slo is not None and kind != 'warmup':
            self._slo.observe_request(kind, wall_s, status)

    def _on_slo_breach(self, objective: str, entry: Dict[str, Any]) -> None:
        """SLO engine breach hook: dump the flight recorder (rate-limited)."""
        self._maybe_dump(
            'slo_breach',
            {'type': 'slo_breach', 'objective': objective, 'evaluation': entry},
        )

    def _submit(
        self, payload: '_Payload', kind: str,
        ctx: Optional[RequestContext] = None,
    ) -> Future:
        """Enqueue via the batcher, counting ``Overloaded`` bursts."""
        try:
            return self._batcher.submit(payload, kind=kind, ctx=ctx)
        except Overloaded:
            self._note_overload()
            raise

    # -- the flush (runs on the batcher's flusher thread) ------------------

    def _frame_goalscore(self, frame: pd.DataFrame, home_team_id: Any) -> np.ndarray:
        """Whole-frame goalscore block ``(1, A, 3)`` computed on host.

        Every request carries this block (not just session windows) so
        all flushes execute the SAME program per bucket — one compiled
        shape, whether the batch mixes fresh matches and live suffixes
        or not. Values come from the session module's ``score_prefix``
        (the single host mirror of the device kernel): small integer
        counts, bitwise what the kernel computes.
        """
        is_home = frame['team_id'].to_numpy() == home_team_id
        team, opp, _a, _b = score_prefix(
            frame['type_id'].to_numpy(dtype=np.int64),
            frame['result_id'].to_numpy(dtype=np.int64),
            is_home == bool(is_home[0]),
        )
        return goalscore_block(team, opp, self.max_actions)

    def _device_rate(
        self,
        host_batch: ActionBatch,
        gs: Optional[np.ndarray],
        model: Any,
        bucket: int,
        lane: int = 0,
        extra_overrides: Optional[Dict[str, np.ndarray]] = None,
        time_len: Optional[int] = None,
    ) -> np.ndarray:
        """Pad to the bucket, dispatch on ``lane``'s device, fetch to host.

        The single-replica service dispatches ``rate_batch`` on the
        default device (the pre-mesh path, byte for byte); the fan-out
        service routes every lane — replica 0 included — through the
        mesh executor's committed per-device dispatch, which runs the
        same program (bitwise-pinned by the parity tests). Shape
        accounting is per replica: each lane compiles its own ladder,
        and the trace counters must plateau per replica.

        ``extra_overrides`` carries a scenario grid's custom dense
        blocks (already expanded to ``(bucket, A, width)``). The replica
        dispatcher's wire protocol only ships the goalscore block, so a
        dispatch WITH extra overrides runs locally on the lane's default
        path even on a fan-out service — the rare custom-grid case
        degrades to local dispatch rather than growing the mesh wire
        format.

        ``time_len`` is the window-length rung for time-rung models
        (``model.time_rungs``): the action axis is sliced to the rung
        AFTER bucket padding, dispatched at the reduced shape, and the
        returned values are zero-padded back to the caller's capacity —
        so unpacking against full-capacity staging masks is unchanged.
        Safe because every kernel is backward-looking over masked tails
        and the rung never truncates a valid row
        (``bucket_window(max n_actions) >= max n_actions``). The sliced
        ``max_actions`` lands in the compiled-shape key, so each rung is
        its own pinned program — the time analogue of the game-axis
        bucket ladder.
        """
        import jax
        import jax.numpy as jnp

        host_batch, gs = _pad_to_bucket(host_batch, gs, bucket)
        orig_A = host_batch.max_actions
        if time_len is not None and time_len < orig_A:
            host_batch, gs = _slice_window(host_batch, gs, time_len)
            if extra_overrides:
                extra_overrides = {
                    k: v[:, :time_len] for k, v in extra_overrides.items()
                }
            counter('seq/window_slices', unit='count').inc(
                1, window=str(time_len)
            )
        key = (bucket, host_batch.max_actions, lane)
        with self._shape_lock:
            new_shape = key not in self._seen_shapes
            if new_shape:
                self._seen_shapes.add(key)
                n_shapes = len(self._seen_shapes)
        if new_shape:
            counter('serve/shape_traces', unit='count').inc(
                1, bucket=str(bucket), **self._replica_kw(lane)
            )
            gauge('serve/compiled_shapes', unit='shapes').set(n_shapes)
        fault_point('serve.dispatch', bucket=bucket)
        if self.n_replicas > 1 and not extra_overrides:
            values = self._dispatcher_for(model).rate_replica(
                lane, host_batch, gs if self._gs_enabled else None
            )
            return _pad_values_time(np.asarray(values), orig_A)
        batch = jax.device_put(host_batch)
        overrides: Dict[str, Any] = {}
        if self._gs_enabled and gs is not None:
            overrides['goalscore'] = jnp.asarray(gs)
        if extra_overrides:
            # custom scenario dense-override blocks: same program shape
            # discipline, their own compiled signature per bucket
            overrides.update(
                {k: jnp.asarray(v) for k, v in extra_overrides.items()}
            )
        values = model.rate_batch(
            batch, dense_overrides=overrides or None, bucket=False
        )
        return _pad_values_time(np.asarray(jax.device_get(values)), orig_A)

    def _reference_rate(
        self,
        host_batch: ActionBatch,
        gs: Optional[np.ndarray],
        model: Any,
    ) -> np.ndarray:
        """The degraded path: the materialized reference rating.

        Same values contract as the fused dispatch (parity-pinned) but
        computed through the materialized feature tensor — the path the
        parity probe already keeps warm and honest. Slower per flush;
        correct, which is what degradation is for.
        """
        import jax
        import jax.numpy as jnp

        batch = jax.device_put(host_batch)
        overrides = (
            {'goalscore': jnp.asarray(gs)}
            if self._gs_enabled and gs is not None
            else None
        )
        values = model.rate_batch_reference(batch, dense_overrides=overrides)
        return np.asarray(jax.device_get(values))

    def _rate_with_breaker(
        self,
        host_batch: ActionBatch,
        gs: Optional[np.ndarray],
        model: Any,
        bucket: int,
        lane: int = 0,
        time_len: Optional[int] = None,
    ) -> Tuple[np.ndarray, str]:
        """One flush's rating through its lane's breaker; (values, path).

        ``path`` is ``'fused'`` (healthy or successful half-open probe)
        or ``'fallback'`` (breaker open, or this flush's fused dispatch
        failed). A fused failure is recorded on the breaker and the
        SAME flush is served through the fallback — callers see
        degraded latency, never a spurious error, and
        ``failure_threshold`` consecutive failures trip the breaker so
        later flushes skip the doomed dispatch entirely. A fallback
        failure propagates (the batcher fails the flush's futures —
        when both paths are down there is nothing to degrade to).

        Each replica lane carries its OWN breaker: a device fault on one
        replica trips that lane alone onto the materialized fallback
        while the other lanes keep dispatching fused — the mesh
        topology's single-sick-replica degradation, pinned by test.
        """
        breaker = self._breakers[lane]
        if breaker is None:
            return (
                self._device_rate(
                    host_batch, gs, model, bucket, lane, time_len=time_len
                ),
                'fused',
            )
        verdict = breaker.allow()
        if verdict == 'open':
            counter('serve/fallback_flushes', unit='count').inc(
                1, **self._replica_kw(lane)
            )
            return self._reference_rate(host_batch, gs, model), 'fallback'
        try:
            values = self._device_rate(
                host_batch, gs, model, bucket, lane, time_len=time_len
            )
        except Exception as e:
            tripped = breaker.record_failure(e)
            if tripped:
                self._maybe_dump(
                    'breaker_open',
                    {
                        'type': 'breaker_open',
                        'error': f'{type(e).__name__}: {e}',
                        'breaker': breaker.to_dict(),
                    },
                )
            counter('serve/fallback_flushes', unit='count').inc(
                1, **self._replica_kw(lane)
            )
            return self._reference_rate(host_batch, gs, model), 'fallback'
        breaker.record_success()
        return values, 'fused'

    def _flush(
        self, payloads: List[Any], bucket: int, *, lane: int = 0
    ) -> List[Any]:
        """The batcher's runner: route a take to its dispatch shape(s).

        Plain rate/session payloads coalesce into one bucket-padded
        dispatch (:meth:`_flush_rate`, the classic path — byte for byte
        when no scenario traffic is queued). Scenario payloads fold
        their perturbation axis into the game axis at their OWN bucket,
        so each dispatches as its own flush (:meth:`_flush_scenario`);
        a mixed take is partitioned and results are reassembled in
        payload order.
        """
        if not any(isinstance(p, _ScenarioPayload) for p in payloads):
            return self._flush_rate(payloads, bucket, lane=lane)
        plain = [p for p in payloads if not isinstance(p, _ScenarioPayload)]
        results: Dict[int, Any] = {}
        if plain:
            plain_bucket = self._batcher.bucket_for(len(plain))
            for p, r in zip(
                plain, self._flush_rate(plain, plain_bucket, lane=lane)
            ):
                results[id(p)] = r
        for p in payloads:
            if isinstance(p, _ScenarioPayload):
                results[id(p)] = self._flush_scenario(p, lane=lane)
        return [results[id(p)] for p in payloads]

    def _flush_scenario(
        self, p: '_ScenarioPayload', *, lane: int = 0
    ) -> np.ndarray:
        """One scenario request -> ``(P, n_rows, 3)``, ONE fused dispatch.

        The perturbation count snaps to its power-of-two bucket
        (edge-padded grid, sliced back), the grid expands to a
        ``(P_bucket, A)`` staging batch, and the dispatch goes through
        the lane's breaker exactly like a rate flush — a field-update
        grid at bucket ``b`` runs the SAME compiled program as a
        ``b``-game rate flush, so scenario rungs share warmup, the
        compile cache and AOT artifacts with the serving ladder.
        """
        _name, _version, model = self._active()  # ONE read per flush
        t0 = time.perf_counter()
        P = p.grid.n_perturbations
        p_bucket = bucket_perturbations(P)
        grid = pad_perturbations(p.grid, p_bucket)
        expanded, extra = expand_scenarios(p.staging, grid)
        if 'goalscore' in extra:
            # a grid that perturbs goalscore overrides the service's
            # factual block — one source per dense name, grid wins
            gs_full: Optional[np.ndarray] = extra.pop('goalscore')
        elif self._gs_enabled and p.gs is not None:
            gs_full = np.tile(p.gs, (p_bucket, 1, 1))
        else:
            gs_full = None
        bucket_label = str(p_bucket)
        with self._shape_lock:
            new_bucket = p_bucket not in self._seen_scenario_buckets
            if new_bucket:
                self._seen_scenario_buckets.add(p_bucket)
        if new_bucket:
            counter('scenario/shape_traces', unit='count').inc(
                1, n_perturbations_bucket=bucket_label
            )
        t_pad = time.perf_counter()
        values, path = self._rate_scenarios_with_breaker(
            p, expanded, gs_full, extra or None, model, p_bucket, lane
        )
        t_dispatch = time.perf_counter()
        dispatch_s = t_dispatch - t_pad
        if path == 'fused':
            # the scenario dispatch runs the pair program at the
            # perturbation bucket: feed the live roofline like any
            # other fused flush
            record_dispatch('pair_probs', dispatch_s, bucket=p_bucket)
            counter('scenario/dispatches', unit='count').inc(
                1, n_perturbations_bucket=bucket_label
            )
        else:
            counter('scenario/fallbacks', unit='count').inc(1)
        self._drain_numeric_guards()
        rows = np.stack(
            [unpack_values(values[q : q + 1], p.staging) for q in range(P)]
        )
        t_slice = time.perf_counter()
        histogram('scenario/dispatch_seconds', unit='s').observe(
            dispatch_s, n_perturbations_bucket=bucket_label
        )
        n_values = P * rows.shape[1]
        counter('scenario/values', unit='values').inc(n_values)
        if dispatch_s > 0:
            gauge('scenario/values_per_sec', unit='values/s').set(
                n_values / dispatch_s, n_perturbations_bucket=bucket_label
            )
        exemplar = p.ctx.request_id if p.ctx is not None else None
        replica_kw = self._replica_kw(lane)
        pad_s = t_pad - t0
        slice_s = t_slice - t_dispatch
        record_segment('pad', pad_s, exemplar, **replica_kw)
        record_segment('dispatch', dispatch_s, exemplar, **replica_kw)
        record_segment('slice', slice_s, exemplar, **replica_kw)
        if p.ctx is not None:
            p.ctx.segments.update(
                pad=pad_s, dispatch=dispatch_s, slice=slice_s
            )
        return rows

    def _rate_scenarios_with_breaker(
        self,
        p: '_ScenarioPayload',
        expanded: ActionBatch,
        gs_full: Optional[np.ndarray],
        extra: Optional[Dict[str, np.ndarray]],
        model: Any,
        p_bucket: int,
        lane: int,
    ) -> Tuple[np.ndarray, str]:
        """The scenario dispatch through its lane's breaker; (values, path).

        Same contract as :meth:`_rate_with_breaker` — ``'fused'`` means
        the one-dispatch expanded batch served, ``'fallback'`` means the
        looped materialized reference
        (:func:`~socceraction_tpu.scenario.engine.rate_scenarios_reference`
        over the UNPADDED grid: ``P`` slow-but-correct dispatches,
        counted against the same breaker state as rate flushes so a sick
        device degrades every verb on the lane together).
        """

        def fallback() -> np.ndarray:
            counter('serve/fallback_flushes', unit='count').inc(
                1, **self._replica_kw(lane)
            )
            overrides = (
                {'goalscore': p.gs}
                if self._gs_enabled and p.gs is not None
                and 'goalscore' not in p.grid.dense_overrides
                else None
            )
            ref = rate_scenarios_reference(
                model, p.staging, p.grid, dense_overrides=overrides
            )
            return ref.reshape(ref.shape[0], *ref.shape[2:])

        breaker = self._breakers[lane]
        if breaker is None:
            return (
                self._device_rate(
                    expanded, gs_full, model, p_bucket, lane,
                    extra_overrides=extra,
                ),
                'fused',
            )
        if breaker.allow() == 'open':
            return fallback(), 'fallback'
        try:
            values = self._device_rate(
                expanded, gs_full, model, p_bucket, lane,
                extra_overrides=extra,
            )
        except Exception as e:
            tripped = breaker.record_failure(e)
            if tripped:
                self._maybe_dump(
                    'breaker_open',
                    {
                        'type': 'breaker_open',
                        'error': f'{type(e).__name__}: {e}',
                        'breaker': breaker.to_dict(),
                    },
                )
            return fallback(), 'fallback'
        breaker.record_success()
        return values, 'fused'

    def _flush_rate(
        self, payloads: List[_Payload], bucket: int, *, lane: int = 0
    ) -> List[Any]:
        _name, _version, model = self._active()  # ONE read per flush
        t0 = time.perf_counter()
        stagings = [p.staging for p in payloads]
        if len(stagings) == 1:
            host_batch = stagings[0]
            gs = payloads[0].gs
        else:
            import jax

            host_batch = jax.tree.map(
                lambda *xs: np.concatenate(xs, axis=0), *stagings
            )
            gs = (
                np.concatenate([p.gs for p in payloads], axis=0)
                if self._gs_enabled
                else None
            )
        # pad here (not inside the dispatch) so the host-side concat+pad
        # overhead is charged to the 'pad' segment, never to 'dispatch'
        # (_device_rate's own pad then no-ops; warmup still relies on it)
        host_batch, gs = _pad_to_bucket(host_batch, gs, bucket)
        # time-rung models (seq heads) also snap the WINDOW length to a
        # power-of-two rung: the flush's longest game picks the rung, the
        # dispatch runs at (bucket, rung), and values come back padded to
        # full capacity so unpacking below is rung-blind
        time_len = (
            bucket_window(
                int(np.asarray(host_batch.n_actions).max()), self.max_actions
            )
            if getattr(model, 'time_rungs', False)
            else None
        )
        t_pad = time.perf_counter()
        values, path = self._rate_with_breaker(
            host_batch, gs, model, bucket, lane, time_len=time_len
        )
        t_dispatch = time.perf_counter()
        if path == 'fused':
            # the live roofline's serve feed: the flush's dispatch wall
            # is host-synced (it ends after the device_get), so AOT cost
            # over it is an honest achieved rate. Fallback flushes run
            # the materialized reference — a different program whose
            # wall must not be divided by the fused path's cost — and
            # the same call feeds the flusher loop's idle detector
            # (inter-dispatch gaps -> perf/device_idle_frac).
            record_dispatch(
                'pair_probs', t_dispatch - t_pad, bucket=bucket
            )
        # the dispatch's results are on host now, so its side-band guard
        # scalars are ready: draining here converts without syncing
        # anything the flush did not already wait for
        self._drain_numeric_guards()
        # fallback flushes already ARE the reference path — probing them
        # would compare the reference against itself and read as parity
        # evidence for a fused path that never ran
        if (
            self.parity is not None
            and path == 'fused'
            and self.parity.should_sample()
        ):
            self.parity.submit_flush(
                model, host_batch,
                gs if self._gs_enabled else None, values,
                exemplar=next(
                    (p.ctx.request_id for p in payloads if p.ctx is not None),
                    None,
                ),
            )

        results: List[Any] = []
        for i, p in enumerate(payloads):
            if p.keep is None:
                rows = unpack_values(values[i : i + 1], p.staging)
                results.append(
                    pd.DataFrame(rows, columns=RATING_COLUMNS, index=p.index)
                )
            else:
                context, m = p.keep
                results.append(values[i, context : context + m, :].copy())
        t_slice = time.perf_counter()

        # the flush-shared half of the per-request wall decomposition
        # (queue_wait is the batcher's): pad/dispatch are one shared cost
        # per flush, slicing is attributed evenly — recorded once per
        # flush with the first coalesced request id as the exemplar, and
        # onto every request's context for its request_done event
        exemplar = next(
            (p.ctx.request_id for p in payloads if p.ctx is not None), None
        )
        pad_s = t_pad - t0
        dispatch_s = t_dispatch - t_pad
        slice_s = t_slice - t_dispatch
        replica_kw = self._replica_kw(lane)
        record_segment('pad', pad_s, exemplar, **replica_kw)
        record_segment('dispatch', dispatch_s, exemplar, **replica_kw)
        record_segment('slice', slice_s, exemplar, **replica_kw)
        for p in payloads:
            if p.ctx is not None:
                p.ctx.segments.update(
                    pad=pad_s, dispatch=dispatch_s, slice=slice_s
                )
        return results

    # -- numeric health -----------------------------------------------------

    def _drain_numeric_guards(self) -> None:
        """Drain pending in-dispatch guards; act on nonzero detections.

        Runs on the flusher thread, after the flush's ``device_get``.
        A detection is already counted/evented by the drain itself
        (``num/nonfinite_total`` + ``nonfinite_detected``); the service
        adds the operational response — the rate-limited debug bundle
        and the :meth:`health` degradation — for **nonfinite** events
        only. Overflow events (finite-but-saturating logits) stay a
        metric-level warning (``num/overflow_guard_total``): the served
        values were valid probabilities, so they must not flip health or
        block promotions as if a NaN had shipped.
        """
        try:
            events = drain_guards()
        except Exception:  # guard telemetry must never fail a flush
            return
        bad = [e for e in events if e.kind == 'nonfinite']
        if not bad:
            return
        with self._dump_lock:
            self._nonfinite_events += len(bad)
        self._maybe_dump(
            'nonfinite',
            {
                'type': 'nonfinite_dispatch',
                'events': [e.to_dict() for e in bad],
            },
        )

    def _on_parity_exceed(self, observation: Dict[str, Any]) -> None:
        """Parity-probe band breach: dump the flight recorder (rate-limited)."""
        self._maybe_dump(
            'parity', {'type': 'parity_exceeded', 'observation': observation}
        )

    # -- flight recorder + health ------------------------------------------

    def _queue_state(self) -> Dict[str, Any]:
        """The batcher's current state, for triggers and ``health()``."""
        b = self._batcher
        crashed = b.crashed
        return {
            'queue_depth': b.queue_depth,
            'max_queue': b.max_queue,
            'flusher_alive': b.flusher_alive,
            'flusher_error': (
                f'{type(crashed).__name__}: {crashed}' if crashed else None
            ),
            'last_flush_age_s': b.last_flush_age_s,
        }

    def _maybe_dump(self, reason: str, trigger: Dict[str, Any]) -> Optional[str]:
        """Write a debug bundle, rate-limited per reason; never raises.

        Every trigger increments ``serve/debug_dumps{reason=...}`` even
        when the bundle itself is rate-limited away (the counter counts
        trigger events, the files stay bounded).
        """
        counter('serve/debug_dumps', unit='count').inc(1, reason=reason)
        now = time.monotonic()
        with self._dump_lock:
            last = self._last_dump_t.get(reason)
            if last is not None and now - last < self.dump_interval_s:
                return None
            self._last_dump_t[reason] = now
        try:
            path = dump_debug_bundle(
                self.debug_dir,
                reason=reason,
                trigger={**trigger, 'queue_state': self._queue_state()},
            )
        except Exception:  # a failing dump must never mask the trigger
            return None
        self.last_dump_path = path
        return path

    def _on_flusher_crash(self, exc: BaseException) -> None:
        """Batcher crash hook: the service is dead — dump the recorder."""
        self._maybe_dump(
            'flusher_crash',
            {'type': 'flusher_crash', 'error': f'{type(exc).__name__}: {exc}'},
        )

    def _note_overload(self) -> None:
        """Track ``Overloaded`` raises; a burst past the threshold dumps."""
        now = time.monotonic()
        with self._dump_lock:
            self._overloads.append(now)
            cutoff = now - self.overload_dump_window_s
            while self._overloads and self._overloads[0] < cutoff:
                self._overloads.popleft()
            burst = len(self._overloads)
        if burst >= self.overload_dump_threshold:
            self._maybe_dump(
                'overload',
                {
                    'type': 'overload_burst',
                    'rejections_in_window': burst,
                    'window_s': self.overload_dump_window_s,
                },
            )

    def _aot_block(self) -> Dict[str, Any]:
        """The ``health()['aot']`` entry: the last AOT-tier load verdict.

        ``available`` is False until a load was attempted (model-backed
        service without artifacts, or warmup not yet run); afterwards
        the block carries the outcome (``hit``/``stale``/``miss``),
        entries loaded, the shipped fingerprint, and — for ``stale`` —
        the mismatched fingerprint keys an operator needs to see
        *which* environment axis moved (jaxlib upgrade? different
        device kind?) without digging through the recorder.
        """
        state = self._aot_state
        if state is None:
            block: Dict[str, Any] = {'available': False}
        else:
            block = {
                'available': True,
                'outcome': state.get('outcome'),
                'entries_loaded': state.get('entries_loaded', 0),
            }
            for key in ('model', 'reason', 'mismatch', 'fingerprint'):
                if state.get(key) is not None:
                    block[key] = state[key]
        if self._cache_state is not None:
            # tier 2's status: dir (None = off/broken) and, when the
            # configured cache failed to enable, the error — "off by
            # choice" and "silently inactive" must read differently
            block['compile_cache'] = dict(self._cache_state)
        return block

    def health(self) -> Dict[str, Any]:
        """Liveness/pressure dict for external pollers (one cheap call).

        Reads only host state and the typed metric snapshot — no device
        work, safe on any thread at any rate. Keys: ``status``
        (``'ok'`` | ``'degraded'`` | ``'flusher-dead'``), the queue
        state (depth/bounds/last-flush age), the active model
        ``{'name', 'version'}``, compiled-shape budget vs. ladder, the
        measured request p99 vs. the ``slo_p99_ms`` budget, the
        ``numerics`` block (in-dispatch guard detections + parity-probe
        stats — ``status`` degrades to ``'degraded'`` when this
        service's flushes detected non-finite values or a parity probe
        breached its band), the ``breaker`` block (a non-closed
        fused-dispatch breaker also reads ``'degraded'`` — flushes are
        being served through the reference fallback),
        ``flusher_restarts`` (supervised restarts absorbed so far),
        the ``aot`` block (the shipped-executable tier's last load
        verdict — outcome, entries, fingerprint; see
        :mod:`socceraction_tpu.serve.aot`),
        the ``capacity`` block (the live roofline's per-function
        ``perf`` entries — achieved FLOPs/bytes, roofline fraction
        where a device peak is known, device-idle fraction — plus the
        residency ledger's ``owned_bytes`` per owner; host state only,
        no live-array census — that walk is ``obsctl capacity`` /
        ``residency_report()``'s on-demand cost), rejection and
        debug-dump totals, and ``last_dump`` (path or None).
        """
        snap = REGISTRY.snapshot()
        # worst p99 across traffic kinds (rate AND session) — a
        # session-only deployment must not report a permanently blind SLO
        lat = snap.get('serve/request_seconds')
        p99s = [
            s.quantiles['p99']
            for s in (lat.series if lat is not None else ())
            if s.count and s.quantiles and s.labels.get('kind') != 'warmup'
        ]
        p99_ms = max(p99s) * 1e3 if p99s else None
        name, version, _model = self._active()
        state = self._queue_state()
        slo_block: Dict[str, Any] = {
            'request_p99_ms': p99_ms,
            'budget_p99_ms': self.slo_p99_ms,
            'ok': None if p99_ms is None else bool(p99_ms <= self.slo_p99_ms),
        }
        if self._slo is not None:
            # per-objective burn rates + budget remaining, freshly
            # evaluated (health is the poll that keeps the windows moving
            # even when no admission decision forced an evaluation)
            evaluation = self._slo.evaluate()
            slo_block['objectives'] = evaluation['objectives']
            slo_block['shed_burn_rate'] = evaluation['shed_burn_rate']
            slo_block['shedding'] = bool(
                self._slo.should_shed('rate')[0]
                or self._slo.should_shed('session')[0]
            )
        with self._dump_lock:
            nonfinite_events = self._nonfinite_events
        parity_stats = self.parity.stats() if self.parity is not None else None
        numerics_ok = nonfinite_events == 0 and (
            parity_stats is None or parity_stats['exceedances'] == 0
        )
        breaker_block = (
            self._breaker.to_dict() if self._breaker is not None else None
        )
        breaker_ok = breaker_block is None or breaker_block['state'] == 'closed'
        replicas_block: Optional[Dict[str, Any]] = None
        sick: List[str] = []
        if self.replica_ids:
            # the mesh view: one entry per replica, naming exactly which
            # lane is sick (breaker open/probing, or its flusher retired)
            dead = self._batcher.dead_lanes
            per_replica: Dict[str, Any] = {}
            for lane, rid in enumerate(self.replica_ids):
                b = self._breakers[lane]
                b_dict = b.to_dict() if b is not None else None
                lane_dead = lane in dead
                healthy = not lane_dead and (
                    b_dict is None or b_dict['state'] == 'closed'
                )
                per_replica[rid] = {
                    'breaker': b_dict,
                    'flusher_dead': lane_dead,
                    'healthy': healthy,
                }
                if not healthy:
                    sick.append(rid)
                breaker_ok = breaker_ok and (
                    b_dict is None or b_dict['state'] == 'closed'
                )
            replicas_block = {
                'n': self.n_replicas,
                'per_replica': per_replica,
                'sick': sick,
            }
        owned = owned_bytes()
        if not state['flusher_alive']:
            status = 'flusher-dead'
        elif not numerics_ok or not breaker_ok or sick:
            status = 'degraded'
        else:
            status = 'ok'
        out_replicas = (
            {'replicas': replicas_block} if replicas_block is not None else {}
        )
        return {
            'status': status,
            **state,
            **out_replicas,
            'numerics': {
                'ok': numerics_ok,
                'nonfinite_events': nonfinite_events,
                'parity': parity_stats,
            },
            'breaker': breaker_block,
            'flusher_restarts': self._batcher.flusher_restarts,
            'model': {
                'name': name,
                'version': version,
                # the serving numerics configuration: table-storage mode
                # + the resolved first-layer lowering (operators gating a
                # quantized deploy read these next to numerics.parity)
                'quantize': self._model_quantize(),
                'kernel': self._model_kernel(),
            },
            'ladder': list(self.ladder),
            'compiled_shapes': self.compiled_shapes,
            'aot': self._aot_block(),
            'capacity': {
                'perf': perf_snapshot(),
                'owned_bytes': owned,
                'owned_total_bytes': sum(owned.values()),
            },
            'slo': slo_block,
            'rejected_total': int(snap.value('serve/rejected_total')),
            'debug_dumps': int(
                sum(s.total for s in dumps.series)
                if (dumps := snap.get('serve/debug_dumps')) is not None
                else 0
            ),
            'last_dump': self.last_dump_path,
            'uptime_s': time.monotonic() - self._started_t,
        }

    def telemetry(self, replica: Optional[str] = None) -> Any:
        """This replica's exposition bundle for the fleet scrape surface.

        Returns an :class:`~socceraction_tpu.obs.endpoint.Telemetry`
        wired to the process registry, this service's :meth:`health`
        and the flight recorder; start the per-replica endpoint with::

            from socceraction_tpu.obs.endpoint import serve
            endpoint = serve(telemetry=service.telemetry(replica='replica-0'))

        ``replica`` is the fleet slot name, governed by the bounded
        :class:`~socceraction_tpu.obs.wire.ReplicaRegistry` (default: a
        host-pid id). Every route reads host state only — a replica
        under scrape never touches the device, keeping the compiled
        ladder's zero steady-state retraces.
        """
        from ..obs.endpoint import Telemetry

        return Telemetry(replica=replica, health=self.health)

    # -- lifecycle ---------------------------------------------------------

    def _aot_source(self, name: str, version: str) -> Optional[str]:
        """Where this service's shipped executables live, or ``None``."""
        if self._aot_dir_override is not None:
            return self._aot_dir_override
        if self._registry is not None:
            return self._registry.aot_dir(name, version)
        return None

    def _load_aot_for(
        self, name: str, version: str, model: Any
    ) -> Optional[Dict[str, Any]]:
        """Try the AOT tier for one model version; never raises.

        The whole deserialize path — manifest parse, fingerprint check,
        checksum-verified artifact reads (the ``registry.aot`` fault
        point + retry site), preloading — lives in
        :func:`socceraction_tpu.serve.aot.load_serving_aot`, which
        reports every failure as a counted ``stale``/``miss`` outcome
        instead of raising. So a corrupt artifact, a moved jaxlib or a
        foreign device kind can never fail a warmup or a swap: the
        caller's compile loop runs right after and pays XLA for
        whatever did not preload.
        """
        source = self._aot_source(name, version)
        if source is None:
            return None
        from .aot import load_serving_aot

        state = load_serving_aot(
            model,
            source,
            ladder=self._batcher.ladder,
            max_actions=self.max_actions,
            context={'model': f'{name}/{version}'},
        )
        self._aot_state = state
        self._aot_tried_for = (name, version)
        return state

    def load_aot(self) -> Optional[Dict[str, Any]]:
        """Deserialize shipped executables for the active model (tier 1).

        The explicit first tier of :meth:`warmup` — callers that meter
        their cold start phase-by-phase (``bench.py --cold-start``'s
        ``aot_deserialize`` phase) run it separately; ``warmup()``
        otherwise runs it implicitly. Returns the load summary
        (``outcome`` ``hit``/``stale``/``miss`` — see
        :func:`socceraction_tpu.serve.aot.load_serving_aot`), or
        ``None`` when the service has no artifact source (model-backed,
        no ``aot_dir=``). Idempotent per active version.
        """
        name, version, model = self._active()
        if self._aot_tried_for == (name, version):
            return self._aot_state
        return self._load_aot_for(name, version, model)

    def warmup(
        self,
        buckets: Optional[Tuple[int, ...]] = None,
        *,
        scenario_buckets: Optional[Tuple[int, ...]] = None,
    ) -> Tuple[int, ...]:
        """Warm the bucket ladder: deserialize > cached compile > compile.

        ``scenario_buckets`` unions extra perturbation-bucket rungs into
        the warm set: a scenario dispatch at bucket ``b`` runs the SAME
        program as a ``b``-game rate flush, so warming (or
        AOT-exporting) a ladder that includes the scenario buckets —
        e.g. ``service.scenario_ladder`` or just ``(64, 4096)`` — makes
        steady-state scenario traffic retrace-free with no scenario-
        specific compile machinery at all.

        Three tiers, best available first (the cold-start ladder the
        serving runbook is written around):

        1. **shipped AOT executables** — :meth:`load_aot`: when the
           registry version carries ``aot/`` artifacts and the
           environment fingerprint matches, every rung's compiled
           programs deserialize and preload; the warmup dispatches
           below then execute them without compiling anything.
        2. **persistent compile cache** — when
           ``SOCCERACTION_TPU_COMPILE_CACHE`` names a directory
           (:func:`socceraction_tpu.serve.aot.enable_compile_cache`),
           rungs that did not preload compile through jax's persistent
           cache — a warm cache turns XLA compiles into reads.
        3. **cold compile** — the pre-AOT behavior; serving the first
           real request on a cold shape would otherwise pay XLA inside
           its latency budget.

        After warmup the per-bucket trace counters must stay flat
        regardless of tier (pinned by the tests and the
        ``serve_throughput`` bench). Returns the buckets warmed.
        """
        buckets = tuple(buckets) if buckets is not None else self._batcher.ladder
        if scenario_buckets:
            buckets = tuple(
                sorted(set(buckets) | {int(b) for b in scenario_buckets})
            )
        name, version, model = self._active()
        from .aot import enable_compile_cache

        try:
            self._cache_state = {'dir': enable_compile_cache()}
        except Exception as e:
            # a broken cache dir must not fail warmup — but a configured
            # tier silently inactive is the exact failure mode this
            # module's loud-degradation stance exists for: record it
            # where the AOT outcomes already live (health()['aot'],
            # flight recorder) so "cache off by choice" and "cache
            # broken" are distinguishable
            self._cache_state = {
                'dir': None, 'error': f'{type(e).__name__}: {e}'
            }
            from ..obs.recorder import RECORDER

            try:
                RECORDER.record('compile_cache_error', **self._cache_state)
            except Exception:
                pass
        if self._aot_tried_for != (name, version):
            self._load_aot_for(name, version, model)
        A = self.max_actions
        # time-rung models compile one program per (bucket, window rung):
        # warm the full grid so mixed-length steady-state traffic — short
        # live windows and whole-match replays alike — retraces nowhere
        rungs: Tuple[Optional[int], ...] = (
            window_ladder(A)
            if getattr(model, 'time_rungs', False)
            else (None,)
        )
        with span('serve/warmup', buckets=list(buckets)):
            # every replica warms its own ladder: lanes compile (or
            # preload) independently, so steady-state traffic retraces
            # on NO replica, not just replica 0
            for lane in range(self.n_replicas):
                for b in buckets:
                    for tl in rungs:
                        self._device_rate(
                            _empty_host_batch(1, A), _empty_gs(1, A),
                            model, b, lane=lane, time_len=tl,
                        )
        return buckets

    def close(self, *, drain: bool = True) -> None:
        """Flush (or fail) queued requests and stop the flusher thread.

        The parity probe (when attached) is closed too — its pending
        probes are processed first, so a smoke run's last sampled flush
        is never lost.
        """
        self._batcher.close(drain=drain)
        if self.parity is not None:
            self.parity.close()

    def __enter__(self) -> 'RatingService':
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- introspection -----------------------------------------------------

    @property
    def ladder(self) -> Tuple[int, ...]:
        """The bucket ladder (compiled-shape budget) of this service."""
        return self._batcher.ladder

    @property
    def scenario_ladder(self) -> Tuple[int, ...]:
        """The scenario verb's perturbation bucket ladder.

        ``(1, 2, 4, ..., max_perturbations)`` — every rung a scenario
        request's ``P`` can snap to. Each rung is the same compiled
        program as a rate flush of that many games, so
        ``warmup(scenario_buckets=service.scenario_ladder)`` (or an AOT
        export whose ladder includes these rungs) covers the verb
        end to end.
        """
        return perturbation_ladder(self.max_perturbations)

    @property
    def compiled_shapes(self) -> int:
        """Distinct ``(bucket, max_actions)`` shapes dispatched so far."""
        with self._shape_lock:
            return len(self._seen_shapes)

    @property
    def breaker(self) -> Optional[CircuitBreaker]:
        """The fused-dispatch circuit breaker (None when disabled).

        Replica 0's on a fan-out service — :attr:`breakers` has them all.
        """
        return self._breakers[0]

    @property
    def breakers(self) -> Tuple[Optional[CircuitBreaker], ...]:
        """Every lane's circuit breaker, indexed by replica."""
        return tuple(self._breakers)

    @property
    def nonfinite_events(self) -> int:
        """Nonfinite in-dispatch guard events drained by this service.

        Anything above zero means a NaN reached values served through
        the process's rating path (see the scope caveat on the backing
        counter: the guard ring is process-global) — the learn gate's
        numerics input reads this (fail closed with
        ``GateConfig(max_parity_err=)`` set: traffic served, and
        captured, by a non-finite dispatch is not promotion evidence).
        Overflow (saturating-but-finite logits) is excluded — it counts
        under ``num/overflow_guard_total`` without degrading health.
        """
        with self._dump_lock:
            return self._nonfinite_events


def _pad_to_bucket(
    host_batch: ActionBatch, gs: Optional[np.ndarray], bucket: int
) -> Tuple[ActionBatch, Optional[np.ndarray]]:
    """Pad a staging batch (and its goalscore block) up to the bucket.

    The ONE home of the shape-critical padding rule, shared by the flush
    (which pads early so the cost lands in the 'pad' segment) and
    ``_device_rate`` (whose call no-ops on pre-padded batches but still
    covers warmup's direct 1-game dispatches).
    """
    if host_batch.n_games != bucket:
        host_batch = pad_batch_games(host_batch, bucket)
        if gs is not None:
            gs = np.pad(gs, [(0, bucket - gs.shape[0]), (0, 0), (0, 0)])
    return host_batch, gs


def _slice_window(
    host_batch: ActionBatch, gs: Optional[np.ndarray], time_len: int
) -> Tuple[ActionBatch, Optional[np.ndarray]]:
    """Slice the action axis of a staging batch to its window rung.

    Per-action ``(G, A)`` fields (and the ``(G, A, 3)`` goalscore block)
    drop their masked tail beyond ``time_len``; per-game ``(G,)`` fields
    pass through. Only valid for ``time_len >= n_actions.max()`` — the
    rung choice (:func:`~socceraction_tpu.core.batch.bucket_window`)
    guarantees that, so no valid row is ever cut.
    """
    import jax

    sliced = jax.tree.map(
        lambda a: a[:, :time_len] if getattr(a, 'ndim', 0) >= 2 else a,
        host_batch,
    )
    if gs is not None:
        gs = gs[:, :time_len]
    return sliced, gs


def _pad_values_time(values: np.ndarray, max_actions: int) -> np.ndarray:
    """Zero-pad a ``(G, a, 3)`` values block back to full action capacity.

    The inverse of :func:`_slice_window` on the output side: rows beyond
    the dispatched rung are padding by construction (masked in staging),
    so callers unpack against full-capacity masks without knowing which
    rung served them.
    """
    if values.shape[1] < max_actions:
        values = np.pad(
            values, [(0, 0), (0, max_actions - values.shape[1]), (0, 0)]
        )
    return values


def _empty_host_batch(n_games: int, max_actions: int) -> ActionBatch:
    """An all-padding staging batch (used to warm compile caches)."""
    G, A = n_games, max_actions
    i32 = np.zeros((G, A), dtype=np.int32)
    f32 = np.zeros((G, A), dtype=np.float32)
    return ActionBatch(
        type_id=i32, result_id=i32, bodypart_id=i32, period_id=i32,
        is_home=np.zeros((G, A), dtype=bool),
        time_seconds=f32, start_x=f32, start_y=f32, end_x=f32, end_y=f32,
        mask=np.zeros((G, A), dtype=bool),
        n_actions=np.zeros((G,), dtype=np.int32),
        game_id=np.arange(G, dtype=np.int32),
        row_index=np.full((G, A), -1, dtype=np.int32),
    )


def _empty_gs(n_games: int, max_actions: int) -> np.ndarray:
    return np.zeros((n_games, max_actions, 3), dtype=np.float32)
