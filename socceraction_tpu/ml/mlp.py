"""A JAX MLP binary classifier for on-device probability estimation.

The reference estimates P(score)/P(concede) with host-side gradient-boosted
trees (reference ``socceraction/vaep/base.py:199-282``). Trees stay
supported (see :mod:`socceraction_tpu.ml.learners`), but the TPU-native
default for the fused rating path is this MLP: with it, the entire
``features -> probabilities -> VAEP formula`` pipeline runs as XLA kernels
on device with zero host round-trips, which is what makes the >= 1M
actions/sec rating target reachable.

Training follows the reference's protocol shape: random 75/25 split done
by the caller, early stopping on a validation set with a patience window.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

__all__ = ['MLPClassifier']


class _MLP(nn.Module):
    hidden: Sequence[int]

    @nn.compact
    def __call__(self, x):
        for h in self.hidden:
            x = nn.Dense(h)(x)
            x = nn.relu(x)
        return nn.Dense(1)(x)[..., 0]  # logits


class MLPClassifier:
    """Binary classifier: standardized inputs -> ReLU MLP -> sigmoid.

    Parameters
    ----------
    hidden : sequence of int
        Hidden layer widths.
    learning_rate : float
        Adam learning rate.
    batch_size : int
        Minibatch size for training.
    max_epochs : int
        Maximum number of passes over the training data.
    patience : int
        Early-stopping patience in epochs (requires an eval set).
    pos_weight : float
        Weight multiplier for positive examples in the loss; useful for the
        heavily imbalanced scoring/conceding labels. Default 1.0.
    seed : int
        PRNG seed.
    """

    def __init__(
        self,
        hidden: Sequence[int] = (128, 128),
        learning_rate: float = 1e-3,
        batch_size: int = 8192,
        max_epochs: int = 50,
        patience: int = 5,
        pos_weight: float = 1.0,
        seed: int = 0,
    ) -> None:
        self.hidden = tuple(hidden)
        self.learning_rate = learning_rate
        self.batch_size = batch_size
        self.max_epochs = max_epochs
        self.patience = patience
        self.pos_weight = pos_weight
        self.seed = seed
        self.module = _MLP(self.hidden)
        self.params = None
        self.mean_: Optional[np.ndarray] = None
        self.std_: Optional[np.ndarray] = None

    # -- training ----------------------------------------------------------

    def fit(
        self,
        X: np.ndarray,
        y: np.ndarray,
        eval_set: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ) -> 'MLPClassifier':
        """Train with the reference's split/early-stop protocol.

        Standardizes features, minimizes sigmoid BCE with adam, and -- when
        ``eval_set`` is given -- early-stops on its loss exactly like the
        gradient-boosted learners (reference ``vaep/base.py:199-213``).
        """
        X = np.asarray(X, dtype=np.float32)
        y = np.asarray(y, dtype=np.float32)
        self.mean_ = X.mean(axis=0)
        std = X.std(axis=0)
        self.std_ = np.where(std > 0, std, 1.0).astype(np.float32)

        rng = jax.random.PRNGKey(self.seed)
        rng, init_rng = jax.random.split(rng)
        params = self.module.init(init_rng, jnp.zeros((1, X.shape[1])))
        tx = optax.adam(self.learning_rate)
        opt_state = tx.init(params)

        mean = jnp.asarray(self.mean_)
        std_dev = jnp.asarray(self.std_)
        pos_w = self.pos_weight

        def loss_fn(params, xb, yb):
            logits = self.module.apply(params, (xb - mean) / std_dev)
            losses = optax.sigmoid_binary_cross_entropy(logits, yb)
            weights = jnp.where(yb > 0.5, pos_w, 1.0)
            return jnp.mean(losses * weights)

        @jax.jit
        def train_step(params, opt_state, xb, yb):
            loss, grads = jax.value_and_grad(loss_fn)(params, xb, yb)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        eval_loss = jax.jit(loss_fn)

        n = len(X)
        bs = min(self.batch_size, n)
        # ceil so the tail is trained on; the last batch wraps around the
        # permutation to keep a fixed shape (no per-epoch recompilation)
        steps = (n + bs - 1) // bs
        best_loss = np.inf
        best_params = params
        bad_epochs = 0
        np_rng = np.random.default_rng(self.seed)

        Xd = jnp.asarray(X)
        yd = jnp.asarray(y)
        if eval_set is not None:
            Xv = jnp.asarray(np.asarray(eval_set[0], dtype=np.float32))
            yv = jnp.asarray(np.asarray(eval_set[1], dtype=np.float32))

        for _ in range(self.max_epochs):
            perm = np_rng.permutation(n)
            for s in range(steps):
                sel = jnp.asarray(perm[np.arange(s * bs, (s + 1) * bs) % n])
                xb = jnp.take(Xd, sel, axis=0)
                yb = jnp.take(yd, sel, axis=0)
                params, opt_state, _ = train_step(params, opt_state, xb, yb)
            if eval_set is not None:
                vloss = float(eval_loss(params, Xv, yv))
                if vloss < best_loss - 1e-6:
                    best_loss = vloss
                    best_params = params
                    bad_epochs = 0
                else:
                    bad_epochs += 1
                    if bad_epochs >= self.patience:
                        break
            else:
                best_params = params
        self.params = best_params
        return self

    # -- inference ---------------------------------------------------------

    def predict_proba_device(self, X: jax.Array) -> jax.Array:
        """P(y=1) for a device array of any leading shape ``(..., F)``.

        Stays on device; safe to call inside a jitted pipeline.
        """
        if self.params is None:
            raise ValueError('classifier is not fitted')
        xn = (X - jnp.asarray(self.mean_)) / jnp.asarray(self.std_)
        return jax.nn.sigmoid(self.module.apply(self.params, xn))

    def predict_proba(self, X: Any) -> np.ndarray:
        """sklearn-style ``(n, 2)`` probability matrix on host."""
        X = jnp.asarray(np.asarray(X, dtype=np.float32))
        p1 = np.asarray(self.predict_proba_device(X))
        return np.stack([1.0 - p1, p1], axis=1)

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        """Save the fitted classifier to one ``.npz`` file.

        Stores the flax parameter pytree (msgpack bytes), the input
        standardization statistics and the hyperparameters; no reference
        counterpart (the reference's VAEP classifiers have no save/load
        API at all, SURVEY §5 "Checkpoint / resume").
        """
        import json

        from flax import serialization

        if self.params is None:
            raise ValueError('cannot save an unfitted classifier')
        hyper = {
            'hidden': list(self.hidden),
            'learning_rate': self.learning_rate,
            'batch_size': self.batch_size,
            'max_epochs': self.max_epochs,
            'patience': self.patience,
            'pos_weight': self.pos_weight,
            'seed': self.seed,
        }
        # write through a handle so np.savez honors the exact path instead
        # of appending '.npz'
        with open(path, 'wb') as f:
            np.savez(
                f,
                params_msgpack=np.frombuffer(
                    serialization.to_bytes(self.params), dtype=np.uint8
                ),
                mean=self.mean_,
                std=self.std_,
                hyper_json=np.array(json.dumps(hyper)),
            )

    @classmethod
    def load(cls, path: str) -> 'MLPClassifier':
        """Load a classifier saved with :meth:`save`."""
        import json

        from flax import serialization

        with np.load(path, allow_pickle=False) as data:
            hyper = json.loads(str(data['hyper_json']))
            mean = data['mean']
            std = data['std']
            raw = data['params_msgpack'].tobytes()
        clf = cls(**hyper)
        clf.mean_ = mean.astype(np.float32)
        clf.std_ = std.astype(np.float32)
        template = clf.module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, mean.shape[0]))
        )
        clf.params = serialization.from_bytes(template, raw)
        return clf

    def predict_proba_device_batch(
        self, batch: Any, *, names: Tuple[str, ...], k: int, registry: str = 'standard'
    ) -> jax.Array:
        """P(y=1) per action of a packed batch via the fused first layer.

        Equivalent to ``predict_proba_device(compute_features(batch, ...))``
        but applies one-hot feature blocks as first-layer row gathers
        (:mod:`socceraction_tpu.ops.fused`), never materializing the
        feature tensor. ``names``/``k``/``registry`` must match the layout
        the classifier was trained on ('standard' or 'atomic').
        """
        from ..ops.fused import REGISTRIES, fused_mlp_logits

        if self.params is None:
            raise ValueError('classifier is not fitted')
        logits = fused_mlp_logits(
            self.params,
            batch,
            names=tuple(names),
            k=k,
            hidden_layers=len(self.hidden),
            mean=self.mean_,
            std=self.std_,
            registry=REGISTRIES[registry],
        )
        return jax.nn.sigmoid(logits)
