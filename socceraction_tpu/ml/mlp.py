"""A JAX MLP binary classifier for on-device probability estimation.

The reference estimates P(score)/P(concede) with host-side gradient-boosted
trees (reference ``socceraction/vaep/base.py:199-282``). Trees stay
supported (see :mod:`socceraction_tpu.ml.learners`), but the TPU-native
default for the fused rating path is this MLP: with it, the entire
``features -> probabilities -> VAEP formula`` pipeline runs as XLA kernels
on device with zero host round-trips, which is what makes the >= 1M
actions/sec rating target reachable.

Training follows the reference's protocol shape: random 75/25 split done
by the caller, early stopping on a validation set with a patience window.

Dispatch model (``docs/training.md``): one epoch is ONE jitted XLA
computation — a ``jax.lax.scan`` over minibatches with the shuffle drawn
on device (``jax.random.permutation`` keyed by ``fold_in(seed, epoch)``)
and ``(params, opt_state)`` donated, so an epoch costs one dispatch
instead of one per step (the pre-rework trainer paid ~6.5 ms of dispatch
latency on each of ~100 steps per epoch). Two data paths feed the same
loop:

- **materialized** (:meth:`MLPClassifier.fit`): the caller's ``(n, F)``
  feature matrix lives on device and minibatches are row gathers from it.
- **fused** (:meth:`MLPClassifier.fit_packed`): the batch stays in the
  packed game-state representation (dense sub-tensor + per-state combined
  categorical ids, :mod:`socceraction_tpu.ops.fused`) and the first layer
  is applied by folding the master ``Dense_0`` kernel into combined
  tables every step — the one-hot feature columns (~90% of ``F``) are
  never built, in training or inference.

Minibatch tail: ``steps = ceil(n / batch_size)`` with every batch the
same static shape, so the last batch *wraps around* the permutation.
Wrapped slots carry zero loss weight — each sample contributes exactly
once per epoch — and per-batch losses are normalized by the *real*
(unwrapped, unpadded) sample count, not the slot count.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from ..obs import counter, histogram, span
from ..obs.perf import record_dispatch
from ..obs.xla import instrument_jit

__all__ = ['MLPClassifier', 'MLP_FORMAT_VERSION']

#: Version stamped into :meth:`MLPClassifier.save` artifacts. Bump on any
#: layout change; :meth:`MLPClassifier.load` rejects artifacts from a
#: NEWER version with a clear error instead of failing deep inside
#: ``np.load`` key access (the model registry depends on this contract).
#: Version 2 adds the ``quantize`` serving mode to the hyperparameters;
#: :meth:`MLPClassifier.save` stamps the MINIMUM version able to read
#: the artifact — a ``quantize='none'`` checkpoint still stamps 1, so
#: pre-quantization libraries keep loading everything that does not use
#: the feature, while a quantized checkpoint fails them loudly
#: ("newer than this library understands") instead of crashing on the
#: unknown hyperparameter.
MLP_FORMAT_VERSION = 2


class _MLP(nn.Module):
    hidden: Sequence[int]

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for h in self.hidden:
            x = nn.Dense(h)(x)
            x = nn.relu(x)
        return nn.Dense(1)(x)[..., 0]  # logits


def _weighted_bce(
    logits: jax.Array, y: jax.Array, w: jax.Array, pos_w: jax.Array
) -> jax.Array:
    """Σ bce·w·posw / Σw — wrapped/padded rows (w=0) contribute nothing."""
    losses = optax.sigmoid_binary_cross_entropy(logits, y)
    weights = w * jnp.where(y > 0.5, pos_w, 1.0)
    return jnp.sum(losses * weights) / jnp.maximum(jnp.sum(w), 1.0)


class _EpochTrainer:
    """One-dispatch-per-epoch minibatch trainer.

    ``run(params, opt_state, epoch, data)`` executes a full epoch as a
    single jitted ``lax.scan``: the permutation is drawn on device from
    ``fold_in(PRNGKey(seed), epoch)``, minibatches are row gathers from
    ``data`` (any pytree of ``(n, ...)`` arrays), and ``params``/
    ``opt_state`` buffers are donated. ``n_traces`` counts retraces —
    pinned to 1 across epochs by ``tests/test_fused_train.py`` (fixed
    shapes: the tail batch wraps instead of shrinking).

    Each epoch additionally returns a **health dict** of device scalars
    computed inside the same scan — per-step global grad/update norms
    (finite-masked means over the epoch), the post-epoch weight norm and
    a count of steps whose loss or gradient went non-finite. The scalars
    ride back as device arrays (no sync added to the epoch loop); the
    fit loop materializes them ONCE at the end of training
    (``train/grad_norm`` etc. + the divergence verdict in
    ``train_health_``).
    """

    def __init__(
        self,
        loss_fn: Callable[..., Any],
        tx: Any,
        n: int,
        batch_size: int,
        seed: int,
    ) -> None:
        self.n = n
        self.batch_size = min(batch_size, n)
        # ceil so the tail is trained on; the last batch wraps around the
        # permutation to keep a fixed shape (no per-epoch recompilation)
        # and the wrapped duplicate slots get zero weight (module
        # docstring) so they cannot double-count
        self.steps = (n + self.batch_size - 1) // self.batch_size
        self.n_traces = 0
        base_rng = jax.random.PRNGKey(seed)
        slots = self.steps * self.batch_size
        slot_pos = jnp.arange(slots) % n
        #: (steps, batch_size) loss weights: 0 on the wrapped tail slots,
        #: so each of the n samples counts exactly once per epoch
        self.slot_weight = (
            (jnp.arange(slots) < n)
            .astype(jnp.float32)
            .reshape(self.steps, self.batch_size)
        )
        slot_valid = self.slot_weight

        def epoch_fn(params, opt_state, epoch, data):
            self.n_traces += 1  # trace-time counter: 1 == no recompilation
            rng = jax.random.fold_in(base_rng, epoch)
            perm = jax.random.permutation(rng, n)
            sel = jnp.take(perm, slot_pos).reshape(self.steps, self.batch_size)

            def body(carry, step):
                p, o = carry
                idx, valid = step
                mb = jax.tree.map(lambda a: jnp.take(a, idx, axis=0), data)
                loss, grads = jax.value_and_grad(loss_fn)(p, mb, valid)
                gnorm = optax.global_norm(grads)
                updates, o = tx.update(grads, o)
                unorm = optax.global_norm(updates)
                return (
                    (optax.apply_updates(p, updates), o),
                    (loss, gnorm, unorm),
                )

            (params, opt_state), (losses, gnorms, unorms) = jax.lax.scan(
                body, (params, opt_state), (sel, slot_valid)
            )
            # training-health scalars, computed in the SAME dispatch: a
            # step is unhealthy when its loss or its gradient norm went
            # non-finite; the norm telemetry averages the finite steps so
            # one blown-up step cannot make the whole epoch's norms NaN
            step_ok = jnp.isfinite(losses) & jnp.isfinite(gnorms)

            def finite_mean(x):
                ok = jnp.isfinite(x)
                return jnp.sum(jnp.where(ok, x, 0.0)) / jnp.maximum(
                    jnp.sum(ok), 1
                )

            health = {
                'nonfinite_steps': jnp.sum(~step_ok).astype(jnp.int32),
                'grad_norm': finite_mean(gnorms),
                'update_norm': finite_mean(unorms),
                'weight_norm': optax.global_norm(params),
            }
            return params, opt_state, jnp.mean(losses), health

        # cost=False: epoch_fn has a trace-time side effect (the
        # n_traces counter above) — the observatory's AOT cost lowering
        # would run the trace a second time and inflate it
        self._epoch = instrument_jit(
            epoch_fn, 'train_epoch', cost=False, donate_argnums=(0, 1)
        )

    def run(self, params: Any, opt_state: Any, epoch: int, data: Any) -> Any:
        return self._epoch(params, opt_state, np.int32(epoch), data)


class MLPClassifier:
    """Binary classifier: standardized inputs -> ReLU MLP -> sigmoid.

    Parameters
    ----------
    hidden : sequence of int
        Hidden layer widths.
    learning_rate : float
        Adam learning rate.
    batch_size : int
        Minibatch size for training.
    max_epochs : int
        Maximum number of passes over the training data.
    patience : int
        Early-stopping patience in epochs (requires an eval set).
    pos_weight : float
        Weight multiplier for positive examples in the loss; useful for the
        heavily imbalanced scoring/conceding labels. Default 1.0.
    seed : int
        PRNG seed (parameter init and the on-device epoch shuffles).
    train_dtype : str, optional
        Narrow dtype (e.g. ``'bfloat16'``) for the training matmuls:
        minibatch feature/hidden matmuls run in this dtype while the
        master weights, the optimizer state and the loss stay f32 (the
        logit head accumulates back in f32 —
        :func:`socceraction_tpu.ops.fused._hidden_chain`). Opt-in;
        ``None`` (default) trains fully in f32.
    quantize : {'none', 'bf16', 'int8'}
        Storage format of the fused serving fold's combined tables
        (:mod:`socceraction_tpu.ops.quant`). ``'none'`` (default) serves
        the bit-pinned f32 path. Narrow modes quantize the prepared
        tables at fold-build time and dequantize inside the dispatch
        (f32 accumulation); when set *before* :meth:`fit_packed`, the
        fused training path also trains quantization-aware
        (straight-through fake-quant of the per-step tables). Master
        weights, checkpointed parameters and the materialized reference
        path stay f32 regardless — quantization is a serving-storage
        decision, metered in production by the serve layer's
        ``ParityProbe``.
    """

    def __init__(
        self,
        hidden: Sequence[int] = (128, 128),
        learning_rate: float = 1e-3,
        batch_size: int = 8192,
        max_epochs: int = 50,
        patience: int = 5,
        pos_weight: float = 1.0,
        seed: int = 0,
        train_dtype: Optional[str] = None,
        quantize: str = 'none',
    ) -> None:
        from ..ops.quant import check_quantize_mode

        self.hidden = tuple(hidden)
        self.learning_rate = learning_rate
        self.batch_size = batch_size
        self.max_epochs = max_epochs
        self.patience = patience
        self.pos_weight = pos_weight
        self.seed = seed
        self.train_dtype = train_dtype
        self.quantize = check_quantize_mode(quantize)
        self.module = _MLP(self.hidden)
        self.params = None
        self._mean: Optional[np.ndarray] = None
        self._std: Optional[np.ndarray] = None
        self._mean_dev = None
        self._std_dev = None
        #: epoch-function retrace count of the last fit (1 == the epoch
        #: compiled once and was reused across every epoch)
        self.n_epoch_traces_: int = 0
        #: adam state matching :attr:`params` — the best-eval epoch's
        #: snapshot under early stopping, else the last trained epoch's.
        #: Hand it to the next incremental
        #: ``fit_packed(init_opt_state=...)`` so a warm-started
        #: continuation keeps its second-moment scale instead of
        #: re-estimating it from zero. In-process only: the
        #: ``save``/``load`` checkpoint deliberately stores parameters,
        #: not optimizer state.
        self.opt_state_: Any = None
        #: training-health verdict of the last fit (None before any):
        #: ``{'finite': bool, 'epochs': int, 'nonfinite_steps': int,
        #: 'grad_norm_last': float, 'update_norm_last': float,
        #: 'weight_norm_last': float}`` — computed inside the epoch
        #: dispatches and materialized once at the end of training. The
        #: continuous-learning loop rejects a candidate whose heads
        #: report ``finite=False`` (a diverging incremental retrain must
        #: never reach the shadow gate as a healthy candidate).
        self.train_health_: Optional[Dict[str, Any]] = None

    # -- standardization statistics ----------------------------------------
    # mean_/std_ are properties so the device copies predict_proba_device
    # uses can be cached: re-uploading jnp.asarray(self.mean_) on every
    # call cost a host->device transfer per prediction. Assigning either
    # statistic invalidates its cached device constant.

    @property
    def mean_(self) -> Optional[np.ndarray]:
        """Per-feature standardization mean (host f32 array, or None)."""
        return self._mean

    @mean_.setter
    def mean_(self, value: Any) -> None:
        """Set the mean and drop its cached device constant."""
        self._mean = (
            None if value is None else np.asarray(value, dtype=np.float32)
        )
        self._mean_dev = None

    @property
    def std_(self) -> Optional[np.ndarray]:
        """Per-feature standardization scale (host f32 array, or None)."""
        return self._std

    @std_.setter
    def std_(self, value: Any) -> None:
        """Set the scale and drop its cached device constant."""
        self._std = (
            None if value is None else np.asarray(value, dtype=np.float32)
        )
        self._std_dev = None

    def _device_stats(self) -> Tuple[jax.Array, jax.Array]:
        """Cached device copies of ``(mean_, std_)``."""
        if self._mean_dev is None:
            self._mean_dev = jnp.asarray(self._mean)
        if self._std_dev is None:
            self._std_dev = jnp.asarray(self._std)
        return self._mean_dev, self._std_dev

    def _compute_dtype(self) -> Optional[Any]:
        return jnp.dtype(self.train_dtype) if self.train_dtype else None

    # -- training ----------------------------------------------------------

    def _init_params(self, n_features: int) -> Any:
        # distinct stream from the epoch shuffle keys (fold_in(seed, epoch)
        # for epoch in 0..max_epochs): a shared key would correlate the
        # init bits with epoch-1's minibatch permutation
        rng = jax.random.fold_in(jax.random.PRNGKey(self.seed), 2**31 - 1)
        return self.module.init(rng, jnp.zeros((1, n_features)))

    def _check_init_params(self, init_params: Any, n_features: int) -> Any:
        """Validate + deep-copy a warm-start parameter pytree.

        The structure and every leaf shape must match a fresh init of
        this classifier's architecture at ``n_features`` — a silent
        shape broadcast here would train a corrupted head. The template
        is abstract (``jax.eval_shape``): validation allocates nothing
        and runs no PRNG dispatch, which matters on the incremental
        path that warm-starts every head every loop iteration. The copy
        is mandatory: the epoch dispatch donates its parameter buffers,
        and donating the caller's live (possibly actively serving)
        pytree would invalidate it.
        """
        template = jax.eval_shape(lambda: self._init_params(n_features))
        t_struct = jax.tree.structure(template)
        i_struct = jax.tree.structure(init_params)
        if t_struct != i_struct:
            raise ValueError(
                f'init_params tree structure {i_struct} does not match '
                f'this classifier (hidden={self.hidden}): {t_struct}'
            )
        t_shapes = [jnp.shape(l) for l in jax.tree.leaves(template)]
        i_shapes = [jnp.shape(l) for l in jax.tree.leaves(init_params)]
        if t_shapes != i_shapes:
            raise ValueError(
                f'init_params leaf shapes {i_shapes} do not match the '
                f'feature layout / architecture ({t_shapes}); warm starts '
                'require an unchanged layout'
            )
        return jax.tree.map(lambda a: jnp.array(a, jnp.float32), init_params)

    def _dense_logits(
        self, params: Any, x: jax.Array, mean: jax.Array, std: jax.Array
    ) -> jax.Array:
        """``module.apply`` on standardized rows, optionally narrowed.

        The narrowed form follows the same policy as the fused path: the
        first-layer matmul takes ``train_dtype`` inputs with f32
        accumulation and the logit head stays f32
        (:func:`socceraction_tpu.ops.fused._hidden_chain`), so bf16
        deltas measure the dtype, never the path.
        """
        xn = (x - mean) / std
        dt = self._compute_dtype()
        if dt is None:
            return self.module.apply(params, xn)
        from ..ops.fused import _hidden_chain

        leaves = params['params']
        d0 = leaves['Dense_0']
        h = (
            jnp.dot(
                xn.astype(dt),
                jnp.asarray(d0['kernel']).astype(dt),
                preferred_element_type=jnp.float32,
            )
            + jnp.asarray(d0['bias'])
        )
        return _hidden_chain(leaves, h, len(self.hidden), dt)

    def _fit_loop(
        self,
        params: Any,
        data: Any,
        n: int,
        loss_fn: Callable[..., Any],
        eval_data: Any = None,
        *,
        path: str,
        n_samples: Optional[int] = None,
        init_opt_state: Any = None,
    ) -> Any:
        """Shared epoch loop: scan-train, eval, early-stop, telemetry.

        ``loss_fn(params, minibatch, slot_weights)`` is the per-batch
        objective; evaluation reuses it with all-ones slot weights.
        Records ``train/*`` metrics per ``(path, platform)`` — one
        ``train/epochs`` increment per epoch IS the XLA dispatch count of
        the training work (the per-epoch eval is a second, tiny one).
        ``init_opt_state`` warm-starts adam (incremental fits); it is
        deep-copied because the epoch dispatch donates its buffers.
        """
        tx = optax.adam(self.learning_rate)
        if init_opt_state is None:
            opt_state = tx.init(params)
        else:
            opt_state = jax.tree.map(jnp.array, init_opt_state)
        trainer = _EpochTrainer(loss_fn, tx, n, self.batch_size, self.seed)
        eval_fn = None
        if eval_data is not None:
            n_eval = len(jax.tree.leaves(eval_data)[0])
            ones = jnp.ones((n_eval,), jnp.float32)
            eval_fn = jax.jit(lambda p, d: loss_fn(p, d, ones))

        labels = {'path': path, 'platform': jax.default_backend()}
        best_params = None
        best_opt_state = None
        best_loss = np.inf
        bad_epochs = 0
        samples = n_samples if n_samples is not None else n
        epoch_health: list = []
        with span('train/fit', **labels):
            for epoch in range(self.max_epochs):
                t0 = time.perf_counter()
                params, opt_state, _, health = trainer.run(
                    params, opt_state, epoch, data
                )
                # device scalars only — materialized AFTER the loop, so
                # the health telemetry adds no per-epoch sync
                epoch_health.append(health)
                # dispatch wall, not device wall: the epoch is async like
                # every hot path; bench.py owns synced throughput numbers
                epoch_wall = time.perf_counter() - t0
                histogram('train/epoch_seconds', unit='s').observe(
                    epoch_wall, **labels
                )
                # live-roofline feed: inter-epoch gaps drive the
                # trainer's perf/device_idle_frac and the dispatch-wall
                # histogram. train_epoch is instrumented cost=False (no
                # AOT analysis per fit instance), so record_dispatch
                # finds no flops/bytes here and the achieved-rate
                # gauges stay absent for this loop — the idle fraction
                # is the trainer's capacity signal
                record_dispatch('train_epoch', epoch_wall)
                counter('train/epochs', unit='count').inc(1, **labels)
                counter('train/steps', unit='count').inc(
                    trainer.steps, **labels
                )
                counter('train/samples', unit='count').inc(samples, **labels)
                if eval_fn is not None:
                    vloss = float(eval_fn(params, eval_data))
                    if vloss < best_loss - 1e-6:
                        best_loss = vloss
                        # deep copy: the live params buffers are donated
                        # to the next epoch's dispatch. The optimizer
                        # state is snapshotted WITH the parameters — a
                        # warm start must continue adam from the epoch
                        # the restored parameters came from, not from
                        # wherever patience ran out
                        best_params = jax.tree.map(jnp.copy, params)
                        best_opt_state = jax.tree.map(jnp.copy, opt_state)
                        bad_epochs = 0
                    else:
                        bad_epochs += 1
                        if bad_epochs >= self.patience:
                            break
        self.n_epoch_traces_ = trainer.n_traces
        self.params = best_params if best_params is not None else params
        self.opt_state_ = (
            best_opt_state if best_params is not None else opt_state
        )
        self._record_train_health(epoch_health, labels, path)
        return self

    def _record_train_health(
        self, epoch_health: Any, labels: Dict[str, str], path: str
    ) -> None:
        """Materialize the per-epoch health scalars; record + verdict.

        One host conversion at the END of the fit (the epochs were
        dispatched asynchronously; anything consuming the trained
        parameters waits for the same stream anyway). Lands per-epoch
        ``train/grad_norm`` / ``train/update_norm`` / ``train/weight_norm``
        histograms, counts nonfinite steps into ``train/nonfinite_loss``
        AND the cross-cutting ``num/nonfinite_total{fn=train_epoch}``
        guard counter, and stores the :attr:`train_health_` verdict.
        """
        from ..obs.numerics import record_nonfinite

        nonfinite_steps = 0
        last = {'grad_norm': None, 'update_norm': None, 'weight_norm': None}
        for h in epoch_health:
            gn = float(h['grad_norm'])
            un = float(h['update_norm'])
            wn = float(h['weight_norm'])
            histogram('train/grad_norm', unit='value').observe(gn, **labels)
            histogram('train/update_norm', unit='value').observe(un, **labels)
            histogram('train/weight_norm', unit='value').observe(wn, **labels)
            nonfinite_steps += int(h['nonfinite_steps'])
            last = {'grad_norm': gn, 'update_norm': un, 'weight_norm': wn}
        if nonfinite_steps:
            counter('train/nonfinite_loss', unit='count').inc(
                nonfinite_steps, **labels
            )
            record_nonfinite('train_epoch', 'loss', nonfinite_steps)
        finite = nonfinite_steps == 0 and all(
            v is None or np.isfinite(v) for v in last.values()
        )
        self.train_health_ = {
            'finite': bool(finite),
            'path': path,
            'epochs': len(epoch_health),
            'nonfinite_steps': nonfinite_steps,
            'grad_norm_last': last['grad_norm'],
            'update_norm_last': last['update_norm'],
            'weight_norm_last': last['weight_norm'],
        }

    def fit(
        self,
        X: np.ndarray,
        y: np.ndarray,
        eval_set: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ) -> 'MLPClassifier':
        """Train with the reference's split/early-stop protocol.

        Standardizes features, minimizes sigmoid BCE with adam, and -- when
        ``eval_set`` is given -- early-stops on its loss exactly like the
        gradient-boosted learners (reference ``vaep/base.py:199-213``).
        Each epoch is one jitted scan dispatch (module docstring); this
        path keeps the materialized ``(n, F)`` matrix on device — use
        :meth:`fit_packed` to train from packed game states without it.
        """
        X = np.asarray(X, dtype=np.float32)
        y = np.asarray(y, dtype=np.float32)
        self.mean_ = X.mean(axis=0)
        std = X.std(axis=0)
        self.std_ = np.where(std > 0, std, 1.0).astype(np.float32)
        mean, std_dev = self._device_stats()

        params = self._init_params(X.shape[1])
        pos_w = self.pos_weight

        def loss_fn(params, mb, w):
            logits = self._dense_logits(params, mb['x'], mean, std_dev)
            return _weighted_bce(logits, mb['y'], w, pos_w)

        data = {'x': jnp.asarray(X), 'y': jnp.asarray(y)}
        eval_data = None
        if eval_set is not None:
            eval_data = {
                'x': jnp.asarray(np.asarray(eval_set[0], dtype=np.float32)),
                'y': jnp.asarray(np.asarray(eval_set[1], dtype=np.float32)),
            }
        return self._fit_loop(
            params, data, len(X), loss_fn, eval_data, path='materialized'
        )

    def fit_packed(
        self,
        batch: Any,
        y: Any,
        *,
        names: Tuple[str, ...],
        k: int,
        registry: str = 'standard',
        eval_set: Optional[Tuple[Any, Any]] = None,
        mean: Optional[Any] = None,
        std: Optional[Any] = None,
        path: str = 'fused',
        init_params: Any = None,
        init_opt_state: Any = None,
    ) -> 'MLPClassifier':
        """Train directly on packed game states — no feature matrix in HBM.

        Parameters
        ----------
        batch
            A packed :class:`~socceraction_tpu.core.batch.ActionBatch` (or
            the precomputed ``(TrainStates, TrainLayout)`` pair from
            :func:`socceraction_tpu.ops.fused.build_train_states`, so
            several heads can share one pack).
        y
            Labels, shape ``(G, A)`` or flat ``(G*A,)``; padding rows are
            ignored via the states' zero weights.
        names, k, registry
            Feature layout, as in :meth:`predict_proba_device_batch`.
        eval_set
            Optional ``(batch_like, y)`` validation pair for the
            reference early-stop protocol.
        mean, std
            Optional precomputed standardization statistics over the full
            feature columns. Default: computed from the packed form
            (:func:`socceraction_tpu.ops.fused.packed_feature_stats`) —
            one-hot column moments are exact functions of activation
            frequencies, so the matrix is not needed for them either.
        path
            ``'fused'`` (default) trains through the combined-table fold;
            ``'materialized'`` builds the feature tensor and gathers rows
            from it — the same minibatch stream and loss, kept as the
            parity/bench baseline (requires ``batch`` to be an
            ``ActionBatch``).
        init_params, init_opt_state
            Warm start: initialize from an already-trained parameter
            pytree (and optionally its adam state, e.g. a previous fit's
            :attr:`opt_state_`) instead of a fresh random init — the
            incremental-training entry the continuous-learning loop
            (:mod:`socceraction_tpu.learn`) drives. Both are deep-copied
            before the first epoch (dispatches donate their buffers), so
            the caller's live model is never invalidated; with
            ``max_epochs=0`` the fit is a bitwise no-op on the provided
            parameters. ``init_params`` must match the feature layout and
            ``hidden`` architecture of this classifier.
        """
        params, data, loss_fn, make_data, states, layout = self._packed_problem(
            batch, y, names=tuple(names), k=k, registry=registry,
            mean=mean, std=std, path=path, init_params=init_params,
        )
        eval_data = None
        if eval_set is not None:
            ev_states, ev_layout, ev_batch = self._resolve_states(
                eval_set[0], names=tuple(names), k=k, registry=registry
            )
            if ev_layout.n_features != layout.n_features:
                raise ValueError('eval_set feature layout differs from train')
            ev_y = jnp.asarray(eval_set[1], dtype=jnp.float32).reshape(-1)
            eval_data = make_data(ev_states, ev_y, ev_batch)

        n = int(states.weight.shape[0])
        n_valid = int(np.asarray(jnp.sum(states.weight)))
        return self._fit_loop(
            params, data, n, loss_fn, eval_data, path=path,
            n_samples=n_valid, init_opt_state=init_opt_state,
        )

    def _packed_problem(
        self,
        batch: Any,
        y: Any,
        *,
        names: Tuple[str, ...],
        k: int,
        registry: str = 'standard',
        mean: Optional[Any] = None,
        std: Optional[Any] = None,
        path: str = 'fused',
        init_params: Any = None,
    ) -> Tuple[Any, Any, Any, Any, Any, Any]:
        """Build the packed training problem (also used by ``bench.py``).

        Returns ``(params, data, loss_fn, make_data, states, layout)``:
        everything :class:`_EpochTrainer` needs, so the bench can time
        epoch dispatches directly without going through the early-stop
        loop.
        """
        from ..ops.fused import (
            REGISTRIES,
            fused_train_logits,
            packed_feature_stats,
        )

        if path not in ('fused', 'materialized'):
            raise ValueError(f'unknown training path {path!r}')
        if registry not in REGISTRIES:
            raise ValueError(f'unknown fused registry {registry!r}')

        states, layout, raw_batch = self._resolve_states(
            batch, names=tuple(names), k=k, registry=registry
        )
        yd = jnp.asarray(y, dtype=jnp.float32).reshape(-1)
        if yd.shape[0] != states.weight.shape[0]:
            raise ValueError(
                f'labels have {yd.shape[0]} rows, packed states have '
                f'{states.weight.shape[0]}'
            )

        if mean is None or std is None:
            mean, raw_std = packed_feature_stats(states, layout)
            std = jnp.where(raw_std > 0, raw_std, 1.0)
        self.mean_ = np.asarray(mean)
        self.std_ = np.asarray(std)
        # the stats are (often) already device arrays: seed the caches
        # directly instead of re-uploading the host copies the property
        # setters just made
        self._mean_dev = jnp.asarray(mean)
        self._std_dev = jnp.asarray(std)
        mean_dev, std_dev = self._device_stats()

        if init_params is None:
            params = self._init_params(layout.n_features)
        else:
            params = self._check_init_params(init_params, layout.n_features)
        pos_w = self.pos_weight
        hidden_layers = len(self.hidden)
        compute_dtype = self._compute_dtype()
        quantize = self.quantize

        if path == 'fused':

            def loss_fn(params, mb, w):
                logits = fused_train_logits(
                    params,
                    mb['x'],
                    mb['ids'],
                    layout=layout,
                    hidden_layers=hidden_layers,
                    mean=mean_dev,
                    std=std_dev,
                    compute_dtype=compute_dtype,
                    quantize=quantize,
                )
                return _weighted_bce(logits, mb['y'], w * mb['w'], pos_w)

            def make_data(states, yd, batch):
                return {
                    'x': states.x_dense,
                    'ids': states.combo_ids,
                    'w': states.weight,
                    'y': yd,
                }

        else:

            def loss_fn(params, mb, w):
                logits = self._dense_logits(params, mb['x'], mean_dev, std_dev)
                return _weighted_bce(logits, mb['y'], w * mb['w'], pos_w)

            def make_data(states, yd, batch):
                if batch is None:
                    raise ValueError(
                        "path='materialized' needs ActionBatch inputs "
                        '(precomputed TrainStates cannot rebuild the '
                        'feature tensor)'
                    )
                feats = self._materialize_features(batch, layout)
                return {
                    'x': feats.reshape(-1, layout.n_features),
                    'w': states.weight,
                    'y': yd,
                }

        data = make_data(states, yd, raw_batch)
        return params, data, loss_fn, make_data, states, layout

    @staticmethod
    def _resolve_states(
        batch: Any, *, names: Tuple[str, ...], k: int, registry: str
    ) -> Tuple[Any, Any, Any]:
        """``batch`` -> (TrainStates, TrainLayout, ActionBatch | None)."""
        from ..ops.fused import TrainStates, build_train_states

        if (
            isinstance(batch, tuple)
            and len(batch) == 2
            and isinstance(batch[0], TrainStates)
        ):
            return batch[0], batch[1], None
        states, layout = build_train_states(
            batch, names=names, k=k, registry_name=registry
        )
        return states, layout, batch

    @staticmethod
    def _materialize_features(batch: Any, layout: Any) -> jax.Array:
        if layout.registry_name == 'atomic':
            from ..ops.atomic import compute_features
        else:
            from ..ops.features import compute_features
        return compute_features(batch, names=layout.names, k=layout.k)

    # -- inference ---------------------------------------------------------

    def predict_proba_device(self, X: jax.Array) -> jax.Array:
        """P(y=1) for a device array of any leading shape ``(..., F)``.

        Stays on device; safe to call inside a jitted pipeline. The
        standardization constants are cached device arrays (not
        re-uploaded per call).
        """
        if self.params is None:
            raise ValueError('classifier is not fitted')
        mean, std = self._device_stats()
        xn = (X - mean) / std
        return jax.nn.sigmoid(self.module.apply(self.params, xn))

    def predict_proba(self, X: Any) -> np.ndarray:
        """sklearn-style ``(n, 2)`` probability matrix on host."""
        X = jnp.asarray(np.asarray(X, dtype=np.float32))
        p1 = np.asarray(self.predict_proba_device(X))
        return np.stack([1.0 - p1, p1], axis=1)

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        """Save the fitted classifier to one ``.npz`` file.

        Stores the flax parameter pytree (msgpack bytes), the input
        standardization statistics and the hyperparameters; no reference
        counterpart (the reference's VAEP classifiers have no save/load
        API at all, SURVEY §5 "Checkpoint / resume").
        """
        import json

        from flax import serialization

        if self.params is None:
            raise ValueError('cannot save an unfitted classifier')
        hyper: Dict[str, Any] = {
            'hidden': list(self.hidden),
            'learning_rate': self.learning_rate,
            'batch_size': self.batch_size,
            'max_epochs': self.max_epochs,
            'patience': self.patience,
            'pos_weight': self.pos_weight,
            'seed': self.seed,
        }
        if self.train_dtype is not None:
            hyper['train_dtype'] = self.train_dtype
        if self.quantize != 'none':
            hyper['quantize'] = self.quantize
        # the stamp is the MINIMUM reader version: a checkpoint that uses
        # no post-v1 feature stamps 1 so pre-quantization libraries keep
        # loading it; a quantized one stamps the LITERAL version that
        # introduced the feature (2 — not MLP_FORMAT_VERSION, which
        # future features will bump past it), failing older loaders with
        # the actionable "newer than this library" error instead of a
        # TypeError on the unknown hyperparameter
        format_version = 2 if self.quantize != 'none' else 1
        # write through a handle so np.savez honors the exact path instead
        # of appending '.npz'
        with open(path, 'wb') as f:
            np.savez(
                f,
                format_version=np.array(format_version),
                params_msgpack=np.frombuffer(
                    serialization.to_bytes(self.params), dtype=np.uint8
                ),
                mean=self.mean_,
                std=self.std_,
                hyper_json=np.array(json.dumps(hyper)),
            )

    @classmethod
    def load(cls, path: str) -> 'MLPClassifier':
        """Load a classifier saved with :meth:`save`.

        A damaged artifact — truncated write, bit rot, a file that is
        not an npz at all — raises a ``ValueError`` naming the artifact
        (zipfile/parse internals make terrible operator errors). The
        registry path additionally verifies content checksums *before*
        this runs (``save_model`` records sha256 per head); this guard
        covers direct ``MLPClassifier.load`` callers.
        """
        import json
        import zipfile

        from flax import serialization

        try:
            with np.load(path, allow_pickle=False) as data:
                # pre-versioning artifacts (format 1 without the stamp)
                # load; anything stamped NEWER than this library is
                # rejected up front with an actionable error
                version = (
                    int(data['format_version'])
                    if 'format_version' in data
                    else 1
                )
                if version > MLP_FORMAT_VERSION:
                    raise ValueError(
                        f'checkpoint at {path!r} has '
                        f'format_version={version}, newer than this '
                        f'library understands (<= {MLP_FORMAT_VERSION}); '
                        'upgrade socceraction_tpu to load it'
                    )
                hyper = json.loads(str(data['hyper_json']))
                mean = data['mean']
                std = data['std']
                raw = data['params_msgpack'].tobytes()
        except (
            zipfile.BadZipFile,
            EOFError,
            KeyError,
            json.JSONDecodeError,
        ) as e:
            raise ValueError(
                f'checkpoint artifact corrupt: {path!r} failed to parse '
                f'as an MLP checkpoint ({type(e).__name__}: {e}); the '
                'file is truncated, damaged or not a save() artifact'
            ) from e
        clf = cls(**hyper)
        clf.mean_ = mean.astype(np.float32)
        clf.std_ = std.astype(np.float32)
        template = clf.module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, mean.shape[0]))
        )
        clf.params = serialization.from_bytes(template, raw)
        return clf

    def predict_proba_device_batch(
        self, batch: Any, *, names: Tuple[str, ...], k: int, registry: str = 'standard'
    ) -> jax.Array:
        """P(y=1) per action of a packed batch via the fused first layer.

        Equivalent to ``predict_proba_device(compute_features(batch, ...))``
        but applies one-hot feature blocks as first-layer row gathers
        (:mod:`socceraction_tpu.ops.fused`), never materializing the
        feature tensor. ``names``/``k``/``registry`` must match the layout
        the classifier was trained on ('standard' or 'atomic').
        """
        from ..ops.fused import REGISTRIES, fused_mlp_logits

        if self.params is None:
            raise ValueError('classifier is not fitted')
        logits = fused_mlp_logits(
            self.params,
            batch,
            names=tuple(names),
            k=k,
            hidden_layers=len(self.hidden),
            mean=self.mean_,
            std=self.std_,
            registry=REGISTRIES[registry],
        )
        return jax.nn.sigmoid(logits)
