"""Gradient-boosted-tree learners for the VAEP probability models.

The reference supports xgboost / catboost / lightgbm, each instantiated
with the same default shape (100 estimators, depth 3, AUC early stopping;
reference ``socceraction/vaep/base.py:215-282``). All three remain
supported when importable; this environment additionally gets an
always-available scikit-learn fallback so the framework works with zero
optional dependencies.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

try:
    import xgboost
except ImportError:  # pragma: no cover
    xgboost = None
try:
    import catboost
except ImportError:  # pragma: no cover
    catboost = None
try:
    import lightgbm
except ImportError:  # pragma: no cover
    lightgbm = None

from sklearn.ensemble import HistGradientBoostingClassifier

from .mlp import MLPClassifier

EvalSet = Optional[List[Tuple[Any, Any]]]


def fit_xgboost(
    X: Any,
    y: Any,
    eval_set: EvalSet = None,
    tree_params: Optional[Dict[str, Any]] = None,
    fit_params: Optional[Dict[str, Any]] = None,
) -> Any:
    """xgboost with the reference's defaults (base.py:215-235).

    Written against the xgboost >= 2.0 API, where ``eval_metric`` and
    ``early_stopping_rounds`` are constructor parameters rather than
    ``fit()`` kwargs.
    """
    if xgboost is None:
        raise ImportError('xgboost is not installed')
    if tree_params is None:
        tree_params = dict(n_estimators=100, max_depth=3, eval_metric='auc')
    else:
        tree_params = dict(tree_params)
    if eval_set is not None:
        tree_params.setdefault('early_stopping_rounds', 10)
    if fit_params is None:
        fit_params = dict(verbose=False)
    if eval_set is not None:
        fit_params = {**fit_params, 'eval_set': eval_set}
    model = xgboost.XGBClassifier(**tree_params)
    return model.fit(X, y, **fit_params)


def fit_catboost(
    X: Any,
    y: Any,
    eval_set: EvalSet = None,
    tree_params: Optional[Dict[str, Any]] = None,
    fit_params: Optional[Dict[str, Any]] = None,
) -> Any:
    """catboost with the reference's defaults (base.py:237-261)."""
    if catboost is None:
        raise ImportError('catboost is not installed')
    if tree_params is None:
        tree_params = dict(eval_metric='BrierScore', loss_function='Logloss', iterations=100)
    if fit_params is None:
        is_cat = [str(X[c].dtype) == 'category' for c in X.columns]
        fit_params = dict(cat_features=np.nonzero(is_cat)[0].tolist(), verbose=False)
    if eval_set is not None:
        fit_params = {**fit_params, 'early_stopping_rounds': 10, 'eval_set': eval_set}
    model = catboost.CatBoostClassifier(**tree_params)
    return model.fit(X, y, **fit_params)


def fit_lightgbm(
    X: Any,
    y: Any,
    eval_set: EvalSet = None,
    tree_params: Optional[Dict[str, Any]] = None,
    fit_params: Optional[Dict[str, Any]] = None,
) -> Any:
    """lightgbm with the reference's defaults (base.py:263-282)."""
    if lightgbm is None:
        raise ImportError('lightgbm is not installed')
    if tree_params is None:
        tree_params = dict(n_estimators=100, max_depth=3)
    if fit_params is None:
        fit_params = dict(eval_metric='auc')
    if eval_set is not None:
        # lightgbm >= 4 dropped early_stopping_rounds from fit(); the
        # callback keeps the reference's early-stopping-on-eval-set behavior
        callbacks = list(fit_params.get('callbacks', []))
        callbacks.append(lightgbm.early_stopping(10, verbose=False))
        fit_params = {**fit_params, 'eval_set': eval_set, 'callbacks': callbacks}
    model = lightgbm.LGBMClassifier(**tree_params)
    return model.fit(X, y, **fit_params)


def fit_sklearn(
    X: Any,
    y: Any,
    eval_set: EvalSet = None,
    tree_params: Optional[Dict[str, Any]] = None,
    fit_params: Optional[Dict[str, Any]] = None,
) -> Any:
    """Histogram gradient boosting from scikit-learn (always available).

    Mirrors the reference's learner shape: 100 boosting iterations of
    depth-3 trees with early stopping when a validation fraction is used.
    Deterministic by default: this learner is this repo's own addition
    (no reference behavior to preserve), and HistGB's internal randomness
    (early-stopping split, binning subsample) would otherwise draw from
    the global numpy RNG — pass ``random_state=None`` in ``tree_params``
    to opt back into that.
    """
    if tree_params is None:
        tree_params = dict(max_iter=100, max_depth=3, early_stopping=eval_set is not None)
    tree_params = {'random_state': 0, **tree_params}
    model = HistGradientBoostingClassifier(**tree_params)
    return model.fit(X, y, **(fit_params or {}))


def fit_mlp(
    X: Any,
    y: Any,
    eval_set: EvalSet = None,
    tree_params: Optional[Dict[str, Any]] = None,
    fit_params: Optional[Dict[str, Any]] = None,
) -> Any:
    """The on-device JAX MLP (see :class:`socceraction_tpu.ml.mlp.MLPClassifier`)."""
    model = MLPClassifier(**(tree_params or {}))
    es = eval_set[0] if eval_set else None
    return model.fit(np.asarray(X), np.asarray(y), eval_set=es)


def fit_mlp_packed(
    batch: Any,
    y: Any,
    eval_set: EvalSet = None,
    tree_params: Optional[Dict[str, Any]] = None,
    fit_params: Optional[Dict[str, Any]] = None,
    *,
    names: Any,
    k: int,
    registry: str = 'standard',
    mean: Any = None,
    std: Any = None,
) -> Any:
    """The MLP trained directly on packed game states — no feature matrix.

    ``batch`` is a packed ``ActionBatch`` or a precomputed
    ``(TrainStates, TrainLayout)`` pair; ``y`` the flat/``(G, A)`` labels
    (:meth:`socceraction_tpu.ml.mlp.MLPClassifier.fit_packed`). The tree
    learners have no packed path — they need the materialized matrix —
    which is why only ``'mlp'`` appears in :data:`PACKED_LEARNERS`.
    """
    model = MLPClassifier(**(tree_params or {}))
    es = eval_set[0] if eval_set else None
    return model.fit_packed(
        batch, y, names=tuple(names), k=k, registry=registry,
        eval_set=es, mean=mean, std=std, **(fit_params or {}),
    )


def fit_seq_packed(
    batch: Any,
    y: Any,
    eval_set: EvalSet = None,
    tree_params: Optional[Dict[str, Any]] = None,
    fit_params: Optional[Dict[str, Any]] = None,
    *,
    names: Any,
    k: int,
    registry: str = 'standard',
    mean: Any = None,
    std: Any = None,
) -> Any:
    """The GRU sequence head trained on packed game states (ISSUE 19).

    Same calling convention as :func:`fit_mlp_packed` — the packed
    learners are interchangeable behind ``VAEP.fit_packed(learner=...)``
    — but the head is a
    :class:`~socceraction_tpu.seq.classifier.SeqClassifier`: an ordered
    model of the k-action window that can credit defensive / off-ball
    value the per-state MLP cannot (arXiv 2106.01786).
    """
    from ..seq.classifier import SeqClassifier

    model = SeqClassifier(**(tree_params or {}))
    es = eval_set[0] if eval_set else None
    return model.fit_packed(
        batch, y, names=tuple(names), k=k, registry=registry,
        eval_set=es, mean=mean, std=std, **(fit_params or {}),
    )


LEARNERS: Dict[str, Any] = {
    'xgboost': fit_xgboost,
    'catboost': fit_catboost,
    'lightgbm': fit_lightgbm,
    'sklearn': fit_sklearn,
    'mlp': fit_mlp,
}

#: Learners able to train from the packed game-state representation
#: (``VAEP.fit_packed``). Trees require the materialized feature matrix.
PACKED_LEARNERS: Dict[str, Any] = {
    'mlp': fit_mlp_packed,
    'seq': fit_seq_packed,
}
