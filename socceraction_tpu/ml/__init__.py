"""Probability models: gradient-boosted trees (host) and a JAX MLP (device)."""

from .learners import LEARNERS
from .mlp import MLPClassifier

__all__ = ['LEARNERS', 'MLPClassifier']
