"""Lightweight columnar schema validation.

The reference library uses ``pandera`` schema models to validate every
DataFrame that crosses a layer boundary (see e.g. reference
``socceraction/spadl/schema.py:10-33``). pandera is not available in this
environment, and the TPU build additionally needs the *same* invariants
expressed as dtype/range checks on packed device tensors. This module
implements a small, dependency-free schema core that serves both:

- :class:`Field` declares per-column constraints (dtype kind, bounds,
  allowed values, nullability).
- :class:`Schema` validates a :class:`pandas.DataFrame` (strict column set,
  coercion to declared dtypes) and doubles as the source of truth for the
  tensor packing code in :mod:`socceraction_tpu.core.batch`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Optional, Sequence

import numpy as np
import pandas as pd

__all__ = ['Field', 'Schema', 'SchemaError']


class SchemaError(ValueError):
    """Raised when a DataFrame does not satisfy a :class:`Schema`."""


@dataclass
class Field:
    """Constraints for a single column.

    Parameters
    ----------
    dtype : str, optional
        Target numpy dtype the column is coerced to (e.g. ``'int64'``,
        ``'float64'``, ``'object'``, ``'str'``). ``None`` leaves the column
        dtype untouched.
    ge, le : float, optional
        Inclusive lower/upper bounds (checked on non-null values).
    isin : sequence, optional
        Set of allowed values (checked on non-null values).
    nullable : bool
        Whether nulls are allowed. Default ``False``.
    required : bool
        Whether the column must be present. Default ``True``.
    """

    dtype: Optional[str] = None
    ge: Optional[float] = None
    le: Optional[float] = None
    isin: Optional[Sequence[Any]] = None
    nullable: bool = False
    required: bool = True

    def validate(self, name: str, col: pd.Series) -> pd.Series:
        """Coerce and validate a single column, returning the coerced column."""
        if self.dtype is not None:
            try:
                if self.dtype in ('str', 'object'):
                    col = col.astype('object')
                else:
                    col = col.astype(self.dtype)
            except (TypeError, ValueError) as exc:
                raise SchemaError(f'column {name!r}: cannot coerce to {self.dtype}: {exc}')
        nulls = col.isna()
        if not self.nullable and nulls.any():
            raise SchemaError(f'column {name!r}: contains {int(nulls.sum())} null values')
        valid = col[~nulls]
        if self.ge is not None and len(valid) and (valid < self.ge).any():
            raise SchemaError(f'column {name!r}: values below minimum {self.ge}')
        if self.le is not None and len(valid) and (valid > self.le).any():
            raise SchemaError(f'column {name!r}: values above maximum {self.le}')
        if self.isin is not None and len(valid):
            bad = ~valid.isin(list(self.isin))
            if bad.any():
                raise SchemaError(
                    f'column {name!r}: {int(bad.sum())} values outside allowed set'
                )
        return col


@dataclass
class Schema:
    """An ordered collection of :class:`Field` constraints for a DataFrame.

    Parameters
    ----------
    fields : dict(str, Field)
        Mapping of column name to its constraints, in canonical column order.
    strict : bool
        When True, columns not declared in ``fields`` are rejected.
    """

    fields: Dict[str, Field] = field(default_factory=dict)
    strict: bool = True

    def columns(self, required_only: bool = False) -> Iterable[str]:
        """Return the declared column names in canonical order."""
        return [n for n, f in self.fields.items() if f.required or not required_only]

    def validate(self, df: pd.DataFrame) -> pd.DataFrame:
        """Validate ``df``, returning a copy with columns coerced and ordered.

        Raises
        ------
        SchemaError
            If a required column is missing, an unknown column is present
            (``strict``), or any field constraint is violated.
        """
        missing = [n for n, f in self.fields.items() if f.required and n not in df.columns]
        if missing:
            raise SchemaError(f'missing required columns: {missing}')
        if self.strict:
            unknown = [c for c in df.columns if c not in self.fields]
            if unknown:
                raise SchemaError(f'unexpected columns: {unknown}')
        out = df.copy()
        for name, fld in self.fields.items():
            if name in out.columns:
                out[name] = fld.validate(name, out[name])
        # Canonical ordering: declared columns first (present ones), then extras.
        ordered = [n for n in self.fields if n in out.columns]
        extras = [c for c in out.columns if c not in self.fields]
        return out[ordered + extras]

    def is_valid(self, df: pd.DataFrame) -> bool:
        """Return whether ``df`` satisfies the schema."""
        try:
            self.validate(df)
            return True
        except SchemaError:
            return False


def numeric_dtype_kind(dtype: Any) -> str:
    """Classify a dtype as 'int', 'float', 'bool' or 'other' (packing helper)."""
    dt = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    if dt.kind in 'iu':
        return 'int'
    if dt.kind == 'f':
        return 'float'
    if dt.kind == 'b':
        return 'bool'
    return 'other'
