"""SPADL: the Soccer Player Action Description Language.

Vocabulary, schema, shared converter passes, utilities and the per-provider
``convert_to_actions`` converters (reference ``socceraction/spadl``).
"""

from .config import (
    actiontypes,
    actiontypes_df,
    bodyparts,
    bodyparts_df,
    field_length,
    field_width,
    results,
    results_df,
)
from .schema import SPADLSchema
from . import config  # noqa: F401
from .utils import add_names, play_left_to_right, play_left_to_right_sa
from . import statsbomb  # noqa: F401  (provider converters)
from . import wyscout  # noqa: F401
from . import wyscout_v3  # noqa: F401
from . import opta  # noqa: F401

__all__ = [
    'config',
    'statsbomb',
    'wyscout',
    'wyscout_v3',
    'opta',
    'actiontypes',
    'actiontypes_df',
    'bodyparts',
    'bodyparts_df',
    'field_length',
    'field_width',
    'results',
    'results_df',
    'SPADLSchema',
    'add_names',
    'play_left_to_right',
    'play_left_to_right_sa',
]
