"""Wyscout (API v2) event stream → SPADL converter.

Parity: reference ``socceraction/spadl/wyscout.py:24-898`` (the infamous
"HERE BE DRAGONS" converter). Same observable semantics, different
engineering: the reference determines type/result/bodypart with row-wise
``DataFrame.apply`` over an if/elif chain; here every per-event decision is
an ``np.select`` over columnar masks (first-match-wins reproduces the
if/elif precedence exactly), so the whole conversion is vectorized
host-side before the frame crosses into the packed tensor pipeline.

Pipeline stages:

1. tag list → boolean tag columns (``_tag_frame``)
2. positions list → raw start/end coordinates (``_position_columns``)
3. event surgery on the raw (0-100)² Wyscout pitch: shot end-coordinate
   estimation from goal-zone tags, duel rewriting, interception-pass
   splitting, offside attachment, touch & simulation rewriting
4. columnar type/result/bodypart determination, non-action removal
5. coordinate rescale to 105×68 m (y flipped) + goalkick/foul/keeper-save
   repairs
6. shared post-processing (direction of play, clearances, dribbles)
"""

from __future__ import annotations

from typing import Any, Dict, List, Set, Tuple

import numpy as np
import pandas as pd

from . import config as spadlconfig
from .base import (
    _add_dribbles,
    _fix_clearances,
    _fix_direction_of_play,
    min_dribble_length,
)
from .schema import SPADLSchema

__all__ = ['convert_to_actions']

#: Wyscout tag id → boolean column name (reference ``spadl/wyscout.py:78-138``).
WYSCOUT_TAGS: Dict[int, str] = {
    101: 'goal',
    102: 'own_goal',
    301: 'assist',
    302: 'key_pass',
    1901: 'counter_attack',
    401: 'left_foot',
    402: 'right_foot',
    403: 'head/body',
    1101: 'direct',
    1102: 'indirect',
    2001: 'dangerous_ball_lost',
    2101: 'blocked',
    801: 'high',
    802: 'low',
    1401: 'interception',
    1501: 'clearance',
    201: 'opportunity',
    1301: 'feint',
    1302: 'missed_ball',
    501: 'free_space_right',
    502: 'free_space_left',
    503: 'take_on_left',
    504: 'take_on_right',
    1601: 'sliding_tackle',
    601: 'anticipated',
    602: 'anticipation',
    1701: 'red_card',
    1702: 'yellow_card',
    1703: 'second_yellow_card',
    1201: 'position_goal_low_center',
    1202: 'position_goal_low_right',
    1203: 'position_goal_mid_center',
    1204: 'position_goal_mid_left',
    1205: 'position_goal_low_left',
    1206: 'position_goal_mid_right',
    1207: 'position_goal_high_center',
    1208: 'position_goal_high_left',
    1209: 'position_goal_high_right',
    1210: 'position_out_low_right',
    1211: 'position_out_mid_left',
    1212: 'position_out_low_left',
    1213: 'position_out_mid_right',
    1214: 'position_out_high_center',
    1215: 'position_out_high_left',
    1216: 'position_out_high_right',
    1217: 'position_post_low_right',
    1218: 'position_post_mid_left',
    1219: 'position_post_low_left',
    1220: 'position_post_mid_right',
    1221: 'position_post_high_center',
    1222: 'position_post_high_left',
    1223: 'position_post_high_right',
    901: 'through',
    1001: 'fairplay',
    701: 'lost',
    702: 'neutral',
    703: 'won',
    1801: 'accurate',
    1802: 'not_accurate',
}

_TAG_COLUMNS = list(WYSCOUT_TAGS.values())


def convert_to_actions(events: pd.DataFrame, home_team_id: int) -> pd.DataFrame:
    """Convert Wyscout events of one game to SPADL actions.

    Parameters
    ----------
    events : pd.DataFrame
        Wyscout events of a single game (see
        :meth:`~socceraction_tpu.data.wyscout.PublicWyscoutLoader.events`).
    home_team_id : int
        ID of the game's home team.

    Returns
    -------
    pd.DataFrame
        The game's actions in SPADL format.
    """
    events = pd.concat([events.reset_index(drop=True), _tag_frame(events)], axis=1)
    events = _position_columns(events)
    events = _estimate_shot_end_coordinates(events)
    events = _rewrite_duels(events)
    events = _split_interception_passes(events)
    events = _attach_offsides(events)
    events = _rewrite_touches(events)
    events = _rewrite_simulations(events)
    actions = _build_actions(events)
    actions = _rescale_and_repair(actions)
    actions = _fix_direction_of_play(actions, home_team_id)
    actions = _fix_clearances(actions)
    actions['action_id'] = range(len(actions))
    actions = _add_dribbles(actions)
    return SPADLSchema.validate(actions)


def _tag_frame(events: pd.DataFrame) -> pd.DataFrame:
    """Expand each event's tag list into one boolean column per known tag."""
    tag_sets: List[Set[int]] = [
        {t['id'] for t in tags} for tags in events['tags']
    ]
    data = {
        column: np.fromiter(
            (tag_id in s for s in tag_sets), dtype=bool, count=len(tag_sets)
        )
        for tag_id, column in WYSCOUT_TAGS.items()
    }
    return pd.DataFrame(data, index=range(len(tag_sets)))


def _position_columns(events: pd.DataFrame) -> pd.DataFrame:
    """Extract start/end coordinates from each event's ``positions`` list.

    Two entries give start and end; a single entry is both; an empty list
    yields missing coordinates (the event is dropped later).
    """
    n = len(events)
    coords = np.full((n, 4), np.nan)
    for i, positions in enumerate(events['positions']):
        if len(positions) >= 2:
            coords[i] = (
                positions[0]['x'],
                positions[0]['y'],
                positions[1]['x'],
                positions[1]['y'],
            )
        elif len(positions) == 1:
            x, y = positions[0]['x'], positions[0]['y']
            coords[i] = (x, y, x, y)
    events = events.drop(columns=['positions'])
    events[['start_x', 'start_y', 'end_x', 'end_y']] = coords
    return events


# Goal-zone tag groups → estimated shot end coordinates on the raw
# (0-100)² Wyscout pitch (reference ``spadl/wyscout.py:206-283``); the goal
# mouth is at x=100, y≈45-55 from the shooter's perspective.
_SHOT_END_ESTIMATES: List[Tuple[List[str], float, float]] = [
    (['position_goal_low_center', 'position_goal_mid_center', 'position_goal_high_center'], 100.0, 50.0),
    (['position_goal_low_right', 'position_goal_mid_right', 'position_goal_high_right'], 100.0, 55.0),
    (['position_goal_mid_left', 'position_goal_low_left', 'position_goal_high_left'], 100.0, 45.0),
    (['position_out_high_center', 'position_post_high_center'], 100.0, 50.0),
    (['position_out_low_right', 'position_out_mid_right', 'position_out_high_right'], 100.0, 60.0),
    (['position_out_mid_left', 'position_out_low_left', 'position_out_high_left'], 100.0, 40.0),
    (['position_post_mid_left', 'position_post_low_left', 'position_post_high_left'], 100.0, 55.38),
    (['position_post_low_right', 'position_post_mid_right', 'position_post_high_right'], 100.0, 44.62),
]


def _estimate_shot_end_coordinates(events: pd.DataFrame) -> pd.DataFrame:
    """Estimate shot end coordinates from the goal-zone tags."""
    for columns, end_x, end_y in _SHOT_END_ESTIMATES:
        mask = np.logical_or.reduce([events[c].to_numpy() for c in columns])
        events.loc[mask, 'end_x'] = end_x
        events.loc[mask, 'end_y'] = end_y
    blocked = events['blocked'].to_numpy()
    events.loc[blocked, 'end_x'] = events.loc[blocked, 'start_x']
    events.loc[blocked, 'end_y'] = events.loc[blocked, 'start_y']
    return events


def _rewrite_duels(events: pd.DataFrame) -> pd.DataFrame:
    """Rewrite duel events (type 1).

    A pair of duel rows followed by a ball-out-of-field row (subtype 50) in
    the same period becomes a pass by the duel winner to the (mirrored)
    out-of-field location. Attacking-duel take-ons and sliding tackles are
    kept (retyped on their tags later); all other duels are dropped.
    """
    nxt = events.shift(-1)
    nxt2 = events.shift(-2)

    out_after_duels = (
        (events['type_id'] == 1)
        & (nxt['type_id'] == 1)
        & (nxt2['subtype_id'] == 50)
        & (events['period_id'] == nxt2['period_id'])
    )
    # The winner is whichever of the two duelists is NOT the team that
    # conceded the throw-in/goal-kick (i.e. differs from the out event row).
    won_here = out_after_duels & (events['team_id'] != nxt2['team_id'])
    won_next = out_after_duels & (nxt['team_id'] != nxt2['team_id'])
    won = won_here | won_next
    won_air = (won_here & (events['subtype_id'] == 10)) | (
        won_next & (nxt['subtype_id'] == 10)
    )

    events.loc[won, 'type_id'] = 8
    events.loc[won_air, 'subtype_id'] = 82
    events.loc[won & ~won_air, 'subtype_id'] = 85
    events.loc[won, 'accurate'] = False
    events.loc[won, 'not_accurate'] = True
    events.loc[won, 'end_x'] = 100 - nxt2.loc[won, 'start_x']
    events.loc[won, 'end_y'] = 100 - nxt2.loc[won, 'start_y']

    take_on = (events['subtype_id'] == 11) & (
        events['take_on_left'] | events['take_on_right']
    )
    events.loc[take_on, 'type_id'] = 0
    events.loc[events['sliding_tackle'], 'type_id'] = 0

    return events[events['type_id'] != 1].reset_index(drop=True)


def _split_interception_passes(events: pd.DataFrame) -> pd.DataFrame:
    """Split a pass that is also tagged as an interception into two events.

    The interception copy keeps only the interception tag, gets type 0 /
    subtype 0 and a zero-length trajectory, and sorts in front of the pass.
    """
    is_both = events['interception'] & (events['type_id'] == 8)
    if not is_both.any():
        return events
    intercepts = events[is_both].copy()
    intercepts[_TAG_COLUMNS] = False
    intercepts['interception'] = True
    intercepts['type_id'] = 0
    intercepts['subtype_id'] = 0
    intercepts[['end_x', 'end_y']] = intercepts[['start_x', 'start_y']].to_numpy()
    merged = pd.concat([intercepts, events], ignore_index=True)
    return merged.sort_values(
        ['period_id', 'milliseconds'], kind='stable'
    ).reset_index(drop=True)


def _attach_offsides(events: pd.DataFrame) -> pd.DataFrame:
    """Fold offside events (type 6) into the preceding pass as a flag."""
    events['offside'] = 0
    nxt = events.shift(-1)
    pass_before_offside = (nxt['type_id'] == 6) & (events['type_id'] == 8)
    events.loc[pass_before_offside, 'offside'] = 1
    return events[events['type_id'] != 6].reset_index(drop=True)


def _rewrite_touches(events: pd.DataFrame) -> pd.DataFrame:
    """Turn touches that directly reach another player into passes.

    A touch (subtype 72, not an interception) whose end location coincides
    with the next event's start location becomes a pass — accurate when the
    receiver is a teammate, inaccurate otherwise.
    """
    nxt = events.shift(-1)
    touch = (events['subtype_id'] == 72) & ~events['interception']
    other_player = events['player_id'] != nxt['player_id']
    same_team = events['team_id'] == nxt['team_id']
    near = (
        ((events['end_x'] - nxt['start_x']).abs() < min_dribble_length)
        & ((events['end_y'] - nxt['start_y']).abs() < min_dribble_length)
    )
    to_teammate = touch & other_player & same_team & near
    to_opponent = touch & other_player & ~same_team & near
    for mask, ok in ((to_teammate, True), (to_opponent, False)):
        events.loc[mask, 'type_id'] = 8
        events.loc[mask, 'subtype_id'] = 85
        events.loc[mask, 'accurate'] = ok
        events.loc[mask, 'not_accurate'] = not ok
    return events


def _rewrite_simulations(events: pd.DataFrame) -> pd.DataFrame:
    """Rewrite simulation events (subtype 25).

    A simulation directly after a failed take-on is dropped (the take-on
    already captures the failed attempt); any other simulation becomes a
    failed take-on itself.

    .. note:: the "preceded by failed take-on" test reproduces the
       reference's operator precedence (``spadl/wyscout.py:469-471``):
       ``take_on_left | (take_on_right & not_accurate)``.
    """
    prev = events.shift(1)
    simulation = events['subtype_id'] == 25
    after_failed_take_on = prev['take_on_left'] | (
        prev['take_on_right'] & prev['not_accurate']
    )
    to_take_on = simulation & ~after_failed_take_on
    events.loc[to_take_on, 'type_id'] = 0
    events.loc[to_take_on, 'subtype_id'] = 0
    events.loc[to_take_on, 'accurate'] = False
    events.loc[to_take_on, 'not_accurate'] = True
    events.loc[to_take_on, 'take_on_left'] = True
    return events[~(simulation & after_failed_take_on)].reset_index(drop=True)


def _first_match(
    conditions: List[Any], choices: List[int], default: int
) -> np.ndarray:
    """``np.select`` with if/elif precedence (first matching row wins)."""
    return np.select([np.asarray(c, dtype=bool) for c in conditions], choices, default)


def _build_actions(events: pd.DataFrame) -> pd.DataFrame:
    """Determine SPADL type/result/bodypart columnar and drop non-actions."""
    at = spadlconfig.actiontypes.index
    bp = spadlconfig.bodyparts.index

    type_id = events['type_id']
    subtype_id = events['subtype_id']

    bodypart_id = _first_match(
        [
            subtype_id.isin([81, 36, 21, 90, 91]),
            subtype_id == 82,
            (type_id == 10) & events['head/body'],
        ],
        [bp('other'), bp('head'), bp('head/other')],
        default=bp('foot'),
    )

    action_type = _first_match(
        [
            events['own_goal'],
            (type_id == 8) & (subtype_id == 80),
            type_id == 8,
            subtype_id == 36,
            (subtype_id == 30) & events['high'],
            subtype_id == 30,
            subtype_id == 32,
            subtype_id == 31,
            subtype_id == 34,
            (type_id == 2) & ~subtype_id.isin([22, 23, 24, 26]),
            type_id == 10,
            subtype_id == 35,
            subtype_id == 33,
            type_id == 9,
            subtype_id == 71,
            (subtype_id == 72) & events['not_accurate'],
            subtype_id == 70,
            events['take_on_left'] | events['take_on_right'],
            events['sliding_tackle'],
            events['interception'] & subtype_id.isin([0, 10, 11, 12, 13, 72]),
        ],
        [
            at('bad_touch'),
            at('cross'),
            at('pass'),
            at('throw_in'),
            at('corner_crossed'),
            at('corner_short'),
            at('freekick_crossed'),
            at('freekick_short'),
            at('goalkick'),
            at('foul'),
            at('shot'),
            at('shot_penalty'),
            at('shot_freekick'),
            at('keeper_save'),
            at('clearance'),
            at('bad_touch'),
            at('dribble'),
            at('take_on'),
            at('tackle'),
            at('interception'),
        ],
        default=at('non_action'),
    )

    result_id = _first_match(
        [
            events['offside'] == 1,
            type_id == 2,
            events['goal'],
            events['own_goal'],
            subtype_id.isin([100, 33, 35]),
            events['accurate'],
            events['not_accurate'],
            events['interception'] | events['clearance'] | (subtype_id == 71),
            type_id == 9,
        ],
        [
            spadlconfig.OFFSIDE,
            spadlconfig.SUCCESS,
            spadlconfig.SUCCESS,
            spadlconfig.OWNGOAL,
            spadlconfig.FAIL,
            spadlconfig.SUCCESS,
            spadlconfig.FAIL,
            spadlconfig.SUCCESS,
            spadlconfig.SUCCESS,
        ],
        default=spadlconfig.SUCCESS,
    )

    actions = pd.DataFrame(
        {
            'game_id': events['game_id'],
            'original_event_id': events['event_id'].astype(object),
            'period_id': events['period_id'],
            'time_seconds': events['milliseconds'] / 1000,
            'team_id': events['team_id'],
            'player_id': events['player_id'],
            'start_x': events['start_x'],
            'start_y': events['start_y'],
            'end_x': events['end_x'],
            'end_y': events['end_y'],
            'bodypart_id': bodypart_id,
            'type_id': action_type,
            'result_id': result_id,
        }
    )
    keep = actions['type_id'] != spadlconfig.NON_ACTION
    return actions[keep].reset_index(drop=True)


def _rescale_and_repair(actions: pd.DataFrame) -> pd.DataFrame:
    """Rescale (0-100)² coordinates to 105×68 m and repair special cases."""
    length, width = spadlconfig.field_length, spadlconfig.field_width
    for c in ('start_x', 'end_x'):
        actions[c] = (actions[c] * length / 100).clip(0, length)
    for c in ('start_y', 'end_y'):
        # Wyscout's y axis runs top-to-bottom.
        actions[c] = ((100 - actions[c]) * width / 100).clip(0, width)

    at = spadlconfig.actiontypes.index

    # Goalkicks: start from a fixed point in front of goal.
    goalkick = actions['type_id'] == at('goalkick')
    actions.loc[goalkick, 'start_x'] = 5.0
    actions.loc[goalkick, 'start_y'] = 34.0

    # Goalkick result: retained possession = success.
    nxt = actions.shift(-1)
    keeps_ball = actions['team_id'] == nxt['team_id']
    actions.loc[goalkick & keeps_ball, 'result_id'] = spadlconfig.SUCCESS
    actions.loc[goalkick & ~keeps_ball, 'result_id'] = spadlconfig.FAIL

    # Fouls happen in place.
    foul = actions['type_id'] == at('foul')
    actions.loc[foul, 'end_x'] = actions.loc[foul, 'start_x']
    actions.loc[foul, 'end_y'] = actions.loc[foul, 'start_y']

    # Keeper saves: coordinates are recorded from the shooter's perspective;
    # mirror them to the keeper's own goal and collapse to a point.
    save = actions['type_id'] == at('keeper_save')
    actions.loc[save, 'end_x'] = length - actions.loc[save, 'end_x']
    actions.loc[save, 'end_y'] = width - actions.loc[save, 'end_y']
    actions.loc[save, 'start_x'] = actions.loc[save, 'end_x']
    actions.loc[save, 'start_y'] = actions.loc[save, 'end_y']

    # Drop the keeper's pick-up directly after a conceded goal.
    prev = actions.shift(1)
    same_phase = prev['time_seconds'] + 10 > actions['time_seconds']
    prev_goal = prev['type_id'].isin(
        [at('shot'), at('shot_penalty'), at('shot_freekick')]
    ) & (prev['result_id'] == spadlconfig.SUCCESS)
    drop = same_phase & prev_goal & save
    return actions[~drop.fillna(False)].reset_index(drop=True)
