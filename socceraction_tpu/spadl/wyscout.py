"""Wyscout (API v2) event stream → SPADL converter.

Parity: reference ``socceraction/spadl/wyscout.py:24-898`` (the infamous
"HERE BE DRAGONS" converter). Same observable semantics, different
engineering: the reference determines type/result/bodypart with row-wise
``DataFrame.apply`` over an if/elif chain; here every per-event decision is
an ``np.select`` over columnar masks (first-match-wins reproduces the
if/elif precedence exactly), so the whole conversion is vectorized
host-side before the frame crosses into the packed tensor pipeline.

Pipeline stages:

1. tag list → boolean tag columns (``get_tagsdf``)
2. positions list → raw start/end coordinates (``make_new_positions``)
3. event surgery on the raw (0-100)² Wyscout pitch: shot end-coordinate
   estimation from goal-zone tags, duel rewriting, interception-pass
   splitting, offside attachment, touch & simulation rewriting
4. columnar type/result/bodypart determination, non-action removal
5. coordinate rescale to 105×68 m (y flipped) + goalkick/foul/keeper-save
   repairs
6. shared post-processing (direction of play, clearances, dribbles)

Every stage is exported under the reference's public name (``get_tagsdf``,
``fix_wyscout_events``, ``create_df_actions``, ``fix_actions``, …,
reference ``spadl/wyscout.py:58-898``) so pipelines written against the
reference keep working; the per-row ``determine_*`` functions are thin
wrappers over the columnar decision tables. The deprecated loader/schema
re-exports (reference ``spadl/wyscout.py:901-991``) are served lazily via
module ``__getattr__`` with the same :class:`DeprecationWarning`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Set, Tuple

import numpy as np
import pandas as pd

from . import config as spadlconfig
from .base import (
    _add_dribbles,
    _fix_clearances,
    _fix_direction_of_play,
    _single_event,
    min_dribble_length,
)
from .schema import SPADLSchema

__all__ = [
    'convert_to_actions',
    'get_tagsdf',
    'make_new_positions',
    'fix_wyscout_events',
    'create_shot_coordinates',
    'convert_duels',
    'insert_interception_passes',
    'add_offside_variable',
    'convert_touches',
    'convert_simulations',
    'create_df_actions',
    'determine_bodypart_id',
    'determine_type_id',
    'determine_result_id',
    'remove_non_actions',
    'fix_actions',
    'fix_goalkick_coordinates',
    'adjust_goalkick_result',
    'fix_foul_coordinates',
    'fix_keeper_save_coordinates',
    'remove_keeper_goal_actions',
]

# Deprecated pre-1.2 re-exports (reference ``spadl/wyscout.py:901-991``):
# the loaders and raw-data schemas moved to
# :mod:`socceraction_tpu.data.wyscout` but remain importable here with a
# DeprecationWarning.
from ._deprecated import deprecated_reexports as _deprecated_reexports

__getattr__ = _deprecated_reexports(
    __name__,
    'socceraction_tpu.data.wyscout',
    (
        'WyscoutLoader',
        'PublicWyscoutLoader',
        'WyscoutCompetitionSchema',
        'WyscoutGameSchema',
        'WyscoutPlayerSchema',
        'WyscoutTeamSchema',
        'WyscoutEventSchema',
    ),
)

#: Wyscout tag id → boolean column name (reference ``spadl/wyscout.py:78-138``).
WYSCOUT_TAGS: Dict[int, str] = {
    101: 'goal',
    102: 'own_goal',
    301: 'assist',
    302: 'key_pass',
    1901: 'counter_attack',
    401: 'left_foot',
    402: 'right_foot',
    403: 'head/body',
    1101: 'direct',
    1102: 'indirect',
    2001: 'dangerous_ball_lost',
    2101: 'blocked',
    801: 'high',
    802: 'low',
    1401: 'interception',
    1501: 'clearance',
    201: 'opportunity',
    1301: 'feint',
    1302: 'missed_ball',
    501: 'free_space_right',
    502: 'free_space_left',
    503: 'take_on_left',
    504: 'take_on_right',
    1601: 'sliding_tackle',
    601: 'anticipated',
    602: 'anticipation',
    1701: 'red_card',
    1702: 'yellow_card',
    1703: 'second_yellow_card',
    1201: 'position_goal_low_center',
    1202: 'position_goal_low_right',
    1203: 'position_goal_mid_center',
    1204: 'position_goal_mid_left',
    1205: 'position_goal_low_left',
    1206: 'position_goal_mid_right',
    1207: 'position_goal_high_center',
    1208: 'position_goal_high_left',
    1209: 'position_goal_high_right',
    1210: 'position_out_low_right',
    1211: 'position_out_mid_left',
    1212: 'position_out_low_left',
    1213: 'position_out_mid_right',
    1214: 'position_out_high_center',
    1215: 'position_out_high_left',
    1216: 'position_out_high_right',
    1217: 'position_post_low_right',
    1218: 'position_post_mid_left',
    1219: 'position_post_low_left',
    1220: 'position_post_mid_right',
    1221: 'position_post_high_center',
    1222: 'position_post_high_left',
    1223: 'position_post_high_right',
    901: 'through',
    1001: 'fairplay',
    701: 'lost',
    702: 'neutral',
    703: 'won',
    1801: 'accurate',
    1802: 'not_accurate',
}

_TAG_COLUMNS = list(WYSCOUT_TAGS.values())


def convert_to_actions(events: pd.DataFrame, home_team_id: int) -> pd.DataFrame:
    """Convert Wyscout events of one game to SPADL actions.

    Parameters
    ----------
    events : pd.DataFrame
        Wyscout events of a single game (see
        :meth:`~socceraction_tpu.data.wyscout.PublicWyscoutLoader.events`).
    home_team_id : int
        ID of the game's home team.

    Returns
    -------
    pd.DataFrame
        The game's actions in SPADL format.
    """
    events = pd.concat([events.reset_index(drop=True), get_tagsdf(events)], axis=1)
    events = make_new_positions(events)
    events = fix_wyscout_events(events)
    actions = create_df_actions(events)
    actions = fix_actions(actions)
    actions = _fix_direction_of_play(actions, home_team_id)
    actions = _fix_clearances(actions)
    actions['action_id'] = range(len(actions))
    actions = _add_dribbles(actions)
    return SPADLSchema.validate(actions)


def get_tagsdf(events: pd.DataFrame) -> pd.DataFrame:
    """Expand each event's tag list into one boolean column per known tag."""
    tag_sets: List[Set[int]] = [
        {t['id'] for t in tags} for tags in events['tags']
    ]
    data = {
        column: np.fromiter(
            (tag_id in s for s in tag_sets), dtype=bool, count=len(tag_sets)
        )
        for tag_id, column in WYSCOUT_TAGS.items()
    }
    return pd.DataFrame(data, index=range(len(tag_sets)))


def make_new_positions(events: pd.DataFrame) -> pd.DataFrame:
    """Extract start/end coordinates from each event's ``positions`` list.

    Two entries give start and end; a single entry is both; an empty list
    yields missing coordinates (the event is dropped later).
    """
    n = len(events)
    coords = np.full((n, 4), np.nan)
    for i, positions in enumerate(events['positions']):
        if len(positions) >= 2:
            coords[i] = (
                positions[0]['x'],
                positions[0]['y'],
                positions[1]['x'],
                positions[1]['y'],
            )
        elif len(positions) == 1:
            x, y = positions[0]['x'], positions[0]['y']
            coords[i] = (x, y, x, y)
    events = events.drop(columns=['positions'])
    events[['start_x', 'start_y', 'end_x', 'end_y']] = coords
    return events


# Goal-zone tag groups → estimated shot end coordinates on the raw
# (0-100)² Wyscout pitch (reference ``spadl/wyscout.py:206-283``); the goal
# mouth is at x=100, y≈45-55 from the shooter's perspective.
_SHOT_END_ESTIMATES: List[Tuple[List[str], float, float]] = [
    (['position_goal_low_center', 'position_goal_mid_center', 'position_goal_high_center'], 100.0, 50.0),
    (['position_goal_low_right', 'position_goal_mid_right', 'position_goal_high_right'], 100.0, 55.0),
    (['position_goal_mid_left', 'position_goal_low_left', 'position_goal_high_left'], 100.0, 45.0),
    (['position_out_high_center', 'position_post_high_center'], 100.0, 50.0),
    (['position_out_low_right', 'position_out_mid_right', 'position_out_high_right'], 100.0, 60.0),
    (['position_out_mid_left', 'position_out_low_left', 'position_out_high_left'], 100.0, 40.0),
    (['position_post_mid_left', 'position_post_low_left', 'position_post_high_left'], 100.0, 55.38),
    (['position_post_low_right', 'position_post_mid_right', 'position_post_high_right'], 100.0, 44.62),
]


def fix_wyscout_events(df_events: pd.DataFrame) -> pd.DataFrame:
    """Event surgery on the raw (0-100)² Wyscout pitch.

    Chains the six rewriting stages in the reference's order
    (``spadl/wyscout.py:184-206``): shot end-coordinate estimation, duel
    rewriting, interception-pass splitting, offside attachment, touch and
    simulation rewriting.
    """
    df_events = create_shot_coordinates(df_events)
    df_events = convert_duels(df_events)
    df_events = insert_interception_passes(df_events)
    df_events = add_offside_variable(df_events)
    df_events = convert_touches(df_events)
    df_events = convert_simulations(df_events)
    return df_events


def create_shot_coordinates(events: pd.DataFrame) -> pd.DataFrame:
    """Estimate shot end coordinates from the goal-zone tags."""
    for columns, end_x, end_y in _SHOT_END_ESTIMATES:
        mask = np.logical_or.reduce([events[c].to_numpy() for c in columns])
        events.loc[mask, 'end_x'] = end_x
        events.loc[mask, 'end_y'] = end_y
    blocked = events['blocked'].to_numpy()
    events.loc[blocked, 'end_x'] = events.loc[blocked, 'start_x']
    events.loc[blocked, 'end_y'] = events.loc[blocked, 'start_y']
    return events


def convert_duels(events: pd.DataFrame) -> pd.DataFrame:
    """Rewrite duel events (type 1).

    A pair of duel rows followed by a ball-out-of-field row (subtype 50) in
    the same period becomes a pass by the duel winner to the (mirrored)
    out-of-field location. Attacking-duel take-ons and sliding tackles are
    kept (retyped on their tags later); all other duels are dropped.
    """
    nxt = events.shift(-1)
    nxt2 = events.shift(-2)

    out_after_duels = (
        (events['type_id'] == 1)
        & (nxt['type_id'] == 1)
        & (nxt2['subtype_id'] == 50)
        & (events['period_id'] == nxt2['period_id'])
    )
    # The winner is whichever of the two duelists is NOT the team that
    # conceded the throw-in/goal-kick (i.e. differs from the out event row).
    won_here = out_after_duels & (events['team_id'] != nxt2['team_id'])
    won_next = out_after_duels & (nxt['team_id'] != nxt2['team_id'])
    won = won_here | won_next
    won_air = (won_here & (events['subtype_id'] == 10)) | (
        won_next & (nxt['subtype_id'] == 10)
    )

    events.loc[won, 'type_id'] = 8
    events.loc[won_air, 'subtype_id'] = 82
    events.loc[won & ~won_air, 'subtype_id'] = 85
    events.loc[won, 'accurate'] = False
    events.loc[won, 'not_accurate'] = True
    events.loc[won, 'end_x'] = 100 - nxt2.loc[won, 'start_x']
    events.loc[won, 'end_y'] = 100 - nxt2.loc[won, 'start_y']

    take_on = (events['subtype_id'] == 11) & (
        events['take_on_left'] | events['take_on_right']
    )
    events.loc[take_on, 'type_id'] = 0
    events.loc[events['sliding_tackle'], 'type_id'] = 0

    return events[events['type_id'] != 1].reset_index(drop=True)


def insert_interception_passes(events: pd.DataFrame) -> pd.DataFrame:
    """Split a pass that is also tagged as an interception into two events.

    The interception copy keeps only the interception tag, gets type 0 /
    subtype 0 and a zero-length trajectory, and sorts in front of the pass.
    """
    is_both = events['interception'] & (events['type_id'] == 8)
    if not is_both.any():
        return events
    intercepts = events[is_both].copy()
    intercepts[_TAG_COLUMNS] = False
    intercepts['interception'] = True
    intercepts['type_id'] = 0
    intercepts['subtype_id'] = 0
    intercepts[['end_x', 'end_y']] = intercepts[['start_x', 'start_y']].to_numpy()
    merged = pd.concat([intercepts, events], ignore_index=True)
    return merged.sort_values(
        ['period_id', 'milliseconds'], kind='stable'
    ).reset_index(drop=True)


def add_offside_variable(events: pd.DataFrame) -> pd.DataFrame:
    """Fold offside events (type 6) into the preceding pass as a flag."""
    events['offside'] = 0
    nxt = events.shift(-1)
    pass_before_offside = (nxt['type_id'] == 6) & (events['type_id'] == 8)
    events.loc[pass_before_offside, 'offside'] = 1
    return events[events['type_id'] != 6].reset_index(drop=True)


def convert_touches(events: pd.DataFrame) -> pd.DataFrame:
    """Turn touches that directly reach another player into passes.

    A touch (subtype 72, not an interception) whose end location coincides
    with the next event's start location becomes a pass — accurate when the
    receiver is a teammate, inaccurate otherwise.
    """
    nxt = events.shift(-1)
    touch = (events['subtype_id'] == 72) & ~events['interception']
    other_player = events['player_id'] != nxt['player_id']
    same_team = events['team_id'] == nxt['team_id']
    near = (
        ((events['end_x'] - nxt['start_x']).abs() < min_dribble_length)
        & ((events['end_y'] - nxt['start_y']).abs() < min_dribble_length)
    )
    to_teammate = touch & other_player & same_team & near
    to_opponent = touch & other_player & ~same_team & near
    for mask, ok in ((to_teammate, True), (to_opponent, False)):
        events.loc[mask, 'type_id'] = 8
        events.loc[mask, 'subtype_id'] = 85
        events.loc[mask, 'accurate'] = ok
        events.loc[mask, 'not_accurate'] = not ok
    return events


def convert_simulations(events: pd.DataFrame) -> pd.DataFrame:
    """Rewrite simulation events (subtype 25).

    A simulation directly after a failed take-on is dropped (the take-on
    already captures the failed attempt); any other simulation becomes a
    failed take-on itself.

    .. note:: the "preceded by failed take-on" test reproduces the
       reference's operator precedence (``spadl/wyscout.py:469-471``):
       ``take_on_left | (take_on_right & not_accurate)``.
    """
    prev = events.shift(1)
    simulation = events['subtype_id'] == 25
    after_failed_take_on = prev['take_on_left'] | (
        prev['take_on_right'] & prev['not_accurate']
    )
    to_take_on = simulation & ~after_failed_take_on
    events.loc[to_take_on, 'type_id'] = 0
    events.loc[to_take_on, 'subtype_id'] = 0
    events.loc[to_take_on, 'accurate'] = False
    events.loc[to_take_on, 'not_accurate'] = True
    events.loc[to_take_on, 'take_on_left'] = True
    return events[~(simulation & after_failed_take_on)].reset_index(drop=True)


def _first_match(
    conditions: List[Any], choices: List[int], default: int
) -> np.ndarray:
    """``np.select`` with if/elif precedence (first matching row wins)."""
    return np.select([np.asarray(c, dtype=bool) for c in conditions], choices, default)


def _bodypart_ids(events: pd.DataFrame) -> np.ndarray:
    """Columnar bodypart decision table (reference ``spadl/wyscout.py:579``)."""
    bp = spadlconfig.bodyparts.index
    type_id = events['type_id']
    subtype_id = events['subtype_id']
    return _first_match(
        [
            subtype_id.isin([81, 36, 21, 90, 91]),
            subtype_id == 82,
            (type_id == 10) & events['head/body'],
        ],
        [bp('other'), bp('head'), bp('head/other')],
        default=bp('foot'),
    )


def _type_ids(events: pd.DataFrame) -> np.ndarray:
    """Columnar action-type decision table (reference ``spadl/wyscout.py:603``)."""
    at = spadlconfig.actiontypes.index
    type_id = events['type_id']
    subtype_id = events['subtype_id']
    return _first_match(
        [
            events['own_goal'],
            (type_id == 8) & (subtype_id == 80),
            type_id == 8,
            subtype_id == 36,
            (subtype_id == 30) & events['high'],
            subtype_id == 30,
            subtype_id == 32,
            subtype_id == 31,
            subtype_id == 34,
            (type_id == 2) & ~subtype_id.isin([22, 23, 24, 26]),
            type_id == 10,
            subtype_id == 35,
            subtype_id == 33,
            type_id == 9,
            subtype_id == 71,
            (subtype_id == 72) & events['not_accurate'],
            subtype_id == 70,
            events['take_on_left'] | events['take_on_right'],
            events['sliding_tackle'],
            events['interception'] & subtype_id.isin([0, 10, 11, 12, 13, 72]),
        ],
        [
            at('bad_touch'),
            at('cross'),
            at('pass'),
            at('throw_in'),
            at('corner_crossed'),
            at('corner_short'),
            at('freekick_crossed'),
            at('freekick_short'),
            at('goalkick'),
            at('foul'),
            at('shot'),
            at('shot_penalty'),
            at('shot_freekick'),
            at('keeper_save'),
            at('clearance'),
            at('bad_touch'),
            at('dribble'),
            at('take_on'),
            at('tackle'),
            at('interception'),
        ],
        default=at('non_action'),
    )


def _result_ids(events: pd.DataFrame) -> np.ndarray:
    """Columnar result decision table (reference ``spadl/wyscout.py:666``)."""
    type_id = events['type_id']
    subtype_id = events['subtype_id']
    return _first_match(
        [
            events['offside'] == 1,
            type_id == 2,
            events['goal'],
            events['own_goal'],
            subtype_id.isin([100, 33, 35]),
            events['accurate'],
            events['not_accurate'],
            events['interception'] | events['clearance'] | (subtype_id == 71),
            type_id == 9,
        ],
        [
            spadlconfig.OFFSIDE,
            spadlconfig.SUCCESS,
            spadlconfig.SUCCESS,
            spadlconfig.OWNGOAL,
            spadlconfig.FAIL,
            spadlconfig.SUCCESS,
            spadlconfig.FAIL,
            spadlconfig.SUCCESS,
            spadlconfig.SUCCESS,
        ],
        default=spadlconfig.SUCCESS,
    )


def determine_bodypart_id(event: Any) -> int:
    """Bodypart id of one Wyscout event (row-wise reference API)."""
    return int(_bodypart_ids(_single_event(event))[0])


def determine_type_id(event: Any) -> int:
    """SPADL action-type id of one Wyscout event (row-wise reference API)."""
    return int(_type_ids(_single_event(event))[0])


def determine_result_id(event: Any) -> int:
    """SPADL result id of one Wyscout event (row-wise reference API)."""
    return int(_result_ids(_single_event(event))[0])


def create_df_actions(df_events: pd.DataFrame) -> pd.DataFrame:
    """Build the raw SPADL action frame and drop non-actions.

    Type/result/bodypart come from the columnar decision tables; like the
    reference (``spadl/wyscout.py:542-576``) the remaining non-actions are
    removed before returning.
    """
    df_actions = pd.DataFrame(
        {
            'game_id': df_events['game_id'],
            'original_event_id': df_events['event_id'].astype(object),
            'period_id': df_events['period_id'],
            'time_seconds': df_events['milliseconds'] / 1000,
            'team_id': df_events['team_id'],
            'player_id': df_events['player_id'],
            'start_x': df_events['start_x'],
            'start_y': df_events['start_y'],
            'end_x': df_events['end_x'],
            'end_y': df_events['end_y'],
            'bodypart_id': _bodypart_ids(df_events),
            'type_id': _type_ids(df_events),
            'result_id': _result_ids(df_events),
        }
    )
    return remove_non_actions(df_actions)


def remove_non_actions(df_actions: pd.DataFrame) -> pd.DataFrame:
    """Drop rows typed ``non_action``."""
    keep = df_actions['type_id'] != spadlconfig.NON_ACTION
    return df_actions[keep].reset_index(drop=True)


def fix_actions(df_actions: pd.DataFrame) -> pd.DataFrame:
    """Rescale (0-100)² coordinates to 105×68 m and repair special cases.

    Same repair chain and order as the reference
    (``spadl/wyscout.py:722-760``): goalkick coordinates, goalkick results,
    foul coordinates, keeper-save coordinates, post-goal keeper-save
    removal.
    """
    length, width = spadlconfig.field_length, spadlconfig.field_width
    for c in ('start_x', 'end_x'):
        df_actions[c] = (df_actions[c] * length / 100).clip(0, length)
    for c in ('start_y', 'end_y'):
        # Wyscout's y axis runs top-to-bottom.
        df_actions[c] = ((100 - df_actions[c]) * width / 100).clip(0, width)
    df_actions = fix_goalkick_coordinates(df_actions)
    df_actions = adjust_goalkick_result(df_actions)
    df_actions = fix_foul_coordinates(df_actions)
    df_actions = fix_keeper_save_coordinates(df_actions)
    df_actions = remove_keeper_goal_actions(df_actions)
    return df_actions.reset_index(drop=True)


def fix_goalkick_coordinates(df_actions: pd.DataFrame) -> pd.DataFrame:
    """Goalkicks start from a fixed point in front of goal."""
    goalkick = df_actions['type_id'] == spadlconfig.actiontypes.index('goalkick')
    df_actions.loc[goalkick, 'start_x'] = 5.0
    df_actions.loc[goalkick, 'start_y'] = 34.0
    return df_actions


def adjust_goalkick_result(df_actions: pd.DataFrame) -> pd.DataFrame:
    """Goalkick result: retained possession = success."""
    goalkick = df_actions['type_id'] == spadlconfig.actiontypes.index('goalkick')
    nxt = df_actions.shift(-1)
    keeps_ball = df_actions['team_id'] == nxt['team_id']
    df_actions.loc[goalkick & keeps_ball, 'result_id'] = spadlconfig.SUCCESS
    df_actions.loc[goalkick & ~keeps_ball, 'result_id'] = spadlconfig.FAIL
    return df_actions


def fix_foul_coordinates(df_actions: pd.DataFrame) -> pd.DataFrame:
    """Fouls happen in place: end coordinates equal start coordinates."""
    foul = df_actions['type_id'] == spadlconfig.actiontypes.index('foul')
    df_actions.loc[foul, 'end_x'] = df_actions.loc[foul, 'start_x']
    df_actions.loc[foul, 'end_y'] = df_actions.loc[foul, 'start_y']
    return df_actions


def fix_keeper_save_coordinates(df_actions: pd.DataFrame) -> pd.DataFrame:
    """Mirror keeper-save coordinates to the keeper's own goal.

    Coordinates are recorded from the shooter's perspective; mirror them
    and collapse the save to a point.
    """
    length, width = spadlconfig.field_length, spadlconfig.field_width
    save = df_actions['type_id'] == spadlconfig.actiontypes.index('keeper_save')
    df_actions.loc[save, 'end_x'] = length - df_actions.loc[save, 'end_x']
    df_actions.loc[save, 'end_y'] = width - df_actions.loc[save, 'end_y']
    df_actions.loc[save, 'start_x'] = df_actions.loc[save, 'end_x']
    df_actions.loc[save, 'start_y'] = df_actions.loc[save, 'end_y']
    return df_actions


def remove_keeper_goal_actions(df_actions: pd.DataFrame) -> pd.DataFrame:
    """Drop the keeper's pick-up directly after a conceded goal."""
    at = spadlconfig.actiontypes.index
    save = df_actions['type_id'] == at('keeper_save')
    prev = df_actions.shift(1)
    same_phase = prev['time_seconds'] + 10 > df_actions['time_seconds']
    prev_goal = prev['type_id'].isin(
        [at('shot'), at('shot_penalty'), at('shot_freekick')]
    ) & (prev['result_id'] == spadlconfig.SUCCESS)
    drop = same_phase & prev_goal & save
    return df_actions[~drop.fillna(False)].reset_index(drop=True)
