"""Opta event stream → SPADL converter.

Parity: reference ``socceraction/spadl/opta.py:12-170``. Same observable
semantics, vectorized: the reference maps row-wise if/elif chains with
``DataFrame.apply``; here type/result/bodypart are ``np.select`` over
columnar masks (first-match-wins reproduces the precedence), with the
qualifier-set membership tests precomputed once as boolean arrays.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np
import pandas as pd

from . import config as spadlconfig
from .base import _add_dribbles, _fix_clearances, _fix_direction_of_play
from .schema import SPADLSchema

__all__ = ['convert_to_actions']


def convert_to_actions(events: pd.DataFrame, home_team_id: int) -> pd.DataFrame:
    """Convert Opta events of one game to SPADL actions.

    Parameters
    ----------
    events : pd.DataFrame
        Opta events of a single game (see
        :meth:`~socceraction_tpu.data.opta.OptaLoader.events`).
    home_team_id : int
        ID of the game's home team.

    Returns
    -------
    pd.DataFrame
        The game's actions in SPADL format.
    """
    actions = pd.DataFrame(
        {
            'game_id': events['game_id'],
            'original_event_id': events['event_id'].astype(object),
            'period_id': events['period_id'],
            'time_seconds': (
                60 * events['minute']
                + events['second']
                - ((events['period_id'] > 1) * 45 * 60)
                - ((events['period_id'] > 2) * 45 * 60)
                - ((events['period_id'] > 3) * 15 * 60)
                - ((events['period_id'] > 4) * 15 * 60)
            ),
            'team_id': events['team_id'],
            'player_id': events['player_id'],
        }
    )
    for col in ('start_x', 'end_x'):
        actions[col] = events[col].clip(0, 100) / 100 * spadlconfig.field_length
    for col in ('start_y', 'end_y'):
        actions[col] = events[col].clip(0, 100) / 100 * spadlconfig.field_width

    type_name = events['type_name']
    n = len(events)
    # `outcome` is nullable: the reference distinguishes `outcome is False`
    # (type mapping) from plain truthiness (result mapping); None matches
    # neither a strict False nor a truthy success.
    outcome_false = np.fromiter(
        (v is False for v in events['outcome']), dtype=bool, count=n
    )
    outcome_truthy = np.fromiter(
        (bool(v) for v in events['outcome']), dtype=bool, count=n
    )
    has_q = _qualifier_masks(
        events['qualifiers'], [2, 5, 6, 9, 15, 21, 26, 28, 107, 124]
    )

    actions['type_id'] = _determine_type(type_name, outcome_false, has_q)
    actions['result_id'] = _determine_result(type_name, outcome_truthy, has_q)
    actions['bodypart_id'] = np.select(
        [has_q[15], has_q[21]],
        [spadlconfig.HEAD, spadlconfig.OTHER],
        default=spadlconfig.FOOT,
    )

    actions = (
        actions[actions['type_id'] != spadlconfig.NON_ACTION]
        .sort_values(['game_id', 'period_id', 'time_seconds'])
        .reset_index(drop=True)
    )
    actions = _fix_owngoals(actions)
    actions = _fix_direction_of_play(actions, home_team_id)
    actions = _fix_clearances(actions)
    actions['action_id'] = range(len(actions))
    actions = _add_dribbles(actions)
    return SPADLSchema.validate(actions)


def _qualifier_masks(
    qualifiers: pd.Series, ids: List[int]
) -> Dict[int, np.ndarray]:
    """Precompute ``id in qualifiers`` membership per event for each id."""
    sets = [set(q) if isinstance(q, dict) else set() for q in qualifiers]
    return {
        qid: np.fromiter((qid in s for s in sets), dtype=bool, count=len(sets))
        for qid in ids
    }


def _determine_type(
    type_name: pd.Series, outcome_false: np.ndarray, q: Dict[int, np.ndarray]
) -> np.ndarray:
    """Columnar equivalent of the reference's per-event type mapping.

    Qualifiers: 2 cross, 5 freekick, 6 corner, 9 penalty, 26 freekick
    shot, 107 throw-in, 124 goalkick (reference ``spadl/opta.py:103-156``).
    """
    at = spadlconfig.actiontypes.index
    is_pass = type_name.isin(['pass', 'offside pass']).to_numpy()
    is_shot = type_name.isin(['miss', 'post', 'attempt saved', 'goal']).to_numpy()
    conditions = [
        is_pass & q[107],
        is_pass & q[5] & q[2],
        is_pass & q[5],
        is_pass & q[6] & q[2],
        is_pass & q[6],
        is_pass & q[2],
        is_pass & q[124],
        is_pass,
        (type_name == 'take on').to_numpy(),
        (type_name == 'foul').to_numpy() & outcome_false,
        (type_name == 'tackle').to_numpy(),
        type_name.isin(['interception', 'blocked pass']).to_numpy(),
        is_shot & q[9],
        is_shot & q[26],
        is_shot,
        (type_name == 'save').to_numpy(),
        (type_name == 'claim').to_numpy(),
        (type_name == 'punch').to_numpy(),
        (type_name == 'keeper pick-up').to_numpy(),
        (type_name == 'clearance').to_numpy(),
        (type_name == 'ball touch').to_numpy() & outcome_false,
    ]
    choices = [
        at('throw_in'),
        at('freekick_crossed'),
        at('freekick_short'),
        at('corner_crossed'),
        at('corner_short'),
        at('cross'),
        at('goalkick'),
        at('pass'),
        at('take_on'),
        at('foul'),
        at('tackle'),
        at('interception'),
        at('shot_penalty'),
        at('shot_freekick'),
        at('shot'),
        at('keeper_save'),
        at('keeper_claim'),
        at('keeper_punch'),
        at('keeper_pick_up'),
        at('clearance'),
        at('bad_touch'),
    ]
    return np.select(conditions, choices, default=spadlconfig.NON_ACTION)


def _determine_result(
    type_name: pd.Series, outcome_truthy: np.ndarray, q: Dict[int, np.ndarray]
) -> np.ndarray:
    """Columnar equivalent of the reference's per-event result mapping.

    Qualifier 28 marks an own goal (reference ``spadl/opta.py:81-100``).
    """
    conditions = [
        (type_name == 'offside pass').to_numpy(),
        (type_name == 'foul').to_numpy(),
        type_name.isin(['attempt saved', 'miss', 'post']).to_numpy(),
        ((type_name == 'goal') & q[28]).to_numpy(),
        (type_name == 'goal').to_numpy(),
        (type_name == 'ball touch').to_numpy(),
        outcome_truthy,
    ]
    choices = [
        spadlconfig.OFFSIDE,
        spadlconfig.FAIL,
        spadlconfig.FAIL,
        spadlconfig.OWNGOAL,
        spadlconfig.SUCCESS,
        spadlconfig.FAIL,
        spadlconfig.SUCCESS,
    ]
    return np.select(conditions, choices, default=spadlconfig.FAIL)


def _fix_owngoals(actions: pd.DataFrame) -> pd.DataFrame:
    """Mirror own-goal end coordinates and retype them as bad touches."""
    owngoal = (actions['result_id'] == spadlconfig.OWNGOAL) & (
        actions['type_id'] == spadlconfig.SHOT
    )
    actions.loc[owngoal, 'end_x'] = (
        spadlconfig.field_length - actions.loc[owngoal, 'end_x']
    )
    actions.loc[owngoal, 'end_y'] = (
        spadlconfig.field_width - actions.loc[owngoal, 'end_y']
    )
    actions.loc[owngoal, 'type_id'] = spadlconfig.actiontypes.index('bad_touch')
    return actions


# Deprecated pre-1.2 re-exports (reference ``spadl/opta.py:166-248``): the
# loader and raw-data schemas moved to :mod:`socceraction_tpu.data.opta`
# but remain importable here with a DeprecationWarning.
from ._deprecated import deprecated_reexports as _deprecated_reexports

__getattr__ = _deprecated_reexports(
    __name__,
    'socceraction_tpu.data.opta',
    (
        'OptaLoader',
        'OptaCompetitionSchema',
        'OptaGameSchema',
        'OptaPlayerSchema',
        'OptaTeamSchema',
        'OptaEventSchema',
    ),
)
