"""Schema of a SPADL action table.

Parity: reference ``socceraction/spadl/schema.py:10-33`` (pandera model),
re-expressed with the dependency-free schema core in
:mod:`socceraction_tpu.schema`. The same field specs drive tensor packing
(dtype selection and range asserts) in :mod:`socceraction_tpu.core.batch`.
"""

from __future__ import annotations

from . import config as spadlconfig
from ..schema import Field, Schema

SPADLSchema = Schema(
    fields={
        'game_id': Field(),
        'original_event_id': Field(nullable=True),
        'action_id': Field(dtype='int64'),
        'period_id': Field(dtype='int64', ge=1, le=5),
        'time_seconds': Field(dtype='float64', ge=0),
        'team_id': Field(),
        'player_id': Field(),
        'start_x': Field(dtype='float64', ge=0, le=spadlconfig.field_length),
        'start_y': Field(dtype='float64', ge=0, le=spadlconfig.field_width),
        'end_x': Field(dtype='float64', ge=0, le=spadlconfig.field_length),
        'end_y': Field(dtype='float64', ge=0, le=spadlconfig.field_width),
        'bodypart_id': Field(dtype='int64', isin=range(len(spadlconfig.bodyparts))),
        'bodypart_name': Field(dtype='str', isin=spadlconfig.bodyparts, required=False),
        'type_id': Field(dtype='int64', isin=range(len(spadlconfig.actiontypes))),
        'type_name': Field(dtype='str', isin=spadlconfig.actiontypes, required=False),
        'result_id': Field(dtype='int64', isin=range(len(spadlconfig.results))),
        'result_name': Field(dtype='str', isin=spadlconfig.results, required=False),
    },
    strict=False,
)
