"""Wyscout (API v3) event stream → SPADL converter.

Parity: reference ``socceraction/spadl/wyscout_v3.py`` — a work-in-progress
fork-only converter for the flat-column Wyscout v3 feed. The reference file
is a spec sketch, not working code (its ``convert_to_actions`` returns the
*events* frame, reference ``spadl/wyscout_v3.py:54``; dribble synthesis and
schema validation are commented out, ``:52-55``; ``determine_type_id``
returns string names instead of ids, ``:832-833``). This module implements
the *intended* pipeline to completion, vectorized (``np.select`` over
columnar masks instead of row-wise ``DataFrame.apply``), producing a valid
SPADL frame like every other provider converter:

1. start/end coordinate extraction per event family
   (reference ``:76-103``), shot end-coordinate estimation from
   ``shot_goal_zone`` (``:155-203``)
2. event surgery on the raw (0-100)² Wyscout pitch: duel →
   dribble/take_on rewriting with duel-outcome flags (``:226-304``),
   interception (``:387-412``) and fairplay (``:414-447``) coordinates,
   offside attachment (``:513-544``), touch (``:590-658``) and
   acceleration (``:661-723``) success inference, end-coordinate
   backfill for remaining move actions (``:449-475``)
3. columnar type/result/bodypart determination (``:749-881``) mapped onto
   the SPADL id spaces (the WIP leaves v3 strings like ``acceleration``
   and ``goal_kick`` that are not SPADL vocabulary; here they map to
   ``dribble``/``goalkick``)
4. coordinate rescale to 105×68 m with y flip (``:901-937``),
   keeper-save inversion (``:979-1004``), foul end-coordinate repair
   (``:960-976``, defined but never wired up in the WIP — required for a
   schema-valid frame)
5. shared post-processing: direction of play, clearances, action ids,
   dribble synthesis, schema validation (upstream ``_sa`` semantics)

The xA enrichment (``:206-223``) never lands in the SPADL frame itself:
:func:`fix_wyscout_events` attaches it to the *events* when the feed
carries ``shot_xg`` (reference behavior) and skips it otherwise, and
:func:`add_expected_assists` stays callable on its own.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import pandas as pd

from . import config as spadlconfig
from .base import (
    _add_dribbles,
    _fix_clearances,
    _fix_direction_of_play,
    _single_event,
)
from .schema import SPADLSchema

# Keeper-save mirroring is identical across feed versions; the v2 module
# owns the implementation and this module re-exports it.
from .wyscout import fix_keeper_save_coordinates  # noqa: F401

__all__ = [
    'convert_to_actions',
    'add_expected_assists',
    'make_new_positions',
    'fix_wyscout_events',
    'create_shot_coordinates',
    'convert_duels',
    'insert_interception_coordinates',
    'insert_fairplay_coordinates',
    'insert_coordinates_edge_cases',
    'add_offside_variable',
    'convert_touches',
    'convert_accelerations',
    'create_df_actions',
    'determine_bodypart_id',
    'determine_type_id',
    'determine_result_id',
    'fix_actions',
    'fix_foul_coordinates',
    'fix_keeper_save_coordinates',
]

#: matchPeriod string → SPADL period id.
_PERIODS = {'1H': 1, '2H': 2, 'E1': 3, 'E2': 4, 'P': 5}

#: shot_goal_zone → estimated (end_x, end_y) on the (0-100)² Wyscout pitch
#: (reference spadl/wyscout_v3.py:166-196).
_GOAL_ZONE_COORDS = {
    **dict.fromkeys(['gt', 'gc', 'gb'], (100.0, 50.0)),
    **dict.fromkeys(['gtr', 'gr', 'gbr'], (100.0, 55.0)),
    **dict.fromkeys(['gtl', 'gl', 'glb'], (100.0, 45.0)),
    **dict.fromkeys(['ot', 'pt'], (100.0, 50.0)),
    **dict.fromkeys(['otr', 'or', 'obr'], (100.0, 60.0)),
    **dict.fromkeys(['otl', 'ol', 'olb'], (100.0, 40.0)),
    **dict.fromkeys(['ptl', 'pl', 'plb'], (100.0, 55.38)),
    **dict.fromkeys(['ptr', 'pr', 'pbr'], (100.0, 44.62)),
}

#: v3 primaries whose pass_end_location is the action's end point
#: (reference spadl/wyscout_v3.py:80-82).
_PASS_LIKE_PRIMARIES = [
    'pass', 'clearance', 'throw_in', 'interception', 'goal_kick',
    'free_kick', 'corner', 'fairplay',
]

#: v3 primaries that may carry the ball (reference :87).
_CARRY_PRIMARIES = ['touch', 'duel', 'acceleration', 'goalkeeper_exit']

#: "possession continues" next-event primaries for touch/acceleration
#: success inference (reference :609-613).
_KEEP_PRIMARIES = [
    'pass', 'shot', 'acceleration', 'clearance', 'touch', 'interception',
]
#: "possession lost / play stops" next-event primaries (reference :614-617).
#: Note 'offside' is unreachable here — offside rows are dropped by
#: ``add_offside_variable`` before touch/acceleration inference runs, exactly
#: like the reference surgery order (``:144-146``); kept for parity.
_LOSE_PRIMARIES = ['game_interruption', 'infraction', 'offside', 'shot_against']


def _col(events: pd.DataFrame, name: str, default: Any = 0) -> pd.Series:
    """Column accessor tolerant of feeds that omit optional v3 columns."""
    if name in events.columns:
        col = events[name]
        if default == 0 or default is False:
            return col.fillna(default).infer_objects()
        return col
    return pd.Series([default] * len(events), index=events.index)


def _str_col(events: pd.DataFrame, name: str) -> pd.Series:
    return _col(events, name, default='').astype(str).replace('nan', '')


def convert_to_actions(
    events: pd.DataFrame, home_team_id: Optional[int] = None
) -> pd.DataFrame:
    """Convert Wyscout v3 events of one game to SPADL actions.

    Parameters
    ----------
    events : pd.DataFrame
        Flat-column Wyscout v3 events of a single game (camelCase feed
        fields flattened to snake_case with ``_`` separators, e.g.
        ``pass.endLocation.x`` → ``pass_end_location_x``).
    home_team_id : int, optional
        ID of the game's home team. May be omitted when the frame carries a
        ``home_team_id`` column (the v3 feed convention).

    Returns
    -------
    pd.DataFrame
        The game's actions in SPADL format.
    """
    if home_team_id is None:
        if 'home_team_id' not in events.columns:
            raise ValueError(
                'home_team_id must be given (argument or events column)'
            )
        home_team_id = events['home_team_id'].iloc[0]
    events = events.reset_index(drop=True).copy()
    events = make_new_positions(events)
    events = fix_wyscout_events(events)
    actions = create_df_actions(events)
    actions = fix_actions(actions)
    actions = _fix_direction_of_play(actions, home_team_id)
    actions = _fix_clearances(actions)
    actions['action_id'] = range(len(actions))
    actions = _add_dribbles(actions)
    return SPADLSchema.validate(actions)


def fix_wyscout_events(df_events: pd.DataFrame) -> pd.DataFrame:
    """Event surgery on the raw (0-100)² Wyscout-v3 pitch.

    Chains the rewriting stages in the reference's order
    (``spadl/wyscout_v3.py:128-153``). :func:`add_expected_assists`
    requires a ``shot_xg`` feed column that not every v3 export carries,
    so it runs conditionally: feeds that carry the column get the
    reference behavior (the xA column on the returned events), feeds
    that don't simply skip the stage instead of erroring.
    """
    df_events = create_shot_coordinates(df_events)
    if 'shot_xg' in df_events.columns:
        df_events = add_expected_assists(df_events)
    df_events = convert_duels(df_events)
    df_events = insert_interception_coordinates(df_events)
    df_events = add_offside_variable(df_events)
    df_events = convert_touches(df_events)
    df_events = convert_accelerations(df_events)
    df_events = insert_fairplay_coordinates(df_events)
    df_events = insert_coordinates_edge_cases(df_events)
    return df_events


def add_expected_assists(events: pd.DataFrame) -> pd.DataFrame:
    """Attach xA to shot assists: the assisted shot's xG.

    Reference ``spadl/wyscout_v3.py:206-223``. Returns the events frame
    with a ``metric_xa`` column (NaN for non-assists).
    """
    events = events.copy()
    nxt = events.shift(-1)
    is_assist = _col(events, 'type_shot_assist') == 1
    events.loc[is_assist, 'metric_xa'] = nxt['shot_xg']
    return events


# ---------------------------------------------------------------------------
# coordinate extraction + event surgery (raw 0-100 pitch)
# ---------------------------------------------------------------------------


def make_new_positions(events: pd.DataFrame) -> pd.DataFrame:
    """Select start/end coordinates per event family (reference :76-103).

    Blocked passes end where they start; pass-like events end at
    ``pass_end_location``; carries end at ``carry_end_location``; everything
    else has no end point yet.
    """
    loc_x = _col(events, 'location_x', np.nan).astype(float)
    loc_y = _col(events, 'location_y', np.nan).astype(float)
    primary = _str_col(events, 'type_primary')
    blocked = _str_col(events, 'pass_height') == 'blocked'
    pass_like = primary.isin(_PASS_LIKE_PRIMARIES)
    carry = primary.isin(_CARRY_PRIMARIES) & (_col(events, 'type_carry') == 1)

    events['start_x'] = loc_x
    events['start_y'] = loc_y
    events['end_x'] = np.select(
        [blocked, pass_like, carry],
        [
            loc_x,
            _col(events, 'pass_end_location_x', np.nan).astype(float),
            _col(events, 'carry_end_location_x', np.nan).astype(float),
        ],
        default=np.nan,
    )
    events['end_y'] = np.select(
        [blocked, pass_like, carry],
        [
            loc_y,
            _col(events, 'pass_end_location_y', np.nan).astype(float),
            _col(events, 'carry_end_location_y', np.nan).astype(float),
        ],
        default=np.nan,
    )
    return events


def create_shot_coordinates(events: pd.DataFrame) -> pd.DataFrame:
    """Estimate shot end points from the goal-zone code (reference :155-203)."""
    zone = _str_col(events, 'shot_goal_zone')
    known = zone.map(lambda z: _GOAL_ZONE_COORDS.get(z))
    has = known.notna()
    events.loc[has, 'end_x'] = [c[0] for c in known[has]]
    events.loc[has, 'end_y'] = [c[1] for c in known[has]]
    blocked = zone == 'bc'
    events.loc[blocked, 'end_x'] = events.loc[blocked, 'start_x']
    events.loc[blocked, 'end_y'] = events.loc[blocked, 'start_y']
    return events


def convert_duels(events: pd.DataFrame) -> pd.DataFrame:
    """Duels → dribble/take_on with outcome flags (reference :226-304).

    A ground duel of duel-type ``dribble`` becomes a dribbling action
    (``take_on`` when the take-on flag is set). The duel outcome is won when
    any possession/progress flag is set. End coordinates come from the next
    event — or the one after it when the next event is the duel's paired
    opposite-side record — mirrored when that event belongs to the other
    team.
    """
    nxt_id = events['id'].shift(-1)
    nxt_team = events['team_id'].shift(-1)
    nxt2_team = events['team_id'].shift(-2)
    primary = _str_col(events, 'type_primary')
    is_duel = primary == 'duel'
    is_dribble = _str_col(events, 'ground_duel_duel_type') == 'dribble'
    is_take_on = (_col(events, 'ground_duel_take_on') == 1.0) & is_dribble
    related_next = (
        _col(events, 'ground_duel_related_duel_id', np.nan) == nxt_id
    ) | (_col(events, 'aerial_duel_related_duel_id', np.nan) == nxt_id)
    same_team_1 = events['team_id'] == nxt_team
    same_team_2 = events['team_id'] == nxt2_team
    is_carry = _col(events, 'type_carry') == 1

    won = (
        (_col(events, 'ground_duel_kept_possession') == 1.0)
        | (_col(events, 'ground_duel_recovered_possession') == 1.0)
        | (_col(events, 'aerial_duel_first_touch') == 1.0)
        | (_col(events, 'ground_duel_progressed_with_ball') == 1.0)
        | (_col(events, 'ground_duel_stopped_progress') == 1.0)
    )
    events['duel_success'] = np.where(is_duel, won, np.nan)
    events['duel_failure'] = np.where(is_duel, ~won, np.nan)

    events.loc[is_duel & is_dribble, 'type_primary'] = 'dribble'
    events.loc[is_duel & is_take_on, 'type_primary'] = 'take_on'

    # end point: next event's location (next2 when next is the paired duel
    # record), mirrored for the other team
    nxt_x = _col(events, 'location_x', np.nan).shift(-1)
    nxt_y = _col(events, 'location_y', np.nan).shift(-1)
    nxt2_x = _col(events, 'location_x', np.nan).shift(-2)
    nxt2_y = _col(events, 'location_y', np.nan).shift(-2)
    base = ~is_carry & is_duel
    cases_x = [
        (base & ~related_next & same_team_1, nxt_x),
        (base & ~related_next & ~same_team_1, 100 - nxt_x),
        (base & related_next & same_team_2, nxt2_x),
        (base & related_next & ~same_team_2, 100 - nxt2_x),
    ]
    cases_y = [
        (base & ~related_next & same_team_1, nxt_y),
        (base & ~related_next & ~same_team_1, 100 - nxt_y),
        (base & related_next & same_team_2, nxt2_y),
        (base & related_next & ~same_team_2, 100 - nxt2_y),
    ]
    for mask, val in cases_x:
        events.loc[mask, 'end_x'] = val[mask]
    for mask, val in cases_y:
        events.loc[mask, 'end_y'] = val[mask]
    return events.reset_index(drop=True)


def insert_interception_coordinates(events: pd.DataFrame) -> pd.DataFrame:
    """Interceptions end at the next event's start (reference :387-412)."""
    nxt_x = events['start_x'].shift(-1)
    nxt_y = events['start_y'].shift(-1)
    is_interception = _str_col(events, 'type_primary') == 'interception'
    same_team = events['team_id'] == events['team_id'].shift(-1)
    events.loc[is_interception & same_team, 'end_x'] = nxt_x
    events.loc[is_interception & same_team, 'end_y'] = nxt_y
    events.loc[is_interception & ~same_team, 'end_x'] = 100 - nxt_x
    events.loc[is_interception & ~same_team, 'end_y'] = 100 - nxt_y
    return events


def add_offside_variable(events: pd.DataFrame) -> pd.DataFrame:
    """Mark passes followed by an offside; drop offside events (reference :513-544)."""
    nxt_primary = events['type_primary'].astype(str).shift(-1)
    primary = _str_col(events, 'type_primary')
    events['offside'] = 0
    offside_pass = nxt_primary.eq('offside') & (primary == 'pass')
    events.loc[offside_pass, 'offside'] = 1
    events = events[primary != 'offside']
    return events.reset_index(drop=True)


def convert_touches(events: pd.DataFrame) -> pd.DataFrame:
    """Touch success from the next event (reference :590-658).

    A touch keeps possession when the same team acts next (or a duel
    follows); it loses possession when play stops or the other team acts.
    Non-carry touches end where the next event starts (mirrored for the
    other team).
    """
    return _infer_followup_results(events, 'touch', 'touch_success', 'touch_fail')


def convert_accelerations(events: pd.DataFrame) -> pd.DataFrame:
    """Acceleration success from the next event (reference :661-723)."""
    return _infer_followup_results(
        events, 'acceleration', 'acceleration_success', 'acceleration_fail'
    )


def _infer_followup_results(
    events: pd.DataFrame, primary_type: str, success_col: str, fail_col: str
) -> pd.DataFrame:
    primary = _str_col(events, 'type_primary')
    nxt_primary = events['type_primary'].astype(str).shift(-1)
    is_type = primary == primary_type
    is_carry = _col(events, 'type_carry') == 1
    keeps = nxt_primary.isin(_KEEP_PRIMARIES)
    loses = nxt_primary.isin(_LOSE_PRIMARIES)
    next_duel = nxt_primary == 'duel'
    same_team = events['team_id'] == events['team_id'].shift(-1)

    events[success_col] = pd.Series(np.nan, index=events.index, dtype=object)
    events[fail_col] = pd.Series(np.nan, index=events.index, dtype=object)
    success = (is_type & next_duel) | (is_type & same_team & keeps) | (
        is_type & ~same_team & loses
    )
    fail = (is_type & same_team & loses) | (is_type & ~same_team & keeps)
    events.loc[success, success_col] = True
    events.loc[success, fail_col] = False
    events.loc[fail, success_col] = False
    events.loc[fail, fail_col] = True

    nxt_x = _col(events, 'location_x', np.nan).shift(-1)
    nxt_y = _col(events, 'location_y', np.nan).shift(-1)
    move = ~is_carry & is_type
    events.loc[move & same_team, 'end_x'] = nxt_x[move & same_team]
    events.loc[move & same_team, 'end_y'] = nxt_y[move & same_team]
    events.loc[move & ~same_team, 'end_x'] = (100 - nxt_x)[move & ~same_team]
    events.loc[move & ~same_team, 'end_y'] = (100 - nxt_y)[move & ~same_team]
    return events


def insert_fairplay_coordinates(events: pd.DataFrame) -> pd.DataFrame:
    """Give game interruptions before fairplay events coordinates (reference :414-447)."""
    primary = _str_col(events, 'type_primary')
    prv_x = events['start_x'].shift(1)
    prv_y = events['start_y'].shift(1)
    nxt_primary = events['type_primary'].astype(str).shift(-1)
    nxt2_primary = events['type_primary'].astype(str).shift(-2)
    interruption = (primary == 'game_interruption') & (nxt_primary == 'fairplay')
    same_team_prev = events['team_id'] == events['team_id'].shift(1)
    for cols, src in ((['end_x', 'start_x'], prv_x), (['end_y', 'start_y'], prv_y)):
        mask = interruption & same_team_prev
        events.loc[mask, cols] = np.stack([src[mask]] * 2, axis=1)
        mask = interruption & ~same_team_prev
        events.loc[mask, cols] = np.stack([(100 - src)[mask]] * 2, axis=1)
    # the event before such an interruption ends where it started
    before = (nxt_primary == 'game_interruption') & (nxt2_primary == 'fairplay')
    events.loc[before, 'end_x'] = events.loc[before, 'start_x']
    events.loc[before, 'end_y'] = events.loc[before, 'start_y']
    return events


def insert_coordinates_edge_cases(events: pd.DataFrame) -> pd.DataFrame:
    """Remaining move actions without an end point end in place (reference :449-475)."""
    primary = _str_col(events, 'type_primary')
    move = primary.isin(['pass', 'carry', 'cross', 'acceleration', 'dribble', 'take_on'])
    fix = move & events['end_x'].isna()
    events.loc[fix, 'end_x'] = events.loc[fix, 'start_x']
    fix = move & events['end_y'].isna()
    events.loc[fix, 'end_y'] = events.loc[fix, 'start_y']
    return events


# ---------------------------------------------------------------------------
# SPADL frame construction
# ---------------------------------------------------------------------------


def _period_ids(events: pd.DataFrame) -> pd.Series:
    if 'period_id' in events.columns:
        return events['period_id'].astype(np.int64)
    return _str_col(events, 'match_period').map(_PERIODS).astype(np.int64)


def _time_seconds(events: pd.DataFrame) -> pd.Series:
    if 'milliseconds' in events.columns:
        return events['milliseconds'] / 1000.0
    # v3 feeds carry absolute minute/second; make them period-relative
    # (periods restart at 45'/90'/105' like reference spadl/statsbomb.py:39-46)
    period = _period_ids(events)
    offset = period.map({1: 0, 2: 45, 3: 90, 4: 105, 5: 120}).fillna(0) * 60
    total = _col(events, 'minute').astype(float) * 60 + _col(events, 'second').astype(float)
    return (total - offset).clip(lower=0.0)


def create_df_actions(events: pd.DataFrame) -> pd.DataFrame:
    """Flat v3 events -> SPADL action frame (reference ``:725-745``).

    Applies the type/result/bodypart decision tables, drops non-actions
    and orders by (game, period, time); coordinates are fixed later by
    :func:`fix_actions`.
    """
    primary = _str_col(events, 'type_primary')
    type_id = _determine_type_ids(events, primary)
    result_id = _determine_result_ids(events, primary, type_id)
    bodypart_id = _determine_bodypart_ids(events, primary)

    actions = pd.DataFrame(
        {
            'game_id': events['match_id']
            if 'match_id' in events.columns
            else _col(events, 'game_id', 0),
            'original_event_id': events['id'].astype(object),
            'period_id': _period_ids(events),
            'time_seconds': _time_seconds(events),
            'team_id': events['team_id'],
            'player_id': events['player_id'],
            'start_x': events['start_x'],
            'start_y': events['start_y'],
            'end_x': events['end_x'],
            'end_y': events['end_y'],
            'type_id': type_id,
            'result_id': result_id,
            'bodypart_id': bodypart_id,
        }
    )
    actions = actions[actions['type_id'] != spadlconfig.NON_ACTION]
    actions = actions.sort_values(
        ['game_id', 'period_id', 'time_seconds'], kind='stable'
    ).reset_index(drop=True)
    return actions


def _determine_type_ids(events: pd.DataFrame, primary: pd.Series) -> pd.Series:
    """SPADL type ids (reference :772-833 completed onto the SPADL vocab).

    First-match-wins ``np.select`` reproduces the if/elif precedence. The
    WIP's pass-through branch leaves non-SPADL names (``acceleration``,
    ``goal_kick``, ``touch``, ``carry``); they map to their SPADL
    equivalents here (hinted at by the reference's commented branches
    ``:806-807`` and ``:820-821``).
    """
    t = spadlconfig.actiontypes.index
    infraction_type = _str_col(events, 'infraction_type')
    conditions = [
        (primary == 'pass') & (_col(events, 'type_cross') == 1),
        primary == 'pass',
        primary == 'throw_in',
        (primary == 'corner') & (_col(events, 'pass_length').astype(float) > 25),
        primary == 'corner',
        (primary == 'free_kick') & (_col(events, 'type_free_kick_cross') == 1),
        (primary == 'free_kick') & (_col(events, 'type_free_kick_shot') == 1),
        primary == 'free_kick',
        (primary == 'infraction')
        & infraction_type.isin(['hand_foul', 'regular_foul']),
        primary == 'penalty',
        _col(events, 'type_save') == 1,
        (primary == 'touch') & (_col(events, 'type_carry') == 1),
        # both duel-derived primaries (dribbling duel, flagged take-on) are a
        # SPADL take_on; the finer split only matters for the xT-v3 move set
        primary.isin(['take_on', 'dribble']),
        primary == 'interception',
        primary == 'shot',
        primary == 'clearance',
        primary == 'goal_kick',
        primary == 'acceleration',
        primary == 'touch',
    ]
    choices = [
        t('cross'),
        t('pass'),
        t('throw_in'),
        t('corner_crossed'),
        t('corner_short'),
        t('freekick_crossed'),
        t('shot_freekick'),
        t('freekick_short'),
        t('foul'),
        t('shot_penalty'),
        t('keeper_save'),
        t('dribble'),
        t('take_on'),
        t('interception'),
        t('shot'),
        t('clearance'),
        t('goalkick'),
        t('dribble'),
        t('dribble'),
    ]
    return pd.Series(
        np.select(conditions, choices, default=spadlconfig.NON_ACTION).astype(np.int64),
        index=events.index,
    )


def _determine_result_ids(
    events: pd.DataFrame, primary: pd.Series, type_id: pd.Series
) -> pd.Series:
    """SPADL result ids (reference :836-881 precedence)."""
    pass_accurate = _col(events, 'pass_accurate', np.nan)
    shot_like = type_id.isin(
        [spadlconfig.SHOT, spadlconfig.SHOT_FREEKICK, spadlconfig.SHOT_PENALTY]
    )
    pass_like = type_id.isin(
        [
            spadlconfig.actiontypes.index(n)
            for n in (
                'pass', 'cross', 'throw_in', 'goalkick', 'freekick_short',
                'freekick_crossed', 'corner_crossed', 'corner_short',
            )
        ]
    )
    conditions = [
        _col(events, 'offside') == 1,
        type_id == spadlconfig.actiontypes.index('foul'),
        _col(events, 'shot_own_goal') == 1,
        _col(events, 'touch_success', np.nan) == True,  # noqa: E712
        _col(events, 'touch_fail', np.nan) == True,  # noqa: E712
        _col(events, 'acceleration_success', np.nan) == True,  # noqa: E712
        _col(events, 'acceleration_fail', np.nan) == True,  # noqa: E712
        _col(events, 'shot_is_goal') == 1,
        _col(events, 'duel_success', np.nan) == True,  # noqa: E712
        _col(events, 'duel_failure', np.nan) == True,  # noqa: E712
        shot_like,
        pass_like & (pass_accurate == 1),
        pass_like & (pass_accurate == 0),
    ]
    choices = [
        spadlconfig.OFFSIDE,
        spadlconfig.SUCCESS,
        spadlconfig.OWNGOAL,
        spadlconfig.SUCCESS,
        spadlconfig.FAIL,
        spadlconfig.SUCCESS,
        spadlconfig.FAIL,
        spadlconfig.SUCCESS,
        spadlconfig.SUCCESS,
        spadlconfig.FAIL,
        spadlconfig.FAIL,
        spadlconfig.SUCCESS,
        spadlconfig.FAIL,
    ]
    # clearance/interception/keeper_save and the no-information fallback are
    # all "success" (reference :876-881)
    return pd.Series(
        np.select(conditions, choices, default=spadlconfig.SUCCESS).astype(np.int64),
        index=events.index,
    )


def _determine_bodypart_ids(events: pd.DataFrame, primary: pd.Series) -> pd.Series:
    """SPADL bodypart ids (reference :749-769 precedence)."""
    other = (
        (_col(events, 'type_save') == 1)
        | (primary == 'throw_in')
        | (_col(events, 'type_hand_pass') == 1)
        | (_str_col(events, 'infraction_type') == 'hand_foul')
    )
    head = (
        (_col(events, 'type_head_pass') == 1)
        | (_col(events, 'type_head_shot') == 1)
        | (_col(events, 'type_aerial_duel') == 1)
    )
    return pd.Series(
        np.select(
            [other, head], [spadlconfig.OTHER, spadlconfig.HEAD],
            default=spadlconfig.FOOT,
        ).astype(np.int64),
        index=events.index,
    )


def fix_actions(actions: pd.DataFrame) -> pd.DataFrame:
    """(0-100)² → 105×68 m with y flip, plus coordinate repairs.

    Reference ``:901-937`` (rescale + keeper-save inversion) and ``:960-976``
    (foul end coordinates; required for schema validity).
    """
    actions = actions.copy()
    length, width = spadlconfig.field_length, spadlconfig.field_width
    actions['start_x'] = (actions['start_x'] * length / 100).clip(0, length)
    actions['end_x'] = (actions['end_x'] * length / 100).clip(0, length)
    actions['start_y'] = ((100 - actions['start_y']) * width / 100).clip(0, width)
    actions['end_y'] = ((100 - actions['end_y']) * width / 100).clip(0, width)
    actions = fix_foul_coordinates(actions)
    actions = fix_keeper_save_coordinates(actions)
    return actions


def fix_foul_coordinates(df_actions: pd.DataFrame) -> pd.DataFrame:
    """Fouls (and any other still-endless action) end where they start."""
    no_end = df_actions['end_x'].isna() | df_actions['end_y'].isna()
    df_actions.loc[no_end, 'end_x'] = df_actions.loc[no_end, 'start_x']
    df_actions.loc[no_end, 'end_y'] = df_actions.loc[no_end, 'start_y']
    return df_actions





def determine_type_id(event: Any) -> int:
    """SPADL action-type id of one Wyscout-v3 event (row-wise reference API).

    Documented deviation: the reference's WIP ``determine_type_id`` returns
    string *names* (``spadl/wyscout_v3.py:832-833``, see SURVEY.md §0); the
    intended semantics — and this implementation — return the vocabulary id.
    """
    ev = _single_event(event)
    return int(_determine_type_ids(ev, _str_col(ev, 'type_primary')).iloc[0])


def determine_result_id(event: Any) -> int:
    """SPADL result id of one Wyscout-v3 event (row-wise reference API)."""
    ev = _single_event(event)
    primary = _str_col(ev, 'type_primary')
    type_id = _determine_type_ids(ev, primary)
    return int(_determine_result_ids(ev, primary, type_id).iloc[0])


def determine_bodypart_id(event: Any) -> int:
    """SPADL bodypart id of one Wyscout-v3 event (row-wise reference API)."""
    ev = _single_event(event)
    return int(_determine_bodypart_ids(ev, _str_col(ev, 'type_primary')).iloc[0])
