"""Deprecated re-export shims for the ``spadl`` provider modules.

The reference re-exports each provider's loader and schemas from its SPADL
converter module with a :class:`DeprecationWarning` (e.g.
``socceraction/spadl/statsbomb.py:325-413``) so pre-1.2 imports like
``from socceraction.spadl.statsbomb import StatsBombLoader`` keep working.
This module provides one factory that gives a converter module a PEP 562
``__getattr__`` doing the same: the named symbols resolve lazily from the
corresponding ``socceraction_tpu.data`` subpackage, with the same warning.
"""

from __future__ import annotations

import importlib
import warnings
from typing import Any, Callable, Tuple


def deprecated_reexports(
    spadl_module: str, data_module: str, names: Tuple[str, ...]
) -> Callable[[str], Any]:
    """Build a module ``__getattr__`` forwarding ``names`` to ``data_module``.

    Parameters
    ----------
    spadl_module : str
        Fully qualified name of the converter module (for the warning text).
    data_module : str
        Fully qualified name of the data subpackage the names live in now.
    names : tuple of str
        The deprecated public names to forward.

    Returns
    -------
    callable
        A ``__getattr__(name)`` implementation for the converter module.
    """

    def __getattr__(name: str) -> Any:
        if name in names:
            warnings.warn(
                f'{spadl_module}.{name} is deprecated, '
                f'use {data_module}.{name} instead',
                DeprecationWarning,
                stacklevel=2,
            )
            return getattr(importlib.import_module(data_module), name)
        raise AttributeError(
            f'module {spadl_module!r} has no attribute {name!r}'
        )

    return __getattr__
