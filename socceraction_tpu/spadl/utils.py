"""Utility functions for working with SPADL action tables.

Parity: reference ``socceraction/spadl/utils.py:8-57`` (`add_names` and the
upstream two-argument `play_left_to_right_sa`, which is the canonical
semantics -- see SURVEY.md section 0).
"""

from __future__ import annotations

import pandas as pd

from . import config as spadlconfig
from .base import _fix_direction_of_play
from .schema import SPADLSchema


def add_names(actions: pd.DataFrame) -> pd.DataFrame:
    """Add 'type_name', 'result_name' and 'bodypart_name' columns.

    Any pre-existing name columns are replaced.
    """
    out = (
        actions.drop(columns=['type_name', 'result_name', 'bodypart_name'], errors='ignore')
        .merge(spadlconfig.actiontypes_df(), how='left')
        .merge(spadlconfig.results_df(), how='left')
        .merge(spadlconfig.bodyparts_df(), how='left')
    )
    return SPADLSchema.validate(out)


def play_left_to_right(actions: pd.DataFrame, home_team_id: int) -> pd.DataFrame:
    """Mirror the away team's actions so every team plays left-to-right.

    Parameters
    ----------
    actions : pd.DataFrame
        The SPADL actions of one game.
    home_team_id
        The ID of the game's home team.

    Returns
    -------
    pd.DataFrame
        A copy with away-team coordinates mirrored in both axes.
    """
    return _fix_direction_of_play(actions.copy(), home_team_id)


#: Alias kept for reference compatibility: upstream renamed the canonical
#: two-argument function to ``play_left_to_right_sa`` when the fork
#: repurposed the unsuffixed name (reference ``spadl/utils.py:31-57``,
#: SURVEY.md section 0). Here the unsuffixed name already carries the
#: canonical semantics, so both names point at the same function.
play_left_to_right_sa = play_left_to_right
