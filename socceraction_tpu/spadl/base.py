"""Shared post-processing passes used by all event-stream -> SPADL converters.

These implement the upstream (``_sa``) semantics of the reference fork -- see
reference ``socceraction/spadl/base.py:12-19`` (`_fix_clearances_sa`),
``:39-46`` (`_fix_direction_of_play_sa`) and ``:49-93`` (`_add_dribbles`).
The fork's unsuffixed variants expect raw Wyscout-v3 frames and are broken
for SPADL frames; the canonical behavior rebuilt here is the suffixed one.

All three passes are host-side, row-count-changing or in-place frame surgery
and therefore live on the pandas side of the host/device boundary: the packed
tensor pipeline (:mod:`socceraction_tpu.core.batch`) consumes their output.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from ..config import MAX_DRIBBLE_DURATION, MAX_DRIBBLE_LENGTH, MIN_DRIBBLE_LENGTH
from . import config as spadlconfig

min_dribble_length: float = MIN_DRIBBLE_LENGTH
max_dribble_length: float = MAX_DRIBBLE_LENGTH
max_dribble_duration: float = MAX_DRIBBLE_DURATION


def _fix_clearances(actions: pd.DataFrame) -> pd.DataFrame:
    """Set each clearance's end location to the next action's start location.

    The last row acts as its own successor (a trailing clearance's end
    location becomes its own start location).
    """
    next_start_x = np.append(actions['start_x'].to_numpy()[1:], np.nan)
    next_start_y = np.append(actions['start_y'].to_numpy()[1:], np.nan)
    if len(actions):
        next_start_x[-1] = actions['start_x'].iloc[-1]
        next_start_y[-1] = actions['start_y'].iloc[-1]
    clearance = (actions['type_id'] == spadlconfig.CLEARANCE).to_numpy()
    actions.loc[clearance, 'end_x'] = next_start_x[clearance]
    actions.loc[clearance, 'end_y'] = next_start_y[clearance]
    return actions


def _fix_direction_of_play(actions: pd.DataFrame, home_team_id: int) -> pd.DataFrame:
    """Mirror the away team's coordinates so both teams play left-to-right."""
    away = (actions['team_id'] != home_team_id).to_numpy()
    for col, extent in (
        ('start_x', spadlconfig.field_length),
        ('end_x', spadlconfig.field_length),
        ('start_y', spadlconfig.field_width),
        ('end_y', spadlconfig.field_width),
    ):
        actions.loc[away, col] = extent - actions.loc[away, col].to_numpy()
    return actions


def _add_dribbles(actions: pd.DataFrame) -> pd.DataFrame:
    """Synthesize dribble actions between consecutive same-team actions.

    A dribble row is inserted between action i and i+1 when the same team
    performs both, the gap between i's end and (i+1)'s start is 3-60 m,
    less than 10 s elapses, and both are in the same period. The inserted
    row gets ``action_id = i + 0.1`` so the final sort slots it between the
    two, after which action ids are renumbered 0..n-1.

    Matches reference ``socceraction/spadl/base.py:54-93`` including its
    ``shift(-1, fill_value=0)`` edge semantics (the last action is compared
    against an all-zero phantom successor).
    """
    nex = actions.shift(-1, fill_value=0)

    same_team = actions['team_id'] == nex['team_id']
    dx = actions['end_x'] - nex['start_x']
    dy = actions['end_y'] - nex['start_y']
    gap_sq = dx**2 + dy**2
    far_enough = gap_sq >= min_dribble_length**2
    not_too_far = gap_sq <= max_dribble_length**2
    same_phase = (nex['time_seconds'] - actions['time_seconds']) < max_dribble_duration
    same_period = actions['period_id'] == nex['period_id']

    dribble_idx = same_team & far_enough & not_too_far & same_phase & same_period

    prev_sel = actions[dribble_idx]
    next_sel = nex[dribble_idx]

    dribbles = pd.DataFrame(
        {
            'game_id': next_sel['game_id'],
            'period_id': next_sel['period_id'],
            'action_id': prev_sel['action_id'] + 0.1,
            'time_seconds': (prev_sel['time_seconds'] + next_sel['time_seconds']) / 2,
            'team_id': next_sel['team_id'],
            'player_id': next_sel['player_id'],
            'start_x': prev_sel['end_x'],
            'start_y': prev_sel['end_y'],
            'end_x': next_sel['start_x'],
            'end_y': next_sel['start_y'],
            'bodypart_id': spadlconfig.FOOT,
            'type_id': spadlconfig.DRIBBLE,
            'result_id': spadlconfig.SUCCESS,
        }
    )
    if 'timestamp' in actions.columns:
        dribbles['timestamp'] = next_sel['timestamp']

    actions = pd.concat([actions, dribbles], ignore_index=True, sort=False)
    actions = actions.sort_values(['game_id', 'period_id', 'action_id']).reset_index(
        drop=True
    )
    actions['action_id'] = range(len(actions))
    return actions


def _single_event(event: pd.Series | pd.DataFrame) -> pd.DataFrame:
    """Wrap a per-row ``pd.Series`` (the reference's row-wise API) as a frame.

    Shared by the Wyscout converters' row-wise ``determine_*`` wrappers.
    """
    return pd.DataFrame([event]) if isinstance(event, pd.Series) else event
