"""StatsBomb event stream → SPADL converter (columnar).

Parity: reference ``socceraction/spadl/statsbomb.py:12-322`` with the
upstream (``_sa``) post-processing semantics (see :mod:`.base`). Same
observable semantics, different engineering: the reference parses each
event's ragged ``extra`` JSON row-by-row through one Python parser function
per event type; here the scalar leaves the decisions depend on are dug out
of the dicts once (``_extract_scalars``) and every type/result/bodypart
decision is an ``np.select`` over columnar masks, first-match-wins
reproducing the reference's if/elif precedence — the same design as the
Wyscout converter (:mod:`.wyscout`).

Stages:

1. pull the decision-relevant scalar leaves out of ``extra`` (one host-side
   pass over the ragged dicts — the only non-columnar step)
2. period-relative clock + 120×80 yard-cell → 105×68 m rescale with y-flip
3. columnar type/result/bodypart decision tables
4. drop non-actions, sort, shared post-processing (direction of play,
   clearances, dribbles)
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np
import pandas as pd

from . import config as spadlconfig
from .base import _add_dribbles, _fix_clearances, _fix_direction_of_play
from .schema import SPADLSchema

__all__ = ['convert_to_actions']

#: flat column name → path of keys into the ``extra`` dict
_EXTRA_SCALARS: Dict[str, Tuple[str, ...]] = {
    'pass_type': ('pass', 'type', 'name'),
    'pass_height': ('pass', 'height', 'name'),
    'pass_cross': ('pass', 'cross'),
    'pass_outcome': ('pass', 'outcome', 'name'),
    'pass_bodypart': ('pass', 'body_part', 'name'),
    'dribble_outcome': ('dribble', 'outcome', 'name'),
    'foul_card': ('foul_committed', 'card', 'name'),
    'duel_type': ('duel', 'type', 'name'),
    'duel_outcome': ('duel', 'outcome', 'name'),
    'interception_outcome': ('interception', 'outcome', 'name'),
    'shot_type': ('shot', 'type', 'name'),
    'shot_outcome': ('shot', 'outcome', 'name'),
    'shot_bodypart': ('shot', 'body_part', 'name'),
    'keeper_type': ('goalkeeper', 'type', 'name'),
    'keeper_outcome': ('goalkeeper', 'outcome', 'name'),
    'keeper_bodypart': ('goalkeeper', 'body_part', 'name'),
}

#: a duel/interception with one of these outcomes went to the opponent
_LOST = ('Lost In Play', 'Lost Out')


def _dig(d: Any, path: Tuple[str, ...]) -> Any:
    for key in path:
        if not isinstance(d, dict):
            return None
        d = d.get(key)
    return d


def _extract_scalars(extra: pd.Series) -> pd.DataFrame:
    """Flatten the ragged ``extra`` dicts into scalar decision columns."""
    return pd.DataFrame(
        {
            name: [_dig(d, path) for d in extra]
            for name, path in _EXTRA_SCALARS.items()
        },
        index=extra.index,
        dtype=object,
    )


def _period_clock(events: pd.DataFrame) -> pd.Series:
    """Clock relative to the period start (regular period lengths assumed)."""
    offsets = np.select(
        [events['period_id'] == p for p in (2, 3, 4, 5)],
        [45 * 60, 90 * 60, 105 * 60, 120 * 60],
        default=0,
    )
    return 60 * events['minute'] + events['second'] - offsets


def _to_meters(coords: pd.Series) -> Tuple[pd.Series, pd.Series]:
    """(x, y) yard-cell pairs → meters on the 105×68 pitch, y flipped.

    StatsBomb's pitch is a 120×80 grid of 1-yard cells indexed from (1, 1);
    cell centers are rescaled onto the metric pitch.
    """
    x = pd.Series([c[0] if c else 1 for c in coords], index=coords.index)
    y = pd.Series([c[1] if c else 1 for c in coords], index=coords.index)
    x_m = (x.clip(1, 120) - 1) / 119 * spadlconfig.field_length
    y_m = spadlconfig.field_width - (y.clip(1, 80) - 1) / 79 * spadlconfig.field_width
    return x_m, y_m


def _end_coordinates(events: pd.DataFrame) -> pd.Series:
    """End location: pass/shot/carry target if present, else the start."""

    def end_of(start: Any, extra: Dict[str, Any]) -> Any:
        for family in ('pass', 'shot', 'carry'):
            leaf = extra.get(family)
            if isinstance(leaf, dict) and 'end_location' in leaf:
                return leaf['end_location']
        return start

    return pd.Series(
        [end_of(loc, x) for loc, x in zip(events['location'], events['extra'])],
        index=events.index,
        dtype=object,
    )


def _bodypart_ids(relevant: pd.Series) -> np.ndarray:
    """Map raw StatsBomb body-part names onto the 4-entry SPADL vocabulary."""
    names = np.select(
        [
            relevant.isna(),
            relevant.str.contains('Head', na=False),
            relevant.str.contains('Foot', na=False) | (relevant == 'Drop Kick'),
        ],
        ['foot', 'head', 'foot'],
        default='other',
    )
    lookup = {name: i for i, name in enumerate(spadlconfig.bodyparts)}
    return pd.Series(names, index=relevant.index).map(lookup).to_numpy()


def _classify(
    events: pd.DataFrame,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Columnar (type_id, result_id, bodypart_id) decision tables."""
    tn = events['type_name']
    x = _extract_scalars(events['extra'])

    is_pass = tn == 'Pass'
    is_shot = tn == 'Shot'
    is_keeper = tn == 'Goal Keeper'
    is_tackle = (tn == 'Duel') & (x['duel_type'] == 'Tackle')
    is_cross = np.array([bool(v) for v in x['pass_cross']])
    high_or_cross = (x['pass_height'] == 'High Pass') | is_cross
    card = x['foul_card'].fillna('').astype(str)

    type_names = np.select(
        [
            is_pass & (x['pass_type'] == 'Free Kick') & high_or_cross,
            is_pass & (x['pass_type'] == 'Free Kick'),
            is_pass & (x['pass_type'] == 'Corner') & high_or_cross,
            is_pass & (x['pass_type'] == 'Corner'),
            is_pass & (x['pass_type'] == 'Goal Kick'),
            is_pass & (x['pass_type'] == 'Throw-in'),
            is_pass & is_cross,
            is_pass,
            tn == 'Dribble',
            tn == 'Carry',
            tn == 'Foul Committed',
            is_tackle,
            tn == 'Interception',
            is_shot & (x['shot_type'] == 'Free Kick'),
            is_shot & (x['shot_type'] == 'Penalty'),
            is_shot,
            tn == 'Own Goal Against',
            is_keeper & (x['keeper_type'] == 'Shot Saved'),
            is_keeper & x['keeper_type'].isin(('Collected', 'Keeper Sweeper')),
            is_keeper & (x['keeper_type'] == 'Punch'),
            tn == 'Clearance',
            tn == 'Miscontrol',
        ],
        [
            'freekick_crossed',
            'freekick_short',
            'corner_crossed',
            'corner_short',
            'goalkick',
            'throw_in',
            'cross',
            'pass',
            'take_on',
            'dribble',
            'foul',
            'tackle',
            'interception',
            'shot_freekick',
            'shot_penalty',
            'shot',
            'bad_touch',
            'keeper_save',
            'keeper_claim',
            'keeper_punch',
            'clearance',
            'bad_touch',
        ],
        default='non_action',
    )

    result_names = np.select(
        [
            is_pass & x['pass_outcome'].isin(('Incomplete', 'Out')),
            is_pass & (x['pass_outcome'] == 'Pass Offside'),
            (tn == 'Dribble') & (x['dribble_outcome'] == 'Incomplete'),
            (tn == 'Foul Committed') & card.str.contains('Yellow'),
            (tn == 'Foul Committed') & card.str.contains('Red'),
            is_tackle & x['duel_outcome'].isin(_LOST),
            (tn == 'Interception') & x['interception_outcome'].isin(_LOST),
            is_shot & (x['shot_outcome'] != 'Goal'),
            tn == 'Own Goal Against',
            is_keeper & x['keeper_outcome'].isin(('In Play Danger', 'No Touch')),
            tn == 'Miscontrol',
        ],
        [
            'fail',
            'offside',
            'fail',
            'yellow_card',
            'red_card',
            'fail',
            'fail',
            'fail',
            'owngoal',
            'fail',
            'fail',
        ],
        default='success',
    )

    relevant_bodypart = pd.Series(
        np.select(
            [is_pass, is_shot, is_keeper],
            [x['pass_bodypart'], x['shot_bodypart'], x['keeper_bodypart']],
            default=None,
        ),
        index=events.index,
        dtype=object,
    )

    type_lookup = {name: i for i, name in enumerate(spadlconfig.actiontypes)}
    result_lookup = {name: i for i, name in enumerate(spadlconfig.results)}
    return (
        pd.Series(type_names, index=events.index).map(type_lookup).to_numpy(),
        pd.Series(result_names, index=events.index).map(result_lookup).to_numpy(),
        _bodypart_ids(relevant_bodypart),
    )


def convert_to_actions(events: pd.DataFrame, home_team_id: int) -> pd.DataFrame:
    """Convert StatsBomb events of one game to SPADL actions.

    Parameters
    ----------
    events : pd.DataFrame
        StatsBomb events of a single game (see
        :meth:`~socceraction_tpu.data.statsbomb.StatsBombLoader.events`).
    home_team_id : int
        ID of the game's home team.

    Returns
    -------
    pd.DataFrame
        The game's actions in SPADL format.
    """
    events = events.copy()
    events['extra'] = events['extra'].apply(lambda d: d if isinstance(d, dict) else {})
    events = events.fillna(0)

    start_x, start_y = _to_meters(events['location'])
    end_x, end_y = _to_meters(_end_coordinates(events))
    type_ids, result_ids, bodypart_ids = _classify(events)

    actions = pd.DataFrame(
        {
            'game_id': events['game_id'],
            'original_event_id': events['event_id'],
            'period_id': events['period_id'],
            'time_seconds': _period_clock(events),
            'team_id': events['team_id'],
            'player_id': events['player_id'],
            'start_x': start_x,
            'start_y': start_y,
            'end_x': end_x,
            'end_y': end_y,
            'type_id': type_ids,
            'result_id': result_ids,
            'bodypart_id': bodypart_ids,
        }
    )

    actions = (
        actions[actions['type_id'] != spadlconfig.NON_ACTION]
        .sort_values(['game_id', 'period_id', 'time_seconds'])
        .reset_index(drop=True)
    )
    actions = _fix_direction_of_play(actions, home_team_id)
    actions = _fix_clearances(actions)

    actions['action_id'] = range(len(actions))
    actions = _add_dribbles(actions)

    return SPADLSchema.validate(actions)


# Deprecated pre-1.2 re-exports (reference ``spadl/statsbomb.py:325-413``):
# the loader, ``extract_player_games`` and the raw-data schemas moved to
# :mod:`socceraction_tpu.data.statsbomb` but remain importable here with a
# DeprecationWarning.
from ._deprecated import deprecated_reexports as _deprecated_reexports

__getattr__ = _deprecated_reexports(
    __name__,
    'socceraction_tpu.data.statsbomb',
    (
        'StatsBombLoader',
        'extract_player_games',
        'StatsBombCompetitionSchema',
        'StatsBombGameSchema',
        'StatsBombPlayerSchema',
        'StatsBombTeamSchema',
        'StatsBombEventSchema',
    ),
)
