"""StatsBomb event stream → SPADL converter.

Parity: reference ``socceraction/spadl/statsbomb.py:12-322`` with the
upstream (``_sa``) post-processing semantics (see :mod:`.base`). The
vectorizable core — period-relative clock, the 120×80 → 105×68 coordinate
rescale with y-flip, sorting and the direction/clearance fixes — runs
columnar; the per-event ``extra``-dict parsing necessarily stays host-side
(ragged JSON), organized as one parser function per StatsBomb event type.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import pandas as pd

from . import config as spadlconfig
from .base import _add_dribbles, _fix_clearances, _fix_direction_of_play
from .schema import SPADLSchema

__all__ = ['convert_to_actions']

Location = Tuple[float, float]


def convert_to_actions(events: pd.DataFrame, home_team_id) -> pd.DataFrame:
    """Convert StatsBomb events of one game to SPADL actions.

    Parameters
    ----------
    events : pd.DataFrame
        StatsBomb events of a single game (see
        :meth:`~socceraction_tpu.data.statsbomb.StatsBombLoader.events`).
    home_team_id : int
        ID of the game's home team.

    Returns
    -------
    pd.DataFrame
        The game's actions in SPADL format.
    """
    actions = pd.DataFrame()

    events = events.copy()
    events['extra'] = events['extra'].apply(lambda d: d if isinstance(d, dict) else {})
    events = events.fillna(0)

    actions['game_id'] = events['game_id']
    actions['original_event_id'] = events['event_id']
    actions['period_id'] = events['period_id']

    # Clock relative to the period start (regular period lengths assumed).
    actions['time_seconds'] = (
        60 * events['minute']
        + events['second']
        - ((events['period_id'] > 1) * 45 * 60)
        - ((events['period_id'] > 2) * 45 * 60)
        - ((events['period_id'] > 3) * 15 * 60)
        - ((events['period_id'] > 4) * 15 * 60)
    )
    actions['team_id'] = events['team_id']
    actions['player_id'] = events['player_id']

    # StatsBomb's pitch is a 120x80 grid of 1-yard cells indexed from (1, 1);
    # rescale cell centers onto the 105x68 m pitch and flip the y axis.
    actions['start_x'] = events['location'].apply(lambda x: x[0] if x else 1).clip(1, 120)
    actions['start_y'] = events['location'].apply(lambda x: x[1] if x else 1).clip(1, 80)
    actions['start_x'] = (actions['start_x'] - 1) / 119 * spadlconfig.field_length
    actions['start_y'] = (
        spadlconfig.field_width - (actions['start_y'] - 1) / 79 * spadlconfig.field_width
    )

    end_location = events[['location', 'extra']].apply(_get_end_location, axis=1)
    actions['end_x'] = end_location.apply(lambda x: x[0] if x else 1).clip(1, 120)
    actions['end_y'] = end_location.apply(lambda x: x[1] if x else 1).clip(1, 80)
    actions['end_x'] = (actions['end_x'] - 1) / 119 * spadlconfig.field_length
    actions['end_y'] = (
        spadlconfig.field_width - (actions['end_y'] - 1) / 79 * spadlconfig.field_width
    )

    actions[['type_id', 'result_id', 'bodypart_id']] = events[
        ['type_name', 'extra']
    ].apply(_parse_event, axis=1, result_type='expand')

    actions = (
        actions[actions['type_id'] != spadlconfig.NON_ACTION]
        .sort_values(['game_id', 'period_id', 'time_seconds'])
        .reset_index(drop=True)
    )
    actions = _fix_direction_of_play(actions, home_team_id)
    actions = _fix_clearances(actions)

    actions['action_id'] = range(len(actions))
    actions = _add_dribbles(actions)

    return SPADLSchema.validate(actions)


def _get_end_location(q: Tuple[Any, Dict[str, Any]]) -> Any:
    start_location, extra = q
    for event in ('pass', 'shot', 'carry'):
        if event in extra and 'end_location' in extra[event]:
            return extra[event]['end_location']
    return start_location


def _bodypart_name(bp: Any) -> str:
    if bp is None:
        return 'foot'
    if 'Head' in bp:
        return 'head'
    if 'Foot' in bp or bp == 'Drop Kick':
        return 'foot'
    return 'other'


def _parse_pass(extra: Dict[str, Any]) -> Tuple[str, str, str]:
    p = extra.get('pass', {})
    ptype = p.get('type', {}).get('name')
    height = p.get('height', {}).get('name')
    cross = p.get('cross')
    if ptype == 'Free Kick':
        a = 'freekick_crossed' if (height == 'High Pass' or cross) else 'freekick_short'
    elif ptype == 'Corner':
        a = 'corner_crossed' if (height == 'High Pass' or cross) else 'corner_short'
    elif ptype == 'Goal Kick':
        a = 'goalkick'
    elif ptype == 'Throw-in':
        a = 'throw_in'
    elif cross:
        a = 'cross'
    else:
        a = 'pass'

    outcome = p.get('outcome', {}).get('name')
    if outcome in ('Incomplete', 'Out'):
        r = 'fail'
    elif outcome == 'Pass Offside':
        r = 'offside'
    else:
        r = 'success'
    return a, r, _bodypart_name(p.get('body_part', {}).get('name'))


def _parse_dribble(extra: Dict[str, Any]) -> Tuple[str, str, str]:
    outcome = extra.get('dribble', {}).get('outcome', {}).get('name')
    return 'take_on', 'fail' if outcome == 'Incomplete' else 'success', 'foot'


def _parse_carry(_extra: Dict[str, Any]) -> Tuple[str, str, str]:
    return 'dribble', 'success', 'foot'


def _parse_foul(extra: Dict[str, Any]) -> Tuple[str, str, str]:
    card = extra.get('foul_committed', {}).get('card', {}).get('name', '')
    if 'Yellow' in card:
        r = 'yellow_card'
    elif 'Red' in card:
        r = 'red_card'
    else:
        r = 'success'
    return 'foul', r, 'foot'


def _parse_duel(extra: Dict[str, Any]) -> Tuple[str, str, str]:
    if extra.get('duel', {}).get('type', {}).get('name') == 'Tackle':
        outcome = extra.get('duel', {}).get('outcome', {}).get('name')
        r = 'fail' if outcome in ('Lost In Play', 'Lost Out') else 'success'
        return 'tackle', r, 'foot'
    return _parse_non_action(extra)


def _parse_interception(extra: Dict[str, Any]) -> Tuple[str, str, str]:
    outcome = extra.get('interception', {}).get('outcome', {}).get('name')
    r = 'fail' if outcome in ('Lost In Play', 'Lost Out') else 'success'
    return 'interception', r, 'foot'


def _parse_shot(extra: Dict[str, Any]) -> Tuple[str, str, str]:
    s = extra.get('shot', {})
    stype = s.get('type', {}).get('name')
    if stype == 'Free Kick':
        a = 'shot_freekick'
    elif stype == 'Penalty':
        a = 'shot_penalty'
    else:
        a = 'shot'
    r = 'success' if s.get('outcome', {}).get('name') == 'Goal' else 'fail'
    return a, r, _bodypart_name(s.get('body_part', {}).get('name'))


def _parse_own_goal(_extra: Dict[str, Any]) -> Tuple[str, str, str]:
    return 'bad_touch', 'owngoal', 'foot'


def _parse_goalkeeper(extra: Dict[str, Any]) -> Tuple[str, str, str]:
    g = extra.get('goalkeeper', {})
    gtype = g.get('type', {}).get('name')
    if gtype == 'Shot Saved':
        a = 'keeper_save'
    elif gtype in ('Collected', 'Keeper Sweeper'):
        a = 'keeper_claim'
    elif gtype == 'Punch':
        a = 'keeper_punch'
    else:
        a = 'non_action'
    outcome = g.get('outcome', {}).get('name', 'x')
    r = 'fail' if outcome in ('In Play Danger', 'No Touch') else 'success'
    return a, r, _bodypart_name(g.get('body_part', {}).get('name'))


def _parse_clearance(_extra: Dict[str, Any]) -> Tuple[str, str, str]:
    return 'clearance', 'success', 'foot'


def _parse_miscontrol(_extra: Dict[str, Any]) -> Tuple[str, str, str]:
    return 'bad_touch', 'fail', 'foot'


def _parse_non_action(_extra: Dict[str, Any]) -> Tuple[str, str, str]:
    return 'non_action', 'success', 'foot'


_EVENT_PARSERS = {
    'Pass': _parse_pass,
    'Dribble': _parse_dribble,
    'Carry': _parse_carry,
    'Foul Committed': _parse_foul,
    'Duel': _parse_duel,
    'Interception': _parse_interception,
    'Shot': _parse_shot,
    'Own Goal Against': _parse_own_goal,
    'Goal Keeper': _parse_goalkeeper,
    'Clearance': _parse_clearance,
    'Miscontrol': _parse_miscontrol,
}


def _parse_event(q: Tuple[str, Dict[str, Any]]) -> Tuple[int, int, int]:
    type_name, extra = q
    a, r, b = _EVENT_PARSERS.get(type_name, _parse_non_action)(extra)
    return (
        spadlconfig.actiontypes.index(a),
        spadlconfig.results.index(r),
        spadlconfig.bodyparts.index(b),
    )
