"""Vocabulary and pitch constants of the SPADL action language.

SPADL ("Soccer Player Action Description Language") describes every
on-the-ball action as one row with a fixed vocabulary: 23 action types,
6 results and 4 bodyparts, with coordinates in meters on a 105 x 68 pitch.
The vocabulary *order defines the id spaces* used everywhere downstream
(one-hot widths, grid kernels, label masks), so these lists are the single
source of truth for both the pandas oracle backend and the packed tensor
backend.

Parity: reference ``socceraction/spadl/config.py:21-91``.
"""

from __future__ import annotations

from typing import List

import numpy as np
import pandas as pd

field_length: float = 105.0  # meters
field_width: float = 68.0  # meters

bodyparts: List[str] = ['foot', 'head', 'other', 'head/other']

results: List[str] = [
    'fail',
    'success',
    'offside',
    'owngoal',
    'yellow_card',
    'red_card',
]

actiontypes: List[str] = [
    'pass',
    'cross',
    'throw_in',
    'freekick_crossed',
    'freekick_short',
    'corner_crossed',
    'corner_short',
    'take_on',
    'foul',
    'tackle',
    'interception',
    'shot',
    'shot_penalty',
    'shot_freekick',
    'keeper_save',
    'keeper_claim',
    'keeper_punch',
    'keeper_pick_up',
    'clearance',
    'bad_touch',
    'non_action',
    'dribble',
    'goalkick',
]

# Frequently needed id constants, resolved once at import time. The tensor
# kernels in socceraction_tpu.ops index with these as static Python ints so
# XLA sees fixed masks rather than dynamic lookups.
PASS = actiontypes.index('pass')
CROSS = actiontypes.index('cross')
DRIBBLE = actiontypes.index('dribble')
SHOT = actiontypes.index('shot')
SHOT_PENALTY = actiontypes.index('shot_penalty')
SHOT_FREEKICK = actiontypes.index('shot_freekick')
CLEARANCE = actiontypes.index('clearance')
NON_ACTION = actiontypes.index('non_action')

FAIL = results.index('fail')
SUCCESS = results.index('success')
OFFSIDE = results.index('offside')
OWNGOAL = results.index('owngoal')
YELLOW_CARD = results.index('yellow_card')
RED_CARD = results.index('red_card')

FOOT = bodyparts.index('foot')
HEAD = bodyparts.index('head')
OTHER = bodyparts.index('other')

# Action-type ids whose name contains 'shot' -- the goal predicate used by the
# VAEP labels and goalscore feature (reference vaep/labels.py:28,
# vaep/features.py:522 use `type_name.str.contains('shot')`).
SHOT_LIKE = tuple(i for i, t in enumerate(actiontypes) if 'shot' in t)

shot_like_mask: np.ndarray = np.zeros(len(actiontypes), dtype=bool)
shot_like_mask[list(SHOT_LIKE)] = True


def actiontypes_df() -> pd.DataFrame:
    """Return the 'type_id' and 'type_name' of each SPADL action type."""
    return pd.DataFrame(
        {'type_id': np.arange(len(actiontypes)), 'type_name': actiontypes}
    )


def results_df() -> pd.DataFrame:
    """Return the 'result_id' and 'result_name' of each SPADL result."""
    return pd.DataFrame({'result_id': np.arange(len(results)), 'result_name': results})


def bodyparts_df() -> pd.DataFrame:
    """Return the 'bodypart_id' and 'bodypart_name' of each SPADL bodypart."""
    return pd.DataFrame(
        {'bodypart_id': np.arange(len(bodyparts)), 'bodypart_name': bodyparts}
    )
