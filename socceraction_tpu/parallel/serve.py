"""Replica fan-out execution tier for the serving dispatch path.

:class:`~socceraction_tpu.serve.service.RatingService` multiplexes every
caller onto ONE device; this module is the compute half of the N-replica
topology the fleet telemetry plane (wire merge, per-replica endpoints,
mesh-wide SLO) was built for. It owns exactly the device-placement story:

- **params replicated once at model load** — the serving dispatch's
  parameter-side arguments (the model params + folded device stats of the
  legacy lowering, or the prepared quantized fold) are resolved ONCE via
  :func:`~socceraction_tpu.ops.fused.pair_dispatch_plan` and committed to
  every replica device up front. Flushes ship only the batch.
- **per-replica lane dispatch** (:meth:`ReplicaDispatcher.rate_replica`)
  — the service's N flush lanes each dispatch to their own device with
  every argument committed there, so lanes never contend for one chip
  and a dispatch is in flight per replica. The program is the *same*
  instrumented jit the single-device path runs (``pair_probs`` /
  ``pair_probs_prepared`` + the ``vaep_values`` formula kernel), so the
  single-replica output is bitwise the existing path's on CPU — only the
  argument placement differs, never the computation.
- **gang dispatch** (:meth:`ReplicaDispatcher.rate_mesh`) — one
  ``shard_map`` call over the 1-D ``('replicas',)`` mesh
  (:func:`~socceraction_tpu.parallel.mesh.make_replica_mesh`, through
  the compat shim :mod:`socceraction_tpu.ops.compat`): per-replica flush
  batches, each already padded to the same bucket rung, are concatenated
  and scattered along the game axis; every shard runs the fused pair
  probs + formula body with the replicated params. No collective crosses
  the axis — the rating is game-local by construction — so the gang form
  is pure SPMD fan-out. The offline twin of the lane form; the bench's
  replica sweep and the parity tests pin both against the single-device
  path.

The tier is deliberately jax-heavy and policy-free: admission, queues,
breakers, swaps and telemetry stay in ``serve/``; this module only
answers "run this padded staging batch on replica ``i`` (or on all of
them) and give me host values".
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.batch import ActionBatch
from ..ops.compat import has_shard_map, shard_map
from .mesh import make_replica_mesh

__all__ = ['ReplicaDispatcher']


class ReplicaDispatcher:
    """Replicated-params, batch-scattered executor for one fitted model.

    Parameters
    ----------
    model : VAEP
        A fitted model whose label heads can serve through the fused
        pair dispatch (``_can_fuse()`` and a fused-path platform
        profile). The materialized path has no replica tier — it is the
        degradation target, not the scale-out one.
    n_replicas : int
        Size of the ``('replicas',)`` mesh axis.
    devices : sequence, optional
        Explicit device list (default: the first ``n_replicas`` of
        ``jax.devices()``). Replica ``0`` should be the process default
        device so the single-replica configuration stays bitwise the
        pre-mesh service.
    """

    def __init__(
        self,
        model: Any,
        n_replicas: int = 1,
        *,
        devices: Optional[Sequence[Any]] = None,
    ) -> None:
        import jax

        from ..ops.fused import pair_dispatch_plan
        from ..ops.profile import (
            FUSED_PATH_HIDDEN_DTYPES,
            hidden_dtype_for,
            preferred_rating_path,
        )

        n_replicas = int(n_replicas)
        if n_replicas < 1:
            raise ValueError('n_replicas must be >= 1')
        path = preferred_rating_path()
        if not (
            getattr(model, '_can_fuse', lambda: False)()
            and path in FUSED_PATH_HIDDEN_DTYPES
        ):
            raise ValueError(
                'replica fan-out serves the fused dispatch path only; this '
                f'model/platform resolves the {path!r} rating path '
                '(materialized serving stays single-device — it is the '
                'breaker fallback, not the scale-out tier)'
            )
        self.model = model
        self.n_replicas = n_replicas
        self.mesh = make_replica_mesh(n_replicas, devices=devices)
        self.devices: Tuple[Any, ...] = tuple(self.mesh.devices.flat)
        cols = list(model._label_columns)
        clf_a, clf_b = model._models[cols[0]], model._models[cols[1]]
        # Resolve the dispatch ONCE (fn + params-side args + statics);
        # nothing in the plan inspects batch values, so batch/overrides
        # slots stay None here and are filled per dispatch. This is the
        # same resolution ``VAEP.rate_batch`` performs, so lane dispatch
        # runs the identical program under the identical statics.
        self._plan = pair_dispatch_plan(
            clf_a,
            clf_b,
            None,
            names=model._kernel_names(),
            k=model.nb_prev_actions,
            registry_name=model._fused_registry,
            dense_overrides=None,
            hidden_dtype=hidden_dtype_for(path),
            prepared=model._prepared_pair(),
        )
        # params + device stats (or the prepared fold) replicated once at
        # model load: one committed copy per replica device. Replica 0 is
        # the default device, so its copy aliases what the single-device
        # path already holds resident.
        param_args = self._plan.args[:-2]
        self._params: Tuple[Any, ...] = tuple(
            jax.device_put(param_args, d) for d in self.devices
        )
        #: lazily built mesh-replicated copy for the gang form
        self._gang_params: Any = None
        self._gang_lock = threading.Lock()
        self._gang_fns: Dict[bool, Any] = {}

    # -- shared dispatch plumbing ------------------------------------------

    def _dispatch_kwargs(self) -> Tuple[Dict[str, Any], bool]:
        """The plan's static kwargs with ``guard`` re-resolved per call.

        Guards are a runtime toggle; the plan carries the value at
        build time. ``guard`` is a static argname, so a fixed setting
        still compiles once per signature.
        """
        from ..obs import numerics

        guard = numerics.guards_enabled()
        if guard == self._plan.kwargs.get('guard'):
            return self._plan.kwargs, guard
        kwargs = dict(self._plan.kwargs)
        kwargs['guard'] = guard
        return kwargs, guard

    def _pair_values(
        self,
        params: Any,
        batch: Any,
        overrides: Any,
        kwargs: Dict[str, Any],
        guard: bool,
    ) -> Any:
        """One fused pair dispatch + formula kernel; notes guard events."""
        from ..obs import numerics

        out = self._plan.fn(*params, batch, overrides, **kwargs)
        if guard:
            pa, pb, (n_nonfinite, n_overflow) = out
            # same side-band contract as fused_pair_probs: stash now,
            # drain after the flush's outputs were fetched
            numerics.note_guard('pair_probs', 'probs', n_nonfinite)
            numerics.note_guard(
                'pair_probs', 'logits', n_overflow, kind='overflow'
            )
        else:
            pa, pb = out
        return self.model._formula_kernel(batch, pa, pb)

    # -- lane form: one replica, one committed dispatch --------------------

    def rate_replica(
        self,
        replica: int,
        host_batch: ActionBatch,
        gs: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Rate one padded staging batch on replica ``replica``.

        Every argument is committed to the replica's device before
        dispatch, so concurrent lanes each keep exactly one dispatch in
        flight on their own chip. Returns host ``(G, A, 3)`` values —
        bitwise what ``VAEP.rate_batch(bucket=False)`` returns for the
        same staging batch on CPU (same program, same values, different
        placement).
        """
        import jax

        d = self.devices[replica]
        batch = jax.device_put(host_batch, d)
        overrides = (
            {'goalscore': jax.device_put(np.asarray(gs), d)}
            if gs is not None
            else None
        )
        kwargs, guard = self._dispatch_kwargs()
        values = self._pair_values(
            self._params[replica], batch, overrides, kwargs, guard
        )
        return np.asarray(jax.device_get(values))

    # -- gang form: one shard_map over the whole mesh ----------------------

    def _gang_fn(self, with_gs: bool) -> Any:
        """The jitted ``shard_map`` gang dispatch (cached per arity)."""
        import jax
        from jax.sharding import PartitionSpec as P

        fn = self._gang_fns.get(with_gs)
        if fn is not None:
            return fn
        if not has_shard_map():
            raise RuntimeError(
                'no shard_map in this jax build; the gang dispatch needs '
                'it (per-replica lane dispatch does not)'
            )
        # the gang body runs under an outer trace, where the side-band
        # guard scalars cannot be stashed (note_guard skips tracers) —
        # the serving lanes keep guards; the gang form is the
        # bench/parity twin and dispatches unguarded
        kwargs = dict(self._plan.kwargs)
        kwargs['guard'] = False
        plan_fn = self._plan.fn
        formula = self.model._formula_kernel

        def body(params, batch, gs):
            overrides = {'goalscore': gs} if gs is not None else None
            pa, pb = plan_fn(*params, batch, overrides, **kwargs)
            return formula(batch, pa, pb)

        if with_gs:
            mapped = shard_map(
                body,
                mesh=self.mesh,
                in_specs=(P(), P('replicas'), P('replicas')),
                out_specs=P('replicas'),
            )
        else:
            mapped = shard_map(
                functools.partial(body, gs=None),
                mesh=self.mesh,
                in_specs=(P(), P('replicas')),
                out_specs=P('replicas'),
            )
        fn = jax.jit(mapped)
        self._gang_fns[with_gs] = fn
        return fn

    def _gang_replicated_params(self) -> Any:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        with self._gang_lock:
            if self._gang_params is None:
                self._gang_params = jax.device_put(
                    self._plan.args[:-2], NamedSharding(self.mesh, P())
                )
            return self._gang_params

    def rate_mesh(
        self,
        host_batches: Sequence[ActionBatch],
        gs_list: Optional[Sequence[Optional[np.ndarray]]] = None,
    ) -> List[np.ndarray]:
        """One gang dispatch: every replica's flush in a single program.

        ``host_batches`` carries one staging batch per replica, each
        already padded to the SAME bucket rung (per-replica ladders pad
        before the scatter, so every shard executes the pinned bucket
        shape). The batches are concatenated along the game axis,
        scattered over ``('replicas',)`` by ``shard_map``, rated
        against the mesh-replicated params, and the ``(G, A, 3)``
        values are split back per replica.
        """
        import jax

        R = self.n_replicas
        if len(host_batches) != R:
            raise ValueError(
                f'{len(host_batches)} flush batches for {R} replicas; '
                'the gang dispatch takes exactly one per replica'
            )
        per = host_batches[0].n_games
        for hb in host_batches:
            if hb.n_games != per:
                raise ValueError(
                    'per-replica flush batches must share one bucket rung '
                    f'(got game counts {[b.n_games for b in host_batches]}); '
                    'pad each lane to the common rung before the scatter'
                )
        stacked = jax.tree.map(
            lambda *xs: np.concatenate(xs, axis=0), *host_batches
        )
        params = self._gang_replicated_params()
        with_gs = gs_list is not None and any(g is not None for g in gs_list)
        if with_gs:
            # all-or-none: a goalscore override SUBSTITUTES the computed
            # dense block, so "absent" cannot be emulated with zeros —
            # a mixed gang would silently rate some shards wrong
            if any(g is None for g in gs_list):  # type: ignore[union-attr]
                raise ValueError(
                    'gang dispatch needs a goalscore block for every '
                    'replica or for none (an override replaces the '
                    'computed feature; zeros are not "no override")'
                )
            gs = np.concatenate(
                [np.asarray(g) for g in gs_list], axis=0  # type: ignore[union-attr]
            )
            values = self._gang_fn(True)(params, stacked, gs)
        else:
            values = self._gang_fn(False)(params, stacked)
        values = np.asarray(jax.device_get(values))
        return [values[i * per : (i + 1) * per] for i in range(R)]
