"""Multi-device scale-out for the action-tensor runtime.

The reference is single-process pandas with no parallelism of any kind
(SURVEY §2 #26/#27: no reference counterpart exists). Here scale-out is a
first-class subsystem built on ``jax.sharding``:

- the **game axis** of an :class:`~socceraction_tpu.core.batch.ActionBatch`
  is the data-parallel axis, sharded over a 1-D or 2-D
  :class:`jax.sharding.Mesh` (ICI within a slice, DCN across slices),
- xT training reduces its per-shard count matrices with a single ``psum``
  (the only cross-game reduction in the whole system, reference
  ``socceraction/xthreat.py:177-218`` builds it serially),
- VAEP MLP training runs data-parallel (batch over ``games``) with
  optionally tensor-parallel hidden layers (weights over ``model``);
  XLA inserts the gradient all-reduce / activation collectives from the
  sharding annotations,
- for sequences too long for one device, the **action axis** itself can
  shard over a ``(games, seq)`` mesh with halo-exchange kernels
  (:mod:`~socceraction_tpu.parallel.sequence` — the action-stream analog
  of ring attention),
- serving fan-out replicates the fused rating dispatch across a 1-D
  ``replicas`` mesh (:mod:`~socceraction_tpu.parallel.serve` —
  replicated params, batch-sharded games, zero collectives), the
  execution tier behind ``RatingService(n_replicas=N)``.
"""

from .mesh import (
    batch_sharding,
    make_mesh,
    make_replica_mesh,
    pad_games,
    replicated,
    shard_batch,
)
from .xt import sharded_xt_counts, sharded_xt_fit, sharded_xt_fit_matrix_free
from .vaep import (
    data_parallel_rate,
    make_train_step,
    sharded_rate,
    train_distributed,
)
from .serve import ReplicaDispatcher
from .sequence import (
    make_sequence_mesh,
    sequence_features,
    sequence_labels,
    sequence_rate,
    sequence_values,
    shard_batch_seq,
)

__all__ = [
    'make_mesh',
    'make_replica_mesh',
    'batch_sharding',
    'pad_games',
    'replicated',
    'shard_batch',
    'sharded_xt_counts',
    'sharded_xt_fit',
    'sharded_xt_fit_matrix_free',
    'data_parallel_rate',
    'make_train_step',
    'sharded_rate',
    'train_distributed',
    'ReplicaDispatcher',
    'make_sequence_mesh',
    'shard_batch_seq',
    'sequence_features',
    'sequence_labels',
    'sequence_rate',
    'sequence_values',
]
