"""Distributed VAEP probability-model training and rating.

The reference trains its probability models with host-side XGBoost, one
label at a time, single-process (``socceraction/vaep/base.py:199-282``).
The TPU-native path trains both MLP heads *jointly, on device, from the
packed batch*: the feature and label kernels run inside the training step
(no materialized feature matrix round-trip), the batch is sharded over the
``'games'`` mesh axis, and the MLP hidden layers can additionally be
tensor-parallel over ``'model'`` (Megatron-style column/row split). All
collectives (gradient all-reduce, TP activation reductions) are inserted
by XLA from the sharding annotations — there is no hand-written
communication code.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.batch import ActionBatch
from ..ml.mlp import MLPClassifier, _MLP
from ..ops.features import compute_features
from ..ops.fused import fused_pair_logits
from ..ops.labels import scores_concedes
from .mesh import shard_batch

__all__ = [
    'data_parallel_rate',
    'make_train_step',
    'param_shardings',
    'sharded_rate',
    'train_distributed',
]


def param_shardings(params: Any, mesh: Mesh) -> Any:
    """Megatron-style TP shardings for an ``_MLP`` parameter pytree.

    Alternating hidden ``Dense`` layers are column- then row-partitioned
    over the ``'model'`` axis; the scalar output head is replicated. With
    ``model_parallel == 1`` meshes this degenerates to full replication.
    """

    def one_layer(name: str, leaf_name: str) -> P:
        if not name.startswith('Dense_'):
            return P()
        i = int(name.split('_')[1])
        if leaf_name == 'kernel':
            return P(None, 'model') if i % 2 == 0 else P('model', None)
        return P('model') if i % 2 == 0 else P()

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    # Find the last Dense index: its output is the (replicated) logit head.
    last = max(
        int(str(kp[-2].key).split('_')[1])
        for kp, _ in flat
        if str(kp[-2].key).startswith('Dense_')
    )

    def spec_for(path, leaf) -> NamedSharding:
        layer = str(path[-2].key)
        leaf_name = str(path[-1].key)
        if layer == f'Dense_{last}':
            spec = P()
        else:
            spec = one_layer(layer, leaf_name)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def _masked_bce(logits: jax.Array, y: jax.Array, mask: jax.Array) -> jax.Array:
    losses = optax.sigmoid_binary_cross_entropy(logits, y.astype(jnp.float32))
    weights = mask.astype(jnp.float32)
    return jnp.sum(losses * weights) / jnp.maximum(jnp.sum(weights), 1.0)


def make_train_step(
    mesh: Mesh,
    names: Tuple[str, ...],
    k: int = 3,
    hidden: Sequence[int] = (128, 128),
    learning_rate: float = 1e-3,
    nr_actions: int = 10,
) -> Tuple[Callable, Callable, Callable]:
    """Build ``(init_fn, step_fn, place_batch)`` for the fused distributed VAEP step.

    ``step_fn(params, opt_state, batch) -> (params, opt_state, loss)`` runs
    features → labels → two-head MLP loss → grads → adam update as ONE
    XLA computation over the sharded batch. ``params`` holds both heads:
    ``{'scores': ..., 'concedes': ...}``.
    """
    module = _MLP(tuple(hidden))
    tx = optax.adam(learning_rate)
    batch_sh = NamedSharding(mesh, P('games'))

    def init_fn(rng: jax.Array, n_features: int):
        dummy = jnp.zeros((1, n_features))
        rng_s, rng_c = jax.random.split(rng)
        params = {
            'scores': module.init(rng_s, dummy),
            'concedes': module.init(rng_c, dummy),
        }
        shardings = {h: param_shardings(p, mesh) for h, p in params.items()}
        params = jax.tree.map(jax.device_put, params, shardings)
        opt_state = tx.init(params)
        return params, opt_state

    def loss_fn(params, batch: ActionBatch):
        # the fused combined-table forward (ops/fused.py) avoids
        # materializing the (G, A, F) feature tensor in HBM, and the
        # stacked two-head fold computes ONE gather per state for both
        # heads; the gather's backward is the explicit segment-machinery
        # scatter-add (ops/fused.py:table_lookup -> segment_sum_rows — a
        # one-hot MXU contraction on TPU) over the small (T*R*B, 2H)
        # tables, so the backward pass stays fused too
        ys, yc = scores_concedes(batch, nr_actions=nr_actions)
        mask = batch.mask
        logit_s, logit_c = fused_pair_logits(
            params['scores'], params['concedes'], batch, names=names, k=k,
            hidden_layers_a=len(hidden), hidden_layers_b=len(hidden),
        )
        l_s = _masked_bce(logit_s, ys, mask)
        l_c = _masked_bce(logit_c, yc, mask)
        return l_s + l_c

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step_fn(params, opt_state, batch: ActionBatch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    def place_batch(batch: ActionBatch) -> ActionBatch:
        return jax.tree.map(lambda x: jax.device_put(x, batch_sh), batch)

    return init_fn, step_fn, place_batch


def train_distributed(
    batch: ActionBatch,
    mesh: Mesh,
    names: Tuple[str, ...],
    *,
    k: int = 3,
    hidden: Sequence[int] = (128, 128),
    learning_rate: float = 1e-3,
    epochs: int = 10,
    seed: int = 0,
) -> Dict[str, MLPClassifier]:
    """Train both probability heads data/tensor-parallel on a mesh.

    Returns ``{'scores': MLPClassifier, 'concedes': MLPClassifier}`` with
    the trained parameters installed (identity normalization), directly
    usable as ``VAEP._models`` for the fused rating path.
    """
    batch = shard_batch(batch, mesh)
    n_features = int(
        compute_features.eval_shape(batch, names=tuple(names), k=k).shape[-1]
    )
    init_fn, step_fn, _ = make_train_step(
        mesh, tuple(names), k, hidden, learning_rate
    )
    params, opt_state = init_fn(jax.random.PRNGKey(seed), n_features)
    for _ in range(epochs):
        params, opt_state, _ = step_fn(params, opt_state, batch)

    models: Dict[str, MLPClassifier] = {}
    for head in ('scores', 'concedes'):
        clf = MLPClassifier(hidden=tuple(hidden), learning_rate=learning_rate)
        clf.params = jax.tree.map(np.asarray, params[head])
        clf.mean_ = np.zeros(n_features, dtype=np.float32)
        clf.std_ = np.ones(n_features, dtype=np.float32)
        models[head] = clf
    return models


def sharded_rate(
    model: Any, batch: ActionBatch, mesh: Mesh
) -> Tuple[jax.Array, ActionBatch]:
    """Rate a batch with its game axis sharded over the mesh.

    ``model`` is a fitted :class:`~socceraction_tpu.vaep.base.VAEP` (or
    subclass) whose probability models are on-device MLPs. Returns the
    sharded ``(G, A, 3)`` value tensor; unpack with
    :func:`~socceraction_tpu.core.batch.unpack_values` against the
    *sharded* batch (padding games carry all-False masks).
    """
    sharded = shard_batch(batch, mesh)
    return model.rate_batch(sharded), sharded


def data_parallel_rate(
    model: Any,
    host_batches: Sequence[ActionBatch],
    *,
    n_replicas: int = None,
    devices: Sequence[Any] = None,
) -> Tuple[np.ndarray, ...]:
    """Rate N equal-shaped host batches, one per replica, in one dispatch.

    The `shard_map` counterpart to :func:`sharded_rate`: where that
    function shards ONE batch's game axis via sharding annotations and
    lets XLA insert collectives, this one ships N already-split batches
    through the serving tier's gang dispatch
    (:meth:`~socceraction_tpu.parallel.serve.ReplicaDispatcher.rate_mesh`)
    — replicated params, per-replica batch shards, no collectives at
    all. Requires the fused rating path (the materialized path stays
    single-device; it is the serving breaker's fallback).

    Returns one ``(G, A, 3)`` numpy value array per input batch, each
    bitwise-identical to ``model.rate_batch(batch, bucket=False)`` on
    that batch alone.
    """
    from .serve import ReplicaDispatcher

    n = len(host_batches) if n_replicas is None else int(n_replicas)
    if n != len(host_batches):
        raise ValueError(
            f'{len(host_batches)} batches for {n} replicas — '
            'gang dispatch needs exactly one batch per replica'
        )
    dispatcher = ReplicaDispatcher(model, n, devices=devices)
    return tuple(dispatcher.rate_mesh(list(host_batches)))
