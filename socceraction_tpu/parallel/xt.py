"""Sharded xT training: per-shard count matrices + one ``psum``.

The xT count/transition matrices are plain sums over actions (reference
``socceraction/xthreat.py:40-67,177-218``), so the distributed form is
textbook: each device scatter-adds its local game shard into device-local
matrices, one ``psum`` over the ``'games'`` axis reduces them, and the
(small, replicated) value iteration runs identically on every device.
This is the only cross-game collective in the whole framework.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.batch import ActionBatch
from ..ops.compat import shard_map
from ..ops.xt import (
    XTCounts,
    XTProbabilities,
    solve_xt,
    solve_xt_matrix_free,
    xt_counts,
    xt_probabilities,
)

__all__ = ['sharded_xt_counts', 'sharded_xt_fit', 'sharded_xt_fit_matrix_free']


def _local_counts(batch: ActionBatch, l: int, w: int) -> XTCounts:
    counts = xt_counts(
        batch.type_id,
        batch.result_id,
        batch.start_x,
        batch.start_y,
        batch.end_x,
        batch.end_y,
        batch.mask,
        l=l,
        w=w,
    )
    return jax.tree.map(lambda c: jax.lax.psum(c, 'games'), counts)


def sharded_xt_counts(batch: ActionBatch, mesh: Mesh, *, l: int, w: int) -> XTCounts:
    """All-reduced xT counts for a game-sharded batch.

    The batch must already be sharded/shardable over ``mesh`` (game axis a
    multiple of the ``'games'`` axis size; see
    :func:`~socceraction_tpu.parallel.mesh.shard_batch`).
    """
    fn = shard_map(
        functools.partial(_local_counts, l=l, w=w),
        mesh=mesh,
        in_specs=P('games'),
        out_specs=P(),
    )
    return fn(batch)


def sharded_xt_fit(
    batch: ActionBatch,
    mesh: Mesh,
    *,
    l: int = 16,
    w: int = 12,
    eps: float = 1e-5,
    max_iter: int = 1000,
    accelerate: bool = False,
    solver: Optional[str] = None,
) -> Tuple[jax.Array, XTProbabilities, jax.Array]:
    """Fit xT on a game-sharded batch: psum'd counts, replicated solve.

    ``solver`` selects the value-iteration variant
    (:data:`~socceraction_tpu.ops.xt.SOLVERS`; ``accelerate`` is the
    deprecated Anderson alias).

    Returns ``(grid, probabilities, n_iterations)`` — identical values to
    the single-device :func:`~socceraction_tpu.ops.xt.xt_counts` path
    (count sums are order-insensitive in fp32 up to reassociation).
    """
    counts = sharded_xt_counts(batch, mesh, l=l, w=w)
    probs = xt_probabilities(counts, l=l, w=w)
    sol = solve_xt(
        probs, eps=eps, max_iter=max_iter, solver=solver, accelerate=accelerate
    )
    rep = NamedSharding(mesh, P())
    grid = jax.device_put(sol.grid, rep)
    return grid, probs, sol.iterations


def sharded_xt_fit_matrix_free(
    batch: ActionBatch,
    mesh: Mesh,
    *,
    l: int,
    w: int,
    eps: float = 1e-5,
    max_iter: int = 1000,
    accelerate: bool = False,
    solver: Optional[str] = None,
    group_id: Optional[jax.Array] = None,
    n_groups: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Fine-grid sharded xT fit: per-shard segment-sums, psum'd sweeps.

    The matrix-free twin of :func:`sharded_xt_fit` for grids whose dense
    transition matrix is intractable (e.g. 192×125). Each device
    segment-sums its local game shard; the count vectors and every
    value-iteration payoff are ``psum``-reduced over the ``'games'`` axis,
    so all devices iterate the identical global surface
    (:func:`~socceraction_tpu.ops.xt.solve_xt_matrix_free` with
    ``axis_name='games'``).

    The batch axis composes with the shard axis: pass a per-action
    ``group_id`` shaped like the batch fields (``(G_games, A)``, sharded
    the same way) plus ``n_groups`` and every device solves the SAME
    replicated ``(n_groups, w, l)`` fleet from its local action shard —
    grouped counts and every batched sweep payoff are psum'd like the
    single-grid case. ``solver`` selects the value-iteration variant.

    Returns ``(grid, n_iterations)``; the grid is replicated (stacked
    with per-grid iteration counts for grouped fits).
    """

    def local_fit(b: ActionBatch, gid: Optional[jax.Array] = None):
        sol, _ = solve_xt_matrix_free(
            b.type_id,
            b.result_id,
            b.start_x,
            b.start_y,
            b.end_x,
            b.end_y,
            b.mask,
            l=l,
            w=w,
            eps=eps,
            max_iter=max_iter,
            axis_name='games',
            accelerate=accelerate,
            solver=solver,
            group_id=gid,
            n_groups=n_groups,
        )
        return sol.grid, sol.iterations

    if group_id is None:
        fn = shard_map(
            local_fit, mesh=mesh, in_specs=P('games'), out_specs=P()
        )
        return fn(batch)
    fn = shard_map(
        local_fit, mesh=mesh, in_specs=(P('games'), P('games')), out_specs=P()
    )
    return fn(batch, group_id)
