"""Device-mesh construction and batch sharding helpers.

A mesh has up to two named axes:

- ``'games'`` — the data-parallel axis. Games are embarrassingly parallel
  for every transform in the system (the reference's only loop over games,
  its L5 pipelines, is sequential), so this axis does the heavy lifting.
- ``'model'`` — optional tensor-parallel axis for the MLP probability
  head's hidden dimension.

On a multi-host pod the same code runs unchanged: ``jax.devices()`` spans
hosts and the mesh lays 'games' along DCN-adjacent devices last, so the
frequent collectives (gradient psum) ride ICI.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.batch import ActionBatch

__all__ = [
    'make_mesh',
    'make_replica_mesh',
    'batch_sharding',
    'pad_games',
    'replicated',
    'shard_batch',
]


def make_mesh(
    n_devices: Optional[int] = None,
    model_parallel: int = 1,
    *,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build a ``(games, model)`` mesh over the available devices.

    Parameters
    ----------
    n_devices : int, optional
        Use the first ``n_devices`` devices (default: all).
    model_parallel : int
        Size of the tensor-parallel ``'model'`` axis; must divide the
        device count. Default 1 (pure data parallelism).
    devices : sequence, optional
        Explicit device list overriding ``n_devices``.
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    devices = list(devices)
    n = len(devices)
    if n % model_parallel != 0:
        raise ValueError(
            f'model_parallel={model_parallel} does not divide {n} devices'
        )
    arr = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(arr, axis_names=('games', 'model'))


def make_replica_mesh(
    n_replicas: Optional[int] = None,
    *,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build the 1-D ``('replicas',)`` mesh of the serving fan-out.

    The serving tier (:mod:`socceraction_tpu.parallel.serve`) is pure
    data parallelism with a different contract than the training mesh:
    params are replicated once at model load, each replica owns whole
    flush batches (scattered along the game axis by
    ``shard_map`` — resolved through the compat shim,
    :mod:`socceraction_tpu.ops.compat` — for gang dispatches, or
    committed per-device for independent flush lanes), and no
    collective ever crosses the axis. A distinct axis name keeps a
    serving mesh from ever being confused with a ``('games','model')``
    training mesh in sharding specs.
    """
    if devices is None:
        devices = jax.devices()
        if n_replicas is not None:
            devices = devices[:n_replicas]
    devices = list(devices)
    if n_replicas is not None and len(devices) < n_replicas:
        raise ValueError(
            f'{n_replicas} replicas requested but only {len(devices)} '
            'devices are available (on CPU, raise '
            '--xla_force_host_platform_device_count)'
        )
    return Mesh(np.asarray(devices), axis_names=('replicas',))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of per-action ``(G, A)`` tensors: split the game axis."""
    return NamedSharding(mesh, P('games'))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding (model grids, parameters, vocab tables)."""
    return NamedSharding(mesh, P())


def pad_games(batch: ActionBatch, multiple: int) -> ActionBatch:
    """Pad the game axis up to a multiple of the mesh's data axis size.

    Padding games carry ``mask == False`` and ``n_actions == 0``; every
    kernel either ignores them via the mask or clamps its per-game gathers
    (JAX gather semantics clip out-of-range indices), so they are inert.
    """
    G = batch.n_games
    G_pad = ((G + multiple - 1) // multiple) * multiple
    if G_pad == G:
        return batch
    extra = G_pad - G

    def pad(x: jax.Array) -> jax.Array:
        pad_width = [(0, extra)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, pad_width)

    padded = jax.tree.map(pad, batch)
    return padded.replace(row_index=padded.row_index.at[G:].set(-1))


def shard_batch(batch: ActionBatch, mesh: Mesh) -> ActionBatch:
    """Place a batch on the mesh, game axis sharded over ``'games'``.

    The game axis is padded (with inert games) to a multiple of the data
    axis so every device holds an equal shard. Use the returned batch's
    ``row_index``/``mask`` to drop the padding on unpack —
    :func:`~socceraction_tpu.core.batch.unpack_values` already does.
    """
    data_size = mesh.shape['games']
    batch = pad_games(batch, data_size)
    sh = NamedSharding(mesh, P('games'))
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)
