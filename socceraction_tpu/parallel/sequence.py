"""Sequence (context) parallelism: shard the ACTION axis across devices.

The framework's default scale-out shards the game axis and keeps each
game's action stream on one device — correct for SPADL's ~1.5-2.5k-action
games (docs/design.md "Scale-out"). This module is the long-context path
for when that assumption breaks (arbitrarily long tracking/event streams,
or more devices than games): the `(G, A)` batch is sharded over a
``(games, seq)`` mesh and every kernel runs shard-local with **halo
exchange**, the action-stream analog of ring attention — communication
cost is O(halo), not O(sequence). Both action families are supported:
standard SPADL (:class:`~socceraction_tpu.core.batch.ActionBatch`) and
Atomic-SPADL (:class:`~socceraction_tpu.core.batch.AtomicActionBatch`),
dispatched on the batch type.

Why it decomposes: every cross-action dependence in the valuation stack
is bounded (SURVEY §5 "Long-context"):

- features look back ``k-1 ≤ 2`` actions (edge-clamped shifts),
- labels look ahead ``nr_actions-1 ≤ 9`` actions (per-game tail clamp),
- the VAEP formula lags exactly 1 action,
- the only global dependence is ``goalscore``'s running score — a prefix
  sum, solved with a per-shard reduction + exclusive cross-shard scan
  (``all_gather`` of one scalar pair per (game, shard)).

So each shard pulls ``HL = k-1`` columns from its left neighbor (none at
``k = 1``) and ``HR = nr_actions-1`` from its right neighbor via
``ppermute`` over ICI, the stateless feature kernels run unchanged on the
extended local view, and the three sequence-global quantities (goalscore
prefix, the game's first-action team, the per-game last-valid-row clamp)
are reconstructed from one tiny collective each. Numerical results are
asserted identical to the unsharded kernels in
``tests/test_sequence_parallel.py``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.batch import ActionBatch, AtomicActionBatch
from ..ops.compat import axis_size, shard_map

__all__ = [
    'make_sequence_mesh',
    'shard_batch_seq',
    'sequence_features',
    'sequence_labels',
    'sequence_values',
    'sequence_rate',
]


# ------------------------------------------------------------- families ----


class _Family(NamedTuple):
    """Everything family-specific the sequence kernels need.

    ``formula`` takes ``(get, lag, p_scores, p_concedes, psp, pcp)`` where
    ``get(field)`` returns the local column and ``lag(field)`` its lag-1
    view (halo-fed), and must flow through the family's ``vaep_core`` so
    sharded and unsharded formulas cannot diverge.
    """

    name: str
    batch_cls: type
    seq_fields: Tuple[str, ...]  # every (G, A) field of the batch
    state_fields: Tuple[str, ...]  # the subset the state views consume
    make_states: Callable[[Any, int], Any]
    kernels: Dict[str, Callable]
    goal_masks: Callable[[Any], Tuple[jax.Array, jax.Array]]  # batch -> (goals, owngoals)
    formula: Callable


def _standard_formula(
    get: Callable[[str], Any],
    lag: Callable[[str], Any],
    ps: Any,
    pc: Any,
    psp: Any,
    pcp: Any,
) -> Any:
    from ..ops.formula import vaep_core

    return vaep_core(
        get('type_id'),
        get('time_seconds'),
        ps,
        pc,
        type_prev=lag('type_id'),
        result_prev=lag('result_id'),
        sameteam=lag('is_home') == get('is_home'),
        time_prev=lag('time_seconds'),
        p_scores_prev=psp,
        p_concedes_prev=pcp,
    )


def _atomic_formula(
    get: Callable[[str], Any],
    lag: Callable[[str], Any],
    ps: Any,
    pc: Any,
    psp: Any,
    pcp: Any,
) -> Any:
    from ..ops.atomic import vaep_core

    return vaep_core(
        ps,
        pc,
        type_prev=lag('type_id'),
        sameteam=lag('is_home') == get('is_home'),
        p_scores_prev=psp,
        p_concedes_prev=pcp,
    )


@functools.cache
def _standard_family() -> _Family:
    from ..ops.features import KERNELS, _States
    from ..ops.labels import _goal_masks

    seq = (
        'type_id', 'result_id', 'bodypart_id', 'period_id', 'is_home',
        'time_seconds', 'start_x', 'start_y', 'end_x', 'end_y', 'mask',
        'row_index',
    )
    return _Family(
        name='standard',
        batch_cls=ActionBatch,
        seq_fields=seq,
        state_fields=tuple(f for f in seq if f not in ('mask', 'row_index')),
        make_states=_States,
        kernels=KERNELS,
        goal_masks=lambda b: _goal_masks(b.type_id, b.result_id),
        formula=_standard_formula,
    )


@functools.cache
def _atomic_family() -> _Family:
    from ..ops.atomic import ATOMIC_KERNELS, _AtomicStates, _goal_masks

    seq = (
        'type_id', 'bodypart_id', 'period_id', 'is_home', 'time_seconds',
        'x', 'y', 'dx', 'dy', 'mask', 'row_index',
    )
    return _Family(
        name='atomic',
        batch_cls=AtomicActionBatch,
        seq_fields=seq,
        state_fields=tuple(f for f in seq if f not in ('mask', 'row_index')),
        make_states=_AtomicStates,
        kernels=ATOMIC_KERNELS,
        goal_masks=lambda b: _goal_masks(b.type_id),
        formula=_atomic_formula,
    )


def _family_of(batch: Any) -> _Family:
    if isinstance(batch, AtomicActionBatch):
        return _atomic_family()
    if isinstance(batch, ActionBatch):
        return _standard_family()
    raise TypeError(f'not an action batch: {type(batch).__name__}')


# ----------------------------------------------------------------- mesh ----


def make_sequence_mesh(n_devices: int = None, seq_parallel: int = 2) -> Mesh:
    """A ``(games, seq)`` mesh: data-parallel games × sequence shards."""
    import numpy as np

    devices = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    n = len(devices)
    if n % seq_parallel != 0:
        raise ValueError(f'seq_parallel={seq_parallel} does not divide {n} devices')
    arr = np.asarray(devices).reshape(n // seq_parallel, seq_parallel)
    return Mesh(arr, axis_names=('games', 'seq'))


def shard_batch_seq(batch: Any, mesh: Mesh) -> Any:
    """Place a batch with games over ``'games'`` AND actions over ``'seq'``.

    Accepts standard and atomic batches. The action axis must divide by
    the ``'seq'`` axis size (pad with
    :func:`~socceraction_tpu.core.batch.pad_length` / ``max_actions`` at
    pack time); the game axis is padded like
    :func:`~socceraction_tpu.parallel.mesh.shard_batch`.
    """
    from .mesh import pad_games

    fam = _family_of(batch)
    batch = pad_games(batch, mesh.shape['games'])
    if batch.max_actions % mesh.shape['seq'] != 0:
        raise ValueError(
            f'action axis {batch.max_actions} does not divide over '
            f"seq={mesh.shape['seq']} shards; pack with a divisible max_actions"
        )
    seq_sh = NamedSharding(mesh, P('games', 'seq'))
    game_sh = NamedSharding(mesh, P('games'))

    def place(name, x):
        return jax.device_put(x, seq_sh if name in fam.seq_fields else game_sh)

    return fam.batch_cls(
        **{
            name: place(name, getattr(batch, name))
            for name in (*fam.seq_fields, 'n_actions', 'game_id')
        }
    )


def _batch_specs(fam: _Family) -> Any:
    """PartitionSpec pytree for a sequence-sharded batch of ``fam``."""
    specs = {f: P('games', 'seq') for f in fam.seq_fields}
    specs['n_actions'] = P('games')
    specs['game_id'] = P('games')
    return fam.batch_cls(**specs)


# ---------------------------------------------------------------- halos ----


def _check_halo(h: int, local_width: int) -> None:
    if h > local_width:
        raise ValueError(
            f'halo width {h} exceeds the local shard width {local_width}; '
            'a shard only holds its neighbor-adjacent columns once — use '
            'fewer seq shards or a larger max_actions at pack time'
        )


def _left_halo(x: jax.Array, h: int, axis_name: str) -> jax.Array:
    """``(G, h)`` columns owned by the left neighbor (edge: replicate col 0).

    The edge fill IS the kernels' clamp semantics: the unsharded shifts
    read ``max(j - i, 0)`` — row 0 of the game — and games are
    left-aligned, so shard 0's first local column is the game's first row.
    """
    _check_halo(h, x.shape[1])
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    tail = x[:, -h:]
    recv = jax.lax.ppermute(tail, axis_name, [(i, (i + 1) % n) for i in range(n)])
    edge = jnp.broadcast_to(x[:, :1], (*x.shape[:-1], h))
    return jnp.where(idx == 0, edge, recv)


def _right_halo(x: jax.Array, h: int, axis_name: str) -> jax.Array:
    """``(G, h)`` columns owned by the right neighbor (edge: replicate last)."""
    _check_halo(h, x.shape[1])
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    head = x[:, :h]
    recv = jax.lax.ppermute(head, axis_name, [(i, (i - 1) % n) for i in range(n)])
    edge = jnp.broadcast_to(x[:, -1:], (*x.shape[:-1], h))
    return jnp.where(idx == n - 1, edge, recv)


def _extend(x: jax.Array, hl: int, hr: int, axis_name: str) -> jax.Array:
    parts = []
    if hl:
        parts.append(_left_halo(x, hl, axis_name).astype(x.dtype))
    parts.append(x)
    if hr:
        parts.append(_right_halo(x, hr, axis_name).astype(x.dtype))
    return jnp.concatenate(parts, axis=1)


def _extended_batch(fam: _Family, batch: Any, hl: int, hr: int, axis_name: str) -> Any:
    """Local batch whose state fields carry ``hl``/``hr`` halo columns.

    Only ``fam.state_fields`` are exchanged — ``mask``/``row_index`` are
    never read from an extended view, so their halos would be pure wasted
    ICI traffic.
    """
    return batch.replace(
        **{
            f: _extend(getattr(batch, f), hl, hr, axis_name)
            for f in fam.state_fields
        }
    )


# ----------------------------------------------------------- goalscore ----


def _goalscore_seq(fam: _Family, batch: Any, axis_name: str) -> jax.Array:
    """Cross-shard ``goalscore`` block: local cumsum + exclusive shard scan.

    Mirrors the family's ``_goalscore`` kernel exactly, with the two
    global quantities rebuilt from collectives: the game's first-action
    team (column 0 of shard 0, via ``all_gather``) and the pre-shard goal
    prefix (exclusive scan of per-shard counts).
    """
    team = batch.is_home
    goals, owngoals = fam.goal_masks(batch)

    # team "A" = team of the game's FIRST action = shard 0's column 0
    firsts = jax.lax.all_gather(team[:, 0], axis_name)  # (n_seq, G)
    teamisA = team == firsts[0][:, None]
    f = jnp.float32
    goalsA = (goals & teamisA) | (owngoals & ~teamisA)
    goalsB = (goals & ~teamisA) | (owngoals & teamisA)

    def prefixed(g):
        local = jnp.cumsum(g.astype(f), axis=1) - g.astype(f)
        sums = jax.lax.all_gather(g.astype(f).sum(axis=1), axis_name)  # (n, G)
        n = axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        before = (jnp.arange(n) < idx)[:, None]  # exclusive scan mask
        return local + (sums * before).sum(axis=0)[:, None]

    scoreA, scoreB = prefixed(goalsA), prefixed(goalsB)
    team_score = jnp.where(teamisA, scoreA, scoreB)
    opp_score = jnp.where(teamisA, scoreB, scoreA)
    return jnp.stack([team_score, opp_score, team_score - opp_score], axis=-1)


# ------------------------------------------------------------- kernels ----


def sequence_features(
    batch: Any, mesh: Mesh, *, names: Tuple[str, ...], k: int
) -> jax.Array:
    """``(G, A, F)`` features with the action axis sharded over ``'seq'``.

    Identical values to the family's unsharded ``compute_features``;
    communication is one ``HL``-column halo exchange plus goalscore's
    scalar collectives.
    """
    fam = _family_of(batch)
    hl = max(k - 1, 0)

    def local(b) -> jax.Array:
        ext = _extended_batch(fam, b, hl, 0, 'seq')
        s = fam.make_states(ext, k)
        blocks = []
        for name in names:
            if name == 'goalscore':
                blocks.append(_goalscore_seq(fam, b, 'seq'))
            else:
                blocks.append(fam.kernels[name](s)[:, hl:])
        return jnp.concatenate(blocks, axis=-1)

    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(_batch_specs(fam),),
            out_specs=P('games', 'seq', None),
        )
    )
    return fn(batch)


def sequence_labels(
    batch: Any, mesh: Mesh, *, nr_actions: int = 10
) -> Tuple[jax.Array, jax.Array]:
    """``scores``/``concedes`` labels with the action axis sharded.

    Identical values to the family's unsharded ``scores_concedes`` on
    valid rows (padded rows carry arbitrary values on both paths). The
    per-game tail clamp (``min(j + i, last_valid)``) is evaluated in local
    coordinates: shards left of the clamp gather true neighbor values from
    the right halo, the shard containing it clamps exactly, and shards
    past it hold only padding.
    """
    fam = _family_of(batch)
    hr = nr_actions - 1

    def local(b) -> Tuple[jax.Array, jax.Array]:
        goal, owngoal = fam.goal_masks(b)
        team = b.is_home
        goal_e = _extend(goal, 0, hr, 'seq')
        owngoal_e = _extend(owngoal, 0, hr, 'seq')
        team_e = _extend(team, 0, hr, 'seq')

        A_loc = goal.shape[1]
        offset = jax.lax.axis_index('seq') * A_loc
        # per-game last valid row, in local coordinates (may be negative
        # for pure-padding shards: those rows are masked downstream)
        last_loc = (b.n_actions - 1 - offset)[:, None]

        scores, concedes = goal, owngoal
        for i in range(1, nr_actions):
            idx = jnp.clip(
                jnp.minimum(jnp.arange(A_loc) + i, last_loc), 0, A_loc + hr - 1
            )
            goal_i = jnp.take_along_axis(goal_e, idx, axis=1)
            owngoal_i = jnp.take_along_axis(owngoal_e, idx, axis=1)
            same = jnp.take_along_axis(team_e, idx, axis=1) == team
            scores = scores | (goal_i & same) | (owngoal_i & ~same)
            concedes = concedes | (goal_i & ~same) | (owngoal_i & same)
        return scores, concedes

    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(_batch_specs(fam),),
            out_specs=(P('games', 'seq'), P('games', 'seq')),
        )
    )
    return fn(batch)


def sequence_values(
    batch: Any, p_scores: jax.Array, p_concedes: jax.Array, mesh: Mesh
) -> jax.Array:
    """``(G, A, 3)`` VAEP values with the action axis sharded.

    Identical to the family's unsharded ``vaep_values`` — both flow
    through the family's ``vaep_core``; the lag-1 dependence needs a
    single-column left halo.
    """
    fam = _family_of(batch)

    def local(b, ps: jax.Array, pc: jax.Array) -> jax.Array:
        def lag_arr(cur):
            halo = _left_halo(cur, 1, 'seq')
            return jnp.concatenate([halo, cur[:, :-1]], axis=1)

        return fam.formula(
            lambda f: getattr(b, f),
            lambda f: lag_arr(getattr(b, f)),
            ps,
            pc,
            lag_arr(ps),
            lag_arr(pc),
        )

    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(_batch_specs(fam), P('games', 'seq'), P('games', 'seq')),
            out_specs=P('games', 'seq', None),
        )
    )
    return fn(batch, p_scores, p_concedes)


def sequence_rate(model: Any, batch: Any, mesh: Mesh) -> jax.Array:
    """``(G, A, 3)`` VAEP values with the action axis sharded end-to-end.

    The sequence-parallel twin of ``VAEP.rate_batch`` /
    :func:`~socceraction_tpu.parallel.vaep.sharded_rate` for both
    families: the fused combined-table forward
    (:mod:`socceraction_tpu.ops.fused`) runs on each shard's
    halo-extended view — probabilities for the halo columns come out of
    the same forward pass, so the formula's lag-1 needs no second
    collective — and only the bounded halos ever cross ICI. ``model`` is
    a fitted VAEP or AtomicVAEP with MLP heads.
    """
    from ..ops.fused import REGISTRIES, fused_pair_logits

    fam = _family_of(batch)
    if not model._can_fuse():
        raise ValueError(
            "sequence_rate needs fitted on-device MLP heads (learner='mlp')"
        )
    if model._fused_registry != fam.name:
        raise ValueError(
            f'model feature family {model._fused_registry!r} does not match '
            f'the batch family {fam.name!r}'
        )
    clf_s, clf_c = (model._models[c] for c in model._label_columns)
    names = model._kernel_names()
    k = model.nb_prev_actions
    registry = REGISTRIES[model._fused_registry]
    # the formula lags 1 action, and that previous column's OWN forward
    # needs its k-1 lookback states, so the halo is k columns wide
    hl = k

    def local(b) -> jax.Array:
        ext = _extended_batch(fam, b, hl, 0, 'seq')

        # goalscore is the one dense block with whole-sequence dependence
        # (running-score prefix): inject the cross-shard-corrected values,
        # halo columns included, instead of the shard-local cumsum the
        # kernel would compute
        overrides = None
        if 'goalscore' in names:
            gs = _goalscore_seq(fam, b, 'seq')  # (G, A_loc, 3), corrected
            gs_ext = jnp.stack(
                [_extend(gs[..., c], hl, 0, 'seq') for c in range(gs.shape[-1])],
                axis=-1,
            )
            overrides = {'goalscore': gs_ext}

        # stacked two-head fold: one combined-table gather per state and
        # one dense matmul serve both heads (ops/fused.py module NOTE)
        logit_s, logit_c = fused_pair_logits(
            clf_s.params, clf_c.params, ext, names=names, k=k,
            hidden_layers_a=len(clf_s.hidden),
            hidden_layers_b=len(clf_c.hidden),
            mean_a=clf_s.mean_, std_a=clf_s.std_,
            mean_b=clf_c.mean_, std_b=clf_c.std_,
            registry=registry, dense_overrides=overrides,
        )
        ps_e, pc_e = jax.nn.sigmoid(logit_s), jax.nn.sigmoid(logit_c)

        # lag-1 views: local column j's predecessor is extended column
        # hl + j - 1 (the halo supplies j = 0's)
        def lag_ext(x_ext):
            return jax.lax.slice_in_dim(
                x_ext, hl - 1, hl - 1 + b.type_id.shape[1], axis=1
            )

        return fam.formula(
            lambda f: getattr(b, f),
            lambda f: lag_ext(getattr(ext, f)),
            ps_e[:, hl:],
            pc_e[:, hl:],
            lag_ext(ps_e),
            lag_ext(pc_e),
        )

    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(_batch_specs(fam),),
            out_specs=P('games', 'seq', None),
        )
    )
    return fn(batch)
