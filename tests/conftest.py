"""Shared test fixtures.

Tests run on a virtual 8-device CPU mesh. In this image a sitecustomize
hook registers the remote-TPU ("axon") PJRT plugin at *interpreter startup*
and latches JAX_PLATFORMS before any test code runs, so setting env vars
here is too late -- instead the conftest re-execs pytest once with a clean
CPU environment (axon registration disabled via empty
PALLAS_AXON_POOL_IPS). Benchmarks (bench.py) run on the real TPU.
"""

import os
import sys


def pytest_configure(config):
    if os.environ.get('SOCCERACTION_TPU_TEST_ENV') == '1':
        return
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from socceraction_tpu.utils.env import cpu_device_env

    # override=False: an --xla_force_host_platform_device_count already in
    # XLA_FLAGS wins, so callers can pin their own mesh size
    env = cpu_device_env(8, override=False)
    env['SOCCERACTION_TPU_TEST_ENV'] = '1'
    # pytest has already dup2'd fd 1/2 into its capture files; restore them
    # so the re-exec'd run writes to the real terminal.
    capman = config.pluginmanager.getplugin('capturemanager')
    if capman is not None:
        capman.stop_global_capturing()
    args = list(config.invocation_params.args)
    os.execve(sys.executable, [sys.executable, '-m', 'pytest'] + args, env)

import json
from pathlib import Path

import pandas as pd
import pytest

DATA_DIR = Path(__file__).parent / 'datasets'

#: Shared skip for the shard_map compute tiers. The gate is the compat
#: shim (``ops/compat.py``), not the top-level ``jax.shard_map`` alias:
#: jax builds that predate the promotion still ship the experimental
#: home, the shim resolves it, and every library call site dispatches
#: through the shim — so these tiers run wherever the shim resolves
#: (including this image). Test modules import this marker from conftest
#: so the condition and reason live in exactly one place.
from socceraction_tpu.ops.compat import has_shard_map

requires_shard_map = pytest.mark.skipif(
    not has_shard_map(),
    reason='no shard_map in this jax build (neither jax.shard_map nor '
    'jax.experimental.shard_map resolves)',
)


@pytest.fixture(scope='session')
def spadl_actions() -> pd.DataFrame:
    """The 200-action golden SPADL snapshot (game 8657).

    Provenance: vendored VERBATIM from the reference's checked-in golden
    test data (reference ``tests/datasets/spadl/spadl.json``; byte-identical)
    so it can serve as the bit-exact oracle. The reference generated it with
    ``create_spadl(8657, 777)`` (reference tests/datasets/download.py:303);
    team 777 does not occur in game 8657 (teams are 782 and 768), so every
    action was coordinate-mirrored during that conversion. Tests treat the
    frame purely as a fixed SPADL input, so the orientation quirk is
    irrelevant to what they assert.
    """
    df = pd.read_json(DATA_DIR / 'spadl' / 'spadl.json')
    return df


@pytest.fixture(scope='session')
def atomic_spadl_actions() -> pd.DataFrame:
    """The golden Atomic-SPADL snapshot for the same game.

    Vendored verbatim from the reference's golden data (byte-identical),
    same provenance as :func:`spadl_actions`.
    """
    df = pd.read_json(DATA_DIR / 'spadl' / 'atomic_spadl.json')
    return df


@pytest.fixture(scope='session')
def sb_worldcup_store():
    """Read-only handle on the real WC2018 per-game SPADL store.

    The @e2e tier's data source (reference fixture ``sb_worldcup_data``,
    upstream tests/conftest.py). Built by ``tests/datasets/download.py``
    from the StatsBomb open data; skips when the store is absent (e.g. in
    an air-gapped environment).
    """
    from socceraction_tpu.pipeline import SeasonStore

    path = Path(
        os.environ.get(
            'SOCCERACTION_TPU_WC_STORE',
            DATA_DIR / 'statsbomb' / 'spadl-WorldCup-2018.h5',
        )
    )
    if not path.exists():
        pytest.skip(
            'WC2018 SPADL store not available; run '
            '`python tests/datasets/download.py` (requires network egress) '
            'or point SOCCERACTION_TPU_WC_STORE at a stand-in store '
            '(tests/datasets/make_synthetic_store.py)'
        )
    store = SeasonStore(str(path), mode='r')
    yield store
    store.close()


@pytest.fixture(scope='session')
def home_team_id() -> int:
    """Home team id tests pass alongside the golden snapshot.

    We use 782 -- the game's actual home side -- so that direction-sensitive
    code paths exercise both the mirrored and unmirrored branches (the
    snapshot itself contains both teams' actions). This does NOT claim the
    snapshot was generated with 782; see :func:`spadl_actions` provenance.
    """
    return 782
