"""Shared test fixtures.

Tests run on a virtual 8-device CPU mesh: the env vars below must be set
before jax is first imported, which this conftest guarantees by being the
pytest entry point. Benchmarks (bench.py) run on real TPU hardware instead.
"""

import os

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
xla_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in xla_flags:
    os.environ['XLA_FLAGS'] = (
        xla_flags + ' --xla_force_host_platform_device_count=8'
    ).strip()

import json
from pathlib import Path

import pandas as pd
import pytest

DATA_DIR = Path(__file__).parent / 'datasets'


@pytest.fixture(scope='session')
def spadl_actions() -> pd.DataFrame:
    """The 200-action golden SPADL snapshot (game 8657, home team 782)."""
    df = pd.read_json(DATA_DIR / 'spadl' / 'spadl.json')
    return df


@pytest.fixture(scope='session')
def atomic_spadl_actions() -> pd.DataFrame:
    """The golden Atomic-SPADL snapshot derived from the same game."""
    df = pd.read_json(DATA_DIR / 'spadl' / 'atomic_spadl.json')
    return df


@pytest.fixture(scope='session')
def home_team_id() -> int:
    """Home team used for the golden snapshot game.

    Note: the reference generated the snapshot with ``create_spadl(8657, 777)``
    (reference tests/datasets/download.py:303) but team 777 does not occur in
    game 8657 (teams are 782 and 768), so every action was mirrored during
    conversion. We use 782 -- the game's actual home side -- so that
    direction-sensitive tests exercise both branches.
    """
    return 782
