"""Shared test fixtures.

Tests run on a virtual 8-device CPU mesh. In this image a sitecustomize
hook registers the remote-TPU ("axon") PJRT plugin at *interpreter startup*
and latches JAX_PLATFORMS before any test code runs, so setting env vars
here is too late -- instead the conftest re-execs pytest once with a clean
CPU environment (axon registration disabled via empty
PALLAS_AXON_POOL_IPS). Benchmarks (bench.py) run on the real TPU.
"""

import os
import sys


def pytest_configure(config):
    if os.environ.get('SOCCERACTION_TPU_TEST_ENV') == '1':
        return
    env = dict(os.environ)
    env['SOCCERACTION_TPU_TEST_ENV'] = '1'
    env['JAX_PLATFORMS'] = 'cpu'
    env['PALLAS_AXON_POOL_IPS'] = ''  # skip remote-TPU plugin registration
    xla_flags = env.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in xla_flags:
        env['XLA_FLAGS'] = (
            xla_flags + ' --xla_force_host_platform_device_count=8'
        ).strip()
    # pytest has already dup2'd fd 1/2 into its capture files; restore them
    # so the re-exec'd run writes to the real terminal.
    capman = config.pluginmanager.getplugin('capturemanager')
    if capman is not None:
        capman.stop_global_capturing()
    args = list(config.invocation_params.args)
    os.execve(sys.executable, [sys.executable, '-m', 'pytest'] + args, env)

import json
from pathlib import Path

import pandas as pd
import pytest

DATA_DIR = Path(__file__).parent / 'datasets'


@pytest.fixture(scope='session')
def spadl_actions() -> pd.DataFrame:
    """The 200-action golden SPADL snapshot (game 8657, home team 782)."""
    df = pd.read_json(DATA_DIR / 'spadl' / 'spadl.json')
    return df


@pytest.fixture(scope='session')
def atomic_spadl_actions() -> pd.DataFrame:
    """The golden Atomic-SPADL snapshot derived from the same game."""
    df = pd.read_json(DATA_DIR / 'spadl' / 'atomic_spadl.json')
    return df


@pytest.fixture(scope='session')
def home_team_id() -> int:
    """Home team used for the golden snapshot game.

    Note: the reference generated the snapshot with ``create_spadl(8657, 777)``
    (reference tests/datasets/download.py:303) but team 777 does not occur in
    game 8657 (teams are 782 and 768), so every action was mirrored during
    conversion. We use 782 -- the game's actual home side -- so that
    direction-sensitive tests exercise both branches.
    """
    return 782
