"""Tests for mesh-sharded serving (socceraction_tpu.parallel.serve +
the replica fan-out inside serve/).

Covers the ISSUE-16 contract: per-replica lane dispatch bitwise equal
to ``rate_batch``, the ``shard_map`` gang form, 1-vs-N replica service
parity, mesh-wide hot-swap atomicity (one lane's failed warm aborts
the swap for every lane), single-sick-replica degradation (one tripped
breaker degrades that lane ALONE onto the fallback while the others
stay fused and health names the replica), the N-lane MicroBatcher's
crash isolation, and the unix-socket front end's RPC round trip —
including deadline propagation and ``obsctl trace`` stitching the
client hop to the replica flush on the preserved request id.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from conftest import requires_shard_map
from socceraction_tpu.core.batch import pack_actions, unpack_values
from socceraction_tpu.core.synthetic import synthetic_actions_frame
from socceraction_tpu.obs import REGISTRY
from socceraction_tpu.obs import trace as obs_trace
from socceraction_tpu.parallel import data_parallel_rate
from socceraction_tpu.parallel.serve import ReplicaDispatcher
from socceraction_tpu.resil.faults import FaultPlan, FaultSpec
from socceraction_tpu.serve import (
    MicroBatcher,
    ModelRegistry,
    RatingService,
)
from socceraction_tpu.serve.frontend import (
    FrontendClient,
    FrontendError,
    ServingFrontend,
)
from socceraction_tpu.vaep.base import VAEP

HOME = 100
MAX_ACTIONS = 512
N_REPLICAS = 4


def _fit_model(hidden=(16,), seed_games=(0, 1)):
    frames = [
        synthetic_actions_frame(game_id=i, seed=i, n_actions=300)
        for i in seed_games
    ]
    model = VAEP()
    X, y = [], []
    for i, f in zip(seed_games, frames):
        game = pd.Series({'game_id': i, 'home_team_id': HOME})
        X.append(model.compute_features(game, f))
        y.append(model.compute_labels(game, f))
    np.random.seed(0)
    model.fit(
        pd.concat(X, ignore_index=True),
        pd.concat(y, ignore_index=True),
        learner='mlp',
        tree_params={'hidden': hidden, 'max_epochs': 2},
    )
    return model


@pytest.fixture(scope='module')
def model():
    return _fit_model()


@pytest.fixture(scope='module')
def model_b():
    """Same feature layout, different weights (the hot-swap partner)."""
    return _fit_model(seed_games=(2, 3))


@pytest.fixture
def mesh_registry(tmp_path, model, model_b):
    reg = ModelRegistry(str(tmp_path / 'models'))
    reg.publish('vaep', '1', model)
    reg.publish('vaep', '2', model_b)
    reg.activate('vaep', '1')
    return reg


def _reference(model, frame, max_actions=MAX_ACTIONS):
    batch, _ = pack_actions(frame, home_team_id=HOME, max_actions=max_actions)
    return unpack_values(model.rate_batch(batch, bucket=False), batch)


def _frames(n, base=50, lo=60, hi=200):
    rng = np.random.default_rng(base)
    return [
        synthetic_actions_frame(
            game_id=base + i, seed=base + i,
            n_actions=int(rng.integers(lo, hi)),
        )
        for i in range(n)
    ]


# ----------------------------------------------------- ReplicaDispatcher ----


def test_lane_dispatch_is_bitwise_the_single_device_path(model):
    """Every replica lane returns exactly ``rate_batch(bucket=False)``'s
    values: same program, same statics — only argument placement moves."""
    frame = synthetic_actions_frame(game_id=50, seed=50, n_actions=200)
    batch, _ = pack_actions(frame, home_team_id=HOME, max_actions=MAX_ACTIONS)
    ref = np.asarray(model.rate_batch(batch, bucket=False))
    disp = ReplicaDispatcher(model, n_replicas=N_REPLICAS)
    assert len(disp.devices) == N_REPLICAS
    for r in range(N_REPLICAS):
        out = disp.rate_replica(r, batch)
        assert out.shape == ref.shape
        np.testing.assert_array_equal(out, ref)


def test_lane_dispatch_goalscore_override_parity(model):
    """An override rides the lane dispatch bitwise too (it SUBSTITUTES
    the computed feature, so parity must hold under it as well)."""
    frame = synthetic_actions_frame(game_id=51, seed=51, n_actions=150)
    batch, _ = pack_actions(frame, home_team_id=HOME, max_actions=MAX_ACTIONS)
    gs = np.random.default_rng(0).normal(
        size=(batch.n_games, batch.max_actions, 3)
    ).astype(np.float32)
    ref = np.asarray(
        model.rate_batch(batch, dense_overrides={'goalscore': gs}, bucket=False)
    )
    disp = ReplicaDispatcher(model, n_replicas=2)
    np.testing.assert_array_equal(disp.rate_replica(1, batch, gs), ref)


@requires_shard_map
def test_gang_dispatch_parity(model):
    """``rate_mesh``: one shard_map over ('replicas',) returns each
    shard's values — the single-replica gang bitwise, the 4-replica
    gang within float tolerance of the single-device program."""
    frame = synthetic_actions_frame(game_id=52, seed=52, n_actions=180)
    batch, _ = pack_actions(frame, home_team_id=HOME, max_actions=MAX_ACTIONS)
    ref = np.asarray(model.rate_batch(batch, bucket=False))

    (g1,) = ReplicaDispatcher(model, n_replicas=1).rate_mesh([batch])
    np.testing.assert_array_equal(g1, ref)

    disp = ReplicaDispatcher(model, n_replicas=N_REPLICAS)
    outs = disp.rate_mesh([batch] * N_REPLICAS)
    assert len(outs) == N_REPLICAS
    for out in outs:
        np.testing.assert_allclose(out, ref, atol=1e-5)


@requires_shard_map
def test_gang_dispatch_rejects_mixed_goalscore(model):
    """All-or-none: a goalscore override replaces the computed dense
    block, so a mixed gang (zeros are not "no override") must refuse."""
    frame = synthetic_actions_frame(game_id=53, seed=53, n_actions=100)
    batch, _ = pack_actions(frame, home_team_id=HOME, max_actions=MAX_ACTIONS)
    gs = np.zeros((batch.n_games, batch.max_actions, 3), dtype=np.float32)
    disp = ReplicaDispatcher(model, n_replicas=2)
    with pytest.raises(ValueError, match='every replica or for none'):
        disp.rate_mesh([batch, batch], [gs, None])
    ref = np.asarray(
        model.rate_batch(batch, dense_overrides={'goalscore': gs}, bucket=False)
    )
    for out in disp.rate_mesh([batch, batch], [gs, gs]):
        np.testing.assert_allclose(out, ref, atol=1e-5)


def test_dispatcher_validates_topology(model):
    import jax

    with pytest.raises(ValueError, match='n_replicas must be >= 1'):
        ReplicaDispatcher(model, 0)
    with pytest.raises(ValueError, match='devices are available'):
        ReplicaDispatcher(model, jax.device_count() + 1)


@requires_shard_map
def test_data_parallel_rate_matches_single_device(model):
    frame = synthetic_actions_frame(game_id=54, seed=54, n_actions=160)
    batch, _ = pack_actions(frame, home_team_id=HOME, max_actions=MAX_ACTIONS)
    ref = np.asarray(model.rate_batch(batch, bucket=False))
    outs = data_parallel_rate(model, [batch] * N_REPLICAS)
    assert len(outs) == N_REPLICAS
    for out in outs:
        np.testing.assert_allclose(out, ref, atol=1e-5)
    with pytest.raises(ValueError, match='one batch per replica'):
        data_parallel_rate(model, [batch, batch], n_replicas=4)


# ------------------------------------------------- N-replica RatingService ----


def test_one_vs_four_replica_service_bitwise_parity(model):
    """The mesh service is a pure fan-out: its values are bitwise the
    single-replica service's for the same requests, health carries the
    per-replica block, and steady traffic compiles nothing."""
    frames = _frames(8)
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=4, max_wait_ms=1.0
    ) as svc1:
        ref = [svc1.rate_sync(f, home_team_id=HOME, timeout=60) for f in frames]
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=4, max_wait_ms=1.0,
        n_replicas=N_REPLICAS,
    ) as svc:
        assert svc.replica_ids == ('r0', 'r1', 'r2', 'r3')
        svc.warmup()
        futs = [svc.rate(f, home_team_id=HOME) for f in frames]
        for r, fut in zip(ref, futs):
            out = fut.result(timeout=60)
            assert (out.index == r.index).all()
            np.testing.assert_array_equal(out.to_numpy(), r.to_numpy())

        health = svc.health()
        assert health['status'] == 'ok'
        replicas = health['replicas']
        assert replicas['n'] == N_REPLICAS and replicas['sick'] == []
        assert set(replicas['per_replica']) == set(svc.replica_ids)

        # steady state: more of the same traffic retraces nothing
        shapes = svc.compiled_shapes
        futs = [svc.rate(f, home_team_id=HOME) for f in frames]
        for fut in futs:
            fut.result(timeout=60)
        assert svc.compiled_shapes == shapes


def test_mesh_service_breaker_topology(model):
    """n_replicas > 1 builds one breaker per lane (a shared instance
    defeats per-replica degradation and is refused at construction)."""
    from socceraction_tpu.resil.breaker import CircuitBreaker

    with pytest.raises(ValueError, match='per-replica'):
        RatingService(
            model, max_actions=256, n_replicas=2,
            breaker=CircuitBreaker(failure_threshold=2, name='serve.dispatch'),
        )
    with RatingService(
        model, max_actions=256, max_batch_size=2, n_replicas=N_REPLICAS,
    ) as svc:
        assert len(svc.breakers) == N_REPLICAS
        assert svc.breaker is svc.breakers[0]
        names = {b.name for b in svc.breakers}
        assert names == {f'serve.dispatch.r{i}' for i in range(N_REPLICAS)}
    with RatingService(
        model, max_actions=256, max_batch_size=2, n_replicas=2,
        breaker_failures=0,
    ) as svc:
        assert svc.breakers == (None, None)


def test_mesh_swap_failed_warm_aborts_all_replicas(mesh_registry, model, model_b):
    """Mesh-wide swap atomicity: EVERY lane warms before any activates.

    A fault injected into a LATER lane's ladder warm (lane 0 already
    warmed clean) must abort the swap for the whole mesh — no lane ever
    serves version 2 — and the same swap succeeds once the fault clears.
    """
    probe = synthetic_actions_frame(game_id=90, seed=90, n_actions=150)
    ref_a = np.asarray(_reference(model, probe))
    ref_b = np.asarray(_reference(model_b, probe))
    assert not np.array_equal(ref_a, ref_b)

    with RatingService(
        registry=mesh_registry, max_actions=MAX_ACTIONS, max_batch_size=4,
        max_wait_ms=1.0, n_replicas=N_REPLICAS,
    ) as svc:
        svc.warmup()
        out = svc.rate_sync(probe, home_team_id=HOME, timeout=60)
        np.testing.assert_array_equal(out.to_numpy(), ref_a)

        # the warm loop is deterministic — lane 0 takes calls
        # 1..len(ladder), lane 1 the next len(ladder), ... — so failing
        # call len(ladder)+2 fails lane 1's warm AFTER lane 0 finished
        k = len(svc.ladder) + 2
        plan = FaultPlan(
            seed=0,
            specs=[
                FaultSpec('serve.dispatch', error=RuntimeError, on_calls=(k,))
            ],
        )
        with plan:
            with pytest.raises(RuntimeError):
                svc.swap_model('vaep', '2')
        assert [h['point'] for h in plan.history] == ['serve.dispatch']

        # no mixed-version mesh: every subsequent request (whatever lane
        # flushes it) still serves version 1, bitwise — and the failed
        # rollout degraded nothing
        for _ in range(N_REPLICAS):
            out = svc.rate_sync(probe, home_team_id=HOME, timeout=60)
            np.testing.assert_array_equal(out.to_numpy(), ref_a)
        health = svc.health()
        assert health['model']['version'] == '1'
        assert health['status'] == 'ok'

        # fault cleared: the identical swap lands mesh-wide, and
        # rollback restores version 1 — both bitwise
        assert svc.swap_model('vaep', '2') == ('vaep', '2')
        out = svc.rate_sync(probe, home_team_id=HOME, timeout=60)
        np.testing.assert_array_equal(out.to_numpy(), ref_b)
        assert svc.rollback_model() == ('vaep', '1')
        out = svc.rate_sync(probe, home_team_id=HOME, timeout=60)
        np.testing.assert_array_equal(out.to_numpy(), ref_a)


def test_single_sick_replica_degrades_alone(model):
    """One lane's open breaker degrades THAT lane onto the materialized
    fallback; the other lanes keep dispatching fused, every caller still
    gets correct values, and health names the sick replica."""
    sick = 2
    rid = f'r{sick}'
    frames = _frames(4, base=80, lo=80, hi=120)
    expected = [np.asarray(_reference(model, f)) for f in frames]
    before = REGISTRY.snapshot()

    def fallbacks(snap, replica):
        return snap.value('serve/fallback_flushes', replica=replica)

    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=2, max_wait_ms=1.0,
        n_replicas=N_REPLICAS, breaker_failures=2, breaker_recovery_s=1000.0,
    ) as svc:
        svc.warmup()
        # deterministic trip: two consecutive failures recorded on lane
        # 2's breaker (a FaultSpec matches the point name mesh-wide and
        # cannot single out a lane)
        svc.breakers[sick].record_failure(RuntimeError('induced device fault'))
        svc.breakers[sick].record_failure(RuntimeError('induced device fault'))
        assert svc.breakers[sick].state == 'open'

        health = svc.health()
        assert health['status'] == 'degraded'
        assert health['replicas']['sick'] == [rid]
        per = health['replicas']['per_replica']
        assert per[rid]['healthy'] is False
        assert per[rid]['breaker']['state'] == 'open'
        for other in svc.replica_ids:
            if other != rid:
                assert per[other]['healthy'] is True

        # drive traffic until the sick lane has served at least one
        # fallback flush (lanes race for the queue, so which lane
        # flushes a given request is scheduling-dependent)
        sick_served = False
        deadline = time.monotonic() + 60.0
        while not sick_served and time.monotonic() < deadline:
            futs = [svc.rate(f, home_team_id=HOME) for f in frames]
            for fut, exp in zip(futs, expected):
                np.testing.assert_allclose(
                    fut.result(timeout=60).to_numpy(), exp, atol=1e-4
                )
            snap = REGISTRY.snapshot()
            sick_served = fallbacks(snap, rid) > fallbacks(before, rid)
        assert sick_served, 'sick lane never took a flush in 60s'

        # the healthy lanes never fell back — degradation stayed local
        snap = REGISTRY.snapshot()
        for other in svc.replica_ids:
            if other != rid:
                assert fallbacks(snap, other) == fallbacks(before, other)
        assert svc.breakers[sick].state == 'open'  # dwell not elapsed


# --------------------------------------------------- multi-lane batcher ----


def test_batcher_lanes_flush_concurrently():
    """N lanes really drain the one queue in parallel: a barrier only
    every lane can satisfy trips, with each lane's flush in flight at
    the same time."""
    barrier = threading.Barrier(4, timeout=30)
    lanes_seen = set()

    def runner(payloads, bucket, lane):
        barrier.wait()
        lanes_seen.add(lane)
        return [p * 10 for p in payloads]

    with MicroBatcher(
        runner, max_batch_size=1, max_wait_ms=0.0, n_lanes=4,
        lane_names=('r0', 'r1', 'r2', 'r3'),
    ) as b:
        futs = [b.submit(i) for i in range(4)]
        assert sorted(f.result(timeout=30) for f in futs) == [0, 10, 20, 30]
    assert lanes_seen == {0, 1, 2, 3}
    snap = REGISTRY.snapshot()
    for name in ('r0', 'r1', 'r2', 'r3'):
        assert sum(
            snap.value('serve/flushes', reason=reason, replica=name)
            for reason in ('full', 'deadline')
        ) >= 1


def test_batcher_single_lane_death_leaves_survivors_serving():
    """One lane's permanent death retires it ALONE: its taken requests
    re-queue for the survivors, submits keep flowing, and only
    ``dead_lanes`` records the casualty."""
    plan = FaultPlan(
        seed=0,
        specs=[FaultSpec('batcher.flush', error=RuntimeError, on_calls=(1,))],
    )
    with MicroBatcher(
        lambda p, b: [x * 10 for x in p], max_batch_size=1, max_wait_ms=0.0,
        n_lanes=4, max_flusher_restarts=0,
    ) as b:
        with plan:
            futs = [b.submit(i) for i in range(8)]
            assert sorted(f.result(timeout=30) for f in futs) == [
                i * 10 for i in range(8)
            ]
        assert len(b.dead_lanes) == 1
        assert b.crashed is None  # the SERVICE is not dead
        assert b.flusher_alive
        # survivors still serve new submits
        assert b.submit(99).result(timeout=30) == 990
    assert plan.injections() == 1


def test_batcher_all_lanes_dead_fails_queue_and_rejects():
    """Only the LAST live lane's permanent death fails the queue,
    rejects new submits, and fires on_crash exactly once."""
    crashes = []
    plan = FaultPlan(
        seed=0, specs=[FaultSpec('batcher.flush', error=RuntimeError)]
    )
    b = MicroBatcher(
        lambda p, bk: p, max_batch_size=1, max_wait_ms=0.0, n_lanes=2,
        max_flusher_restarts=0, on_crash=crashes.append,
    )
    try:
        with plan:
            fut = b.submit('doomed')
            with pytest.raises(RuntimeError, match='flusher thread died'):
                fut.result(timeout=30)
        assert len(b.dead_lanes) == 2
        assert isinstance(b.crashed, RuntimeError)
        assert not b.flusher_alive
        assert len(crashes) == 1
        with pytest.raises(RuntimeError, match='flusher thread died'):
            b.submit('rejected')
    finally:
        plan.disarm()
        b.close()


def test_batcher_validates_lane_config():
    runner = lambda p, b: p  # noqa: E731
    with pytest.raises(ValueError, match='n_lanes must be >= 1'):
        MicroBatcher(runner, n_lanes=0)
    with pytest.raises(ValueError, match='lane_names'):
        MicroBatcher(runner, n_lanes=2, lane_names=('only-one',))


# ------------------------------------------------------------- front end ----


@pytest.fixture
def frontend(model, tmp_path):
    sock = str(tmp_path / 'frontend.sock')
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=4, max_wait_ms=1.0,
        n_replicas=2,
    ) as svc:
        with ServingFrontend(svc, unix_path=sock):
            yield svc, FrontendClient(sock), sock
    assert not os.path.exists(sock), 'socket not unlinked on close'


def test_frontend_rate_round_trip_is_bitwise(frontend, model):
    svc, client, _sock = frontend
    frame = synthetic_actions_frame(game_id=70, seed=70, n_actions=150)
    ref = svc.rate_sync(frame, home_team_id=HOME, timeout=60)
    out = client.rate(frame, home_team_id=HOME)
    assert list(out.columns) == list(ref.columns)
    assert (out.index == ref.index).all()
    np.testing.assert_array_equal(out.to_numpy(), ref.to_numpy())
    assert client.last_request_id

    health = client.health()
    assert health['status'] == 'ok'
    assert health['replicas']['n'] == 2


def test_frontend_deadline_propagates_to_the_flush(frontend):
    """An impossible client deadline ships over the wire and fails the
    request at the service (504) or sheds it (429) — never serves late
    as if nothing happened."""
    _svc, client, _sock = frontend
    frame = synthetic_actions_frame(game_id=71, seed=71, n_actions=100)
    with pytest.raises(FrontendError) as err:
        client.rate(frame, home_team_id=HOME, deadline_ms=0.001)
    assert err.value.status in (504, 429)
    # a generous deadline rides the same wire field and succeeds
    out = client.rate(frame, home_team_id=HOME, deadline_ms=60_000)
    assert len(out) == len(frame)


def test_frontend_sessions_round_trip(frontend):
    svc, client, _sock = frontend
    frame = synthetic_actions_frame(game_id=72, seed=72, n_actions=120)
    half = len(frame) // 2

    sid = client.open_session('m1', home_team_id=HOME)
    v1 = client.session_add(sid, frame.iloc[:half])
    v2 = client.session_add(sid, frame.iloc[half:])
    ref = svc.open_session('m2', home_team_id=HOME)
    np.testing.assert_array_equal(
        v1.to_numpy(), ref.add_actions(frame.iloc[:half]).to_numpy()
    )
    np.testing.assert_array_equal(
        v2.to_numpy(), ref.add_actions(frame.iloc[half:]).to_numpy()
    )
    client.session_close(sid)
    with pytest.raises(FrontendError) as err:
        client.session_add(sid, frame.iloc[:4])
    assert err.value.status == 400


def test_frontend_error_mapping(frontend):
    _svc, client, _sock = frontend
    with pytest.raises(FrontendError) as err:
        client._call('POST', '/rate', {'actions': {'columns': {}}})
    assert err.value.status == 400
    assert not err.value.retriable
    with pytest.raises(FrontendError) as err:
        client._call('POST', '/nope', {})
    assert err.value.status == 404


def test_frontend_trace_stitches_client_hop_to_replica_flush(model, tmp_path):
    """``obsctl trace <request_id>`` reconstructs the full path: the
    client hop's enqueue/done plus the service hop (hop=1 via
    RequestContext.to_wire) with the flush-segment decomposition — on
    ONE preserved request id across both run logs."""
    sock = str(tmp_path / 'fe.sock')
    log = obs_trace.RunLog(str(tmp_path / 'combined.jsonl'))
    frame = synthetic_actions_frame(game_id=77, seed=77, n_actions=120)
    with log:
        with RatingService(
            model, max_actions=MAX_ACTIONS, max_batch_size=4,
            max_wait_ms=1.0, n_replicas=2,
        ) as svc:
            with ServingFrontend(svc, unix_path=sock):
                client = FrontendClient(sock)
                client.rate(frame, home_team_id=HOME)
                rid = client.last_request_id

    with open(log.path, encoding='utf-8') as fh:
        events = [json.loads(line) for line in fh if line.strip()]
    mine = [
        e for e in events
        if e.get('request_id') == rid
        and e['event'] in ('request_enqueue', 'request_done')
    ]
    # both processes' views landed: hop 0 (client) and hop 1 (service),
    # each with its enqueue and done, on the SAME request id
    by_hop = {}
    for e in mine:
        by_hop.setdefault(int(e.get('hop') or 0), []).append(e)
    assert set(by_hop) == {0, 1}
    for hop, hop_events in by_hop.items():
        assert {e['event'] for e in hop_events} == {
            'request_enqueue', 'request_done'
        }
    service_done = next(
        e for e in by_hop[1] if e['event'] == 'request_done'
    )
    assert service_done['status'] == 'ok'
    assert {'queue_wait', 'pad', 'dispatch', 'slice'} <= set(
        service_done['segments']
    )

    # split per originating process (the in-process test's stand-in for
    # fleet_smoke's two real processes) and stitch through the CLI
    run_start = [e for e in events if e.get('event') == 'run_start']
    client_log = str(tmp_path / 'client' / 'obs.jsonl')
    server_log = str(tmp_path / 'server' / 'obs.jsonl')
    for path, hop in ((client_log, 0), (server_log, 1)):
        os.makedirs(os.path.dirname(path))
        with open(path, 'w', encoding='utf-8') as fh:
            for e in run_start + by_hop[hop]:
                fh.write(json.dumps(e) + '\n')

    from tools.obsctl import main as obsctl_main

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = obsctl_main(['trace', rid, client_log, server_log, '--json'])
    assert rc == 0
    trace = json.loads(out.getvalue())
    assert trace['request_id'] == rid
    assert [h['hop'] for h in trace['hops']] == [0, 1]
    assert trace['hops'][0]['enqueue'] is not None
    assert trace['status'] == 'ok'
    assert {'queue_wait', 'pad', 'dispatch', 'slice'} <= set(trace['segments'])


# ------------------------------------------------------------ governance ----


def test_benchdiff_headline_includes_replica_sweep():
    """The replica sweep's ledger metric is a benchdiff headline: a
    regression in 4-replica throughput fails ``make bench-diff``."""
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        'benchdiff', os.path.join(root, 'tools', 'benchdiff.py')
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert 'serve_req_per_sec_r4' in mod.HEADLINE_KEYS


def test_make_and_ci_run_the_mesh_smoke():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, 'Makefile'), encoding='utf-8') as fh:
        makefile = fh.read()
    target = makefile.split('mesh-smoke:')[1].split('\n\n')[0]
    assert 'tools/mesh_smoke.py' in target
    assert '--mesh-sweep' in target
    with open(
        os.path.join(root, '.github', 'workflows', 'ci.yml'), encoding='utf-8'
    ) as fh:
        assert 'make mesh-smoke' in fh.read()
