"""Drift watch: PSI/KS statistics, gauges, gate fail-closed, learner pins.

Covers the ISSUE-8 tentpole's third piece: device-side PSI/KS of a
traffic window vs the training reference (one vmap'd dispatch with
packed-mask semantics), the ``drift/*`` telemetry surface, the gate's
fail-closed ``max_drift_psi`` band, and the acceptance pin — drift on an
unchanged traffic distribution stays below trigger across 3 learner
iterations (no false-positive retrains) while a genuinely shifted
distribution early-triggers a retrain past the ``min_new_games`` floor.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pandas as pd
import pytest

from socceraction_tpu.core.batch import pack_actions
from socceraction_tpu.core.synthetic import (
    append_synthetic_games,
    synthetic_actions_frame,
    write_synthetic_season,
)
from socceraction_tpu.learn import (
    ContinuousLearner,
    DriftConfig,
    DriftWatch,
    GateConfig,
    LearnConfig,
    evaluate_gate,
)
from socceraction_tpu.learn.drift import DriftResult
from socceraction_tpu.obs import REGISTRY
from socceraction_tpu.pipeline.store import SeasonStore
from socceraction_tpu.serve import ModelRegistry, RatingService, TrafficCapture
from socceraction_tpu.vaep.base import VAEP

HOME = 100


def _frame(i, n=200):
    return synthetic_actions_frame(
        game_id=i, home_team_id=HOME, away_team_id=HOME + 1,
        seed=i, n_actions=n,
    )


def _batch(games=(0, 1, 2, 3), n=200, max_actions=256):
    stagings = []
    for i in games:
        s, _ = pack_actions(
            _frame(i, n).assign(game_id=i),
            home_team_id=HOME, max_actions=max_actions, as_numpy=True,
        )
        stagings.append(s)
    if len(stagings) == 1:
        return stagings[0]
    return jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *stagings)


def _fit_model():
    frame = _frame(0)
    model = VAEP()
    game = pd.Series({'game_id': 0, 'home_team_id': HOME})
    np.random.seed(0)
    model.fit(
        model.compute_features(game, frame),
        model.compute_labels(game, frame),
        learner='mlp',
        tree_params={'hidden': (16,), 'max_epochs': 2},
    )
    return model


@pytest.fixture(scope='module')
def model():
    return _fit_model()


# ----------------------------------------------------------- statistics ----


def test_same_distribution_scores_zero_psi():
    cfg = DriftConfig(min_actions=64, include_predictions=False)
    watch = DriftWatch.from_batch(None, _batch(), cfg)
    res = watch.check(None, _batch())
    assert res.evaluated and not res.triggered
    assert res.max_psi == pytest.approx(0.0, abs=1e-6)
    assert res.max_ks == pytest.approx(0.0, abs=1e-6)


def test_shifted_distribution_triggers_on_the_right_feature():
    cfg = DriftConfig(min_actions=64, include_predictions=False)
    watch = DriftWatch.from_batch(None, _batch(), cfg)
    base = _batch()
    shifted = dataclasses.replace(base, start_x=base.start_x * 0.2 + 80.0)
    res = watch.check(None, shifted)
    assert res.triggered and res.max_psi_feature == 'start_x'
    assert res.max_psi > cfg.psi_trigger
    assert 'start_x' in res.reasons[0]
    # the untouched features stay calm
    assert res.psi['start_y'] < 0.05


def test_padding_rows_are_not_evidence():
    """Mask semantics: extra all-padding games change nothing."""
    cfg = DriftConfig(min_actions=64, include_predictions=False)
    watch = DriftWatch.from_batch(None, _batch(), cfg)
    base = _batch()
    # append two all-padding game rows (mask False everywhere)
    padded = jax.tree.map(
        lambda a: np.concatenate(
            [np.asarray(a), np.zeros((2,) + np.asarray(a).shape[1:],
                                     np.asarray(a).dtype)], axis=0
        ),
        base,
    )
    r1 = watch.check(None, base)
    r2 = watch.check(None, padded)
    assert r1.psi == r2.psi and r1.ks == r2.ks
    assert r2.n_actions == r1.n_actions


def test_small_window_reports_unevaluated():
    cfg = DriftConfig(min_actions=10_000, include_predictions=False)
    watch = DriftWatch.from_batch(None, _batch(), cfg)
    res = watch.check(None, _batch(games=(0,)))
    assert not res.evaluated and not res.triggered
    assert 'too small' in res.reasons[0]


def test_prediction_heads_ride_the_same_dispatch(model):
    cfg = DriftConfig(min_actions=64)
    watch = DriftWatch.from_batch(model, _batch(), cfg)
    assert 'pred_scores' in watch.reference.names
    assert 'pred_concedes' in watch.reference.names
    res = watch.check(model, _batch())
    assert res.max_psi == pytest.approx(0.0, abs=1e-6)
    assert set(res.psi) == set(watch.reference.names)
    # prediction rows bin on the fixed [0, 1] range
    names = list(watch.reference.names)
    i = names.index('pred_scores')
    assert watch.reference.lo[i] == 0.0 and watch.reference.hi[i] == 1.0


def test_mismatched_reference_is_a_loud_error(model):
    cfg = DriftConfig(min_actions=64, include_predictions=False)
    watch = DriftWatch.from_batch(None, _batch(), cfg)
    with pytest.raises(ValueError, match='do not match the reference'):
        # predictions present in the window but absent from the reference
        from socceraction_tpu.learn.drift import drift_statistics
        from socceraction_tpu.learn.shadow import replay_probs

        drift_statistics(
            watch.reference, _batch(), replay_probs(model, _batch())
        )


def test_drift_telemetry_surface():
    REGISTRY.get('drift/checks') and REGISTRY.get('drift/checks').reset()
    cfg = DriftConfig(min_actions=64, include_predictions=False)
    watch = DriftWatch.from_batch(None, _batch(), cfg)
    base = _batch()
    watch.check(None, base)
    shifted = dataclasses.replace(base, start_x=base.start_x * 0.2 + 80.0)
    watch.check(None, shifted)
    snap = REGISTRY.snapshot()
    assert snap.value('drift/checks') >= 2
    assert snap.value('drift/triggers') >= 1
    assert snap.value('drift/psi', stat='last', feature='start_x') > 0.25
    assert snap.value('drift/max_psi', stat='last') > 0.25
    from socceraction_tpu.obs.recorder import RECORDER

    kinds = [e['kind'] for e in RECORDER.events()]
    assert 'drift_check' in kinds


# ------------------------------------------------------- gate fail-closed --


def _result(max_psi, evaluated=True):
    return DriftResult(
        psi={'start_x': max_psi}, ks={'start_x': 0.0},
        max_psi=max_psi, max_psi_feature='start_x',
        evaluated=evaluated, n_actions=1000,
    )


def test_gate_drift_band_blocks_and_fails_closed():
    cfg = GateConfig(max_drift_psi=0.25)
    # no statistics at all: fail closed
    passed, reasons = evaluate_gate(None, {}, cfg, drift=None)
    assert not passed and 'unavailable' in reasons[0]
    # unevaluated statistics (window too small): fail closed
    passed, reasons = evaluate_gate(
        None, {}, cfg, drift=_result(0.0, evaluated=False)
    )
    assert not passed and 'unavailable' in reasons[0]
    # drifted past the band: blocked with the feature named
    passed, reasons = evaluate_gate(None, {}, cfg, drift=_result(0.9))
    assert not passed and 'start_x' in reasons[0]
    # calm drift: the bootstrap case passes as before
    passed, reasons = evaluate_gate(None, {}, cfg, drift=_result(0.01))
    assert passed and 'bootstrap' in reasons[0]
    # band unset (default): drift is ignored entirely
    passed, _ = evaluate_gate(None, {}, GateConfig(), drift=None)
    assert passed


# ------------------------------------------------------- learner wiring ----


def test_unchanged_traffic_never_false_positives_across_iterations(tmp_path):
    """Acceptance pin: 3 learner iterations over an unchanged traffic
    distribution keep drift below trigger — no false-positive retrains —
    and a shifted distribution early-triggers past min_new_games."""
    A = 192
    store_path = str(tmp_path / 'season')
    write_synthetic_season(store_path, n_games=4, n_actions=A, seed=0)
    registry = ModelRegistry(str(tmp_path / 'registry'))
    cfg = LearnConfig(
        model_name='vaep', max_actions=A, games_per_batch=4, random_state=0,
        debug_dir=str(tmp_path / 'debug'),
        train_params={'hidden': (16,), 'max_epochs': 2, 'batch_size': 512},
        gate=GateConfig(n_boot=8),
        # psi_trigger sits above the ~0.3 sampling noise of few-hundred-
        # action windows at 16 bins, far below a real shift's PSI (~8)
        drift=DriftConfig(
            min_actions=64, reference_games=4, include_predictions=False,
            psi_trigger=0.6,
        ),
        min_new_games=100,  # only drift can trigger a retrain here
    )
    # the bootstrap has no active model (no drift reference, no floor)
    boot_cfg = dataclasses.replace(cfg, min_new_games=1, drift=None)
    snap0 = REGISTRY.snapshot()
    checks_before = snap0.value('drift/checks')
    triggers_before = snap0.value('drift/triggers')
    early_before = snap0.value('learn/early_trains')
    with SeasonStore(store_path, mode='a') as store:
        boot = ContinuousLearner(store, registry, config=boot_cfg)
        assert boot.run_once().verdict == 'promoted'

        capture = TrafficCapture(max_frames=16)
        home_ids = store.home_team_ids()
        steady = [
            (store.get_actions(gid), home_ids.get(gid))
            for gid in list(store.game_ids())
        ]
        with RatingService(
            registry=registry, max_actions=A, max_batch_size=4,
            max_wait_ms=1.0, capture=capture,
        ) as svc:
            svc.warmup()
            # steady traffic: the store's own matches — by construction
            # the exact distribution the reference was built from
            for frame, home in steady:
                svc.rate_sync(frame, home_team_id=home, timeout=120)

            learner = ContinuousLearner(
                store, registry, service=svc, config=cfg
            )
            # one new game lands per iteration — under the floor, so only
            # a drift trigger could retrain
            reports = []
            for it in range(3):
                append_synthetic_games(
                    store_path, 1, n_actions=A, seed=200 + it
                )
                reports.append(learner.run_once())
            assert [r.verdict for r in reports] == ['no_new_data'] * 3
            for r in reports:
                assert r.drift and r.drift['evaluated']
                assert r.drift['triggered'] is False
                assert r.drift['max_psi'] < cfg.drift.psi_trigger
            snap = REGISTRY.snapshot()
            assert snap.value('drift/triggers') == triggers_before
            assert snap.value('learn/early_trains') == early_before
            # drift stats surfaced in the check counter too
            assert snap.value('drift/checks') >= checks_before + 3

            # ---- now the distribution genuinely shifts
            shifted = steady[0][0].copy()
            shifted['start_x'] = shifted['start_x'] * 0.2 + 80.0
            shifted['end_x'] = shifted['end_x'] * 0.2 + 80.0
            capture.clear()
            for _ in range(3):
                svc.rate(
                    shifted, home_team_id=steady[0][1]
                ).result(timeout=120)
            import time as _time

            _time.sleep(0.1)  # capture callbacks land on the flusher
            report = learner.run_once()
            # the pending (uncommitted) game plus drift => early retrain
            assert report.verdict in ('promoted', 'rejected')
            assert report.drift['triggered'] is True
            assert REGISTRY.snapshot().value('learn/early_trains') >= (
                early_before + 1
            )
    assert registry.active()[0] == 'vaep'
