"""xG model over SPADL shots (reference EXTRA notebook as library API)."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

from socceraction_tpu.core.synthetic import synthetic_actions_frame
from socceraction_tpu.spadl import config as spadlconfig
from socceraction_tpu.xg import XGModel, xfns_default


class _Game:
    def __init__(self, game_id, home_team_id):
        self.game_id = game_id
        self.home_team_id = home_team_id


@pytest.fixture(scope='module')
def season():
    # 12 games / 3 held out: ~130 held-out shots. Smaller pools put the
    # held-out AUC's standard error near the quality floor itself
    # (measured: 8g/2t logistic 0.544, 12g/3t 0.616 on the same generator)
    games, actions = [], {}
    for i in range(12):
        gid, home, away = 100 + i, 200 + 2 * i, 201 + 2 * i
        games.append(_Game(gid, home))
        actions[gid] = synthetic_actions_frame(
            gid, home_team_id=home, away_team_id=away, seed=i, n_actions=1200
        )
    return games, actions


_N_TEST = 3


@pytest.fixture(scope='module')
def fitted(season):
    games, actions = season
    model = XGModel()
    X = pd.concat(
        [model.compute_features(g, actions[g.game_id]) for g in games[:-_N_TEST]],
        ignore_index=True,
    )
    y = pd.concat(
        [model.compute_labels(g, actions[g.game_id]) for g in games[:-_N_TEST]],
        ignore_index=True,
    )
    model.fit(X, y, learner='logistic')
    return model, X, y


def test_features_one_row_per_shot(season):
    games, actions = season
    model = XGModel()
    g = games[0]
    X = model.compute_features(g, actions[g.game_id])
    n_shots = actions[g.game_id]['type_id'].isin(spadlconfig.SHOT_LIKE).sum()
    assert len(X) == n_shots
    assert list(X.columns) == model.feature_column_names()


def test_leak_filter_drops_shot_own_columns():
    names = XGModel().feature_column_names()
    assert not any(n.startswith('type_') and n.endswith('_a0') for n in names)
    for leaked in ('dx_a0', 'dy_a0', 'movement_a0'):
        assert leaked not in names
    # the previous action's columns survive
    assert any(n.endswith('_a1') for n in names)
    # disabling the filter restores the full matrix
    full = XGModel(drop_leaky=False).feature_column_names()
    assert 'dx_a0' in full and len(full) > len(names)


def test_labels_match_shot_results(season):
    games, actions = season
    model = XGModel()
    g = games[0]
    y = model.compute_labels(g, actions[g.game_id])
    a = actions[g.game_id]
    shots = a['type_id'].isin(spadlconfig.SHOT_LIKE)
    expected = (a.loc[shots, 'result_id'] == spadlconfig.SUCCESS).to_numpy()
    np.testing.assert_array_equal(y['goal'].to_numpy(), expected)


def test_fit_estimate_nan_pattern(season, fitted):
    games, actions = season
    model, _, _ = fitted
    g = games[-1]
    out = model.estimate(g, actions[g.game_id])
    shots = actions[g.game_id]['type_id'].isin(spadlconfig.SHOT_LIKE).to_numpy()
    assert out['xg'].notna().to_numpy().tolist() == shots.tolist()
    vals = out['xg'].dropna()
    assert ((vals >= 0) & (vals <= 1)).all()


def test_held_out_quality_beats_chance(season, fitted):
    """Synthetic shots encode distance-dependent conversion (QUALITY.md);
    a fitted xG model must recover it on held-out games. Counterattack
    finishes (round-4 generator) are location-independent by design, so
    the pure-location ceiling here is lower than the VAEP tier's."""
    games, actions = season
    model, _, _ = fitted
    X = pd.concat(
        [model.compute_features(g, actions[g.game_id]) for g in games[-_N_TEST:]],
        ignore_index=True,
    )
    y = pd.concat(
        [model.compute_labels(g, actions[g.game_id]) for g in games[-_N_TEST:]],
        ignore_index=True,
    )
    assert y['goal'].nunique() == 2, 'need both classes in the held-out pool'
    metrics = model.score(X, y)
    assert metrics['auroc'] > 0.55
    assert 0 < metrics['brier'] < 0.25


def test_unknown_learner_raises(fitted):
    model, X, y = fitted
    with pytest.raises(ValueError, match='unknown learner'):
        XGModel().fit(X, y, learner='nope')


def test_unfitted_raises(season):
    games, actions = season
    with pytest.raises(ValueError, match='fit'):
        XGModel().estimate(games[0], actions[games[0].game_id])


def test_xgboost_learner_if_available(fitted):
    pytest.importorskip('xgboost')
    model, X, y = fitted
    m = XGModel().fit(X, y, learner='xgboost')
    assert m.clf is not None


def test_default_xfns_match_notebook_recipe():
    names = [f.__name__ for f in xfns_default]
    assert names == [
        'actiontype_onehot',
        'bodypart_onehot',
        'startlocation',
        'movement',
        'space_delta',
        'startpolar',
        'team',
    ]


def test_non_default_index_frames_are_normalized(season, fitted):
    """A filtered frame (non-RangeIndex) must not misalign the features."""
    games, actions = season
    model, _, _ = fitted
    g = games[0]
    a = actions[g.game_id]
    filtered = a[a['period_id'] == 1]  # keeps the original sparse index
    X = model.compute_features(g, filtered)
    n_shots = filtered['type_id'].isin(spadlconfig.SHOT_LIKE).sum()
    assert len(X) == n_shots
    est = model.estimate(g, filtered)
    assert len(est) == len(filtered)
    assert (est.index == filtered.index).all()
