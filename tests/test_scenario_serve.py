"""Tests for the counterfactual serving verb (``rate_scenarios``).

The ISSUE-18 contract, serving side: ``RatingService.rate_scenarios``
values a ``P``-perturbation grid in ONE fused dispatch bitwise equal to
the looped goalscore-carrying ``rate_batch`` oracle; ``P`` snaps to its
own power-of-two bucket ladder so warmup (``scenario_buckets=``) makes
steady-state scenario traffic retrace-free; the per-lane circuit
breaker degrades the verb onto the looped materialized reference
(correct, slow) instead of failing; deadlines shed queued scenario
requests exactly like rate requests; mixed rate+scenario takes
partition and reassemble in order; the caller thread gets the named
validation errors (never the flusher); and the frontend ``POST
/scenarios`` RPC round-trips the grid and the ``(P, n, 3)`` value block
bit for bit.
"""

from __future__ import annotations

import os

import numpy as np
import pandas as pd
import pytest

from socceraction_tpu.core.batch import pack_actions, unpack_values
from socceraction_tpu.core.synthetic import synthetic_actions_frame
from socceraction_tpu.obs import REGISTRY
from socceraction_tpu.obs.context import DeadlineExceeded
from socceraction_tpu.scenario import (
    action_type_sweep,
    custom_grid,
    end_location_grid,
    rate_scenarios_looped,
)
from socceraction_tpu.serve import (
    FrontendClient,
    FrontendError,
    RatingService,
    ServingFrontend,
)
from socceraction_tpu.vaep.base import VAEP

HOME = 100
MAX_ACTIONS = 256


@pytest.fixture(scope='module', autouse=True)
def _drain_pair_probs_storm_window():
    """Retire this module's serving-ladder compiles from the storm
    window (same rationale as tests/test_quant.py)."""
    yield
    from socceraction_tpu.ops.fused import _pair_probs, _pair_probs_prepared

    for fn in (_pair_probs, _pair_probs_prepared):
        fn.drain_storm_window()


def _fit_model():
    frames = [
        synthetic_actions_frame(game_id=i, seed=i, n_actions=200)
        for i in (0, 1)
    ]
    model = VAEP()
    X, y = [], []
    for i, f in zip((0, 1), frames):
        game = pd.Series({'game_id': i, 'home_team_id': HOME})
        X.append(model.compute_features(game, f))
        y.append(model.compute_labels(game, f))
    np.random.seed(0)
    model.fit(
        pd.concat(X, ignore_index=True),
        pd.concat(y, ignore_index=True),
        learner='mlp',
        tree_params={'hidden': (16,), 'max_epochs': 2},
    )
    return model


@pytest.fixture(scope='module')
def model():
    return _fit_model()


def _frame(n_actions=120, game_id=90):
    return synthetic_actions_frame(
        game_id=game_id, seed=game_id, n_actions=n_actions
    )


def _looped_oracle(svc, model, frame, grid):
    """What the serving verb must match: one rate_batch per perturbation
    over the request's staging batch, carrying the FACTUAL goalscore
    block (the scenario fold never recomputes score state from the
    perturbed fields)."""
    staging, _ = pack_actions(
        frame, home_team_id=HOME, max_actions=svc.max_actions, as_numpy=True
    )
    overrides = (
        {'goalscore': svc._frame_goalscore(frame, HOME)}
        if svc._gs_enabled
        else None
    )
    looped = rate_scenarios_looped(
        model, staging, grid, dense_overrides=overrides, bucket=False
    )
    return np.stack(
        [unpack_values(looped[p], staging) for p in range(looped.shape[0])]
    )


# --------------------------------------------------------- the verb ----


def test_rate_scenarios_matches_looped_oracle_bitwise(model):
    frame = _frame(120)
    grid = action_type_sweep(type_ids=[0, 1, 2, 11, 21])
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=4, max_wait_ms=1.0
    ) as svc:
        svc.warmup()
        out = svc.rate_scenarios_sync(frame, grid, home_team_id=HOME)
    assert out.shape == (5, len(frame), 3)
    np.testing.assert_array_equal(out, _looped_oracle(svc, model, frame, grid))


def test_rate_scenarios_end_location_grid_and_product_flow(model):
    """The product path end to end: an end-location sweep served, then
    folded into a heatmap — P=12 snaps to bucket 16 transparently."""
    from socceraction_tpu.scenario import decision_surface

    frame = _frame(80, game_id=91)
    grid = end_location_grid(nx=4, ny=3)
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=4, max_wait_ms=1.0
    ) as svc:
        svc.warmup()
        out = svc.rate_scenarios_sync(frame, grid, home_team_id=HOME)
        np.testing.assert_array_equal(
            out, _looped_oracle(svc, model, frame, grid)
        )
    # the serving verb's (P, n, 3) block folds directly (single game)
    surf = decision_surface(out, grid, game=0, action=3)
    assert surf.shape == (3, 4)
    np.testing.assert_array_equal(surf.ravel(), out[:, 3, 2])


def test_scenario_zero_steady_state_retraces_after_warmup(model):
    """Warming the scenario rungs (same compiled program as a rate flush
    of that many games) makes scenario traffic compile NOTHING new."""
    frame = _frame(100, game_id=92)
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=4, max_wait_ms=1.0,
        max_perturbations=8,
    ) as svc:
        assert svc.scenario_ladder == (1, 2, 4, 8)
        svc.warmup(scenario_buckets=svc.scenario_ladder)
        shapes = svc.compiled_shapes
        snap = REGISTRY.snapshot()
        compiles = sum(
            snap.value('xla/compiles', fn=fn)
            for fn in ('pair_probs', 'pair_probs_prepared')
        )
        traces_before = snap.value(
            'scenario/shape_traces', n_perturbations_bucket='8'
        ) or 0
        # P=5 and P=7 both snap to the warmed bucket 8; repeats re-use it
        for _ in range(2):
            for p_count in (5, 7):
                grid = action_type_sweep(type_ids=list(range(p_count)))
                out = svc.rate_scenarios_sync(frame, grid, home_team_id=HOME)
                assert out.shape == (p_count, len(frame), 3)
        assert svc.compiled_shapes == shapes
        snap = REGISTRY.snapshot()
        assert compiles == sum(
            snap.value('xla/compiles', fn=fn)
            for fn in ('pair_probs', 'pair_probs_prepared')
        )
        # the whole plateau is ONE scenario shape trace (bucket 8)
        assert REGISTRY.snapshot().value(
            'scenario/shape_traces', n_perturbations_bucket='8'
        ) == traces_before + 1


def test_scenario_breaker_fallback_serves_correct_values(model, monkeypatch):
    """A sick device dispatch degrades the verb onto the looped
    materialized reference: the future still resolves, values stay in
    the fused-vs-materialized band, and the fallback is counted."""
    frame = _frame(60, game_id=93)
    grid = action_type_sweep(type_ids=[0, 1, 2])
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=4, max_wait_ms=1.0
    ) as svc:
        svc.warmup()
        fused = svc.rate_scenarios_sync(frame, grid, home_team_id=HOME)
        fallbacks = REGISTRY.snapshot().value('scenario/fallbacks') or 0

        def boom(*a, **k):
            raise RuntimeError('injected device failure')

        monkeypatch.setattr(svc, '_device_rate', boom)
        degraded = svc.rate_scenarios_sync(frame, grid, home_team_id=HOME)
    assert degraded.shape == fused.shape
    np.testing.assert_allclose(degraded, fused, atol=1e-4)
    snap = REGISTRY.snapshot()
    assert snap.value('scenario/fallbacks') == fallbacks + 1


def test_scenario_deadline_shed(model):
    """A scenario request still queued past its deadline fails with
    DeadlineExceeded and is never dispatched — same shedding contract
    as rate requests (it rides the same queue)."""
    frame = _frame(50, game_id=94)
    grid = action_type_sweep(type_ids=[0, 1])
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=8, max_wait_ms=200.0
    ) as svc:
        svc.warmup()
        fut = svc.rate_scenarios(frame, grid, home_team_id=HOME, deadline_ms=5)
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=60)
    assert 'queue_wait' in fut.context.segments
    assert 'dispatch' not in fut.context.segments


def test_mixed_flush_partitions_and_reassembles_in_order(model):
    """One coalesced take mixing rate and scenario payloads: each verb
    dispatches at its own bucket and every future gets its own result."""
    rate_frame = _frame(70, game_id=95)
    scn_frame = _frame(40, game_id=96)
    grid = action_type_sweep(type_ids=[0, 1, 2])
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=8, max_wait_ms=60.0
    ) as svc:
        svc.warmup()
        rate_ref = svc.rate_sync(rate_frame, home_team_id=HOME, timeout=60)
        scn_ref = svc.rate_scenarios_sync(scn_frame, grid, home_team_id=HOME)
        # enqueue within one wait window so they coalesce into one take
        futs = [
            svc.rate(rate_frame, home_team_id=HOME),
            svc.rate_scenarios(scn_frame, grid, home_team_id=HOME),
            svc.rate(rate_frame, home_team_id=HOME),
        ]
        r1, s, r2 = (f.result(timeout=120) for f in futs)
    np.testing.assert_array_equal(r1.to_numpy(), rate_ref.to_numpy())
    np.testing.assert_array_equal(r2.to_numpy(), rate_ref.to_numpy())
    np.testing.assert_array_equal(s, scn_ref)


def test_rate_scenarios_caller_thread_validation(model):
    frame = _frame(30, game_id=97)
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=4, max_wait_ms=1.0,
        max_perturbations=4,
    ) as svc:
        with pytest.raises(TypeError, match='ScenarioGrid'):
            svc.rate_scenarios(frame, {'end_x': [1.0]}, home_team_id=HOME)
        with pytest.raises(ValueError, match='max_perturbations=4'):
            svc.rate_scenarios(
                frame, action_type_sweep(), home_team_id=HOME
            )
        with pytest.raises(ValueError, match='empty actions frame'):
            svc.rate_scenarios(
                frame.iloc[:0], action_type_sweep(type_ids=[0]),
                home_team_id=HOME,
            )
        multi = pd.concat(
            [frame, _frame(30, game_id=98)], ignore_index=True
        )
        with pytest.raises(ValueError, match='one request rates one match'):
            svc.rate_scenarios(
                multi, action_type_sweep(type_ids=[0]), home_team_id=HOME
            )
        # a malformed per-action update fails HERE, naming the shape
        bad_shape = custom_grid(
            field_updates={'end_x': np.zeros((2, 1, 99), dtype=np.float32)}
        )
        with pytest.raises(ValueError, match=r'\(P, 1, max_actions\)'):
            svc.rate_scenarios(frame, bad_shape, home_team_id=HOME)
        # a dense block the model can't override fails with the model's
        # named validation error, not a flusher-side shape blowup
        bad_dense = custom_grid(
            dense_overrides={
                'actiontype_onehot': np.zeros(
                    (2, 1, MAX_ACTIONS, 23), dtype=np.float32
                )
            }
        )
        with pytest.raises(ValueError, match='not a dense feature block'):
            svc.rate_scenarios(frame, bad_dense, home_team_id=HOME)


def test_rate_scenarios_validates_max_perturbations_config():
    with pytest.raises(ValueError, match='max_perturbations'):
        RatingService(
            _fit_model(), max_actions=64, max_perturbations=0
        )


# --------------------------------------------------------- frontend ----


@pytest.fixture(scope='module')
def frontend(model, tmp_path_factory):
    sock = str(tmp_path_factory.mktemp('scn') / 'frontend.sock')
    with RatingService(
        model, max_actions=MAX_ACTIONS, max_batch_size=4, max_wait_ms=1.0
    ) as svc:
        svc.warmup()
        with ServingFrontend(svc, unix_path=sock):
            yield svc, FrontendClient(sock)
    assert not os.path.exists(sock)


def test_frontend_scenario_round_trip_is_bitwise(frontend):
    svc, client = frontend
    frame = _frame(90, game_id=99)
    grid = action_type_sweep(type_ids=[0, 1, 11])
    ref = svc.rate_scenarios_sync(frame, grid, home_team_id=HOME)
    out = client.rate_scenarios(frame, grid, home_team_id=HOME)
    assert out.shape == ref.shape == (3, len(frame), 3)
    np.testing.assert_array_equal(out, ref)


def test_frontend_scenario_error_mapping(frontend):
    _svc, client = frontend
    frame = _frame(20, game_id=100)
    bad = custom_grid(
        dense_overrides={
            'actiontype_onehot': np.zeros(
                (2, 1, MAX_ACTIONS, 23), dtype=np.float32
            )
        }
    )
    with pytest.raises(FrontendError) as err:
        client.rate_scenarios(frame, bad, home_team_id=HOME)
    assert err.value.status == 400
    assert 'dense feature block' in str(err.value)
