"""Anderson-accelerated xT solving: same fixed point, fewer sweeps.

The sweep is an affine contraction, so Anderson mixing (PAPERS.md's
accelerated-value-iteration literature) must converge to the plain
solver's surface; these tests pin the fixed point, the sweep-count win,
and the API guards.
"""

import numpy as np
import pandas as pd
import pytest

from conftest import requires_shard_map
from socceraction_tpu import xthreat as xt
from socceraction_tpu.core.batch import pack_actions
from socceraction_tpu.core.synthetic import synthetic_actions_frame
from socceraction_tpu.ops.xt import (
    solve_xt,
    solve_xt_matrix_free,
    xt_counts,
    xt_probabilities,
)


@pytest.fixture(scope='module')
def season():
    frames = [
        synthetic_actions_frame(game_id=1000 + g, n_actions=1200, seed=g)
        for g in range(8)
    ]
    df = pd.concat(frames, ignore_index=True)
    batch, _ = pack_actions(
        df, home_team_ids={g: 100 for g in df['game_id'].unique()}
    )
    return df, batch


def test_anderson_dense_matches_plain(season):
    _, batch = season
    counts = xt_counts(
        batch.type_id, batch.result_id,
        batch.start_x, batch.start_y, batch.end_x, batch.end_y,
        batch.mask, l=16, w=12,
    )
    probs = xt_probabilities(counts, l=16, w=12)
    plain = solve_xt(probs)
    acc = solve_xt(probs, accelerate=True)
    np.testing.assert_allclose(
        np.asarray(acc.grid), np.asarray(plain.grid), atol=5e-5
    )
    assert int(acc.iterations) < int(plain.iterations)
    assert bool(plain.converged) and bool(acc.converged)


def test_anderson_matrix_free_matches_plain(season):
    _, batch = season
    args = (
        batch.type_id, batch.result_id,
        batch.start_x, batch.start_y, batch.end_x, batch.end_y, batch.mask,
    )
    plain, _ = solve_xt_matrix_free(*args, l=24, w=16)
    acc, _ = solve_xt_matrix_free(*args, l=24, w=16, accelerate=True)
    np.testing.assert_allclose(
        np.asarray(acc.grid), np.asarray(plain.grid), atol=5e-5
    )
    assert int(acc.iterations) < int(plain.iterations)


def test_model_level_accelerate(season):
    df, _ = season
    ltr = df  # synthetic frames are already orientation-consistent per team
    plain = xt.ExpectedThreat(l=16, w=12, backend='jax').fit(ltr)
    acc = xt.ExpectedThreat(l=16, w=12, backend='jax', accelerate=True).fit(ltr)
    np.testing.assert_allclose(acc.xT, plain.xT, atol=5e-5)
    assert acc.n_iter < plain.n_iter
    # ratings flow through the same grid
    r_plain = plain.rate(ltr)
    r_acc = acc.rate(ltr)
    np.testing.assert_allclose(r_acc, r_plain, atol=5e-5, equal_nan=True)


@requires_shard_map
def test_sharded_anderson_matches_unsharded(season):
    """Accelerated + sharded: psum'd sweeps inside the Anderson loop must
    still land on the plain unsharded fixed point."""
    import jax

    from socceraction_tpu.parallel import (
        make_mesh,
        shard_batch,
        sharded_xt_fit_matrix_free,
    )

    assert len(jax.devices()) == 8
    _, batch = season
    mesh = make_mesh()
    sharded = shard_batch(batch, mesh)
    grid_acc, it_acc = sharded_xt_fit_matrix_free(
        sharded, mesh, l=24, w=16, accelerate=True
    )
    ref, _ = solve_xt_matrix_free(
        batch.type_id, batch.result_id,
        batch.start_x, batch.start_y, batch.end_x, batch.end_y, batch.mask,
        l=24, w=16,
    )
    np.testing.assert_allclose(
        np.asarray(grid_acc), np.asarray(ref.grid), atol=5e-5
    )
    assert int(it_acc) < int(ref.iterations)


def test_accelerate_guards(season):
    df, _ = season
    with pytest.raises(ValueError, match='JAX-backend'):
        xt.ExpectedThreat(backend='pandas', accelerate=True)
    with pytest.raises(ValueError, match='Picard'):
        xt.ExpectedThreat(backend='jax', accelerate=True, keep_heatmaps=True)
    # attributes are public and mutable: the guard must also hold at fit
    # time, not just in __init__ (codebase convention, xthreat.py)
    model = xt.ExpectedThreat(backend='jax', accelerate=True)
    model.keep_heatmaps = True
    with pytest.raises(ValueError, match='Picard'):
        model.fit(df)
    model2 = xt.ExpectedThreat(backend='jax', accelerate=True)
    model2.backend = 'pandas'
    with pytest.raises(ValueError, match='JAX-backend'):
        model2.fit(df)


def test_anderson_respects_max_iter(season):
    """n_sweeps must never exceed max_iter (bench relies on this)."""
    _, batch = season
    counts = xt_counts(
        batch.type_id, batch.result_id,
        batch.start_x, batch.start_y, batch.end_x, batch.end_y,
        batch.mask, l=16, w=12,
    )
    probs = xt_probabilities(counts, l=16, w=12)
    sol = solve_xt(probs, eps=0.0, max_iter=7, accelerate=True)
    assert int(sol.iterations) == 7
    assert not bool(sol.converged)
