"""Unit tests for the declarative field-spec engine behind the Opta parsers.

The engine must keep the reference's ``assertget`` contract (missing or
null source → AssertionError) while covering both reference fallback
idioms with output-domain defaults (see
``socceraction/data/opta/parsers/f24_json.py:67-122`` vs
``f24_xml.py:38-104``: the same attribute is required in one dialect
and optional in the other).
"""

from datetime import datetime

import pytest

from socceraction_tpu.data.opta.parsers.spec import (
    Field,
    derived,
    extract_record,
    flag,
    ref_id,
    ts,
)


def test_cast_and_path_walk():
    raw = {'id': '7', 'nest': {'deep': {'x': '3.5'}}}
    rec = extract_record(
        raw,
        (Field('event_id', 'id', int), Field('x', ('nest', 'deep', 'x'), float)),
    )
    assert rec == {'event_id': 7, 'x': 3.5}


def test_missing_required_raises_assertget_style():
    with pytest.raises(AssertionError, match='KeyError'):
        extract_record({}, (Field('event_id', 'id', int),))


def test_explicit_null_counts_as_missing():
    # assertget uses .get + `assert value is not None`: JSON null and an
    # absent key are the same condition.
    with pytest.raises(AssertionError):
        extract_record({'id': None}, (Field('event_id', 'id', int),))


def test_default_is_output_domain_never_cast():
    # default=True stands in for the reference's bool(int(attr.get('outcome', 1)))
    rec = extract_record({}, (Field('outcome', 'outcome', flag, default=True),))
    assert rec['outcome'] is True
    rec = extract_record(
        {'outcome': '0'}, (Field('outcome', 'outcome', flag, default=True),)
    )
    assert rec['outcome'] is False


def test_default_none_emitted_without_cast():
    rec = extract_record({}, (Field('player_id', 'player_id', int, default=None),))
    assert rec['player_id'] is None


def test_derived_sees_seed_and_prior_fields():
    fields = (
        Field('start_x', 'x', float),
        derived('end_x', lambda rec, raw: rec['qualifiers'].get(140, rec['start_x'])),
    )
    rec = extract_record({'x': '10'}, fields, seed={'qualifiers': {140: 55.0}})
    assert rec['end_x'] == 55.0
    rec = extract_record({'x': '10'}, fields, seed={'qualifiers': {}})
    assert rec['end_x'] == 10.0


def test_ts_fallback_formats_and_tz_strip():
    parse = ts('%Y-%m-%dT%H:%M:%S.%fZ', '%Y-%m-%dT%H:%M:%SZ')
    assert parse('2018-06-14T15:00:00.123Z') == datetime(2018, 6, 14, 15, 0, 0, 123000)
    assert parse('2018-06-14T15:00:00Z') == datetime(2018, 6, 14, 15, 0, 0)
    with pytest.raises(ValueError):
        parse('June 14th')
    naive = ts('%Y%m%dT%H%M%S%z')('20180614T150000+0200')
    assert naive.tzinfo is None


def test_ref_id_and_flag_casts():
    assert ref_id('g123456') == 123456
    assert ref_id('t88') == 88
    assert flag('1') is True and flag(0) is False
