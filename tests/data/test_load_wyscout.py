"""Wyscout loader tests against the synthetic fixtures.

Mirrors reference ``tests/data/test_load_wyscout.py`` (public + API-v2
loaders, minutes-played edge cases) on the hand-built fixture games.
"""

import os

import pytest

from socceraction_tpu.data.wyscout import (
    PublicWyscoutLoader,
    WyscoutCompetitionSchema,
    WyscoutEventSchema,
    WyscoutGameSchema,
    WyscoutLoader,
    WyscoutPlayerSchema,
    WyscoutTeamSchema,
)

PUBLIC_DIR = os.path.join(
    os.path.dirname(__file__), os.pardir, 'datasets', 'wyscout_public', 'raw'
)
API_DIR = os.path.join(os.path.dirname(__file__), os.pardir, 'datasets', 'wyscout_api')
GAME_ID = 2058007


@pytest.fixture(scope='module')
def WSL() -> PublicWyscoutLoader:
    return PublicWyscoutLoader(root=PUBLIC_DIR, download=False)


@pytest.fixture(scope='module')
def API() -> WyscoutLoader:
    feeds = {
        'competitions': 'competitions.json',
        'seasons': 'seasons_{competition_id}.json',
        'events': 'events_{game_id}.json',
    }
    return WyscoutLoader(root=API_DIR, getter='local', feeds=feeds)


class TestPublicWyscoutLoader:
    def test_competitions(self, WSL):
        df = WSL.competitions()
        assert len(df) == 1
        WyscoutCompetitionSchema.validate(df)
        row = df.iloc[0]
        assert row['competition_id'] == 28
        assert row['season_id'] == 10078
        assert row['country_name'] == 'International'
        assert row['season_name'] == '2018'

    def test_games(self, WSL):
        df = WSL.games(28, 10078)
        assert len(df) == 1
        WyscoutGameSchema.validate(df)
        g = df.iloc[0]
        assert g['game_id'] == GAME_ID
        assert g['home_team_id'] == 5629
        assert g['away_team_id'] == 12913

    def test_teams(self, WSL):
        df = WSL.teams(GAME_ID)
        assert len(df) == 2
        WyscoutTeamSchema.validate(df)
        assert set(df['team_id']) == {5629, 12913}
        assert 'Fixture United FC' in set(df['team_name'])

    def test_players(self, WSL):
        df = WSL.players(GAME_ID)
        WyscoutPlayerSchema.validate(df)
        # 6 starters + 1 substitute made it onto the pitch
        assert len(df) == 7
        players = df.set_index('player_id')
        # unicode-escaped names are decoded
        assert players.at[101, 'firstname'] == 'José'
        # both halves ran to 48 min -> 96 match minutes
        assert players.at[101, 'minutes_played'] == 96
        # substituted at 60' (+3' stoppage) and his replacement
        assert players.at[103, 'minutes_played'] == 63
        assert players.at[104, 'minutes_played'] == 96 - 63
        assert not bool(players.at[104, 'is_starter'])
        # red card at 85' -> expanded to 88'
        assert players.at[203, 'minutes_played'] == 88

    def test_events(self, WSL):
        df = WSL.events(GAME_ID)
        WyscoutEventSchema.validate(df)
        assert len(df) == 21
        assert (df['game_id'] == GAME_ID).all()
        assert df['period_id'].isin([1, 2]).all()
        # eventSec is converted to milliseconds
        assert df.iloc[0]['milliseconds'] == 2000.0
        # eventId/subEventId become the type ids
        assert df.iloc[0]['type_id'] == 8
        assert df.iloc[0]['subtype_id'] == 85


def test_minutes_exclude_penalty_shootout():
    from socceraction_tpu.data.wyscout.loader import _minutes_played

    teams_data = [
        {
            'teamId': 1,
            'formation': {
                'lineup': [{'playerId': 1, 'shirtNumber': 1, 'redCards': '0'}],
                'bench': [],
                'substitutions': 'null',
            },
        }
    ]
    events = [
        {'matchPeriod': '1H', 'eventSec': 45 * 60.0},
        {'matchPeriod': '2H', 'eventSec': 45 * 60.0},
        {'matchPeriod': 'E1', 'eventSec': 15 * 60.0},
        {'matchPeriod': 'E2', 'eventSec': 15 * 60.0},
        {'matchPeriod': 'P', 'eventSec': 10 * 60.0},  # shootout: not played time
    ]
    mp = _minutes_played(teams_data, events)
    assert mp.set_index('player_id').at[1, 'minutes_played'] == 120


class TestWyscoutAPILoader:
    def test_competitions(self, API):
        df = API.competitions()
        assert len(df) == 1
        WyscoutCompetitionSchema.validate(df)
        assert df.iloc[0]['competition_id'] == 77
        assert df.iloc[0]['season_id'] == 2021

    def test_games(self, API):
        df = API.games(77, 2021)
        assert len(df) == 1
        WyscoutGameSchema.validate(df)
        assert df.iloc[0]['game_id'] == 555001

    def test_teams(self, API):
        df = API.teams(555001)
        assert len(df) == 2
        WyscoutTeamSchema.validate(df)

    def test_players(self, API):
        df = API.players(555001)
        WyscoutPlayerSchema.validate(df)
        assert len(df) == 5  # 4 starters + 1 sub
        players = df.set_index('player_id')
        # halves of 45 and 46 min -> 91 match minutes
        assert players.at[9001, 'minutes_played'] == 91
        assert players.at[9002, 'minutes_played'] == 70
        assert players.at[9003, 'minutes_played'] == 21

    def test_events(self, API):
        df = API.events(555001)
        WyscoutEventSchema.validate(df)
        assert len(df) == 5


class TestWyscoutAPIFeedLayouts:
    """The feed-dict degrees of freedom the reference supports
    (``data/wyscout/loader.py:339-382``): a 'games' index feed vs an
    events glob, a seasons glob without a 'competitions' feed, missing
    detail files (warn + skip), and malformed feeds (ParseError)."""

    @pytest.fixture()
    def root(self, tmp_path):
        import shutil

        for name in ('competitions.json', 'seasons_77.json', 'events_555001.json'):
            shutil.copy(os.path.join(API_DIR, name), tmp_path / name)
        return tmp_path

    def test_games_index_feed(self, root):
        """A 'games' feed lists matchIds; details come from each game's
        events feed rather than an events glob."""
        import json

        with open(root / 'matches_2021.json', 'w') as fh:
            json.dump({'matches': [{'matchId': 555001}]}, fh)
        loader = WyscoutLoader(
            root=str(root),
            getter='local',
            feeds={
                'games': 'matches_{season_id}.json',
                'events': 'events_{game_id}.json',
            },
        )
        df = loader.games(77, 2021)
        assert len(df) == 1
        assert df.iloc[0]['game_id'] == 555001
        WyscoutGameSchema.validate(df)

    def test_games_missing_detail_warns_and_skips(self, root):
        import json

        with open(root / 'matches_2021.json', 'w') as fh:
            json.dump({'matches': [{'matchId': 555001}, {'matchId': 555999}]}, fh)
        loader = WyscoutLoader(
            root=str(root),
            getter='local',
            feeds={
                'games': 'matches_{season_id}.json',
                'events': 'events_{game_id}.json',
            },
        )
        with pytest.warns(UserWarning, match='555999'):
            df = loader.games(77, 2021)
        assert list(df['game_id']) == [555001]

    def test_competitions_from_seasons_glob(self, root):
        """No 'competitions' feed: competitions() globs the seasons files."""
        loader = WyscoutLoader(
            root=str(root),
            getter='local',
            feeds={
                'seasons': 'seasons_*.json',
                'events': 'events_{game_id}.json',
            },
        )
        df = loader.competitions()
        assert len(df) == 1
        assert df.iloc[0]['competition_id'] == 77
        WyscoutCompetitionSchema.validate(df)

    def test_malformed_feeds_raise_parse_error(self, root):
        import json

        from socceraction_tpu.data.base import ParseError

        with open(root / 'competitions.json', 'w') as fh:
            json.dump({'not_competitions': []}, fh)
        loader = WyscoutLoader(
            root=str(root),
            getter='local',
            feeds={
                'competitions': 'competitions.json',
                'seasons': 'seasons_{competition_id}.json',
                'events': 'events_{game_id}.json',
            },
        )
        with pytest.raises(ParseError):
            loader.competitions()

        with open(root / 'matches_2021.json', 'w') as fh:
            json.dump({'wrong': True}, fh)
        loader2 = WyscoutLoader(
            root=str(root),
            getter='local',
            feeds={
                'games': 'matches_{season_id}.json',
                'events': 'events_{game_id}.json',
            },
        )
        with pytest.raises(ParseError):
            loader2.games(77, 2021)

    def test_empty_glob_is_missing_data(self, root):
        from socceraction_tpu.data.base import MissingDataError

        loader = WyscoutLoader(
            root=str(root),
            getter='local',
            feeds={'seasons': 'nonexistent_*.json', 'events': 'events_{game_id}.json'},
        )
        with pytest.raises(MissingDataError):
            loader.competitions()
