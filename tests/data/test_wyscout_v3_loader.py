"""Tests for the Wyscout v3 raw-event flattener and its converter handoff."""

import json


from socceraction_tpu.data.wyscout import flatten_v3_events, load_v3_events
from socceraction_tpu.spadl import wyscout_v3
from socceraction_tpu.spadl.schema import SPADLSchema


def _raw_events():
    return [
        {
            'id': 1001,
            'matchId': 9000,
            'matchPeriod': '1H',
            'minute': 0,
            'second': 10,
            'team': {'id': 1, 'name': 'Home FC'},
            'player': {'id': 11, 'name': 'A. Passer'},
            'location': {'x': 50, 'y': 50},
            'type': {'primary': 'pass', 'secondary': []},
            'pass': {
                'accurate': True,
                'endLocation': {'x': 62, 'y': 41},
                'height': None,
                'length': 14.2,
            },
        },
        {
            'id': 1002,
            'matchId': 9000,
            'matchPeriod': '1H',
            'minute': 0,
            'second': 16,
            'team': {'id': 1, 'name': 'Home FC'},
            'player': {'id': 12, 'name': 'B. Winger'},
            'location': {'x': 62, 'y': 41},
            'type': {'primary': 'pass', 'secondary': ['cross', 'head_pass']},
            'pass': {
                'accurate': False,
                'endLocation': {'x': 92, 'y': 30},
                'height': 'high',
                'length': 30.0,
            },
        },
        {
            'id': 1003,
            'matchId': 9000,
            'matchPeriod': '1H',
            'minute': 1,
            'second': 2,
            'team': {'id': 2, 'name': 'Away FC'},
            'player': {'id': 21, 'name': 'C. Striker'},
            'location': {'x': 85, 'y': 48},
            'type': {'primary': 'shot', 'secondary': []},
            'shot': {'isGoal': 1, 'onTarget': True, 'goalZone': 'gc', 'xg': 0.31},
        },
        {
            'id': 1004,
            'matchId': 9000,
            'matchPeriod': '2H',
            'minute': 50,
            'second': 30,
            'team': {'id': 2, 'name': 'Away FC'},
            'player': {'id': 22, 'name': 'D. Duelist'},
            'location': {'x': 40, 'y': 60},
            'type': {'primary': 'duel', 'secondary': ['ground_duel']},
            'groundDuel': {
                'duelType': 'dribble',
                'takeOn': True,
                'keptPossession': True,
                'relatedDuelId': None,
            },
        },
    ]


def test_flatten_columns():
    df = flatten_v3_events(_raw_events())
    assert len(df) == 4
    # nested paths -> snake_case flat columns
    assert df.loc[0, 'type_primary'] == 'pass'
    assert df.loc[0, 'pass_end_location_x'] == 62
    assert df.loc[0, 'pass_accurate'] == True  # noqa: E712
    assert df.loc[2, 'shot_is_goal'] == 1
    assert df.loc[2, 'shot_goal_zone'] == 'gc'
    assert df.loc[3, 'ground_duel_duel_type'] == 'dribble'
    assert df.loc[3, 'ground_duel_kept_possession'] == True  # noqa: E712
    assert df.loc[0, 'match_period'] == '1H'
    assert df.loc[0, 'team_id'] == 1 and df.loc[0, 'player_id'] == 11


def test_secondary_flags_dense():
    df = flatten_v3_events(_raw_events())
    # flags exist for every event, 0 where the label is absent
    assert df['type_cross'].tolist() == [0, 1, 0, 0]
    assert df['type_head_pass'].tolist() == [0, 1, 0, 0]
    assert df['type_ground_duel'].tolist() == [0, 0, 0, 1]


def test_flattened_frame_converts_to_spadl():
    df = flatten_v3_events(_raw_events())
    df = df.rename(columns={})  # converter reads match_id/minute/second directly
    actions = wyscout_v3.convert_to_actions(df, home_team_id=1)
    SPADLSchema.validate(actions)
    # cross detected through the secondary flag
    from socceraction_tpu.spadl import config as spadlconfig

    by_event = {
        eid: spadlconfig.actiontypes[tid]
        for eid, tid in zip(actions['original_event_id'], actions['type_id'])
    }
    assert by_event[1001] == 'pass'
    assert by_event[1002] == 'cross'
    assert by_event[1003] == 'shot'
    assert by_event[1004] == 'take_on'


def test_load_v3_events(tmp_path):
    path = tmp_path / 'match.json'
    path.write_text(json.dumps({'events': _raw_events()}))
    df = load_v3_events(str(path))
    assert len(df) == 4
    # bare-array feeds work too
    path2 = tmp_path / 'bare.json'
    path2.write_text(json.dumps(_raw_events()))
    assert len(load_v3_events(str(path2))) == 4
